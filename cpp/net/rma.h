// One-sided RMA plane — registered-memory put with completion bitmaps.
//
// Parity: brpc's RDMA one-sided verbs (rdma/rdma_endpoint + block_pool
// RegisterMemory) and fabric-lib's (arXiv 2510.27656) transfer engine:
// large payloads are WRITTEN by the sender straight into memory the
// receiver registered in advance, and the byte-stream transport carries
// only a tiny completion control message.  "RPC Considered Harmful"
// (arXiv 1805.08430) names the defect this removes: receiver-side copy
// orchestration — the shm path used to move one 64MB body through THREE
// memcpys (producer→ring, ring→IOBuf, IOBuf→landing block); the rma path
// moves it through ONE (sender→registered region), fanned out over
// parallel rail fibers.
//
// Model:
//  - A REGION is pinned memory under an rkey.  Exportable regions are
//    shm-backed (rma_alloc) and carry a fixed header: the peer maps
//    /trpc_rma_<pid>_<ordinal> and writes at offset.  rma_reg pins
//    arbitrary caller memory locally (no export — such regions can be
//    landing targets for the receiver-side copy path only).
//  - Every rma-capable connection (shm rings, ici rings — Transport::rma)
//    owns a WINDOW: an exportable region whose data area is a 64-slot
//    arena the PEER allocates spans from (CAS on a slot bitmap shared in
//    the region header; the receiver frees slots when the payload's last
//    IOBuf reference drops — end-to-end backpressure, window-full sends
//    fall back to the striped copy path).
//  - A transfer cuts the body into chunks written CONCURRENTLY by
//    trpc_{shm,ici}_rails rail fibers (per-rail FIFO: each rail owns a
//    contiguous chunk range written in order).  Each chunk write is
//    followed by a release-fenced bit set in the span's chunk bitmap, and
//    the control message is sent only after every rail joined — so a
//    receiver that observes the control frame either finds EVERY bit set
//    (acquire loads) and takes the whole payload, or drops the message
//    whole.  Torn reads are impossible; faulted (dropped/truncated)
//    chunks leave their bit clear and fail the CALL whole-or-nothing.
//  - The batch plane's registered resp_bufs become genuine remote-write
//    targets: when a caller's landing buffer lives in an rma_alloc'd
//    region, the REQUEST advertises {rkey, cap} (meta tail-group 6) and
//    the server puts the response straight into the caller's buffer
//    (control offset kRmaDirectOff; completion bitmap in the region
//    header), with zero receiver-side copies.
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <memory>
#include <mutex>

#include "base/iobuf.h"
#include "net/deadline.h"
#include "net/protocol.h"

namespace trpc {

class Socket;

// Control-frame rma_off value meaning "the payload landed at offset 0 of
// the named region's data area, completion bitmap in the REGION header"
// (the direct-to-caller-buffer path).  Window spans use their byte
// offset inside the window's data area instead.
constexpr uint64_t kRmaDirectOff = UINT64_MAX;

// Refcounted mapping of one region's shm object.  The registry, peer
// caches and every wrapped-payload consumer co-own it: neither a dying
// connection nor rma_free can munmap under a live reader.
struct RmaMapping {
  char* base = nullptr;
  size_t len = 0;
  bool owned = false;  // false: alias of another mapping (never unmapped)
  ~RmaMapping();
};

// Per-connection one-sided state, returned by Transport::rma().  The
// owning conn (ShmConn / IciConn) creates it at establishment, publishes
// local_rkey in the shared segment, and points peer_rkey_slot at the
// segment word where the PEER publishes its window.
struct RmaSession {
  uint64_t local_rkey = 0;  // our receive window (we own the region)
  // Segment word the peer publishes its window rkey into; acquire-read
  // at first send (may still be 0 while the peer bootstraps).
  std::atomic<uint64_t>* peer_rkey_slot = nullptr;

  // Lazily-resolved peer window (sender side), guarded by mu.  The
  // geometry is a TRUSTED snapshot validated at map time (the live
  // header is peer-writable; see rma.cc RmaGeom).
  std::mutex mu;
  uint64_t peer_rkey = 0;
  std::shared_ptr<RmaMapping> peer_map;
  uint64_t peer_data_len = 0;
  uint32_t peer_slot_bytes = 0;
  uint32_t peer_nslots = 0;

  ~RmaSession();  // releases the local window region (deferred munmap)
};

// Creates a session with a fresh local window region sized by the
// reloadable trpc_rma_window_bytes flag.  nullptr when the flag is 0
// (one-sided plane disabled) or the region could not be created — the
// connection then simply has no rma capability.
std::shared_ptr<RmaSession> rma_session_create();

// -- region registry -------------------------------------------------------

// Allocates an exportable (shm-backed) region and returns its DATA
// pointer (len usable bytes, page-aligned); *rkey_out names it for peers.
// nullptr on failure.
void* rma_alloc(size_t len, uint64_t* rkey_out);
// Unlinks the shm name and drops the registry reference; the munmap is
// deferred by the mapping refcount until the last wrapped-payload
// consumer drops (use-after-free guard).  `data` is the rma_alloc return.
void rma_free(void* data);
// Pins arbitrary caller memory under an rkey (local-only: not peer-
// mappable; landing lookups resolve it, remote puts cannot target it).
// Returns 0 on failure.
uint64_t rma_reg(const void* buf, size_t len);
// Unpins.  Returns 0, or -1 when the rkey is unknown.
int rma_unreg(uint64_t rkey);
// True (filling *rkey/*off) when [buf, buf+len) lies inside one live
// EXPORTABLE region's data area.
bool rma_exportable(const void* buf, size_t len, uint64_t* rkey,
                    uint64_t* off);
// Live regions (tests, /vars).
size_t rma_region_count();
// Window spans currently ALLOCATED across this process's receive
// windows (set bits in the shared slot bitmaps).  A peer's in-flight
// one-sided put holds its span until the payload's last IOBuf reference
// drops, so Server::Drain polls this to zero before tearing the process
// down — handing the listeners off while a span is live would let the
// successor's client observe a half-written window.
size_t rma_spans_in_use();
// Co-owning reference to the exportable region containing [buf, buf+len)
// (net/kvstore.h serves KV-block bytes zero-copy out of registered
// pages; the returned mapping refcount defers rma_free's munmap past
// any in-flight reader).  Fills *rkey/*off like rma_exportable.
// nullptr when the range is not inside one live exportable region.
std::shared_ptr<RmaMapping> rma_pin_exportable(const void* buf, size_t len,
                                               uint64_t* rkey,
                                               uint64_t* off);

// -- landing binds (batch plane) ------------------------------------------

// Binds cid → the exportable region holding [buf, buf+cap) so the
// request can advertise it as the response's remote-write target.  The
// buffer may sit at ANY offset inside the region's data area
// (collective pulls land shards mid-buffer); the offset is recorded
// locally and advertised, and resolve trusts only the LOCAL record.
// No-op when the buffer is not inside an exportable region, or when
// another in-flight cid is already bound to the same region — the
// region header holds ONE direct-transfer completion descriptor, so
// direct puts into one region are serialized; the striped copy path
// still catches the refused call.  Called by stripe_register_landing —
// one registration surface for both paths.
void rma_landing_bind(uint64_t cid, void* buf, size_t cap);
void rma_landing_unbind(uint64_t cid);
// The bound rkey for cid (0 = none); *max_out = usable bytes,
// *off_out = byte offset of the landing inside the region's data area.
uint64_t rma_landing_rkey(uint64_t cid, uint64_t* max_out,
                          uint64_t* off_out = nullptr);

// -- send (channel.cc / server.cc) ----------------------------------------

// Stamps meta's response-advertisement fields (tail-group 6) when cid has
// a bound exportable landing region AND the socket has an rma session —
// the server may then put the response straight into the caller's buffer.
void rma_advertise_response(SocketId sid, uint64_t cid, RpcMeta* meta);

// Attempts the one-sided path for meta+body on `primary`.
//   0  sent: body consumed, chunks written into the peer region, control
//      frame queued on the primary socket.
//   1  not applicable (below threshold, no session, descriptor path
//      preferred, window full): body untouched — caller falls back to
//      the stripe/frame path.
//  -1  hard failure (control write failed / fault reset): the call fails.
// target_rkey (from the request's advertisement) routes a response
// direct-to-region when the body fits target_max — written target_off
// bytes into the region's data area; otherwise the connection window
// is used.
// tok (net/deadline.h): the rail writers poll it between chunks — a
// cancelled request / expired budget stops the transfer within one
// chunk (remaining chunks never written, their bits never set, the
// control frame never sent, so the receiver's whole-or-nothing admit
// drops nothing partial; an abandoned window span is reclaimed by the
// scavenger).  Cancelled sends return -1.
int rma_try_send(SocketId primary, RpcMeta* meta, IOBuf* body,
                 uint64_t target_rkey, uint64_t target_max,
                 uint64_t target_off = 0,
                 const DeadlineToken& tok = DeadlineToken{});

// -- receive (messenger hook) ---------------------------------------------

// Resolves an rma control frame IN PLACE: validates the named region
// against the socket's session (or the cid's landing bind), checks the
// release-fenced completion bitmap and per-chunk CRCs, and swaps the
// out-of-band payload into msg->payload (window spans wrap zero-copy
// with a slot-freeing deleter; direct transfers wrap the caller's own
// buffer).  False: drop the message whole — the call times out, no
// partial bytes ever dispatch.
bool rma_resolve(InputMessage* msg, Socket* sock);

// Rails configured for a mode (trpc_shm_rails / trpc_ici_rails).
int rma_rails_for(int socket_mode);

// -- span scavenger --------------------------------------------------------

// Reclaims receive-window slots whose control frame never arrived (the
// documented span-leak-on-dropped-control degradation): a slot that has
// stayed allocated for longer than trpc_rma_span_scavenge_ms WITHOUT its
// span ever being admitted by rma_resolve is leaked — the sender's
// control frame was dropped (chaos) or its connection died mid-handoff —
// and is cleared back into the window.  Admitted spans are exempt for as
// long as any payload reference holds them, so a long-lived zero-copy
// consumer is never scavenged.  Runs lazily: piggybacked (rate-limited)
// on rma_resolve, from rma_spans_in_use (the drain quiesce poll), and
// callable directly.  Reclaims are counted by the rma_span_scavenged
// var.  The timeout must exceed the slowest legitimate write+control
// latency: a still-writing sender whose span is scavenged out from
// under it degrades to a failed call (token/bitmap/CRC verification
// rejects the stale transfer), never a torn admit — the same inherent
// shared-memory race class as the documented RmaBuffer reuse contract.
// `now_us` 0 reads the clock.  Returns slots reclaimed by THIS pass.
size_t rma_scavenge(int64_t now_us = 0);

// -- readiness maps (producer-stamped chunk-ready bitmaps) -----------------
//
// A ready map tracks which granularity-sized chunks of a producer's
// buffer have been filled, with the SAME release-fence discipline as
// the RMA completion bitmaps above: the producer stamps a range with a
// release fetch_or AFTER writing the bytes, and any consumer that
// observes the bit with an acquire load is guaranteed to see the
// producer's bytes.  Maps are process-local (the collective serve
// handlers and push loops run in the producer's process); the handle
// is an opaque non-zero token safe to pass through the C API.
//
// Used by the overlap-aware collective executor (net/collective.h):
// transfers whose compiled input dependency covers [off, off+len) fire
// as soon as the range is stamped instead of waiting for a
// whole-buffer barrier.

// Registers [base, base+len) with the given chunk granularity
// (bytes > 0; the final chunk may be short).  Returns a non-zero
// handle, or 0 on invalid arguments.
uint64_t rma_ready_create(const void* base, uint64_t len,
                          uint64_t granularity);

// Marks [off, off+len) ready.  `off` must be chunk-aligned and `len` a
// multiple of the granularity (or reach exactly to the end of the
// buffer); release-fenced against the producer's preceding writes.
// Stamping is monotonic — re-stamping a range is a no-op.  Wakes all
// range waiters.  Returns 0, or -1 on bad handle / misaligned or
// out-of-range span.
int rma_ready_stamp(uint64_t handle, uint64_t off, uint64_t len);

// True (1) when every chunk overlapping [off, off+len) is stamped;
// acquire-fenced so a true answer publishes the producer's bytes.
// 0 when not yet ready, -1 on bad handle / out-of-range span.
int rma_ready_test(uint64_t handle, uint64_t off, uint64_t len);

// Blocks until rma_ready_test(handle, off, len) would return 1, or the
// absolute deadline (monotonic µs; -1 = no deadline) passes.
// Fiber- and pthread-safe (fiber Event underneath).  Returns 0 ready,
// ETIMEDOUT on deadline, EINVAL on bad handle / span.
int rma_ready_wait(uint64_t handle, uint64_t off, uint64_t len,
                   int64_t deadline_us);

// Bytes stamped ready so far (monotonic; for stats/tests).
uint64_t rma_ready_bytes(uint64_t handle);

// Unregisters the map.  Pending waiters wake and observe EINVAL.
void rma_ready_destroy(uint64_t handle);

// Live map count (quiescence checks in tests).
size_t rma_ready_maps();

}  // namespace trpc
