#include "net/rtmp.h"

#include <errno.h>

#include <cstring>
#include <deque>
#include <mutex>

#include "base/logging.h"
#include "base/rand.h"
#include "base/sha256.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/messenger.h"
#include "net/protocol.h"
#include "net/server.h"

namespace trpc {

namespace {

constexpr size_t kHandshakeSize = 1536;

// Public Genuine-Adobe handshake keys (the 30/36-char strings plus a
// fixed 32-byte tail; both halves are published constants of the
// protocol, implemented by every open media server).
const uint8_t kGenuineTail[32] = {
    0xF0, 0xEE, 0xC2, 0x4A, 0x80, 0x68, 0xBE, 0xE8, 0x2E, 0x00, 0xD0,
    0xD1, 0x02, 0x9E, 0x7E, 0x57, 0x6E, 0xEC, 0x5D, 0x2D, 0x29, 0x80,
    0x6F, 0xAB, 0x93, 0xB8, 0xE6, 0x36, 0xCF, 0xEB, 0x31, 0xAE};
const char kFpKeyText[] = "Genuine Adobe Flash Player 001";       // 30
const char kFmsKeyText[] = "Genuine Adobe Flash Media Server 001";  // 36

// Partial key (text only) signs one's own C1/S1; the full key (text +
// tail) derives the S2/C2 ack key.
void handshake_keys(bool client, std::string* partial,
                    std::string* full) {
  const char* text = client ? kFpKeyText : kFmsKeyText;
  partial->assign(text);
  full->assign(text);
  full->append(reinterpret_cast<const char*>(kGenuineTail), 32);
}
constexpr uint32_t kDefaultChunkSize = 128;
constexpr uint32_t kOurChunkSize = 4096;
constexpr size_t kMaxMessage = 16u << 20;
constexpr uint32_t kCsidCommand = 3;
constexpr uint32_t kCsidMedia = 4;
constexpr int kMaxAmfDepth = 16;

void put_u8(std::string* out, uint8_t v) {
  out->push_back(static_cast<char>(v));
}

void put_u16be(std::string* out, uint16_t v) {
  out->push_back(static_cast<char>(v >> 8));
  out->push_back(static_cast<char>(v));
}

void put_u24be(std::string* out, uint32_t v) {
  out->push_back(static_cast<char>(v >> 16));
  out->push_back(static_cast<char>(v >> 8));
  out->push_back(static_cast<char>(v));
}

void put_u32be(std::string* out, uint32_t v) {
  out->push_back(static_cast<char>(v >> 24));
  put_u24be(out, v & 0xffffff);
}

void put_u32le(std::string* out, uint32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);
}

uint32_t read_u24be(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 16) |
         (static_cast<uint32_t>(p[1]) << 8) | p[2];
}

uint32_t read_u32be(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) | read_u24be(p + 1);
}

uint32_t read_u32le(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

}  // namespace

// ---- digest handshake ----------------------------------------------------

size_t rtmp_digest_offset(const uint8_t* hs, int scheme) {
  const size_t base = scheme == 0 ? 8 : 772;
  const uint32_t sum = hs[base] + hs[base + 1] + hs[base + 2] +
                       static_cast<uint32_t>(hs[base + 3]);
  return (sum % 728) + base + 4;
}

void rtmp_install_digest(std::string* hs, bool client) {
  std::string partial, full;
  handshake_keys(client, &partial, &full);
  const size_t off = rtmp_digest_offset(
      reinterpret_cast<const uint8_t*>(hs->data()), 0);
  // Digest = HMAC over the 1504 bytes AROUND the digest slot.
  std::string msg = hs->substr(0, off) + hs->substr(off + kSha256Size);
  uint8_t d[kSha256Size];
  hmac_sha256(partial.data(), partial.size(), msg.data(), msg.size(), d);
  hs->replace(off, kSha256Size, reinterpret_cast<const char*>(d),
              kSha256Size);
}

bool rtmp_verify_digest(const std::string& hs, bool client,
                        std::string* digest) {
  if (hs.size() != kHandshakeSize) {
    return false;
  }
  std::string partial, full;
  handshake_keys(client, &partial, &full);
  for (int scheme = 0; scheme < 2; ++scheme) {
    const size_t off = rtmp_digest_offset(
        reinterpret_cast<const uint8_t*>(hs.data()), scheme);
    std::string msg = hs.substr(0, off) + hs.substr(off + kSha256Size);
    uint8_t d[kSha256Size];
    hmac_sha256(partial.data(), partial.size(), msg.data(), msg.size(),
                d);
    if (memcmp(d, hs.data() + off, kSha256Size) == 0) {
      digest->assign(hs, off, kSha256Size);
      return true;
    }
  }
  return false;
}

void rtmp_make_digest_ack(const std::string& peer_digest, bool client,
                          std::string* out) {
  std::string partial, full;
  handshake_keys(client, &partial, &full);
  out->clear();
  out->reserve(kHandshakeSize);
  for (size_t i = 0; i < kHandshakeSize - kSha256Size; ++i) {
    out->push_back(static_cast<char>(fast_rand()));
  }
  // Two-stage: tmp = HMAC(full_key, peer_digest); tail = HMAC(tmp, body).
  uint8_t tmp[kSha256Size];
  hmac_sha256(full.data(), full.size(), peer_digest.data(),
              peer_digest.size(), tmp);
  uint8_t tail[kSha256Size];
  hmac_sha256(tmp, kSha256Size, out->data(), out->size(), tail);
  out->append(reinterpret_cast<const char*>(tail), kSha256Size);
}

// ---- AMF0 ----------------------------------------------------------------

Amf0Value Amf0Value::Number(double v) {
  Amf0Value a;
  a.type = kNumber;
  a.num = v;
  return a;
}
Amf0Value Amf0Value::Boolean(bool v) {
  Amf0Value a;
  a.type = kBool;
  a.b = v;
  return a;
}
Amf0Value Amf0Value::Str(std::string v) {
  Amf0Value a;
  a.type = kString;
  a.str = std::move(v);
  return a;
}
Amf0Value Amf0Value::Object(
    std::vector<std::pair<std::string, Amf0Value>> p) {
  Amf0Value a;
  a.type = kObject;
  a.props = std::move(p);
  return a;
}
Amf0Value Amf0Value::Null() { return Amf0Value(); }

const Amf0Value* Amf0Value::prop(const std::string& key) const {
  for (const auto& [k, v] : props) {
    if (k == key) return &v;
  }
  return nullptr;
}

bool Amf0Value::operator==(const Amf0Value& o) const {
  if (type != o.type) return false;
  switch (type) {
    case kNumber:
      return num == o.num;
    case kBool:
      return b == o.b;
    case kString:
      return str == o.str;
    case kObject:
    case kEcmaArray:
      return props == o.props;
    case kNull:
      return true;
  }
  return false;
}

void amf0_write(const Amf0Value& v, std::string* out) {
  put_u8(out, v.type);
  switch (v.type) {
    case Amf0Value::kNumber: {
      uint64_t bits;
      std::memcpy(&bits, &v.num, 8);
      for (int i = 7; i >= 0; --i) {
        put_u8(out, static_cast<uint8_t>(bits >> (8 * i)));
      }
      break;
    }
    case Amf0Value::kBool:
      put_u8(out, v.b ? 1 : 0);
      break;
    case Amf0Value::kString:
      put_u16be(out, static_cast<uint16_t>(v.str.size()));
      out->append(v.str);
      break;
    case Amf0Value::kEcmaArray:
      put_u32be(out, static_cast<uint32_t>(v.props.size()));
      [[fallthrough]];
    case Amf0Value::kObject:
      for (const auto& [k, pv] : v.props) {
        put_u16be(out, static_cast<uint16_t>(k.size()));
        out->append(k);
        amf0_write(pv, out);
      }
      put_u16be(out, 0);
      put_u8(out, 0x09);  // object end
      break;
    case Amf0Value::kNull:
      break;
  }
}

int amf0_read(const std::string& in, size_t* pos, Amf0Value* out,
              int depth) {
  if (depth > kMaxAmfDepth) return -1;
  if (*pos >= in.size()) return 0;
  const uint8_t type = static_cast<uint8_t>(in[*pos]);
  size_t p = *pos + 1;
  switch (type) {
    case Amf0Value::kNumber: {
      if (in.size() - p < 8) return 0;
      uint64_t bits = 0;
      for (int i = 0; i < 8; ++i) {
        bits = (bits << 8) | static_cast<uint8_t>(in[p + i]);
      }
      out->type = Amf0Value::kNumber;
      std::memcpy(&out->num, &bits, 8);
      *pos = p + 8;
      return 1;
    }
    case Amf0Value::kBool: {
      if (p >= in.size()) return 0;
      out->type = Amf0Value::kBool;
      out->b = in[p] != 0;
      *pos = p + 1;
      return 1;
    }
    case Amf0Value::kString: {
      if (in.size() - p < 2) return 0;
      const uint16_t len = static_cast<uint16_t>(
          (static_cast<uint8_t>(in[p]) << 8) |
          static_cast<uint8_t>(in[p + 1]));
      if (in.size() - p - 2 < len) return 0;
      out->type = Amf0Value::kString;
      out->str.assign(in, p + 2, len);
      *pos = p + 2 + len;
      return 1;
    }
    case Amf0Value::kEcmaArray:
      if (in.size() - p < 4) return 0;
      p += 4;  // declared count is advisory; terminator is authoritative
      [[fallthrough]];
    case Amf0Value::kObject: {
      out->type = static_cast<Amf0Value::Type>(type);
      out->props.clear();
      while (true) {
        if (in.size() - p < 2) return 0;
        const uint16_t klen = static_cast<uint16_t>(
            (static_cast<uint8_t>(in[p]) << 8) |
            static_cast<uint8_t>(in[p + 1]));
        if (in.size() - p - 2 < klen) return 0;
        if (klen == 0) {
          if (in.size() - p - 2 < 1) return 0;
          if (static_cast<uint8_t>(in[p + 2]) != 0x09) return -1;
          *pos = p + 3;
          return 1;
        }
        std::string key(in, p + 2, klen);
        p += 2 + klen;
        Amf0Value pv;
        size_t vp = p;
        const int rc = amf0_read(in, &vp, &pv, depth + 1);
        if (rc != 1) return rc;
        p = vp;
        out->props.emplace_back(std::move(key), std::move(pv));
        if (out->props.size() > 256) return -1;
      }
    }
    case Amf0Value::kNull:
    case 0x06:  // undefined decodes as null
      out->type = Amf0Value::kNull;
      *pos = p;
      return 1;
    default:
      return -1;  // types outside the condensed set
  }
}

// ---- connection state ----------------------------------------------------

namespace {

struct RtmpWaiter {
  CountdownEvent ev{1};
  bool ok = false;
  std::vector<Amf0Value> args;  // _result payload after the command name
};

struct RtmpConn {
  // Handshake progress.  Server: wait C0+C1, reply S0S1S2, wait C2.
  // Client: sent C0+C1, wait S0+S1+S2, reply C2.
  enum Phase { kAwaitC0C1, kAwaitC2, kAwaitS0S1S2, kChunks };
  Phase phase = kAwaitC0C1;
  bool is_client = false;
  bool use_digest = false;  // client: sent a digested C1
  Event handshook;  // value 1 once phase == kChunks (client connect waits)

  uint32_t in_chunk_size = kDefaultChunkSize;
  uint32_t out_chunk_size = kDefaultChunkSize;

  // Per-chunk-stream incoming assembly state.
  struct CsState {
    uint8_t type = 0;
    uint32_t ts = 0;
    uint32_t ts_delta = 0;
    uint32_t len = 0;
    uint32_t msid = 0;
    bool ext_ts = false;
    std::string partial;
  };
  std::map<uint32_t, CsState> cs_in;

  // Server-side roles.
  std::string publishing;  // non-empty: this connection publishes it
  std::vector<std::string> playing;

  // Client-side.
  std::mutex wmu;
  std::map<double, std::shared_ptr<RtmpWaiter>> by_txn;
  std::deque<std::shared_ptr<RtmpWaiter>> status_waiters;  // onStatus FIFO
  RtmpClient::MediaHandler on_media;
};

const char kRtmpSrvTag = 0;
const char kRtmpCliTag = 0;

RtmpConn* rtmp_conn_of(Socket* s, bool client) {
  return proto_conn_of<RtmpConn>(s, client ? &kRtmpCliTag : &kRtmpSrvTag);
}

// ---- chunk writer --------------------------------------------------------

// fmt0 message header for `m` (basic header + headers, no payload).
std::string pack_header(uint32_t csid, const RtmpMessage& m) {
  std::string out;
  const uint32_t ts = m.timestamp;
  const bool ext = ts >= 0xffffff;
  put_u8(&out, static_cast<uint8_t>(csid & 0x3f));
  put_u24be(&out, ext ? 0xffffff : ts);
  put_u24be(&out, static_cast<uint32_t>(m.payload.size()));
  put_u8(&out, m.type);
  put_u32le(&out, m.stream_id);
  if (ext) {
    put_u32be(&out, ts);
  }
  return out;
}

// Payload split into chunks with fmt3 continuation headers; everything
// AFTER the fmt0 header (shareable across fan-out targets whose only
// difference is the header's stream id).
void pack_tail(uint32_t csid, uint32_t chunk_size, const RtmpMessage& m,
               std::string* out) {
  const bool ext = m.timestamp >= 0xffffff;
  size_t off = 0;
  while (off < m.payload.size() || m.payload.empty()) {
    const size_t take =
        std::min<size_t>(chunk_size, m.payload.size() - off);
    out->append(m.payload, off, take);
    off += take;
    if (off >= m.payload.size()) {
      break;
    }
    put_u8(out, static_cast<uint8_t>(0xc0 | (csid & 0x3f)));  // fmt3
    if (ext) {
      put_u32be(out, m.timestamp);  // fmt3 repeats the extended ts
    }
  }
}

// Serializes one message as fmt0 + fmt3 continuation chunks.
void pack_message(const RtmpConn* conn, uint32_t csid,
                  const RtmpMessage& m, std::string* out) {
  out->append(pack_header(csid, m));
  pack_tail(csid, conn->out_chunk_size, m, out);
}

void write_message(Socket* sock, RtmpConn* conn, uint32_t csid,
                   const RtmpMessage& m) {
  std::string wire;
  pack_message(conn, csid, m, &wire);
  IOBuf out;
  out.append(wire);
  sock->Write(std::move(out));
}

void write_command(Socket* sock, RtmpConn* conn, uint32_t msid,
                   const std::vector<Amf0Value>& fields) {
  RtmpMessage m;
  m.type = static_cast<uint8_t>(RtmpMsgType::kCommandAmf0);
  m.stream_id = msid;
  for (const Amf0Value& f : fields) {
    amf0_write(f, &m.payload);
  }
  write_message(sock, conn, kCsidCommand, m);
}

void write_set_chunk_size(Socket* sock, RtmpConn* conn, uint32_t size) {
  RtmpMessage m;
  m.type = static_cast<uint8_t>(RtmpMsgType::kSetChunkSize);
  put_u32be(&m.payload, size);
  write_message(sock, conn, 2, m);
  conn->out_chunk_size = size;  // applies to subsequent messages
}

// ---- chunk reader --------------------------------------------------------

// Consumes ONE chunk if fully available.  1 = consumed (maybe completing
// *done_msg), 0 = need more bytes, -1 = corrupt.
int read_one_chunk(IOBuf* source, RtmpConn* conn, RtmpMessage* done_msg,
                   bool* completed) {
  *completed = false;
  uint8_t hdr[3 + 11 + 4];
  const size_t avail = source->copy_to(hdr, sizeof(hdr), 0);
  if (avail < 1) {
    return 0;
  }
  const uint8_t fmt = hdr[0] >> 6;
  uint32_t csid = hdr[0] & 0x3f;
  size_t pos = 1;
  if (csid == 0) {
    if (avail < 2) return 0;
    csid = 64 + hdr[1];
    pos = 2;
  } else if (csid == 1) {
    if (avail < 3) return 0;
    csid = 64 + hdr[1] + (static_cast<uint32_t>(hdr[2]) << 8);
    pos = 3;
  }
  RtmpConn::CsState& cs = conn->cs_in[csid];
  if (conn->cs_in.size() > 64) {
    return -1;  // bound per-connection chunk streams
  }
  const size_t mh_len = fmt == 0 ? 11 : fmt == 1 ? 7 : fmt == 2 ? 3 : 0;
  if (avail < pos + mh_len) {
    return 0;
  }
  const uint8_t* mh = hdr + pos;
  uint32_t ts_field = 0;
  switch (fmt) {
    case 0:
      ts_field = read_u24be(mh);
      cs.len = read_u24be(mh + 3);
      cs.type = mh[6];
      cs.msid = read_u32le(mh + 7);
      cs.ts_delta = 0;
      break;
    case 1:
      ts_field = read_u24be(mh);
      cs.len = read_u24be(mh + 3);
      cs.type = mh[6];
      cs.ts_delta = ts_field;
      break;
    case 2:
      ts_field = read_u24be(mh);
      cs.ts_delta = ts_field;
      break;
    case 3:
      break;
  }
  pos += mh_len;
  const bool ext = (fmt < 3 && ts_field == 0xffffff) ||
                   (fmt == 3 && cs.ext_ts);
  uint32_t ts_full = ts_field;
  if (ext) {
    if (avail < pos + 4) return 0;
    ts_full = read_u32be(hdr + pos);
    pos += 4;
  }
  cs.ext_ts = fmt < 3 ? ts_field == 0xffffff : cs.ext_ts;
  if (cs.len > kMaxMessage) {
    return -1;
  }
  const size_t remaining = cs.len - cs.partial.size();
  const size_t take = std::min<size_t>(conn->in_chunk_size, remaining);
  if (source->size() < pos + take) {
    return 0;
  }
  // Commit: timestamps only advance when a message STARTS.
  if (cs.partial.empty()) {
    if (fmt == 0) {
      cs.ts = ts_full;
    } else if (fmt == 3 && ext) {
      // A fmt3 chunk opening a NEW message repeats the extended field as
      // an ABSOLUTE timestamp (FFmpeg/OBS practice) — adding it as a
      // delta would double every post-0xffffff timestamp.
      cs.ts = ts_full;
    } else {
      cs.ts += ext ? ts_full : cs.ts_delta;
    }
  }
  source->pop_front(pos);
  IOBuf body;
  source->cutn(&body, take);
  const size_t old = cs.partial.size();
  cs.partial.resize(old + take);
  body.copy_to(cs.partial.data() + old, take, 0);
  if (cs.partial.size() >= cs.len) {
    done_msg->type = cs.type;
    done_msg->timestamp = cs.ts;
    done_msg->stream_id = cs.msid;
    done_msg->payload = std::move(cs.partial);
    cs.partial.clear();
    *completed = true;
  }
  return 1;
}

// Handles protocol-control messages INSIDE the parser (SetChunkSize must
// apply before the next chunk is cut).  True = consumed internally.
bool handle_control(RtmpConn* conn, const RtmpMessage& m) {
  switch (static_cast<RtmpMsgType>(m.type)) {
    case RtmpMsgType::kSetChunkSize:
      if (m.payload.size() >= 4) {
        const uint32_t sz = read_u32be(
            reinterpret_cast<const uint8_t*>(m.payload.data()));
        if (sz >= 1 && sz <= kMaxMessage) {
          conn->in_chunk_size = sz;
        }
      }
      return true;
    case RtmpMsgType::kAck:
    case RtmpMsgType::kWindowAckSize:
    case RtmpMsgType::kSetPeerBandwidth:
    case RtmpMsgType::kUserControl:
      return true;  // windows are advisory in the condensed scope
    default:
      return false;
  }
}

// Shared chunk-phase parse: cut chunks until one full app-level message.
ParseError parse_chunks(IOBuf* source, InputMessage* out, Socket* sock,
                        RtmpConn* conn) {
  while (true) {
    RtmpMessage msg;
    bool completed = false;
    const int rc = read_one_chunk(source, conn, &msg, &completed);
    if (rc < 0) {
      uint8_t dbg[16] = {};
      const size_t n = source->copy_to(dbg, sizeof(dbg), 0);
      char hex[64];
      for (size_t i = 0; i < n; ++i) {
        snprintf(hex + i * 3, 4, "%02x ", dbg[i]);
      }
      LOG(Warning) << "rtmp corrupt chunk, head: " << hex;
      return ParseError::kCorrupted;
    }
    if (rc == 0) {
      return ParseError::kNotEnoughData;
    }
    if (!completed) {
      continue;
    }
    if (handle_control(conn, msg)) {
      continue;
    }
    out->ctx = std::make_shared<RtmpMessage>(std::move(msg));
    out->socket = sock->id();
    return ParseError::kOk;
  }
}

// ---- server protocol -----------------------------------------------------

ParseError rtmp_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  const bool probing = sock->pinned_protocol < 0;
  if (probing) {
    Server* srv = static_cast<Server*>(sock->user_data);
    if (srv == nullptr || srv->rtmp_service() == nullptr) {
      return ParseError::kTryOtherProtocol;
    }
    // The 0x03 first-byte gate only applies to FRESH connections: once
    // the handshake machine is installed, later probe rounds see C2 /
    // chunk bytes (arbitrary leading byte) and must re-enter the
    // machine, not disclaim the connection.
    const bool ours = sock->parse_state != nullptr &&
                      sock->parse_state_owner == &kRtmpSrvTag;
    if (!ours && source->front() != 0x03) {
      return ParseError::kTryOtherProtocol;
    }
  }
  RtmpConn* conn = rtmp_conn_of(sock, /*client=*/false);
  if (conn->phase == RtmpConn::kAwaitC0C1) {
    // First byte 0x03 on an rtmp-enabled server is a strong claim
    // (checked above while probing): HOLD for the rest of C0+C1 —
    // kTryOtherProtocol on a fragmented handshake would fall through
    // every protocol and kill the connection.
    if (source->size() < 1 + kHandshakeSize) {
      return ParseError::kNotEnoughData;
    }
    uint8_t c0;
    source->copy_to(&c0, 1, 0);
    if (c0 != 0x03) {
      return probing ? ParseError::kTryOtherProtocol
                     : ParseError::kCorrupted;
    }
    source->pop_front(1);
    IOBuf c1;
    source->cutn(&c1, kHandshakeSize);
    const std::string c1s = c1.to_string();
    // A nonzero C1 version signals the digest handshake; validate the
    // client digest (either scheme) and answer with a digested S1 and
    // a keyed-ack S2.  Version 0 (or an unverifiable digest) takes the
    // plain path: random S1, S2 = echo of C1.
    std::string cdigest;
    const bool complex =
        (c1s[4] | c1s[5] | c1s[6] | c1s[7]) != 0 &&
        rtmp_verify_digest(c1s, /*client=*/true, &cdigest);
    std::string s1;
    put_u32be(&s1, 0);                            // time
    put_u32be(&s1, complex ? 0x04050001u : 0u);   // version
    for (size_t i = 0; i < kHandshakeSize - 8; ++i) {
      s1.push_back(static_cast<char>(fast_rand()));
    }
    IOBuf reply;
    reply.append("\x03", 1);
    if (complex) {
      rtmp_install_digest(&s1, /*client=*/false);
      reply.append(s1);
      std::string s2;
      rtmp_make_digest_ack(cdigest, /*client=*/false, &s2);
      reply.append(s2);
    } else {
      reply.append(s1);
      reply.append(c1);  // S2
    }
    sock->Write(std::move(reply));
    conn->phase = RtmpConn::kAwaitC2;
  }
  if (conn->phase == RtmpConn::kAwaitC2) {
    if (source->size() < kHandshakeSize) {
      return ParseError::kNotEnoughData;
    }
    source->pop_front(kHandshakeSize);
    conn->phase = RtmpConn::kChunks;
  }
  return parse_chunks(source, out, sock, conn);
}

double amf_number_or(const std::vector<Amf0Value>& v, size_t i,
                     double def) {
  return i < v.size() && v[i].type == Amf0Value::kNumber ? v[i].num : def;
}

std::string amf_string_or(const std::vector<Amf0Value>& v, size_t i,
                          const std::string& def) {
  return i < v.size() && v[i].type == Amf0Value::kString ? v[i].str : def;
}

std::vector<Amf0Value> decode_amf_list(const std::string& payload) {
  std::vector<Amf0Value> out;
  size_t pos = 0;
  while (pos < payload.size() && out.size() < 16) {
    Amf0Value v;
    if (amf0_read(payload, &pos, &v) != 1) {
      break;
    }
    out.push_back(std::move(v));
  }
  return out;
}

Amf0Value status_info(const std::string& code, const std::string& desc) {
  return Amf0Value::Object({{"level", Amf0Value::Str("status")},
                            {"code", Amf0Value::Str(code)},
                            {"description", Amf0Value::Str(desc)}});
}

void send_on_status(Socket* sock, RtmpConn* conn, uint32_t msid,
                    const std::string& code) {
  write_command(sock, conn, msid,
                {Amf0Value::Str("onStatus"), Amf0Value::Number(0),
                 Amf0Value::Null(), status_info(code, code)});
}

void rtmp_process_request(InputMessage&& imsg) {
  SocketRef sock(Socket::Address(imsg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  auto msg = std::static_pointer_cast<RtmpMessage>(imsg.ctx);
  if (srv == nullptr || srv->rtmp_service() == nullptr || msg == nullptr) {
    return;
  }
  RtmpService* svc = srv->rtmp_service();
  RtmpConn* conn = rtmp_conn_of(sock.get(), /*client=*/false);

  const RtmpMsgType t = static_cast<RtmpMsgType>(msg->type);
  if (t == RtmpMsgType::kAudio || t == RtmpMsgType::kVideo ||
      t == RtmpMsgType::kDataAmf0) {
    // Publisher media: relay to every player of the stream.
    if (conn->publishing.empty()) {
      return;  // media from a non-publisher: drop
    }
    srv->requests_served.fetch_add(1, std::memory_order_relaxed);
    if (svc->observer()) {
      svc->observer()(conn->publishing, *msg);
    }
    std::vector<std::pair<SocketId, uint32_t>> players;
    {
      LockGuard<FiberMutex> g(svc->mu);
      auto it = svc->hubs.find(conn->publishing);
      if (it != svc->hubs.end()) {
        players = it->second.players;
      }
    }
    // Fan-out: the chunked payload tail is identical for every player
    // (only the fmt0 header's stream id differs), so it is packed ONCE
    // and its blocks are SHARED into each player's write — one payload
    // copy total, not one per player.  Players negotiated to a different
    // chunk size (none today; SetChunkSize goes out on connect) fall
    // back to a private pack.
    IOBuf shared_tail;
    {
      std::string tail;
      pack_tail(kCsidMedia, kOurChunkSize, *msg, &tail);
      shared_tail.append(tail);
    }
    std::vector<SocketId> dead;
    for (const auto& [sid, msid] : players) {
      SocketRef ps(Socket::Address(sid));
      if (!ps || ps->Failed()) {
        dead.push_back(sid);
        continue;
      }
      RtmpConn* pconn = rtmp_conn_of(ps.get(), /*client=*/false);
      RtmpMessage relay;
      relay.type = msg->type;
      relay.timestamp = msg->timestamp;
      relay.stream_id = msid;
      IOBuf out;
      if (pconn->out_chunk_size == kOurChunkSize) {
        out.append(pack_header(kCsidMedia, *msg).substr(0, 8) +
                   [msid] {
                     std::string le;
                     put_u32le(&le, msid);
                     return le;
                   }());
        out.append(shared_tail);  // zero-copy block share
      } else {
        relay.payload = msg->payload;
        std::string wire;
        pack_message(pconn, kCsidMedia, relay, &wire);
        out.append(wire);
      }
      ps->Write(std::move(out));
    }
    if (!dead.empty()) {
      // Reap players whose sockets died without deleteStream; drop the
      // hub entirely once nothing references it (unbounded growth from
      // viewer churn otherwise).
      LockGuard<FiberMutex> g(svc->mu);
      auto it = svc->hubs.find(conn->publishing);
      if (it != svc->hubs.end()) {
        auto& pl = it->second.players;
        for (SocketId d : dead) {
          for (auto pit = pl.begin(); pit != pl.end();) {
            if (pit->first == d) {
              pit = pl.erase(pit);
            } else {
              ++pit;
            }
          }
        }
      }
    }
    return;
  }
  if (t != RtmpMsgType::kCommandAmf0) {
    return;
  }

  std::vector<Amf0Value> cmd = decode_amf_list(msg->payload);
  const std::string name = amf_string_or(cmd, 0, "");
  const double txn = amf_number_or(cmd, 1, 0);
  srv->requests_served.fetch_add(1, std::memory_order_relaxed);

  {  // Interceptor gate for the command surface.
    int ec = 0;
    std::string et;
    if (!srv->accept_request("rtmp." + name, sock->remote(), &ec, &et)) {
      sock->SetFailed(EACCES);
      return;
    }
  }

  if (name == "connect") {
    // Control burst, then the connect _result.
    RtmpMessage was;
    was.type = static_cast<uint8_t>(RtmpMsgType::kWindowAckSize);
    put_u32be(&was.payload, 2500000);
    write_message(sock.get(), conn, 2, was);
    RtmpMessage spb;
    spb.type = static_cast<uint8_t>(RtmpMsgType::kSetPeerBandwidth);
    put_u32be(&spb.payload, 2500000);
    put_u8(&spb.payload, 2);
    write_message(sock.get(), conn, 2, spb);
    write_set_chunk_size(sock.get(), conn, kOurChunkSize);
    write_command(
        sock.get(), conn, 0,
        {Amf0Value::Str("_result"), Amf0Value::Number(txn),
         Amf0Value::Object({{"fmsVer", Amf0Value::Str("TRPC/1,0")},
                            {"capabilities", Amf0Value::Number(31)}}),
         Amf0Value::Object(
             {{"level", Amf0Value::Str("status")},
              {"code",
               Amf0Value::Str("NetConnection.Connect.Success")},
              {"description", Amf0Value::Str("Connection succeeded.")}})});
    return;
  }
  if (name == "createStream") {
    static std::atomic<uint32_t> next_msid{1};
    write_command(sock.get(), conn, 0,
                  {Amf0Value::Str("_result"), Amf0Value::Number(txn),
                   Amf0Value::Null(),
                   Amf0Value::Number(next_msid.fetch_add(1))});
    return;
  }
  if (name == "releaseStream" || name == "FCPublish" ||
      name == "FCUnpublish" || name == "getStreamLength") {
    write_command(sock.get(), conn, 0,
                  {Amf0Value::Str("_result"), Amf0Value::Number(txn),
                   Amf0Value::Null(), Amf0Value::Null()});
    return;
  }
  if (name == "publish") {
    const std::string stream = amf_string_or(cmd, 3, "");
    if (stream.empty()) {
      send_on_status(sock.get(), conn, msg->stream_id,
                     "NetStream.Publish.BadName");
      return;
    }
    bool taken = false;
    {
      LockGuard<FiberMutex> g(svc->mu);
      RtmpService::Hub& hub = svc->hubs[stream];
      if (hub.publisher != 0 && hub.publisher != sock->id()) {
        SocketRef other(Socket::Address(hub.publisher));
        taken = other && !other->Failed();
      }
      if (!taken) {
        hub.publisher = sock->id();
      }
    }
    if (taken) {
      send_on_status(sock.get(), conn, msg->stream_id,
                     "NetStream.Publish.BadName");
      return;
    }
    conn->publishing = stream;
    send_on_status(sock.get(), conn, msg->stream_id,
                   "NetStream.Publish.Start");
    return;
  }
  if (name == "play") {
    const std::string stream = amf_string_or(cmd, 3, "");
    if (stream.empty()) {
      send_on_status(sock.get(), conn, msg->stream_id,
                     "NetStream.Play.StreamNotFound");
      return;
    }
    {
      LockGuard<FiberMutex> g(svc->mu);
      svc->hubs[stream].players.emplace_back(sock->id(), msg->stream_id);
    }
    conn->playing.push_back(stream);
    // UserControl StreamBegin(msid).
    RtmpMessage sb;
    sb.type = static_cast<uint8_t>(RtmpMsgType::kUserControl);
    put_u16be(&sb.payload, 0);
    put_u32be(&sb.payload, msg->stream_id);
    write_message(sock.get(), conn, 2, sb);
    send_on_status(sock.get(), conn, msg->stream_id,
                   "NetStream.Play.Start");
    return;
  }
  if (name == "deleteStream" || name == "closeStream") {
    const uint32_t msid = static_cast<uint32_t>(amf_number_or(cmd, 3, 0));
    LockGuard<FiberMutex> g(svc->mu);
    if (!conn->publishing.empty()) {
      auto it = svc->hubs.find(conn->publishing);
      if (it != svc->hubs.end() && it->second.publisher == sock->id()) {
        it->second.publisher = 0;
      }
      conn->publishing.clear();
    }
    for (const std::string& stream : conn->playing) {
      auto it = svc->hubs.find(stream);
      if (it == svc->hubs.end()) {
        continue;
      }
      auto& pl = it->second.players;
      for (auto pit = pl.begin(); pit != pl.end();) {
        if (pit->first == sock->id() &&
            (msid == 0 || pit->second == msid)) {
          pit = pl.erase(pit);
        } else {
          ++pit;
        }
      }
    }
    return;
  }
  // Unknown command: _error keeps well-behaved clients moving.
  write_command(sock.get(), conn, 0,
                {Amf0Value::Str("_error"), Amf0Value::Number(txn),
                 Amf0Value::Null(),
                 status_info("NetConnection.Call.Failed", name)});
}

void rtmp_process_response(InputMessage&&) {}

}  // namespace

size_t RtmpService::publisher_count() const {
  LockGuard<FiberMutex> g(mu);
  size_t n = 0;
  for (const auto& [name, hub] : hubs) {
    if (hub.publisher != 0) {
      ++n;
    }
  }
  return n;
}

size_t RtmpService::player_count(const std::string& name) const {
  LockGuard<FiberMutex> g(mu);
  auto it = hubs.find(name);
  return it == hubs.end() ? 0 : it->second.players.size();
}

void register_rtmp_protocol() {
  static int once = [] {
    Protocol p = {"rtmp", rtmp_parse, rtmp_process_request,
                  rtmp_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  (void)once;
}

// ---- client --------------------------------------------------------------

namespace {

ParseError rtmpc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    return ParseError::kTryOtherProtocol;
  }
  RtmpConn* conn = rtmp_conn_of(sock, /*client=*/true);
  if (conn->phase == RtmpConn::kAwaitS0S1S2) {
    if (source->size() < 1 + 2 * kHandshakeSize) {
      return ParseError::kNotEnoughData;
    }
    uint8_t s0;
    source->copy_to(&s0, 1, 0);
    if (s0 != 0x03) {
      return ParseError::kCorrupted;
    }
    source->pop_front(1);
    IOBuf s1;
    source->cutn(&s1, kHandshakeSize);
    source->pop_front(kHandshakeSize);  // S2 (ack/echo of our C1; trusted)
    std::string sdigest;
    if (conn->use_digest &&
        rtmp_verify_digest(s1.to_string(), /*client=*/false, &sdigest)) {
      std::string c2;
      rtmp_make_digest_ack(sdigest, /*client=*/true, &c2);
      IOBuf out;
      out.append(c2);
      sock->Write(std::move(out));
    } else {
      sock->Write(std::move(s1));  // C2 = echo of S1 (plain handshake)
    }
    conn->phase = RtmpConn::kChunks;
    conn->handshook.value.store(1, std::memory_order_release);
    conn->handshook.wake_all();
  }
  ParseError rc = parse_chunks(source, out, sock, conn);
  if (rc == ParseError::kOk) {
    out->meta.type = RpcMeta::kResponse;
  }
  return rc;
}

void rtmpc_process_response(InputMessage&& imsg) {
  SocketRef sock(Socket::Address(imsg.socket));
  if (!sock) {
    return;
  }
  auto msg = std::static_pointer_cast<RtmpMessage>(imsg.ctx);
  RtmpConn* conn = rtmp_conn_of(sock.get(), /*client=*/true);
  const RtmpMsgType t = static_cast<RtmpMsgType>(msg->type);
  if (t == RtmpMsgType::kAudio || t == RtmpMsgType::kVideo ||
      t == RtmpMsgType::kDataAmf0) {
    if (conn->on_media) {
      conn->on_media(*msg);
    }
    return;
  }
  if (t != RtmpMsgType::kCommandAmf0) {
    return;
  }
  std::vector<Amf0Value> cmd = decode_amf_list(msg->payload);
  const std::string name = amf_string_or(cmd, 0, "");
  if (name == "_result" || name == "_error") {
    const double txn = amf_number_or(cmd, 1, 0);
    std::shared_ptr<RtmpWaiter> w;
    {
      std::lock_guard<std::mutex> g(conn->wmu);
      auto it = conn->by_txn.find(txn);
      if (it == conn->by_txn.end()) {
        return;
      }
      w = std::move(it->second);
      conn->by_txn.erase(it);
    }
    w->ok = name == "_result";
    w->args.assign(cmd.begin() + (cmd.size() > 2 ? 2 : cmd.size()),
                   cmd.end());
    w->ev.signal();
    return;
  }
  if (name == "onStatus") {
    std::shared_ptr<RtmpWaiter> w;
    {
      std::lock_guard<std::mutex> g(conn->wmu);
      if (conn->status_waiters.empty()) {
        return;
      }
      w = std::move(conn->status_waiters.front());
      conn->status_waiters.pop_front();
    }
    const Amf0Value* info =
        cmd.size() > 3 ? &cmd[3] : nullptr;
    const Amf0Value* code =
        info != nullptr ? info->prop("code") : nullptr;
    w->ok = code != nullptr && code->type == Amf0Value::kString &&
            (code->str.find(".Start") != std::string::npos);
    w->args.assign(cmd.begin() + (cmd.size() > 2 ? 2 : cmd.size()),
                   cmd.end());
    w->ev.signal();
    return;
  }
}

void rtmpc_process_request(InputMessage&&) {}

int rtmpc_protocol_index() {
  static const int index = [] {
    Protocol p = {"rtmpc", rtmpc_parse, rtmpc_process_request,
                  rtmpc_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

}  // namespace

RtmpClient::~RtmpClient() {
  csock_.Shutdown();
}

int RtmpClient::Init(const std::string& addr, const Options* opts) {
  fiber_init(0);
  if (opts != nullptr) {
    opts_ = *opts;
  }
  rtmpc_protocol_index();
  return csock_.Init(addr);
}

int RtmpClient::ensure_connected() {
  SocketId sid = 0;
  const bool digest = opts_.use_digest;
  auto install = [digest](Socket* s) -> int {
    RtmpConn* conn = rtmp_conn_of(s, /*client=*/true);
    conn->is_client = true;
    conn->use_digest = digest;
    conn->phase = RtmpConn::kAwaitS0S1S2;
    // C0 + C1 (nonzero version announces the digest handshake).
    std::string c1;
    put_u32be(&c1, 0);
    put_u32be(&c1, digest ? 0x80000702u : 0u);
    for (size_t i = 0; i < kHandshakeSize - 8; ++i) {
      c1.push_back(static_cast<char>(fast_rand()));
    }
    if (digest) {
      rtmp_install_digest(&c1, /*client=*/true);
    }
    IOBuf out;
    out.append("\x03", 1);
    out.append(c1);
    return s->Write(std::move(out));
  };
  if (csock_.ensure(rtmpc_protocol_index(), install, &sid) != 0) {
    return -1;
  }
  if (sid != last_sid_) {
    // ensure() replaced a failed socket: the fresh connection is mid-
    // handshake and unconnected regardless of what the old one was.
    connected_ = false;
    last_sid_ = sid;
  }
  if (connected_) {
    return 0;
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    return -1;
  }
  RtmpConn* conn = rtmp_conn_of(s.get(), /*client=*/true);
  const int64_t deadline =
      monotonic_time_us() + opts_.timeout_ms * 1000;
  if (conn->handshook.wait(0, deadline) == ETIMEDOUT) {
    return -1;
  }
  // connect(app) — txn 1 by convention.
  auto w = std::make_shared<RtmpWaiter>();
  {
    std::lock_guard<std::mutex> g(conn->wmu);
    conn->by_txn.emplace(1.0, w);
  }
  write_set_chunk_size(s.get(), conn, kOurChunkSize);
  write_command(
      s.get(), conn, 0,
      {Amf0Value::Str("connect"), Amf0Value::Number(1),
       Amf0Value::Object({{"app", Amf0Value::Str(opts_.app)},
                          {"flashVer", Amf0Value::Str("TRPC/1.0")},
                          {"tcUrl", Amf0Value::Str(
                                        "rtmp://" +
                                        endpoint2str(csock_.endpoint()) +
                                        "/" + opts_.app)}})});
  if (w->ev.wait(deadline) != 0 || !w->ok) {
    std::lock_guard<std::mutex> g(conn->wmu);
    conn->by_txn.erase(1.0);  // a retried connect must get a fresh slot
    return -1;
  }
  connected_ = true;
  return 0;
}

int RtmpClient::connect() {
  LockGuard<FiberMutex> g(mu_);
  return ensure_connected();
}

int RtmpClient::create_stream(uint32_t* msid) {
  LockGuard<FiberMutex> g(mu_);
  if (ensure_connected() != 0) {
    return -1;
  }
  SocketId sid = 0;
  if (csock_.ensure(rtmpc_protocol_index(), nullptr, &sid) != 0) {
    return -1;
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    return -1;
  }
  RtmpConn* conn = rtmp_conn_of(s.get(), /*client=*/true);
  const double txn = next_txn_++;
  auto w = std::make_shared<RtmpWaiter>();
  {
    std::lock_guard<std::mutex> g2(conn->wmu);
    conn->by_txn.emplace(txn, w);
  }
  write_command(s.get(), conn, 0,
                {Amf0Value::Str("createStream"), Amf0Value::Number(txn),
                 Amf0Value::Null()});
  const int64_t deadline =
      monotonic_time_us() + opts_.timeout_ms * 1000;
  if (w->ev.wait(deadline) != 0 || !w->ok) {
    std::lock_guard<std::mutex> g2(conn->wmu);
    conn->by_txn.erase(txn);
    return -1;
  }
  // args = [command-object(null), stream id]
  for (const Amf0Value& a : w->args) {
    if (a.type == Amf0Value::kNumber) {
      *msid = static_cast<uint32_t>(a.num);
      return 0;
    }
  }
  return -1;
}

namespace {

int verb_with_status(ClientSocket* csock, double* next_txn,
                     int64_t timeout_ms, int proto_index,
                     const std::string& verb, uint32_t msid,
                     const std::string& stream,
                     RtmpClient::MediaHandler on_media) {
  SocketId sid = 0;
  if (csock->ensure(proto_index, nullptr, &sid) != 0) {
    return -1;
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    return -1;
  }
  RtmpConn* conn = rtmp_conn_of(s.get(), /*client=*/true);
  if (on_media) {
    conn->on_media = std::move(on_media);
  }
  const double txn = (*next_txn)++;
  auto w = std::make_shared<RtmpWaiter>();
  {
    std::lock_guard<std::mutex> g(conn->wmu);
    conn->status_waiters.push_back(w);
  }
  std::vector<Amf0Value> cmd = {Amf0Value::Str(verb),
                                Amf0Value::Number(txn),
                                Amf0Value::Null(),
                                Amf0Value::Str(stream)};
  if (verb == "publish") {
    cmd.push_back(Amf0Value::Str("live"));
  }
  write_command(s.get(), conn, msid, cmd);
  const int64_t deadline = monotonic_time_us() + timeout_ms * 1000;
  if (w->ev.wait(deadline) != 0 || !w->ok) {
    // A timed-out waiter must leave the FIFO or it mispairs the NEXT
    // onStatus with the wrong verb.
    std::lock_guard<std::mutex> g(conn->wmu);
    for (auto it = conn->status_waiters.begin();
         it != conn->status_waiters.end(); ++it) {
      if (*it == w) {
        conn->status_waiters.erase(it);
        break;
      }
    }
    return -1;
  }
  return 0;
}

}  // namespace

int RtmpClient::publish(uint32_t msid, const std::string& name) {
  LockGuard<FiberMutex> g(mu_);
  if (ensure_connected() != 0) {
    return -1;
  }
  return verb_with_status(&csock_, &next_txn_, opts_.timeout_ms,
                          rtmpc_protocol_index(), "publish", msid, name,
                          nullptr);
}

int RtmpClient::play(uint32_t msid, const std::string& name,
                     MediaHandler on_media) {
  LockGuard<FiberMutex> g(mu_);
  if (ensure_connected() != 0) {
    return -1;
  }
  return verb_with_status(&csock_, &next_txn_, opts_.timeout_ms,
                          rtmpc_protocol_index(), "play", msid, name,
                          std::move(on_media));
}

int RtmpClient::send_media(uint32_t msid, RtmpMsgType type,
                           uint32_t timestamp, const std::string& payload) {
  LockGuard<FiberMutex> g(mu_);
  if (ensure_connected() != 0) {
    return -1;
  }
  SocketId sid = 0;
  if (csock_.ensure(rtmpc_protocol_index(), nullptr, &sid) != 0) {
    return -1;
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    return -1;
  }
  RtmpConn* conn = rtmp_conn_of(s.get(), /*client=*/true);
  RtmpMessage m;
  m.type = static_cast<uint8_t>(type);
  m.timestamp = timestamp;
  m.stream_id = msid;
  m.payload = payload;
  write_message(s.get(), conn, kCsidMedia, m);
  return 0;
}

}  // namespace trpc
