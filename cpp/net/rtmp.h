// RTMP media substrate — handshake, chunk streams, AMF0, publish/play relay.
//
// Parity: the reference carries a full media-server substrate
// (/root/reference/src/brpc/rtmp.{h,cpp} ~3.8k, policy/rtmp_protocol.cpp
// ~3.7k, amf.* ~1.5k: RtmpService with server streams, client streams,
// retrying clients, FLV/TS muxing).  Condensed tpu-native scope — the
// live-relay core a media server is built from:
//   - plain (non-digest) C0/C1/C2 handshake,
//   - chunk-stream codec both directions (fmt0-3 headers, extended
//     timestamps, SetChunkSize both ways, message reassembly),
//   - AMF0 codec (number/bool/string/object/null/ecma-array),
//   - the NetConnection/NetStream command flow (connect, createStream,
//     publish, play, deleteStream) with _result/onStatus replies,
//   - publisher -> players relay of audio/video/data messages keyed by
//     stream name (the RtmpService registry),
//   - the digest ("complex") handshake both ways (HMAC-SHA256 with the
//     public Genuine-FP/FMS keys, both schemes on verify),
//   - FLV muxing/demuxing (net/flv.h) fed by the media observer.
// Out of scope (kept to the registries): RTMPS (ride the TLS transport),
// MPEG-TS muxing, aggregate messages, shared objects.
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "base/endpoint.h"
#include "base/iobuf.h"
#include "fiber/sync.h"
#include "net/proto_client.h"
#include "net/socket.h"

namespace trpc {

class Server;

// ---- AMF0 ----------------------------------------------------------------

struct Amf0Value {
  enum Type : uint8_t {
    kNumber = 0x00,
    kBool = 0x01,
    kString = 0x02,
    kObject = 0x03,
    kNull = 0x05,
    kEcmaArray = 0x08,
  };
  Type type = kNull;
  double num = 0;
  bool b = false;
  std::string str;
  // object / ecma array properties, in order.
  std::vector<std::pair<std::string, Amf0Value>> props;

  static Amf0Value Number(double v);
  static Amf0Value Boolean(bool v);
  static Amf0Value Str(std::string v);
  static Amf0Value Object(std::vector<std::pair<std::string, Amf0Value>> p);
  static Amf0Value Null();

  const Amf0Value* prop(const std::string& key) const;
  bool operator==(const Amf0Value& o) const;
};

void amf0_write(const Amf0Value& v, std::string* out);
// 1 ok / 0 partial / -1 malformed; depth-bounded.
int amf0_read(const std::string& in, size_t* pos, Amf0Value* out,
              int depth = 0);

// ---- digest ("complex") handshake ---------------------------------------
// Flash's digest handshake: C1/S1 carry an HMAC-SHA256 digest at an
// offset derived from four offset bytes (scheme 0: bytes 8..11, digest
// block first; scheme 1: bytes 772..775, key block first), keyed by the
// public Genuine-FP/FMS partial keys; S2/C2 ack the peer's digest with
// a two-stage HMAC.  Exposed for tests.

// Offset of the 32-byte digest inside a 1536-byte C1/S1 for `scheme`
// (0 or 1); always in range by construction.
size_t rtmp_digest_offset(const uint8_t* hs, int scheme);
// Computes and installs the scheme-0 digest into a fully-built
// 1536-byte C1 (client=true) / S1 (false).
void rtmp_install_digest(std::string* hs, bool client);
// Tries both schemes; true when a digest validates, filling *digest.
bool rtmp_verify_digest(const std::string& hs, bool client,
                        std::string* digest);
// Builds the 1536-byte S2 (client=false) / C2 (true) acknowledging the
// peer's validated digest.
void rtmp_make_digest_ack(const std::string& peer_digest, bool client,
                          std::string* out);

// ---- messages ------------------------------------------------------------

// RTMP message types used here (public spec values).
enum class RtmpMsgType : uint8_t {
  kSetChunkSize = 1,
  kAck = 3,
  kUserControl = 4,
  kWindowAckSize = 5,
  kSetPeerBandwidth = 6,
  kAudio = 8,
  kVideo = 9,
  kDataAmf0 = 18,
  kCommandAmf0 = 20,
};

struct RtmpMessage {
  uint8_t type = 0;
  uint32_t timestamp = 0;
  uint32_t stream_id = 0;  // message stream id (little-endian on wire)
  std::string payload;
};

// ---- server side ---------------------------------------------------------

// Publish/play registry; assign via Server::set_rtmp_service.  A media
// callback observes every relayed message (hooks for recording etc.).
class RtmpService {
 public:
  using MediaObserver = std::function<void(
      const std::string& stream_name, const RtmpMessage& msg)>;

  void set_media_observer(MediaObserver ob) { observer_ = std::move(ob); }
  const MediaObserver& observer() const { return observer_; }

  // Introspection (tests, /status).
  size_t publisher_count() const;
  size_t player_count(const std::string& name) const;

  // -- internal (protocol) --
  struct Hub {
    SocketId publisher = 0;
    std::vector<std::pair<SocketId, uint32_t>> players;  // (socket, msid)
  };
  mutable FiberMutex mu;
  std::map<std::string, Hub> hubs;

 private:
  MediaObserver observer_;
};

void register_rtmp_protocol();

// ---- client side ---------------------------------------------------------

class RtmpClient {
 public:
  struct Options {
    int64_t timeout_ms = 2000;
    std::string app = "live";
    // Digest (complex) handshake: C1 carries an FP-keyed digest and C2
    // acks the server digest instead of echoing S1.
    bool use_digest = false;
  };
  using MediaHandler = std::function<void(const RtmpMessage& msg)>;

  ~RtmpClient();
  int Init(const std::string& addr, const Options* opts = nullptr);

  // Handshake + connect(app).  0 on success.  Called implicitly by the
  // verbs below when needed.
  int connect();
  // createStream; fills *msid.
  int create_stream(uint32_t* msid);
  // Start publishing `name` on msid.
  int publish(uint32_t msid, const std::string& name);
  // Start playing `name` on msid; media messages arrive on `on_media`
  // (called inline on the read fiber).
  int play(uint32_t msid, const std::string& name, MediaHandler on_media);
  // Send one audio/video/data message on a published stream.
  int send_media(uint32_t msid, RtmpMsgType type, uint32_t timestamp,
                 const std::string& payload);

 private:
  int ensure_connected();  // under mu_

  Options opts_;
  FiberMutex mu_;
  ClientSocket csock_;
  bool connected_ = false;
  SocketId last_sid_ = 0;  // detects ensure() replacing a failed socket
  double next_txn_ = 2;    // txn 1 is connect
};

}  // namespace trpc
