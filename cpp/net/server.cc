#include "net/server.h"

#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include "base/logging.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/http_protocol.h"
#include "net/messenger.h"
#include "net/stream.h"
#include "net/protocol.h"

namespace trpc {

int Server::RegisterMethod(const std::string& full_name, Handler handler) {
  if (running()) {
    return -1;
  }
  MethodProperty prop;
  prop.handler = std::move(handler);
  prop.latency = std::make_shared<LatencyRecorder>();
  prop.latency->expose("rpc_server_" + full_name);
  methods_[full_name] = std::move(prop);
  return 0;
}

int Server::Start(int port) {
  fiber_init(0);
  tstd_protocol();  // ensure registered (first: most traffic is RPC)
  register_http_protocol();
  start_time_us_ = monotonic_time_us();
  const int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) {
    return -1;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(port > 0 ? static_cast<uint16_t>(port) : 0);
  if (bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0 ||
      listen(fd, 1024) != 0) {
    close(fd);
    return -1;
  }
  socklen_t len = sizeof(sa);
  getsockname(fd, reinterpret_cast<sockaddr*>(&sa), &len);
  port_ = ntohs(sa.sin_port);

  Socket::Options opts;
  opts.fd = fd;
  opts.on_readable = &Server::on_acceptable;
  opts.ctx = this;
  opts.user_data = this;
  if (Socket::Create(opts, &listen_id_) != 0) {
    close(fd);
    return -1;
  }
  running_.store(true, std::memory_order_release);
  LOG(Info) << "server started on 127.0.0.1:" << port_;
  return 0;
}

void Server::Stop() {
  if (!running_.exchange(false)) {
    return;
  }
  Socket* s = Socket::Address(listen_id_);
  if (s != nullptr) {
    s->SetFailed(ESHUTDOWN);
    s->Dereference();
  }
}

// Accept-until-EAGAIN (acceptor.cpp:251 parity); runs in the listen
// socket's read fiber.
void Server::on_acceptable(SocketId id, void* ctx) {
  Server* srv = static_cast<Server*>(ctx);
  Socket* listener = Socket::Address(id);
  if (listener == nullptr) {
    return;
  }
  while (true) {
    const int fd = accept4(listener->fd(), nullptr, nullptr,
                           SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) {
      break;  // EAGAIN or error; ET will refire on next connection
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    Socket::Options opts;
    opts.fd = fd;
    opts.on_readable = &messenger_on_readable;
    opts.user_data = srv;
    SocketId conn_id = 0;
    if (Socket::Create(opts, &conn_id) != 0) {
      close(fd);
      continue;
    }
  }
  listener->Dereference();
}

// ---- request execution (tstd protocol hook) -----------------------------

void tstd_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  const SocketId socket_id = msg.socket;
  const uint64_t cid = msg.meta.correlation_id;
  const std::string method = msg.meta.method;

  auto* cntl = new Controller();
  cntl->set_method(method);
  cntl->call().socket_id = socket_id;
  cntl->call().peer_stream = msg.meta.stream_id;
  cntl->call().peer_stream_window = msg.meta.ack_bytes;
  auto* response = new IOBuf();
  const int64_t start_us = monotonic_time_us();
  const Server::MethodProperty* prop =
      (srv != nullptr && srv->running()) ? srv->find_method(method) : nullptr;
  std::shared_ptr<LatencyRecorder> lat =
      prop != nullptr ? prop->latency : nullptr;

  Closure done = [socket_id, cid, cntl, response, start_us, srv, lat] {
    RpcMeta meta;
    meta.type = RpcMeta::kResponse;
    meta.correlation_id = cid;
    meta.error_code = cntl->error_code();
    meta.error_text = cntl->error_text();
    meta.stream_id = cntl->call().accepted_stream;  // acceptance piggyback
    if (meta.stream_id != 0) {
      meta.ack_bytes = stream_recv_window(meta.stream_id);
    }
    IOBuf frame;
    if (!cntl->response_attachment().empty()) {
      meta.attachment_size =
          static_cast<uint32_t>(cntl->response_attachment().size());
      response->append(std::move(cntl->response_attachment()));
    }
    tstd_pack(&frame, meta, *response);
    SocketRef s(Socket::Address(socket_id));
    if (s) {
      s->Write(std::move(frame));
    }
    if (srv != nullptr) {
      srv->requests_served.fetch_add(1, std::memory_order_relaxed);
    }
    if (lat != nullptr) {
      *lat << (monotonic_time_us() - start_us);
    }
    delete response;
    delete cntl;
  };

  if (srv == nullptr || !srv->running()) {
    cntl->SetFailed(ESHUTDOWN, "server stopped");
    done();
    return;
  }
  if (prop == nullptr) {
    cntl->SetFailed(ENOENT, "no such method: " + method);
    done();
    return;
  }
  // Split the attachment tail off the payload.
  IOBuf request = std::move(msg.payload);
  if (msg.meta.attachment_size > 0 &&
      msg.meta.attachment_size <= request.size()) {
    IOBuf body;
    request.cutn(&body, request.size() - msg.meta.attachment_size);
    cntl->request_attachment() = std::move(request);
    request = std::move(body);
  }
  prop->handler(cntl, request, response, std::move(done));
}

}  // namespace trpc
