#include "net/server.h"

#include <signal.h>

#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <map>
#include <mutex>

#include "base/compress.h"
#include "base/flags.h"
#include "base/logging.h"
#include "base/rand.h"
#include "base/recordio.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/h2_protocol.h"
#include "net/http_protocol.h"
#include "net/redis.h"
#include "net/memcache.h"
#include "net/mongo.h"
#include "net/rtmp.h"
#include "net/usercode_pool.h"
#include "net/legacy_pbrpc.h"
#include "net/nshead.h"
#include "net/thrift.h"
#include "net/tls.h"
#include "net/deadline.h"
#include "net/messenger.h"
#include "net/ici_transport.h"
#include "net/shm_transport.h"
#include "net/span.h"
#include "stat/capture.h"
#include "stat/slo.h"
#include "stat/timeline.h"
#include "net/stream.h"
#include "net/rma.h"
#include "net/stripe.h"
#include "net/protocol.h"
#include "stat/tuner.h"

namespace trpc {

Server::~Server() {
  Stop();
  // A request fiber holds a strong socket ref across its entry section
  // (user_data read + in_flight registration), so once every failed
  // connection's refs have drained, in_flight is complete and Join() is
  // exact — no timing-based grace needed.
  const int64_t deadline = monotonic_time_us() + 5000000;
  {
    std::lock_guard<std::mutex> g(conns_mu_);
    for (SocketId id : drain_ids_) {
      while (Socket::Draining(id) && monotonic_time_us() < deadline) {
        usleep(1000);
      }
    }
  }
  Join();
  // Owned components (announcers etc.) die only after every in-flight
  // handler finished — their drain hooks may reference them.
  std::lock_guard<std::mutex> g(drain_mu_);
  components_.clear();
}

namespace {
std::vector<std::string> split_path(const std::string& p) {
  std::vector<std::string> segs;
  size_t pos = 0;
  while (pos < p.size()) {
    while (pos < p.size() && p[pos] == '/') {
      ++pos;
    }
    size_t end = p.find('/', pos);
    if (end == std::string::npos) {
      end = p.size();
    }
    if (end > pos) {
      segs.push_back(p.substr(pos, end - pos));
    }
    pos = end;
  }
  return segs;
}
}  // namespace

int Server::MapRestful(const std::string& pattern, const std::string& method) {
  if (running()) {
    return -1;  // same contract as RegisterMethod: configure before Start
  }
  if (methods_.seek(method) == nullptr) {
    return -1;  // map only registered methods
  }
  RestfulRule rule;
  rule.segs = split_path(pattern);
  if (!rule.segs.empty() && rule.segs.back() == "*") {
    // A trailing '*' matches one-or-more remaining segments.
    rule.tail_wild = true;
    rule.segs.pop_back();
  }
  rule.method = method;
  restful_.push_back(std::move(rule));
  // Longest (most specific) pattern wins at lookup.
  std::stable_sort(restful_.begin(), restful_.end(),
                   [](const RestfulRule& a, const RestfulRule& b) {
                     return a.segs.size() > b.segs.size();
                   });
  return 0;
}

const Server::MethodProperty* Server::find_restful(
    const std::string& path, std::string* method_name) const {
  if (restful_.empty()) {
    return nullptr;
  }
  const std::vector<std::string> segs = split_path(path);
  for (const RestfulRule& rule : restful_) {
    if (rule.tail_wild ? segs.size() <= rule.segs.size()
                       : segs.size() != rule.segs.size()) {
      continue;
    }
    bool ok = true;
    for (size_t i = 0; i < rule.segs.size(); ++i) {
      if (rule.segs[i] != "*" && rule.segs[i] != segs[i]) {
        ok = false;
        break;
      }
    }
    if (ok) {
      if (method_name != nullptr) {
        *method_name = rule.method;
      }
      return methods_.seek(rule.method);
    }
  }
  return nullptr;
}

int Server::RegisterMethod(const std::string& full_name, Handler handler) {
  if (running()) {
    return -1;
  }
  MethodProperty prop;
  prop.handler = std::move(handler);
  prop.latency = std::make_shared<LatencyRecorder>();
  prop.latency->expose("rpc_server_" + full_name,
                       "server-side latency of " + full_name);
  methods_[full_name] = std::move(prop);
  return 0;
}

int Server::SetMethodMaxConcurrency(const std::string& method,
                                    const std::string& spec) {
  if (running()) {
    return -1;
  }
  MethodProperty* prop = methods_.seek(method);
  if (prop == nullptr) {
    return -1;
  }
  auto [ok, limiter] = parse_concurrency_spec(spec);
  if (!ok) {
    return -1;  // typo'd spec must not silently mean "unlimited"
  }
  prop->limiter = std::move(limiter);
  // A constant bound is exposed as a reloadable flag so /flags?setvalue
  // retargets the LIVE limiter (reloadable_flags.h + flags_service parity).
  // Flags are process-global while limiters are per-Server: the update
  // hook fans out to EVERY limiter ever bound to the name (weak refs, so
  // dead servers drop out) instead of the latest binding hijacking it.
  auto* constant = dynamic_cast<ConstantLimiter*>(prop->limiter.get());
  if (constant != nullptr) {
    std::string flag_name = "max_concurrency_" + method;
    for (char& c : flag_name) {
      if (c == '.') {
        c = '_';
      }
    }
    static std::mutex* bindings_mu = new std::mutex();
    static auto* bindings =
        new std::map<std::string,
                     std::vector<std::weak_ptr<ConcurrencyLimiter>>>();
    {
      std::lock_guard<std::mutex> g(*bindings_mu);
      (*bindings)[flag_name].push_back(prop->limiter);
    }
    Flag* f = Flag::define_int64(flag_name, constant->current_limit(),
                                 "admission bound for " + method);
    if (f != nullptr) {
      f->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long n = strtol(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n > 0;
      });
      f->on_update([flag_name](Flag* self) {
        std::lock_guard<std::mutex> g(*bindings_mu);
        auto& vec = (*bindings)[flag_name];
        for (auto it = vec.begin(); it != vec.end();) {
          if (auto l = it->lock()) {
            static_cast<ConstantLimiter*>(l.get())
                ->set_limit(self->int64_value());
            ++it;
          } else {
            it = vec.erase(it);
          }
        }
      });
      // Explicit configuration is authoritative: push this limit into the
      // flag, which fans out to every limiter bound to the name (one knob,
      // one value — a pre-existing flag's stale value must not silently
      // override what this server just configured).
      f->set_from_string(std::to_string(constant->current_limit()));
    }
  }
  return 0;
}

int Server::SetQos(const std::string& spec) {
  if (running()) {
    return -1;
  }
  if (spec.empty()) {
    qos_.reset();
    return 0;
  }
  std::string err;
  auto gov = TenantGovernor::parse(spec, &err);
  if (gov == nullptr) {
    LOG(Warning) << "bad qos spec '" << spec << "': " << err;
    return -1;  // a typo must not silently mean "no QoS"
  }
  qos_ = std::move(gov);
  return 0;
}

int Server::SetSlo(const std::string& spec) {
  if (running()) {
    return -1;
  }
  if (spec.empty()) {
    slo_.reset();
    return 0;
  }
  std::string err;
  auto eng = SloEngine::parse(spec, &err);
  if (eng == nullptr) {
    LOG(Warning) << "bad slo spec '" << spec << "': " << err;
    return -1;  // a typo must not silently mean "no SLO"
  }
  slo_ = std::move(eng);
  return 0;
}

int Server::set_reuseport_shards(int n) {
  if (running() || n < 1 || n > kMaxAcceptShards) {
    return -1;
  }
  reuseport_shards_ = n;
  return 0;
}

std::vector<uint64_t> Server::accept_counts() const {
  std::vector<uint64_t> out(static_cast<size_t>(reuseport_shards_), 0);
  for (size_t i = 0; i < out.size(); ++i) {
    out[i] = accept_counts_[i].load(std::memory_order_relaxed);
  }
  return out;
}

int Server::install_listener(int fd, int shard) {
  auto actx = std::make_unique<AcceptCtx>();
  actx->srv = this;
  actx->shard = shard;
  Socket::Options opts;
  opts.fd = fd;
  opts.on_readable = &Server::on_acceptable;
  opts.ctx = actx.get();
  opts.user_data = this;
  opts.worker_tag = static_cast<uint8_t>(worker_tag_);
  SocketId id = 0;
  if (Socket::Create(opts, &id) != 0) {
    return -1;
  }
  accept_ctxs_.push_back(std::move(actx));
  if (shard == 0) {
    listen_id_ = id;
  } else {
    extra_listen_ids_.push_back(id);
  }
  return 0;
}

void expose_default_variables();  // stat/default_variables.cc
void expose_hotpath_variables();  // net/hotpath_stats.cc

void Server::start_runtime_init() {
  fiber_init(0);
  if (worker_tag_ != 0) {
    fiber_start_tag_workers(worker_tag_, 0);  // default size if not sized
  }
  expose_default_variables();
  expose_hotpath_variables();
  expose_qos_variables();
  if (session_data_factory_ != nullptr && session_data_pool_ == nullptr) {
    session_data_pool_ =
        std::make_unique<SimpleDataPool>(session_data_factory_);
    session_data_pool_->Reserve(session_data_reserve_);
  }
  tstd_protocol();  // ensure registered (first: most traffic is RPC)
  // hulu/sofa next: their 4-byte ASCII magics must be probed before the
  // HTTP parser sees the 'H'/'S' and holds the bytes as a method line.
  register_hulu_protocol();
  register_sofa_protocol();
  register_http_protocol();
  register_h2_protocol();
  if (thrift_service_ != nullptr) {
    register_thrift_protocol();
  }
  if (memcache_service_ != nullptr) {
    register_memcache_protocol();
  }
  if (mongo_service_ != nullptr) {
    register_mongo_protocol();
  }
  if (rtmp_service_ != nullptr) {
    register_rtmp_protocol();
  }
  // redis must precede the nshead family and esp: its '*' marker decides
  // instantly, while those probers HOLD short prefixes (no magic in the
  // first bytes) and would shadow a fragmented RESP command forever.
  if (redis_service_ != nullptr) {
    register_redis_protocol();
  }
  if (nshead_service_ != nullptr) {
    register_nshead_protocol();
  }
  if (nova_pbrpc_) {
    register_nova_protocol();
  }
  if (public_pbrpc_) {
    register_public_pbrpc_protocol();
  }
  if (esp_service_ != nullptr) {
    register_esp_protocol();  // last: esp has no magic to probe
  }
  start_time_us_ = monotonic_time_us();
  // Ring-transport handshakes (net/shm_transport.h, net/ici_transport.h):
  // a client sends the segment name it minted; we map it and serve that
  // connection over the rings.  Registered for every server — harmless if
  // unused.  If the client dies (or gives up) after our "ok", the ring
  // socket is not leaked: an attached-but-silent peer never bumps its
  // segment heartbeat, so the poller's 30s stall reaper fails the socket
  // and unlinks the segment.
  const auto register_ring = [this](const char* method, const char* what,
                                    int (*open_and_attach)(
                                        const std::string&, Server*,
                                        SocketId*)) {
    if (methods_.seek(method) != nullptr) {
      return;
    }
    RegisterMethod(method, [this, what, open_and_attach](
                               Controller* cntl, const IOBuf& req,
                               IOBuf* resp, Closure done) {
      SocketId sid = 0;
      if (open_and_attach(req.to_string(), this, &sid) != 0) {
        cntl->SetFailed(EINVAL, what);
        done();
        return;
      }
      track_connection(sid);
      resp->append("ok");
      done();
    });
  };
  register_ring(kShmConnectMethod, "bad shm segment",
                [](const std::string& name, Server* srv, SocketId* sid) {
                  auto conn = shm_conn_open(name);
                  return conn != nullptr
                             ? shm_socket_create(
                                   conn, &messenger_on_readable, srv, sid)
                             : -1;
                });
  register_ring(kIciConnectMethod, "bad ici segment",
                [](const std::string& name, Server* srv, SocketId* sid) {
                  auto conn = ici_conn_open(name);
                  return conn != nullptr
                             ? ici_socket_create(
                                   conn, &messenger_on_readable, srv, sid)
                             : -1;
                });
}

int Server::Start(int port) {
  if (worker_tag_ != 0 &&
      (worker_tag_ < 0 || worker_tag_ >= kMaxFiberTags)) {
    return -1;
  }
  start_runtime_init();
  int fd;
  if (!unix_path_.empty()) {
    EndPoint uep;
    uep.unix_path = unix_path_;
    sockaddr_un su = endpoint2sockaddr_un(uep);
    // Only a STALE socket file (crashed owner: connect refuses) may be
    // unlinked — silently stealing a live server's path would leave it
    // running yet unreachable.
    const int probe = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (probe >= 0) {
      if (::connect(probe, reinterpret_cast<sockaddr*>(&su),
                    sizeof(su)) == 0) {
        close(probe);
        errno = EADDRINUSE;
        return -1;  // a live server answers on this path
      }
      close(probe);
    }
    ::unlink(unix_path_.c_str());
    fd = ::socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) {
      return -1;
    }
    if (bind(fd, reinterpret_cast<sockaddr*>(&su), sizeof(su)) != 0 ||
        listen(fd, 1024) != 0) {
      close(fd);
      return -1;
    }
    port_ = 0;  // no port on AF_UNIX
  } else {
    fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) {
      return -1;
    }
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    if (reuseport_shards_ > 1) {
      // Every shard (this first socket included) must opt in BEFORE bind
      // for the kernel to co-bind them on one port.
      setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
    }
    sockaddr_in sa = {};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    sa.sin_port = htons(port > 0 ? static_cast<uint16_t>(port) : 0);
    if (bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0 ||
        listen(fd, 4096) != 0) {
      close(fd);
      return -1;
    }
    socklen_t len = sizeof(sa);
    getsockname(fd, reinterpret_cast<sockaddr*>(&sa), &len);
    port_ = ntohs(sa.sin_port);
  }

  if (install_listener(fd, 0) != 0) {
    close(fd);
    return -1;
  }
  if (unix_path_.empty() && reuseport_shards_ > 1) {
    // Acceptor sharding (the 100k-connection front door): sibling
    // SO_REUSEPORT listeners on the discovered port.  Distinct fds land
    // on distinct event-dispatcher epoll threads (dispatcher.h for_fd),
    // so accept storms parallelize instead of serializing behind one
    // listener's read fiber.
    const auto fail_listeners = [this] {
      // running_ is still false here, so Stop() would no-op: tear the
      // partially-installed listeners down directly.
      Socket* s0 = Socket::Address(listen_id_);
      if (s0 != nullptr) {
        s0->SetFailed(ESHUTDOWN);
        s0->Dereference();
      }
      for (SocketId id : extra_listen_ids_) {
        Socket* s = Socket::Address(id);
        if (s != nullptr) {
          s->SetFailed(ESHUTDOWN);
          s->Dereference();
        }
      }
      extra_listen_ids_.clear();
    };
    for (int shard = 1; shard < reuseport_shards_; ++shard) {
      const int sfd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
      if (sfd < 0) {
        fail_listeners();
        return -1;
      }
      int one = 1;
      setsockopt(sfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
      setsockopt(sfd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
      sockaddr_in sa = {};
      sa.sin_family = AF_INET;
      sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
      sa.sin_port = htons(static_cast<uint16_t>(port_));
      if (bind(sfd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0 ||
          listen(sfd, 4096) != 0 || install_listener(sfd, shard) != 0) {
        close(sfd);
        fail_listeners();
        return -1;
      }
    }
  }
  running_.store(true, std::memory_order_release);
  LOG(Info) << "server started on "
            << (unix_path_.empty()
                    ? "127.0.0.1:" + std::to_string(port_)
                    : "unix:" + unix_path_);
  return 0;
}

int Server::StartUnix(const std::string& path) {
  if (path.empty() || path.size() >= sizeof(sockaddr_un{}.sun_path)) {
    return -1;  // over-long paths would silently truncate at bind
  }
  if (reuseport_shards_ > 1) {
    // SO_REUSEPORT sharding is a TCP feature; silently ignoring it here
    // would leave the operator reading n-1 forever-zero accept counters
    // as a broken kernel spread instead of an unsupported config.
    LOG(Warning) << "reuseport shards unsupported on AF_UNIX";
    return -1;
  }
  unix_path_ = path;
  const int rc = Start(0);
  if (rc != 0) {
    unix_path_.clear();
  }
  return rc;
}

void Server::fail_listeners() {
  Socket* s = Socket::Address(listen_id_);
  if (s != nullptr) {
    s->SetFailed(ESHUTDOWN);
    s->Dereference();
  }
  for (SocketId id : extra_listen_ids_) {
    Socket* shard = Socket::Address(id);
    if (shard != nullptr) {
      shard->SetFailed(ESHUTDOWN);
      shard->Dereference();
    }
  }
}

void Server::Stop() {
  if (!running_.exchange(false)) {
    return;
  }
  fail_listeners();
  if (!unix_path_.empty()) {
    ::unlink(unix_path_.c_str());
  }
  // Fail live connections so no NEW request can reach this server while it
  // is being torn down (their user_data points at us).
  std::lock_guard<std::mutex> g(conns_mu_);
  for (SocketId id : conns_) {
    Socket* conn = Socket::Address(id);
    if (conn != nullptr) {
      conn->SetFailed(ESHUTDOWN);
      conn->Dereference();
      drain_ids_.push_back(id);  // ~Server waits for their refs to drain
    }
  }
  conns_.clear();
}

namespace {

Flag* drain_deadline_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_drain_deadline_ms", 5000,
        "default Server::Drain quiesce budget (ms, [100, 600000]): how "
        "long a draining node waits for in-flight requests and RMA "
        "window spans before giving up (ETIMEDOUT) and proceeding with "
        "shutdown anyway");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= 100 &&
               n <= 600000;
      });
    }
    return flag;
  }();
  return f;
}

}  // namespace

void Server::drain_ensure_registered() { drain_deadline_flag(); }

bool Server::EnableTuner(bool on) {
  tuner::ensure_registered();
  return Flag::set("trpc_tuner", on ? "true" : "false") == 0;
}

void Server::add_drain_hook(std::function<void()> hook) {
  std::lock_guard<std::mutex> g(drain_mu_);
  drain_hooks_.push_back(std::move(hook));
}

void Server::own_component(std::shared_ptr<void> c) {
  std::lock_guard<std::mutex> g(drain_mu_);
  components_.push_back(std::move(c));
}

int Server::Drain(int64_t deadline_ms, const std::string& handoff_path) {
  if (!running()) {
    return -1;
  }
  if (deadline_ms <= 0) {
    Flag* f = drain_deadline_flag();
    deadline_ms = f != nullptr ? f->int64_value() : 5000;
  }
  const int64_t deadline_us = monotonic_time_us() + deadline_ms * 1000;
  draining_.store(true, std::memory_order_release);
  // 1. Leave the fleet: naming withdrawal, KV-block tombstoning, watcher
  // wakeups.  Hooks run OUTSIDE drain_mu_ (a hook may add components).
  std::vector<std::function<void()>> hooks;
  {
    std::lock_guard<std::mutex> g(drain_mu_);
    hooks = drain_hooks_;
  }
  for (const auto& hook : hooks) {
    hook();
  }
  // 2. Hand the SO_REUSEPORT listener set to the successor BEFORE
  // closing our own fds: the shared accept queues stay owned throughout,
  // so no SYN is refused across the restart.  A handoff failure (no
  // successor showed up inside the deadline) degrades to a plain drain.
  if (!handoff_path.empty()) {
    if (serve_handoff(handoff_path, deadline_us) != 0) {
      LOG(Warning) << "drain: listener handoff on " << handoff_path
                   << " failed; draining without a successor";
    }
  }
  fail_listeners();
  // 3. Quiesce: every in-flight request completed AND every peer-held
  // RMA window span freed (a span outlives its request until the
  // payload's last IOBuf reference drops).
  while (in_flight.load(std::memory_order_acquire) > 0 ||
         rma_spans_in_use() > 0) {
    if (monotonic_time_us() >= deadline_us) {
      return ETIMEDOUT;
    }
    if (in_fiber()) {
      fiber_sleep_us(1000);
    } else {
      usleep(1000);
    }
  }
  return 0;
}

int Server::serve_handoff(const std::string& path, int64_t deadline_us) {
  if (path.empty() || path.size() >= sizeof(sockaddr_un{}.sun_path)) {
    return -1;
  }
  // Dup every listener fd: the dup shares the open file description (and
  // its accept queue), so the successor's copies keep working after we
  // fail our Socket objects (which close the originals).
  std::vector<int> fds;
  const auto grab = [&fds](SocketId id) {
    Socket* s = Socket::Address(id);
    if (s != nullptr) {
      const int d = ::dup(s->fd());
      if (d >= 0) {
        fds.push_back(d);
      }
      s->Dereference();
    }
  };
  grab(listen_id_);
  for (SocketId id : extra_listen_ids_) {
    grab(id);
  }
  const auto fail = [&fds](int lfd, const std::string& p) {
    for (int fd : fds) {
      close(fd);
    }
    if (lfd >= 0) {
      close(lfd);
      ::unlink(p.c_str());
    }
    return -1;
  };
  if (fds.empty()) {
    return fail(-1, path);
  }
  sockaddr_un su = {};
  su.sun_family = AF_UNIX;
  memcpy(su.sun_path, path.c_str(), path.size() + 1);
  ::unlink(path.c_str());
  const int lfd = ::socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (lfd < 0 ||
      bind(lfd, reinterpret_cast<sockaddr*>(&su), sizeof(su)) != 0 ||
      listen(lfd, 1) != 0) {
    return fail(lfd, path);
  }
  int cfd = -1;
  while (monotonic_time_us() < deadline_us) {
    cfd = ::accept(lfd, nullptr, nullptr);
    if (cfd >= 0) {
      break;
    }
    usleep(10000);
  }
  if (cfd < 0) {
    return fail(lfd, path);
  }
  // {port, nfds} + every fd in ONE SCM_RIGHTS control block.
  int32_t head[2] = {static_cast<int32_t>(port_),
                     static_cast<int32_t>(fds.size())};
  iovec iov = {head, sizeof(head)};
  char cbuf[CMSG_SPACE(sizeof(int) * kMaxAcceptShards)] = {};
  msghdr msg = {};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = cbuf;
  msg.msg_controllen = CMSG_SPACE(sizeof(int) * fds.size());
  cmsghdr* cm = CMSG_FIRSTHDR(&msg);
  cm->cmsg_level = SOL_SOCKET;
  cm->cmsg_type = SCM_RIGHTS;
  cm->cmsg_len = CMSG_LEN(sizeof(int) * fds.size());
  memcpy(CMSG_DATA(cm), fds.data(), sizeof(int) * fds.size());
  const ssize_t sent = ::sendmsg(cfd, &msg, MSG_NOSIGNAL);
  close(cfd);
  const int rc = sent == static_cast<ssize_t>(sizeof(head)) ? 0 : -1;
  fail(lfd, path);  // close OUR dups + the handoff listener either way
  return rc;
}

int Server::StartFromHandoff(const std::string& path, int64_t timeout_ms) {
  if (running() || path.empty() ||
      path.size() >= sizeof(sockaddr_un{}.sun_path)) {
    return -1;
  }
  if (worker_tag_ != 0 &&
      (worker_tag_ < 0 || worker_tag_ >= kMaxFiberTags)) {
    return -1;
  }
  const int64_t deadline_us = monotonic_time_us() + timeout_ms * 1000;
  sockaddr_un su = {};
  su.sun_family = AF_UNIX;
  memcpy(su.sun_path, path.c_str(), path.size() + 1);
  int cfd = -1;
  // Retry until the predecessor starts serving the handoff: the two
  // processes race by design (the successor is launched first so the
  // drain window stays minimal).
  while (monotonic_time_us() < deadline_us) {
    cfd = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (cfd < 0) {
      return -1;
    }
    if (::connect(cfd, reinterpret_cast<sockaddr*>(&su), sizeof(su)) == 0) {
      break;
    }
    close(cfd);
    cfd = -1;
    usleep(20000);
  }
  if (cfd < 0) {
    return -1;
  }
  int32_t head[2] = {0, 0};
  iovec iov = {head, sizeof(head)};
  char cbuf[CMSG_SPACE(sizeof(int) * kMaxAcceptShards)] = {};
  msghdr msg = {};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = cbuf;
  msg.msg_controllen = sizeof(cbuf);
  const ssize_t got = ::recvmsg(cfd, &msg, MSG_CMSG_CLOEXEC);
  close(cfd);
  std::vector<int> fds;
  for (cmsghdr* cm = CMSG_FIRSTHDR(&msg); cm != nullptr;
       cm = CMSG_NXTHDR(&msg, cm)) {
    if (cm->cmsg_level == SOL_SOCKET && cm->cmsg_type == SCM_RIGHTS) {
      const size_t n = (cm->cmsg_len - CMSG_LEN(0)) / sizeof(int);
      const int* data = reinterpret_cast<const int*>(CMSG_DATA(cm));
      fds.assign(data, data + n);
    }
  }
  const auto close_all = [&fds] {
    for (int fd : fds) {
      close(fd);
    }
    return -1;
  };
  if (got != static_cast<ssize_t>(sizeof(head)) || fds.empty() ||
      static_cast<size_t>(head[1]) != fds.size() ||
      fds.size() > static_cast<size_t>(kMaxAcceptShards)) {
    return close_all();
  }
  start_runtime_init();
  port_ = head[0];
  reuseport_shards_ = static_cast<int>(fds.size());
  for (size_t i = 0; i < fds.size(); ++i) {
    if (install_listener(fds[i], static_cast<int>(i)) != 0) {
      for (size_t j = i; j < fds.size(); ++j) {
        close(fds[j]);
      }
      fail_listeners();
      return -1;
    }
  }
  running_.store(true, std::memory_order_release);
  LOG(Info) << "server adopted " << fds.size()
            << " handed-off listener(s) on 127.0.0.1:" << port_;
  return 0;
}

int Server::Join(int64_t timeout_ms) {
  const int64_t deadline =
      timeout_ms >= 0 ? monotonic_time_us() + timeout_ms * 1000 : INT64_MAX;
  while (in_flight.load(std::memory_order_acquire) > 0) {
    if (monotonic_time_us() >= deadline) {
      return ETIMEDOUT;
    }
    if (in_fiber()) {
      fiber_sleep_us(1000);
    } else {
      usleep(1000);
    }
  }
  return 0;
}

namespace {
std::atomic<bool> g_asked_to_quit{false};
void quit_signal_handler(int) {
  g_asked_to_quit.store(true, std::memory_order_release);
}
}  // namespace

void Server::RunUntilAskedToQuit() {
  struct sigaction sa = {};
  sa.sa_handler = &quit_signal_handler;
  sigaction(SIGINT, &sa, nullptr);
  sigaction(SIGTERM, &sa, nullptr);
  while (!g_asked_to_quit.load(std::memory_order_acquire)) {
    usleep(100 * 1000);
  }
}

void Server::track_connection(SocketId id) {
  std::lock_guard<std::mutex> g(conns_mu_);
  if (conns_.size() >= conns_prune_at_) {
    // Prune stale versioned ids.  The threshold then moves to 2x the
    // LIVE count: a fixed threshold would re-walk the whole vector on
    // every accept once past it — O(n^2) across a 100k-connection ramp
    // (the scale harness found exactly that); doubling amortizes the
    // walk to O(1) per accept at any connection count.
    std::vector<SocketId> live;
    live.reserve(conns_.size());
    for (SocketId sid : conns_) {
      Socket* s = Socket::Address(sid);
      if (s != nullptr) {
        live.push_back(sid);
        s->Dereference();
      }
    }
    conns_.swap(live);
    conns_prune_at_ = std::max<size_t>(4096, conns_.size() * 2);
  }
  conns_.push_back(id);
}

// Accept-until-EAGAIN (acceptor.cpp:251 parity); runs in the listen
// socket's read fiber.
void Server::on_acceptable(SocketId id, void* ctx) {
  auto* actx = static_cast<AcceptCtx*>(ctx);
  Server* srv = actx->srv;
  Socket* listener = Socket::Address(id);
  if (listener == nullptr) {
    return;
  }
  while (true) {
    sockaddr_storage peer_sa = {};
    socklen_t peer_len = sizeof(peer_sa);
    const int fd =
        accept4(listener->fd(), reinterpret_cast<sockaddr*>(&peer_sa),
                &peer_len, SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) {
      break;  // EAGAIN or error; ET will refire on next connection
    }
    srv->accept_counts_[actx->shard].fetch_add(1,
                                               std::memory_order_relaxed);
    EndPoint peer_ep;
    if (peer_sa.ss_family == AF_UNIX) {
      // Unix peers are anonymous; identify them by our listening path.
      peer_ep.unix_path = srv->unix_path_;
    } else {
      const auto* sin = reinterpret_cast<const sockaddr_in*>(&peer_sa);
      peer_ep.ip = sin->sin_addr.s_addr;
      peer_ep.port = ntohs(sin->sin_port);
    }
    // Fault point: reject-at-accept (net/fault.h svr_reject) — the peer
    // sees an immediate close, exercising its connect-retry path.
    if (srv->faults_.active() &&
        srv->faults_.decide(FaultPoint::kAccept, peer_ep).kind ==
            FaultKind::kSvrReject) {
      close(fd);
      continue;
    }
    Socket::Options opts;
    opts.fd = fd;
    opts.remote = peer_ep;
    if (peer_sa.ss_family != AF_UNIX) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    }
    opts.on_readable = &messenger_on_readable;
    opts.user_data = srv;
    opts.worker_tag = static_cast<uint8_t>(srv->worker_tag_);
    if (srv->tls_ctx_ != nullptr) {
      // First-byte sniff decides TLS vs plaintext per connection.
      opts.transport = tls_transport();
      opts.transport_ctx_holder = tls_conn_server(srv->tls_ctx_);
    }
    SocketId conn_id = 0;
    if (Socket::Create(opts, &conn_id) != 0) {
      close(fd);
      continue;
    }
    srv->track_connection(conn_id);
  }
  listener->Dereference();
}

int Server::EnableTls(const std::string& cert_file,
                      const std::string& key_file,
                      const std::string& ca_file) {
  std::string err;
  tls_ctx_ = tls_server_ctx(cert_file, key_file, &err, ca_file);
  if (tls_ctx_ == nullptr) {
    LOG(Warning) << "EnableTls failed: " << err;
    return -1;
  }
  return 0;
}

int Server::EnableDump(const std::string& path, double sample_rate) {
  auto writer = std::make_unique<RecordWriter>(path);
  if (!writer->valid()) {
    return -1;
  }
  LockGuard<FiberMutex> g(dump_mu_);
  dump_writer_ = std::move(writer);
  dump_rate_.store(sample_rate, std::memory_order_release);
  return 0;
}

void Server::maybe_dump(const std::string& method, uint32_t attachment_size,
                        const IOBuf& payload) {
  const double rate = dump_rate_.load(std::memory_order_acquire);
  if (rate <= 0.0 ||
      fast_rand_less_than(1000000) >= static_cast<uint64_t>(rate * 1000000)) {
    return;
  }
  // Each record is a complete tstd request frame — replay just re-sends it.
  RpcMeta meta;
  meta.type = RpcMeta::kRequest;
  meta.method = method;
  meta.attachment_size = attachment_size;
  IOBuf frame;
  tstd_pack(&frame, meta, payload);
  LockGuard<FiberMutex> g(dump_mu_);
  if (dump_writer_ != nullptr) {
    dump_writer_->write(frame);
    dump_writer_->flush();
  }
}

// ---- request execution (tstd protocol hook) -----------------------------

void tstd_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  // Connection authentication (auth.h; input_messenger.cpp:271-289
  // parity).  The credential frame verifies once and marks the socket;
  // with an authenticator installed, requests on an unverified socket
  // are refused and the connection failed.
  if (msg.meta.type == RpcMeta::kAuth) {
    const Authenticator* auth =
        srv != nullptr ? srv->authenticator() : nullptr;
    if (auth != nullptr &&
        auth->verify_credential(msg.payload.to_string(), sock->remote()) ==
            0) {
      sock->auth_ok.store(true, std::memory_order_release);
    } else if (auth != nullptr) {
      LOG(Warning) << "auth credential rejected; closing connection";
      sock->SetFailed(EACCES);
    }
    return;  // credential frames carry no request
  }
  if (msg.meta.type == RpcMeta::kCancel) {
    // Cascading-cancel control frame (net/deadline.h): fans out to the
    // named in-flight request's downstream calls and transfers.  Never
    // answered (the caller already abandoned the call); dropped on an
    // unauthenticated connection — an unverified peer must not cancel
    // other clients' work.
    if (srv == nullptr || srv->authenticator() == nullptr ||
        sock->auth_ok.load(std::memory_order_acquire)) {
      if (cancel_fire(msg.socket, msg.meta.correlation_id) &&
          timeline::enabled()) {
        timeline::record(timeline::kDeadline, msg.meta.correlation_id,
                         timeline::kDeadlineCancelFanout << 56);
      }
    }
    return;
  }
  if (srv != nullptr && srv->authenticator() != nullptr &&
      !sock->auth_ok.load(std::memory_order_acquire)) {
    RpcMeta meta;
    meta.type = RpcMeta::kResponse;
    meta.correlation_id = msg.meta.correlation_id;
    meta.error_code = EACCES;
    meta.error_text = "connection not authenticated";
    IOBuf frame;
    tstd_pack(&frame, meta, IOBuf());
    // Flush-then-close: an explicit SetFailed would bump the socket
    // version before the KeepWrite fiber re-Addresses it, dropping the
    // EACCES reply and leaving the client with a bare reset.
    sock->Write(std::move(frame), /*close_after=*/true);
    return;
  }
  const SocketId socket_id = msg.socket;
  const uint64_t cid = msg.meta.correlation_id;
  const std::string method = msg.meta.method;

  auto* cntl = new Controller();
  cntl->set_method(method);
  // Surface the request's QoS tag to the handler (and the capi).
  cntl->set_qos(msg.meta.qos_tenant, msg.meta.qos_priority);
  cntl->call().socket_id = socket_id;
  cntl->call().peer_stream = msg.meta.stream_id;
  cntl->call().peer_stream_window = msg.meta.ack_bytes;
  cntl->call().extra_peer = std::move(msg.meta.extra_streams);
  if (msg.ctx != nullptr && msg.meta.stripe_id != 0) {
    // Reassembled striped request: remember the rails it arrived over so
    // the response stripes back across the same connections.
    cntl->call().stripe_rails =
        static_cast<StripeArrival*>(msg.ctx.get())->rails;
  }
  // One-sided response target (net/rma.h): the caller advertised a
  // registered landing region — the response puts straight into it.
  cntl->call().rma_resp_rkey = msg.meta.rma_resp_rkey;
  cntl->call().rma_resp_max = msg.meta.rma_resp_max;
  cntl->call().rma_resp_off = msg.meta.rma_resp_off;
  cntl->call().sl_pool =
      srv != nullptr ? srv->session_data_pool() : nullptr;
  auto* response = new IOBuf();
  const int64_t start_us = monotonic_time_us();
  // Deadline plane (net/deadline.h): anchor the wire's relative budget
  // to the request's parse-time arrival clock, so QoS-lane queueing and
  // dispatch backlog count against it.  A budget that already expired
  // is shed below, BEFORE it can consume an admission slot or a
  // handler.
  int64_t deadline_abs = 0;
  if (msg.meta.deadline_us != 0 && msg.arrival_us != 0 &&
      deadline_wire_enabled()) {
    // Gated on the SAME flag that controls stamping: trpc_deadline_wire
    // off is the operator kill-switch for the whole plane on this node
    // — incoming stamps from flag-on peers are then ignored too, as the
    // flag's help text promises.
    // The wire value is untrusted (the frame CRC covers only the
    // payload): clamp to a sane ceiling before anchoring, or a hostile
    // u64 near INT64_MAX signed-overflows the add (UB) and wraps a
    // live request into an instant shed.
    constexpr uint64_t kMaxBudgetUs = 24ull * 3600 * 1000 * 1000;  // 24h
    const uint64_t budget = msg.meta.deadline_us < kMaxBudgetUs
                                ? msg.meta.deadline_us
                                : kMaxBudgetUs;
    deadline_abs = msg.arrival_us + static_cast<int64_t>(budget);
    cntl->set_deadline_abs_us(deadline_abs);
  }
  const bool deadline_dead = deadline_abs != 0 && start_us >= deadline_abs;
  // rpcz: server span, linked to the client span via the meta's trace
  // context (baidu_rpc_protocol.cpp:648-661 parity).  Ambient context
  // makes client calls issued from inside the handler children of this
  // span.
  Span* span = nullptr;
  if (rpcz_enabled()) {
    span = start_span(/*server_side=*/true, method, msg.meta.trace_id,
                      msg.meta.span_id);
    span->request_bytes = msg.payload.size();
    set_ambient_span(span);
  }
  // The ambient context must be cleared by THIS fiber on every exit path
  // (the read fiber processes the last message of a batch inline and then
  // keeps serving the connection — stale ambient would leak into later
  // requests).  The done closure may run on a different fiber entirely,
  // so it is the wrong place to clear.
  struct AmbientGuard {
    bool active;
    ~AmbientGuard() {
      if (active) {
        set_ambient_span(nullptr);
      }
    }
  } ambient_guard{span != nullptr};
  const Server::MethodProperty* prop =
      (srv != nullptr && srv->running()) ? srv->find_method(method) : nullptr;
  std::shared_ptr<LatencyRecorder> lat =
      prop != nullptr ? prop->latency : nullptr;
  std::shared_ptr<ConcurrencyLimiter> limiter =
      prop != nullptr ? prop->limiter : nullptr;
  // Per-tenant QoS admission (net/qos.h): runs FIRST so a shed request
  // never consumes a per-method slot.  A shed answers kEOverloaded —
  // distinct from kELimit so the cluster client fails over immediately.
  std::shared_ptr<TenantGovernor> gov =
      srv != nullptr ? srv->qos_governor() : nullptr;
  // SLO scoring (stat/slo.h): flag-off this is ONE relaxed load and the
  // engine is never even ref-counted into the closure.
  std::shared_ptr<SloEngine> slo =
      (srv != nullptr && slo::enabled()) ? srv->slo_engine() : nullptr;
  TenantGovernor::Entry* tenant_entry = nullptr;
  bool tenant_admitted = true;
  if (gov != nullptr && !deadline_dead) {
    tenant_entry = gov->admit(msg.meta.qos_tenant, &tenant_admitted);
    if (!tenant_admitted) {
      tenant_entry = nullptr;  // no on_response for shed calls
    }
  }
  // Admission gate (MethodStatus parity): rejected calls never reach the
  // handler and answer immediately with kELimit.  An already-expired
  // request skips admission entirely — it is shed below without ever
  // billing a tenant or a concurrency slot.
  const bool admitted =
      deadline_dead ||
      (tenant_admitted && (limiter == nullptr || limiter->on_request()));
  if (!admitted || deadline_dead) {
    limiter = nullptr;  // no on_response for rejected/shed calls
  }

  if (srv != nullptr) {
    srv->in_flight.fetch_add(1, std::memory_order_acq_rel);
  }
  // Traffic capture (stat/capture.h): freeze the pre-dispatch facts now
  // — msg.payload is consumed below.  done() submits the record so it
  // also carries status, response bytes and handler latency; shed paths
  // run done() too, so the recorded error mix covers kEOverloaded /
  // kEDeadlineExpired sheds, not just handler outcomes.
  const bool cap_on = capture::enabled();
  const int64_t cap_arrival =
      msg.arrival_us != 0 ? msg.arrival_us : start_us;
  const uint64_t cap_req_bytes = msg.payload.size();
  const uint32_t cap_budget = static_cast<uint32_t>(
      std::min<uint64_t>(msg.meta.deadline_us, 0xffffffffull));
  const uint64_t cap_trace = msg.meta.trace_id;
  const uint64_t cap_pspan = msg.meta.span_id;
  Closure done = [socket_id, cid, cntl, response, start_us, srv, lat,
                  limiter, gov, slo, tenant_entry, span, cap_on,
                  cap_arrival, cap_req_bytes, cap_budget, cap_trace,
                  cap_pspan] {
    RpcMeta meta;
    meta.type = RpcMeta::kResponse;
    meta.correlation_id = cid;
    meta.error_code = cntl->error_code();
    meta.error_text = cntl->error_text();
    meta.stream_id = cntl->call().accepted_stream;  // acceptance piggyback
    if (meta.stream_id != 0) {
      meta.ack_bytes = stream_recv_window(meta.stream_id);
      for (uint64_t sid : cntl->call().extra_accepted) {
        meta.extra_streams.emplace_back(sid, stream_recv_window(sid));
      }
    }
    if (!cntl->Failed() && cntl->response_compress_type() != 0) {
      const Compressor* c = find_compressor(
          static_cast<CompressType>(cntl->response_compress_type()));
      IOBuf squeezed;
      if (c != nullptr && c->compress(*response, &squeezed)) {
        *response = std::move(squeezed);
        meta.compress_type = cntl->response_compress_type();
      }
    }
    if (!cntl->response_attachment().empty()) {
      meta.attachment_size =
          static_cast<uint32_t>(cntl->response_attachment().size());
      response->append(std::move(cntl->response_attachment()));
    }
    if (cntl->checksum_enabled()) {
      meta.has_checksum = true;  // striped sends CRC per chunk
    }
    const size_t response_bytes = response->size();
    // One-sided first (net/rma.h): over shm/ici rings the response body
    // is WRITTEN into the caller's advertised region (or this
    // connection's window) and only a control frame rides back; 1 =
    // not applicable / window full — the stripe/frame path carries it.
    // Long response transfers poll the request's cancel scope and
    // remaining budget between chunks (net/deadline.h): a caller that
    // cancelled, died, or ran out of budget stops the put within one
    // chunk instead of shipping bytes nobody will read.
    const DeadlineToken resp_tok{cntl->call().cancel_scope.get(),
                                 cntl->deadline_abs_us()};
    const int rma_rc =
        rma_try_send(socket_id, &meta, response,
                     cntl->call().rma_resp_rkey,
                     cntl->call().rma_resp_max,
                     cntl->call().rma_resp_off, resp_tok);
    if (rma_rc != 1) {
      // Sent (0) or hard-failed (-1, socket dead: the client times out
      // exactly as a failed stripe_send would have left it).
    } else if (stripe_should(socket_id, meta.stream_id, response_bytes)) {
      // Large response: stripe it back over the rails the request
      // arrived on (or just this connection).  stripe_id is the cid —
      // unique in the client process, and the key its registered
      // landing buffer (batch plane) waits under.
      std::vector<SocketId> rails = cntl->call().stripe_rails;
      if (rails.empty()) {
        rails.push_back(socket_id);
      }
      stripe_send(socket_id, rails, std::move(meta),
                  std::move(*response), cid, resp_tok);
    } else {
      stripe_frame_send(socket_id, std::move(meta),
                        std::move(*response));
    }
    const int64_t latency_us = monotonic_time_us() - start_us;
    if (limiter != nullptr) {
      limiter->on_response(latency_us, cntl->Failed());
    }
    if (gov != nullptr && tenant_entry != nullptr) {
      // Frees the tenant's slot and feeds its qos_tenant_<name> series.
      gov->on_response(tenant_entry, latency_us, cntl->Failed());
    }
    if (lat != nullptr) {
      *lat << latency_us;
    }
    if (slo != nullptr) {
      // Sheds run done() too, so kEOverloaded/kEDeadlineExpired count
      // against the tenant's error budget — an overloaded tenant can't
      // look healthy by shedding its way under its latency target.
      slo->on_response(cntl->qos_tenant(), latency_us, cntl->Failed());
    }
    if (cap_on && capture::enabled()) {
      capture::Sample cs;
      cs.arrival_mono_us = cap_arrival;
      cs.trace_id = cap_trace;
      cs.parent_span_id = cap_pspan;
      cs.request_bytes = cap_req_bytes;
      cs.response_bytes = response_bytes;
      cs.status = cntl->error_code();
      cs.queue_us = static_cast<uint32_t>(
          std::max<int64_t>(0, start_us - cap_arrival));
      cs.handler_us =
          static_cast<uint32_t>(std::max<int64_t>(0, latency_us));
      cs.deadline_budget_us = cap_budget;
      cs.priority = cntl->qos_priority();
      cs.method = cntl->method();
      cs.tenant = cntl->qos_tenant();
      capture::record(std::move(cs));
    }
    if (span != nullptr) {
      span->response_bytes = response_bytes;
      submit_span(span, cntl->error_code());
    }
    if (cntl->call().sl_data != nullptr) {
      cntl->call().sl_pool->Return(cntl->call().sl_data);
    }
    if (cntl->call().cancel_scope != nullptr) {
      // Unregistered only AFTER the response send: a kCancel racing the
      // response must still find the scope to abort an in-flight
      // one-sided put.
      cancel_unregister(socket_id, cid);
    }
    delete response;
    delete cntl;
    if (srv != nullptr) {
      srv->requests_served.fetch_add(1, std::memory_order_relaxed);
      // LAST touch of srv: once in_flight hits 0, Join may free the server.
      srv->in_flight.fetch_sub(1, std::memory_order_acq_rel);
    }
  };

  if (srv == nullptr || !srv->running()) {
    cntl->SetFailed(ESHUTDOWN, "server stopped");
    done();
    return;
  }
  if (srv->draining()) {
    // Graceful leave (Server::Drain): the node is healthy but exiting —
    // answer a WELL-FORMED status the cluster client fails over around
    // WITHOUT quarantining us (kEDraining, concurrency_limiter.h), so
    // the successor that revives on this endpoint moments later isn't
    // serving into a poisoned breaker.
    cntl->SetFailed(kEDraining, "server draining: fail over");
    done();
    return;
  }
  if (deadline_dead) {
    // The caller's end-to-end budget expired before we could dispatch
    // (in flight, or queued in a QoS lane — arrival was stamped at
    // parse).  Shed with the distinct non-retriable status: executing
    // (or retrying) a dead budget is pure wasted work.
    deadline_vars().shed_total << 1;
    if (timeline::enabled()) {
      timeline::record(timeline::kDeadline, cid,
                       (timeline::kDeadlineShedPreDispatch << 56) |
                           static_cast<uint64_t>(msg.meta.deadline_us &
                                                 0xffffffffffffffull));
    }
    cntl->SetFailed(kEDeadlineExpired,
                    "deadline expired before dispatch: " + method);
    done();
    return;
  }
  if (prop == nullptr && !srv->generic_handler()) {
    cntl->SetFailed(ENOENT, "no such method: " + method);
    done();
    return;
  }
  if (!admitted) {
    if (!tenant_admitted) {
      cntl->SetFailed(kEOverloaded,
                      "overloaded: tenant '" + msg.meta.qos_tenant +
                          "' shed by admission control");
    } else {
      cntl->SetFailed(kELimit, "rejected by concurrency limiter");
    }
    done();
    return;
  }
  {
    int ec = 0;
    std::string et;
    if (!srv->accept_request(method, sock->remote(), &ec, &et)) {
      cntl->SetFailed(ec, et);
      done();
      return;
    }
  }
  // Fault points: forced error / delayed dispatch (net/fault.h svr_error,
  // svr_delay).  A forced error is a CLEAN failure — the client gets a
  // well-formed response frame carrying the injected code; a delay parks
  // this request's fiber, exercising client timeout/hedging machinery.
  if (srv->faults().active()) {
    const FaultDecision fd =
        srv->faults().decide(FaultPoint::kDispatch, sock->remote());
    if (fd.kind == FaultKind::kSvrError) {
      cntl->SetFailed(fd.error_code, "injected server fault");
      done();
      return;
    }
    if (fd.kind == FaultKind::kSvrDelay) {
      fiber_sleep_us(fd.delay_ms * 1000);
    }
  }
  if (deadline_abs != 0 && monotonic_time_us() >= deadline_abs) {
    // Expired while parked in the (injected) dispatch delay — the
    // queueing class the plane exists to shed: never half-execute work
    // whose caller has already given up.
    deadline_vars().shed_total << 1;
    if (timeline::enabled()) {
      timeline::record(timeline::kDeadline, cid,
                       timeline::kDeadlineShedQueued << 56);
    }
    cntl->SetFailed(kEDeadlineExpired,
                    "deadline expired in dispatch queue: " + method);
    done();
    return;
  }
  srv->maybe_dump(method, msg.meta.attachment_size, msg.payload);
  // Split the attachment tail off the payload.
  IOBuf request = std::move(msg.payload);
  if (msg.meta.attachment_size > 0 &&
      msg.meta.attachment_size <= request.size()) {
    IOBuf body;
    request.cutn(&body, request.size() - msg.meta.attachment_size);
    cntl->request_attachment() = std::move(request);
    request = std::move(body);
  }
  if (msg.meta.compress_type != 0) {
    const Compressor* c = find_compressor(
        static_cast<CompressType>(msg.meta.compress_type));
    IOBuf plain;
    if (c == nullptr || !c->decompress(request, &plain, 1ull << 30)) {
      cntl->SetFailed(EBADMSG, "request decompression failed");
      done();
      return;
    }
    request = std::move(plain);
    // Symmetric default: reply compressed the same way unless the
    // handler overrides (reference: response follows request unless
    // set_response_compress_type).
    if (cntl->response_compress_type() == 0) {
      cntl->set_response_compress_type(msg.meta.compress_type);
    }
  }
  if (msg.meta.has_checksum) {
    cntl->set_enable_checksum(true);  // checksum the response too
  }
  // Cascading cancellation (net/deadline.h): every DISPATCHED request
  // owns a cancel scope, registered under (connection, cid) so a
  // kCancel control frame — or a poller observing the dead connection /
  // expired budget — fans out to the downstream calls and transfers the
  // handler starts.  Shed/early-error paths above never create one:
  // they own no work worth cancelling.
  auto cancel_scope = std::make_shared<CancelScope>();
  cancel_scope->socket = socket_id;
  cancel_scope->deadline_us = deadline_abs;
  if (!cancel_register(socket_id, cid, cancel_scope)) {
    // The caller's kCancel raced ahead of dispatch (request was still
    // queued when it arrived): shed as cancelled — executing work
    // nobody wants is the waste this plane exists to stop.  The scope
    // was never registered, so done() has nothing to unregister.
    deadline_vars().tombstone_shed << 1;
    if (timeline::enabled()) {
      timeline::record(timeline::kDeadline, cid,
                       timeline::kDeadlineCancelFanout << 56);
    }
    cntl->SetFailed(ECANCELED, "request cancelled before dispatch");
    done();
    return;
  }
  cntl->call().cancel_scope = cancel_scope;
  // Ambient deadline + scope for the handler extent (cleared by this
  // fiber on every exit path, like the span ambient): client calls the
  // handler issues inherit the remaining budget and register for
  // cancellation automatically.  The pthread-pool path skips it — the
  // handler runs off-fiber there and polls the Controller instead.
  struct DeadlineAmbientGuard {
    bool active = false;
    ~DeadlineAmbientGuard() {
      if (active) {
        set_ambient_deadline(0);
        set_ambient_cancel(nullptr);
      }
    }
  } deadline_ambient_guard;
  if (!srv->usercode_in_pthread()) {
    set_ambient_deadline(deadline_abs);
    set_ambient_cancel(cancel_scope.get());
    deadline_ambient_guard.active = true;
  }
  // Registered handler, else the catch-all (generic-call parity).  A
  // pointer, not a copy: both live in server-owned storage that
  // in_flight keeps alive until the last done() runs.
  const Server::Handler* handler =
      prop != nullptr ? &prop->handler : &srv->generic_handler();
  if (srv->usercode_in_pthread()) {
    // Blocking-tolerant path: the handler runs on a backup pthread so a
    // pthread-blocking body cannot pin this fiber worker.  done() is
    // thread-agnostic (Socket::Write is callable from any thread).
    UsercodePool::instance()->run(
        [handler, cntl, request = std::move(request), response,
         done = std::move(done)]() mutable {
          (*handler)(cntl, request, response, std::move(done));
        });
    return;
  }
  (*handler)(cntl, request, response, std::move(done));
}

}  // namespace trpc
