// Server — service registry + acceptor + request execution.
//
// Parity: brpc::Server (/root/reference/src/brpc/server.h:489 AddService /
// Start lifecycle; server.cpp:831 StartInternal; acceptor.cpp:52,251 the
// accept-until-EAGAIN loop).  Condensed: services are method-name → handler
// entries in a FlatMap; each request runs in its own fiber with a done
// closure that packs and writes the response on the wait-free socket path.
#pragma once

#include <atomic>
#include <functional>
#include <memory>
#include <string>
#include <vector>

#include <errno.h>

#include "base/flat_map.h"
#include "net/auth.h"
#include "base/recordio.h"
#include "fiber/sync.h"
#include "net/concurrency_limiter.h"
#include "net/controller.h"
#include "net/data_pool.h"
#include "net/fault.h"
#include "net/qos.h"
#include "net/socket.h"
#include "stat/latency_recorder.h"

namespace trpc {

class RedisService;   // net/redis.h
class ThriftService;  // net/thrift.h
class MemcacheService;  // net/memcache.h
class MongoService;     // net/mongo.h
class RtmpService;      // net/rtmp.h
class NsheadService;  // net/nshead.h
class EspService;     // net/nshead.h
class SloEngine;      // stat/slo.h

class Server {
 public:
  // Handler runs in a fiber; it may block on fiber primitives freely.
  // Call done() exactly once (async responses allowed).
  using Handler = std::function<void(
      Controller* cntl, const IOBuf& request, IOBuf* response, Closure done)>;

  // Per-method properties (parity: MethodProperty + MethodStatus,
  // server.h:399 / details/method_status.h — auto-created qps/latency vars).
  struct MethodProperty {
    Handler handler;
    std::shared_ptr<LatencyRecorder> latency;
    std::shared_ptr<ConcurrencyLimiter> limiter;  // null = unlimited
  };

  // Admission control for one method: "" unlimited, "<N>" constant, "auto"
  // (AIMD).  Call before Start.
  int SetMethodMaxConcurrency(const std::string& method,
                              const std::string& spec);

  // Per-tenant QoS (net/qos.h TenantGovernor): weighted-fair tenants with
  // their own admission limiters, shedding kEOverloaded when a tenant is
  // over its bound.  Spec grammar (';'-separated):
  //   "<tenant>:weight=N,limit=<spec>" with tenant "*" as the default
  //   clause; limit uses the concurrency_limiter.h grammar.
  // Composes with (runs BEFORE) the per-method limiter.  "" removes.
  // Call before Start.  Returns 0, or -1 on a malformed spec (previous
  // governor kept).
  int SetQos(const std::string& spec);
  std::shared_ptr<TenantGovernor> qos_governor() const { return qos_; }

  // Per-tenant SLO targets (stat/slo.h SloEngine): windowed attainment +
  // multi-window error-budget burn rates, fed from the dispatch path when
  // the reloadable `trpc_slo` flag is on.  Spec grammar (';'-separated):
  //   "<tenant>:p99_us=N,avail=P" with tenant "*" as the default clause;
  //   avail is a percent like 99.9.  "" removes.  Call before Start.
  // Returns 0, or -1 on a malformed spec (previous engine kept).
  // Surfaced by /slo, slo_* vars, timeline event 28 and — with
  // trpc_fleet_publish on — the naming:// fleet publication.
  int SetSlo(const std::string& spec);
  std::shared_ptr<SloEngine> slo_engine() const { return slo_; }

  // Shards the TCP acceptor across `n` SO_REUSEPORT listen sockets
  // (1..kMaxAcceptShards), each registered with its own event-dispatcher
  // slot (trpc_event_dispatchers) so accept storms spread over epoll
  // threads instead of serializing on one listener.  The kernel spreads
  // connections across shards by 4-tuple hash.  Call before Start.
  static constexpr int kMaxAcceptShards = 16;
  int set_reuseport_shards(int n);
  int reuseport_shards() const { return reuseport_shards_; }
  // Connections accepted by each shard (accept-distribution telemetry).
  std::vector<uint64_t> accept_counts() const;

  // Installs connection authentication (auth.h; not owned).  Call before
  // Start.  With an authenticator set, every framed-protocol connection
  // must open with a valid kAuth credential or its requests are refused.
  void set_authenticator(const Authenticator* auth) { auth_ = auth; }
  const Authenticator* authenticator() const { return auth_; }

  // Pins this server's connections (read fibers, handlers, KeepWrite — the
  // whole downstream) to a tagged worker group (fiber.h kMaxFiberTags;
  // parity: ServerOptions::bthread_tag, server.h:280 + per-tag TaskControl
  // groups, task_control.h:94-99).  Saturating one server's tag cannot
  // starve another's workers.  Call before Start; the tag's worker group
  // is provisioned on Start (default size unless fiber_start_tag_workers
  // ran first).
  void set_worker_tag(int tag) { worker_tag_ = tag; }
  int worker_tag() const { return worker_tag_; }

  // Request interceptor (parity: brpc::Interceptor, interceptor.h:26,
  // whose Accept sees the Controller): runs before EVERY request on every
  // serving protocol — RPC methods AND builtin observability paths (only
  // /health stays open, like auth) — with the method-or-path and the
  // peer.  Return false (optionally setting *error_code/*error_text) to
  // reject without reaching the handler.  Call before Start.
  using Interceptor = std::function<bool(
      const std::string& method, const EndPoint& peer, int* error_code,
      std::string* error_text)>;
  void set_interceptor(Interceptor icpt) { interceptor_ = std::move(icpt); }
  const Interceptor& interceptor() const { return interceptor_; }

  // Makes this server speak redis (RESP) on its port alongside the other
  // protocols (net/redis.h; parity: ServerOptions::redis_service,
  // redis.h:194).  Not owned.  Call before Start.
  void set_redis_service(RedisService* rs) { redis_service_ = rs; }
  RedisService* redis_service() const { return redis_service_; }

  // Makes this server speak framed thrift (TBinaryProtocol) on its port
  // (net/thrift.h; parity: ServerOptions::thrift_service,
  // thrift_service.h).  Not owned.  Call before Start.
  void set_thrift_service(ThriftService* ts) { thrift_service_ = ts; }
  ThriftService* thrift_service() const { return thrift_service_; }

  // Makes this server speak the memcache binary protocol on its port
  // (net/memcache.h; the reference is client-only — policy/
  // memcache_binary_protocol.cpp — the serving side here doubles as the
  // in-process fixture its tests fake externally).  Not owned.
  void set_memcache_service(MemcacheService* ms) { memcache_service_ = ms; }
  MemcacheService* memcache_service() const { return memcache_service_; }

  // Runs method handlers on the usercode backup pthread pool instead of
  // fiber workers (net/usercode_pool.h; parity: usercode_in_pthread +
  // details/usercode_backup_pool.h:46).  For handlers that block on
  // pthread-level primitives, which would otherwise pin fiber workers.
  // Call before Start.
  void set_usercode_in_pthread(bool on) { usercode_in_pthread_ = on; }
  bool usercode_in_pthread() const { return usercode_in_pthread_; }

  // Session-local data: pooled per-request scratch objects handed to
  // handlers via Controller::session_local_data() (net/data_pool.h;
  // parity: ServerOptions::session_local_data_factory +
  // reserved_session_local_data, simple_data_pool.*).  Factory not
  // owned.  Call before Start.
  void set_session_local_data_factory(DataFactory* f, size_t reserve = 0) {
    session_data_factory_ = f;
    session_data_reserve_ = reserve;
  }
  SimpleDataPool* session_data_pool() const {
    return session_data_pool_.get();
  }

  // Makes this server answer mongo drivers (OP_MSG) on its port
  // (net/mongo.h; parity: policy/mongo_protocol.cpp server adaptor).
  // Not owned.  Call before Start.
  void set_mongo_service(MongoService* ms) { mongo_service_ = ms; }
  MongoService* mongo_service() const { return mongo_service_; }

  // Makes this server speak RTMP (handshake 0x03, publish/play relay)
  // on its port (net/rtmp.h; parity: ServerOptions::rtmp_service,
  // rtmp.h).  Not owned.  Call before Start.
  void set_rtmp_service(RtmpService* rs) { rtmp_service_ = rs; }
  RtmpService* rtmp_service() const { return rtmp_service_; }

  // nshead-family personalities (net/nshead.h, net/legacy_pbrpc.h).  The
  // 36-byte head's magic is the shared discriminator, so install at most
  // ONE nshead-riding personality per server (raw nshead / nova pbrpc /
  // public pbrpc) — parity: ServerOptions::nshead_service is singular.
  void set_nshead_service(NsheadService* ns) { nshead_service_ = ns; }
  NsheadService* nshead_service() const { return nshead_service_; }
  // esp has NO wire magic: an esp-enabled server dedicates its port.
  void set_esp_service(EspService* es) { esp_service_ = es; }
  EspService* esp_service() const { return esp_service_; }

  // nova / public_pbrpc personalities (net/legacy_pbrpc.h): dispatch
  // nshead-framed pb calls into the method registry ("Nova.#<idx>" /
  // "<service>.#<id>" keys).  Same one-per-server rule as nshead above.
  void enable_nova_pbrpc() { nova_pbrpc_ = true; }
  bool nova_pbrpc_enabled() const { return nova_pbrpc_; }
  void enable_public_pbrpc() { public_pbrpc_ = true; }
  bool public_pbrpc_enabled() const { return public_pbrpc_; }

  // Serves TLS on this server's port (net/tls.h; parity: ServerOptions::
  // mutable_ssl_options, details/ssl_helper.cpp).  Plaintext clients KEEP
  // working on the same port — each accepted connection sniffs its first
  // byte (0x16 = TLS handshake record) and picks the path, like the
  // reference's sniffing acceptor.  PEM cert + key.  Call before Start;
  // returns 0 on success.
  // With a non-empty ca_file, client certificates are REQUIRED and
  // verified against it (mTLS); plaintext sniffing on the same port is
  // unaffected.
  int EnableTls(const std::string& cert_file, const std::string& key_file,
                const std::string& ca_file = "");
  // Shared acceptance check (one body for all protocols).  True = admit;
  // false fills *error_code/*error_text.
  bool accept_request(const std::string& method, const EndPoint& peer,
                      int* error_code, std::string* error_text) const {
    if (!interceptor_) {
      return true;
    }
    *error_code = EACCES;
    *error_text = "rejected by interceptor";
    return interceptor_(method, peer, error_code, error_text);
  }

  ~Server();

  // Register before Start.  Name format "Service.Method" by convention.
  int RegisterMethod(const std::string& full_name, Handler handler);

  // Catch-all handler (parity: BaiduMasterService,
  // baidu_master_service.h:36 + generic call proxying): tstd requests
  // whose method has no registered handler route here with the raw
  // body; the method name is Controller::method().  tstd only, like the
  // reference (BaiduMasterService serves baidu_std exclusively) — HTTP
  // and h2 answer 404/unimplemented as usual.  Call before Start.
  void set_generic_handler(Handler h) { generic_handler_ = std::move(h); }
  const Handler& generic_handler() const { return generic_handler_; }

  // Maps an HTTP path pattern onto a registered method (parity: the
  // reference's RestfulMap, restful.h:62).  Patterns match whole path
  // segments; '*' matches exactly one segment, a trailing '*' matches the
  // remainder ("/v1/echo/*").  Call before Start.
  int MapRestful(const std::string& pattern, const std::string& method);
  // Method mapped by the best-matching pattern, or nullptr;
  // *method_name receives the mapped method's registered name.
  const MethodProperty* find_restful(const std::string& path,
                                     std::string* method_name = nullptr) const;

  // port <= 0 picks an ephemeral port (see port() after).  Returns 0 on ok.
  int Start(int port);
  // Hot-restart successor entry point (the receiving half of Drain's
  // listener handoff): connects to the predecessor's unix handoff socket
  // at `path` (retrying until timeout_ms — the predecessor may not be
  // serving the handoff yet), receives the SO_REUSEPORT listener fds via
  // SCM_RIGHTS, and starts THIS server on them — the shared accept
  // queues mean no SYN is ever refused across the restart.  Register
  // methods before calling, exactly like Start.  The successor's RMA
  // windows/regions are minted fresh in this process (new shm segments,
  // new rkeys) — clients re-handshake rings on reconnect and never see a
  // stale rkey.  Returns 0 on ok.
  int StartFromHandoff(const std::string& path, int64_t timeout_ms = 10000);
  // Graceful drain (zero-downtime leave; ISSUE 12): flips this server to
  // kEDraining (new requests answer immediately with that status — the
  // cluster client fails over WITHOUT quarantining us), runs the drain
  // hooks (naming withdrawal, KV-block tombstoning), then — with a
  // non-empty handoff_path — serves the duplicated listener fds to the
  // successor over a unix socket at that path BEFORE closing our own, so
  // the kernel accept queues never go unowned.  Finally waits out
  // in-flight requests AND in-flight RMA window spans under the
  // deadline (<= 0 uses trpc_drain_deadline_ms).  Returns 0 when fully
  // quiesced, ETIMEDOUT when the deadline cut the wait short (the
  // server is draining either way; call Stop()/destroy as usual).
  int Drain(int64_t deadline_ms = 0, const std::string& handoff_path = "");
  // True from the start of Drain until destruction: new requests are
  // being answered kEDraining.
  bool draining() const {
    return draining_.load(std::memory_order_acquire);
  }
  // Flag registration (idempotent): trpc_drain_deadline_ms — the capi
  // calls it so /flags sees the drain knob before the first Drain.
  static void drain_ensure_registered();
  // Attaches the self-tuning controller (stat/tuner.h): registers the
  // trpc_tuner* flags/vars and flips trpc_tuner through the validated
  // reload path — the embedder's one-liner for "tune this process".
  // The controller is process-wide (it actuates process-wide flags),
  // so this is a convenience attach point, not per-server state.
  // Callable before or after Start; on=false flips it back off.
  // Returns true on success.
  bool EnableTuner(bool on = true);
  // Registers a hook run at the START of Drain (before the in-flight
  // wait): the seam the naming announcer (withdraw), the KV store
  // (tombstone + withdraw_all) and embedders use to leave the fleet
  // before the listener handoff.  Callable before or after Start.
  void add_drain_hook(std::function<void()> hook);
  // Ties a component's lifetime to this server (freed after Stop+Join in
  // ~Server) — e.g. the Announcer created by server_announce.
  void own_component(std::shared_ptr<void> c);
  // Listens on an AF_UNIX path instead (reference: unix sockets are
  // first-class EndPoints).  A stale socket file is unlinked first;
  // Stop unlinks it again.  Channel::Init("unix:<path>") connects.
  int StartUnix(const std::string& path);
  // Stops accepting, fails live connections; in-flight handlers finish.
  void Stop();
  // Parks until every in-flight request has completed (bounded by
  // timeout_ms; -1 = forever).  ~Server runs Stop()+Join() so destruction
  // can never race a handler touching server state.
  int Join(int64_t timeout_ms = 5000);
  // Blocks the calling thread until SIGINT/SIGTERM (parity:
  // brpc::Server::RunUntilAskedToQuit — the "serve forever" idiom for a
  // standalone main()).  NOTE: Join() waits for in-flight REQUESTS only,
  // so a daemon must call this, not Join, to stay up.
  static void RunUntilAskedToQuit();
  int port() const { return port_; }
  bool running() const { return running_.load(std::memory_order_acquire); }

  // -- internals --------------------------------------------------------
  const MethodProperty* find_method(const std::string& name) const {
    return methods_.seek(name);
  }
  template <typename Fn>
  void for_each_method(Fn&& fn) const {
    methods_.for_each(
        [&fn](const std::string& name, const MethodProperty&) { fn(name); });
  }
  std::atomic<int64_t> requests_served{0};
  std::atomic<int> in_flight{0};
  int64_t start_time_us() const { return start_time_us_; }
  void track_connection(SocketId id);

  // rpc_dump parity (/root/reference/src/brpc/rpc_dump.h:40-67): sample
  // incoming requests into a recordio file replayable by tools/rpc_replay.
  int EnableDump(const std::string& path, double sample_rate = 0.01);
  void maybe_dump(const std::string& method, uint32_t attachment_size,
                  const IOBuf& payload);

  // Server-side fault injection (net/fault.h; svr_delay / svr_error /
  // svr_reject fields): a PRIVATE actor per server, so one node of an
  // in-process cluster can misbehave while its siblings stay clean (the
  // chaos soak's quarantine-isolation scenario).  "" disables; callable
  // at runtime (also reachable via this server's /faults?server=...).
  // Returns 0, or -1 on a malformed spec (previous schedule kept).
  int SetFaults(const std::string& spec) { return faults_.set(spec); }
  FaultActor& faults() { return faults_; }

 private:
  static void on_acceptable(SocketId id, void* ctx);
  // Shared pre-listen initialization (fibers, vars, protocol registry,
  // ring-handshake methods) for Start and StartFromHandoff.
  void start_runtime_init();
  // The serving half of the hot-restart handoff: listens on `path`,
  // waits (bounded) for the successor to connect, ships {port, nfds} +
  // dup'd listener fds via SCM_RIGHTS.  0 on success.
  int serve_handoff(const std::string& path, int64_t deadline_us);
  // Fails every listen socket (Drain hands off first; Stop reuses it).
  void fail_listeners();
  // One per listen shard; ctx handed to on_acceptable so the accept
  // counter attributes to the right shard.  Address-stable (unique_ptr)
  // for the sockets' lifetime.
  struct AcceptCtx {
    Server* srv;
    int shard;
  };
  // Creates + registers one listen socket for `fd` as shard `shard`.
  int install_listener(int fd, int shard);
  int64_t start_time_us_ = 0;
  std::unique_ptr<RecordWriter> dump_writer_;
  FiberMutex dump_mu_;
  std::atomic<double> dump_rate_{0.0};

  const Authenticator* auth_ = nullptr;
  Interceptor interceptor_;
  RedisService* redis_service_ = nullptr;
  ThriftService* thrift_service_ = nullptr;
  MemcacheService* memcache_service_ = nullptr;
  MongoService* mongo_service_ = nullptr;
  RtmpService* rtmp_service_ = nullptr;
  NsheadService* nshead_service_ = nullptr;
  EspService* esp_service_ = nullptr;
  bool usercode_in_pthread_ = false;
  int worker_tag_ = 0;
  Handler generic_handler_;
  DataFactory* session_data_factory_ = nullptr;
  size_t session_data_reserve_ = 0;
  std::unique_ptr<SimpleDataPool> session_data_pool_;
  bool nova_pbrpc_ = false;
  bool public_pbrpc_ = false;
  void* tls_ctx_ = nullptr;  // SSL_CTX (leaked singleton; net/tls.h)
  FlatMap<std::string, MethodProperty> methods_;
  // (pattern segments, trailing-wildcard, method name), longest first.
  struct RestfulRule {
    std::vector<std::string> segs;
    bool tail_wild = false;
    std::string method;
  };
  std::vector<RestfulRule> restful_;
  SocketId listen_id_ = 0;
  // REUSEPORT shards beyond the first (listen_id_ stays shard 0 so the
  // single-listener paths are untouched).
  std::vector<SocketId> extra_listen_ids_;
  std::vector<std::unique_ptr<AcceptCtx>> accept_ctxs_;
  std::atomic<uint64_t> accept_counts_[kMaxAcceptShards] = {};
  int reuseport_shards_ = 1;
  std::shared_ptr<TenantGovernor> qos_;
  std::shared_ptr<SloEngine> slo_;
  int port_ = -1;
  std::string unix_path_;  // non-empty when listening on AF_UNIX
  std::atomic<bool> running_{false};
  std::atomic<bool> draining_{false};
  std::mutex drain_mu_;  // guards drain_hooks_ and components_
  std::vector<std::function<void()>> drain_hooks_;
  std::vector<std::shared_ptr<void>> components_;
  std::mutex conns_mu_;
  std::vector<SocketId> conns_;      // stale ids harmless (versioned)
  size_t conns_prune_at_ = 4096;     // doubles with the live set (scale)
  std::vector<SocketId> drain_ids_;  // failed at Stop; awaited in ~Server
  // Server-side fault points; kServer scope rejects transport-only specs
  // that could never fire here (silent no-op prevention).
  FaultActor faults_{FaultScope::kServer};
};

}  // namespace trpc
