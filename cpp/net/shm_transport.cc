#include "net/shm_transport.h"

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstring>
#include <mutex>
#include <vector>

#include "base/logging.h"
#include "base/rand.h"
#include "base/time.h"

namespace trpc {

namespace {

constexpr uint32_t kRingCap = 1 << 20;  // 1MB per direction (power of 2)
constexpr uint64_t kShmMagic = 0x54525053484d3254ull;  // "TRPSHM2T"

// SPSC byte ring; head/tail are free-running cursors (cap power of 2).
struct Ring {
  // Cursors on separate cache lines (cross-process false sharing would sit
  // on the hottest path), data likewise aligned.
  alignas(64) std::atomic<uint64_t> head;  // producer cursor
  alignas(64) std::atomic<uint64_t> tail;  // consumer cursor
  alignas(64) char data[kRingCap];

  uint32_t readable() const {
    return static_cast<uint32_t>(head.load(std::memory_order_acquire) -
                                 tail.load(std::memory_order_acquire));
  }
  uint32_t writable() const { return kRingCap - readable(); }

  // Copy bytes at *cursor without publishing: the batched-doorbell write
  // path (the ONLY producer) stages a whole KeepWrite drain, then
  // publish()es once.  The consumer only sees bytes at publish, so a
  // drain of N messages costs the peer one head-cursor cache-line
  // transfer instead of N.
  uint32_t write_staged(const char* src, uint32_t n, uint64_t* cursor) {
    const uint64_t h = *cursor;
    const uint32_t space =
        kRingCap -
        static_cast<uint32_t>(h - tail.load(std::memory_order_acquire));
    n = std::min(n, space);
    const uint32_t off = static_cast<uint32_t>(h) & (kRingCap - 1);
    const uint32_t first = std::min(n, kRingCap - off);
    memcpy(data + off, src, first);
    memcpy(data, src + first, n - first);
    *cursor = h + n;
    return n;
  }

  void publish(uint64_t cursor) {
    head.store(cursor, std::memory_order_release);
  }

  uint32_t read(char* dst, uint32_t n) {
    const uint64_t t = tail.load(std::memory_order_relaxed);
    const uint32_t avail =
        static_cast<uint32_t>(head.load(std::memory_order_acquire) - t);
    n = std::min(n, avail);
    const uint32_t off = static_cast<uint32_t>(t) & (kRingCap - 1);
    const uint32_t first = std::min(n, kRingCap - off);
    memcpy(dst, data + off, first);
    memcpy(dst + first, data, n - first);
    tail.store(t + n, std::memory_order_release);
    return n;
  }
};

struct Segment {
  uint64_t magic;
  // Liveness: each side publishes its pid at map time and its poller
  // bumps a heartbeat word ~1/s. A peer is reaped (crash cleanup) when
  // its process is verifiably gone (ESRCH) OR its heartbeat stalls long
  // enough — the heartbeat covers pid recycling and EPERM ambiguity,
  // where kill(pid, 0) cannot prove liveness. A healthy idle peer is
  // never timed out (ubshm/ keeps segments alive with a shm manager +
  // timers; this is the single-host equivalent).
  std::atomic<int32_t> client_pid;
  std::atomic<int32_t> server_pid;
  std::atomic<uint64_t> client_beat;
  std::atomic<uint64_t> server_beat;
  Ring c2s;
  Ring s2c;
};

}  // namespace

void shm_conn_release_name(const std::string& name);

struct ShmConn {
  Segment* seg = nullptr;
  std::string name;
  bool is_client = false;  // client writes c2s, reads s2c
  bool creator = false;
  // Staged (unpublished) tx head cursor, owned by the socket's single
  // writer role; UINT64_MAX = nothing staged (Transport::flush contract).
  uint64_t tx_staged = UINT64_MAX;

  Ring& tx() { return is_client ? seg->c2s : seg->s2c; }
  Ring& rx() { return is_client ? seg->s2c : seg->c2s; }
  int32_t peer_pid() const {
    return (is_client ? seg->server_pid : seg->client_pid)
        .load(std::memory_order_acquire);
  }
  uint64_t peer_beat() const {
    return (is_client ? seg->server_beat : seg->client_beat)
        .load(std::memory_order_acquire);
  }
  void bump_self_beat() {
    (is_client ? seg->client_beat : seg->server_beat)
        .fetch_add(1, std::memory_order_acq_rel);
  }
  // Reaping a crashed peer promotes this side to cleanup duty even if it
  // was not the creator: the creator is gone and can never unlink.
  bool unlink_on_close = false;

  ~ShmConn() {
    if (seg != nullptr) {
      munmap(seg, sizeof(Segment));
    }
    if (creator || unlink_on_close) {
      shm_unlink(name.c_str());
    }
    if (!creator) {
      shm_conn_release_name(name);
    }
  }
};

namespace {

// ---- poller (the reference's polling completion mode) -------------------

struct PolledRing {
  std::weak_ptr<ShmConn> conn;
  SocketId socket = 0;
  uint64_t last_rx_head = 0;
  uint64_t last_tx_tail = 0;
  int64_t created_us = 0;
  int64_t last_liveness_us = 0;
  uint64_t last_peer_beat = 0;
  int64_t peer_beat_changed_us = 0;
};

class ShmPoller {
 public:
  static ShmPoller* instance() {
    // Deliberately leaked (detached thread outlives static destruction).
    static ShmPoller* p = new ShmPoller();
    return p;
  }

  void add(std::shared_ptr<ShmConn> conn, SocketId socket) {
    std::lock_guard<std::mutex> g(mu_);
    rings_.push_back(PolledRing{conn, socket, 0, 0, monotonic_time_us()});
  }

 private:
  ShmPoller() {
    pthread_t tid;
    pthread_create(
        &tid, nullptr,
        [](void* self) -> void* {
          static_cast<ShmPoller*>(self)->run();
          return nullptr;
        },
        this);
    pthread_detach(tid);
  }

  void run() {
    int idle_spins = 0;
    while (true) {
      bool any = false;
      {
        // One clock read per pass (the loop below is the hot spin path).
        const int64_t now_us = monotonic_time_us();
        std::lock_guard<std::mutex> g(mu_);
        for (size_t i = 0; i < rings_.size();) {
          PolledRing& pr = rings_[i];
          std::shared_ptr<ShmConn> conn = pr.conn.lock();
          if (conn == nullptr) {  // socket torn down; drop the entry
            rings_[i] = rings_.back();
            rings_.pop_back();
            continue;
          }
          const uint64_t rx_head =
              conn->rx().head.load(std::memory_order_acquire);
          // Liveness, rate-limited to ~1/s per ring (kill() is a syscall
          // and beats are cross-core cache traffic). Reap when:
          //  - the peer never published a pid (hostile/foreign segment
          //    content; our own handshake always publishes pre-poll), or
          //  - the peer pid verifiably exited (ESRCH), or
          //  - the peer heartbeat stalled >30s (covers pid recycling and
          //    kill() EPERM, where the pid alone proves nothing).
          if (now_us - pr.last_liveness_us > 1000 * 1000) {
            pr.last_liveness_us = now_us;
            conn->bump_self_beat();
            const uint64_t beat = conn->peer_beat();
            if (beat != pr.last_peer_beat || pr.peer_beat_changed_us == 0) {
              pr.last_peer_beat = beat;
              pr.peer_beat_changed_us = now_us;
            }
            const int32_t peer = conn->peer_pid();
            const bool no_pid =
                peer == 0 && now_us - pr.created_us > 30 * 1000 * 1000;
            const bool dead_pid =
                peer != 0 && kill(static_cast<pid_t>(peer), 0) != 0 &&
                errno == ESRCH;
            const bool stalled =
                now_us - pr.peer_beat_changed_us > 30 * 1000 * 1000;
            if (no_pid || dead_pid || stalled) {
              LOG(Warning) << "shm peer lost (" << conn->name << ", pid "
                           << peer << ", "
                           << (dead_pid ? "exited"
                                        : (no_pid ? "never published"
                                                  : "heartbeat stalled"))
                           << "); reaping segment";
              conn->unlink_on_close = true;  // peer can't clean up; we do
              SocketRef dead(Socket::Address(pr.socket));
              if (dead) {
                dead->SetFailed(no_pid ? ETIMEDOUT : ECONNRESET);
              }
              rings_[i] = rings_.back();
              rings_.pop_back();
              continue;
            }
          }
          if (rx_head != pr.last_rx_head) {
            pr.last_rx_head = rx_head;
            any = true;
            SocketRef s(Socket::Address(pr.socket));
            if (s) {
              s->on_input_event();
            }
          }
          const uint64_t tx_tail =
              conn->tx().tail.load(std::memory_order_acquire);
          if (tx_tail != pr.last_tx_tail) {
            pr.last_tx_tail = tx_tail;
            any = true;
            SocketRef s(Socket::Address(pr.socket));
            if (s) {
              s->on_output_event();  // peer consumed → writable edge
            }
          }
          ++i;
        }
      }
      if (any) {
        idle_spins = 0;
        continue;  // hot: stay on the rings
      }
      if (++idle_spins < 1000) {
        sched_yield();
      } else {
        usleep(100);  // adaptive backoff when quiet
      }
    }
  }

  std::mutex mu_;
  std::vector<PolledRing> rings_;
};

// ---- the Transport ------------------------------------------------------

class ShmRingTransport final : public Transport {
 public:
  ssize_t cut_from_iobuf(Socket* s, IOBuf* from) override {
    auto* conn = static_cast<ShmConn*>(s->transport_ctx);
    if (conn == nullptr) {
      errno = ENOTCONN;
      return -1;
    }
    Ring& tx = conn->tx();
    // Stage the whole buffer at an unpublished cursor; flush() rings the
    // doorbell once per drain (peer sees nothing until then).
    if (conn->tx_staged == UINT64_MAX) {
      conn->tx_staged = tx.head.load(std::memory_order_relaxed);
    }
    size_t total = 0;
    while (!from->empty()) {
      const IOBuf::BlockRef& ref = from->ref_at(0);
      const uint32_t wrote = tx.write_staged(ref.block->data + ref.offset,
                                             ref.length, &conn->tx_staged);
      if (wrote == 0) {
        break;  // ring full
      }
      from->pop_front(wrote);
      total += wrote;
    }
    return static_cast<ssize_t>(total);  // 0 = EAGAIN-equivalent
  }

  void flush(Socket* s) override {
    auto* conn = static_cast<ShmConn*>(s->transport_ctx);
    if (conn == nullptr || conn->tx_staged == UINT64_MAX) {
      return;
    }
    conn->tx().publish(conn->tx_staged);
    conn->tx_staged = UINT64_MAX;
  }

  ssize_t append_to_iobuf(Socket* s, IOBuf* to, size_t max) override {
    auto* conn = static_cast<ShmConn*>(s->transport_ctx);
    if (conn == nullptr) {
      errno = ENOTCONN;
      return -1;
    }
    Ring& rx = conn->rx();
    char tmp[16 * 1024];
    size_t total = 0;
    while (total < max) {
      const uint32_t got = rx.read(
          tmp, static_cast<uint32_t>(std::min(sizeof(tmp), max - total)));
      if (got == 0) {
        break;
      }
      to->append(tmp, got);
      total += got;
    }
    return static_cast<ssize_t>(total);  // 0 = drained
  }

  int connect(Socket*) override { return 0; }  // established at handshake
  bool fd_based() const override { return false; }
  const char* name() const override { return "shm_ring"; }
};

ShmRingTransport* shm_transport() {
  static ShmRingTransport t;
  return &t;
}

Segment* map_segment(int fd) {
  void* mem = mmap(nullptr, sizeof(Segment), PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  return mem == MAP_FAILED ? nullptr : static_cast<Segment*>(mem);
}

}  // namespace

std::shared_ptr<ShmConn> shm_conn_create(std::string* name_out) {
  char name[64];
  snprintf(name, sizeof(name), "/trpc_%d_%llx", getpid(),
           static_cast<unsigned long long>(fast_rand()));
  const int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    return nullptr;
  }
  if (ftruncate(fd, sizeof(Segment)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Segment* seg = map_segment(fd);
  if (seg == nullptr) {
    shm_unlink(name);
    return nullptr;
  }
  memset(static_cast<void*>(seg), 0, sizeof(Segment));
  seg->magic = kShmMagic;
  seg->client_pid.store(static_cast<int32_t>(getpid()),
                        std::memory_order_release);
  auto conn = std::make_shared<ShmConn>();
  conn->seg = seg;
  conn->name = name;
  conn->is_client = true;
  conn->creator = true;
  *name_out = name;
  return conn;
}

namespace {
// One server-side consumer per segment, ever: re-opening a name would put
// two readers on one SPSC ring.
std::mutex& open_names_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
std::vector<std::string>& open_names() {
  static auto* v = new std::vector<std::string>();
  return *v;
}
}  // namespace

void shm_conn_release_name(const std::string& name) {
  std::lock_guard<std::mutex> g(open_names_mu());
  auto& v = open_names();
  v.erase(std::remove(v.begin(), v.end(), name), v.end());
}

std::shared_ptr<ShmConn> shm_conn_open(const std::string& name) {
  // Only names our handshake mints are acceptable (the peer is untrusted
  // input at this boundary).
  if (name.empty() || name[0] != '/' || name.rfind("/trpc_", 0) != 0 ||
      name.size() > 60) {
    return nullptr;
  }
  {
    std::lock_guard<std::mutex> g(open_names_mu());
    auto& v = open_names();
    if (std::find(v.begin(), v.end(), name) != v.end()) {
      return nullptr;  // duplicate consumer attempt
    }
    v.push_back(name);
  }
  const int fd = shm_open(name.c_str(), O_RDWR, 0600);
  if (fd < 0) {
    shm_conn_release_name(name);
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size != sizeof(Segment)) {
    close(fd);
    shm_conn_release_name(name);
    return nullptr;
  }
  Segment* seg = map_segment(fd);
  if (seg == nullptr || seg->magic != kShmMagic) {
    if (seg != nullptr) {
      munmap(seg, sizeof(Segment));
    }
    shm_conn_release_name(name);
    return nullptr;
  }
  seg->server_pid.store(static_cast<int32_t>(getpid()),
                        std::memory_order_release);
  auto conn = std::make_shared<ShmConn>();
  conn->seg = seg;
  conn->name = name;
  conn->is_client = false;
  return conn;
}

void shm_conn_set_self_pid(ShmConn& c, int32_t pid) {
  (c.is_client ? c.seg->client_pid : c.seg->server_pid)
      .store(pid, std::memory_order_release);
}

int shm_socket_create(std::shared_ptr<ShmConn> conn,
                      void (*on_readable)(SocketId, void*), void* user_data,
                      SocketId* out) {
  Socket::Options opts;
  opts.fd = -1;
  opts.mode = SocketMode::kShm;  // fd-less: no epoll registration
  opts.on_readable = on_readable;
  opts.user_data = user_data;
  opts.transport = shm_transport();
  opts.transport_ctx_holder = conn;  // keeps the mapping alive w/ the socket
  if (Socket::Create(opts, out) != 0) {
    return -1;
  }
  ShmPoller::instance()->add(conn, *out);
  return 0;
}

}  // namespace trpc
