#include "net/shm_transport.h"

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstring>
#include <mutex>
#include <vector>

#include "base/flags.h"
#include "base/logging.h"
#include "base/rand.h"
#include "base/time.h"
#include "net/rma.h"

namespace trpc {

namespace {

// Bumped from "...3T": the segment grew the per-side rma window rkey
// words — a mixed-version pair must fail the handshake, not misread
// ring offsets.
constexpr uint64_t kShmMagic = 0x54525053484d3454ull;  // "TRPSHM4T"

// Ring capacity per direction: a reloadable flag read at SEGMENT CREATE
// time (the cap is baked into the segment header; live connections keep
// theirs).  The old fixed 1MB ring forced a 64MB transfer through 64
// fill/drain round trips with a wakeup each — large-message throughput
// satellite of the stripe work (ISSUE 5).
Flag* ring_bytes_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_shm_ring_bytes", 4 << 20,
        "shm ring capacity per direction for NEW connections (bytes, "
        "power of two in [64KB, 256MB])");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= (64 << 10) &&
               n <= (256ll << 20) && (n & (n - 1)) == 0;
      });
      // Bounds hint only: the validator checks power-of-two on top of
      // the range, so set_int_range would be too permissive.
      flag->set_bounds_hint(64 << 10, 256ll << 20);
    }
    return flag;
  }();
  return f;
}
[[maybe_unused]] Flag* const g_ring_bytes_eager = ring_bytes_flag();

// Producer publishes its staged cursor every this-many staged bytes so
// the consumer's copy-out overlaps the producer's copy-in (double/triple
// buffering through the ring) instead of waiting for the whole drain.
constexpr uint32_t kEagerPublishBytes = 128 * 1024;

// SPSC byte ring: head/tail are free-running cursors over a power-of-two
// capacity picked at segment creation; cursors live on their own cache
// lines (cross-process false sharing would sit on the hottest path).
struct RingHdr {
  alignas(64) std::atomic<uint64_t> head;  // producer cursor
  alignas(64) std::atomic<uint64_t> tail;  // consumer cursor
};

struct Segment {
  uint64_t magic;
  uint32_t ring_cap;  // bytes per direction (power of two)
  // Liveness: each side publishes its pid at map time and its poller
  // bumps a heartbeat word ~1/s. A peer is reaped (crash cleanup) when
  // its process is verifiably gone (ESRCH) OR its heartbeat stalls long
  // enough — the heartbeat covers pid recycling and EPERM ambiguity,
  // where kill(pid, 0) cannot prove liveness. A healthy idle peer is
  // never timed out (ubshm/ keeps segments alive with a shm manager +
  // timers; this is the single-host equivalent).
  std::atomic<int32_t> client_pid;
  std::atomic<int32_t> server_pid;
  std::atomic<uint64_t> client_beat;
  std::atomic<uint64_t> server_beat;
  // One-sided plane (net/rma.h): each side publishes the rkey of its
  // registered receive window here (release; 0 while absent/disabled).
  // The peer maps it and WRITES large bodies straight in — the rings
  // then carry only control frames for those transfers.
  std::atomic<uint64_t> client_rma_rkey;
  std::atomic<uint64_t> server_rma_rkey;
  RingHdr c2s;
  RingHdr s2c;
  alignas(64) char ring_data[];  // c2s bytes, then s2c bytes
};

size_t segment_size(uint32_t cap) {
  return sizeof(Segment) + 2ull * cap;
}

// Header + data-slice view of one direction (cap from the mapped header).
struct RingView {
  RingHdr* h;
  char* data;
  uint32_t cap;

  uint32_t readable() const {
    return static_cast<uint32_t>(h->head.load(std::memory_order_acquire) -
                                 h->tail.load(std::memory_order_acquire));
  }

  // Copy bytes at *cursor without publishing: the batched-doorbell write
  // path (the ONLY producer) stages a KeepWrite drain and publishes at
  // eager intervals + once at flush, so the peer sees few head-cursor
  // cache-line transfers while still overlapping its copy-out.
  uint32_t write_staged(const char* src, uint32_t n, uint64_t* cursor) {
    const uint64_t hd = *cursor;
    const uint32_t space =
        cap - static_cast<uint32_t>(
                  hd - h->tail.load(std::memory_order_acquire));
    n = std::min(n, space);
    const uint32_t off = static_cast<uint32_t>(hd) & (cap - 1);
    const uint32_t first = std::min(n, cap - off);
    memcpy(data + off, src, first);
    memcpy(data, src + first, n - first);
    *cursor = hd + n;
    return n;
  }

  void publish(uint64_t cursor) {
    h->head.store(cursor, std::memory_order_release);
  }

  uint32_t read(char* dst, uint32_t n) {
    const uint64_t t = h->tail.load(std::memory_order_relaxed);
    const uint32_t avail =
        static_cast<uint32_t>(h->head.load(std::memory_order_acquire) - t);
    n = std::min(n, avail);
    const uint32_t off = static_cast<uint32_t>(t) & (cap - 1);
    const uint32_t first = std::min(n, cap - off);
    memcpy(dst, data + off, first);
    memcpy(dst + first, data, n - first);
    h->tail.store(t + n, std::memory_order_release);
    return n;
  }
};

}  // namespace

void shm_conn_release_name(const std::string& name);

struct ShmConn {
  Segment* seg = nullptr;
  std::string name;
  bool is_client = false;  // client writes c2s, reads s2c
  bool creator = false;
  // Staged (unpublished) tx head cursor, owned by the socket's single
  // writer role; UINT64_MAX = nothing staged (Transport::flush contract).
  uint64_t tx_staged = UINT64_MAX;
  // One-sided session (net/rma.h): local window + peer window resolve.
  std::shared_ptr<RmaSession> rma;

  RingView ring(bool c2s_dir) {
    RingView v;
    v.h = c2s_dir ? &seg->c2s : &seg->s2c;
    v.cap = seg->ring_cap;
    v.data = seg->ring_data + (c2s_dir ? 0 : seg->ring_cap);
    return v;
  }
  RingView tx() { return ring(is_client); }
  RingView rx() { return ring(!is_client); }
  int32_t peer_pid() const {
    return (is_client ? seg->server_pid : seg->client_pid)
        .load(std::memory_order_acquire);
  }
  uint64_t peer_beat() const {
    return (is_client ? seg->server_beat : seg->client_beat)
        .load(std::memory_order_acquire);
  }
  void bump_self_beat() {
    (is_client ? seg->client_beat : seg->server_beat)
        .fetch_add(1, std::memory_order_acq_rel);
  }
  // Reaping a crashed peer promotes this side to cleanup duty even if it
  // was not the creator: the creator is gone and can never unlink.
  bool unlink_on_close = false;

  ~ShmConn() {
    if (seg != nullptr) {
      munmap(seg, segment_size(seg->ring_cap));
    }
    if (creator || unlink_on_close) {
      shm_unlink(name.c_str());
    }
    if (!creator) {
      shm_conn_release_name(name);
    }
  }
};

namespace {

// ---- poller (the reference's polling completion mode) -------------------

struct PolledRing {
  std::weak_ptr<ShmConn> conn;
  SocketId socket = 0;
  uint64_t last_rx_head = 0;
  uint64_t last_tx_tail = 0;
  int64_t created_us = 0;
  int64_t last_liveness_us = 0;
  uint64_t last_peer_beat = 0;
  int64_t peer_beat_changed_us = 0;
};

class ShmPoller {
 public:
  static ShmPoller* instance() {
    // Deliberately leaked (detached thread outlives static destruction).
    static ShmPoller* p = new ShmPoller();
    return p;
  }

  void add(std::shared_ptr<ShmConn> conn, SocketId socket) {
    std::lock_guard<std::mutex> g(mu_);
    rings_.push_back(PolledRing{conn, socket, 0, 0, monotonic_time_us()});
  }

 private:
  ShmPoller() {
    pthread_t tid;
    pthread_create(
        &tid, nullptr,
        [](void* self) -> void* {
          static_cast<ShmPoller*>(self)->run();
          return nullptr;
        },
        this);
    pthread_detach(tid);
  }

  void run() {
    int idle_spins = 0;
    while (true) {
      bool any = false;
      {
        // One clock read per pass (the loop below is the hot spin path).
        const int64_t now_us = monotonic_time_us();
        std::lock_guard<std::mutex> g(mu_);
        for (size_t i = 0; i < rings_.size();) {
          PolledRing& pr = rings_[i];
          std::shared_ptr<ShmConn> conn = pr.conn.lock();
          if (conn == nullptr) {  // socket torn down; drop the entry
            rings_[i] = rings_.back();
            rings_.pop_back();
            continue;
          }
          const uint64_t rx_head =
              conn->rx().h->head.load(std::memory_order_acquire);
          // Liveness, rate-limited to ~1/s per ring (kill() is a syscall
          // and beats are cross-core cache traffic). Reap when:
          //  - the peer never published a pid (hostile/foreign segment
          //    content; our own handshake always publishes pre-poll), or
          //  - the peer pid verifiably exited (ESRCH), or
          //  - the peer heartbeat stalled >30s (covers pid recycling and
          //    kill() EPERM, where the pid alone proves nothing).
          if (now_us - pr.last_liveness_us > 1000 * 1000) {
            pr.last_liveness_us = now_us;
            conn->bump_self_beat();
            const uint64_t beat = conn->peer_beat();
            if (beat != pr.last_peer_beat || pr.peer_beat_changed_us == 0) {
              pr.last_peer_beat = beat;
              pr.peer_beat_changed_us = now_us;
            }
            const int32_t peer = conn->peer_pid();
            const bool no_pid =
                peer == 0 && now_us - pr.created_us > 30 * 1000 * 1000;
            const bool dead_pid =
                peer != 0 && kill(static_cast<pid_t>(peer), 0) != 0 &&
                errno == ESRCH;
            const bool stalled =
                now_us - pr.peer_beat_changed_us > 30 * 1000 * 1000;
            if (no_pid || dead_pid || stalled) {
              LOG(Warning) << "shm peer lost (" << conn->name << ", pid "
                           << peer << ", "
                           << (dead_pid ? "exited"
                                        : (no_pid ? "never published"
                                                  : "heartbeat stalled"))
                           << "); reaping segment";
              conn->unlink_on_close = true;  // peer can't clean up; we do
              SocketRef dead(Socket::Address(pr.socket));
              if (dead) {
                dead->SetFailed(no_pid ? ETIMEDOUT : ECONNRESET);
              }
              rings_[i] = rings_.back();
              rings_.pop_back();
              continue;
            }
          }
          if (rx_head != pr.last_rx_head) {
            pr.last_rx_head = rx_head;
            any = true;
            SocketRef s(Socket::Address(pr.socket));
            if (s) {
              s->on_input_event();
            }
          }
          const uint64_t tx_tail =
              conn->tx().h->tail.load(std::memory_order_acquire);
          if (tx_tail != pr.last_tx_tail) {
            pr.last_tx_tail = tx_tail;
            any = true;
            SocketRef s(Socket::Address(pr.socket));
            if (s) {
              s->on_output_event();  // peer consumed → writable edge
            }
          }
          ++i;
        }
      }
      if (any) {
        idle_spins = 0;
        continue;  // hot: stay on the rings
      }
      if (++idle_spins < 1000) {
        sched_yield();
      } else {
        usleep(100);  // adaptive backoff when quiet
      }
    }
  }

  std::mutex mu_;
  std::vector<PolledRing> rings_;
};

// ---- the Transport ------------------------------------------------------

class ShmRingTransport final : public Transport {
 public:
  ssize_t cut_from_iobuf(Socket* s, IOBuf* from) override {
    auto* conn = static_cast<ShmConn*>(s->transport_ctx);
    if (conn == nullptr) {
      errno = ENOTCONN;
      return -1;
    }
    RingView tx = conn->tx();
    // Stage at an unpublished cursor; publish at eager intervals so the
    // peer's copy-out overlaps this copy-in (a multi-MB drain would
    // otherwise fill the whole ring before the consumer sees byte one),
    // with flush() as the final doorbell of the drain.
    if (conn->tx_staged == UINT64_MAX) {
      conn->tx_staged = tx.h->head.load(std::memory_order_relaxed);
    }
    size_t total = 0;
    while (!from->empty()) {
      const IOBuf::BlockRef& ref = from->ref_at(0);
      const uint32_t wrote = tx.write_staged(ref.block->data + ref.offset,
                                             ref.length, &conn->tx_staged);
      if (wrote == 0) {
        break;  // ring full
      }
      from->pop_front(wrote);
      total += wrote;
      if (conn->tx_staged -
              tx.h->head.load(std::memory_order_relaxed) >=
          kEagerPublishBytes) {
        tx.publish(conn->tx_staged);
      }
    }
    return static_cast<ssize_t>(total);  // 0 = EAGAIN-equivalent
  }

  void flush(Socket* s) override {
    auto* conn = static_cast<ShmConn*>(s->transport_ctx);
    if (conn == nullptr || conn->tx_staged == UINT64_MAX) {
      return;
    }
    conn->tx().publish(conn->tx_staged);
    conn->tx_staged = UINT64_MAX;
  }

  ssize_t append_to_iobuf(Socket* s, IOBuf* to, size_t max) override {
    auto* conn = static_cast<ShmConn*>(s->transport_ctx);
    if (conn == nullptr) {
      errno = ENOTCONN;
      return -1;
    }
    RingView rx = conn->rx();
    size_t total = 0;
    while (total < max) {
      // Single copy, ring → IOBuf tail: reserve what is readable (bulk
      // transfers get big pooled blocks) instead of bouncing through a
      // 16KB stack buffer.  avail only grows under the consumer, so
      // read() returns exactly n.
      const uint32_t avail = rx.readable();
      if (avail == 0) {
        break;
      }
      uint32_t n = static_cast<uint32_t>(
          std::min<size_t>(avail, max - total));
      if (n < HostArena::kBigBlockMin) {
        // Mid-size reserves would allocate odd-cap blocks that neither
        // the TLS block cache (exact default size) nor the big-block
        // pool (>=256KB pow2) recycles — cut them to default-block
        // granularity so a steady small-message stream reuses cached
        // blocks instead of malloc/free per sweep.
        n = std::min(n, HostArena::kDefaultBlockSize);
      }
      char* dst = to->reserve(n);
      rx.read(dst, n);
      total += n;
    }
    return static_cast<ssize_t>(total);  // 0 = drained
  }

  int connect(Socket*) override { return 0; }  // established at handshake
  bool fd_based() const override { return false; }
  const char* name() const override { return "shm_ring"; }

  // One-sided capability: the connection's window session (nullptr when
  // trpc_rma_window_bytes was 0 at establishment).
  RmaSession* rma(Socket* s) override {
    auto* conn = static_cast<ShmConn*>(s->transport_ctx);
    return conn != nullptr ? conn->rma.get() : nullptr;
  }
};

ShmRingTransport* shm_transport() {
  static ShmRingTransport t;
  return &t;
}

Segment* map_segment(int fd, size_t bytes) {
  void* mem =
      mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  return mem == MAP_FAILED ? nullptr : static_cast<Segment*>(mem);
}

}  // namespace

std::shared_ptr<ShmConn> shm_conn_create(std::string* name_out) {
  char name[64];
  snprintf(name, sizeof(name), "/trpc_%d_%llx", getpid(),
           static_cast<unsigned long long>(fast_rand()));
  const uint32_t cap =
      static_cast<uint32_t>(ring_bytes_flag()->int64_value());
  const size_t bytes = segment_size(cap);
  const int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    return nullptr;
  }
  if (ftruncate(fd, static_cast<off_t>(bytes)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Segment* seg = map_segment(fd, bytes);
  if (seg == nullptr) {
    shm_unlink(name);
    return nullptr;
  }
  memset(static_cast<void*>(seg), 0, sizeof(Segment));
  seg->magic = kShmMagic;
  seg->ring_cap = cap;
  seg->client_pid.store(static_cast<int32_t>(getpid()),
                        std::memory_order_release);
  auto conn = std::make_shared<ShmConn>();
  conn->seg = seg;
  conn->name = name;
  conn->is_client = true;
  conn->creator = true;
  conn->rma = rma_session_create();
  if (conn->rma != nullptr) {
    conn->rma->peer_rkey_slot = &seg->server_rma_rkey;
    // Release: the window region is fully built before the peer can
    // observe its rkey.
    seg->client_rma_rkey.store(conn->rma->local_rkey,
                               std::memory_order_release);
  }
  *name_out = name;
  return conn;
}

namespace {
// One server-side consumer per segment, ever: re-opening a name would put
// two readers on one SPSC ring.
std::mutex& open_names_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
std::vector<std::string>& open_names() {
  static auto* v = new std::vector<std::string>();
  return *v;
}
}  // namespace

void shm_conn_release_name(const std::string& name) {
  std::lock_guard<std::mutex> g(open_names_mu());
  auto& v = open_names();
  v.erase(std::remove(v.begin(), v.end(), name), v.end());
}

std::shared_ptr<ShmConn> shm_conn_open(const std::string& name) {
  // Only names our handshake mints are acceptable (the peer is untrusted
  // input at this boundary).
  if (name.empty() || name[0] != '/' || name.rfind("/trpc_", 0) != 0 ||
      name.size() > 60) {
    return nullptr;
  }
  {
    std::lock_guard<std::mutex> g(open_names_mu());
    auto& v = open_names();
    if (std::find(v.begin(), v.end(), name) != v.end()) {
      return nullptr;  // duplicate consumer attempt
    }
    v.push_back(name);
  }
  const int fd = shm_open(name.c_str(), O_RDWR, 0600);
  if (fd < 0) {
    shm_conn_release_name(name);
    return nullptr;
  }
  // The header carries the creator's ring capacity; validate BEFORE
  // trusting it: magic + power-of-two cap + exact file size (a hostile
  // or stale segment must not become out-of-bounds ring indexing).
  struct stat st;
  if (fstat(fd, &st) != 0 ||
      st.st_size < static_cast<off_t>(sizeof(Segment))) {
    close(fd);
    shm_conn_release_name(name);
    return nullptr;
  }
  Segment* seg = map_segment(fd, static_cast<size_t>(st.st_size));
  if (seg == nullptr || seg->magic != kShmMagic ||
      seg->ring_cap < (64 << 10) || seg->ring_cap > (256u << 20) ||
      (seg->ring_cap & (seg->ring_cap - 1)) != 0 ||
      static_cast<size_t>(st.st_size) != segment_size(seg->ring_cap)) {
    if (seg != nullptr) {
      munmap(seg, static_cast<size_t>(st.st_size));
    }
    shm_conn_release_name(name);
    return nullptr;
  }
  seg->server_pid.store(static_cast<int32_t>(getpid()),
                        std::memory_order_release);
  auto conn = std::make_shared<ShmConn>();
  conn->seg = seg;
  conn->name = name;
  conn->is_client = false;
  conn->rma = rma_session_create();
  if (conn->rma != nullptr) {
    conn->rma->peer_rkey_slot = &seg->client_rma_rkey;
    // Release: pairs with the peer's acquire read at first rma send.
    seg->server_rma_rkey.store(conn->rma->local_rkey,
                               std::memory_order_release);
  }
  return conn;
}

void shm_conn_set_self_pid(ShmConn& c, int32_t pid) {
  (c.is_client ? c.seg->client_pid : c.seg->server_pid)
      .store(pid, std::memory_order_release);
}

int shm_socket_create(std::shared_ptr<ShmConn> conn,
                      void (*on_readable)(SocketId, void*), void* user_data,
                      SocketId* out) {
  Socket::Options opts;
  opts.fd = -1;
  opts.mode = SocketMode::kShm;  // fd-less: no epoll registration
  opts.on_readable = on_readable;
  opts.user_data = user_data;
  opts.transport = shm_transport();
  opts.transport_ctx_holder = conn;  // keeps the mapping alive w/ the socket
  if (Socket::Create(opts, out) != 0) {
    return -1;
  }
  ShmPoller::instance()->add(conn, *out);
  return 0;
}

}  // namespace trpc
