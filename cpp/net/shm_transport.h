// Shared-memory ring transport — same-host RPC without the kernel socket
// path.
//
// Parity: the fork's UBRing transport (/root/reference/src/brpc/ubshm/:
// ring buffers in POSIX shm with head/tail control words, ub_ring.h:165;
// poller registration mirroring epoll, ub_endpoint.h:93-120; selected via
// SocketMode::UBRING).  Re-designed condensed:
//
// - A connection is one shm segment holding two SPSC byte rings (c2s, s2c)
//   with atomic head/tail cursors — cross-process visible, lock-free.
// - Establishment mirrors rdma_handshake-over-TCP: the client creates and
//   maps the segment, then registers it with the server via a normal RPC
//   ("__shm.Connect") carrying the segment name; each side then runs a
//   dedicated fd-less Socket whose Transport is the ring pair.  No
//   transport rebinding on live sockets — no torn frames.
// - Readiness is a polling thread (the reference's rdma_use_polling mode,
//   input_messenger.cpp:300-306): it watches all registered rings and
//   injects on_input_event / on_output_event exactly like the epoll
//   dispatcher, with adaptive backoff when idle.
#pragma once

#include <cstdint>
#include <memory>
#include <string>

#include "net/socket.h"

namespace trpc {

struct ShmConn;  // mapped segment + direction binding

// Creates a new segment (ring capacity per direction from the reloadable
// trpc_shm_ring_bytes flag, default 4MB) and maps it as the CLIENT side.
// Returns nullptr on failure; *name_out is the segment name to send to the
// server.
std::shared_ptr<ShmConn> shm_conn_create(std::string* name_out);
// Maps an existing segment as the SERVER side.
std::shared_ptr<ShmConn> shm_conn_open(const std::string& name);

// Builds the fd-less socket bound to `conn` and registers it with the
// poller.  on_readable/user_data as for Socket::Create.
int shm_socket_create(std::shared_ptr<ShmConn> conn,
                      void (*on_readable)(SocketId, void*), void* user_data,
                      SocketId* out);

// The handshake method name Servers auto-register.
inline const char* kShmConnectMethod = "__shm.Connect";

// Overrides the pid this side published in the segment (liveness is
// pid-based; tests use this to impersonate a crashed peer without a full
// client process).
void shm_conn_set_self_pid(ShmConn& c, int32_t pid);

}  // namespace trpc
