#include "net/socket.h"

#include <errno.h>
#include <fcntl.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include "base/logging.h"
#include "base/tls_cache.h"
#include "base/time.h"
#include "base/tsan.h"
#include "fiber/fiber.h"
#include "fiber/scheduler.h"
#include "net/fault.h"
#include "net/hotpath_stats.h"
#include "net/protocol.h"
#include "net/dispatcher.h"
#include "stat/timeline.h"

namespace trpc {

extern std::atomic<int64_t> g_socket_count;  // exposed via /connections

namespace {
using SocketPool = ResourcePool<Socket>;

constexpr uint64_t kRefUnit = 1;
inline uint32_t ver_of(uint64_t rv) { return static_cast<uint32_t>(rv >> 32); }
inline uint32_t ref_of(uint64_t rv) { return static_cast<uint32_t>(rv); }
inline uint64_t pack(uint32_t ver, uint32_t ref) {
  return (static_cast<uint64_t>(ver) << 32) | ref;
}
}  // namespace

int Socket::Create(const Options& opts, SocketId* out) {
  Socket* s = nullptr;
  const uint32_t slot = SocketPool::instance()->acquire(&s);
  if (s == nullptr) {
    return -1;
  }
  // Relaxed: the release store of ref_ver_ below is the single
  // publication point — nothing reads slot_/count before it lands.
  s->slot_.store(slot, std::memory_order_relaxed);
  s->reset_for_reuse(opts);
  const uint32_t ver =
      ver_of(s->ref_ver_.load(std::memory_order_relaxed)) + 1;  // → odd
  // One owner reference.
  s->ref_ver_.store(pack(ver, 1), std::memory_order_release);
  g_socket_count.fetch_add(1, std::memory_order_relaxed);
  *out = pack(ver, 0) | slot;  // ver<<32 | slot (ref bits reused as slot)
  if (s->fd_ >= 0) {
    make_nonblocking(s->fd_);
    if (EventDispatcher::for_fd(s->fd_)->add(s->fd_, *out) != 0) {
      LOG(Error) << "epoll add failed for fd " << s->fd_;
    }
  }
  return 0;
}

void Socket::reset_for_reuse(const Options& opts) {
  fd_ = opts.fd;
  mode_ = opts.mode;
  remote_ = opts.remote;
  // Every socket's transport rides behind the fault-injection decorator
  // (net/fault.h): one atomic load when inactive, schedule-driven chaos
  // when armed — runtime-togglable without touching live sockets.
  transport_ = fault_wrap(
      opts.transport != nullptr ? opts.transport : tcp_transport());
  transport_ctx_holder_ = opts.transport_ctx_holder;
  transport_ctx = transport_ctx_holder_.get();
  // Relaxed init stores through wq_head_ below: this slot is not yet
  // published (Address() can't hand out refs until Create()'s release
  // store of ref_ver_), so there is no concurrent reader to order with.
  failed_.store(false, std::memory_order_relaxed);
  // fd-less transports (shm/ICI) are born connected.
  connected_.store(opts.fd >= 0 ||
                       (opts.transport != nullptr && !opts.transport->fd_based()),
                   std::memory_order_relaxed);
  nevent_.store(0, std::memory_order_relaxed);
  on_readable_ = opts.on_readable;
  ctx_ = opts.ctx;
  read_buf_.clear();
  pinned_protocol = -1;
  user_data = opts.user_data;
  worker_tag = opts.worker_tag;
  wr_ev_.value.store(0, std::memory_order_relaxed);   // pre-publication
  writing_.store(false, std::memory_order_relaxed);    // pre-publication
  pending_.clear();
  pending_close_ = false;
  probe_stall_len = 0;
  read_block_hint = 0;
  parse_state.reset();
  parse_state_owner = nullptr;
  auth_ok.store(false, std::memory_order_relaxed);    // pre-publication
  wq_head_.store(nullptr, std::memory_order_relaxed);  // pre-publication
}

Socket* Socket::Address(SocketId id) {
  const uint32_t slot = static_cast<uint32_t>(id);
  const uint32_t ver = static_cast<uint32_t>(id >> 32);
  if ((ver & 1) == 0) {
    return nullptr;
  }
  Socket* s = SocketPool::instance()->at(slot);
  if (s == nullptr) {
    return nullptr;
  }
  // Acquire: pairs with Create()'s release publication so a ref taken
  // here sees the fully-initialized socket state behind it.
  uint64_t rv = s->ref_ver_.load(std::memory_order_acquire);
  while (true) {
    if (ver_of(rv) != ver) {
      return nullptr;
    }
    if (s->ref_ver_.compare_exchange_weak(rv, rv + kRefUnit,
                                          std::memory_order_acq_rel)) {
      return s;
    }
  }
}

bool Socket::Draining(SocketId id) {
  Socket* s = SocketPool::instance()->at(static_cast<uint32_t>(id));
  if (s == nullptr) {
    return false;
  }
  // Acquire: must observe SetFailed's generation bump, not a stale odd
  // version that would misreport a draining socket as live.
  const uint64_t rv = s->ref_ver_.load(std::memory_order_acquire);
  // SetFailed bumped the generation to id_ver+1 (even); refs drain to 0.
  return ver_of(rv) == static_cast<uint32_t>(id >> 32) + 1 && ref_of(rv) > 0;
}

SocketId Socket::id() const {
  // Acquire on the version (diagnostic readers must not see a stale
  // generation); slot_ is immutable after Create → relaxed.
  return pack(ver_of(ref_ver_.load(std::memory_order_acquire)), 0) |
         slot_.load(std::memory_order_relaxed);
}

std::string Socket::DumpAll(size_t max_rows) {
  return dump_pool_table<Socket>(
      "live sockets (id  fd  remote  mode  proto  state)\n", max_rows,
      [](uint32_t slot, Socket* s, std::string* line) {
        // Acquire: liveness must see the latest generation/refcount.
        const uint64_t rv = s->ref_ver_.load(std::memory_order_acquire);
        if ((ver_of(rv) & 1) == 0 || ref_of(rv) == 0) {
          return false;  // even generation = recycled/failed slot
        }
        if (line == nullptr) {
          return true;  // counted, rows already capped
        }
        // Hold a real reference while reading the non-atomic fields —
        // a bare snapshot would race reset_for_reuse on a recycled
        // slot.  Address re-validates the generation; a slot recycled
        // since the check above simply drops out of the table.
        SocketRef ref(Socket::Address(pack(ver_of(rv), 0) | slot));
        if (!ref) {
          return false;
        }
        const Protocol* p = protocol_at(ref->pinned_protocol);
        char buf[192];
        snprintf(buf, sizeof(buf), "%016llx  %3d  %s  %s  %s  %s\n",
                 static_cast<unsigned long long>(pack(ver_of(rv), slot)),
                 ref->fd(), endpoint2str(ref->remote()).c_str(),
                 ref->mode() == SocketMode::kTcp
                     ? "tcp"
                     : ref->mode() == SocketMode::kShm
                           ? "shm"
                           : ref->mode() == SocketMode::kIci ? "ici" : "?",
                 p != nullptr ? p->name : "-",
                 ref->connected() ? "connected" : "connecting");
        *line = buf;
        return true;
      });
}

std::string Socket::DumpHotState() {
  return dump_pool_table<Socket>(
      "socket hot state (fd  nevent  writing  queued  conn  failed)\n",
      200, [](uint32_t slot, Socket* s, std::string* line) {
        // Acquire: liveness must see the latest generation/refcount.
        const uint64_t rv = s->ref_ver_.load(std::memory_order_acquire);
        if ((ver_of(rv) & 1) == 0 || ref_of(rv) == 0) {
          return false;
        }
        if (line == nullptr) {
          return true;
        }
        SocketRef ref(Socket::Address(pack(ver_of(rv), 0) | slot));
        if (!ref) {
          return false;
        }
        // Atomics only — never walk the write chain (a concurrent drain
        // frees/reuses nodes) and never touch the read buffer (owned by
        // the read fiber).  queued=1 with writing=0 is the wedge
        // signature this view exists to catch.
        const bool queued =
            ref->wq_head_.load(std::memory_order_acquire) != nullptr;
        char buf[160];
        snprintf(buf, sizeof(buf),
                 "fd=%d nevent=%d writing=%d queued=%d conn=%d failed=%d\n",
                 ref->fd(), ref->nevent_.load(), (int)ref->writing_.load(),
                 (int)queued, (int)ref->connected(), (int)ref->Failed());
        *line = buf;
        return true;
      });
}

void Socket::Dereference() {
  const uint64_t prev = ref_ver_.fetch_sub(kRefUnit, std::memory_order_acq_rel);
  if (ref_of(prev) == 1) {
    // Last reference.  SetFailed already bumped the version to even, so
    // Address() cannot revive this slot — teardown is single-threaded here.
    if (fd_ >= 0) {
      EventDispatcher::for_fd(fd_)->remove(fd_);
      close(fd_);
      fd_ = -1;
    }
    drop_write_queue();
    pending_.clear();
    pending_close_ = false;
    read_buf_.clear();
    transport_ctx = nullptr;
    transport_ctx_holder_.reset();  // releases e.g. the shm mapping
    g_socket_count.fetch_sub(1, std::memory_order_relaxed);
    SocketPool::instance()->release(slot_.load(std::memory_order_relaxed));
  }
}

namespace {
std::atomic<void (*)(SocketId)> g_failure_observer{nullptr};
}  // namespace

void Socket::set_failure_observer(void (*cb)(SocketId)) {
  g_failure_observer.store(cb, std::memory_order_release);
}

void Socket::SetFailed(int err) {
  bool expect = false;
  if (!failed_.compare_exchange_strong(expect, true,
                                       std::memory_order_acq_rel)) {
    return;  // already failed
  }
  (void)err;
  // Captured BEFORE the version bump: this is the id every holder (stream
  // bindings, pending calls) stored; id() after the bump names the next
  // incarnation.
  const SocketId failed_id = id();
  // Bump the version to even FIRST: from this point Address() fails, so the
  // refcount can only drain — the teardown in Dereference can never race a
  // revival (the ordering socket.h:498's versioned-ref pattern exists for).
  uint64_t rv = ref_ver_.load(std::memory_order_relaxed);
  while (!ref_ver_.compare_exchange_weak(
      rv, pack(ver_of(rv) + 1, ref_of(rv)), std::memory_order_acq_rel)) {
  }
  // Wake any fiber parked on writability so it observes the failure.
  wr_ev_.value.fetch_add(1, std::memory_order_release);
  wr_ev_.wake_all();
  void (*observer)(SocketId) =
      g_failure_observer.load(std::memory_order_acquire);
  if (observer != nullptr) {
    observer(failed_id);
  }
  // Drop the owner reference (Create's).
  Dereference();
}

namespace {

// TLS WriteNode freelist.  One node is allocated per Socket::Write; at
// 100k+ qps that malloc/free pair plus the inner IOBuf refs-vector churn
// is measurable (r5 1KB-echo profile).  Nodes freed on one thread serve
// later Writes on the same thread; cross-thread imbalance just degrades
// to plain malloc.
struct WriteNodeCacheTag {};

void drain_write_node(void*& n) { Socket::destroy_write_node_opaque(n); }

std::vector<void*>* tls_write_node_cache() {
  return TlsFreeCache<void*, WriteNodeCacheTag>::get(&drain_write_node);
}

constexpr size_t kMaxCachedWriteNodes = 64;
// Byte cap on what the freelist may pin: a cached node's cleared IOBuf
// still owns its refs-vector capacity (a 64MB write sliced into 16KB
// blocks leaves a ~64KB vector), so 64 nodes could silently hold MBs per
// thread.  Nodes over the per-thread budget get their storage shrunk
// before caching.
constexpr size_t kMaxCachedWriteBytes = 256 * 1024;

// Refs-vector capacity bytes currently pinned by this thread's cache.
thread_local size_t tls_write_node_cache_bytes = 0;

}  // namespace

Socket::WriteNode* Socket::alloc_write_node(IOBuf&& data, bool close_after) {
  std::vector<void*>* cache = tls_write_node_cache();
  if (cache != nullptr && !cache->empty()) {
    auto* n = static_cast<WriteNode*>(cache->back());
    cache->pop_back();
    const size_t held = n->data.ref_capacity_bytes();
    tls_write_node_cache_bytes -=
        std::min(tls_write_node_cache_bytes, held);
    n->data = std::move(data);
    n->close_after = close_after;
    n->next = nullptr;
    return n;
  }
  return new WriteNode{std::move(data), close_after, nullptr};
}

void Socket::free_write_node(WriteNode* n) {
  std::vector<void*>* cache = tls_write_node_cache();
  if (cache != nullptr && cache->size() < kMaxCachedWriteNodes) {
    n->data.clear();  // release block refs NOW, not at reuse time
    size_t held = n->data.ref_capacity_bytes();
    if (tls_write_node_cache_bytes + held > kMaxCachedWriteBytes) {
      n->data.shrink_storage();  // over budget: drop the vector heap too
      held = n->data.ref_capacity_bytes();
    }
    tls_write_node_cache_bytes += held;
    cache->push_back(n);
    return;
  }
  delete n;
}

void Socket::destroy_write_node_opaque(void* n) {
  delete static_cast<WriteNode*>(n);
}

void Socket::drop_write_queue() {
  // Acquire: claims the chain — must see every producer's node payload
  // (their CAS push released it into wq_head_).
  WriteNode* n = wq_head_.exchange(nullptr, std::memory_order_acquire);
  while (n != nullptr) {
    WriteNode* next = n->next;
    free_write_node(n);
    n = next;
  }
}

// ---- input path ---------------------------------------------------------

void Socket::on_input_event() {
  if (nevent_.fetch_add(1, std::memory_order_acq_rel) == 0 &&
      on_readable_ != nullptr) {
    // Hand off to a fiber carrying the versioned id (the fiber re-Addresses).
    // The tag pin routes a tagged server's whole pipeline (this read fiber,
    // and by inheritance its handler + KeepWrite fibers) to its group.
    fiber_start(nullptr, &Socket::read_fiber_thunk,
                reinterpret_cast<void*>(id()),
                kFiberUrgent | fiber_tag_flags(worker_tag));
  }
}

void Socket::read_fiber_thunk(void* arg) {
  const SocketId id = reinterpret_cast<uint64_t>(arg);
  Socket* s = Socket::Address(id);
  if (s == nullptr) {
    return;
  }
  // Close the connect→first-readable kernel edge (see ensure_connected).
  TRPC_TSAN_ACQUIRE(s);
  while (true) {
    const int seen = s->nevent_.load(std::memory_order_acquire);
    s->on_readable_(id, s->ctx_);
    int expect = seen;
    if (s->nevent_.compare_exchange_strong(expect, 0,
                                           std::memory_order_acq_rel)) {
      break;
    }
  }
  s->Dereference();
}

void Socket::on_output_event() {
  wr_ev_.value.fetch_add(1, std::memory_order_release);
  wr_ev_.wake_all();
}

int Socket::wait_writable(uint32_t snap, int64_t deadline_us) {
  const int rc = wr_ev_.wait(snap, deadline_us);
  return rc == ETIMEDOUT ? rc : 0;
}

// ---- connect ------------------------------------------------------------

int Socket::ensure_connected() {
  if (connected_.load(std::memory_order_acquire)) {
    return 0;
  }
  if (fd_ < 0) {
    const bool un = remote_.is_unix();
    const int fd =
        ::socket(un ? AF_UNIX : AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) {
      return -1;
    }
    if (!un) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    }
    fd_ = fd;
    if (EventDispatcher::for_fd(fd_)->add(fd_, id()) != 0) {
      return -1;
    }
  }
  const int rc = transport_->connect(this);
  if (rc == 0) {
    // Kernel-mediated edge TSan cannot model: the read fiber's first
    // readv is ordered after connect() by the kernel (a readable event
    // needs delivered bytes, which need an established connection), but
    // TSan only draws epoll_ctl→epoll_wait.  Pairs with the acquire at
    // read_fiber_thunk entry; replaces the old blanket
    // race:trpc::Socket::ensure_connected suppression (ISSUE 7).
    TRPC_TSAN_RELEASE(this);
    connected_.store(true, std::memory_order_release);
  }
  return rc;
}

// ---- wait-free write path ----------------------------------------------
//
// One MPSC Treiber chain + a writer-role flag.  The producer that pushes
// onto an EMPTY chain claims the role; everyone else just enqueues.  The
// role-holder drains the WHOLE reversed chain into pending_ (one
// coalesced buffer → one writev/doorbell per drain) and, on the fast
// path, flushes it INLINE on the caller — no KeepWrite fiber, no
// ParkingLot signal, no context switch.  Only EAGAIN leftovers, lazy
// connects and close_after teardown fall back to the KeepWrite fiber.
//
// The role handoff is the delicate part: the exit sequence
// [writing_=false; re-check head] races the producer sequence
// [push head; try-claim writing_].  Both sides are seq_cst — with
// anything weaker the StoreLoad pairs can miss each other (x86 reorders
// a release-store past a later acquire-load of a DIFFERENT word), each
// side concludes the other owns the drain, and the queued node wedges
// the connection forever.  That exact lost-wakeup shipped in the seed
// and capped the 1KB bench at a few hundred QPS per wedge window.

int Socket::Write(IOBuf&& data, bool close_after) {
  if (Failed()) {
    return -1;
  }
  WriteNode* node = alloc_write_node(std::move(data), close_after);
  // Relaxed initial read: the CAS below (seq_cst, see the role-handoff
  // comment above Write) is what orders the push; a stale head only
  // costs one CAS retry.
  WriteNode* old = wq_head_.load(std::memory_order_relaxed);
  do {
    node->next = old;
  } while (!wq_head_.compare_exchange_weak(old, node,
                                           std::memory_order_seq_cst,
                                           // failure: retry re-reads head
                                           std::memory_order_relaxed));
  if (old != nullptr) {
    return 0;  // an active writer owns the drain
  }
  bool expect = false;
  if (!writing_.compare_exchange_strong(expect, true,
                                        std::memory_order_seq_cst)) {
    return 0;  // the exiting writer's re-check adopts our node
  }
  // We hold the writer role.  Fast path: flush inline on this thread.
  // A true return covers graceful close_after teardown and transport
  // errors too — like the KeepWrite path, those surface through the
  // socket's failed state, not through this (already-accepted) Write.
  if (try_inline_write()) {
    return 0;
  }
  // Leftovers (EAGAIN / not yet connected / bounded rounds exhausted):
  // continue in a KeepWrite fiber that inherits pending_ with the role.
  // Take a strong ref for the fiber's lifetime.
  Socket* self = Socket::Address(id());
  if (self == nullptr) {
    // Failed under us; nothing will ever drain — purge and bail.
    abort_writer(ECONNRESET);
    return -1;
  }
  if (timeline::enabled()) {
    // The wait-free fast path ends here: the role (and any EAGAIN
    // leftovers) hand off to a KeepWrite fiber.
    timeline::record(timeline::kWriterHandoff, id(), 0);
  }
  fiber_start(nullptr, &Socket::keep_write_thunk, self,
              kFiberUrgent | fiber_tag_flags(worker_tag));
  return 0;
}

size_t Socket::drain_queue_into_pending() {
  // Acquire: claims the chain — pairs with producers' CAS release so
  // the drain sees every node's IOBuf payload.
  WriteNode* chain = wq_head_.exchange(nullptr, std::memory_order_acquire);
  if (chain == nullptr) {
    return 0;
  }
  WriteNode* fifo = nullptr;
  while (chain != nullptr) {  // LIFO chain → FIFO
    WriteNode* next = chain->next;
    chain->next = fifo;
    fifo = chain;
    chain = next;
  }
  size_t n = 0;
  while (fifo != nullptr) {
    pending_.append(std::move(fifo->data));
    pending_close_ |= fifo->close_after;
    WriteNode* done = fifo;
    fifo = fifo->next;
    free_write_node(done);
    ++n;
  }
  HotPathVars& hv = hotpath_vars();
  hv.write_coalesce_drains << 1;
  hv.write_coalesce_nodes << static_cast<int64_t>(n);
  hv.write_coalesce_max << static_cast<int64_t>(n);
  if (hotpath_sample16()) {
    hv.write_coalesce_batch << static_cast<int64_t>(n);
  }
  if (timeline::enabled() && n > 1) {
    // Coalesce depth > 1 is the interesting signal (a writer absorbed
    // concurrent producers); depth-1 drains are every uncontended write.
    timeline::record(timeline::kWriteCoalesce, id(), n);
  }
  return n;
}

bool Socket::release_writer_role() {
  writing_.store(false, std::memory_order_seq_cst);
  if (wq_head_.load(std::memory_order_seq_cst) != nullptr) {
    bool expect = false;
    if (writing_.compare_exchange_strong(expect, true,
                                         std::memory_order_seq_cst)) {
      return false;  // adopted a late node; keep draining
    }
  }
  return true;
}

void Socket::abort_writer(int err) {
  SetFailed(err);
  pending_.clear();
  pending_close_ = false;
  drop_write_queue();
  // writing_ stays true: the socket is failed, so no producer will ever
  // need the role again; reset_for_reuse re-arms it with the slot.
}

bool Socket::try_inline_write() {
  // Lazy connects park the calling fiber — never inline-eligible.
  if (!connected_.load(std::memory_order_acquire)) {
    return false;
  }
  HotPathVars& hv = hotpath_vars();
  hv.inline_write_attempts << 1;
  uint64_t flushed = 0;  // bytes cut inline (the write_flush event arg)
  // Bounded rounds: an inline writer should flush what WAS queued, not
  // become an unwitting forever-writer for every concurrent producer.
  for (int round = 0; round < 4; ++round) {
    drain_queue_into_pending();
    if (pending_.empty()) {
      if (pending_close_) {
        // An empty-payload close_after batch (everything before it
        // already flushed): honor the close now — releasing the role
        // here would drop the close AND leave the latch armed for an
        // unrelated later batch.
        drop_write_queue();
        SetFailed(ESHUTDOWN);
        return true;
      }
      if (release_writer_role()) {
        hv.inline_write_hits << 1;
        if (timeline::enabled() && flushed > 0) {
          timeline::record(timeline::kWriteFlush, id(), flushed);
        }
        return true;
      }
      continue;  // late node adopted with the role
    }
    while (!pending_.empty()) {
      const ssize_t rc = transport_->cut_from_iobuf(this, &pending_);
      if (rc < 0) {
        transport_->flush(this);
        abort_writer(errno);
        return true;  // role retired with the socket
      }
      if (rc == 0) {  // EAGAIN: the KeepWrite fiber parks on the edge
        transport_->flush(this);
        return false;
      }
      flushed += static_cast<uint64_t>(rc);
    }
    transport_->flush(this);
    if (pending_close_) {
      // Fully flushed Connection:-close batch — graceful close here;
      // anything enqueued after it is void by contract.
      drop_write_queue();
      SetFailed(ESHUTDOWN);
      return true;
    }
  }
  // Rounds exhausted with the queue still live: hand off.
  return false;
}

void Socket::keep_write_thunk(void* arg) {
  Socket* s = static_cast<Socket*>(arg);
  s->keep_write();
  s->Dereference();
}

void Socket::keep_write() {
  while (true) {
    // Drain newly queued nodes on top of any inline-path leftovers.
    drain_queue_into_pending();
    if (pending_.empty()) {
      if (pending_close_) {  // empty-payload close_after: honor it now
        drop_write_queue();
        SetFailed(ESHUTDOWN);
        return;
      }
      if (release_writer_role()) {
        return;
      }
      continue;
    }
    if (ensure_connected() != 0) {
      abort_writer(errno);
      return;
    }
    while (!pending_.empty()) {
      const uint32_t snap = writable_snap();
      const ssize_t rc = transport_->cut_from_iobuf(this, &pending_);
      if (rc < 0) {
        transport_->flush(this);
        abort_writer(errno);
        return;
      }
      if (rc == 0) {  // EAGAIN: park until the writable edge
        // Publish staged descriptors BEFORE parking: a ring that only
        // learns of them at the next flush would never drain, and the
        // writable edge this fiber waits for would never come.
        transport_->flush(this);
        if (Failed()) {
          abort_writer(ECONNRESET);
          return;
        }
        // Sliced wait: fd-less transports have no HUP edge, so a dead peer
        // is only noticed through Failed() re-checks.
        wait_writable(snap, monotonic_time_us() + 1000000);
      }
    }
    transport_->flush(this);
    if (pending_close_) {
      // This batch carried a Connection: close response and it has fully
      // flushed — graceful close (anything enqueued after it is void).
      drop_write_queue();
      SetFailed(ESHUTDOWN);
      return;
    }
  }
}

// ---- misc ---------------------------------------------------------------

void make_nonblocking(int fd) {
  const int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

}  // namespace trpc
