// Socket — fd + lifecycle + wait-free write queue behind a versioned handle.
//
// Parity: brpc::Socket (/root/reference/src/brpc/socket.h:498-509 SetFailed/
// Address wait-free strong refs; socket.cpp:1624-1890 the MPSC write path
// with the KeepWrite continuation; socket.cpp:2254 input-event dedup).
// Re-designed: version+refcount packed in one atomic64; the write queue is a
// Treiber/flag MPSC (ExecutionQueue-style) instead of the reference's
// exchanged linked list; the first write is attempted inline, leftovers
// continue in a KeepWrite fiber parked on the writable-edge Event.
#pragma once

#include <atomic>
#include <functional>
#include <memory>

#include "base/endpoint.h"
#include "base/iobuf.h"
#include "base/resource_pool.h"
#include "fiber/event.h"
#include "net/transport.h"

namespace trpc {

using SocketId = uint64_t;  // version<<32 | pool slot

class Socket {
 public:
  struct Options {
    int fd = -1;                     // accepted/listen fd, or -1 to connect
    EndPoint remote;
    SocketMode mode = SocketMode::kTcp;
    // Fiber-spawned on each readable edge (versioned id passed through).
    void (*on_readable)(SocketId id, void* ctx) = nullptr;
    void* ctx = nullptr;
    // Owner context (Server*/Channel*); set BEFORE the fd is registered
    // with the dispatcher so the first event can never observe null.
    void* user_data = nullptr;
    // Non-TCP transports (shm rings, ICI): the transport instance and its
    // per-connection context.  The holder keeps the context (e.g. a mapped
    // segment) alive exactly as long as the socket generation.
    Transport* transport = nullptr;
    std::shared_ptr<void> transport_ctx_holder;
    // Worker group for this connection's fibers (read fiber, and via
    // inheritance the handler/KeepWrite fibers).  Server.h:280 bthread_tag
    // parity: a server pins its connections to its tag's worker group.
    uint8_t worker_tag = 0;
  };

  // Creates a socket with one owner reference; registers with the
  // dispatcher when fd >= 0.  Returns 0 and the versioned id.
  static int Create(const Options& opts, SocketId* out);
  // Wait-free strong ref; nullptr if the id is stale or failed.
  static Socket* Address(SocketId id);
  void Dereference();
  // True while a failed socket of this id's generation still has strong
  // references draining (holders may still be inside request entry paths).
  static bool Draining(SocketId id);

  // Marks failed: future Address() fails, fd closed once refs drain, the
  // owner reference is dropped, waiters woken.
  void SetFailed(int err);
  // Single-slot observer invoked once per socket failure (from whatever
  // thread called SetFailed), with the PRE-failure id — the generation
  // holders stored before the version bump invalidated it.  The stream
  // plane registers here so logical streams bound to a dead connection
  // close promptly instead of waiting out a write probe (net/stream.cc).
  // The callback must not park and must tolerate ids it never saw.
  static void set_failure_observer(void (*cb)(SocketId id));
  // Acquire on both state bits: an observer acting on failed/connected
  // (e.g. skipping ensure_connected) must also see the writes SetFailed
  // or the connect path published before flipping them.
  bool Failed() const {
    return failed_.load(std::memory_order_acquire);
  }
  bool connected() const {
    // Acquire: see Failed() — same publication pairing.
    return connected_.load(std::memory_order_acquire);
  }

  // Appends data to the wait-free write queue; the queue guarantees FIFO
  // per socket and writes happen in a KeepWrite fiber (first try inline).
  // Returns 0 if queued/sent, -1 if the socket is failed.
  // close_after (Connection: close semantics) rides the write NODE — the
  // socket fails itself only after this write (and everything queued with
  // it) has flushed, so a racing drain of earlier responses can never
  // close before this one leaves.
  int Write(IOBuf&& data, bool close_after = false);

  // Text table of every live socket (/sockets builtin; reference:
  // builtin/sockets_service.cpp printing Socket::DebugString).
  static std::string DumpAll(size_t max_rows);
  // One line per live socket of hot-path state (queued-write flag, writer
  // role, pending input events) — wedge forensics, atomics only.
  static std::string DumpHotState();

  int fd() const { return fd_; }
  SocketMode mode() const { return mode_; }
  SocketId id() const;
  const EndPoint& remote() const { return remote_; }
  Transport* transport() const { return transport_; }
  IOBuf& read_buf() { return read_buf_; }
  // Protocol index pinned after first successful parse (-1 = unknown).
  int pinned_protocol = -1;
  // Set once the server verified this connection's kAuth credential
  // (auth.h); requests on unverified sockets are rejected when the
  // server has an authenticator installed.
  std::atomic<bool> auth_ok{false};
  void* user_data = nullptr;  // Server*/Channel* context, set by owner
  void* transport_ctx = nullptr;  // per-connection transport state
  uint8_t worker_tag = 0;  // worker group for this connection's fibers
  // Protocol-probe memo: buffer length at the last inconclusive probe
  // sweep (every protocol said NotEnoughData/TryOther).  The messenger
  // skips re-probing until more bytes than this have arrived — a partial
  // prefix no longer pays a full multi-protocol probe per read event.
  // 0 = no stalled probe.  Read-fiber-owned; reset with the socket.
  size_t probe_stall_len = 0;
  // Bulk-read hint: bytes the current (partially buffered) frame still
  // needs, published by the parser on NotEnoughData.  The messenger and
  // transport size their next reads/blocks from it, turning a 64MB body
  // into a few large-iovec readvs instead of thousands of 8KB ones.
  // 0 = no known remainder.  Read-fiber-owned; reset with the socket.
  size_t read_block_hint = 0;
  // Incremental parser state for protocols that need it (HTTP chunked
  // bodies resume scanning; h2 connection state).  Owned by the read
  // fiber; cleared on socket reuse.  `parse_state_owner` tags WHICH
  // protocol the state belongs to (a unique static address per protocol):
  // during protocol probing several parsers see the same socket, and one
  // that consumed a prefix (h2's preface) must reclaim its state on the
  // next round instead of misreading another protocol's.
  std::shared_ptr<void> parse_state;
  const void* parse_state_owner = nullptr;

  // -- dispatcher integration (internal) -------------------------------
  static void destroy_write_node_opaque(void* n);  // TLS cache teardown
  void on_input_event();    // readable edge (any thread)
  void on_output_event();   // writable edge (any thread)
  int wait_writable(uint32_t snap, int64_t deadline_us);
  uint32_t writable_snap() const {
    return const_cast<Event&>(wr_ev_).value.load(std::memory_order_acquire);
  }
  int ensure_connected();   // lazy non-blocking connect (parks fiber)

 private:
  friend class ResourcePool<Socket>;
  struct WriteNode {
    IOBuf data;
    bool close_after = false;
    WriteNode* next = nullptr;
  };

  static void read_fiber_thunk(void* arg);
  static void keep_write_thunk(void* arg);
  void keep_write();
  // Inline fast path: called by Write with the writer role held.  Returns
  // true when the queue fully flushed (or the socket failed) and the role
  // is done with; false when bytes remain and a KeepWrite fiber must take
  // over (role stays held).
  bool try_inline_write();
  // Moves the whole MPSC chain (reversed to FIFO) into pending_; returns
  // the node count absorbed.  Writer-role holder only.
  size_t drain_queue_into_pending();
  // Releases the writer role with the seq_cst handoff that closes the
  // producer/exit Dekker race; returns false when new nodes arrived and
  // the role was re-acquired (caller must keep draining).
  bool release_writer_role();
  // Failure/teardown of an active writer: fail the socket, purge pending_
  // and the queue.  The writer role is intentionally left held — the
  // socket is dead, reset_for_reuse re-arms the flag.
  void abort_writer(int err);
  void reset_for_reuse(const Options& opts);
  void drop_write_queue();
  // TLS-cached WriteNode alloc/free (one node per Write on the hot path;
  // pooling also retains the inner IOBuf's refs vector capacity).
  static WriteNode* alloc_write_node(IOBuf&& data, bool close_after);
  static void free_write_node(WriteNode* n);

  std::atomic<uint64_t> ref_ver_{0};  // version<<32 | refcount
  std::atomic<uint32_t> slot_{0};
  int fd_ = -1;
  SocketMode mode_ = SocketMode::kTcp;
  EndPoint remote_;
  Transport* transport_ = nullptr;
  std::atomic<bool> failed_{false};
  std::atomic<bool> connected_{false};
  std::atomic<int> nevent_{0};
  void (*on_readable_)(SocketId, void*) = nullptr;
  void* ctx_ = nullptr;
  IOBuf read_buf_;
  std::shared_ptr<void> transport_ctx_holder_;
  Event wr_ev_;  // writable-edge counter
  // MPSC write queue.
  std::atomic<WriteNode*> wq_head_{nullptr};
  std::atomic<bool> writing_{false};
  // Coalesced unwritten bytes + deferred close flag, owned by whoever
  // holds the writer role (writing_): the inline fast path hands both to
  // the KeepWrite fiber through here on EAGAIN.
  IOBuf pending_;
  bool pending_close_ = false;
};

void make_nonblocking(int fd);

// RAII strong reference.
class SocketRef {
 public:
  SocketRef() = default;
  explicit SocketRef(Socket* s) : s_(s) {}
  SocketRef(SocketRef&& o) noexcept : s_(o.s_) { o.s_ = nullptr; }
  ~SocketRef() {
    if (s_ != nullptr) {
      s_->Dereference();
    }
  }
  Socket* operator->() const { return s_; }
  Socket* get() const { return s_; }
  explicit operator bool() const { return s_ != nullptr; }

 private:
  Socket* s_ = nullptr;
};

}  // namespace trpc
