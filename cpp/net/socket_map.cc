#include "net/socket_map.h"

#include "net/messenger.h"

namespace trpc {

bool parse_connection_type(const std::string& s, ConnectionType* out) {
  if (s.empty() || s == "single") {
    *out = ConnectionType::kSingle;
    return true;
  }
  if (s == "pooled") {
    *out = ConnectionType::kPooled;
    return true;
  }
  if (s == "short") {
    *out = ConnectionType::kShort;
    return true;
  }
  return false;
}

SocketMap* SocketMap::instance() {
  static SocketMap* m = new SocketMap();  // leaked registry
  return m;
}

int SocketMap::create_socket(const EndPoint& ep, SocketId* out) {
  Socket::Options sopts;
  sopts.fd = -1;  // lazy connect in the write fiber
  sopts.remote = ep;
  sopts.on_readable = &messenger_on_readable;
  return Socket::Create(sopts, out);
}

int SocketMap::take_pooled(const EndPoint& ep, const Authenticator* auth,
                           SocketId* out, bool* fresh) {
  if (fresh != nullptr) {
    *fresh = false;
  }
  {
    std::lock_guard<std::mutex> g(mu_);
    auto it = pools_.find(PoolKey{ep, auth});
    while (it != pools_.end() && !it->second.empty()) {
      const SocketId id = it->second.back();
      it->second.pop_back();
      Socket* s = Socket::Address(id);
      if (s != nullptr) {
        if (!s->Failed()) {
          s->Dereference();
          *out = id;
          return 0;
        }
        s->Dereference();
      }
      // Stale/failed: drop and keep scanning.
    }
  }
  if (fresh != nullptr) {
    *fresh = true;
  }
  return create_socket(ep, out);
}

void SocketMap::give_back(const EndPoint& ep, const Authenticator* auth,
                          SocketId id) {
  Socket* s = Socket::Address(id);
  if (s == nullptr) {
    return;  // died in flight; nothing to pool
  }
  const bool healthy = !s->Failed();
  s->Dereference();
  if (!healthy) {
    return;
  }
  std::lock_guard<std::mutex> g(mu_);
  pools_[PoolKey{ep, auth}].push_back(id);
}

int SocketMap::create_short(const EndPoint& ep, SocketId* out) {
  return create_socket(ep, out);
}

size_t SocketMap::pooled_count(const EndPoint& ep,
                               const Authenticator* auth) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = pools_.find(PoolKey{ep, auth});
  return it == pools_.end() ? 0 : it->second.size();
}

}  // namespace trpc
