// SocketMap — shared pool of client connections per endpoint.
//
// Parity: brpc's SocketMap + connection-type matrix
// (/root/reference/src/brpc/socket_map.h:80-114; socket.h:611-627
// GetPooledSocket/GetShortSocket; ChannelOptions.connection_type).
// Semantics match the reference:
//   single — one shared connection per Channel, many in-flight calls
//            multiplexed by correlation id (the default).
//   pooled — each call EXCLUSIVELY owns one connection for its duration;
//            returned to a per-endpoint free list afterwards.  More fds,
//            but no head-of-line blocking between large payloads — the
//            reference's 2.3 GB/s headline configuration.
//   short  — a fresh connection per call, closed on completion.
#pragma once

#include <cstdint>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "base/endpoint.h"
#include "net/socket.h"

namespace trpc {

class Authenticator;

enum class ConnectionType : uint8_t {
  kSingle = 0,
  kPooled = 1,
  kShort = 2,
};

// "", "single", "pooled", "short" (adaptive_connection_type.h parity);
// returns false on an unknown spec.
bool parse_connection_type(const std::string& s, ConnectionType* out);

class SocketMap {
 public:
  static SocketMap* instance();

  // Exclusive pooled connection to ep: reuses a healthy free one or
  // creates a new one.  Returns 0 and a socket the caller owns until
  // give_back.
  // The pool key includes the channel's authenticator: a connection
  // authenticated under one identity must never serve another (the
  // reference keys SocketMap by auth for the same reason).
  int take_pooled(const EndPoint& ep, const Authenticator* auth,
                  SocketId* out, bool* fresh = nullptr);
  // Returns the connection for reuse (failed ones are dropped).
  void give_back(const EndPoint& ep, const Authenticator* auth, SocketId id);
  // Fresh one-shot connection; the caller fails it after the call.
  int create_short(const EndPoint& ep, SocketId* out);

  // Free connections currently pooled for ep (tests/introspection).
  size_t pooled_count(const EndPoint& ep, const Authenticator* auth = nullptr);

 private:
  struct PoolKey {
    EndPoint ep;
    const Authenticator* auth;
    bool operator==(const PoolKey& o) const {
      return ep == o.ep && auth == o.auth;
    }
  };
  struct PoolKeyHash {
    size_t operator()(const PoolKey& k) const {
      return EndPointHash()(k.ep) ^
             std::hash<const void*>()(k.auth);
    }
  };
  int create_socket(const EndPoint& ep, SocketId* out);

  std::mutex mu_;
  std::unordered_map<PoolKey, std::vector<SocketId>, PoolKeyHash> pools_;
};

}  // namespace trpc
