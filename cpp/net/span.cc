#include "net/span.h"

#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <mutex>

#include "base/flags.h"
#include "base/json.h"
#include "base/rand.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "fiber/scheduler.h"
#include "stat/timeline.h"

namespace trpc {

namespace {

constexpr size_t kDefaultRingSize = 4096;

Flag* rpcz_ring_size_flag();

Flag* rpcz_flag() {
  static Flag* f = [] {
    // Register the companion ring-size knob alongside, so any process
    // that can flip rpcz_enabled (every server's /flags) can also widen
    // the span window without a separate lazy touch.
    rpcz_ring_size_flag();
    return Flag::define_bool(
        "rpcz_enabled", false,
        "collect per-RPC spans, browsable via /rpcz "
        "(reference: -enable_rpcz)");
  }();
  return f;
}

// Leaked ring of finished spans (runtime registries outlive statics).
std::mutex& ring_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
struct SpanRing {
  std::vector<Span> slots{kDefaultRingSize};
  size_t next = 0;
  size_t count = 0;
};
SpanRing& ring() {
  static SpanRing* r = new SpanRing();
  return *r;
}

// Rebuilds the ring at `cap` slots, keeping the newest spans that fit
// (oldest-of-kept lands at slot 0 so the walk order is unchanged).
void resize_ring(size_t cap) {
  std::lock_guard<std::mutex> g(ring_mu());
  SpanRing& r = ring();
  if (cap == r.slots.size()) {
    return;
  }
  std::vector<Span> fresh(cap);
  const size_t keep = r.count < cap ? r.count : cap;
  for (size_t i = 0; i < keep; ++i) {
    const size_t idx =
        (r.next + r.slots.size() - keep + i) % r.slots.size();
    fresh[i] = std::move(r.slots[idx]);
  }
  r.slots = std::move(fresh);
  r.count = keep;
  r.next = keep % cap;
}

// Reloadable ring capacity: a busy server at the default 4096 evicts a
// hunted span in well under a second; /flags/trpc_rpcz_ring_size lets an
// operator widen the window live without a restart.
Flag* rpcz_ring_size_flag() {
  static Flag* f = [] {
    Flag* fl = Flag::define_int64(
        "trpc_rpcz_ring_size", kDefaultRingSize,
        "rpcz span ring capacity (spans kept for /rpcz); reloadable, "
        "16..1048576, newest spans survive a resize");
    fl->set_validator([](const std::string& v) {
      if (v.empty()) {
        return false;
      }
      char* end = nullptr;
      const long n = strtol(v.c_str(), &end, 10);
      return end != nullptr && *end == '\0' && n >= 16 && n <= (1 << 20);
    });
    fl->on_update([](Flag* f2) {
      resize_ring(static_cast<size_t>(f2->int64_value()));
    });
    return fl;
  }();
  return f;
}

// Ambient (fiber-local) trace context, stored by VALUE directly on the
// FiberMeta (two relaxed-atomic u64 fields — no per-RPC allocation, no
// destructor, and the Span object may die before a child fiber reads
// the context).  Moved off FLS slots in ISSUE 9: the timeline recorder's
// scheduler-side events (ready/wake, emitted on the WAKER's thread) must
// read the TARGET fiber's context, which thread-keyed fls_get cannot
// serve.

// Off-fiber fallback: ctypes callers (Python threads) have no fiber
// context, but must still be able to install a trace around their sync
// calls — trpc_trace_set / trpc_span_start land here.
thread_local uint64_t tls_ambient_trace = 0;
thread_local uint64_t tls_ambient_span = 0;

// Register the ambient context as the flight recorder's context reader
// (stat/timeline.h): every timeline event carries the trace/span of the
// fiber (or pthread) that emitted it.  Safe at static init — the hook
// slot is a constant-initialized atomic.
[[maybe_unused]] const bool g_timeline_ctx_hook = [] {
  timeline::set_context_reader(&get_ambient_trace);
  return true;
}();

}  // namespace

bool rpcz_enabled() { return rpcz_flag()->bool_value(); }

uint64_t new_span_id() {
  uint64_t id;
  do {
    id = fast_rand();
  } while (id == 0);
  return id;
}

Span* start_span(bool server_side, const std::string& method,
                 uint64_t trace_id, uint64_t parent_span_id) {
  auto* s = new Span();
  s->server_side = server_side;
  s->method = method;
  s->fid = fiber_self();  // exact span↔timeline join key (0 off-fiber)
  s->start_us = monotonic_time_us();
  s->span_id = new_span_id();
  if (trace_id != 0) {
    s->trace_id = trace_id;
    s->parent_span_id = parent_span_id;
  } else {
    uint64_t amb_trace = 0;
    uint64_t amb_span = 0;
    get_ambient_trace(&amb_trace, &amb_span);
    if (amb_trace != 0) {
      s->trace_id = amb_trace;
      s->parent_span_id = amb_span;
    } else {
      s->trace_id = new_span_id();  // fresh trace rooted here
    }
  }
  return s;
}

void span_annotate(Span* s, const std::string& text) {
  if (s != nullptr) {
    s->annotations.emplace_back(monotonic_time_us(), text);
  }
}

void submit_span(Span* s, int32_t error_code) {
  if (s == nullptr) {
    return;
  }
  s->end_us = monotonic_time_us();
  s->error_code = error_code;
  {
    std::lock_guard<std::mutex> g(ring_mu());
    SpanRing& r = ring();
    const size_t cap = r.slots.size();
    r.slots[r.next] = std::move(*s);
    r.next = (r.next + 1) % cap;
    if (r.count < cap) {
      ++r.count;
    }
  }
  delete s;
}

void set_ambient_span(const Span* s) {
  set_ambient_trace(s != nullptr ? s->trace_id : 0,
                    s != nullptr ? s->span_id : 0);
}

void set_ambient_trace(uint64_t trace_id, uint64_t span_id) {
  Worker* w = tls_worker;
  if (w != nullptr && w->current() != nullptr) {
    // Relaxed: same-fiber reads are program-ordered (migration rides the
    // scheduler's queue handoff); cross-thread timeline reads tolerate a
    // stale snapshot (see scheduler.h).
    w->current()->ambient_trace.store(trace_id, std::memory_order_relaxed);
    w->current()->ambient_span.store(span_id, std::memory_order_relaxed);
  } else {
    tls_ambient_trace = trace_id;
    tls_ambient_span = span_id;
  }
}

void get_ambient_trace(uint64_t* trace_id, uint64_t* span_id) {
  Worker* w = tls_worker;
  if (w != nullptr && w->current() != nullptr) {
    // Relaxed: own-fiber context read (see set_ambient_trace).
    *trace_id = w->current()->ambient_trace.load(std::memory_order_relaxed);
    *span_id = w->current()->ambient_span.load(std::memory_order_relaxed);
  } else {
    *trace_id = tls_ambient_trace;
    *span_id = tls_ambient_span;
  }
}

std::vector<Span> recent_spans(size_t limit, uint64_t trace_id) {
  std::vector<Span> out;
  std::lock_guard<std::mutex> g(ring_mu());
  const SpanRing& r = ring();
  const size_t cap = r.slots.size();
  for (size_t i = 0; i < r.count && out.size() < limit; ++i) {
    // Newest first: walk backward from next-1.
    const size_t idx = (r.next + cap - 1 - i) % cap;
    const Span& s = r.slots[idx];
    if (trace_id == 0 || s.trace_id == trace_id) {
      out.push_back(s);
    }
  }
  return out;
}

size_t rpcz_ring_capacity() {
  rpcz_ring_size_flag();  // ensure registration
  std::lock_guard<std::mutex> g(ring_mu());
  return ring().slots.size();
}

namespace {
std::string hex_id(uint64_t id) {
  char buf[20];
  snprintf(buf, sizeof(buf), "%016llx",
           static_cast<unsigned long long>(id));
  return buf;
}
}  // namespace

std::string rpcz_dump_json(size_t limit, uint64_t trace_id) {
  Json root = Json::object();
  root.set("pid", Json::number(getpid()));
  // The mono/wall pair is read as close together as possible so the
  // stitcher's monotonic→wall mapping error is bounded by this gap.
  root.set("now_mono_us", Json::number(
      static_cast<double>(monotonic_time_us())));
  root.set("now_wall_us", Json::number(
      static_cast<double>(realtime_us())));
  Json spans = Json::array();
  for (const Span& s : recent_spans(limit, trace_id)) {
    Json j = Json::object();
    j.set("trace_id", Json::str(hex_id(s.trace_id)));
    j.set("span_id", Json::str(hex_id(s.span_id)));
    j.set("parent_span_id", Json::str(hex_id(s.parent_span_id)));
    j.set("fid", Json::str(hex_id(s.fid)));
    j.set("side", Json::str(s.server_side ? "server" : "client"));
    j.set("method", Json::str(s.method));
    j.set("start_us", Json::number(static_cast<double>(s.start_us)));
    j.set("end_us", Json::number(static_cast<double>(s.end_us)));
    j.set("latency_us",
          Json::number(static_cast<double>(s.end_us - s.start_us)));
    j.set("error_code", Json::number(s.error_code));
    j.set("request_bytes",
          Json::number(static_cast<double>(s.request_bytes)));
    j.set("response_bytes",
          Json::number(static_cast<double>(s.response_bytes)));
    Json anns = Json::array();
    for (const auto& [ts, text] : s.annotations) {
      Json a = Json::object();
      a.set("ts_us", Json::number(static_cast<double>(ts)));
      a.set("text", Json::str(text));
      anns.push_back(std::move(a));
    }
    j.set("annotations", std::move(anns));
    spans.push_back(std::move(j));
  }
  root.set("spans", std::move(spans));
  return root.dump();
}

}  // namespace trpc
