// rpcz tracing spans — per-RPC timing records with trace propagation.
//
// Parity: the reference's Span machinery (/root/reference/src/brpc/
// span.h:52-88: CreateClientSpan/CreateServerSpan wired at
// channel.cpp:506-527 and baidu_rpc_protocol.cpp:648-661; trace context
// trace_id/span_id/parent_span_id rides inside the RpcMeta; spans browsed
// via /rpcz, builtin/rpcz_service.*).  Redesigned condensed: spans land in
// an in-memory ring (the reference persists to a per-process
// leveldb — an embedded KV store is out of scope; the ring holds the
// recent window /rpcz actually shows) whose capacity is the reloadable
// flag `trpc_rpcz_ring_size` (default 4096; flip via
// /flags/trpc_rpcz_ring_size?setvalue=N so a busy server does not evict
// the span being hunted before it can be read), collection is gated by
// the reloadable flag `rpcz_enabled`, and the ambient trace context
// lives in fiber-local storage so nested client calls inherit the
// server span.
#pragma once

#include <cstdint>
#include <string>
#include <utility>
#include <vector>

namespace trpc {

struct Span {
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  uint64_t parent_span_id = 0;
  // Fiber the span was started on (0 off-fiber) — makes the
  // span↔timeline join exact: a timeline fiber_run slice with the same
  // fid IS this span's execution, no timestamp inference needed.
  uint64_t fid = 0;
  bool server_side = false;
  std::string method;
  int64_t start_us = 0;
  int64_t end_us = 0;
  int32_t error_code = 0;
  uint64_t request_bytes = 0;
  uint64_t response_bytes = 0;
  std::vector<std::pair<int64_t, std::string>> annotations;
};

// True when span collection is on (flag `rpcz_enabled`, default off —
// same default as the reference's -enable_rpcz).
bool rpcz_enabled();

// Starts a span.  trace_id/parent resolution order: explicit args (from
// wire meta) > ambient fiber context > fresh trace.  The returned span is
// owned by the caller until submit_span.
Span* start_span(bool server_side, const std::string& method,
                 uint64_t trace_id = 0, uint64_t parent_span_id = 0);
void span_annotate(Span* s, const std::string& text);
// Finishes the span and moves it into the ring (frees it).
void submit_span(Span* s, int32_t error_code);

// Ambient trace context: the server span a request handler runs under;
// client spans started in this context become its children.  Storage is
// fiber-local on fibers and falls back to plain thread-local off them,
// so a ctypes caller (Python, a non-fiber pthread) can install a trace
// around `trpc_channel_call` and have the client span inherit it.
void set_ambient_span(const Span* s);  // nullptr clears
void set_ambient_trace(uint64_t trace_id, uint64_t span_id);  // 0,0 clears
void get_ambient_trace(uint64_t* trace_id, uint64_t* span_id);

// /rpcz support: most-recent spans, newest first (bounded by ring size);
// trace_id filter when nonzero.
std::vector<Span> recent_spans(size_t limit, uint64_t trace_id = 0);

// Structured span dump shared by /rpcz?format=json and trpc_rpcz_dump:
// {"pid":n,"now_mono_us":n,"now_wall_us":n,"spans":[...]} with 64-bit ids
// as 16-hex-digit strings (doubles would truncate them) and annotations
// as [{"ts_us":n,"text":s}].  The mono/wall clock pair lets a cross-node
// stitcher (tools/trace_stitch.py) map each node's monotonic span times
// onto one wall-clock timeline.
std::string rpcz_dump_json(size_t limit, uint64_t trace_id = 0);

// Live span-ring capacity (the `trpc_rpcz_ring_size` flag's value;
// touching this also registers the flag).  Resizing preserves the
// newest spans that fit.
size_t rpcz_ring_capacity();

uint64_t new_span_id();

}  // namespace trpc
