#include "net/stream.h"

#include <cerrno>
#include <mutex>
#include <unordered_map>
#include <vector>

#include "base/logging.h"
#include "base/resource_pool.h"
#include "base/time.h"
#include "fiber/event.h"
#include "fiber/execution_queue.h"
#include "fiber/fiber.h"
#include "net/protocol.h"
#include "net/socket.h"

namespace trpc {

namespace {

struct StreamMeta {
  std::atomic<uint32_t> version{0};  // even = idle slot
  uint32_t slot = 0;
  // Guards version transitions vs queue submission (closes the
  // validated-then-recycled race on arriving frames).
  std::atomic_flag mu = ATOMIC_FLAG_INIT;
  void lock() {
    while (mu.test_and_set(std::memory_order_acquire)) {
    }
  }
  void unlock() { mu.clear(std::memory_order_release); }

  SocketId sock = 0;
  std::atomic<uint64_t> peer_sid{0};  // 0 until established
  Event established_ev;               // value flips 0→1 when peer_sid set

  StreamOptions opts;

  // Sender credit (bytes we may still send before more ACKs).
  std::atomic<int64_t> send_window{0};
  Event window_ev;  // bumped on every ACK / close

  // Receiver: consumed-but-unacked bytes; ACK when above half window.
  std::atomic<int64_t> unacked{0};

  std::atomic<bool> closed{false};
  Event close_ev;  // value flips 0→1 on close

  // Allocated once per slot and REUSED across stream incarnations (type-
  // stable, like the meta itself) so late frames can never touch freed
  // memory; stopped_ rejects them instead.
  ExecutionQueue<IOBuf*>* consume_q = nullptr;

  StreamId id() const {
    return (static_cast<uint64_t>(version.load(std::memory_order_relaxed))
            << 32) |
           slot;
  }
};

using StreamPool = ResourcePool<StreamMeta>;

void mark_closed(StreamMeta* m);

// socket id → live StreamIds bound to it, so a connection failure can
// close its streams eagerly (stream_on_connection_failed).  Bound at
// establishment (when m->sock is set), unbound at StreamClose.  A plain
// mutex: establishment/close are per-stream events, not per-frame.
std::mutex& by_socket_mu() {
  static std::mutex mu;
  return mu;
}
std::unordered_multimap<uint64_t, StreamId>& by_socket() {
  // Heap-allocated and intentionally never destroyed: detached consumer
  // fibers can still be delivering deferred CLOSEs (→ StreamClose →
  // unbind_socket) while static destructors run at process exit, and an
  // at-exit teardown of this map races them.
  static auto* m = new std::unordered_multimap<uint64_t, StreamId>();
  return *m;
}

void bind_socket(uint64_t sock, StreamId sid) {
  if (sock == 0) {
    return;
  }
  std::lock_guard<std::mutex> g(by_socket_mu());
  by_socket().emplace(sock, sid);
}

void unbind_socket(uint64_t sock, StreamId sid) {
  if (sock == 0) {
    return;
  }
  std::lock_guard<std::mutex> g(by_socket_mu());
  auto range = by_socket().equal_range(sock);
  for (auto it = range.first; it != range.second; ++it) {
    if (it->second == sid) {
      by_socket().erase(it);
      return;
    }
  }
}

void drop_chunk(IOBuf*& chunk) { delete chunk; }

StreamMeta* stream_of(StreamId id) {
  const uint32_t ver = static_cast<uint32_t>(id >> 32);
  if ((ver & 1) == 0) {
    return nullptr;
  }
  StreamMeta* m = StreamPool::instance()->at(static_cast<uint32_t>(id));
  if (m == nullptr || m->version.load(std::memory_order_acquire) != ver) {
    return nullptr;
  }
  return m;
}

// Sends accumulated credit back when above half the granted window.  A
// stream whose peer is not yet bound (early frames racing the accept
// response) keeps accumulating; the bind path re-tries the ack.
void maybe_send_ack(StreamMeta* m) {
  const uint64_t peer = m->peer_sid.load(std::memory_order_acquire);
  if (peer == 0) {
    return;
  }
  const int64_t unacked = m->unacked.load(std::memory_order_acquire);
  if (unacked < m->opts.window_bytes / 2) {
    return;
  }
  m->unacked.fetch_sub(unacked, std::memory_order_acq_rel);
  RpcMeta ack;
  ack.type = RpcMeta::kStreamFrame;
  ack.stream_flags = RpcMeta::kStreamAck;
  ack.stream_id = peer;
  ack.ack_bytes = static_cast<uint64_t>(unacked);
  IOBuf frame;
  tstd_pack(&frame, ack, IOBuf());
  SocketRef s(Socket::Address(m->sock));
  if (s) {
    s->Write(std::move(frame));
  }
}

int consume_handler(void* meta, IOBuf** chunks, size_t n) {
  StreamMeta* m = static_cast<StreamMeta*>(meta);
  const StreamId sid = m->id();
  for (size_t i = 0; i < n; ++i) {
    IOBuf* chunk = chunks[i];
    if (chunk == nullptr) {
      // CLOSE sentinel: rides the queue so every data chunk ahead of it is
      // delivered first (ordered close).  Data frames racing the close may
      // land BEHIND the sentinel in this same batch — they are dropped, but
      // their heap chunks must still be freed (consume() only deletes the
      // batch array).  Nothing may touch `m` after mark_closed — on_closed
      // typically calls StreamClose which recycles the meta.
      for (size_t j = i + 1; j < n; ++j) {
        delete chunks[j];
      }
      mark_closed(m);
      return 1;
    }
    const size_t bytes = chunk->size();
    if (m->opts.on_message && !m->closed.load(std::memory_order_acquire)) {
      m->opts.on_message(sid, std::move(*chunk));
    }
    delete chunk;
    m->unacked.fetch_add(bytes, std::memory_order_acq_rel);
    maybe_send_ack(m);  // feedback frame parity
  }
  return 0;
}

StreamId new_stream(const StreamOptions& opts) {
  // First stream in the process arms the socket-failure observer so
  // connection death reaches every bound stream (closes the wedge where a
  // reader with no pending write never learns the peer died).
  static const bool hooked = [] {
    Socket::set_failure_observer(&stream_on_connection_failed);
    return true;
  }();
  (void)hooked;
  StreamMeta* m = nullptr;
  const uint32_t slot = StreamPool::instance()->acquire(&m);
  if (m == nullptr) {
    return 0;
  }
  if (m->consume_q != nullptr) {
    // Previous incarnation's consumer must finish BEFORE any state is
    // reset, not merely before the queue is reconfigured: a peer CLOSE
    // sentinel that raced into the queue just ahead of the local
    // StreamClose is still draining here, and its mark_closed must land
    // on the old incarnation (where `closed` is already true — a no-op)
    // rather than close the next stream at birth.  Found as a ~2%
    // born-closed rate under sequential completion traffic.
    while (!m->consume_q->idle()) {
      if (in_fiber()) {
        fiber_yield();
      } else {
        sched_yield();
      }
    }
  }
  m->slot = slot;
  m->opts = opts;
  m->sock = 0;
  m->peer_sid.store(0, std::memory_order_relaxed);
  m->established_ev.value.store(0, std::memory_order_relaxed);
  m->send_window.store(opts.window_bytes, std::memory_order_relaxed);
  m->window_ev.value.store(0, std::memory_order_relaxed);
  m->unacked.store(0, std::memory_order_relaxed);
  m->closed.store(false, std::memory_order_relaxed);
  m->close_ev.value.store(0, std::memory_order_relaxed);
  m->lock();
  if (m->consume_q == nullptr) {
    m->consume_q = new ExecutionQueue<IOBuf*>();
    m->consume_q->start(consume_handler, m, drop_chunk);
  } else {
    m->consume_q->restart(consume_handler, m, drop_chunk);
  }
  const uint32_t ver = m->version.load(std::memory_order_relaxed) + 1;
  m->version.store(ver, std::memory_order_release);
  m->unlock();
  return m->id();
}

void mark_closed(StreamMeta* m) {
  if (m->closed.exchange(true, std::memory_order_acq_rel)) {
    return;
  }
  m->close_ev.value.store(1, std::memory_order_release);
  m->close_ev.wake_all();
  m->window_ev.value.fetch_add(1, std::memory_order_release);
  m->window_ev.wake_all();
  // Writers parked awaiting establishment must observe the death NOW
  // (an unaccepted batch offer would otherwise wait out its timeout).
  m->established_ev.wake_all();
  if (m->opts.on_closed) {
    m->opts.on_closed(m->id());
  }
}

}  // namespace

int StreamCreate(StreamId* out, Controller* cntl, const StreamOptions& opts) {
  const StreamId sid = new_stream(opts);
  if (sid == 0) {
    return ENOMEM;
  }
  cntl->call().offered_stream = sid;
  *out = sid;
  return 0;
}

namespace {

// Accepts ONE offered (peer_sid, peer_window); returns the local id.
StreamId accept_one(Controller* cntl, const StreamOptions& opts,
                    uint64_t peer_sid, uint64_t peer_window) {
  const StreamId sid = new_stream(opts);
  if (sid == 0) {
    return 0;
  }
  StreamMeta* m = stream_of(sid);
  m->sock = cntl->call().socket_id;
  m->peer_sid.store(peer_sid, std::memory_order_release);
  // Our send credit is whatever receive window the CLIENT advertised.
  m->send_window.store(static_cast<int64_t>(peer_window),
                       std::memory_order_release);
  m->established_ev.value.store(1, std::memory_order_release);
  m->established_ev.wake_all();
  bind_socket(m->sock, sid);
  return sid;
}

}  // namespace

int StreamAccept(StreamId* out, Controller* cntl, const StreamOptions& opts) {
  if (cntl->call().peer_stream == 0) {
    return EINVAL;  // request offered no stream
  }
  const StreamId sid = accept_one(cntl, opts, cntl->call().peer_stream,
                                  cntl->call().peer_stream_window);
  if (sid == 0) {
    return ENOMEM;
  }
  cntl->call().accepted_stream = sid;  // rides back in the response meta
  *out = sid;
  return 0;
}

int StreamCreateBatch(std::vector<StreamId>* out, int count,
                      Controller* cntl, const StreamOptions& opts) {
  if (count <= 0 || count > 256) {
    return EINVAL;
  }
  out->clear();
  for (int i = 0; i < count; ++i) {
    const StreamId sid = new_stream(opts);
    if (sid == 0) {
      for (StreamId created : *out) {
        StreamClose(created);
      }
      out->clear();
      return ENOMEM;
    }
    out->push_back(sid);
  }
  cntl->call().offered_stream = (*out)[0];
  cntl->call().extra_offered.assign(out->begin() + 1, out->end());
  return 0;
}

int StreamAcceptBatch(std::vector<StreamId>* out, Controller* cntl,
                      const StreamOptions& opts) {
  if (cntl->call().peer_stream == 0) {
    return EINVAL;
  }
  out->clear();
  const StreamId first = accept_one(cntl, opts, cntl->call().peer_stream,
                                    cntl->call().peer_stream_window);
  if (first == 0) {
    return ENOMEM;
  }
  out->push_back(first);
  for (const auto& [peer_sid, peer_window] : cntl->call().extra_peer) {
    const StreamId sid = accept_one(cntl, opts, peer_sid, peer_window);
    if (sid == 0) {
      for (StreamId created : *out) {
        StreamClose(created);
      }
      out->clear();
      return ENOMEM;
    }
    out->push_back(sid);
  }
  cntl->call().accepted_stream = (*out)[0];
  cntl->call().extra_accepted.assign(out->begin() + 1, out->end());
  return 0;
}

int StreamWrite(StreamId id, IOBuf&& data) {
  StreamMeta* m = stream_of(id);
  if (m == nullptr) {
    return EINVAL;
  }
  // Wait for establishment (client side: response not yet back).
  while (m->established_ev.value.load(std::memory_order_acquire) == 0) {
    if (m->closed.load(std::memory_order_acquire)) {
      return EPIPE;
    }
    m->established_ev.wait(0, monotonic_time_us() + 10 * 1000 * 1000);
    if (stream_of(id) != m) {
      return EINVAL;
    }
  }
  const int64_t bytes = static_cast<int64_t>(data.size());
  // Credit gate: park until the window admits this chunk.  Each wakeup
  // also probes the connection so a dead peer (no CLOSE ever arriving)
  // unparks the writer within one probe interval.
  int64_t window = m->send_window.load(std::memory_order_acquire);
  while (true) {
    if (m->closed.load(std::memory_order_acquire) || stream_of(id) != m) {
      return EPIPE;
    }
    {
      SocketRef s(Socket::Address(m->sock));
      if (!s || s->Failed()) {
        mark_closed(m);
        return EPIPE;
      }
    }
    if (window >= bytes) {
      if (m->send_window.compare_exchange_weak(window, window - bytes,
                                               std::memory_order_acq_rel)) {
        break;
      }
      continue;  // `window` reloaded by the failed CAS
    }
    const uint32_t snap = m->window_ev.value.load(std::memory_order_acquire);
    window = m->send_window.load(std::memory_order_acquire);
    if (window >= bytes) {
      continue;  // refilled between checks
    }
    m->window_ev.wait(snap, monotonic_time_us() + 1000 * 1000);
    window = m->send_window.load(std::memory_order_acquire);
  }
  RpcMeta meta;
  meta.type = RpcMeta::kStreamFrame;
  meta.stream_flags = RpcMeta::kStreamData;
  meta.stream_id = m->peer_sid.load(std::memory_order_acquire);
  IOBuf frame;
  tstd_pack(&frame, meta, data);
  SocketRef s(Socket::Address(m->sock));
  if (!s || s->Write(std::move(frame)) != 0) {
    mark_closed(m);
    return EPIPE;
  }
  return 0;
}

int StreamClose(StreamId id) {
  StreamMeta* m = stream_of(id);
  if (m == nullptr) {
    return EINVAL;
  }
  // Best-effort CLOSE to the peer.
  const uint64_t peer = m->peer_sid.load(std::memory_order_acquire);
  if (peer != 0 && !m->closed.load(std::memory_order_acquire)) {
    RpcMeta meta;
    meta.type = RpcMeta::kStreamFrame;
    meta.stream_flags = RpcMeta::kStreamClose;
    meta.stream_id = peer;
    IOBuf frame;
    tstd_pack(&frame, meta, IOBuf());
    SocketRef s(Socket::Address(m->sock));
    if (s) {
      s->Write(std::move(frame));
    }
  }
  mark_closed(m);
  // Destroy the local id under the meta lock: frame submission validates
  // the version under the same lock, so no frame can enter the queue after
  // the bump; the queue itself is persistent (stopped, reused on next
  // incarnation after it drains).
  const uint64_t sock = m->sock;
  const uint32_t ver = static_cast<uint32_t>(id >> 32);
  m->lock();
  uint32_t expect = ver;
  if (!m->version.compare_exchange_strong(expect, ver + 1,
                                          std::memory_order_acq_rel)) {
    m->unlock();
    return 0;  // someone else destroyed concurrently
  }
  m->consume_q->stop();
  m->unlock();
  unbind_socket(sock, id);
  StreamPool::instance()->release(m->slot);
  return 0;
}

int StreamWait(StreamId id, int64_t deadline_us) {
  StreamMeta* m = stream_of(id);
  if (m == nullptr) {
    return 0;  // already gone == closed
  }
  while (!m->closed.load(std::memory_order_acquire)) {
    if (stream_of(id) != m) {
      return 0;
    }
    const int rc = m->close_ev.wait(0, deadline_us);
    if (rc == ETIMEDOUT) {
      return rc;
    }
  }
  return 0;
}

bool StreamExists(StreamId id) { return stream_of(id) != nullptr; }

// ---- wiring ---------------------------------------------------------------

void stream_on_frame(InputMessage&& msg) {
  StreamMeta* m = stream_of(msg.meta.stream_id);
  if (m == nullptr) {
    return;  // stale frame after close: harmless (versioned id armor)
  }
  switch (msg.meta.stream_flags) {
    case RpcMeta::kStreamData: {
      auto* chunk = new IOBuf(std::move(msg.payload));
      // Submit under the meta lock so a concurrent StreamClose (version
      // bump + queue stop under the same lock) can't recycle the slot
      // between our validation and the enqueue.
      m->lock();
      const bool ok =
          m->version.load(std::memory_order_relaxed) ==
              static_cast<uint32_t>(msg.meta.stream_id >> 32) &&
          m->consume_q != nullptr && m->consume_q->execute(chunk) == 0;
      m->unlock();
      if (!ok) {
        delete chunk;
      }
      break;
    }
    case RpcMeta::kStreamAck:
      m->send_window.fetch_add(static_cast<int64_t>(msg.meta.ack_bytes),
                               std::memory_order_acq_rel);
      m->window_ev.value.fetch_add(1, std::memory_order_release);
      m->window_ev.wake_all();
      break;
    case RpcMeta::kStreamClose: {
      // Ordered close: deliver queued data first via the sentinel.
      m->lock();
      const bool ver_ok =
          m->version.load(std::memory_order_relaxed) ==
          static_cast<uint32_t>(msg.meta.stream_id >> 32);
      const bool queued =
          ver_ok && m->consume_q != nullptr &&
          m->consume_q->execute(nullptr) == 0;
      m->unlock();
      if (ver_ok && !queued) {
        mark_closed(m);
      }
      break;
    }
    default:
      break;
  }
}

void stream_on_accept_response(uint64_t local_sid, uint64_t peer_sid,
                               uint64_t socket_id, uint64_t peer_window) {
  StreamMeta* m = stream_of(local_sid);
  if (m == nullptr) {
    return;
  }
  m->sock = socket_id;
  m->peer_sid.store(peer_sid, std::memory_order_release);
  m->send_window.store(static_cast<int64_t>(peer_window),
                       std::memory_order_release);
  m->established_ev.value.store(1, std::memory_order_release);
  m->established_ev.wake_all();
  bind_socket(socket_id, local_sid);
}

uint64_t stream_recv_window(StreamId id) {
  StreamMeta* m = stream_of(id);
  return m != nullptr ? static_cast<uint64_t>(m->opts.window_bytes) : 0;
}

uint64_t stream_send_window(StreamId id) {
  StreamMeta* m = stream_of(id);
  if (m == nullptr) {
    return 0;
  }
  const int64_t w = m->send_window.load(std::memory_order_acquire);
  return w > 0 ? static_cast<uint64_t>(w) : 0;
}

void stream_on_connection_failed(uint64_t socket_id) {
  // Snapshot-then-close: mark_closed runs user on_closed callbacks, which
  // may call StreamClose (unbind takes the same mutex) — never hold the
  // registry lock across them.
  std::vector<StreamId> victims;
  {
    std::lock_guard<std::mutex> g(by_socket_mu());
    auto range = by_socket().equal_range(socket_id);
    for (auto it = range.first; it != range.second; ++it) {
      victims.push_back(it->second);
    }
    by_socket().erase(socket_id);
  }
  for (StreamId sid : victims) {
    StreamMeta* m = stream_of(sid);
    if (m == nullptr) {
      continue;
    }
    // Route the close through the consume queue under the meta lock
    // (the kStreamFrame close path): a concurrent StreamClose + slot
    // reuse between the stream_of snapshot and an unguarded mark_closed
    // would close the NEXT incarnation at birth.  The version bump and
    // queue stop happen under this same lock, so a stale sid can no
    // longer reach the new stream; a sentinel that lands anyway drains
    // against the old incarnation before new_stream resets state.
    m->lock();
    const bool ver_ok = m->version.load(std::memory_order_relaxed) ==
                        static_cast<uint32_t>(sid >> 32);
    const bool queued = ver_ok && m->consume_q != nullptr &&
                        m->consume_q->execute(nullptr) == 0;
    m->unlock();
    if (ver_ok && !queued) {
      mark_closed(m);
    }
  }
}

}  // namespace trpc
