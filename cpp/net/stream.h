// Streaming RPC — ordered byte-chunk streams with credit flow control.
//
// Parity: brpc streaming (/root/reference/src/brpc/stream.h:106-150,
// stream.cpp: Create :78, ExecutionQueue consumer :109/:582, credit-window
// AppendIfNotFull :326, feedback frames via streaming_rpc_meta.proto).
// Re-designed: a stream is a pooled versioned object bound to an existing
// connection; frames ride the tstd protocol (meta.type = kStreamFrame) and
// are consumed through a per-stream ExecutionQueue so handlers see chunks
// in order; ACK frames reopen the writer's window, writers park on an
// Event when credit runs out.
//
// Establishment piggybacks on a normal RPC (like the reference):
//   client: StreamCreate(&sid, &cntl, opts); channel.CallMethod(...);
//   server handler: StreamAccept(&sid, cntl, opts); ... done();
// After the response returns, both sides may StreamWrite / receive
// on_message callbacks.  Each side must StreamClose its own id.
#pragma once

#include <cstdint>
#include <functional>
#include <vector>

#include "base/iobuf.h"
#include "net/controller.h"

namespace trpc {

using StreamId = uint64_t;  // version<<32 | slot

struct StreamOptions {
  // Called in arrival order (serialized per stream), from a fiber.
  std::function<void(StreamId, IOBuf&&)> on_message;
  // Peer closed (or connection died).
  std::function<void(StreamId)> on_closed;
  int64_t window_bytes = 2 * 1024 * 1024;  // receive window we grant
};

// Client side: create a local stream and attach it to `cntl` so the next
// CallMethod on that controller offers it to the server.
int StreamCreate(StreamId* out, Controller* cntl, const StreamOptions& opts);

// Server side: accept the stream offered by the current request (fails if
// the request carries none).  Must be called before done().
int StreamAccept(StreamId* out, Controller* cntl, const StreamOptions& opts);

// Batch establishment (StreamIds parity, ref stream.h:114): one RPC
// offers `count` streams at once; the server accepts ALL of them in one
// call.  All share `opts` (each still gets its own window/queue).  The
// batch accepts/fails atomically: a mid-batch allocation failure
// destroys the partial set and returns ENOMEM.
int StreamCreateBatch(std::vector<StreamId>* out, int count,
                      Controller* cntl, const StreamOptions& opts);
int StreamAcceptBatch(std::vector<StreamId>* out, Controller* cntl,
                      const StreamOptions& opts);

// Ordered write; parks the calling fiber while the peer's window is
// exhausted.  Returns 0, EINVAL (gone), EPIPE (closed/conn dead).
int StreamWrite(StreamId id, IOBuf&& data);

// Graceful close: sends CLOSE (best effort) and destroys the local id.
int StreamClose(StreamId id);

// Park until the peer closes the stream (or it dies).  0 on close.
int StreamWait(StreamId id, int64_t deadline_us = -1);

// True while the id refers to a live stream.
bool StreamExists(StreamId id);

// -- internal (messenger hook) -------------------------------------------
struct InputMessage;
void stream_on_frame(InputMessage&& msg);
// Bind the client stream to the server's accepted id (response path).
// `peer_window` is the receive window the peer advertised — it becomes our
// send credit (windows are exchanged at establishment, like the stream
// settings in streaming_rpc_meta.proto).
void stream_on_accept_response(uint64_t local_sid, uint64_t peer_sid,
                               uint64_t socket_id, uint64_t peer_window);
// The receive window a local stream grants (advertised to the peer).
uint64_t stream_recv_window(StreamId id);
// Remaining send credit (the peer's advertised window minus unacked
// writes).  0 for unknown/unestablished ids.  The inference scheduler
// caps per-request token budgets with this so a batch write can never
// park the shared decode loop on one slow reader.
uint64_t stream_send_window(StreamId id);
// Invoked by Socket::SetFailed (registered failure observer): closes
// every stream bound to the dead connection so readers get on_closed
// promptly instead of wedging until a write probes the socket.
void stream_on_connection_failed(uint64_t socket_id);

}  // namespace trpc
