#include "net/stripe.h"

#include <errno.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <memory>
#include <mutex>
#include <unordered_map>
#include <utility>

#include "base/compress.h"
#include "base/flags.h"
#include "base/logging.h"
#include "base/rand.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/rma.h"
#include "net/hotpath_stats.h"
#include "net/socket.h"
#include "stat/timeline.h"

namespace trpc {

namespace {

// Landing buffers are single contiguous blocks, so a stripe total must
// fit a Block's 32-bit length; bodies at/above this fall back to the
// single-frame path (still correct, just unstriped).
constexpr uint64_t kMaxStripeTotal = 3ull << 30;
// Global bound on bytes parked in incomplete reassemblies: a flood of
// heads with huge totals must exhaust the map, not the heap.
constexpr uint64_t kPendingCapBytes = 8ull << 30;

int64_t flag_value(Flag* f, int64_t dflt) {
  return f != nullptr ? f->int64_value() : dflt;
}

Flag* int_flag(const char* name, int64_t dflt, const char* desc,
               int64_t lo, int64_t hi) {
  Flag* f = Flag::define_int64(name, dflt, desc);
  if (f != nullptr) {
    // Range validator + introspectable bounds in one declaration (the
    // tuner and /flags?format=json read them back).
    f->set_int_range(lo, hi);
  }
  return f;
}

Flag* threshold_flag() {
  static Flag* f = int_flag(
      "trpc_stripe_threshold", 2ll << 20,
      "payloads above this many bytes are striped into concurrent chunk "
      "frames (0 disables striping)",
      0, static_cast<int64_t>(kMaxStripeTotal));
  return f;
}

Flag* chunk_flag() {
  static Flag* f = int_flag(
      "trpc_stripe_chunk_bytes", 2ll << 20,
      "stripe chunk size in bytes (per-frame unit of the multi-rail "
      "large-message path)",
      64 << 10, 64 << 20);
  return f;
}

Flag* rails_flag() {
  static Flag* f = int_flag(
      "trpc_stripe_rails", 4,
      "connections a striped message spreads over (pooled channels; "
      "includes the primary)",
      1, 16);
  return f;
}

Flag* reassembly_timeout_flag() {
  static Flag* f = int_flag(
      "trpc_stripe_reassembly_timeout_ms", 30000,
      "incomplete stripe reassemblies older than this are dropped "
      "(whole-call failure surfaces via the RPC timeout)",
      100, 3600 * 1000);
  return f;
}

// ---- reassembly map ------------------------------------------------------

struct StripeEntry {
  uint64_t id = 0;
  uint64_t total = 0;
  char* dest = nullptr;   // landing base (block->data or caller buffer)
  Block* block = nullptr;  // arena landing block (null: caller-registered)
  bool caller_buf = false;
  SocketId head_socket = 0;
  int64_t created_us = 0;
  std::mutex mu;  // head/rails/dispatch bookkeeping (chunk-rate, not hot)
  bool have_head = false;
  bool dispatched = false;
  RpcMeta head_meta;
  std::vector<SocketId> rails;
  // Admitted chunk spans, kept sorted and verified DISJOINT: chunks are
  // admitted only if they overlap nothing already accepted, so admitted
  // spans summing to `total` within [0, total) is a proof of exact
  // cover — landed == total can then never dispatch a payload with
  // unwritten gaps (duplicate offsets from a buggy/hostile peer are
  // dropped instead of double-counted).  Guarded by mu.
  std::vector<std::pair<uint64_t, uint64_t>> spans;  // (offset, end)
  std::atomic<uint64_t> landed{0};
  // Landers currently able to touch `dest`; incremented under the map
  // mutex BEFORE the landing fiber is spawned, so an unregistering
  // caller that removed the entry and then observed landers == 0 knows
  // no copy into its buffer can ever start again.
  std::atomic<int> landers{0};
  std::atomic<bool> abandoned{false};

  ~StripeEntry() {
    if (block != nullptr) {
      block->release();
    }
  }
};

struct LandingReg {
  void* buf = nullptr;
  size_t cap = 0;
  std::shared_ptr<StripeEntry> entry;  // bound when chunks start landing
};

std::mutex& map_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
std::unordered_map<uint64_t, std::shared_ptr<StripeEntry>>& entries() {
  static auto* m =
      new std::unordered_map<uint64_t, std::shared_ptr<StripeEntry>>();
  return *m;
}
std::unordered_map<uint64_t, LandingReg>& landings() {
  static auto* m = new std::unordered_map<uint64_t, LandingReg>();
  return *m;
}
std::atomic<uint64_t> g_pending_bytes{0};
std::atomic<int64_t> g_last_gc_us{0};

// Eager flag definitions: settable via /flags (and trpc_flag_set) before
// the first striped message would lazily create them.
[[maybe_unused]] Flag* const g_stripe_flags_eager[] = {
    threshold_flag(), chunk_flag(), rails_flag(), reassembly_timeout_flag()};

void maybe_gc() {
  const int64_t now = monotonic_time_us();
  // Relaxed load + CAS: the stamp only rate-limits GC claims; the map
  // itself is read under map_mu(), so no data rides this word.
  int64_t last = g_last_gc_us.load(std::memory_order_relaxed);
  if (now - last < 1000 * 1000 ||
      !g_last_gc_us.compare_exchange_strong(last, now,
                                            std::memory_order_relaxed)) {
    return;
  }
  stripe_gc(now);
}

// Finds-or-creates the entry for id and ADMITS one chunk: validates
// bounds, records the arrival rail, and counts the lander in — all in
// ONE map-mutex critical section.  The lander count must rise under the
// same lock that stripe_unregister_landing abandons entries under, or an
// unregistering caller could observe zero landers (buffer "quiescent"),
// recycle the buffer, and then have this chunk's copy land in it.
// nullptr when the chunk is unacceptable (over caps, total mismatch,
// bad bounds) — it is dropped and the call times out whole.
std::shared_ptr<StripeEntry> admit_chunk(uint64_t id, uint64_t total,
                                         uint64_t offset, uint64_t len,
                                         SocketId from) {
  if (id == 0 || total == 0 || total >= kMaxStripeTotal || len == 0 ||
      offset + len > total || offset + len < offset) {
    return nullptr;
  }
  std::lock_guard<std::mutex> g(map_mu());
  std::shared_ptr<StripeEntry> e;
  auto it = entries().find(id);
  if (it != entries().end()) {
    if (it->second->total != total) {
      return nullptr;  // id collision / corrupted peer: drop
    }
    e = it->second;
  } else {
    if (g_pending_bytes.load(std::memory_order_relaxed) + total >
        kPendingCapBytes) {
      return nullptr;  // reassembly arena over budget: shed, don't OOM
    }
    e = std::make_shared<StripeEntry>();
    e->id = id;
    e->total = total;
    e->created_us = monotonic_time_us();
    auto reg = landings().find(id);
    if (reg != landings().end() && reg->second.cap >= total) {
      // Caller-registered landing (batch plane): chunks memcpy straight
      // into the caller's buffer — no arena bounce, no boundary copy.
      e->dest = static_cast<char*>(reg->second.buf);
      e->caller_buf = true;
      reg->second.entry = e;
    } else {
      e->block = HostArena::instance()->allocate(
          static_cast<uint32_t>(total));
      e->block->size = static_cast<uint32_t>(total);
      e->dest = e->block->data;
    }
    // Relaxed: pure accounting var (stripe_pending_bytes) — readers
    // tolerate transient skew, no ordering needed.
    g_pending_bytes.fetch_add(total, std::memory_order_relaxed);
    entries().emplace(id, e);
  }
  {
    std::lock_guard<std::mutex> eg(e->mu);
    // Disjointness check: sorted insert, reject any overlap with an
    // already-admitted span (see the `spans` member comment).
    auto pos = std::lower_bound(
        e->spans.begin(), e->spans.end(),
        std::make_pair(offset, offset + len));
    if ((pos != e->spans.end() && pos->first < offset + len) ||
        (pos != e->spans.begin() && std::prev(pos)->second > offset)) {
      return nullptr;  // duplicate/overlapping chunk: drop it
    }
    e->spans.insert(pos, {offset, offset + len});
    bool seen = false;
    for (SocketId r : e->rails) {
      if (r == from) {
        seen = true;
        break;
      }
    }
    if (!seen) {
      e->rails.push_back(from);
    }
  }
  e->landers.fetch_add(1, std::memory_order_acq_rel);
  return e;
}

void drop_entry_locked(const std::shared_ptr<StripeEntry>& e) {
  // Relaxed: accounting only (see the fetch_add at entry creation).
  g_pending_bytes.fetch_sub(e->total, std::memory_order_relaxed);
  entries().erase(e->id);
}

void noop_deleter(void*, void*) {}

// Dispatches the fully landed message through the tstd protocol hooks
// (runs on the finishing lander's worker fiber — the same place a
// per-message dispatch fiber would have run).
void dispatch_entry(const std::shared_ptr<StripeEntry>& e) {
  hotpath_vars().stripe_reassembled << 1;
  InputMessage m;
  {
    std::lock_guard<std::mutex> g(e->mu);
    m.meta = std::move(e->head_meta);
    if (m.meta.type == RpcMeta::kRequest) {
      auto arrival = std::make_shared<StripeArrival>();
      arrival->rails = e->rails;
      m.ctx = std::move(arrival);
    }
  }
  // Per-chunk CRCs were verified frame-by-frame at parse; the head's CRC
  // covered only chunk 0, so it must not masquerade as a whole-body one.
  m.meta.checksum = 0;
  m.socket = e->head_socket;
  if (e->caller_buf) {
    m.payload.append_user_data(e->dest, e->total, &noop_deleter);
  } else {
    m.payload.append_block(e->block, 0, static_cast<uint32_t>(e->total));
    e->block = nullptr;  // reference consumed by the payload
  }
  const Protocol& p = tstd_protocol();
  if (m.meta.type == RpcMeta::kResponse) {
    p.process_response(std::move(m));
  } else {
    p.process_request(std::move(m));
  }
}

// Checks completion and dispatches exactly once.
void maybe_finalize(const std::shared_ptr<StripeEntry>& e) {
  if (e->landed.load(std::memory_order_acquire) != e->total) {
    return;
  }
  {
    std::lock_guard<std::mutex> g(e->mu);
    // Acquire on abandoned: pairs with the GC's release store so a
    // dispatch racing expiry never delivers a half-reclaimed entry.
    if (!e->have_head || e->dispatched ||
        e->abandoned.load(std::memory_order_acquire)) {
      return;
    }
    e->dispatched = true;
  }
  if (timeline::enabled()) {
    timeline::record(timeline::kStripeDone, e->id, e->total);
  }
  {
    std::lock_guard<std::mutex> g(map_mu());
    drop_entry_locked(e);
  }
  dispatch_entry(e);
}

struct LandJob {
  std::shared_ptr<StripeEntry> entry;
  IOBuf data;
  uint64_t offset = 0;
};

void land_job_run(LandJob* j) {
  const std::shared_ptr<StripeEntry>& e = j->entry;
  const uint64_t n = j->data.size();
  // Acquire: a lander observing the GC's abandoned release-store must
  // also see the entry's landing block already detached — copying into
  // e->dest after reclaim would scribble freed arena memory.
  if (!e->abandoned.load(std::memory_order_acquire)) {
    j->data.copy_to(e->dest + j->offset, n);
  }
  if (timeline::enabled()) {
    timeline::record(timeline::kStripeLand, e->id, j->offset);
  }
  j->data.clear();  // release parse-buffer blocks before the dispatch
  const uint64_t landed =
      e->landed.fetch_add(n, std::memory_order_acq_rel) + n;
  // The lander count gates buffer reuse (stripe_unregister_landing):
  // drop it BEFORE finalize, whose dispatch path may park this fiber in
  // a fid lock held by a concurrent timeout completion that is itself
  // waiting for landers to drain.
  e->landers.fetch_sub(1, std::memory_order_release);
  if (landed == e->total) {
    maybe_finalize(e);
  }
}

void land_job_fiber(void* arg) {
  auto* j = static_cast<LandJob*>(arg);
  land_job_run(j);
  delete j;
}

// Queues one chunk's landing memcpy on a worker fiber (inline fallback
// when the pool is exhausted).  Caller must have incremented
// entry->landers under the map mutex.
void enqueue_land(std::shared_ptr<StripeEntry> e, IOBuf&& data,
                  uint64_t offset) {
  auto* j = new LandJob{std::move(e), std::move(data), offset};
  if (fiber_start(nullptr, land_job_fiber, j, 0) != 0) {
    land_job_run(j);
    delete j;
  }
}

}  // namespace

bool stripe_eligible(uint64_t n) {
  const int64_t thr = flag_value(threshold_flag(), 0);
  return thr > 0 && n > static_cast<uint64_t>(thr) && n < kMaxStripeTotal;
}

uint64_t stripe_chunk_bytes() {
  return static_cast<uint64_t>(flag_value(chunk_flag(), 2 << 20));
}

int stripe_rails() {
  return static_cast<int>(flag_value(rails_flag(), 4));
}

uint64_t stripe_make_id() {
  uint64_t id;
  do {
    id = fast_rand();
  } while (id == 0);
  return id;
}

bool stripe_should(SocketId primary, uint64_t stream_id,
                   uint64_t body_bytes) {
  if (stream_id != 0 || !stripe_eligible(body_bytes)) {
    return false;
  }
  SocketRef s(Socket::Address(primary));
  return s && s->mode() != SocketMode::kIci;
}

int stripe_frame_send(SocketId primary, RpcMeta&& meta, IOBuf&& body) {
  if (meta.has_checksum) {
    meta.checksum = crc32c(body);
  }
  IOBuf frame;
  tstd_pack(&frame, meta, body);
  SocketRef s(Socket::Address(primary));
  return s && s->Write(std::move(frame)) == 0 ? 0 : -1;
}

int stripe_send(SocketId primary, const std::vector<SocketId>& rails,
                RpcMeta&& meta, IOBuf&& body, uint64_t stripe_id,
                const DeadlineToken& tok) {
  const uint64_t total = body.size();
  const uint64_t chunk =
      std::max<uint64_t>(64 << 10, stripe_chunk_bytes());
  const bool tl = timeline::enabled();  // hoisted: one load per message
  if (tl) {
    timeline::record(timeline::kStripeCut, stripe_id, total);
  }
  meta.stripe_id = stripe_id;
  meta.stripe_offset = 0;
  meta.stripe_total = total;
  IOBuf first;
  body.cutn(&first, chunk);
  if (meta.has_checksum) {
    meta.checksum = crc32c(first);  // head CRC covers chunk 0 only
  }
  uint64_t nchunks = 1;
  {
    // Head rides the primary so the call's own connection sees it in
    // the position a single-frame message would have held.
    IOBuf frame;
    tstd_pack(&frame, meta, first);
    SocketRef p(Socket::Address(primary));
    if (!p || p->Write(std::move(frame)) != 0) {
      return -1;
    }
    if (tl) {
      // Head rides the primary, never a numbered rail.
      timeline::record(timeline::kStripeSend, stripe_id,
                       timeline::kStripePrimaryRail << 48);
    }
  }
  uint64_t off = chunk;
  size_t rail_i = 0;
  while (!body.empty()) {
    if (tok.aborted()) {
      // Cascading cancel / expired budget: stop cutting within one
      // chunk.  The receiver's partial reassembly never dispatches and
      // expires whole after trpc_stripe_reassembly_timeout_ms.
      deadline_vars().cancel_saved_bytes
          << static_cast<int64_t>(body.size());
      return -1;
    }
    IOBuf piece;
    body.cutn(&piece, chunk);
    RpcMeta cm;
    cm.type = RpcMeta::kStripe;
    cm.stripe_id = stripe_id;
    cm.stripe_offset = off;
    cm.stripe_total = total;
    off += piece.size();
    if (meta.has_checksum) {
      cm.has_checksum = true;
      cm.checksum = crc32c(piece);
    }
    ++nchunks;
    uint64_t tl_rail =
        rails.empty() ? timeline::kStripePrimaryRail
                      : static_cast<uint64_t>(rail_i % rails.size());
    const SocketId rid =
        rails.empty() ? primary : rails[rail_i++ % rails.size()];
    bool sent = false;
    if (rid != 0) {
      // tstd_pack shares `piece`'s blocks by reference, so a failed rail
      // write leaves the chunk intact for the primary retry below.
      IOBuf frame;
      tstd_pack(&frame, cm, piece);
      SocketRef r(Socket::Address(rid));
      sent = r && r->Write(std::move(frame)) == 0;
    }
    if (!sent) {
      if (rid == primary) {
        return -1;
      }
      IOBuf frame;
      tstd_pack(&frame, cm, piece);
      SocketRef p(Socket::Address(primary));
      if (!p || p->Write(std::move(frame)) != 0) {
        return -1;  // primary gone: the whole call fails, cleanly
      }
      tl_rail = timeline::kStripePrimaryRail;  // dead rail: retried there
    }
    if (tl) {
      // Recorded AFTER the send resolved so the event names the rail
      // the chunk actually traveled; b packs (rail << 48 | offset) —
      // totals are capped at kMaxStripeTotal (3GB), far inside 48 bits.
      timeline::record(timeline::kStripeSend, cm.stripe_id,
                       (tl_rail << 48) | cm.stripe_offset);
    }
  }
  hotpath_vars().stripe_tx_chunks << static_cast<int64_t>(nchunks);
  hotpath_vars().stripe_tx_bytes << static_cast<int64_t>(total);
  return 0;
}

void stripe_on_head(InputMessage&& msg) {
  maybe_gc();
  hotpath_vars().stripe_rx_chunks << 1;
  hotpath_vars().stripe_rx_bytes
      << static_cast<int64_t>(msg.payload.size());
  const uint64_t id = msg.meta.stripe_id;
  const uint64_t total = msg.meta.stripe_total;
  const uint64_t off = msg.meta.stripe_offset;
  const uint64_t len = msg.payload.size();
  std::shared_ptr<StripeEntry> e =
      admit_chunk(id, total, off, len, msg.socket);
  if (e == nullptr) {
    LOG(Warning) << "stripe head dropped (id=" << id << " total=" << total
                 << " len=" << len << ")";
    return;
  }
  {
    std::lock_guard<std::mutex> g(e->mu);
    e->have_head = true;
    e->head_meta = std::move(msg.meta);
    e->head_socket = msg.socket;
  }
  enqueue_land(std::move(e), std::move(msg.payload), off);
}

void stripe_on_chunk(InputMessage&& msg) {
  maybe_gc();
  hotpath_vars().stripe_rx_chunks << 1;
  hotpath_vars().stripe_rx_bytes
      << static_cast<int64_t>(msg.payload.size());
  const uint64_t off = msg.meta.stripe_offset;
  std::shared_ptr<StripeEntry> e =
      admit_chunk(msg.meta.stripe_id, msg.meta.stripe_total, off,
                  msg.payload.size(), msg.socket);
  if (e == nullptr) {
    return;  // expired/foreign stripe: drop; the call times out whole
  }
  enqueue_land(std::move(e), std::move(msg.payload), off);
}

void stripe_register_landing(uint64_t cid, void* buf, size_t cap) {
  {
    std::lock_guard<std::mutex> g(map_mu());
    landings()[cid] = LandingReg{buf, cap, nullptr};
  }
  // One registration surface for both landing paths (net/rma.h): when
  // the buffer is an exportable rma region, bind it so the request can
  // advertise a genuine remote-write target; otherwise only the striped
  // copy path above catches the response.
  rma_landing_bind(cid, buf, cap);
}

void stripe_unregister_landing(uint64_t cid) {
  // Unbind FIRST: a control frame arriving after this point must reject
  // (use-after-unregister), not resolve into a buffer being recycled.
  rma_landing_unbind(cid);
  std::shared_ptr<StripeEntry> e;
  {
    std::lock_guard<std::mutex> g(map_mu());
    auto it = landings().find(cid);
    if (it == landings().end()) {
      return;
    }
    e = std::move(it->second.entry);
    landings().erase(it);
    if (e != nullptr && entries().count(e->id) != 0) {
      // Incomplete reassembly into the caller's buffer: orphan it so a
      // late chunk re-creates an arena-backed entry instead.
      e->abandoned.store(true, std::memory_order_release);
      drop_entry_locked(e);
    }
  }
  if (e == nullptr || !e->caller_buf) {
    return;
  }
  // The buffer may be recycled the moment we return: wait out any lander
  // already counted in (bounded by one chunk memcpy each).
  while (e->landers.load(std::memory_order_acquire) != 0) {
    if (in_fiber()) {
      fiber_sleep_us(50);
    } else {
      usleep(50);
    }
  }
}

void stripe_gc(int64_t now_us) {
  const int64_t timeout_us =
      flag_value(reassembly_timeout_flag(), 30000) * 1000;
  std::vector<std::shared_ptr<StripeEntry>> dead;
  {
    std::lock_guard<std::mutex> g(map_mu());
    auto& m = entries();
    for (auto it = m.begin(); it != m.end();) {
      StripeEntry& e = *it->second;
      // Acquire/release on abandoned: the release store publishes the
      // expiry decision to landers (land_job_run's acquire); relaxed on
      // the byte counter — accounting only.
      if (e.abandoned.load(std::memory_order_acquire) ||
          now_us - e.created_us > timeout_us) {
        e.abandoned.store(true, std::memory_order_release);
        g_pending_bytes.fetch_sub(e.total, std::memory_order_relaxed);
        dead.push_back(it->second);
        it = m.erase(it);
      } else {
        ++it;
      }
    }
  }
  if (!dead.empty()) {
    hotpath_vars().stripe_expired << static_cast<int64_t>(dead.size());
  }
}

size_t stripe_pending_reassemblies() {
  std::lock_guard<std::mutex> g(map_mu());
  return entries().size();
}

}  // namespace trpc
