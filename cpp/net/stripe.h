// Large-message striping — the multi-rail data path for payloads above a
// reloadable threshold.
//
// Parity: fabric-lib (arxiv 2510.27656) stripes LLM-scale point-to-point
// transfers across multiple rails/QPs to saturate links, and brpc's
// pooled-connection matrix exists for exactly the per-payload-exclusive-
// connection reason; this layer combines the two: one logical
// request/response is cut into K chunk frames issued CONCURRENTLY across
// the pooled connection set (per-rail FIFO preserved, cross-rail order
// free), and the receiver scatters each chunk straight into a single
// preallocated contiguous landing buffer via offset-addressed writes,
// with the per-chunk memcpy fanned out over worker fibers instead of
// serializing on the parse fiber.
//
// Wire shape (net/protocol.h): the HEAD frame is a normal
// kRequest/kResponse whose meta carries {stripe_id, stripe_total} and
// whose payload is chunk 0; the remaining chunks ride kStripe frames
// addressed by stripe_id + stripe_offset, each individually
// crc32c-checksummed when the call asked for checksums.  Sub-threshold
// messages never touch any of this — same wait-free inline-write path,
// byte-identical frames.
//
// Failure semantics: a dropped/truncated chunk either kills its
// connection (parser-level corruption) or simply never lands; the
// reassembly entry expires after trpc_stripe_reassembly_timeout_ms and
// the CALL fails as a whole (client timeout), never with a partial
// payload.  A rail whose socket died at send time retries its chunk on
// the primary connection; only a primary failure fails the send.
#pragma once

#include <cstdint>
#include <vector>

#include "base/iobuf.h"
#include "net/deadline.h"
#include "net/protocol.h"

namespace trpc {

// -- sending ---------------------------------------------------------------

// True when a payload of n bytes should be striped: the reloadable
// trpc_stripe_threshold flag is nonzero, n exceeds it, and n fits a
// single landing block (< 3GB; larger bodies fall back to one frame).
bool stripe_eligible(uint64_t n);

// Chunk size currently configured (trpc_stripe_chunk_bytes).
uint64_t stripe_chunk_bytes();

// Rails to spread chunks over (trpc_stripe_rails, including the primary).
int stripe_rails();

// Nonzero random stripe id for a REQUEST.  (Responses reuse the call's
// correlation id, which is unique in the client process doing the
// reassembly — and lets a registered caller buffer catch chunks that
// arrive before the head frame.)
uint64_t stripe_make_id();

// The one striping decision, shared by client (channel.cc) and server
// (server.cc): eligible size, no stream-establishment piggyback on the
// frame, and not an ICI ring — ICI payloads ride sender-owned zero-copy
// descriptors over a 32-slot SQ (already multi-slot pipelining), and
// chunking would trade descriptors for per-chunk landing copies.  The
// socket-mode probe runs only for above-threshold bodies.
bool stripe_should(SocketId primary, uint64_t stream_id,
                   uint64_t body_bytes);

// Single-frame fallback shared by both sides: whole-body crc32c when
// meta.has_checksum, pack, write on primary.  Returns 0 when accepted.
int stripe_frame_send(SocketId primary, RpcMeta&& meta, IOBuf&& body);

// Sends meta+body as head + kStripe chunks.  rails lists the candidate
// connections (may include primary; may be empty = primary only); chunks
// round-robin over them, and any chunk whose rail is dead reroutes to
// the primary.  meta's stripe fields are filled here; with
// meta.has_checksum each frame carries the crc32c of ITS OWN payload
// (verified per frame by the receiving parser).  Returns 0 when every
// frame was accepted by a write queue.  tok (net/deadline.h): polled
// between chunk frames — a cancelled caller / expired budget stops
// cutting, the receiver's partial reassembly expires whole (reassembly
// timeout), and the skipped bytes count as cancel_saved_bytes.
int stripe_send(SocketId primary, const std::vector<SocketId>& rails,
                RpcMeta&& meta, IOBuf&& body, uint64_t stripe_id,
                const DeadlineToken& tok = DeadlineToken{});

// -- receiving (messenger hooks) ------------------------------------------

// A parsed HEAD frame (kRequest/kResponse with stripe_id != 0).
void stripe_on_head(InputMessage&& msg);
// A parsed kStripe chunk frame.
void stripe_on_chunk(InputMessage&& msg);

// Rails a reassembled REQUEST arrived over, published to the server so
// its response stripes back across the same connections.  Carried via
// InputMessage::ctx.
struct StripeArrival {
  std::vector<SocketId> rails;
};

// -- caller-buffer landing (Python batch plane) ---------------------------

// Registers a caller-owned buffer as the landing destination for the
// striped RESPONSE of call `cid`: chunks memcpy straight into it (no
// arena bounce, no extra copy at the Python boundary).  Also a thin
// wrapper over rma_landing_bind (net/rma.h): a buffer that is itself an
// exportable rma region is additionally EXPORTED, so the request can
// advertise it and the server's one-sided put lands the response with
// zero receiver-side copies.  The buffer must stay valid until
// stripe_unregister_landing(cid) returns.
void stripe_register_landing(uint64_t cid, void* buf, size_t cap);
// Idempotent.  Blocks (bounded: at most one in-flight chunk memcpy per
// lander fiber) until no lander can touch the buffer again.
void stripe_unregister_landing(uint64_t cid);

// -- maintenance / introspection ------------------------------------------

// Expires reassembly entries older than trpc_stripe_reassembly_timeout_ms
// (also run lazily from the receive hooks, ~1/s).
void stripe_gc(int64_t now_us);
// Live (incomplete) reassemblies — tests and /vars.
size_t stripe_pending_reassemblies();

}  // namespace trpc
