// TCP transport (parity: the fork's TcpTransport,
// /root/reference/src/brpc/tcp_transport.cpp:42-104 — writev scatter-gather
// from IOBuf refs; connect parks the calling fiber on the writable edge).
#include <errno.h>
#include <sys/socket.h>

#include "base/time.h"
#include "net/socket.h"
#include "net/transport.h"

namespace trpc {

namespace {

class TcpTransport final : public Transport {
 public:
  ssize_t cut_from_iobuf(Socket* s, IOBuf* from) override {
    const ssize_t rc = from->cut_into_fd(s->fd());
    if (rc < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      return 0;
    }
    return rc;
  }

  ssize_t append_to_iobuf(Socket* s, IOBuf* to, size_t max) override {
    // Bulk hint from the parser: the frame's known remainder sizes the
    // fresh blocks, so a multi-MB body arrives in a few contiguous
    // blocks (one iovec each) instead of thousands of 8KB slivers.
    const ssize_t rc = to->append_from_fd(s->fd(), max, s->read_block_hint);
    if (rc < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      return 0;
    }
    if (rc == 0) {
      errno = 0;  // orderly EOF
      return -1;
    }
    return rc;
  }

  int connect(Socket* s) override {
    // One storage, two families: the remote's flavor picks the sockaddr.
    sockaddr_storage ss = {};
    socklen_t sa_len;
    if (s->remote().is_unix()) {
      sockaddr_un su = endpoint2sockaddr_un(s->remote());
      memcpy(&ss, &su, sizeof(su));
      sa_len = sizeof(su);
    } else {
      sockaddr_in si = endpoint2sockaddr(s->remote());
      memcpy(&ss, &si, sizeof(si));
      sa_len = sizeof(si);
    }
    while (true) {
      const uint32_t snap = s->writable_snap();
      const int rc =
          ::connect(s->fd(), reinterpret_cast<sockaddr*>(&ss), sa_len);
      if (rc == 0) {
        return 0;
      }
      if (errno == EISCONN) {
        return 0;
      }
      if (errno == EINPROGRESS || errno == EALREADY) {
        // Park until the writable edge, then re-check with SO_ERROR.
        s->wait_writable(snap, monotonic_time_us() + 10 * 1000 * 1000);
        int err = 0;
        socklen_t len = sizeof(err);
        if (getsockopt(s->fd(), SOL_SOCKET, SO_ERROR, &err, &len) == 0 &&
            err == 0) {
          int probe = ::connect(s->fd(), reinterpret_cast<sockaddr*>(&ss),
                                sa_len);
          if (probe == 0 || errno == EISCONN) {
            return 0;
          }
          continue;
        }
        errno = err != 0 ? err : ETIMEDOUT;
        return -1;
      }
      if (errno == EINTR) {
        continue;
      }
      return -1;
    }
  }

  const char* name() const override { return "tcp"; }
};

}  // namespace

Transport* tcp_transport() {
  static TcpTransport t;
  return &t;
}

}  // namespace trpc
