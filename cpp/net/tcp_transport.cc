// TCP transport (parity: the fork's TcpTransport,
// /root/reference/src/brpc/tcp_transport.cpp:42-104 — writev scatter-gather
// from IOBuf refs; connect parks the calling fiber on the writable edge).
#include <errno.h>
#include <sys/socket.h>

#include "base/time.h"
#include "net/socket.h"
#include "net/transport.h"

namespace trpc {

namespace {

class TcpTransport final : public Transport {
 public:
  ssize_t cut_from_iobuf(Socket* s, IOBuf* from) override {
    const ssize_t rc = from->cut_into_fd(s->fd());
    if (rc < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      return 0;
    }
    return rc;
  }

  ssize_t append_to_iobuf(Socket* s, IOBuf* to, size_t max) override {
    // Bulk hint from the parser: the frame's known remainder sizes the
    // fresh blocks, so a multi-MB body arrives in a few contiguous
    // blocks (one iovec each) instead of thousands of 8KB slivers.
    const ssize_t rc = to->append_from_fd(s->fd(), max, s->read_block_hint);
    if (rc < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      return 0;
    }
    if (rc == 0) {
      errno = 0;  // orderly EOF
      return -1;
    }
    return rc;
  }

  int connect(Socket* s) override {
    // One storage, two families: the remote's flavor picks the sockaddr.
    sockaddr_storage ss = {};
    socklen_t sa_len;
    if (s->remote().is_unix()) {
      sockaddr_un su = endpoint2sockaddr_un(s->remote());
      memcpy(&ss, &su, sizeof(su));
      sa_len = sizeof(su);
    } else {
      sockaddr_in si = endpoint2sockaddr(s->remote());
      memcpy(&ss, &si, sizeof(si));
      sa_len = sizeof(si);
    }
    while (true) {
      const uint32_t snap = s->writable_snap();
      const int rc =
          ::connect(s->fd(), reinterpret_cast<sockaddr*>(&ss), sa_len);
      if (rc == 0) {
        return 0;
      }
      if (errno == EISCONN) {
        return 0;
      }
      if (errno == EINPROGRESS || errno == EALREADY) {
        // Completion loop: once the handshake is in flight we only ever
        // park + probe — NEVER re-issue ::connect.  Probing completion
        // with getpeername instead of a second ::connect matters twice
        // over (ISSUE 7): connect() on an ESTABLISHED fd performs
        // fd-context writes that race the read fiber's first readv at
        // the TSan interceptor level (the exact report the old blanket
        // ensure_connected suppression papered over), while getpeername
        // succeeds iff the handshake completed (ENOTCONN while still in
        // flight) and writes nothing.
        // One overall 10s application deadline for the whole handshake —
        // re-arming it per park would wait out the kernel's ~2min SYN
        // retry ladder against a blackholed peer.
        const int64_t deadline_us = monotonic_time_us() + 10 * 1000 * 1000;
        uint32_t wsnap = snap;
        while (true) {
          const int wait_rc = s->wait_writable(wsnap, deadline_us);
          wsnap = s->writable_snap();  // re-arm before the next probe
          int err = 0;
          socklen_t len = sizeof(err);
          if (getsockopt(s->fd(), SOL_SOCKET, SO_ERROR, &err, &len) != 0 ||
              err != 0) {
            errno = err != 0 ? err : ETIMEDOUT;
            return -1;
          }
          sockaddr_storage peer;
          socklen_t plen = sizeof(peer);
          if (getpeername(s->fd(), reinterpret_cast<sockaddr*>(&peer),
                          &plen) == 0) {
            return 0;
          }
          if (errno != ENOTCONN) {
            return -1;
          }
          if (wait_rc == ETIMEDOUT) {
            errno = ETIMEDOUT;
            return -1;
          }
          // Spurious wake before establishment: park again; the kernel
          // surfaces a failed handshake through SO_ERROR above.
        }
      }
      if (errno == EINTR) {
        continue;
      }
      return -1;
    }
  }

  const char* name() const override { return "tcp"; }
};

}  // namespace

Transport* tcp_transport() {
  static TcpTransport t;
  return &t;
}

}  // namespace trpc
