#include "net/thrift.h"

#include <errno.h>

#include <cstring>
#include <deque>
#include <memory>
#include <mutex>

#include "base/logging.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/messenger.h"
#include "net/protocol.h"
#include "net/server.h"

namespace trpc {

namespace {

constexpr uint32_t kVersion1 = 0x80010000u;
constexpr uint32_t kVersionMask = 0xffff0000u;
constexpr size_t kMaxFrame = 64ull << 20;
constexpr size_t kMaxMethod = 1024;
constexpr size_t kMaxElements = 1 << 20;
constexpr int kMaxDepth = 32;
// Total decoded values per message: each ThriftValue costs ~150 host
// bytes, so per-container caps alone allow ~128x amplification from one
// pre-auth frame (a 64MB frame of 1-byte elements -> ~9GB).  The global
// budget bounds decode memory to ~150MB worst case.
constexpr size_t kMaxTotalValues = 1 << 20;

void put_u8(std::string* out, uint8_t v) {
  out->push_back(static_cast<char>(v));
}

void put_u16(std::string* out, uint16_t v) {
  out->push_back(static_cast<char>(v >> 8));
  out->push_back(static_cast<char>(v));
}

void put_u32(std::string* out, uint32_t v) {
  out->push_back(static_cast<char>(v >> 24));
  out->push_back(static_cast<char>(v >> 16));
  out->push_back(static_cast<char>(v >> 8));
  out->push_back(static_cast<char>(v));
}

void put_u64(std::string* out, uint64_t v) {
  put_u32(out, static_cast<uint32_t>(v >> 32));
  put_u32(out, static_cast<uint32_t>(v));
}

bool get_bytes(std::string_view in, size_t* pos, size_t n, void* dst) {
  if (in.size() - *pos < n) return false;
  std::memcpy(dst, in.data() + *pos, n);
  *pos += n;
  return true;
}

bool get_u8(std::string_view in, size_t* pos, uint8_t* v) {
  return get_bytes(in, pos, 1, v);
}

bool get_u16(std::string_view in, size_t* pos, uint16_t* v) {
  uint8_t b[2];
  if (!get_bytes(in, pos, 2, b)) return false;
  *v = static_cast<uint16_t>((b[0] << 8) | b[1]);
  return true;
}

bool get_u32(std::string_view in, size_t* pos, uint32_t* v) {
  uint8_t b[4];
  if (!get_bytes(in, pos, 4, b)) return false;
  *v = (static_cast<uint32_t>(b[0]) << 24) |
       (static_cast<uint32_t>(b[1]) << 16) |
       (static_cast<uint32_t>(b[2]) << 8) | b[3];
  return true;
}

bool get_u64(std::string_view in, size_t* pos, uint64_t* v) {
  uint32_t hi, lo;
  if (!get_u32(in, pos, &hi) || !get_u32(in, pos, &lo)) return false;
  *v = (static_cast<uint64_t>(hi) << 32) | lo;
  return true;
}

bool valid_ttype(uint8_t t) {
  switch (static_cast<TType>(t)) {
    case TType::kBool:
    case TType::kByte:
    case TType::kDouble:
    case TType::kI16:
    case TType::kI32:
    case TType::kI64:
    case TType::kString:
    case TType::kStruct:
    case TType::kMap:
    case TType::kSet:
    case TType::kList:
      return true;
    default:
      return false;
  }
}

}  // namespace

// ---- builders ------------------------------------------------------------

ThriftValue ThriftValue::Bool(bool v) {
  ThriftValue t;
  t.type = TType::kBool;
  t.b = v;
  return t;
}
ThriftValue ThriftValue::Byte(int8_t v) {
  ThriftValue t;
  t.type = TType::kByte;
  t.i = v;
  return t;
}
ThriftValue ThriftValue::I16(int16_t v) {
  ThriftValue t;
  t.type = TType::kI16;
  t.i = v;
  return t;
}
ThriftValue ThriftValue::I32(int32_t v) {
  ThriftValue t;
  t.type = TType::kI32;
  t.i = v;
  return t;
}
ThriftValue ThriftValue::I64(int64_t v) {
  ThriftValue t;
  t.type = TType::kI64;
  t.i = v;
  return t;
}
ThriftValue ThriftValue::Double(double v) {
  ThriftValue t;
  t.type = TType::kDouble;
  t.d = v;
  return t;
}
ThriftValue ThriftValue::Str(std::string s) {
  ThriftValue t;
  t.type = TType::kString;
  t.str = std::move(s);
  return t;
}
ThriftValue ThriftValue::Struct() {
  ThriftValue t;
  t.type = TType::kStruct;
  return t;
}
ThriftValue ThriftValue::List(TType elem) {
  ThriftValue t;
  t.type = TType::kList;
  t.elem_type = elem;
  return t;
}
ThriftValue ThriftValue::Set(TType elem) {
  ThriftValue t;
  t.type = TType::kSet;
  t.elem_type = elem;
  return t;
}
ThriftValue ThriftValue::Map(TType key, TType val) {
  ThriftValue t;
  t.type = TType::kMap;
  t.key_type = key;
  t.val_type = val;
  return t;
}

ThriftValue& ThriftValue::add_field(int16_t id, ThriftValue v) {
  fields.emplace_back(id, std::move(v));
  return *this;
}

const ThriftValue* ThriftValue::field(int16_t id) const {
  for (const auto& [fid, v] : fields) {
    if (fid == id) return &v;
  }
  return nullptr;
}

bool ThriftValue::operator==(const ThriftValue& o) const {
  if (type != o.type) return false;
  switch (type) {
    case TType::kBool:
      return b == o.b;
    case TType::kByte:
    case TType::kI16:
    case TType::kI32:
    case TType::kI64:
      return i == o.i;
    case TType::kDouble:
      return d == o.d;
    case TType::kString:
      return str == o.str;
    case TType::kStruct:
      return fields == o.fields;
    case TType::kList:
    case TType::kSet:
      return elem_type == o.elem_type && elems == o.elems;
    case TType::kMap:
      return key_type == o.key_type && val_type == o.val_type &&
             kvs == o.kvs;
    default:
      return true;
  }
}

// ---- codec ---------------------------------------------------------------

void thrift_write_value(const ThriftValue& v, std::string* out) {
  switch (v.type) {
    case TType::kBool:
      put_u8(out, v.b ? 1 : 0);
      break;
    case TType::kByte:
      put_u8(out, static_cast<uint8_t>(v.i));
      break;
    case TType::kI16:
      put_u16(out, static_cast<uint16_t>(v.i));
      break;
    case TType::kI32:
      put_u32(out, static_cast<uint32_t>(v.i));
      break;
    case TType::kI64:
      put_u64(out, static_cast<uint64_t>(v.i));
      break;
    case TType::kDouble: {
      uint64_t bits;
      std::memcpy(&bits, &v.d, 8);
      put_u64(out, bits);
      break;
    }
    case TType::kString:
      put_u32(out, static_cast<uint32_t>(v.str.size()));
      out->append(v.str);
      break;
    case TType::kStruct:
      for (const auto& [fid, fv] : v.fields) {
        put_u8(out, static_cast<uint8_t>(fv.type));
        put_u16(out, static_cast<uint16_t>(fid));
        thrift_write_value(fv, out);
      }
      put_u8(out, 0);  // STOP
      break;
    case TType::kMap:
      put_u8(out, static_cast<uint8_t>(v.key_type));
      put_u8(out, static_cast<uint8_t>(v.val_type));
      put_u32(out, static_cast<uint32_t>(v.kvs.size()));
      for (const auto& [k, val] : v.kvs) {
        thrift_write_value(k, out);
        thrift_write_value(val, out);
      }
      break;
    case TType::kSet:
    case TType::kList:
      put_u8(out, static_cast<uint8_t>(v.elem_type));
      put_u32(out, static_cast<uint32_t>(v.elems.size()));
      for (const ThriftValue& e : v.elems) {
        thrift_write_value(e, out);
      }
      break;
    default:
      break;
  }
}

namespace {

int read_value_impl(std::string_view in, size_t* pos, TType t,
                    ThriftValue* out, int depth, size_t* budget) {
  if (depth > kMaxDepth) return -1;
  if (*budget == 0) return -1;  // total-values bound (see kMaxTotalValues)
  --*budget;
  out->type = t;
  switch (t) {
    case TType::kBool: {
      uint8_t v;
      if (!get_u8(in, pos, &v)) return 0;
      out->b = v != 0;
      return 1;
    }
    case TType::kByte: {
      uint8_t v;
      if (!get_u8(in, pos, &v)) return 0;
      out->i = static_cast<int8_t>(v);
      return 1;
    }
    case TType::kI16: {
      uint16_t v;
      if (!get_u16(in, pos, &v)) return 0;
      out->i = static_cast<int16_t>(v);
      return 1;
    }
    case TType::kI32: {
      uint32_t v;
      if (!get_u32(in, pos, &v)) return 0;
      out->i = static_cast<int32_t>(v);
      return 1;
    }
    case TType::kI64: {
      uint64_t v;
      if (!get_u64(in, pos, &v)) return 0;
      out->i = static_cast<int64_t>(v);
      return 1;
    }
    case TType::kDouble: {
      uint64_t bits;
      if (!get_u64(in, pos, &bits)) return 0;
      std::memcpy(&out->d, &bits, 8);
      return 1;
    }
    case TType::kString: {
      uint32_t len;
      if (!get_u32(in, pos, &len)) return 0;
      if (len > kMaxFrame) return -1;
      if (in.size() - *pos < len) return 0;
      out->str.assign(in.data() + *pos, len);
      *pos += len;
      return 1;
    }
    case TType::kStruct: {
      out->fields.clear();
      while (true) {
        uint8_t ft;
        if (!get_u8(in, pos, &ft)) return 0;
        if (ft == 0) return 1;  // STOP
        if (!valid_ttype(ft)) return -1;
        uint16_t fid;
        if (!get_u16(in, pos, &fid)) return 0;
        ThriftValue fv;
        int rc = read_value_impl(in, pos, static_cast<TType>(ft), &fv,
                                 depth + 1, budget);
        if (rc != 1) return rc;
        out->fields.emplace_back(static_cast<int16_t>(fid),
                                 std::move(fv));
      }
    }
    case TType::kMap: {
      uint8_t kt, vt;
      uint32_t n;
      if (!get_u8(in, pos, &kt) || !get_u8(in, pos, &vt) ||
          !get_u32(in, pos, &n)) {
        return 0;
      }
      if (n > kMaxElements) return -1;
      if (n > 0 && (!valid_ttype(kt) || !valid_ttype(vt))) return -1;
      out->key_type = static_cast<TType>(kt);
      out->val_type = static_cast<TType>(vt);
      out->kvs.clear();
      for (uint32_t i = 0; i < n; ++i) {
        ThriftValue k, v;
        int rc = read_value_impl(in, pos, out->key_type, &k, depth + 1,
                                 budget);
        if (rc != 1) return rc;
        rc = read_value_impl(in, pos, out->val_type, &v, depth + 1, budget);
        if (rc != 1) return rc;
        out->kvs.emplace_back(std::move(k), std::move(v));
      }
      return 1;
    }
    case TType::kSet:
    case TType::kList: {
      uint8_t et;
      uint32_t n;
      if (!get_u8(in, pos, &et) || !get_u32(in, pos, &n)) return 0;
      if (n > kMaxElements) return -1;
      if (n > 0 && !valid_ttype(et)) return -1;
      out->elem_type = static_cast<TType>(et);
      out->elems.clear();
      for (uint32_t i = 0; i < n; ++i) {
        ThriftValue e;
        int rc = read_value_impl(in, pos, out->elem_type, &e, depth + 1,
                                 budget);
        if (rc != 1) return rc;
        out->elems.push_back(std::move(e));
      }
      return 1;
    }
    default:
      return -1;
  }
}

}  // namespace

int thrift_read_value(std::string_view in, size_t* pos, TType t,
                      ThriftValue* out, int depth) {
  size_t budget = kMaxTotalValues;
  return read_value_impl(in, pos, t, out, depth, &budget);
}

void thrift_pack_message(const ThriftMessage& m, std::string* out) {
  std::string payload;
  put_u32(&payload, kVersion1 | static_cast<uint32_t>(m.mtype));
  put_u32(&payload, static_cast<uint32_t>(m.method.size()));
  payload.append(m.method);
  put_u32(&payload, m.seq_id);
  thrift_write_value(m.body, &payload);
  put_u32(out, static_cast<uint32_t>(payload.size()));
  out->append(payload);
}

bool thrift_parse_payload(std::string_view payload, ThriftMessage* out) {
  size_t pos = 0;
  uint32_t verw, name_len;
  if (!get_u32(payload, &pos, &verw) || (verw & kVersionMask) != kVersion1) {
    return false;
  }
  out->mtype = static_cast<TMessageType>(verw & 0xff);
  if (!get_u32(payload, &pos, &name_len) || name_len > kMaxMethod ||
      payload.size() - pos < name_len) {
    return false;
  }
  out->method.assign(payload.data() + pos, name_len);
  pos += name_len;
  if (!get_u32(payload, &pos, &out->seq_id)) return false;
  int rc = thrift_read_value(payload, &pos, TType::kStruct, &out->body, 0);
  return rc == 1 && pos == payload.size();
}

// ---- service registry ----------------------------------------------------

bool ThriftService::AddMethodHandler(const std::string& method,
                                     MethodHandler h) {
  return handlers_.emplace(method, std::move(h)).second;
}

const ThriftService::MethodHandler* ThriftService::FindMethodHandler(
    const std::string& method) const {
  auto it = handlers_.find(method);
  return it == handlers_.end() ? nullptr : &it->second;
}

// ---- shared frame cutter -------------------------------------------------

namespace {

// Cuts one complete frame's PAYLOAD into msg->payload.  The 8-byte peek
// (length + version word) is also the probe discriminator.
ParseError cut_thrift_frame(IOBuf* source, InputMessage* out, Socket* sock,
                            bool probing) {
  uint8_t head[8];
  const size_t got = source->copy_to(head, sizeof(head), 0);
  if (got < sizeof(head)) {
    // Not enough to discriminate.  While probing, hold the connection
    // (kNotEnoughData) ONLY if every byte seen so far is still consistent
    // with a thrift frame — returning kTryOtherProtocol on a short
    // fragmented prefix would let the probe loop fall through all
    // protocols and kill a legitimate connection.
    if (probing) {
      if (got >= 1 && head[0] > (kMaxFrame >> 24)) {
        return ParseError::kTryOtherProtocol;
      }
      if (got >= 5 && head[4] != 0x80) return ParseError::kTryOtherProtocol;
      if (got >= 6 && head[5] != 0x01) return ParseError::kTryOtherProtocol;
    }
    return ParseError::kNotEnoughData;
  }
  const uint32_t frame_len = (static_cast<uint32_t>(head[0]) << 24) |
                             (static_cast<uint32_t>(head[1]) << 16) |
                             (static_cast<uint32_t>(head[2]) << 8) |
                             head[3];
  const bool versioned = head[4] == 0x80 && head[5] == 0x01;
  if (probing && (!versioned || frame_len > kMaxFrame || frame_len < 12)) {
    return ParseError::kTryOtherProtocol;
  }
  if (!versioned || frame_len > kMaxFrame || frame_len < 12) {
    return ParseError::kCorrupted;
  }
  if (source->size() < 4u + frame_len) {
    return ParseError::kNotEnoughData;
  }
  source->pop_front(4);
  source->cutn(&out->payload, frame_len);
  out->socket = sock != nullptr ? sock->id() : 0;
  return ParseError::kOk;
}

// ---- server protocol -----------------------------------------------------

ParseError thrift_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  const bool probing = sock->pinned_protocol < 0;
  if (probing) {
    Server* srv = static_cast<Server*>(sock->user_data);
    if (srv == nullptr || srv->thrift_service() == nullptr) {
      return ParseError::kTryOtherProtocol;
    }
  }
  return cut_thrift_frame(source, out, sock, probing);
}

void thrift_respond(Socket* sock, const ThriftMessage& m) {
  std::string wire;
  thrift_pack_message(m, &wire);
  IOBuf out;
  out.append(wire);
  sock->Write(std::move(out));
}

ThriftMessage make_app_exception(const std::string& method, uint32_t seq,
                                 int32_t type, const std::string& text) {
  // TApplicationException struct: 1=message string, 2=type i32.
  ThriftMessage m;
  m.mtype = TMessageType::kException;
  m.method = method;
  m.seq_id = seq;
  m.body = ThriftValue::Struct();
  m.body.add_field(1, ThriftValue::Str(text));
  m.body.add_field(2, ThriftValue::I32(type));
  return m;
}

constexpr int32_t kUnknownMethod = 1;   // TApplicationException codes
constexpr int32_t kInternalError = 6;

// Runs in its own fiber (frames carry seq ids; requests may interleave).
void thrift_process_request(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  Server* srv = static_cast<Server*>(sock->user_data);
  if (srv == nullptr || srv->thrift_service() == nullptr) {
    return;
  }
  std::string payload;
  payload.resize(msg.payload.size());
  msg.payload.copy_to(payload.data(), payload.size(), 0);
  ThriftMessage req;
  if (!thrift_parse_payload(payload, &req) ||
      (req.mtype != TMessageType::kCall &&
       req.mtype != TMessageType::kOneway)) {
    sock->SetFailed(EPROTO);
    return;
  }
  const bool oneway = req.mtype == TMessageType::kOneway;

  {  // Interceptor gate (same body as every serving protocol).
    int ec = 0;
    std::string et;
    if (!srv->accept_request(req.method, sock->remote(), &ec, &et)) {
      if (!oneway) {
        thrift_respond(sock.get(), make_app_exception(
                                       req.method, req.seq_id,
                                       kInternalError, et));
      }
      return;
    }
  }

  const ThriftService::MethodHandler* h =
      srv->thrift_service()->FindMethodHandler(req.method);
  if (h == nullptr) {
    if (!oneway) {
      thrift_respond(sock.get(),
                     make_app_exception(req.method, req.seq_id,
                                        kUnknownMethod,
                                        "Unknown method " + req.method));
    }
    return;
  }
  std::string app_error;
  ThriftValue result = (*h)(req.body, &app_error);
  srv->requests_served.fetch_add(1, std::memory_order_relaxed);
  if (oneway) {
    return;
  }
  if (!app_error.empty()) {
    thrift_respond(sock.get(), make_app_exception(req.method, req.seq_id,
                                                  kInternalError,
                                                  app_error));
    return;
  }
  ThriftMessage rsp;
  rsp.mtype = TMessageType::kReply;
  rsp.method = req.method;
  rsp.seq_id = req.seq_id;
  rsp.body = std::move(result);
  thrift_respond(sock.get(), rsp);
}

void thrift_process_response(InputMessage&&) {}

}  // namespace

void register_thrift_protocol() {
  static int once = [] {
    Protocol p = {"thrift", thrift_parse, thrift_process_request,
                  thrift_process_response,
                  /*process_in_order=*/false};
    return register_protocol(p);
  }();
  (void)once;
}

// ---- client --------------------------------------------------------------

namespace {

struct ThriftWaiter {
  CountdownEvent ev{1};
  uint32_t seq = 0;
  ThriftClient::Result result;
};

// Replies correlate by seq id (the server runs requests in parallel
// fibers, so wire order is NOT call order — unlike redis's FIFO).
struct ThriftCliConn {
  std::mutex mu;
  std::map<uint32_t, std::shared_ptr<ThriftWaiter>> pending;
};

const char kThriftCliTag = 0;

ThriftCliConn* tcli_conn_of(Socket* s) {
  return proto_conn_of<ThriftCliConn>(s, &kThriftCliTag);
}

ParseError thriftc_parse(IOBuf* source, InputMessage* out, Socket* sock) {
  if (sock == nullptr || source->empty()) {
    return ParseError::kNotEnoughData;
  }
  if (sock->pinned_protocol < 0) {
    return ParseError::kTryOtherProtocol;  // client sockets are pre-pinned
  }
  ParseError rc = cut_thrift_frame(source, out, sock, /*probing=*/false);
  if (rc == ParseError::kOk) {
    out->meta.type = RpcMeta::kResponse;
  }
  return rc;
}

// Inline in the read fiber: replies resolve their seq-keyed waiter.
void thriftc_process_response(InputMessage&& msg) {
  SocketRef sock(Socket::Address(msg.socket));
  if (!sock) {
    return;
  }
  std::string payload;
  payload.resize(msg.payload.size());
  msg.payload.copy_to(payload.data(), payload.size(), 0);
  ThriftMessage rsp;
  const bool parsed = thrift_parse_payload(payload, &rsp);

  ThriftCliConn* c = tcli_conn_of(sock.get());
  if (!parsed) {
    // Framing survived but the payload didn't decode: the stream itself
    // is suspect — fail every in-flight call and the connection.
    std::map<uint32_t, std::shared_ptr<ThriftWaiter>> orphans;
    {
      std::lock_guard<std::mutex> g(c->mu);
      orphans.swap(c->pending);
    }
    for (auto& [seq, ow] : orphans) {
      ow->result.error = "malformed reply";
      ow->ev.signal();
    }
    sock->SetFailed(EPROTO);
    return;
  }
  std::shared_ptr<ThriftWaiter> w;
  {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->pending.find(rsp.seq_id);
    if (it == c->pending.end()) {
      return;  // unsolicited / timed-out seq
    }
    w = std::move(it->second);
    c->pending.erase(it);
  }
  if (rsp.mtype == TMessageType::kException) {
    const ThriftValue* text = rsp.body.field(1);
    w->result.error = text != nullptr && text->type == TType::kString
                          ? text->str
                          : "application exception";
  } else if (rsp.mtype != TMessageType::kReply) {
    w->result.error = "unexpected mtype";
  } else {
    w->result.ok = true;
    w->result.result = std::move(rsp.body);
  }
  w->ev.signal();
}

void thriftc_process_request(InputMessage&&) {}

int thriftc_protocol_index() {
  static const int index = [] {
    Protocol p = {"thriftc", thriftc_parse, thriftc_process_request,
                  thriftc_process_response,
                  /*process_in_order=*/true};
    return register_protocol(p);
  }();
  return index;
}

}  // namespace

ThriftClient::~ThriftClient() {
  csock_.Shutdown();
}

namespace {
int install_thrift_conn(Socket* s) {
  tcli_conn_of(s);  // install state while single-threaded
  return 0;
}
}  // namespace

int ThriftClient::Init(const std::string& addr, const Options* opts) {
  fiber_init(0);
  if (opts != nullptr) {
    opts_ = *opts;
  }
  thriftc_protocol_index();
  return csock_.Init(addr);
}

ThriftClient::Result ThriftClient::call(const std::string& method,
                                        const ThriftValue& args) {
  Result fail;
  ThriftMessage m;
  m.mtype = TMessageType::kCall;
  m.method = method;
  m.body = args;

  SocketId sid = 0;
  std::shared_ptr<ThriftWaiter> w = std::make_shared<ThriftWaiter>();
  {
    LockGuard<FiberMutex> g(sock_mu_);
    if (csock_.ensure(thriftc_protocol_index(), install_thrift_conn,
                      &sid) != 0) {
      fail.error = "cannot reach " + endpoint2str(csock_.endpoint());
      return fail;
    }
    m.seq_id = next_seq_++;
  }
  w->seq = m.seq_id;
  SocketRef s(Socket::Address(sid));
  if (!s) {
    fail.error = "connection failed";
    return fail;
  }
  ThriftCliConn* c = tcli_conn_of(s.get());
  std::string wire;
  thrift_pack_message(m, &wire);
  {
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.emplace(w->seq, w);
  }
  IOBuf frame;
  frame.append(wire);
  if (s->Write(std::move(frame)) != 0) {
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.erase(w->seq);
    fail.error = "write failed";
    return fail;
  }
  const int64_t deadline = monotonic_time_us() + opts_.timeout_ms * 1000;
  if (w->ev.wait(deadline) != 0) {
    std::lock_guard<std::mutex> g(c->mu);
    c->pending.erase(w->seq);  // reclaim the slot; a late reply is dropped
    fail.error = "timeout";
    return fail;
  }
  return std::move(w->result);
}

int ThriftClient::call_oneway(const std::string& method,
                              const ThriftValue& args) {
  ThriftMessage m;
  m.mtype = TMessageType::kOneway;
  m.method = method;
  m.body = args;
  SocketId sid = 0;
  {
    LockGuard<FiberMutex> g(sock_mu_);
    if (csock_.ensure(thriftc_protocol_index(), install_thrift_conn,
                      &sid) != 0) {
      return -1;
    }
    m.seq_id = next_seq_++;
  }
  SocketRef s(Socket::Address(sid));
  if (!s) {
    return -1;
  }
  std::string wire;
  thrift_pack_message(m, &wire);
  IOBuf frame;
  frame.append(wire);
  return s->Write(std::move(frame));
}

}  // namespace trpc
