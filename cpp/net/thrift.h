// Thrift framed-transport protocol (TBinaryProtocol) — server AND client.
//
// Parity: the reference serves and calls thrift framed+binary
// (/root/reference/src/brpc/policy/thrift_protocol.cpp: 4-byte frame
// length, message header 0x8001<<16|mtype + method + seq_id, then a
// TBinary struct; src/brpc/thrift_service.h server vtable).  The
// reference depends on libthrift's generated codecs; this runtime has no
// codegen, so the condensed form models any TBinary value as a variant
// tree (ThriftValue) the way RedisReply models RESP — handlers read
// request args and build result structs field-by-field, which is exactly
// what thrift's generated code does under the hood.
//
// Wire facts implemented (public thrift spec, strict framing only):
//   frame     := u32_be length, payload
//   payload   := u32_be (0x80010000 | mtype) u32_be name_len name
//                u32_be seq_id, struct
//   struct    := { u8 ftype, i16_be fid, value }* then u8 0 (STOP)
//   bool 1B / byte 1B / i16 2B / i32 4B / i64 8B / double 8B (all BE)
//   string    := u32_be len, bytes
//   map       := u8 ktype, u8 vtype, u32_be n, n*(key,value)
//   set/list  := u8 etype, u32_be n, n*elem
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <string>
#include <string_view>
#include <vector>

#include "base/endpoint.h"
#include "base/iobuf.h"
#include "fiber/sync.h"
#include "net/proto_client.h"
#include "net/socket.h"

namespace trpc {

class Server;

// TBinaryProtocol type codes (on-wire values).
enum class TType : uint8_t {
  kStop = 0,
  kBool = 2,
  kByte = 3,
  kDouble = 4,
  kI16 = 6,
  kI32 = 8,
  kI64 = 10,
  kString = 11,
  kStruct = 12,
  kMap = 13,
  kSet = 14,
  kList = 15,
};

// Thrift message types (header mtype).
enum class TMessageType : uint8_t {
  kCall = 1,
  kReply = 2,
  kException = 3,
  kOneway = 4,
};

// One TBinary value.  Struct fields carry ids; containers carry their
// declared element types so empty containers roundtrip byte-exactly.
struct ThriftValue {
  TType type = TType::kStruct;
  bool b = false;
  int64_t i = 0;         // byte / i16 / i32 / i64
  double d = 0;
  std::string str;
  std::vector<std::pair<int16_t, ThriftValue>> fields;       // struct
  std::vector<ThriftValue> elems;                            // list / set
  std::vector<std::pair<ThriftValue, ThriftValue>> kvs;      // map
  TType elem_type = TType::kStop;                            // list / set
  TType key_type = TType::kStop, val_type = TType::kStop;    // map

  static ThriftValue Bool(bool v);
  static ThriftValue Byte(int8_t v);
  static ThriftValue I16(int16_t v);
  static ThriftValue I32(int32_t v);
  static ThriftValue I64(int64_t v);
  static ThriftValue Double(double v);
  static ThriftValue Str(std::string s);
  static ThriftValue Struct();
  static ThriftValue List(TType elem);
  static ThriftValue Set(TType elem);
  static ThriftValue Map(TType key, TType val);

  // Struct helpers.
  ThriftValue& add_field(int16_t id, ThriftValue v);
  const ThriftValue* field(int16_t id) const;  // nullptr when absent

  bool operator==(const ThriftValue& o) const;
};

// ---- codec (exposed for tests + the fuzzer) ------------------------------

// Serializes `v` (value encoding only; structs append their fields + STOP).
void thrift_write_value(const ThriftValue& v, std::string* out);

// Reads one value of wire type `t` at (*pos).  1 ok / 0 partial /
// -1 malformed.  Depth- and size-bounded.
int thrift_read_value(std::string_view in, size_t* pos, TType t,
                      ThriftValue* out, int depth = 0);

// One framed message (without the 4-byte frame length).
struct ThriftMessage {
  TMessageType mtype = TMessageType::kCall;
  std::string method;
  uint32_t seq_id = 0;
  ThriftValue body;  // always a struct
};

// Packs frame length + header + body.
void thrift_pack_message(const ThriftMessage& m, std::string* out);

// Parses a complete frame PAYLOAD (after the length prefix was cut).
// False on malformed input.
bool thrift_parse_payload(std::string_view payload, ThriftMessage* out);

// ---- server side ---------------------------------------------------------

// Method handlers for a thrift-speaking server; assign via
// Server::set_thrift_service.  The handler receives the call's argument
// struct; it returns the RESULT struct (by convention field 0 = success
// value, declared-exception fields > 0) or sets *app_error to reply with
// a TApplicationException.
class ThriftService {
 public:
  using MethodHandler = std::function<ThriftValue(
      const ThriftValue& args, std::string* app_error)>;

  bool AddMethodHandler(const std::string& method, MethodHandler h);
  const MethodHandler* FindMethodHandler(const std::string& method) const;

 private:
  std::map<std::string, MethodHandler> handlers_;
};

// Registers the thrift server protocol (idempotent); Server::Start calls
// it when a thrift_service is installed.
void register_thrift_protocol();

// ---- client side ---------------------------------------------------------

// Framed thrift client with FIFO pipelining (one connection, seq-id
// checked replies — the reference routes thrift through Channel, this
// runtime's per-protocol clients own their socket like RedisClient).
class ThriftClient {
 public:
  struct Options {
    int64_t timeout_ms = 1000;
  };

  struct Result {
    bool ok = false;
    std::string error;    // transport error or TApplicationException text
    ThriftValue result;   // REPLY result struct (field 0 = success)
  };

  ~ThriftClient();
  int Init(const std::string& addr, const Options* opts = nullptr);

  // One call, one reply.
  Result call(const std::string& method, const ThriftValue& args);
  // Fire-and-forget (mtype ONEWAY, no reply expected).
  int call_oneway(const std::string& method, const ThriftValue& args);

 private:
  Options opts_;
  FiberMutex sock_mu_;
  ClientSocket csock_;
  uint32_t next_seq_ = 1;
};

}  // namespace trpc
