#include "net/tls.h"

#include <dlfcn.h>
#include <errno.h>
#include <sys/socket.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>

#include "base/logging.h"
#include "net/socket.h"

namespace trpc {

namespace {

// ---- minimal libssl ABI (OpenSSL 3; headers absent from the image) -------

using SSL = void;
using SSL_CTX = void;
using SSL_METHOD = void;

constexpr int kSslErrorWantRead = 2;
constexpr int kSslErrorWantWrite = 3;
constexpr int kSslErrorZeroReturn = 6;
constexpr int kSslFiletypePem = 1;

struct SslApi {
  const SSL_METHOD* (*TLS_method)();
  SSL_CTX* (*SSL_CTX_new)(const SSL_METHOD*);
  void (*SSL_CTX_free)(SSL_CTX*);
  int (*SSL_CTX_use_certificate_chain_file)(SSL_CTX*, const char*);
  int (*SSL_CTX_use_PrivateKey_file)(SSL_CTX*, const char*, int);
  int (*SSL_CTX_check_private_key)(const SSL_CTX*);
  SSL* (*SSL_new)(SSL_CTX*);
  void (*SSL_free)(SSL*);
  int (*SSL_set_fd)(SSL*, int);
  void (*SSL_set_accept_state)(SSL*);
  void (*SSL_set_connect_state)(SSL*);
  int (*SSL_do_handshake)(SSL*);
  int (*SSL_read)(SSL*, void*, int);
  int (*SSL_write)(SSL*, const void*, int);
  int (*SSL_get_error)(const SSL*, int);
  int (*SSL_shutdown)(SSL*);
  unsigned long (*ERR_get_error)();
  void (*ERR_error_string_n)(unsigned long, char*, size_t);
  void (*ERR_clear_error)();
  // ALPN (ssl_helper.h:89-96 parity).  Optional: absent symbols degrade
  // to no-negotiation (h2 still works via preface probing; strict gRPC
  // clients need these, present in every OpenSSL ≥1.0.2).
  int (*SSL_set_alpn_protos)(SSL*, const unsigned char*, unsigned);
  void (*SSL_CTX_set_alpn_select_cb)(
      SSL_CTX*,
      int (*cb)(SSL*, const unsigned char**, unsigned char*,
                const unsigned char*, unsigned, void*),
      void*);
  void (*SSL_get0_alpn_selected)(const SSL*, const unsigned char**,
                                 unsigned*);
  // SNI: SSL_set_tlsext_host_name is a macro over SSL_ctrl(ssl, 55, 0,
  // name) in every OpenSSL; the raw control call is the stable ABI.
  long (*SSL_ctrl)(SSL*, int, long, void*);
  // mTLS (optional symbols like ALPN).
  int (*SSL_CTX_load_verify_locations)(SSL_CTX*, const char*, const char*);
  void (*SSL_CTX_set_verify)(SSL_CTX*, int,
                             int (*)(int, void*));
  int (*SSL_set1_host)(SSL*, const char*);  // hostname pin (≥1.1.0)

  bool ok = false;
};

constexpr int kSslVerifyPeer = 0x01;
constexpr int kSslVerifyFailIfNoPeerCert = 0x02;

constexpr int kSslCtrlSetTlsextHostname = 55;  // SSL_CTRL_SET_TLSEXT_HOSTNAME

const SslApi& api() {
  static SslApi a = [] {
    SslApi s = {};
    // Soname ladder: 3.x, the dev symlink, then 1.1 (this box ships only
    // libssl.so.1.1 — every symbol SslApi binds is a real function there
    // too, so the 1.1 fallback is fully served).
    void* ssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (ssl == nullptr) {
      ssl = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
    }
    if (ssl == nullptr) {
      ssl = dlopen("libssl.so.1.1", RTLD_NOW | RTLD_GLOBAL);
    }
    // ERR_* live in libcrypto; RTLD_GLOBAL above lets one handle serve,
    // but resolve via an explicit handle as well for robustness.
    void* crypto = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (crypto == nullptr) {
      crypto = dlopen("libcrypto.so.1.1", RTLD_NOW | RTLD_GLOBAL);
    }
    if (ssl == nullptr) {
      return s;
    }
    auto sym = [&](const char* name) -> void* {
      void* p = dlsym(ssl, name);
      if (p == nullptr && crypto != nullptr) {
        p = dlsym(crypto, name);
      }
      return p;
    };
    s.TLS_method =
        reinterpret_cast<const SSL_METHOD* (*)()>(sym("TLS_method"));
    s.SSL_CTX_new =
        reinterpret_cast<SSL_CTX* (*)(const SSL_METHOD*)>(sym("SSL_CTX_new"));
    s.SSL_CTX_free =
        reinterpret_cast<void (*)(SSL_CTX*)>(sym("SSL_CTX_free"));
    s.SSL_CTX_use_certificate_chain_file =
        reinterpret_cast<int (*)(SSL_CTX*, const char*)>(
            sym("SSL_CTX_use_certificate_chain_file"));
    s.SSL_CTX_use_PrivateKey_file =
        reinterpret_cast<int (*)(SSL_CTX*, const char*, int)>(
            sym("SSL_CTX_use_PrivateKey_file"));
    s.SSL_CTX_check_private_key = reinterpret_cast<int (*)(const SSL_CTX*)>(
        sym("SSL_CTX_check_private_key"));
    s.SSL_new = reinterpret_cast<SSL* (*)(SSL_CTX*)>(sym("SSL_new"));
    s.SSL_free = reinterpret_cast<void (*)(SSL*)>(sym("SSL_free"));
    s.SSL_set_fd = reinterpret_cast<int (*)(SSL*, int)>(sym("SSL_set_fd"));
    s.SSL_set_accept_state =
        reinterpret_cast<void (*)(SSL*)>(sym("SSL_set_accept_state"));
    s.SSL_set_connect_state =
        reinterpret_cast<void (*)(SSL*)>(sym("SSL_set_connect_state"));
    s.SSL_do_handshake =
        reinterpret_cast<int (*)(SSL*)>(sym("SSL_do_handshake"));
    s.SSL_read =
        reinterpret_cast<int (*)(SSL*, void*, int)>(sym("SSL_read"));
    s.SSL_write = reinterpret_cast<int (*)(SSL*, const void*, int)>(
        sym("SSL_write"));
    s.SSL_get_error =
        reinterpret_cast<int (*)(const SSL*, int)>(sym("SSL_get_error"));
    s.SSL_shutdown = reinterpret_cast<int (*)(SSL*)>(sym("SSL_shutdown"));
    s.ERR_get_error =
        reinterpret_cast<unsigned long (*)()>(sym("ERR_get_error"));
    s.ERR_error_string_n =
        reinterpret_cast<void (*)(unsigned long, char*, size_t)>(
            sym("ERR_error_string_n"));
    s.ERR_clear_error =
        reinterpret_cast<void (*)()>(sym("ERR_clear_error"));
    s.SSL_set_alpn_protos =
        reinterpret_cast<int (*)(SSL*, const unsigned char*, unsigned)>(
            sym("SSL_set_alpn_protos"));
    s.SSL_CTX_set_alpn_select_cb = reinterpret_cast<void (*)(
        SSL_CTX*,
        int (*)(SSL*, const unsigned char**, unsigned char*,
                const unsigned char*, unsigned, void*),
        void*)>(sym("SSL_CTX_set_alpn_select_cb"));
    s.SSL_get0_alpn_selected = reinterpret_cast<void (*)(
        const SSL*, const unsigned char**, unsigned*)>(
        sym("SSL_get0_alpn_selected"));
    s.SSL_ctrl =
        reinterpret_cast<long (*)(SSL*, int, long, void*)>(sym("SSL_ctrl"));
    s.SSL_CTX_load_verify_locations =
        reinterpret_cast<int (*)(SSL_CTX*, const char*, const char*)>(
            sym("SSL_CTX_load_verify_locations"));
    s.SSL_CTX_set_verify =
        reinterpret_cast<void (*)(SSL_CTX*, int, int (*)(int, void*))>(
            sym("SSL_CTX_set_verify"));
    s.SSL_set1_host =
        reinterpret_cast<int (*)(SSL*, const char*)>(sym("SSL_set1_host"));
    s.ok = s.TLS_method != nullptr && s.SSL_CTX_new != nullptr &&
           s.SSL_CTX_use_certificate_chain_file != nullptr &&
           s.SSL_CTX_use_PrivateKey_file != nullptr &&
           s.SSL_new != nullptr && s.SSL_free != nullptr &&
           s.SSL_set_fd != nullptr && s.SSL_set_accept_state != nullptr &&
           s.SSL_set_connect_state != nullptr &&
           s.SSL_do_handshake != nullptr && s.SSL_read != nullptr &&
           s.SSL_write != nullptr && s.SSL_get_error != nullptr &&
           s.ERR_get_error != nullptr;
    return s;
  }();
  return a;
}

std::string last_ssl_error() {
  const SslApi& a = api();
  char buf[256] = "unknown ssl error";
  if (a.ERR_get_error != nullptr && a.ERR_error_string_n != nullptr) {
    const unsigned long e = a.ERR_get_error();
    if (e != 0) {
      a.ERR_error_string_n(e, buf, sizeof(buf));
    }
  }
  return buf;
}

// ---- per-connection state ------------------------------------------------

struct TlsConnState {
  enum Phase : uint8_t {
    kSniff = 0,        // server: first byte decides TLS vs passthrough
    kHandshaking = 1,
    kEstablished = 2,
    kPlain = 3,        // passthrough: plaintext client on a TLS port
  };
  std::mutex mu;  // SSL objects are not thread-safe; read fiber vs
                  // KeepWrite fiber both drive the same SSL*
  SSL* ssl = nullptr;
  SSL_CTX* ctx = nullptr;  // not owned (contexts are leaked singletons)
  Phase phase = kSniff;
  bool client = false;
  std::string alpn_offer;  // client: wire-format protocol list to advertise
  std::string sni_host;    // client: server_name extension (empty = none)

  ~TlsConnState() {
    if (ssl != nullptr) {
      api().SSL_free(ssl);  // frees buffered state; fd is socket-owned
    }
  }
};

// Drives the handshake one step; call with st->mu held and ssl set.
// Returns 1 done, 0 in progress, -1 fatal.
int handshake_step_locked(TlsConnState* st, Socket* s) {
  if (api().ERR_clear_error != nullptr) {
    api().ERR_clear_error();
  }
  const int rc = api().SSL_do_handshake(st->ssl);
  if (rc == 1) {
    st->phase = TlsConnState::kEstablished;
    // A KeepWrite fiber may be parked on the writable edge waiting for
    // the handshake the READ path just completed: poke it.
    s->on_output_event();
    return 1;
  }
  const int err = api().SSL_get_error(st->ssl, rc);
  if (err == kSslErrorWantRead || err == kSslErrorWantWrite) {
    return 0;
  }
  LOG(Warning) << "tls handshake with " << endpoint2str(s->remote())
               << " failed: " << last_ssl_error();
  return -1;
}

class TlsTransport final : public Transport {
 public:
  ssize_t cut_from_iobuf(Socket* s, IOBuf* from) override {
    auto* st = static_cast<TlsConnState*>(s->transport_ctx);
    if (st == nullptr) {
      errno = EINVAL;
      return -1;
    }
    std::lock_guard<std::mutex> g(st->mu);
    if (st->phase == TlsConnState::kPlain) {
      const ssize_t rc = from->cut_into_fd(s->fd());
      return rc < 0 && (errno == EAGAIN || errno == EWOULDBLOCK) ? 0 : rc;
    }
    if (st->phase == TlsConnState::kSniff) {
      return 0;  // server write before any client byte: wait for sniff
    }
    if (st->phase == TlsConnState::kHandshaking) {
      if (!s->connected() || s->fd() < 0) {
        return 0;  // spurious pre-connect edge: SSL must not bind fd -1
      }
      if (st->ssl == nullptr && !init_ssl_locked(st, s)) {
        errno = EIO;
        return -1;
      }
      const int hs = handshake_step_locked(st, s);
      if (hs < 0) {
        errno = ECONNRESET;
        return -1;
      }
      if (hs == 0) {
        return 0;  // progress rides the next readable/writable edge
      }
    }
    // Established: encrypt block by block.
    ssize_t total = 0;
    while (!from->empty()) {
      const IOBuf::BlockRef& ref = from->ref_at(0);
      if (api().ERR_clear_error != nullptr) {
        api().ERR_clear_error();
      }
      const int n = api().SSL_write(
          st->ssl, ref.block->data + ref.offset, static_cast<int>(ref.length));
      if (n > 0) {
        from->pop_front(n);
        total += n;
        continue;
      }
      const int err = api().SSL_get_error(st->ssl, n);
      if (err == kSslErrorWantWrite || err == kSslErrorWantRead) {
        return total;  // partial progress; resume on the next edge
      }
      errno = ECONNRESET;
      return total > 0 ? total : -1;
    }
    return total;
  }

  ssize_t append_to_iobuf(Socket* s, IOBuf* to, size_t max) override {
    auto* st = static_cast<TlsConnState*>(s->transport_ctx);
    if (st == nullptr) {
      errno = EINVAL;
      return -1;
    }
    std::lock_guard<std::mutex> g(st->mu);
    if (st->phase == TlsConnState::kSniff) {
      char first = 0;
      const ssize_t n = recv(s->fd(), &first, 1, MSG_PEEK);
      if (n < 0) {
        return errno == EAGAIN || errno == EWOULDBLOCK ? 0 : -1;
      }
      if (n == 0) {
        errno = 0;  // orderly EOF before any byte
        return -1;
      }
      if (first == 0x16) {  // TLS handshake record
        st->phase = TlsConnState::kHandshaking;
      } else {
        st->phase = TlsConnState::kPlain;  // plaintext client, same port
      }
    }
    if (st->phase == TlsConnState::kPlain) {
      const ssize_t rc = to->append_from_fd(s->fd(), max);
      if (rc < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        return 0;
      }
      if (rc == 0) {
        errno = 0;
        return -1;
      }
      return rc;
    }
    if (st->phase == TlsConnState::kHandshaking) {
      if (!s->connected() || s->fd() < 0) {
        return 0;  // spurious pre-connect edge: SSL must not bind fd -1
      }
      if (st->ssl == nullptr && !init_ssl_locked(st, s)) {
        errno = EIO;
        return -1;
      }
      const int hs = handshake_step_locked(st, s);
      if (hs < 0) {
        errno = ECONNRESET;
        return -1;
      }
      if (hs == 0) {
        return 0;
      }
    }
    // Established: decrypt into the IOBuf (one copy — decryption needs a
    // destination buffer regardless).
    ssize_t total = 0;
    char buf[17 * 1024];  // one TLS record + header
    while (static_cast<size_t>(total) < max) {
      if (api().ERR_clear_error != nullptr) {
        api().ERR_clear_error();
      }
      const int n = api().SSL_read(st->ssl, buf, sizeof(buf));
      if (n > 0) {
        to->append(buf, n);
        total += n;
        continue;
      }
      const int err = api().SSL_get_error(st->ssl, n);
      if (err == kSslErrorWantRead || err == kSslErrorWantWrite) {
        return total;
      }
      if (err == kSslErrorZeroReturn) {
        if (total > 0) {
          return total;
        }
        errno = 0;  // clean TLS shutdown
        return -1;
      }
      if (total > 0) {
        return total;
      }
      errno = ECONNRESET;
      return -1;
    }
    return total;
  }

  int connect(Socket* s) override {
    // TCP establishment first; the TLS handshake is driven lazily from
    // the read/write paths above (both ends nonblocking).
    return tcp_transport()->connect(s);
  }

  const char* name() const override { return "tls"; }

 private:
  static bool init_ssl_locked(TlsConnState* st, Socket* s) {
    st->ssl = api().SSL_new(st->ctx);
    if (st->ssl == nullptr) {
      return false;
    }
    if (api().SSL_set_fd(st->ssl, s->fd()) != 1) {
      api().SSL_free(st->ssl);  // never keep an SSL bound to a bad fd
      st->ssl = nullptr;
      return false;
    }
    if (st->client) {
      if (!st->alpn_offer.empty() && api().SSL_set_alpn_protos != nullptr) {
        // Note the inverted return: 0 = success for this one API.
        api().SSL_set_alpn_protos(
            st->ssl,
            reinterpret_cast<const unsigned char*>(st->alpn_offer.data()),
            static_cast<unsigned>(st->alpn_offer.size()));
      }
      if (!st->sni_host.empty()) {
        if (api().SSL_ctrl != nullptr) {
          // SNI: without it, name-vhosted endpoints (CDNs, ingresses)
          // serve their default cert or abort with unrecognized_name.
          api().SSL_ctrl(st->ssl, kSslCtrlSetTlsextHostname, 0,
                         const_cast<char*>(st->sni_host.c_str()));
        }
        if (api().SSL_set1_host != nullptr) {
          // Hostname pin: when peer VERIFICATION is enabled on the ctx
          // (tls_client_ctx_mtls with a CA), the chain must also match
          // this name — chain-only acceptance would let any same-CA
          // certificate impersonate the server.  No-op when
          // verification is off, and unset for IP-literal addresses
          // (sni_host is empty then): those get chain-only checks.
          api().SSL_set1_host(st->ssl, st->sni_host.c_str());
        }
      }
      api().SSL_set_connect_state(st->ssl);
    } else {
      api().SSL_set_accept_state(st->ssl);
    }
    return true;
  }
};

// Server ALPN selection: prefer h2, then http/1.1, else reject (the
// callback contract: SSL_TLSEXT_ERR_OK=0 / SSL_TLSEXT_ERR_NOACK=3 —
// NOACK omits the extension, letting protocol probing decide, rather
// than aborting clients offering something exotic).
int alpn_select_cb(SSL*, const unsigned char** out, unsigned char* outlen,
                   const unsigned char* in, unsigned inlen, void*) {
  static const char* const kPrefer[] = {"h2", "http/1.1"};
  for (const char* want : kPrefer) {
    const size_t wlen = strlen(want);
    for (unsigned i = 0; i + 1 <= inlen;) {
      const unsigned len = in[i];
      if (i + 1 + len > inlen) {
        break;  // malformed list
      }
      if (len == wlen && memcmp(in + i + 1, want, wlen) == 0) {
        *out = in + i + 1;
        *outlen = static_cast<unsigned char>(len);
        return 0;  // SSL_TLSEXT_ERR_OK
      }
      i += 1 + len;
    }
  }
  return 3;  // SSL_TLSEXT_ERR_NOACK
}

}  // namespace

namespace {

// Loads cert chain + private key into `ctx` (shared by the server and
// mTLS-client context builders so their error paths cannot drift).
bool load_identity(SSL_CTX* ctx, const std::string& cert_file,
                   const std::string& key_file, std::string* err) {
  if (api().SSL_CTX_use_certificate_chain_file(ctx, cert_file.c_str()) !=
          1 ||
      api().SSL_CTX_use_PrivateKey_file(ctx, key_file.c_str(),
                                        kSslFiletypePem) != 1 ||
      (api().SSL_CTX_check_private_key != nullptr &&
       api().SSL_CTX_check_private_key(ctx) != 1)) {
    *err = last_ssl_error();
    return false;
  }
  return true;
}

}  // namespace

bool tls_available() { return api().ok; }

void* tls_server_ctx(const std::string& cert_file,
                     const std::string& key_file, std::string* err,
                     const std::string& ca_file) {
  if (!api().ok) {
    *err = "libssl not available";
    return nullptr;
  }
  SSL_CTX* ctx = api().SSL_CTX_new(api().TLS_method());
  if (ctx == nullptr) {
    *err = last_ssl_error();
    return nullptr;
  }
  if (!load_identity(ctx, cert_file, key_file, err)) {
    if (api().SSL_CTX_free != nullptr) {
      api().SSL_CTX_free(ctx);  // only SUCCESSFUL contexts live forever
    }
    return nullptr;
  }
  if (api().SSL_CTX_set_alpn_select_cb != nullptr) {
    api().SSL_CTX_set_alpn_select_cb(ctx, &alpn_select_cb, nullptr);
  }
  if (!ca_file.empty()) {
    if (api().SSL_CTX_load_verify_locations == nullptr ||
        api().SSL_CTX_set_verify == nullptr) {
      *err = "libssl lacks client-verification symbols";
      api().SSL_CTX_free(ctx);
      return nullptr;
    }
    if (api().SSL_CTX_load_verify_locations(ctx, ca_file.c_str(),
                                            nullptr) != 1) {
      *err = last_ssl_error();
      api().SSL_CTX_free(ctx);
      return nullptr;
    }
    // mTLS: a missing or unverifiable client certificate FAILS the
    // handshake (plaintext sniffing on the same port is unaffected).
    api().SSL_CTX_set_verify(
        ctx, kSslVerifyPeer | kSslVerifyFailIfNoPeerCert, nullptr);
  }
  return ctx;
}

void* tls_client_ctx_mtls(const std::string& cert_file,
                          const std::string& key_file,
                          const std::string& ca_file, std::string* err) {
  if (!api().ok) {
    *err = "libssl not available";
    return nullptr;
  }
  // Contexts are immutable after construction; cache by configuration so
  // a flapping connection does not leak an SSL_CTX + X509 store per
  // reconnect (ensure_socket re-enters here on every fresh socket).
  static std::mutex mu;
  static auto* cache = new std::map<std::string, SSL_CTX*>();
  const std::string key = cert_file + "\x1f" + key_file + "\x1f" + ca_file;
  std::lock_guard<std::mutex> g(mu);
  auto it = cache->find(key);
  if (it != cache->end()) {
    return it->second;
  }
  SSL_CTX* ctx = api().SSL_CTX_new(api().TLS_method());
  if (ctx == nullptr) {
    *err = last_ssl_error();
    return nullptr;
  }
  // cert may be empty: CA-only mode (server verification without a
  // client identity).
  if (!cert_file.empty() && !load_identity(ctx, cert_file, key_file, err)) {
    api().SSL_CTX_free(ctx);
    return nullptr;
  }
  if (!ca_file.empty()) {
    if (api().SSL_CTX_load_verify_locations == nullptr ||
        api().SSL_CTX_set_verify == nullptr) {
      *err = "libssl lacks client-verification symbols";
      api().SSL_CTX_free(ctx);
      return nullptr;
    }
    if (api().SSL_CTX_load_verify_locations(ctx, ca_file.c_str(),
                                            nullptr) != 1) {
      *err = last_ssl_error();
      api().SSL_CTX_free(ctx);
      return nullptr;
    }
    api().SSL_CTX_set_verify(ctx, kSslVerifyPeer, nullptr);
  }
  (*cache)[key] = ctx;
  return ctx;
}

void* tls_client_ctx(std::string* err) {
  if (!api().ok) {
    *err = "libssl not available";
    return nullptr;
  }
  // Retry on later calls if the first allocation failed — a transient
  // failure must not disable client TLS for the process lifetime.
  static std::mutex mu;
  static SSL_CTX* ctx = nullptr;
  std::lock_guard<std::mutex> g(mu);
  if (ctx == nullptr) {
    ctx = api().SSL_CTX_new(api().TLS_method());
  }
  if (ctx == nullptr) {
    *err = last_ssl_error();
  }
  return ctx;
}

Transport* tls_transport() {
  static TlsTransport t;
  return &t;
}

std::shared_ptr<void> tls_conn_server(void* server_ctx) {
  auto st = std::make_shared<TlsConnState>();
  st->ctx = static_cast<SSL_CTX*>(server_ctx);
  st->phase = TlsConnState::kSniff;
  st->client = false;
  return st;
}

std::shared_ptr<void> tls_conn_client(void* client_ctx,
                                      const std::string& alpn_wire,
                                      const std::string& sni_host) {
  auto st = std::make_shared<TlsConnState>();
  st->ctx = static_cast<SSL_CTX*>(client_ctx);
  st->phase = TlsConnState::kHandshaking;
  st->client = true;
  st->alpn_offer = alpn_wire;
  // IP literals must not ride the server_name extension (RFC 6066 §3):
  // skip IPv4 literals (str2endpoint parses them) and IPv6 literals
  // (bracketed, or bare with colons).
  if (!sni_host.empty() && sni_host[0] != '[' &&
      sni_host.find(':') == std::string::npos) {
    EndPoint probe;
    if (str2endpoint((sni_host + ":1").c_str(), &probe) != 0) {
      st->sni_host = sni_host;  // a name, not a literal → send SNI
    }
  }
  return st;
}

std::string tls_alpn_selected(Socket* s) {
  auto* st = static_cast<TlsConnState*>(s->transport_ctx);
  if (st == nullptr || api().SSL_get0_alpn_selected == nullptr) {
    return "";
  }
  std::lock_guard<std::mutex> g(st->mu);
  if (st->ssl == nullptr || st->phase != TlsConnState::kEstablished) {
    return "";
  }
  const unsigned char* data = nullptr;
  unsigned len = 0;
  api().SSL_get0_alpn_selected(st->ssl, &data, &len);
  return data != nullptr ? std::string(reinterpret_cast<const char*>(data),
                                       len)
                         : "";
}

}  // namespace trpc
