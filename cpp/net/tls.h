// TLS transport on the Transport seam.
//
// Parity: the reference's SSL support (/root/reference/src/brpc/details/
// ssl_helper.cpp; ServerOptions::mutable_ssl_options; the TLS-vs-plaintext
// sniff in input_messenger).  Re-designed for this runtime: a Transport
// wrapper holding per-connection SSL state, with the handshake driven
// OPPORTUNISTICALLY from whichever side (read fiber / KeepWrite fiber)
// touches the connection — no dedicated handshake thread.  Server-side
// connections SNIFF the first byte (0x16 = TLS handshake record): TLS and
// plaintext clients coexist on one port, like the reference.
//
// OpenSSL is loaded at runtime via dlopen(libssl.so.3): the image ships
// the runtime libraries but no development headers, so the needed subset
// of the stable libssl ABI is declared locally (tls.cc).
#pragma once

#include <memory>
#include <string>

#include "net/transport.h"

namespace trpc {

// True when libssl.so.3 loaded and every needed symbol resolved.
bool tls_available();

// Server identity: certificate + key (PEM).  With a non-empty
// `ca_file`, client certificates are REQUIRED and verified against it
// (mTLS; parity: VerifyOptions/ca_file_path in the reference's
// ServerSSLOptions — handshakes without a valid client cert fail).
// Returns an opaque SSL_CTX handle (leaked singleton pattern: contexts
// live forever), or nullptr with *err filled.
void* tls_server_ctx(const std::string& cert_file,
                     const std::string& key_file, std::string* err,
                     const std::string& ca_file = "");

// Client context (no peer verification by default — test/loopback grade,
// like the reference's default ssl_options).
void* tls_client_ctx(std::string* err);

// Client context presenting a certificate (the mTLS client half;
// ChannelSSLOptions::client_cert parity); cert may be empty for CA-only
// mode.  With `ca_file`, the SERVER's chain is verified against it, and
// when the channel address is a HOSTNAME the certificate must also match
// it (IP-literal addresses get chain-only verification).  Contexts are
// cached per (cert,key,ca) configuration.
void* tls_client_ctx_mtls(const std::string& cert_file,
                          const std::string& key_file,
                          const std::string& ca_file, std::string* err);

// The transport (stateless singleton; per-connection state rides
// Socket::transport_ctx).  Sockets using it must carry a TlsConnState
// created by one of the factories below in their transport_ctx_holder.
Transport* tls_transport();

// Per-connection state factories.  `sniff` (server side): the first byte
// decides TLS vs plaintext passthrough.  Client connections handshake
// unconditionally.  `alpn_wire` is the RFC 7301 wire-format protocol list
// to advertise (e.g. "\x02h2\x08http/1.1"); empty = no ALPN extension.
// Servers negotiate automatically (prefer h2, then http/1.1; exotic lists
// fall back to byte probing) — ssl_helper.h:89-96 ALPN parity.
std::shared_ptr<void> tls_conn_server(void* server_ctx);
// `sni_host`: hostname for the server_name extension; IP literals are
// filtered out automatically (RFC 6066 §3), empty = no SNI.
std::shared_ptr<void> tls_conn_client(void* client_ctx,
                                      const std::string& alpn_wire = "",
                                      const std::string& sni_host = "");

// Negotiated ALPN protocol of an ESTABLISHED TLS socket ("" before the
// handshake finishes, without ALPN, or on plaintext passthrough).
class Socket;
std::string tls_alpn_selected(Socket* s);

}  // namespace trpc
