// Transport — pluggable byte-moving strategy per socket.
//
// Parity: the fork's Transport seam (/root/reference/src/brpc/transport.h:
// 26-64, selected by SocketMode via transport_factory.cpp) — the exact place
// the reference hangs TCP, RDMA and shared-memory backends, and where our
// ICI endpoint goes.  Condensed to the byte-plane methods; fiber-spawn
// policy lives in the messenger.
#pragma once

#include <sys/types.h>

#include "base/iobuf.h"

namespace trpc {

class Socket;
struct RmaSession;  // net/rma.h — per-connection one-sided state

enum class SocketMode : int {
  kTcp = 0,
  kIci = 1,  // device DMA rings (the north-star seam)
  kShm = 2,  // same-host shared-memory rings (net/shm_transport.*)
};

class Transport {
 public:
  virtual ~Transport() = default;

  // Move bytes from `from` into the connection; pops what was sent.
  // Returns bytes written, 0 on EAGAIN-equivalent, -1 on error.
  virtual ssize_t cut_from_iobuf(Socket* s, IOBuf* from) = 0;

  // Read available bytes into `to`; returns bytes read, 0 on
  // EAGAIN-equivalent, -1 on error/EOF(-with errno 0).
  virtual ssize_t append_to_iobuf(Socket* s, IOBuf* to, size_t max) = 0;

  // Publish everything cut_from_iobuf staged since the last flush — the
  // per-drain doorbell.  Descriptor/ring transports (shm, ici) defer their
  // peer-visible cursor publish to here so a KeepWrite drain of N writes
  // rings the peer once, not N times.  The write path guarantees a flush
  // after every cut_from_iobuf sequence, including before parking on
  // EAGAIN and before abandoning a failed socket.  Default: no-op (TCP's
  // writev is its own doorbell).
  virtual void flush(Socket* s) { (void)s; }

  // Establish the connection if needed (non-blocking; may park the calling
  // fiber).  Returns 0 on success.
  virtual int connect(Socket* s) = 0;

  // True when this transport moves bytes through the socket's fd (TCP,
  // TLS): such sockets need the lazy-connect path before their first
  // write.  fd-less transports (shm rings) are connected at creation.
  virtual bool fd_based() const { return true; }

  // Optional one-sided capability (net/rma.h): transports whose peers
  // share addressable memory (shm, ici) return the connection's RMA
  // session — registered local window + peer window resolution — and
  // large bodies are then WRITTEN into the peer's registered region
  // (rma_put) with only a control frame riding the byte plane.
  // Default: nullptr — TCP/TLS have no one-sided plane and are untouched
  // by it.
  virtual RmaSession* rma(Socket* s) {
    (void)s;
    return nullptr;
  }

  virtual const char* name() const = 0;
};

Transport* tcp_transport();  // stateless singleton

}  // namespace trpc
