#include "net/usercode_pool.h"

#include <pthread.h>

#include <atomic>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>

#include "stat/variable.h"

namespace trpc {

struct UsercodePool::Impl {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<std::function<void()>> queue;
  std::atomic<int> inflight{0};
  std::atomic<int> executed{0};

  void worker() {
    while (true) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [this] { return !queue.empty(); });
        fn = std::move(queue.front());
        queue.pop_front();
      }
      inflight.fetch_add(1, std::memory_order_relaxed);
      fn();
      inflight.fetch_sub(1, std::memory_order_relaxed);
      executed.fetch_add(1, std::memory_order_relaxed);
    }
  }
};

UsercodePool::UsercodePool(int threads) : impl_(new Impl()) {
  if (threads <= 0) {
    const unsigned hw = std::thread::hardware_concurrency();
    threads = hw > 4 ? static_cast<int>(hw) : 4;
  }
  for (int i = 0; i < threads; ++i) {
    std::thread([impl = impl_] { impl->worker(); }).detach();
  }
  // Pressure gauges (observability parity: the reference exposes
  // bthread_count-style vars; here /vars usercode_*).
  static PassiveStatus<int64_t>* g_inflight =
      new PassiveStatus<int64_t>([impl = impl_] {
        return static_cast<int64_t>(impl->inflight.load());
      });
  g_inflight->expose("usercode_inflight",
                     "user callbacks currently running on the pthread "
                     "backup pool (usercode_in_pthread path)");
  static PassiveStatus<int64_t>* g_queue =
      new PassiveStatus<int64_t>([impl = impl_] {
        std::lock_guard<std::mutex> g(impl->mu);
        return static_cast<int64_t>(impl->queue.size());
      });
  g_queue->expose("usercode_queue",
                  "user callbacks queued for the pthread backup pool "
                  "(sustained growth = pool undersized)");
}

UsercodePool* UsercodePool::instance(int threads) {
  static UsercodePool* p = new UsercodePool(threads);  // leaked singleton
  return p;
}

void UsercodePool::run(std::function<void()> fn) {
  {
    std::lock_guard<std::mutex> g(impl_->mu);
    impl_->queue.push_back(std::move(fn));
  }
  impl_->cv.notify_one();
}

int UsercodePool::inflight() const {
  return impl_->inflight.load(std::memory_order_relaxed);
}

int UsercodePool::executed() const {
  return impl_->executed.load(std::memory_order_relaxed);
}

}  // namespace trpc
