// Usercode backup pool — run blocking user handlers on pthreads.
//
// Parity: the reference's usercode_in_pthread escape hatch
// (/root/reference/src/brpc/details/usercode_backup_pool.h:46
// TooManyUserCode + a dedicated pthread pool): user code that blocks on
// pthread-level primitives would otherwise pin fiber workers and starve
// the event loop.  Condensed: Server::set_usercode_in_pthread(true)
// routes every method handler through this pool; the pool is global
// (like the reference's), lazily started, and exports its pressure as
// /vars usercode_inflight + usercode_queue.
#pragma once

#include <functional>

namespace trpc {

class UsercodePool {
 public:
  // Global pool (leaked singleton); `threads` applies on first use only.
  static UsercodePool* instance(int threads = 0);

  // Enqueues `fn` for a backup pthread.  Never blocks the caller; the
  // queue is unbounded (the concurrency limiter upstream is the
  // admission control, same as the reference).
  void run(std::function<void()> fn);

  int inflight() const;   // running right now
  int executed() const;   // lifetime count

 private:
  explicit UsercodePool(int threads);
  struct Impl;
  Impl* impl_;
};

}  // namespace trpc
