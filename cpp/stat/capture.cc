#include "stat/capture.h"

#include <unistd.h>

#include <algorithm>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <vector>

#include "base/flags.h"
#include "base/json.h"
#include "base/recordio.h"
#include "base/time.h"
#include "stat/reducer.h"
#include "stat/timeline.h"
#include "stat/variable.h"

namespace trpc {
namespace capture {

std::atomic<bool> g_enabled{false};

namespace {

// Strings in a retained record are clamped to this many bytes so
// reservoir memory is bounded by record count alone.
constexpr size_t kMaxStringBytes = 64;
// Binary record layout version (first byte of every record payload).
constexpr uint8_t kRecordVersion = 1;
// Fixed-width prefix of a serialized record before the two strings.
constexpr size_t kRecordFixedBytes = 68;

// Timeline event 26 ops (high byte of b).
constexpr uint64_t kOpKeep = 1;
constexpr uint64_t kOpDrop = 2;
constexpr uint64_t kOpDump = 3;

uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

Flag* max_records_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_capture_max_records", 65536,
        "traffic-capture reservoir capacity in records (~100 bytes of "
        "metadata each regardless of body size; per-tenant stratified — "
        "each tenant gets capacity/strata slots)");
    if (flag != nullptr) {
      // Range validator + introspectable bounds in one declaration.
      flag->set_int_range(256, 1 << 20);
    }
    return flag;
  }();
  return f;
}

Flag* sample_permille_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_capture_sample_permille", 1000,
        "traffic-capture admission sampling rate in permille (1000 = "
        "record every request; sampling is a deterministic seeded hash "
        "of the per-window request index, so a seeded stream keeps the "
        "same records on every run)");
    if (flag != nullptr) {
      flag->set_int_range(0, 1000);
    }
    return flag;
  }();
  return f;
}

Flag* seed_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_capture_seed", 1,
        "traffic-capture sampling seed (deterministic admission + "
        "reservoir eviction for a fixed request stream)");
    if (flag != nullptr) {
      flag->set_int_range(1, 1 << 30);
    }
    return flag;
  }();
  return f;
}

Flag* capture_flag() {
  static Flag* f = [] {
    max_records_flag();  // companion knobs register alongside
    sample_permille_flag();
    seed_flag();
    Flag* flag = Flag::define_bool(
        "trpc_capture", false,
        "traffic capture: sampled per-request metadata records (arrival "
        "time, method, tenant/priority, deadline budget, trace ids, "
        "sizes, status, queue+handler latency) in a per-tenant "
        "stratified reservoir, browsable via /capture and replayable by "
        "tools/traffic_replay.py (default off; flag-off cost is one "
        "relaxed load per request)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        return v == "true" || v == "false" || v == "1" || v == "0" ||
               v == "on" || v == "off";
      });
      flag->on_update([](Flag* self) {
        g_enabled.store(self->bool_value(), std::memory_order_release);
      });
    }
    return flag;
  }();
  return f;
}

struct CaptureVars {
  Adder seen;
  Adder sampled;
  Adder dropped;
  std::unique_ptr<PassiveStatus<long>> records;

  CaptureVars() {
    seen.expose(
        "capture_seen_total",
        "requests offered to the traffic-capture reservoir while "
        "trpc_capture was on (frozen at 0 while it has never been on)");
    sampled.expose(
        "capture_sampled_total",
        "requests that passed the trpc_capture_sample_permille "
        "admission gate");
    dropped.expose(
        "capture_dropped_total",
        "sampled requests not retained because the capture reservoir "
        "was full (reservoir eviction or stratum quota) — nonzero means "
        "the capture is a uniform sample, not a complete record");
    records = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(records_held()); });
    records->expose(
        "capture_records",
        "records currently held in the traffic-capture reservoir");
  }
};

CaptureVars* vars() {
  // Deliberately leaked: the var registry outlives statics.
  static CaptureVars* v = new CaptureVars();
  return v;
}

// Per-tenant stratum: an independent Algorithm-R reservoir.
struct Stratum {
  uint64_t seen = 0;  // sampled admissions for this tenant (window)
  std::vector<Sample> recs;
};

struct Buf {
  std::mutex mu;
  std::map<std::string, Stratum> strata;
  size_t total = 0;         // records across all strata
  uint64_t decision_idx = 0;  // per-window admission index (reset() zeroes)
  // Window counters — reset() zeroes these; the lifetime Adders never
  // rewind (Prometheus counter contract).
  uint64_t w_seen = 0;
  uint64_t w_sampled = 0;
  uint64_t w_dropped = 0;
};

Buf& buf() {
  static Buf* b = new Buf();  // leaked: dumps may outlive static teardown
  return *b;
}

void clamp_strings(Sample* s) {
  if (s->method.size() > kMaxStringBytes) {
    s->method.resize(kMaxStringBytes);
  }
  if (s->tenant.size() > kMaxStringBytes) {
    s->tenant.resize(kMaxStringBytes);
  }
}

// Evicts one record (seeded-random slot) from the largest stratum that
// holds more than `quota` records, making room for an under-quota
// stratum.  Returns false when no stratum is over quota.
bool steal_slot(Buf* b, size_t quota, uint64_t rnd) {
  Stratum* victim = nullptr;
  for (auto& kv : b->strata) {
    if (kv.second.recs.size() > quota &&
        (victim == nullptr ||
         kv.second.recs.size() > victim->recs.size())) {
      victim = &kv.second;
    }
  }
  if (victim == nullptr) {
    return false;
  }
  const size_t j = rnd % victim->recs.size();
  victim->recs[j] = std::move(victim->recs.back());
  victim->recs.pop_back();
  b->total--;
  return true;
}

template <typename T>
void append_le(std::string* out, T v) {
  char tmp[sizeof(T)];
  memcpy(tmp, &v, sizeof(T));
  out->append(tmp, sizeof(T));
}

template <typename T>
T read_le(const char* p) {
  T v;
  memcpy(&v, p, sizeof(T));
  return v;
}

std::string hex_id(uint64_t id) {
  char tmp[20];
  snprintf(tmp, sizeof(tmp), "%016llx",
           static_cast<unsigned long long>(id));
  return tmp;
}

struct WindowSnapshot {
  std::vector<Sample> recs;  // arrival order
  uint64_t w_seen = 0;
  uint64_t w_sampled = 0;
  uint64_t w_dropped = 0;
  std::map<std::string, uint64_t> stratum_seen;
};

WindowSnapshot snapshot() {
  WindowSnapshot out;
  Buf& b = buf();
  std::lock_guard<std::mutex> g(b.mu);
  out.w_seen = b.w_seen;
  out.w_sampled = b.w_sampled;
  out.w_dropped = b.w_dropped;
  out.recs.reserve(b.total);
  for (const auto& kv : b.strata) {
    out.stratum_seen[kv.first] = kv.second.seen;
    for (const Sample& s : kv.second.recs) {
      out.recs.push_back(s);
    }
  }
  std::sort(out.recs.begin(), out.recs.end(),
            [](const Sample& a, const Sample& c) {
              return a.arrival_mono_us < c.arrival_mono_us;
            });
  return out;
}

double percentile(std::vector<uint64_t>* v, double p) {
  if (v->empty()) {
    return 0;
  }
  std::sort(v->begin(), v->end());
  const size_t idx = std::min(
      v->size() - 1, static_cast<size_t>(p * (v->size() - 1) + 0.5));
  return static_cast<double>((*v)[idx]);
}

// Arrival-process summary over the kept records: per-second rate series
// + burstiness CV, log2 size histograms, per-tenant rate/latency/error
// mix, and fan-out stats reconstructed from trace ids.  Shared by the
// /capture JSON and the capture-file header (where it doubles as the
// recorded baseline the replay bench compares against).
Json build_summary(const WindowSnapshot& w) {
  Json out = Json::object();
  const size_t n = w.recs.size();
  out.set("kept", Json::number(static_cast<double>(n)));
  const int64_t permille = sample_permille_flag()->int64_value();
  out.set("sample_permille", Json::number(static_cast<double>(permille)));
  if (n == 0) {
    out.set("window_us", Json::number(0));
    return out;
  }
  const int64_t first = w.recs.front().arrival_mono_us;
  const int64_t last = w.recs.back().arrival_mono_us;
  const int64_t window_us = std::max<int64_t>(1, last - first);
  out.set("window_us", Json::number(static_cast<double>(window_us)));
  out.set("start_mono_us", Json::number(static_cast<double>(first)));
  out.set("start_wall_us",
          Json::number(static_cast<double>(w.recs.front().arrival_wall_us)));
  // Scale sampled counts back to offered rates (admission is permille).
  const double scale = permille > 0 ? 1000.0 / permille : 1.0;
  out.set("est_rate_rps",
          Json::number(n * scale * 1e6 / window_us));

  // Per-bucket rate series; bucket widens past 600 buckets so the JSON
  // stays bounded for long windows.
  const int64_t bucket_us =
      std::max<int64_t>(1000000, (window_us + 599) / 600);
  const size_t nbuckets =
      static_cast<size_t>((window_us + bucket_us - 1) / bucket_us) + 1;
  std::vector<uint64_t> series(nbuckets, 0);
  for (const Sample& s : w.recs) {
    series[static_cast<size_t>((s.arrival_mono_us - first) / bucket_us)]++;
  }
  out.set("rate_bucket_us", Json::number(static_cast<double>(bucket_us)));
  Json rate = Json::array();
  double mean = 0;
  for (uint64_t c : series) {
    rate.push_back(Json::number(static_cast<double>(c)));
    mean += static_cast<double>(c);
  }
  mean /= static_cast<double>(series.size());
  double var = 0;
  for (uint64_t c : series) {
    var += (c - mean) * (c - mean);
  }
  var /= static_cast<double>(series.size());
  out.set("rate_series", std::move(rate));
  // Coefficient of variation of the per-bucket counts — ~0 for constant
  // load, ~1 for Poisson-at-1/bucket, >1 for bursty arrivals.
  out.set("burstiness_cv",
          Json::number(mean > 0 ? std::sqrt(var) / mean : 0));

  // Log2 size histograms (bucket k = sizes in [2^(k-1), 2^k), bucket 0
  // = zero bytes), trimmed to the highest non-empty bucket.
  auto log2_bucket = [](uint64_t v) {
    size_t k = 0;
    while (v > 0) {
      v >>= 1;
      k++;
    }
    return k;
  };
  std::vector<uint64_t> req_hist(65, 0);
  std::vector<uint64_t> resp_hist(65, 0);
  for (const Sample& s : w.recs) {
    req_hist[log2_bucket(s.request_bytes)]++;
    resp_hist[log2_bucket(s.response_bytes)]++;
  }
  auto emit_hist = [](const std::vector<uint64_t>& h) {
    size_t hi = h.size();
    while (hi > 0 && h[hi - 1] == 0) {
      hi--;
    }
    Json arr = Json::array();
    for (size_t i = 0; i < hi; ++i) {
      arr.push_back(Json::number(static_cast<double>(h[i])));
    }
    return arr;
  };
  out.set("req_bytes_log2_hist", emit_hist(req_hist));
  out.set("resp_bytes_log2_hist", emit_hist(resp_hist));

  // Per-tenant baseline: rate, sizes, server-side latency percentiles
  // (queue + handler — what the replay bench compares loaded p99
  // against), and the recorded error mix.
  struct TenantAgg {
    uint64_t kept = 0;
    uint64_t req_bytes = 0;
    std::vector<uint64_t> total_us;
    std::vector<uint64_t> handler_us;
    std::map<int32_t, uint64_t> errors;
  };
  std::map<std::string, TenantAgg> agg;
  for (const Sample& s : w.recs) {
    TenantAgg& t = agg[s.tenant];
    t.kept++;
    t.req_bytes += s.request_bytes;
    t.total_us.push_back(static_cast<uint64_t>(s.queue_us) + s.handler_us);
    t.handler_us.push_back(s.handler_us);
    if (s.status != 0) {
      t.errors[s.status]++;
    }
  }
  Json tenants = Json::object();
  for (auto& kv : agg) {
    TenantAgg& t = kv.second;
    Json tj = Json::object();
    tj.set("kept", Json::number(static_cast<double>(t.kept)));
    auto it = w.stratum_seen.find(kv.first);
    const uint64_t seen = it != w.stratum_seen.end() ? it->second : t.kept;
    tj.set("sampled", Json::number(static_cast<double>(seen)));
    tj.set("est_rate_rps",
           Json::number(seen * scale * 1e6 / window_us));
    tj.set("mean_req_bytes",
           Json::number(static_cast<double>(t.req_bytes) / t.kept));
    tj.set("p50_us", Json::number(percentile(&t.total_us, 0.50)));
    tj.set("p99_us", Json::number(percentile(&t.total_us, 0.99)));
    tj.set("handler_p99_us", Json::number(percentile(&t.handler_us, 0.99)));
    Json errs = Json::object();
    for (const auto& e : t.errors) {
      errs.set(std::to_string(e.first),
               Json::number(static_cast<double>(e.second)));
    }
    tj.set("errors", std::move(errs));
    tenants.set(kv.first.empty() ? "*" : kv.first, std::move(tj));
  }
  out.set("tenants", std::move(tenants));

  // Fan-out shape from trace ids: records sharing a trace_id are nodes
  // of one logical request tree; parent_span_id != 0 marks an edge from
  // an upstream RPC.
  std::map<uint64_t, uint64_t> per_trace;
  uint64_t edge_records = 0;
  for (const Sample& s : w.recs) {
    if (s.trace_id != 0) {
      per_trace[s.trace_id]++;
    }
    if (s.parent_span_id != 0) {
      edge_records++;
    }
  }
  uint64_t multi = 0;
  uint64_t max_nodes = 0;
  uint64_t nodes = 0;
  for (const auto& kv : per_trace) {
    nodes += kv.second;
    max_nodes = std::max(max_nodes, kv.second);
    multi += kv.second > 1;
  }
  Json fanout = Json::object();
  fanout.set("traces", Json::number(static_cast<double>(per_trace.size())));
  fanout.set("multi_record_traces",
             Json::number(static_cast<double>(multi)));
  fanout.set("max_records_per_trace",
             Json::number(static_cast<double>(max_nodes)));
  fanout.set("mean_records_per_trace",
             Json::number(per_trace.empty()
                              ? 0
                              : static_cast<double>(nodes) /
                                    static_cast<double>(per_trace.size())));
  fanout.set("edge_records",
             Json::number(static_cast<double>(edge_records)));
  out.set("fanout", std::move(fanout));
  return out;
}

Json record_json(const Sample& s) {
  Json j = Json::object();
  j.set("arrival_mono_us",
        Json::number(static_cast<double>(s.arrival_mono_us)));
  j.set("arrival_wall_us",
        Json::number(static_cast<double>(s.arrival_wall_us)));
  j.set("method", Json::str(s.method));
  j.set("tenant", Json::str(s.tenant));
  j.set("priority", Json::number(s.priority));
  j.set("request_bytes",
        Json::number(static_cast<double>(s.request_bytes)));
  j.set("response_bytes",
        Json::number(static_cast<double>(s.response_bytes)));
  j.set("status", Json::number(s.status));
  j.set("queue_us", Json::number(s.queue_us));
  j.set("handler_us", Json::number(s.handler_us));
  j.set("deadline_budget_us", Json::number(s.deadline_budget_us));
  // Hex strings: 64-bit ids lose low bits as JSON doubles past 2^53.
  j.set("trace_id", Json::str(hex_id(s.trace_id)));
  j.set("parent_span_id", Json::str(hex_id(s.parent_span_id)));
  return j;
}

// Eager registration: /flags can list+flip trpc_capture and /vars shows
// the zeroed series before any traffic (same pattern as trpc_timeline).
[[maybe_unused]] const bool g_capture_eager = [] {
  ensure_registered();
  return true;
}();

}  // namespace

void ensure_registered() {
  capture_flag();
  vars();
}

void record(Sample&& s) {
  if (!enabled()) {
    return;  // call sites gate too; this is belt-and-braces
  }
  ensure_registered();
  clamp_strings(&s);
  if (s.arrival_mono_us == 0) {
    s.arrival_mono_us = monotonic_time_us();
  }
  if (s.arrival_wall_us == 0) {
    // Derive the wall-clock arrival from the mono timestamp so the pair
    // stays coherent even when the record lands long after arrival.
    s.arrival_wall_us =
        realtime_us() - (monotonic_time_us() - s.arrival_mono_us);
  }
  const uint64_t seed =
      static_cast<uint64_t>(seed_flag()->int64_value());
  const int64_t permille = sample_permille_flag()->int64_value();
  const size_t cap = std::max<int64_t>(
      256, max_records_flag()->int64_value());
  const uint64_t trace = s.trace_id;
  const uint64_t req_bytes = s.request_bytes;
  bool kept = false;
  {
    Buf& b = buf();
    std::lock_guard<std::mutex> g(b.mu);
    vars()->seen << 1;
    b.w_seen++;
    const uint64_t idx = b.decision_idx++;
    if (permille < 1000 &&
        splitmix64(seed ^ (idx + 1)) % 1000 >=
            static_cast<uint64_t>(permille)) {
      return;  // not sampled: by design, not a coverage loss
    }
    vars()->sampled << 1;
    b.w_sampled++;
    Stratum& st = b.strata[s.tenant];
    st.seen++;
    const size_t quota =
        std::max<size_t>(1, cap / std::max<size_t>(1, b.strata.size()));
    if (st.recs.size() < quota) {
      // A late-arriving tenant may find the reservoir full of earlier
      // strata; steal a slot from the largest over-quota stratum so
      // every tenant converges to its fair share.
      bool room = b.total < cap;
      if (!room) {
        room = steal_slot(&b, quota, splitmix64(seed ^ ~idx));
        if (room) {
          vars()->dropped << 1;  // the stolen record is the drop
          b.w_dropped++;
        }
      }
      if (room) {
        st.recs.push_back(std::move(s));
        b.total++;
        kept = true;
      }
    }
    if (!kept) {
      // Stratum at quota (or nothing to steal): Algorithm R keeps a
      // uniform sample of this tenant's window — either the incoming
      // record replaces a uniformly-chosen slot, or it is the drop.
      const uint64_t j =
          splitmix64(seed ^ (idx * 0x9e3779b97f4a7c15ULL)) % st.seen;
      if (j < st.recs.size()) {
        st.recs[j] = std::move(s);
        kept = true;
      }
      vars()->dropped << 1;  // exactly one record (old or new) dropped
      b.w_dropped++;
    }
  }
  if (timeline::enabled()) {
    timeline::record(timeline::kCapture, trace,
                     ((kept ? kOpKeep : kOpDrop) << 56) |
                         (req_bytes & 0x00ffffffffffffffULL));
  }
}

void serialize_record(const Sample& s, IOBuf* out) {
  std::string payload;
  payload.reserve(kRecordFixedBytes + s.method.size() + s.tenant.size());
  append_le<uint8_t>(&payload, kRecordVersion);
  append_le<int64_t>(&payload, s.arrival_mono_us);
  append_le<int64_t>(&payload, s.arrival_wall_us);
  append_le<uint64_t>(&payload, s.trace_id);
  append_le<uint64_t>(&payload, s.parent_span_id);
  append_le<uint64_t>(&payload, s.request_bytes);
  append_le<uint64_t>(&payload, s.response_bytes);
  append_le<int32_t>(&payload, s.status);
  append_le<uint32_t>(&payload, s.queue_us);
  append_le<uint32_t>(&payload, s.handler_us);
  append_le<uint32_t>(&payload, s.deadline_budget_us);
  append_le<uint8_t>(&payload, s.priority);
  append_le<uint8_t>(&payload, static_cast<uint8_t>(s.method.size()));
  append_le<uint8_t>(&payload, static_cast<uint8_t>(s.tenant.size()));
  payload += s.method;
  payload += s.tenant;
  out->append(payload);
}

bool parse_record(const IOBuf& in, Sample* out) {
  const size_t n = in.size();
  if (n < kRecordFixedBytes) {
    return false;
  }
  std::string flat = in.to_string();
  const char* p = flat.data();
  if (static_cast<uint8_t>(p[0]) != kRecordVersion) {
    return false;
  }
  out->arrival_mono_us = read_le<int64_t>(p + 1);
  out->arrival_wall_us = read_le<int64_t>(p + 9);
  out->trace_id = read_le<uint64_t>(p + 17);
  out->parent_span_id = read_le<uint64_t>(p + 25);
  out->request_bytes = read_le<uint64_t>(p + 33);
  out->response_bytes = read_le<uint64_t>(p + 41);
  out->status = read_le<int32_t>(p + 49);
  out->queue_us = read_le<uint32_t>(p + 53);
  out->handler_us = read_le<uint32_t>(p + 57);
  out->deadline_budget_us = read_le<uint32_t>(p + 61);
  out->priority = read_le<uint8_t>(p + 65);
  const size_t mlen = static_cast<uint8_t>(p[66]);
  const size_t tlen = static_cast<uint8_t>(p[67]);
  if (n < kRecordFixedBytes + mlen + tlen) {
    return false;
  }
  out->method.assign(p + kRecordFixedBytes, mlen);
  out->tenant.assign(p + kRecordFixedBytes + mlen, tlen);
  return true;
}

std::string dump_json(size_t max_records) {
  ensure_registered();
  const WindowSnapshot w = snapshot();
  Json root = Json::object();
  root.set("pid", Json::number(getpid()));
  // Mono/wall pair read back-to-back (same contract as timeline): maps
  // this node's monotonic arrival times onto wall clock.
  root.set("now_mono_us",
           Json::number(static_cast<double>(monotonic_time_us())));
  root.set("now_wall_us",
           Json::number(static_cast<double>(realtime_us())));
  root.set("enabled", Json::boolean(enabled()));
  Json counters = Json::object();
  counters.set("seen_total",
               Json::number(static_cast<double>(seen_total())));
  counters.set("sampled_total",
               Json::number(static_cast<double>(sampled_total())));
  counters.set("dropped_total",
               Json::number(static_cast<double>(dropped_total())));
  counters.set("window_seen",
               Json::number(static_cast<double>(w.w_seen)));
  counters.set("window_sampled",
               Json::number(static_cast<double>(w.w_sampled)));
  counters.set("window_dropped",
               Json::number(static_cast<double>(w.w_dropped)));
  root.set("counters", std::move(counters));
  Json flags = Json::object();
  flags.set("max_records",
            Json::number(static_cast<double>(
                max_records_flag()->int64_value())));
  flags.set("sample_permille",
            Json::number(static_cast<double>(
                sample_permille_flag()->int64_value())));
  flags.set("seed",
            Json::number(static_cast<double>(seed_flag()->int64_value())));
  root.set("flags", std::move(flags));
  root.set("summary", build_summary(w));
  if (max_records > 0) {
    Json recs = Json::array();
    const size_t start =
        w.recs.size() > max_records ? w.recs.size() - max_records : 0;
    for (size_t i = start; i < w.recs.size(); ++i) {
      recs.push_back(record_json(w.recs[i]));
    }
    root.set("records", std::move(recs));
  }
  return root.dump();
}

int64_t dump_file(const std::string& path) {
  ensure_registered();
  const WindowSnapshot w = snapshot();
  // RecordWriter appends (rpc_dump semantics); a capture file is a
  // self-contained window — replace, never append a second header.
  std::remove(path.c_str());
  RecordWriter writer(path);
  if (!writer.valid()) {
    return -1;
  }
  Json header = Json::object();
  header.set("version", Json::number(kRecordVersion));
  header.set("pid", Json::number(getpid()));
  header.set("now_mono_us",
             Json::number(static_cast<double>(monotonic_time_us())));
  header.set("now_wall_us",
             Json::number(static_cast<double>(realtime_us())));
  Json counters = Json::object();
  counters.set("window_seen",
               Json::number(static_cast<double>(w.w_seen)));
  counters.set("window_sampled",
               Json::number(static_cast<double>(w.w_sampled)));
  counters.set("window_dropped",
               Json::number(static_cast<double>(w.w_dropped)));
  header.set("counters", std::move(counters));
  header.set("summary", build_summary(w));
  IOBuf head;
  head.append(kFileMagic, 8);
  head.append(header.dump());
  if (!writer.write(head)) {
    return -1;
  }
  for (const Sample& s : w.recs) {
    IOBuf rec;
    serialize_record(s, &rec);
    if (!writer.write(rec)) {
      return -1;
    }
  }
  writer.flush();
  if (timeline::enabled()) {
    timeline::record(timeline::kCapture, 0,
                     (kOpDump << 56) |
                         (w.recs.size() & 0x00ffffffffffffffULL));
  }
  return static_cast<int64_t>(w.recs.size());
}

void reset() {
  Buf& b = buf();
  std::lock_guard<std::mutex> g(b.mu);
  b.strata.clear();
  b.total = 0;
  b.decision_idx = 0;
  b.w_seen = 0;
  b.w_sampled = 0;
  b.w_dropped = 0;
}

uint64_t seen_total() {
  ensure_registered();
  return static_cast<uint64_t>(vars()->seen.get_value());
}

uint64_t sampled_total() {
  ensure_registered();
  return static_cast<uint64_t>(vars()->sampled.get_value());
}

uint64_t dropped_total() {
  ensure_registered();
  return static_cast<uint64_t>(vars()->dropped.get_value());
}

size_t records_held() {
  Buf& b = buf();
  std::lock_guard<std::mutex> g(b.mu);
  return b.total;
}

size_t approx_bytes() {
  Buf& b = buf();
  std::lock_guard<std::mutex> g(b.mu);
  size_t n = 0;
  for (const auto& kv : b.strata) {
    n += kv.second.recs.capacity() * sizeof(Sample);
    for (const Sample& s : kv.second.recs) {
      n += s.method.capacity() + s.tenant.capacity();
    }
  }
  return n;
}

}  // namespace capture
}  // namespace trpc
