// Production traffic capture (ISSUE 16) — a sampled per-request metadata
// recorder behind the default-off reloadable `trpc_capture` flag.
//
// Server::EnableDump (rpc_dump parity) keeps request BODIES; this tier
// keeps the TRAFFIC: per-request arrival timestamps, method, tenant and
// priority (tail-group 5), deadline budget (tail-group 7), trace/span
// ids, request/response sizes, status code, and queue + handler latency.
// That is exactly the set a replayer (tools/traffic_replay.py,
// cpp/tools/rpc_replay.cc) needs to regenerate the arrival process,
// tenant mix and size distribution that actually break a serving fleet —
// bodies alone replay *requests*, not *traffic*.
//
// Memory model: a per-tenant stratified reservoir bounded by
// `trpc_capture_max_records` records, each clamped to ~100 bytes of
// metadata regardless of body size (a 64MB request contributes 8 bytes
// of `request_bytes`).  Admission is a deterministic seeded hash of the
// per-window decision index (`trpc_capture_sample_permille`,
// `trpc_capture_seed`) so a seeded stream keeps/drops the same records
// on every run; within a full stratum, Algorithm R keeps a uniform
// sample.  Every sampled-but-not-retained record counts in
// `capture_dropped_total` — a capture that silently thins would lie
// about coverage and poison every downstream regression run.
//
// Off-cost contract (same as trpc_timeline / trpc_analysis): with the
// flag off every hook is one relaxed atomic load + branch, and the
// capture_* vars are provably frozen at 0.
//
// Readers: the /capture builtin (JSON summary + optional records +
// server-side file dump), the trpc_capture_* C API
// (brpc_tpu/rpc/capture.py), and the recordio capture file consumed by
// tools/traffic_replay.py and cpp/tools/rpc_replay.cc.
#pragma once

#include <atomic>
#include <cstdint>
#include <string>

#include "base/iobuf.h"

namespace trpc {
namespace capture {

// One captured request's metadata.  Strings are clamped at record time
// (method/tenant <= 64 bytes) so reservoir memory is bounded by record
// COUNT, never by body size.
struct Sample {
  int64_t arrival_mono_us = 0;  // monotonic arrival (parse or dispatch)
  int64_t arrival_wall_us = 0;  // wall-clock arrival (0 = derive at record)
  uint64_t trace_id = 0;
  uint64_t parent_span_id = 0;  // caller's span — fan-out tree edges
  uint64_t request_bytes = 0;
  uint64_t response_bytes = 0;
  int32_t status = 0;            // 0 ok, else kE* error code
  uint32_t queue_us = 0;         // parse -> dispatch
  uint32_t handler_us = 0;       // dispatch -> response handed off
  uint32_t deadline_budget_us = 0;  // wire tail-group 7 budget (0 = none)
  uint8_t priority = 0;          // tail-group 5
  std::string method;
  std::string tenant;            // tail-group 5 ("" = untagged)
};

// Capture-file record 0 starts with this magic, followed by a JSON
// header; records 1..N are serialize_record() payloads.  Distinguishes
// capture files from legacy EnableDump body files (whose record 0 is a
// tstd frame starting "TRP1") inside the same recordio envelope.
inline constexpr char kFileMagic[] = "TRPCCAP1";  // 8 bytes, no NUL on wire

// Backing switch for the reloadable trpc_capture flag (the flag's
// on_update hook writes it; hot-path gates inline to one relaxed load).
extern std::atomic<bool> g_enabled;

inline bool enabled() {
  return g_enabled.load(std::memory_order_relaxed);
}

// Registers flags + vars (idempotent); eager-registered at load so
// /flags can flip trpc_capture before any traffic.
void ensure_registered();

// Offers one request record to the reservoir.  Call sites MUST gate on
// enabled() themselves — record() re-checks, but the call itself should
// cost nothing when the flag is off.  Thread-safe.
void record(Sample&& s);

// JSON dump shared by /capture and trpc_capture_dump: flag state,
// lifetime + window counters, and the arrival-process summary
// (per-second rate series, burstiness CV, log2 size histograms,
// per-tenant rate/latency/error-mix, fan-out stats from trace ids).
// When max_records > 0 the newest records themselves are embedded
// (arrival order) for debugging; the binary capture file is the
// replayer's format.
std::string dump_json(size_t max_records);

// Writes the reservoir to a recordio capture file (header record +
// binary records, arrival order).  Returns records written, or -1 on
// I/O error.  The header embeds the arrival-process summary and the
// recorded per-tenant latency baseline the replay bench compares
// against.
int64_t dump_file(const std::string& path);

// Serializes one record into the capture-file binary layout (packed
// little-endian, struct format "<BqqQQQQiIIIBBB" + method + tenant).
void serialize_record(const Sample& s, IOBuf* out);
// Parses one record payload; false on truncation/bad version.  Shared
// with cpp/tools/rpc_replay.cc and the roundtrip tests.
bool parse_record(const IOBuf& in, Sample* out);

// Clears the reservoir, the window counters and the sampling decision
// index (a fresh capture window; lifetime capture_*_total vars keep
// counting — Prometheus counters never rewind).
void reset();

// Lifetime admission counters (the capture_* vars; provably frozen at 0
// while the flag has never been on).
uint64_t seen_total();     // records offered while enabled
uint64_t sampled_total();  // passed the permille sampling gate
uint64_t dropped_total();  // sampled but not retained (reservoir full)
// Records currently held / their approximate heap footprint (bounded-
// memory test support).
size_t records_held();
size_t approx_bytes();

}  // namespace capture
}  // namespace trpc
