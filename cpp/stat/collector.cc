#include "stat/collector.h"

#include "base/time.h"

namespace trpc {

Collector::Collector(int64_t samples_per_second)
    : budget_(samples_per_second), tokens_(samples_per_second) {}

void Collector::refill_if_due() {
  const int64_t now = monotonic_time_us();
  int64_t last = last_refill_us_.load(std::memory_order_relaxed);
  if (now - last < 1000000) {
    return;
  }
  if (last_refill_us_.compare_exchange_strong(last, now,
                                              std::memory_order_relaxed)) {
    tokens_.store(budget_, std::memory_order_relaxed);
  }
}

bool Collector::sample() {
  refill_if_due();
  if (tokens_.load(std::memory_order_relaxed) <= 0) {
    return false;
  }
  return tokens_.fetch_sub(1, std::memory_order_relaxed) > 0;
}

void Collector::submit(std::string bytes) {
  submitted_.fetch_add(1, std::memory_order_relaxed);
  std::lock_guard<std::mutex> g(mu_);
  queue_.push_back(std::move(bytes));
  // Bound queue growth if no drainer is attached.
  if (queue_.size() > 65536) {
    queue_.erase(queue_.begin(), queue_.begin() + 32768);
  }
}

std::vector<std::string> Collector::drain() {
  std::vector<std::string> out;
  std::lock_guard<std::mutex> g(mu_);
  out.swap(queue_);
  return out;
}

}  // namespace trpc
