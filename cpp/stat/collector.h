// Collector — bounded-rate sampled-object aggregation.
//
// Parity: bvar::Collector (/root/reference/src/bvar/collector.h): callers
// submit objects ("should I be sampled?"), a global budget caps the
// per-second intake, and a background consumer drains batches to a sink
// (the reference feeds rpc_dump and latency sampling through it).
// Condensed: a token bucket answers sampling cheaply on the hot path and
// an MPSC-ish mutex queue hands batches to the registered drainer.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <mutex>
#include <string>
#include <vector>

namespace trpc {

class Collector {
 public:
  // samples_per_second: global intake budget (reference default 1000).
  explicit Collector(int64_t samples_per_second = 1000);

  // Hot-path gate: true when the caller should hand over a sample now
  // (consumes one token).  Wait-free-ish: one fetch_sub on the bucket.
  bool sample();

  // Submits a sampled payload (only after sample() said yes).
  void submit(std::string bytes);

  // Drains everything queued since the last drain (the background
  // consumer calls this; tests call it directly).
  std::vector<std::string> drain();

  int64_t submitted() const {
    return submitted_.load(std::memory_order_relaxed);
  }

 private:
  void refill_if_due();

  const int64_t budget_;
  std::atomic<int64_t> tokens_;
  std::atomic<int64_t> last_refill_us_{0};
  std::atomic<int64_t> submitted_{0};
  std::mutex mu_;
  std::vector<std::string> queue_;
};

}  // namespace trpc
