// Process-level default variables (parity: bvar/default_variables.cpp —
// cpu, rss, fds, threads read from /proc and exposed in every /vars dump).
#include <stdio.h>
#include <string.h>
#include <unistd.h>

#include <atomic>
#include <memory>

#include "base/proc.h"
#include "base/time.h"
#include "stat/variable.h"

namespace trpc {

namespace {

// CPU: utime+stime deltas from /proc/self/stat, reported as percent of one
// core over the interval since the previous dump (pull-based).
double cpu_percent() {
  // Atomics: concurrent dumps (/vars + a metrics scrape) race otherwise.
  static std::atomic<long> last_ticks{0};
  static std::atomic<int64_t> last_us{0};
  FILE* f = fopen("/proc/self/stat", "r");
  if (f == nullptr) {
    return 0.0;
  }
  long utime = 0;
  long stime = 0;
  // Field 2 (comm) may contain spaces; skip to the closing paren.
  char buf[1024];
  if (fgets(buf, sizeof(buf), f) != nullptr) {
    const char* p = strrchr(buf, ')');
    if (p != nullptr) {
      // fields 3..15: state ppid pgrp session tty tpgid flags minflt
      // cminflt majflt cmajflt utime stime
      sscanf(p + 2, "%*c %*d %*d %*d %*d %*d %*u %*u %*u %*u %*u %ld %ld",
             &utime, &stime);
    }
  }
  fclose(f);
  const long ticks = utime + stime;
  const int64_t now = monotonic_time_us();
  const long prev_ticks = last_ticks.exchange(ticks);
  const int64_t prev_us = last_us.exchange(now);
  double pct = 0.0;
  if (prev_us != 0 && now > prev_us) {
    const double dt_s = (now - prev_us) / 1e6;
    const long hz = sysconf(_SC_CLK_TCK);
    pct = 100.0 * (ticks - prev_ticks) / (hz > 0 ? hz : 100) / dt_s;
  }
  return pct;
}

struct DefaultVars {
  PassiveStatus<long> rss{[] { return proc_status_kb("VmRSS:"); }};
  PassiveStatus<long> vsz{[] { return proc_status_kb("VmSize:"); }};
  PassiveStatus<long> threads{[] { return proc_status_kb("Threads:"); }};
  PassiveStatus<long> fds{[] { return proc_fd_count(); }};
  PassiveStatus<double> cpu{[] { return cpu_percent(); }};
  PassiveStatus<long> io_uring{
      [] { return static_cast<long>(kernel_supports("io_uring")); }};

  DefaultVars() {
    rss.expose("process_memory_rss_kb", "resident set size (VmRSS)");
    vsz.expose("process_memory_vsz_kb", "virtual size (VmSize)");
    threads.expose("process_threads", "OS thread count");
    fds.expose("process_fd_count", "open file descriptors");
    cpu.expose("process_cpu_percent",
               "CPU use since the previous dump, percent of one core");
    io_uring.expose(
        "kernel_io_uring_supported",
        "1 when the running kernel answers io_uring_setup (>= 5.1); 0 "
        "when it returns ENOSYS — the runtime capability gate for the "
        "ROADMAP io_uring data-plane backend");
  }
};

}  // namespace

// Called once from Server::Start (cheap, idempotent) so every serving
// process exports its process vars like the reference does implicitly.
void expose_default_variables() {
  static DefaultVars* v = new DefaultVars();  // leaked with the registry
  (void)v;
}

}  // namespace trpc
