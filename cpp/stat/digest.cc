#include "stat/digest.h"

#include <algorithm>
#include <cmath>
#include <cstring>

namespace trpc {

namespace {

// Append a POD value in the native (little-endian on every supported box,
// same assumption NamingWire already bakes in) layout.
template <typename T>
void put(std::string* out, T v) {
  out->append(reinterpret_cast<const char*>(&v), sizeof(T));
}

template <typename T>
bool get(const uint8_t*& p, const uint8_t* end, T* v) {
  if (static_cast<size_t>(end - p) < sizeof(T)) {
    return false;
  }
  std::memcpy(v, p, sizeof(T));
  p += sizeof(T);
  return true;
}

}  // namespace

constexpr char LatencyDigest::kMagic[];

int digest_octave_of(int64_t v) {
  if (v <= 1) {
    return 0;
  }
  if (v >= (int64_t{1} << 31)) {
    return LatencyDigest::kOctaves - 1;
  }
  const int lg = 63 - __builtin_clzll(static_cast<uint64_t>(v));
  return lg < LatencyDigest::kOctaves - 1 ? lg
                                          : LatencyDigest::kOctaves - 1;
}

void digest_merge(LatencyDigest* into, const LatencyDigest& from) {
  into->count += from.count;
  into->sum_us += from.sum_us;
  into->total_count += from.total_count;
  if (from.max_us > into->max_us) {
    into->max_us = from.max_us;
  }
  // Nodes snapshot the same wall-clock window width, so the pooled window
  // is as wide as the widest contributor and fleet qps = count/window.
  if (from.window_secs > into->window_secs) {
    into->window_secs = from.window_secs;
  }
  for (int i = 0; i < LatencyDigest::kOctaves; ++i) {
    into->oct[i].added += from.oct[i].added;
    into->oct[i].samples.insert(into->oct[i].samples.end(),
                                from.oct[i].samples.begin(),
                                from.oct[i].samples.end());
  }
}

int64_t digest_percentile_us(const LatencyDigest& d, double p) {
  // Identical rank walk to the reference recorder (percentile.h:335
  // get_number): exact per-octave counts locate the owning octave, the
  // pooled reservoir resolves the value within it.
  int64_t total = 0;
  for (int i = 0; i < LatencyDigest::kOctaves; ++i) {
    total += d.oct[i].added;
  }
  if (total == 0) {
    return 0;
  }
  int64_t n =
      static_cast<int64_t>(std::ceil(p * static_cast<double>(total)));
  if (n > total) {
    n = total;
  } else if (n < 1) {
    n = 1;
  }
  for (int i = 0; i < LatencyDigest::kOctaves; ++i) {
    const int64_t in_oct = d.oct[i].added;
    if (in_oct == 0) {
      continue;
    }
    if (n <= in_oct) {
      if (d.oct[i].samples.empty()) {
        return int64_t{1} << i;  // count but no samples: octave floor
      }
      std::vector<int64_t> merged = d.oct[i].samples;
      std::sort(merged.begin(), merged.end());
      size_t sample_n = static_cast<size_t>(
          static_cast<double>(n) * static_cast<double>(merged.size()) /
          static_cast<double>(in_oct));
      if (sample_n >= merged.size()) {
        sample_n = merged.size() - 1;
      } else if (sample_n > 0) {
        --sample_n;
      }
      return merged[sample_n];
    }
    n -= in_oct;
  }
  return d.max_us;
}

std::string digest_encode(const LatencyDigest& d) {
  std::string out;
  out.append(LatencyDigest::kMagic, 8);
  put<int64_t>(&out, d.count);
  put<int64_t>(&out, d.sum_us);
  put<int64_t>(&out, d.max_us);
  put<int64_t>(&out, d.total_count);
  put<double>(&out, d.window_secs);
  uint32_t noct = 0;
  for (int i = 0; i < LatencyDigest::kOctaves; ++i) {
    if (d.oct[i].added != 0 || !d.oct[i].samples.empty()) {
      ++noct;
    }
  }
  put<uint32_t>(&out, noct);
  for (int i = 0; i < LatencyDigest::kOctaves; ++i) {
    const auto& o = d.oct[i];
    if (o.added == 0 && o.samples.empty()) {
      continue;
    }
    put<uint32_t>(&out, static_cast<uint32_t>(i));
    put<int64_t>(&out, o.added);
    put<uint32_t>(&out, static_cast<uint32_t>(o.samples.size()));
    for (int64_t s : o.samples) {
      // u32 caps at ~71 minutes — far above octave 31's 2^31us floor
      // ever resolving finer, and well inside the one-octave error bound.
      const uint64_t clamped =
          s < 0 ? 0
                : std::min<uint64_t>(static_cast<uint64_t>(s), UINT32_MAX);
      put<uint32_t>(&out, static_cast<uint32_t>(clamped));
    }
  }
  return out;
}

size_t digest_decode(const void* data, size_t len, LatencyDigest* out) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  const uint8_t* end = p + len;
  if (len < 8 || std::memcmp(p, LatencyDigest::kMagic, 8) != 0) {
    return 0;
  }
  p += 8;
  *out = LatencyDigest();
  uint32_t noct = 0;
  if (!get(p, end, &out->count) || !get(p, end, &out->sum_us) ||
      !get(p, end, &out->max_us) || !get(p, end, &out->total_count) ||
      !get(p, end, &out->window_secs) || !get(p, end, &noct)) {
    return 0;
  }
  if (noct > LatencyDigest::kOctaves) {
    return 0;
  }
  for (uint32_t k = 0; k < noct; ++k) {
    uint32_t idx = 0, nsamp = 0;
    int64_t added = 0;
    if (!get(p, end, &idx) || !get(p, end, &added) ||
        !get(p, end, &nsamp)) {
      return 0;
    }
    if (idx >= LatencyDigest::kOctaves ||
        nsamp > static_cast<size_t>(end - p) / sizeof(uint32_t)) {
      return 0;
    }
    auto& o = out->oct[idx];
    o.added = added;
    o.samples.reserve(nsamp);
    for (uint32_t s = 0; s < nsamp; ++s) {
      uint32_t v = 0;
      if (!get(p, end, &v)) {
        return 0;
      }
      o.samples.push_back(static_cast<int64_t>(v));
    }
  }
  return static_cast<size_t>(p - static_cast<const uint8_t*>(data));
}

}  // namespace trpc
