// Mergeable latency digests — a versioned, compact binary snapshot of a
// LatencyRecorder's octave-bucketed percentile samples plus counter/qps
// state.  Digests from many nodes MERGE by octave-wise sample pooling;
// fleet percentiles come from a rank walk over the *merged* samples —
// never from averaging per-node p99s (which is statistically meaningless).
// The error bound of a merged percentile is the recorder's existing octave
// bound: the reported value lies within the owning octave [2^i, 2^(i+1)),
// i.e. within 2x of the true pooled percentile.
//
// Wire format (version marker pinned by tools/lint_trpc.py against the
// Python decoder in brpc_tpu/rpc/observe.py):
//
//   digest-wire 1 (TRPCDG01)
//     char[8]  magic = "TRPCDG01"
//     int64    count         (window total sample count)
//     int64    sum_us        (window latency sum, us)
//     int64    max_us        (max latency ever observed, us)
//     int64    total_count   (lifetime sample count — rate/qps basis)
//     double   window_secs   (seconds of data pooled into the window)
//     uint32   noct          (number of non-empty octaves that follow)
//     per octave:
//       uint32 index         (octave i: values in [2^i, 2^(i+1)) us)
//       int64  added         (exact count of values landing in octave)
//       uint32 nsamples      (reservoir samples encoded)
//       uint32 sample[nsamples]   (us; values are clamped to u32 max
//                                  ~71min, far above octave 31's floor)
//
//   digest-wire 2 (TRPCFL01)
//     Fleet node blob published via naming://: char[8] magic "TRPCFL01",
//     int64 wall_us, uint32 nentries, then per tenant entry:
//       uint16 name_len, name bytes,
//       int64 p99_target_us, double avail_target,
//       int64 fast_window_ms, int64 slow_window_ms,
//       int64 fast_total, int64 fast_bad, int64 fast_err,
//       int64 slow_total, int64 slow_bad, int64 slow_err,
//       double burn_fast, double burn_slow, uint8 breached,
//       <digest>  (one TRPCDG01 block, variable length)
//     (Encoded by SloEngine::encode_blob in cpp/stat/slo.cc; decoded by
//      observe.decode_fleet_blob.)
#pragma once

#include <array>
#include <cstdint>
#include <string>
#include <vector>

namespace trpc {

struct LatencyDigest {
  static constexpr int kOctaves = 32;
  static constexpr char kMagic[9] = "TRPCDG01";

  struct Oct {
    int64_t added = 0;                 // exact per-octave count
    std::vector<int64_t> samples;      // reservoir sample values (us)
  };

  int64_t count = 0;        // window sample count
  int64_t sum_us = 0;       // window latency sum
  int64_t max_us = 0;       // lifetime max
  int64_t total_count = 0;  // lifetime count
  double window_secs = 0;   // seconds pooled into the window
  std::array<Oct, kOctaves> oct;

  bool empty() const { return count == 0; }
  double qps() const {
    return window_secs > 0 ? static_cast<double>(count) / window_secs : 0.0;
  }
  double avg_us() const {
    return count > 0 ? static_cast<double>(sum_us) / count : 0.0;
  }
};

// Octave index of a value: clamped floor(log2(v)).  Mirrors the recorder's
// internal bucketing so pooled digests and live recorders agree.
int digest_octave_of(int64_t v);

// Octave-wise pooling: adds `from` into `into` (counts sum, reservoirs
// concatenate, max takes max, window spans take max — nodes snapshot the
// same wall window, so pooled qps = sum(count)/window).
void digest_merge(LatencyDigest* into, const LatencyDigest& from);

// Rank walk over the pooled samples: identical math to
// LatencyRecorder::percentile_over (which delegates here), so a merged
// fleet percentile carries the same one-octave error bound as a single
// node's.  p in (0,1].  Returns 0 for an empty digest.
int64_t digest_percentile_us(const LatencyDigest& d, double p);

// Versioned binary encode/decode.  decode returns the number of bytes
// consumed, or 0 on malformed input; `len` may extend past the digest
// (fleet blobs embed digests back-to-back).
std::string digest_encode(const LatencyDigest& d);
size_t digest_decode(const void* data, size_t len, LatencyDigest* out);

}  // namespace trpc
