#include "stat/heap_profiler.h"

#include <execinfo.h>
#include <stdio.h>
#include <stdlib.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <new>
#include <unordered_map>
#include <vector>

namespace trpc {

namespace {

constexpr size_t kSamplePeriod = 512 * 1024;  // bytes between samples
constexpr int kMaxDepth = 16;

struct AllocRecord {
  size_t size = 0;
  int depth = 0;
  void* frames[kMaxDepth];
};

std::atomic<bool> g_on{false};
// Fast-path gate for frees: true while the live table MAY hold entries
// (it outlives g_on so records retire correctly after stop()).
std::atomic<bool> g_have_records{false};
std::atomic<size_t> g_bytes_since{0};

// Set while THIS thread is inside profiler bookkeeping: the table's own
// allocations must not recurse into sampling.
thread_local bool tl_in_hook = false;

std::mutex& table_mu() {
  static std::mutex* m = new std::mutex();
  return *m;
}

std::unordered_map<void*, AllocRecord>& live_table() {
  static auto* t = new std::unordered_map<void*, AllocRecord>();
  return *t;
}

void maybe_sample(void* p, size_t sz) {
  if (p == nullptr || tl_in_hook ||
      !g_on.load(std::memory_order_relaxed)) {
    return;
  }
  const size_t before =
      g_bytes_since.fetch_add(sz, std::memory_order_relaxed);
  if (before + sz < kSamplePeriod) {
    return;  // period not yet crossed
  }
  // This thread crossed the period boundary: claim the sample (the racy
  // reset loses at most one concurrent sample — fine for a sampler).
  g_bytes_since.store(0, std::memory_order_relaxed);
  tl_in_hook = true;
  AllocRecord rec;
  rec.size = sz;
  rec.depth = backtrace(rec.frames, kMaxDepth);
  {
    std::lock_guard<std::mutex> g(table_mu());
    auto& t = live_table();
    if (t.size() < 65536) {  // bound the table
      t[p] = rec;
      g_have_records.store(true, std::memory_order_relaxed);
    }
  }
  tl_in_hook = false;
}

void maybe_retire(void* p) {
  if (p == nullptr || tl_in_hook ||
      !g_have_records.load(std::memory_order_relaxed)) {
    return;
  }
  tl_in_hook = true;
  {
    std::lock_guard<std::mutex> g(table_mu());
    live_table().erase(p);
  }
  tl_in_hook = false;
}

}  // namespace

// External linkage: the operator overrides below live outside the trpc
// namespace and funnel here.
void* alloc_impl(size_t sz) {
  void* p = malloc(sz);
  maybe_sample(p, sz);
  return p;
}

void* alloc_aligned_impl(size_t sz, size_t align) {
  void* p = nullptr;
  if (posix_memalign(&p, align, sz) != 0) {
    p = nullptr;
  }
  maybe_sample(p, sz);
  return p;
}

void free_impl(void* p) {
  maybe_retire(p);
  free(p);
}

bool heap_profiler_start() {
  void* warm[4];
  backtrace(warm, 4);  // pre-load the unwinder outside hot paths
  table_mu();          // and construct the leaked singletons
  live_table();
  g_bytes_since.store(0, std::memory_order_relaxed);
  g_on.store(true, std::memory_order_release);
  return true;
}

bool heap_profiler_running() {
  return g_on.load(std::memory_order_acquire);
}

void heap_profiler_stop() {
  g_on.store(false, std::memory_order_release);
  tl_in_hook = true;
  {
    std::lock_guard<std::mutex> g(table_mu());
    live_table().clear();
    g_have_records.store(false, std::memory_order_relaxed);
  }
  tl_in_hook = false;
}

std::string heap_profiler_dump() {
  // Aggregate live records by stack.
  struct StackStat {
    int64_t count = 0;
    int64_t bytes = 0;
  };
  std::map<std::vector<void*>, StackStat> by_stack;
  int64_t total_count = 0;
  int64_t total_bytes = 0;
  tl_in_hook = true;
  {
    std::lock_guard<std::mutex> g(table_mu());
    for (const auto& [p, rec] : live_table()) {
      // frames[0..1] are the profiler's own bookkeeping frames.
      const int skip = rec.depth > 2 ? 2 : 0;
      std::vector<void*> key(rec.frames + skip, rec.frames + rec.depth);
      StackStat& s = by_stack[key];
      s.count += 1;
      s.bytes += static_cast<int64_t>(rec.size);
      total_count += 1;
      total_bytes += static_cast<int64_t>(rec.size);
    }
  }
  tl_in_hook = false;

  char line[512];
  snprintf(line, sizeof(line),
           "heap profile: %6lld: %8lld [%6lld: %8lld] @ heap_v2/%zu\n",
           static_cast<long long>(total_count),
           static_cast<long long>(total_bytes),
           static_cast<long long>(total_count),
           static_cast<long long>(total_bytes), kSamplePeriod);
  std::string out = line;
  for (const auto& [frames, st] : by_stack) {
    snprintf(line, sizeof(line), "%6lld: %8lld [%6lld: %8lld] @",
             static_cast<long long>(st.count),
             static_cast<long long>(st.bytes),
             static_cast<long long>(st.count),
             static_cast<long long>(st.bytes));
    out += line;
    for (void* pc : frames) {
      snprintf(line, sizeof(line), " %p", pc);
      out += line;
    }
    out += "\n";
  }
  out += "\nMAPPED_LIBRARIES:\n";
  FILE* maps = fopen("/proc/self/maps", "r");
  if (maps != nullptr) {
    char buf[4096];
    size_t n;
    while ((n = fread(buf, 1, sizeof(buf), maps)) > 0) {
      out.append(buf, n);
    }
    fclose(maps);
  }
  return out;
}

}  // namespace trpc

// ---- global operator new/delete overrides --------------------------------
// Every variant funnels into alloc_impl/free_impl; while the profiler is
// off the added cost is one relaxed atomic load per call.

void* operator new(size_t sz) {
  void* p = trpc::alloc_impl(sz);
  if (p == nullptr) {
    throw std::bad_alloc();
  }
  return p;
}
void* operator new[](size_t sz) { return operator new(sz); }
void* operator new(size_t sz, const std::nothrow_t&) noexcept {
  return trpc::alloc_impl(sz);
}
void* operator new[](size_t sz, const std::nothrow_t&) noexcept {
  return trpc::alloc_impl(sz);
}
void* operator new(size_t sz, std::align_val_t al) {
  void* p = trpc::alloc_aligned_impl(sz, static_cast<size_t>(al));
  if (p == nullptr) {
    throw std::bad_alloc();
  }
  return p;
}
void* operator new[](size_t sz, std::align_val_t al) {
  return operator new(sz, al);
}
void* operator new(size_t sz, std::align_val_t al,
                   const std::nothrow_t&) noexcept {
  return trpc::alloc_aligned_impl(sz, static_cast<size_t>(al));
}
void* operator new[](size_t sz, std::align_val_t al,
                     const std::nothrow_t&) noexcept {
  return trpc::alloc_aligned_impl(sz, static_cast<size_t>(al));
}

void operator delete(void* p) noexcept { trpc::free_impl(p); }
void operator delete[](void* p) noexcept { trpc::free_impl(p); }
void operator delete(void* p, size_t) noexcept { trpc::free_impl(p); }
void operator delete[](void* p, size_t) noexcept { trpc::free_impl(p); }
void operator delete(void* p, std::align_val_t) noexcept {
  trpc::free_impl(p);
}
void operator delete[](void* p, std::align_val_t) noexcept {
  trpc::free_impl(p);
}
void operator delete(void* p, size_t, std::align_val_t) noexcept {
  trpc::free_impl(p);
}
void operator delete[](void* p, size_t, std::align_val_t) noexcept {
  trpc::free_impl(p);
}
void operator delete(void* p, const std::nothrow_t&) noexcept {
  trpc::free_impl(p);
}
void operator delete[](void* p, const std::nothrow_t&) noexcept {
  trpc::free_impl(p);
}
