// Sampling heap profiler — live allocations by call stack.
//
// Parity: the reference exposes tcmalloc's heap profile through
// /pprof/heap (/root/reference/src/brpc/details/tcmalloc_extension.h:72,
// builtin/pprof_service.h).  This image has no tcmalloc, so the runtime
// carries its own sampler: global operator new/delete overrides count
// allocated bytes and record one call stack per ~512KB allocated; frees
// of sampled pointers retire their records, so the aggregate approximates
// LIVE bytes by allocation site.  Overhead while disabled is one relaxed
// atomic load per new/delete.
//
// Dump format is gperftools' text heap profile ("heap profile: ... @
// heap_v2/<period>" + per-stack lines + MAPPED_LIBRARIES), which standard
// pprof tooling parses.
#pragma once

#include <string>

namespace trpc {

// Enables sampling (idempotent).  Returns false if unavailable.
bool heap_profiler_start();
bool heap_profiler_running();
// Renders the live heap profile (empty-profile header when off).
std::string heap_profiler_dump();
// Disables sampling and drops the live-record table.
void heap_profiler_stop();

}  // namespace trpc
