#include "stat/latency_recorder.h"

#include <algorithm>
#include <cmath>

#include "base/rand.h"
#include "stat/sampler.h"

namespace trpc {

namespace {

// Value → octave index (reference detail/percentile.cpp:51
// get_interval_index — log2 bucketing, clamped).
inline int octave_of(int64_t v) {
  if (v <= 1) {
    return 0;
  }
  if (v >= (int64_t{1} << 31)) {
    return LatencyRecorder::kNumOctaves - 1;
  }
  const int lg = 63 - __builtin_clzll(static_cast<uint64_t>(v));
  return lg < LatencyRecorder::kNumOctaves - 1
             ? lg
             : LatencyRecorder::kNumOctaves - 1;
}

}  // namespace

LatencyRecorder::LatencyRecorder() {
  window_.resize(kWindowSecs);
  Sampler::instance()->add(this);
}

LatencyRecorder::~LatencyRecorder() {
  hide();  // deregister from /vars BEFORE members start dying
  Sampler::instance()->remove(this);
}

void LatencyRecorder::operator<<(int64_t latency_us) {
  if (latency_us < 0) {
    latency_us = 0;
  }
  interval_count_.fetch_add(1, std::memory_order_relaxed);
  interval_sum_.fetch_add(latency_us, std::memory_order_relaxed);
  total_count_.fetch_add(1, std::memory_order_relaxed);
  int64_t cur_max = max_us_.load(std::memory_order_relaxed);
  while (latency_us > cur_max &&
         !max_us_.compare_exchange_weak(cur_max, latency_us,
                                        std::memory_order_relaxed)) {
  }
  std::lock_guard<std::mutex> g(res_mu_);
  Octave& o = active_[octave_of(latency_us)];
  ++o.added;
  if (static_cast<int>(o.samples.size()) < kOctaveSamples) {
    o.samples.push_back(latency_us);
  } else {
    // Per-octave reservoir keeps the sample uniform within its octave.
    const uint64_t j = fast_rand_less_than(static_cast<uint64_t>(o.added));
    if (j < static_cast<uint64_t>(kOctaveSamples)) {
      o.samples[j] = latency_us;
    }
  }
}

void LatencyRecorder::take_sample() {
  Second sec;
  {
    std::lock_guard<std::mutex> g(res_mu_);
    for (int i = 0; i < kNumOctaves; ++i) {
      if (active_[i].added != 0) {
        sec.oct[i].added = active_[i].added;
        sec.oct[i].samples.swap(active_[i].samples);
        active_[i].added = 0;
      }
    }
  }
  sec.count = interval_count_.exchange(0, std::memory_order_relaxed);
  sec.sum = interval_sum_.exchange(0, std::memory_order_relaxed);
  for (int i = 0; i < kNumOctaves; ++i) {
    std::sort(sec.oct[i].samples.begin(), sec.oct[i].samples.end());
  }
  std::lock_guard<std::mutex> g(window_mu_);
  window_[window_pos_] = std::move(sec);
  window_pos_ = (window_pos_ + 1) % kWindowSecs;
}

int64_t LatencyRecorder::qps() const {
  std::lock_guard<std::mutex> g(window_mu_);
  int64_t total = 0;
  int secs = 0;
  for (const Second& s : window_) {
    total += s.count;
    ++secs;
  }
  return secs > 0 ? total / secs : 0;
}

int64_t LatencyRecorder::latency_avg_us() const {
  {
    std::lock_guard<std::mutex> g(window_mu_);
    int64_t total = 0, cnt = 0;
    for (const Second& s : window_) {
      total += s.sum;
      cnt += s.count;
    }
    if (cnt > 0) {
      return total / cnt;
    }
  }
  // Window empty (recorder younger than one sampler tick): the live
  // interval's running sum keeps fresh in-process reads meaningful.
  const int64_t cnt = interval_count_.load(std::memory_order_relaxed);
  return cnt > 0 ? interval_sum_.load(std::memory_order_relaxed) / cnt
                 : 0;
}

int64_t LatencyRecorder::percentile_over(
    const std::vector<const Second*>& secs, double p,
    int64_t* total_out) const {
  // Pool the seconds into a digest and delegate to the shared rank walk
  // (digest_percentile_us — reference percentile.h:335 get_number).  One
  // implementation serves both the live recorder and merged fleet
  // digests, so both carry the identical one-octave error bound.  Seconds
  // contribute ≤kOctaveSamples each regardless of their added count — a
  // mild bias WITHIN the owning octave, so the result still lies inside
  // the correct [2^i, 2^(i+1)) band (the bounded-error contract).
  LatencyDigest d;
  for (const Second* s : secs) {
    for (int i = 0; i < kNumOctaves; ++i) {
      d.oct[i].added += s->oct[i].added;
      d.count += s->oct[i].added;
      d.oct[i].samples.insert(d.oct[i].samples.end(),
                              s->oct[i].samples.begin(),
                              s->oct[i].samples.end());
    }
  }
  *total_out = d.count;
  d.max_us = max_us_.load(std::memory_order_relaxed);
  return digest_percentile_us(d, p);
}

int64_t LatencyRecorder::latency_percentile_us(double p) const {
  {
    std::lock_guard<std::mutex> g(window_mu_);
    std::vector<const Second*> secs;
    secs.reserve(window_.size());
    for (const Second& s : window_) {
      secs.push_back(&s);
    }
    int64_t total = 0;
    const int64_t r = percentile_over(secs, p, &total);
    if (total > 0) {
      return r;
    }
  }
  // Window empty — the sampler thread hasn't rotated a full second into
  // it yet.  An in-process reader (trpc_latency_read right after a burst
  // of calls) should see the live interval, not zeros, so snapshot the
  // active octaves and walk those instead.  percentile_over sorts its
  // own merged copy, so the unsorted active samples are fine.
  Second live;
  {
    std::lock_guard<std::mutex> g(res_mu_);
    for (int i = 0; i < kNumOctaves; ++i) {
      live.oct[i].added = active_[i].added;
      live.oct[i].samples = active_[i].samples;
    }
  }
  std::vector<const Second*> secs{&live};
  int64_t total = 0;
  return percentile_over(secs, p, &total);
}

int64_t LatencyRecorder::latency_max_us() const {
  return max_us_.load(std::memory_order_relaxed);
}

void LatencyRecorder::read_stats(double out[8]) const {
  static const double kQuantiles[4] = {0.5, 0.9, 0.99, 0.999};
  out[0] = static_cast<double>(count());
  out[7] = static_cast<double>(latency_max_us());
  {
    std::lock_guard<std::mutex> g(window_mu_);
    std::vector<const Second*> secs;
    secs.reserve(window_.size());
    int64_t sum = 0, cnt = 0;
    for (const Second& s : window_) {
      secs.push_back(&s);
      sum += s.sum;
      cnt += s.count;
    }
    out[1] = window_.empty()
                 ? 0.0
                 : static_cast<double>(cnt) /
                       static_cast<double>(window_.size());
    if (cnt > 0) {
      out[2] = static_cast<double>(sum / cnt);
      int64_t total = 0;
      for (int i = 0; i < 4; ++i) {
        out[3 + i] =
            static_cast<double>(percentile_over(secs, kQuantiles[i],
                                                &total));
      }
      if (total > 0) {
        return;
      }
    }
  }
  // Window empty: live-interval fallback, one snapshot for all four
  // quantiles (mirrors latency_percentile_us's fresh-recorder path).
  const int64_t icnt = interval_count_.load(std::memory_order_relaxed);
  out[2] = icnt > 0 ? static_cast<double>(
                          interval_sum_.load(std::memory_order_relaxed) /
                          icnt)
                    : 0.0;
  Second live;
  {
    std::lock_guard<std::mutex> g(res_mu_);
    for (int i = 0; i < kNumOctaves; ++i) {
      live.oct[i].added = active_[i].added;
      live.oct[i].samples = active_[i].samples;
    }
  }
  std::vector<const Second*> secs{&live};
  int64_t total = 0;
  for (int i = 0; i < 4; ++i) {
    out[3 + i] = static_cast<double>(
        percentile_over(secs, kQuantiles[i], &total));
  }
}

void LatencyRecorder::snapshot_digest(LatencyDigest* out) const {
  *out = LatencyDigest();
  {
    std::lock_guard<std::mutex> g(window_mu_);
    out->window_secs = static_cast<double>(
        window_.empty() ? 1 : window_.size());
    for (const Second& s : window_) {
      out->count += s.count;
      out->sum_us += s.sum;
      for (int i = 0; i < kNumOctaves; ++i) {
        out->oct[i].added += s.oct[i].added;
        out->oct[i].samples.insert(out->oct[i].samples.end(),
                                   s.oct[i].samples.begin(),
                                   s.oct[i].samples.end());
      }
    }
  }
  {
    // Fold in the live interval so a recorder younger than one sampler
    // tick still publishes its traffic (same fallback the read paths use).
    std::lock_guard<std::mutex> g(res_mu_);
    for (int i = 0; i < kNumOctaves; ++i) {
      out->oct[i].added += active_[i].added;
      out->oct[i].samples.insert(out->oct[i].samples.end(),
                                 active_[i].samples.begin(),
                                 active_[i].samples.end());
    }
  }
  out->count += interval_count_.load(std::memory_order_relaxed);
  out->sum_us += interval_sum_.load(std::memory_order_relaxed);
  out->max_us = max_us_.load(std::memory_order_relaxed);
  out->total_count = total_count_.load(std::memory_order_relaxed);
}

std::string LatencyRecorder::prometheus_str(const std::string& name) const {
  const std::string metric = sanitize_metric_name(name);
  std::string out;
  if (!description().empty()) {
    out += "# HELP " + metric + "_latency_us " +
           escape_help(description()) + "\n";
  }
  out += "# TYPE " + metric + "_latency_us summary\n";
  static const std::pair<const char*, double> kQuantiles[] = {
      {"0.5", 0.5}, {"0.9", 0.9}, {"0.99", 0.99}, {"0.999", 0.999}};
  for (const auto& [label, q] : kQuantiles) {
    out += metric + "_latency_us{quantile=\"" + label + "\"} " +
           std::to_string(latency_percentile_us(q)) + "\n";
  }
  out += "# TYPE " + metric + "_qps gauge\n" + metric + "_qps " +
         std::to_string(qps()) + "\n";
  // The cumulative call count is monotonic: counter-typed with the
  // conventional `_total` suffix (the bare `_count` form collided with
  // the Prometheus summary's reserved `<name>_count` series anyway).
  out += "# TYPE " + metric + "_count_total counter\n" + metric +
         "_count_total " + std::to_string(count()) + "\n";
  out += "# TYPE " + metric + "_latency_max_us gauge\n" + metric +
         "_latency_max_us " + std::to_string(latency_max_us()) + "\n";
  return out;
}

std::string LatencyRecorder::value_str() const {
  return "{\"qps\":" + std::to_string(qps()) +
         ",\"avg_us\":" + std::to_string(latency_avg_us()) +
         ",\"p50_us\":" + std::to_string(latency_percentile_us(0.5)) +
         ",\"p99_us\":" + std::to_string(latency_percentile_us(0.99)) +
         ",\"p999_us\":" + std::to_string(latency_percentile_us(0.999)) +
         ",\"max_us\":" + std::to_string(latency_max_us()) +
         ",\"count\":" + std::to_string(count()) + "}";
  // NOTE: shape must stay stable — tests and dashboards parse these keys.
}

}  // namespace trpc
