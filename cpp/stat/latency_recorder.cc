#include "stat/latency_recorder.h"

#include <algorithm>

#include "base/rand.h"
#include "stat/sampler.h"

namespace trpc {

LatencyRecorder::LatencyRecorder() {
  reservoir_.reserve(kReservoir);
  window_.resize(kWindowSecs);
  Sampler::instance()->add(this);
}

LatencyRecorder::~LatencyRecorder() {
  hide();  // deregister from /vars BEFORE members start dying
  Sampler::instance()->remove(this);
}

void LatencyRecorder::operator<<(int64_t latency_us) {
  const int64_t n = interval_count_.fetch_add(1, std::memory_order_relaxed);
  interval_sum_.fetch_add(latency_us, std::memory_order_relaxed);
  total_count_.fetch_add(1, std::memory_order_relaxed);
  int64_t cur_max = max_us_.load(std::memory_order_relaxed);
  while (latency_us > cur_max &&
         !max_us_.compare_exchange_weak(cur_max, latency_us,
                                        std::memory_order_relaxed)) {
  }
  std::lock_guard<std::mutex> g(res_mu_);
  if (static_cast<int>(reservoir_.size()) < kReservoir) {
    reservoir_.push_back(latency_us);
  } else {
    // Reservoir sampling keeps the sample uniform over the interval.
    const uint64_t j = fast_rand_less_than(static_cast<uint64_t>(n) + 1);
    if (j < kReservoir) {
      reservoir_[j] = latency_us;
    }
  }
}

void LatencyRecorder::take_sample() {
  Second sec;
  {
    std::lock_guard<std::mutex> g(res_mu_);
    sec.sorted_latencies.swap(reservoir_);
    reservoir_.reserve(kReservoir);
  }
  sec.count = interval_count_.exchange(0, std::memory_order_relaxed);
  sec.sum = interval_sum_.exchange(0, std::memory_order_relaxed);
  std::sort(sec.sorted_latencies.begin(), sec.sorted_latencies.end());
  std::lock_guard<std::mutex> g(window_mu_);
  window_[window_pos_] = std::move(sec);
  window_pos_ = (window_pos_ + 1) % kWindowSecs;
}

int64_t LatencyRecorder::qps() const {
  std::lock_guard<std::mutex> g(window_mu_);
  int64_t total = 0;
  int secs = 0;
  for (const Second& s : window_) {
    total += s.count;
    ++secs;
  }
  return secs > 0 ? total / secs : 0;
}

int64_t LatencyRecorder::latency_avg_us() const {
  std::lock_guard<std::mutex> g(window_mu_);
  int64_t total = 0, cnt = 0;
  for (const Second& s : window_) {
    total += s.sum;
    cnt += s.count;
  }
  return cnt > 0 ? total / cnt : 0;
}

int64_t LatencyRecorder::latency_percentile_us(double p) const {
  std::lock_guard<std::mutex> g(window_mu_);
  std::vector<int64_t> merged;
  for (const Second& s : window_) {
    merged.insert(merged.end(), s.sorted_latencies.begin(),
                  s.sorted_latencies.end());
  }
  if (merged.empty()) {
    return 0;
  }
  std::sort(merged.begin(), merged.end());
  const size_t idx = std::min(merged.size() - 1,
                              static_cast<size_t>(p * merged.size()));
  return merged[idx];
}

int64_t LatencyRecorder::latency_max_us() const {
  return max_us_.load(std::memory_order_relaxed);
}

std::string LatencyRecorder::prometheus_str(const std::string& name) const {
  const std::string metric = sanitize_metric_name(name);
  std::string out = "# TYPE " + metric + "_latency_us summary\n";
  static const std::pair<const char*, double> kQuantiles[] = {
      {"0.5", 0.5}, {"0.9", 0.9}, {"0.99", 0.99}, {"0.999", 0.999}};
  for (const auto& [label, q] : kQuantiles) {
    out += metric + "_latency_us{quantile=\"" + label + "\"} " +
           std::to_string(latency_percentile_us(q)) + "\n";
  }
  out += "# TYPE " + metric + "_qps gauge\n" + metric + "_qps " +
         std::to_string(qps()) + "\n";
  out += "# TYPE " + metric + "_count counter\n" + metric + "_count " +
         std::to_string(count()) + "\n";
  out += "# TYPE " + metric + "_latency_max_us gauge\n" + metric +
         "_latency_max_us " + std::to_string(latency_max_us()) + "\n";
  return out;
}

std::string LatencyRecorder::value_str() const {
  return "{\"qps\":" + std::to_string(qps()) +
         ",\"avg_us\":" + std::to_string(latency_avg_us()) +
         ",\"p50_us\":" + std::to_string(latency_percentile_us(0.5)) +
         ",\"p99_us\":" + std::to_string(latency_percentile_us(0.99)) +
         ",\"p999_us\":" + std::to_string(latency_percentile_us(0.999)) +
         ",\"max_us\":" + std::to_string(latency_max_us()) +
         ",\"count\":" + std::to_string(count()) + "}";
}

}  // namespace trpc
