// LatencyRecorder — per-second qps/avg/percentiles.
//
// Parity: bvar::LatencyRecorder (/root/reference/src/bvar/
// latency_recorder.h:32-75 over detail/percentile.h reservoir sampling and
// the one-background-thread Sampler, detail/sampler.cpp:60-135).
// Re-designed: one reservoir per recorder, swapped each second by the
// sampler thread into a trailing window of sorted snapshots.
#pragma once

#include <atomic>
#include <cstdint>
#include <mutex>
#include <string>
#include <vector>

#include "stat/reducer.h"
#include "stat/sampler.h"
#include "stat/variable.h"

namespace trpc {

class LatencyRecorder : public Variable, public Sampled {
 public:
  static constexpr int kReservoir = 1024;
  static constexpr int kWindowSecs = 10;

  LatencyRecorder();
  ~LatencyRecorder() override;

  void operator<<(int64_t latency_us);

  int64_t qps() const;              // trailing-window average per second
  int64_t latency_avg_us() const;   // trailing window
  int64_t latency_percentile_us(double p) const;  // 0 < p < 1
  int64_t latency_max_us() const;
  int64_t count() const { return total_count_.load(std::memory_order_relaxed); }

  std::string value_str() const override;
  // Quantile/qps/count series (prometheus_metrics_service parity).
  std::string prometheus_str(const std::string& name) const override;

  // Called by the sampler thread once per second.
  void take_sample() override;

 private:
  struct Second {
    std::vector<int64_t> sorted_latencies;
    int64_t count = 0;
    int64_t sum = 0;
  };

  // Active reservoir (written by hot path, swapped by sampler).
  mutable std::mutex res_mu_;
  std::vector<int64_t> reservoir_;
  std::atomic<int64_t> interval_count_{0};
  std::atomic<int64_t> interval_sum_{0};
  std::atomic<int64_t> total_count_{0};
  std::atomic<int64_t> max_us_{0};

  mutable std::mutex window_mu_;
  std::vector<Second> window_;  // ring of last kWindowSecs
  size_t window_pos_ = 0;
};

}  // namespace trpc
