// LatencyRecorder — per-second qps/avg/percentiles.
//
// Parity: bvar::LatencyRecorder (/root/reference/src/bvar/
// latency_recorder.h:32-75 over detail/percentile.h).  The reference's
// central idea — kept here, replacing the r4 flat reservoir — is that
// samples are bucketed by VALUE OCTAVE (detail/percentile.cpp:51
// get_interval_index: interval = log2(latency)), 32 intervals each with
// its own bounded uniform sample set + exact added count
// (detail/percentile.h:52 PercentileInterval, :280 PercentileSamples,
// :507 get_number's rank walk).  A percentile first walks octaves by
// exact counts, then indexes proportionally into the owning octave's
// samples — so the error is bounded by one octave's sample resolution
// and a rare tail (1% of traffic at 100x the median) gets its own
// octave's entire sample budget instead of ~1% of a shared reservoir.
// Windows combine per-second interval snapshots (the reference's
// ReducerSampler window), mixing no epochs older than kWindowSecs.
#pragma once

#include <array>
#include <atomic>
#include <cstdint>
#include <mutex>
#include <string>
#include <vector>

#include "stat/digest.h"
#include "stat/reducer.h"
#include "stat/sampler.h"
#include "stat/variable.h"

namespace trpc {

class LatencyRecorder : public Variable, public Sampled {
 public:
  static constexpr int kNumOctaves = 32;     // value range [2^i, 2^(i+1))
  static constexpr int kOctaveSamples = 64;  // per octave per second
  static constexpr int kWindowSecs = 10;

  LatencyRecorder();
  ~LatencyRecorder() override;

  void operator<<(int64_t latency_us);

  int64_t qps() const;              // trailing-window average per second
  int64_t latency_avg_us() const;   // trailing window
  int64_t latency_percentile_us(double p) const;  // 0 < p < 1
  int64_t latency_max_us() const;
  // One-pass bulk read for the C API (trpc_latency_read): fills
  // out[8] = {count, qps, avg_us, p50, p90, p99, p999, max_us} taking
  // the window lock ONCE for all four quantiles — callers hold the
  // global var-registry mutex around this, so per-quantile re-locking
  // and re-snapshotting would multiply that critical section by five.
  void read_stats(double out[8]) const;
  int64_t count() const { return total_count_.load(std::memory_order_relaxed); }

  // Mergeable snapshot: pools the trailing window (plus the live interval,
  // so fresh recorders aren't empty) into a LatencyDigest — octave counts
  // and reservoirs, window span, lifetime count/max.  Fleet aggregation
  // merges digests octave-wise and rank-walks the pooled samples
  // (digest_percentile_us — the same walk percentile_over delegates to),
  // keeping the one-octave error bound.
  void snapshot_digest(LatencyDigest* out) const;

  std::string value_str() const override;
  // Quantile/qps/count series (prometheus_metrics_service parity).
  std::string prometheus_str(const std::string& name) const override;

  // Called by the sampler thread once per second.
  void take_sample() override;

 private:
  // One value octave's per-second state: exact count + a uniform sample
  // (reservoir capped at kOctaveSamples; values inside span at most 2x,
  // which is what bounds the percentile error).
  struct Octave {
    int64_t added = 0;
    std::vector<int64_t> samples;
  };
  struct Second {
    std::array<Octave, kNumOctaves> oct;
    int64_t count = 0;
    int64_t sum = 0;
  };

  // Rank-walk percentile over a set of per-second snapshots (samples need
  // not be pre-sorted).  *total_out = combined exact add count; the
  // return value is meaningless when it is 0.
  int64_t percentile_over(const std::vector<const Second*>& secs, double p,
                          int64_t* total_out) const;

  // Active interval (written by hot path, swapped by sampler each second).
  mutable std::mutex res_mu_;
  std::array<Octave, kNumOctaves> active_;
  std::atomic<int64_t> interval_count_{0};
  std::atomic<int64_t> interval_sum_{0};
  std::atomic<int64_t> total_count_{0};
  std::atomic<int64_t> max_us_{0};

  mutable std::mutex window_mu_;
  std::vector<Second> window_;  // ring of last kWindowSecs
  size_t window_pos_ = 0;
};

}  // namespace trpc
