#include "stat/mvariable.h"

namespace trpc {

namespace {
// Prometheus label-value escaping: backslash, quote, newline.
std::string escape_label(const std::string& v) {
  std::string out;
  out.reserve(v.size());
  for (char c : v) {
    if (c == '\\' || c == '"') {
      out.push_back('\\');
      out.push_back(c);
    } else if (c == '\n') {
      out += "\\n";
    } else {
      out.push_back(c);
    }
  }
  return out;
}
}  // namespace

void MAdder::add(const std::vector<std::string>& label_values,
                 int64_t delta) {
  if (label_values.size() != label_names_.size()) {
    return;  // dimensional mismatch: drop (reference CHECKs; we degrade)
  }
  std::lock_guard<std::mutex> g(mu_);
  series_[label_values] += delta;
}

int64_t MAdder::get(const std::vector<std::string>& label_values) const {
  std::lock_guard<std::mutex> g(mu_);
  auto it = series_.find(label_values);
  return it == series_.end() ? 0 : it->second;
}

size_t MAdder::count_series() const {
  std::lock_guard<std::mutex> g(mu_);
  return series_.size();
}

std::string MAdder::value_str() const {
  std::lock_guard<std::mutex> g(mu_);
  std::string out;
  for (const auto& [labels, v] : series_) {
    out += "{";
    for (size_t i = 0; i < labels.size(); ++i) {
      out += (i != 0 ? "," : "") + label_names_[i] + "=" + labels[i];
    }
    out += "}=" + std::to_string(v) + " ";
  }
  return out;
}

std::string MAdder::prometheus_str(const std::string& name) const {
  // Labeled adders are monotonic: `_total`-suffixed like scalar counters.
  const std::string metric =
      ensure_total_suffix(sanitize_metric_name(name));
  std::lock_guard<std::mutex> g(mu_);
  std::string out = "# TYPE " + metric + " counter\n";
  for (const auto& [labels, v] : series_) {
    out += metric + "{";
    for (size_t i = 0; i < labels.size(); ++i) {
      out += (i != 0 ? "," : "") + sanitize_metric_name(label_names_[i]) +
             "=\"" + escape_label(labels[i]) + "\"";
    }
    out += "} " + std::to_string(v) + "\n";
  }
  return out;
}

}  // namespace trpc
