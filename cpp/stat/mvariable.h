// MVariable — labeled (multi-dimensional) metrics.
//
// Parity: bvar::MVariable (/root/reference/src/bvar/multi_dimension.h):
// one logical metric fanned out over label tuples, each combination
// backed by its own underlying variable, dumped as labeled Prometheus
// series.  Condensed: a mutex-guarded map from label values to a stat
// object; the hot path (per-label add) is the underlying reducer's
// thread-local combine, the map lookup amortizes via a caller-held
// handle.
#pragma once

#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "stat/variable.h"

namespace trpc {

// M-dimensional counter family: MAdder("rpc_errors", {"method", "code"}).
class MAdder : public Variable {
 public:
  MAdder(const std::string& name, std::vector<std::string> label_names)
      : label_names_(std::move(label_names)) {
    expose(name);
  }
  ~MAdder() override { hide(); }

  // Adds to the series for `label_values` (size must match label_names).
  void add(const std::vector<std::string>& label_values, int64_t delta);
  int64_t get(const std::vector<std::string>& label_values) const;
  size_t count_series() const;

  std::string value_str() const override;
  std::string prometheus_str(const std::string& name) const override;

 private:
  std::vector<std::string> label_names_;
  mutable std::mutex mu_;
  std::map<std::vector<std::string>, int64_t> series_;
};

}  // namespace trpc
