#include "stat/profiler.h"

#include <dlfcn.h>
#include <execinfo.h>
#include <signal.h>
#include <string.h>
#include <sys/time.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <map>
#include <mutex>
#include <vector>

#include "base/symbolize.h"
#include "base/time.h"
#include "fiber/fiber.h"

namespace trpc {

namespace {

// ---- CPU sampling ring ---------------------------------------------------

constexpr int kMaxDepth = 24;
constexpr size_t kRingSize = 16384;  // samples

struct Sample {
  int depth;
  void* frames[kMaxDepth];
};

// Fixed-size ring written by the signal handler (no locks, no allocation;
// the writer is single — signals are per-process and serialized).
Sample* g_ring = nullptr;
std::atomic<size_t> g_ring_next{0};
std::atomic<bool> g_profiling{false};

void sigprof_handler(int, siginfo_t*, void*) {
  if (!g_profiling.load(std::memory_order_relaxed) || g_ring == nullptr) {
    return;
  }
  // WRAP rather than drop: a long profile keeps its most recent window
  // instead of silently freezing at the first 16K samples.
  const size_t slot =
      g_ring_next.fetch_add(1, std::memory_order_relaxed) % kRingSize;
  Sample& s = g_ring[slot];
  // backtrace() is not strictly async-signal-safe but is the standard
  // practice for SIGPROF samplers (gperftools does its own unwind); the
  // first call pre-loads libgcc outside the handler (profiler_start).
  s.depth = backtrace(s.frames, kMaxDepth);
}

std::string symbolize(void* addr) { return symbolize_addr(addr); }

// One profile at a time.  An atomic flag, NOT a mutex: the /hotspots
// fiber sleeps between start and stop and may resume on a different OS
// thread (work stealing), where unlocking a std::mutex would be UB.
std::atomic<bool> g_prof_busy{false};

// ---- contention aggregate ------------------------------------------------

struct ContentionStat {
  int64_t count = 0;
  int64_t total_wait_us = 0;
};
std::mutex g_cont_mu;
std::map<void*, ContentionStat>& contention_map() {
  static auto* m = new std::map<void*, ContentionStat>();
  return *m;
}

}  // namespace

bool profiler_start(int hz) {
  bool expect = false;
  if (!g_prof_busy.compare_exchange_strong(expect, true,
                                           std::memory_order_acq_rel)) {
    return false;
  }
  // Pre-load the unwinder outside signal context.
  void* warm[4];
  backtrace(warm, 4);
  if (g_ring == nullptr) {
    g_ring = new Sample[kRingSize];  // leaked with the profiler
  }
  g_ring_next.store(0, std::memory_order_relaxed);
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_sigaction = sigprof_handler;
  sa.sa_flags = SA_SIGINFO | SA_RESTART;
  sigaction(SIGPROF, &sa, nullptr);
  g_profiling.store(true, std::memory_order_release);
  itimerval tv;
  tv.it_interval.tv_sec = 0;
  tv.it_interval.tv_usec = 1000000 / (hz > 0 ? hz : 100);
  tv.it_value = tv.it_interval;
  setitimer(ITIMER_PROF, &tv, nullptr);
  return true;
}

namespace {

// Disarms the timer and returns how many ring slots hold valid samples.
size_t profiler_disarm() {
  itimerval off;
  memset(&off, 0, sizeof(off));
  setitimer(ITIMER_PROF, &off, nullptr);
  g_profiling.store(false, std::memory_order_release);
  // A handler delivered just before the disarm may still be mid-write on
  // another thread; give it a beat before reading the ring.
  usleep(2000);
  return std::min(g_ring_next.load(std::memory_order_relaxed), kRingSize);
}

}  // namespace

std::string profiler_stop_and_dump(size_t max_rows) {
  const size_t n = profiler_disarm();

  // Aggregate leaf-ward frames (skip the handler's own frames).
  std::map<std::string, int64_t> by_frame;
  for (size_t i = 0; i < n; ++i) {
    const Sample& s = g_ring[i];
    // frames[0..1] are the signal trampoline/handler; count the rest,
    // each frame once per sample (inclusive counting).
    for (int d = 2; d < s.depth; ++d) {
      ++by_frame[symbolize(s.frames[d])];
    }
  }
  std::vector<std::pair<int64_t, std::string>> rows;
  rows.reserve(by_frame.size());
  for (auto& [sym, cnt] : by_frame) {
    rows.push_back({cnt, sym});
  }
  std::sort(rows.rbegin(), rows.rend());
  std::string out = "samples " + std::to_string(n) + "\n";
  char line[512];
  for (size_t i = 0; i < rows.size() && i < max_rows; ++i) {
    snprintf(line, sizeof(line), "%8lld  %5.1f%%  %s\n",
             static_cast<long long>(rows[i].first),
             n > 0 ? 100.0 * rows[i].first / n : 0.0,
             rows[i].second.c_str());
    out += line;
  }
  g_prof_busy.store(false, std::memory_order_release);
  return out;
}

std::string profile_cpu_for(int seconds, int hz) {
  if (!profiler_start(hz)) {
    return "another profile is already running\n";
  }
  fiber_sleep_us(static_cast<int64_t>(seconds) * 1000000);
  return profiler_stop_and_dump();
}

std::string profile_cpu_pprof(int seconds, int hz) {
  if (!profiler_start(hz)) {
    return "";  // caller reports the conflict
  }
  fiber_sleep_us(static_cast<int64_t>(seconds) * 1000000);
  const size_t n = profiler_disarm();

  // Aggregate identical stacks (handler frames stripped).
  std::map<std::vector<void*>, int64_t> stacks;
  for (size_t i = 0; i < n; ++i) {
    const Sample& s = g_ring[i];
    if (s.depth <= 2) {
      continue;
    }
    std::vector<void*> key(s.frames + 2, s.frames + s.depth);
    ++stacks[key];
  }
  // gperftools legacy CPU profile format (binary machine words; what
  // `pprof` reads when given a raw profile: builtin/pprof_service parity):
  //   header  [0, 3, 0, sampling_period_usec, 0]
  //   records [count, depth, pc...]
  //   trailer [0, 1, 0]
  std::string out;
  auto put_word = [&out](uintptr_t w) {
    out.append(reinterpret_cast<const char*>(&w), sizeof(w));
  };
  put_word(0);
  put_word(3);
  put_word(0);
  put_word(1000000 / (hz > 0 ? hz : 100));
  put_word(0);
  for (const auto& [frames, count] : stacks) {
    put_word(static_cast<uintptr_t>(count));
    put_word(frames.size());
    for (void* pc : frames) {
      put_word(reinterpret_cast<uintptr_t>(pc));
    }
  }
  put_word(0);
  put_word(1);
  put_word(0);
  g_prof_busy.store(false, std::memory_order_release);
  return out;
}

std::string pprof_symbolize_post(const std::string& body) {
  // /pprof/symbol POST: "0xADDR+0xADDR+..." → "0xADDR\tname" lines.
  std::string out;
  size_t pos = 0;
  while (pos < body.size()) {
    size_t end = body.find('+', pos);
    if (end == std::string::npos) {
      end = body.size();
    }
    const std::string tok = body.substr(pos, end - pos);
    if (!tok.empty()) {
      const uintptr_t addr = strtoull(tok.c_str(), nullptr, 16);
      out += tok + "\t" +
             symbolize(reinterpret_cast<void*>(addr)) + "\n";
    }
    pos = end + 1;
  }
  return out;
}

void contention_record(void* site, int64_t wait_us) {
  // Sampled 1/16 (thread-local counter): recording EVERY contended wait
  // through one global mutex would itself become a process-wide
  // serialization point — the reference samples too (bthread/mutex.cpp).
  static thread_local uint32_t counter = 0;
  if ((counter++ & 15) != 0) {
    return;
  }
  std::lock_guard<std::mutex> g(g_cont_mu);
  auto& m = contention_map();
  if (m.size() > 4096 && m.find(site) == m.end()) {
    return;  // bounded
  }
  ContentionStat& s = m[site];
  ++s.count;
  s.total_wait_us += wait_us;
}

std::string contention_dump(size_t max_rows) {
  std::vector<std::pair<int64_t, std::string>> rows;
  {
    std::lock_guard<std::mutex> g(g_cont_mu);
    for (auto& [site, st] : contention_map()) {
      char line[512];
      snprintf(line, sizeof(line), "%10lld us  %8lld waits  %s",
               static_cast<long long>(st.total_wait_us),
               static_cast<long long>(st.count),
               symbolize(site).c_str());
      rows.push_back({st.total_wait_us, line});
    }
  }
  std::sort(rows.rbegin(), rows.rend());
  std::string out = "contended lock sites (by total wait)\n";
  for (size_t i = 0; i < rows.size() && i < max_rows; ++i) {
    out += rows[i].second + "\n";
  }
  return out;
}

}  // namespace trpc
