// Sampling profilers: CPU (/hotspots) and lock contention (/contention).
//
// Parity: the reference's /hotspots service (builtin/hotspots_service.cpp
// — weak-linked gperftools ProfilerStart at :36, ContentionProfilerStart
// at :41, bthread mutex wait sampling in bthread/mutex.cpp).  Redesigned
// self-contained: a SIGPROF itimer samples backtraces into a fixed ring
// (no allocation in the handler), aggregation + symbolization (dladdr)
// happen at dump time; contention events are recorded by the FiberMutex
// slow path with their wait duration and aggregated by call site.
#pragma once

#include <cstdint>
#include <string>

namespace trpc {

// ---- CPU profiler --------------------------------------------------------

// Starts SIGPROF sampling at `hz` (one profile at a time; false if one is
// already running).
bool profiler_start(int hz = 100);
// Stops sampling and renders a flat text profile: sample counts per
// symbolized frame, callers included, most-hit first.
std::string profiler_stop_and_dump(size_t max_rows = 60);
// /pprof/profile: same sampling, emitted in the gperftools legacy binary
// CPU-profile format standard pprof tooling reads (pprof_service.h:26
// parity).  Empty string when another profile is running.
std::string profile_cpu_pprof(int seconds, int hz = 100);
// /pprof/symbol POST body ("0xA+0xB+...") → "0xA\tsymbol" lines.
std::string pprof_symbolize_post(const std::string& body);
// Convenience for /hotspots: profile this process for `seconds` (the
// calling fiber sleeps through it).
std::string profile_cpu_for(int seconds, int hz = 100);

// ---- contention profiler -------------------------------------------------

// Records one contended-lock wait (called by FiberMutex's slow path; keeps
// a bounded aggregate keyed by return address).
void contention_record(void* site, int64_t wait_us);
// Renders aggregated contention sites: total wait, count, symbol.
std::string contention_dump(size_t max_rows = 40);

}  // namespace trpc
