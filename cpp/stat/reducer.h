// Reducers — write-mostly counters combined on read.
//
// Parity: bvar::Adder/Maxer/Miner (/root/reference/src/bvar/reducer.h:
// 335-493 over detail/agent_group.h thread-local agents).  A write touches
// only this thread's cache-line-private agent; reads walk the agent list.
// Re-designed: agents are registered in a per-reducer list keyed by a
// process-unique id (same TLS pattern as DoublyBufferedData).
#pragma once

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <limits>
#include <memory>
#include <mutex>
#include <type_traits>
#include <vector>

#include "stat/variable.h"

namespace trpc {

struct OpAdd;

template <typename Op>
class Reducer : public Variable {
 public:
  Reducer() {
    static std::atomic<uint64_t> next_id{1};
    id_ = next_id.fetch_add(1, std::memory_order_relaxed);
  }

  ~Reducer() override {
    hide();  // deregister from /vars BEFORE members start dying
  }

  void operator<<(int64_t v) {
    Agent* a = tls_agent();
    int64_t cur = a->value.load(std::memory_order_relaxed);
    while (!a->value.compare_exchange_weak(cur, Op::combine(cur, v),
                                           std::memory_order_relaxed)) {
    }
  }

  int64_t get_value() const {
    std::lock_guard<std::mutex> g(agents_mu_);
    int64_t acc = terminated_;
    for (const auto& a : agents_) {
      acc = Op::combine(acc, a->value.load(std::memory_order_relaxed));
    }
    return acc;
  }

  // Atomically reads and clears (used by per-second windows; only
  // meaningful for Adder semantics).
  int64_t reset() {
    std::lock_guard<std::mutex> g(agents_mu_);
    int64_t acc = terminated_;
    terminated_ = Op::identity();
    for (const auto& a : agents_) {
      acc = Op::combine(acc, a->value.exchange(Op::identity(),
                                               std::memory_order_relaxed));
    }
    return acc;
  }

  std::string value_str() const override {
    return std::to_string(get_value());
  }

  // Adders are the monotonic event counters of this runtime; Prometheus
  // wants them typed `counter` (with the `_total` suffix the base class
  // appends) so rate()/increase() work.  Maxer/Miner stay gauges.
  const char* prometheus_type() const override {
    return std::is_same_v<Op, OpAdd> ? "counter" : "gauge";
  }

 private:
  struct Agent {
    std::atomic<int64_t> value{Op::identity()};
  };

  Agent* tls_agent() {
    static thread_local std::vector<
        std::pair<uint64_t, std::shared_ptr<Agent>>> tls;
    for (auto& p : tls) {
      if (p.first == id_) {
        return p.second.get();
      }
    }
    // Prune agents whose reducer died (we hold the only reference) so the
    // per-thread list can't grow without bound across reducer lifetimes.
    if (tls.size() > 64) {
      tls.erase(std::remove_if(tls.begin(), tls.end(),
                               [](const auto& p) {
                                 return p.second.use_count() == 1;
                               }),
                tls.end());
    }
    auto agent = std::make_shared<Agent>();
    {
      std::lock_guard<std::mutex> g(agents_mu_);
      agents_.push_back(agent);
    }
    tls.emplace_back(id_, agent);
    return agent.get();
  }

  uint64_t id_ = 0;
  mutable std::mutex agents_mu_;
  std::vector<std::shared_ptr<Agent>> agents_;
  int64_t terminated_ = Op::identity();
};

struct OpAdd {
  static int64_t identity() { return 0; }
  static int64_t combine(int64_t a, int64_t b) { return a + b; }
};
struct OpMax {
  static int64_t identity() { return std::numeric_limits<int64_t>::min(); }
  static int64_t combine(int64_t a, int64_t b) { return a > b ? a : b; }
};
struct OpMin {
  static int64_t identity() { return std::numeric_limits<int64_t>::max(); }
  static int64_t combine(int64_t a, int64_t b) { return a < b ? a : b; }
};

using Adder = Reducer<OpAdd>;
using Maxer = Reducer<OpMax>;
using Miner = Reducer<OpMin>;

}  // namespace trpc
