#include "stat/sampler.h"

#include <pthread.h>
#include <unistd.h>

#include <algorithm>

namespace trpc {

Sampler* Sampler::instance() {
  // Deliberately leaked: the sampler pthread outlives static destruction.
  static Sampler* s = new Sampler();
  return s;
}

Sampler::Sampler() {
  pthread_t tid;
  pthread_create(
      &tid, nullptr,
      [](void* self) -> void* {
        static_cast<Sampler*>(self)->run();
        return nullptr;
      },
      this);
  pthread_detach(tid);
}

void Sampler::add(Sampled* s) {
  std::lock_guard<std::mutex> g(mu_);
  sampled_.push_back(s);
}

void Sampler::remove(Sampled* s) {
  std::lock_guard<std::mutex> g(mu_);
  sampled_.erase(std::remove(sampled_.begin(), sampled_.end(), s),
                 sampled_.end());
}

void Sampler::run() {
  while (true) {
    usleep(1000000);
    std::lock_guard<std::mutex> g(mu_);
    for (Sampled* s : sampled_) {
      s->take_sample();
    }
  }
}

}  // namespace trpc
