#include "stat/sampler.h"

#include <pthread.h>
#include <unistd.h>

#include <algorithm>

#include "stat/latency_recorder.h"

namespace trpc {

Sampler* Sampler::instance() {
  // Deliberately leaked: the sampler pthread outlives static destruction.
  static Sampler* s = new Sampler();
  return s;
}

Sampler::Sampler() {
  pthread_t tid;
  pthread_create(
      &tid, nullptr,
      [](void* self) -> void* {
        static_cast<Sampler*>(self)->run();
        return nullptr;
      },
      this);
  pthread_detach(tid);
}

void Sampler::add(LatencyRecorder* r) {
  std::lock_guard<std::mutex> g(mu_);
  recorders_.push_back(r);
}

void Sampler::remove(LatencyRecorder* r) {
  std::lock_guard<std::mutex> g(mu_);
  recorders_.erase(std::remove(recorders_.begin(), recorders_.end(), r),
                   recorders_.end());
}

void Sampler::run() {
  while (true) {
    usleep(1000000);
    std::lock_guard<std::mutex> g(mu_);
    for (LatencyRecorder* r : recorders_) {
      r->take_sample();
    }
  }
}

}  // namespace trpc
