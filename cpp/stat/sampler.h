// Sampler — ONE background thread snapshots every registered object each
// second (parity: bvar SamplerCollector, /root/reference/src/bvar/detail/
// sampler.cpp:60-135).
#pragma once

#include <mutex>
#include <vector>

namespace trpc {

// Anything needing a once-per-second snapshot tick.
class Sampled {
 public:
  virtual ~Sampled() = default;
  virtual void take_sample() = 0;
};

class Sampler {
 public:
  static Sampler* instance();
  void add(Sampled* s);
  void remove(Sampled* s);

 private:
  Sampler();
  void run();
  std::mutex mu_;
  std::vector<Sampled*> sampled_;
};

}  // namespace trpc
