// Sampler — ONE background thread snapshots every recorder each second
// (parity: bvar SamplerCollector, /root/reference/src/bvar/detail/
// sampler.cpp:60-135).
#pragma once

#include <mutex>
#include <vector>

namespace trpc {

class LatencyRecorder;

class Sampler {
 public:
  static Sampler* instance();
  void add(LatencyRecorder* r);
  void remove(LatencyRecorder* r);

 private:
  Sampler();
  void run();
  std::mutex mu_;
  std::vector<LatencyRecorder*> recorders_;
};

}  // namespace trpc
