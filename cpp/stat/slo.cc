#include "stat/slo.h"

#include <algorithm>
#include <array>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <mutex>

#include "base/flags.h"
#include "base/json.h"
#include "base/time.h"
#include "stat/digest.h"
#include "stat/latency_recorder.h"
#include "stat/reducer.h"
#include "stat/timeline.h"
#include "stat/variable.h"

namespace trpc {

namespace slo {

std::atomic<bool> g_enabled{false};

namespace {

Flag* fast_window_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_slo_fast_window_ms", 300000,
        "SLO fast burn-rate window in ms (~5m scale; the 'is it still "
        "happening' window — breaches fire and clear within one of "
        "these).  Captured by Server::SetSlo at install time, so "
        "compress it BEFORE installing a spec in tests");
    if (flag != nullptr) {
      flag->set_int_range(200, 3600000);
    }
    return flag;
  }();
  return f;
}

Flag* slow_window_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_slo_slow_window_ms", 3600000,
        "SLO slow burn-rate window in ms (~1h scale; the 'sustained "
        "damage' window — both windows must burn >= trpc_slo_burn_alert "
        "for a breach).  Captured at SetSlo install time");
    if (flag != nullptr) {
      flag->set_int_range(1000, 86400000);
    }
    return flag;
  }();
  return f;
}

Flag* burn_alert_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_double(
        "trpc_slo_burn_alert", 2.0,
        "error-budget burn-rate threshold: a tenant breaches when BOTH "
        "its fast and slow windows burn budget at >= this multiple of "
        "the sustainable rate (1.0 = spending exactly the budget; the "
        "SRE-book fast-page default is ~2x)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const double d = strtod(v.c_str(), &end);
        return end != v.c_str() && *end == '\0' && d >= 1.0 && d <= 1000.0;
      });
    }
    return flag;
  }();
  return f;
}

Flag* slo_flag() {
  static Flag* f = [] {
    fast_window_flag();  // companion knobs register alongside
    slow_window_flag();
    burn_alert_flag();
    Flag* flag = Flag::define_bool(
        "trpc_slo", false,
        "per-tenant SLO engine: windowed attainment + multi-window "
        "error-budget burn rates over Server::SetSlo targets, surfaced "
        "as slo_* vars, /slo, timeline event 28 and the naming:// fleet "
        "publication (default off; flag-off cost is one relaxed load "
        "per response and every slo_* var stays frozen at 0)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        return v == "true" || v == "false" || v == "1" || v == "0" ||
               v == "on" || v == "off";
      });
      flag->on_update([](Flag* self) {
        g_enabled.store(self->bool_value(), std::memory_order_release);
      });
    }
    return flag;
  }();
  return f;
}

struct SloVars {
  Adder breaches;
  Adder observed;

  SloVars() {
    breaches.expose(
        "slo_breach_total",
        "burn-rate breach EDGES fired across all SLO engines (a tenant "
        "entering breach counts once; clears don't count — frozen at 0 "
        "while trpc_slo has never been on)");
    observed.expose(
        "slo_observed_total",
        "responses scored against an SLO target while trpc_slo was on");
  }
};

SloVars* slo_vars() {
  // Deliberately leaked: the var registry outlives statics.
  static SloVars* v = new SloVars();
  return v;
}

std::string var_safe(const std::string& tenant) {
  if (tenant == "*") {
    return "default";  // "slo_tenant__" would be unreadable in /vars
  }
  std::string s = tenant;
  for (char& c : s) {
    if (!((c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
          (c >= '0' && c <= '9') || c == '_')) {
      c = '_';
    }
  }
  return s;
}

std::string unique_name(const std::string& base) {
  std::string probe;
  std::string name = base;
  for (int i = 2; Variable::read_exposed(name, &probe); ++i) {
    name = base + "_" + std::to_string(i);
  }
  return name;
}

bool valid_tenant_name(const std::string& s) {
  if (s.empty() || s.size() > 64) {
    return false;
  }
  for (char c : s) {
    const bool ok = (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
                    (c >= '0' && c <= '9') || c == '_' || c == '-' ||
                    c == '.' || c == '*';
    if (!ok) {
      return false;
    }
  }
  return true;
}

template <typename T>
void put(std::string* out, T v) {
  out->append(reinterpret_cast<const char*>(&v), sizeof(T));
}

}  // namespace

void ensure_registered() {
  slo_flag();
  slo_vars();
}

int64_t fast_window_ms() { return fast_window_flag()->int64_value(); }
int64_t slow_window_ms() { return slow_window_flag()->int64_value(); }
double burn_alert() { return burn_alert_flag()->double_value(); }

uint64_t tenant_hash(const std::string& name) {
  uint64_t h = 1469598103934665603ULL;  // FNV-1a (matches tuner::knob_hash)
  for (char c : name) {
    h ^= static_cast<uint8_t>(c);
    h *= 1099511628211ULL;
  }
  return h;
}

uint64_t breach_total() {
  return static_cast<uint64_t>(slo_vars()->breaches.get_value());
}

}  // namespace slo

namespace {

// One burn-rate window: a bucketed ring with cached running sums, lazily
// advanced by the wall of the monotonic clock.  64 buckets keep the
// advance cheap and the expiry granularity at width/64.
struct BurnWindow {
  static constexpr int kBuckets = 64;
  struct B {
    int64_t total = 0, bad = 0, err = 0;
  };

  int64_t width_us = 0;
  int64_t bucket_us = 0;
  std::array<B, kBuckets> ring;
  int64_t head = -1;  // absolute bucket index at ring head
  int64_t total = 0, bad = 0, err = 0;

  void init(int64_t width_ms) {
    width_us = width_ms * 1000;
    bucket_us = std::max<int64_t>(1, width_us / kBuckets);
  }

  void advance(int64_t now_us) {
    const int64_t abs = now_us / bucket_us;
    if (head < 0) {
      head = abs;
      return;
    }
    if (abs - head >= kBuckets) {
      for (auto& b : ring) {
        b = B();
      }
      total = bad = err = 0;
      head = abs;
      return;
    }
    while (head < abs) {
      ++head;
      B& b = ring[head % kBuckets];
      total -= b.total;
      bad -= b.bad;
      err -= b.err;
      b = B();
    }
  }

  void add(bool is_bad, bool is_err) {
    B& b = ring[head % kBuckets];
    ++b.total;
    ++total;
    if (is_bad) {
      ++b.bad;
      ++bad;
    }
    if (is_err) {
      ++b.err;
      ++err;
    }
  }

  double bad_frac() const {
    return total > 0 ? static_cast<double>(bad) / total : 0.0;
  }

  double burn(double allowed) const { return bad_frac() / allowed; }
};

}  // namespace

struct SloEngine::Entry {
  std::string name;
  uint64_t hash = 0;
  int64_t p99_target_us = 0;  // INT64_MAX when only avail was declared
  double avail_target = 0;    // fraction, e.g. 0.999
  double allowed = 0;         // error budget = max(1 - avail_target, 1e-6)

  mutable std::mutex mu;
  BurnWindow fast, slow;
  std::atomic<bool> breached{false};

  std::shared_ptr<LatencyRecorder> latency;
  std::vector<std::unique_ptr<Variable>> status_vars;
};

SloEngine::~SloEngine() = default;

SloEngine::Entry* SloEngine::find(const std::string& tenant) const {
  for (const auto& e : entries_) {
    if (e->name == tenant) {
      return e.get();
    }
  }
  return default_entry_;
}

namespace {

// Breach-state transition: both windows must burn to fire; either window
// recovering clears (fast recovers within one fast window of the fault
// ending — the "clear within one fast window" contract).  Only EDGES emit
// timeline event 28 / bump slo_breach_total.
void evaluate_breach(SloEngine::Entry* e, double burn_fast,
                     double burn_slow);

}  // namespace

void SloEngine::on_response(const std::string& tenant, int64_t latency_us,
                            bool error) {
  if (!slo::enabled()) {
    return;
  }
  Entry* e = find(tenant);
  if (e == nullptr) {
    return;
  }
  if (latency_us < 0) {
    latency_us = 0;
  }
  slo::slo_vars()->observed << 1;
  *e->latency << latency_us;
  const bool is_bad = error || latency_us > e->p99_target_us;
  const int64_t now = monotonic_time_us();
  double burn_fast, burn_slow;
  {
    std::lock_guard<std::mutex> g(e->mu);
    e->fast.advance(now);
    e->slow.advance(now);
    e->fast.add(is_bad, error);
    e->slow.add(is_bad, error);
    burn_fast = e->fast.burn(e->allowed);
    burn_slow = e->slow.burn(e->allowed);
  }
  evaluate_breach(e, burn_fast, burn_slow);
}

namespace {

void evaluate_breach(SloEngine::Entry* e, double burn_fast,
                     double burn_slow) {
  const double alert = slo::burn_alert();
  const bool now_breached = burn_fast >= alert && burn_slow >= alert;
  bool was = e->breached.load(std::memory_order_relaxed);
  if (now_breached == was ||
      !e->breached.compare_exchange_strong(was, now_breached,
                                           std::memory_order_relaxed)) {
    return;
  }
  if (now_breached) {
    slo::slo_vars()->breaches << 1;
  }
  if (timeline::enabled()) {
    const uint64_t op = now_breached ? 1 : 2;
    const uint64_t milli = static_cast<uint64_t>(std::min(
        burn_fast * 1000.0, static_cast<double>((uint64_t{1} << 56) - 1)));
    timeline::record(timeline::kSloBreach, e->hash, (op << 56) | milli);
  }
}

// Advances both windows to now and re-evaluates the breach state — read
// paths use this so a tenant whose traffic STOPPED after recovery still
// clears (on_response alone would leave the stale burn frozen).
struct EntrySnap {
  int64_t fast_total, fast_bad, fast_err;
  int64_t slow_total, slow_bad, slow_err;
  double burn_fast, burn_slow;
  bool breached;
};

EntrySnap snap_entry(SloEngine::Entry* e) {
  EntrySnap s;
  const int64_t now = monotonic_time_us();
  {
    std::lock_guard<std::mutex> g(e->mu);
    e->fast.advance(now);
    e->slow.advance(now);
    s.fast_total = e->fast.total;
    s.fast_bad = e->fast.bad;
    s.fast_err = e->fast.err;
    s.slow_total = e->slow.total;
    s.slow_bad = e->slow.bad;
    s.slow_err = e->slow.err;
    s.burn_fast = e->fast.burn(e->allowed);
    s.burn_slow = e->slow.burn(e->allowed);
  }
  evaluate_breach(e, s.burn_fast, s.burn_slow);
  s.breached = e->breached.load(std::memory_order_relaxed);
  return s;
}

}  // namespace

std::shared_ptr<SloEngine> SloEngine::parse(const std::string& spec,
                                            std::string* err) {
  err->clear();
  if (spec.empty()) {
    return nullptr;
  }
  slo::ensure_registered();
  std::shared_ptr<SloEngine> eng(new SloEngine());
  const int64_t fast_ms = slo::fast_window_ms();
  const int64_t slow_ms = slo::slow_window_ms();
  size_t pos = 0;
  while (pos < spec.size()) {
    size_t end = spec.find(';', pos);
    if (end == std::string::npos) {
      end = spec.size();
    }
    const std::string clause = spec.substr(pos, end - pos);
    pos = end + 1;
    if (clause.empty()) {
      continue;
    }
    const size_t colon = clause.find(':');
    if (colon == std::string::npos) {
      *err = "clause missing ':': " + clause;
      return nullptr;
    }
    auto entry = std::make_unique<Entry>();
    entry->name = clause.substr(0, colon);
    if (!slo::valid_tenant_name(entry->name)) {
      *err = "bad tenant name: " + entry->name;
      return nullptr;
    }
    for (const auto& prior : eng->entries_) {
      if (prior->name == entry->name) {
        *err = "duplicate tenant clause: " + entry->name;
        return nullptr;
      }
    }
    entry->p99_target_us = INT64_MAX;  // avail-only clause: latency never bad
    entry->avail_target = 0.99;       // default when only p99_us is given
    bool any_key = false;
    size_t kp = colon + 1;
    while (kp < clause.size()) {
      size_t ke = clause.find(',', kp);
      if (ke == std::string::npos) {
        ke = clause.size();
      }
      const std::string kv = clause.substr(kp, ke - kp);
      kp = ke + 1;
      if (kv.empty()) {
        continue;
      }
      const size_t eq = kv.find('=');
      if (eq == std::string::npos) {
        *err = "bad key=val: " + kv;
        return nullptr;
      }
      const std::string key = kv.substr(0, eq);
      const std::string val = kv.substr(eq + 1);
      if (key == "p99_us") {
        char* vend = nullptr;
        const long long t = strtoll(val.c_str(), &vend, 10);
        if (vend == val.c_str() || *vend != '\0' || t < 1) {
          *err = "bad p99_us: " + val;
          return nullptr;
        }
        entry->p99_target_us = t;
        any_key = true;
      } else if (key == "avail") {
        char* vend = nullptr;
        const double pct = strtod(val.c_str(), &vend);
        if (vend == val.c_str() || *vend != '\0' || pct <= 0.0 ||
            pct >= 100.0) {
          *err = "bad avail (percent in (0,100)): " + val;
          return nullptr;
        }
        entry->avail_target = pct / 100.0;
        any_key = true;
      } else {
        *err = "unknown key: " + key;
        return nullptr;
      }
    }
    if (!any_key) {
      *err = "clause declares no target: " + clause;
      return nullptr;
    }
    entry->hash = slo::tenant_hash(entry->name);
    entry->allowed = std::max(1.0 - entry->avail_target, 1e-6);
    entry->fast.init(fast_ms);
    entry->slow.init(slow_ms);
    const std::string base = "slo_tenant_" + slo::var_safe(entry->name);
    entry->latency = std::make_shared<LatencyRecorder>();
    entry->latency->expose(
        slo::unique_name(base),
        "per-tenant SLO latency/qps feed of tenant '" + entry->name +
            "' (frozen while trpc_slo is off; snapshot published to the "
            "fleet as a mergeable digest)");
    Entry* raw = entry.get();
    auto burn_fast_var = std::make_unique<PassiveStatus<long>>([raw] {
      return static_cast<long>(snap_entry(raw).burn_fast * 1000.0);
    });
    burn_fast_var->expose(
        slo::unique_name(base + "_burn_fast_milli"),
        "fast-window error-budget burn rate of tenant '" + entry->name +
            "' in milli (1000 = burning exactly the budget)");
    entry->status_vars.push_back(std::move(burn_fast_var));
    auto burn_slow_var = std::make_unique<PassiveStatus<long>>([raw] {
      return static_cast<long>(snap_entry(raw).burn_slow * 1000.0);
    });
    burn_slow_var->expose(
        slo::unique_name(base + "_burn_slow_milli"),
        "slow-window error-budget burn rate of tenant '" + entry->name +
            "' in milli");
    entry->status_vars.push_back(std::move(burn_slow_var));
    auto attain_var = std::make_unique<PassiveStatus<long>>([raw] {
      const EntrySnap s = snap_entry(raw);
      return s.slow_total > 0
                 ? static_cast<long>(
                       (1.0 - static_cast<double>(s.slow_bad) /
                                  s.slow_total) *
                       1e6)
                 : 0L;  // no traffic in window — stays 0 (flag-off frozen)
    });
    attain_var->expose(
        slo::unique_name(base + "_attainment_ppm"),
        "slow-window SLO attainment of tenant '" + entry->name +
            "' in ppm (999000 = 99.9% of responses met the target; 0 "
            "when the window holds no traffic)");
    entry->status_vars.push_back(std::move(attain_var));
    auto breached_var = std::make_unique<PassiveStatus<long>>([raw] {
      return static_cast<long>(snap_entry(raw).breached ? 1 : 0);
    });
    breached_var->expose(
        slo::unique_name(base + "_breached"),
        "1 while tenant '" + entry->name +
            "' is in burn-rate breach (both windows >= "
            "trpc_slo_burn_alert), else 0");
    entry->status_vars.push_back(std::move(breached_var));
    if (entry->name == "*") {
      eng->default_entry_ = raw;
    }
    eng->entries_.push_back(std::move(entry));
  }
  if (eng->entries_.empty()) {
    *err = "empty spec";
    return nullptr;
  }
  return eng;
}

std::string SloEngine::dump_json() const {
  Json root = Json::object();
  root.set("enabled", Json::boolean(slo::enabled()));
  root.set("burn_alert", Json::number(slo::burn_alert()));
  root.set("breach_total",
           Json::number(static_cast<double>(slo::breach_total())));
  Json tenants = Json::array();
  for (const auto& e : entries_) {
    const EntrySnap s = snap_entry(e.get());
    Json t = Json::object();
    t.set("tenant", Json::str(e->name));
    t.set("p99_target_us",
          Json::number(e->p99_target_us == INT64_MAX
                           ? -1.0
                           : static_cast<double>(e->p99_target_us)));
    t.set("avail_target", Json::number(e->avail_target));
    Json fast = Json::object();
    fast.set("total", Json::number(static_cast<double>(s.fast_total)));
    fast.set("bad", Json::number(static_cast<double>(s.fast_bad)));
    fast.set("err", Json::number(static_cast<double>(s.fast_err)));
    fast.set("window_ms",
             Json::number(static_cast<double>(e->fast.width_us / 1000)));
    t.set("fast", std::move(fast));
    Json slow = Json::object();
    slow.set("total", Json::number(static_cast<double>(s.slow_total)));
    slow.set("bad", Json::number(static_cast<double>(s.slow_bad)));
    slow.set("err", Json::number(static_cast<double>(s.slow_err)));
    slow.set("window_ms",
             Json::number(static_cast<double>(e->slow.width_us / 1000)));
    t.set("slow", std::move(slow));
    t.set("burn_fast", Json::number(s.burn_fast));
    t.set("burn_slow", Json::number(s.burn_slow));
    const double attain =
        s.slow_total > 0
            ? 1.0 - static_cast<double>(s.slow_bad) / s.slow_total
            : 1.0;
    t.set("attainment", Json::number(attain));
    const double budget =
        std::max(0.0, std::min(1.0, 1.0 - s.burn_slow *
                                              (s.slow_total > 0 ? 1.0 : 0.0)));
    t.set("budget_remaining", Json::number(budget));
    t.set("breached", Json::boolean(s.breached));
    LatencyDigest d;
    e->latency->snapshot_digest(&d);
    Json lat = Json::object();
    lat.set("qps", Json::number(d.qps()));
    lat.set("avg_us", Json::number(d.avg_us()));
    lat.set("p50_us",
            Json::number(static_cast<double>(digest_percentile_us(d, 0.5))));
    lat.set("p99_us",
            Json::number(static_cast<double>(digest_percentile_us(d, 0.99))));
    lat.set("max_us", Json::number(static_cast<double>(d.max_us)));
    lat.set("count", Json::number(static_cast<double>(d.total_count)));
    t.set("latency", std::move(lat));
    tenants.push_back(std::move(t));
  }
  root.set("tenants", std::move(tenants));
  return root.dump();
}

std::string SloEngine::encode_blob(int64_t wall_us) const {
  // digest-wire 2 (TRPCFL01) — layout documented in stat/digest.h.
  std::string out;
  out.append("TRPCFL01", 8);
  slo::put<int64_t>(&out, wall_us);
  slo::put<uint32_t>(&out, static_cast<uint32_t>(entries_.size()));
  for (const auto& e : entries_) {
    const EntrySnap s = snap_entry(e.get());
    slo::put<uint16_t>(&out, static_cast<uint16_t>(e->name.size()));
    out.append(e->name);
    slo::put<int64_t>(&out, e->p99_target_us);
    slo::put<double>(&out, e->avail_target);
    slo::put<int64_t>(&out, e->fast.width_us / 1000);
    slo::put<int64_t>(&out, e->slow.width_us / 1000);
    slo::put<int64_t>(&out, s.fast_total);
    slo::put<int64_t>(&out, s.fast_bad);
    slo::put<int64_t>(&out, s.fast_err);
    slo::put<int64_t>(&out, s.slow_total);
    slo::put<int64_t>(&out, s.slow_bad);
    slo::put<int64_t>(&out, s.slow_err);
    slo::put<double>(&out, s.burn_fast);
    slo::put<double>(&out, s.burn_slow);
    slo::put<uint8_t>(&out, s.breached ? 1 : 0);
    LatencyDigest d;
    e->latency->snapshot_digest(&d);
    out += digest_encode(d);
  }
  return out;
}

namespace {

template <typename T>
bool take(const uint8_t*& p, const uint8_t* end, T* v) {
  if (static_cast<size_t>(end - p) < sizeof(T)) {
    return false;
  }
  memcpy(v, p, sizeof(T));
  p += sizeof(T);
  return true;
}

}  // namespace

bool fleet_blob_decode(const void* data, size_t len, FleetNodeBlob* out) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  const uint8_t* end = p + len;
  if (len < 8 || memcmp(p, "TRPCFL01", 8) != 0) {
    return false;
  }
  p += 8;
  *out = FleetNodeBlob();
  uint32_t nentries = 0;
  if (!take(p, end, &out->wall_us) || !take(p, end, &nentries) ||
      nentries > 4096) {
    return false;
  }
  out->tenants.reserve(nentries);
  for (uint32_t i = 0; i < nentries; ++i) {
    FleetTenantRecord r;
    uint16_t name_len = 0;
    if (!take(p, end, &name_len) ||
        static_cast<size_t>(end - p) < name_len) {
      return false;
    }
    r.tenant.assign(reinterpret_cast<const char*>(p), name_len);
    p += name_len;
    uint8_t breached = 0;
    if (!take(p, end, &r.p99_target_us) || !take(p, end, &r.avail_target) ||
        !take(p, end, &r.fast_window_ms) ||
        !take(p, end, &r.slow_window_ms) || !take(p, end, &r.fast_total) ||
        !take(p, end, &r.fast_bad) || !take(p, end, &r.fast_err) ||
        !take(p, end, &r.slow_total) || !take(p, end, &r.slow_bad) ||
        !take(p, end, &r.slow_err) || !take(p, end, &r.burn_fast) ||
        !take(p, end, &r.burn_slow) || !take(p, end, &breached)) {
      return false;
    }
    r.breached = breached != 0;
    const size_t used =
        digest_decode(p, static_cast<size_t>(end - p), &r.digest);
    if (used == 0) {
      return false;
    }
    p += used;
    out->tenants.push_back(std::move(r));
  }
  return true;
}

bool SloEngine::any_breached() const {
  for (const auto& e : entries_) {
    if (snap_entry(e.get()).breached) {
      return true;
    }
  }
  return false;
}

bool SloEngine::tenant_breached(const std::string& tenant) const {
  Entry* e = find(tenant);
  return e != nullptr && snap_entry(e).breached;
}

size_t SloEngine::tenant_count() const { return entries_.size(); }

}  // namespace trpc
