// Per-tenant SLO engine (ISSUE 19) — windowed attainment tracking with
// multi-window error-budget burn rates, fed from the server dispatch path
// and surfaced as slo_* vars, the /slo builtin, timeline event 28
// (slo_breach), and the fleet publication blob the Announcer pushes over
// naming:// (see stat/digest.h digest-wire 2).
//
// Model (SRE multi-window multi-burn-rate alerting): a response is BAD
// when it errors or exceeds the tenant's p99 latency target; the error
// budget is 1 - avail_target.  Each tenant keeps two bucketed rings —
// a fast window (~5m scale) and a slow window (~1h scale), both
// test-compressible via flags — and
//   burn = (bad / total) / (1 - avail_target)
// per window.  A breach requires BOTH burns >= trpc_slo_burn_alert:
// the slow window proves sustained damage, the fast window proves it is
// still happening — and lets the alert clear within one fast window of
// recovery.  Transitions (and only transitions) emit timeline event 28
// and bump slo_breach_total.
//
// Gating: everything is behind the default-off reloadable `trpc_slo`
// flag.  Flag off, the dispatch hook is ONE relaxed atomic load — no
// state is touched, so every slo_* var is provably frozen at 0 (the
// flag-off perf floor gates this).
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "stat/digest.h"

namespace trpc {
namespace slo {

// Backing switch for the reloadable trpc_slo flag (the flag's on_update
// hook writes it; the dispatch hook gates inline on one relaxed load).
extern std::atomic<bool> g_enabled;

inline bool enabled() {
  return g_enabled.load(std::memory_order_relaxed);
}

// Registers trpc_slo / trpc_slo_fast_window_ms / trpc_slo_slow_window_ms /
// trpc_slo_burn_alert + the global slo_* vars (idempotent).
void ensure_registered();

// Current knob values (read at SetSlo install time for the windows, live
// for the alert threshold).
int64_t fast_window_ms();
int64_t slow_window_ms();
double burn_alert();

// FNV-1a of the tenant name — the `a` field of timeline event 28, so
// stitched traces can correlate breaches to qos_tenant_* tracks.
uint64_t tenant_hash(const std::string& name);

// Lifetime count of breach EDGES (fires), across all engines.
uint64_t breach_total();

}  // namespace slo

// One tenant's decoded state from a fleet publication blob (digest-wire 2).
struct FleetTenantRecord {
  std::string tenant;
  int64_t p99_target_us = 0;  // INT64_MAX = latency-unbounded clause
  double avail_target = 0;
  int64_t fast_window_ms = 0, slow_window_ms = 0;
  int64_t fast_total = 0, fast_bad = 0, fast_err = 0;
  int64_t slow_total = 0, slow_bad = 0, slow_err = 0;
  double burn_fast = 0, burn_slow = 0;
  bool breached = false;
  LatencyDigest digest;
};

struct FleetNodeBlob {
  int64_t wall_us = 0;
  std::vector<FleetTenantRecord> tenants;
};

// Decodes one TRPCFL01 blob (the inverse of SloEngine::encode_blob).
// False on malformed input.
bool fleet_blob_decode(const void* data, size_t len, FleetNodeBlob* out);

class SloEngine {
 public:
  ~SloEngine();

  // Parses "tenantA:p99_us=2000,avail=99.9;*:p99_us=10000" — per-clause
  // keys: p99_us (target latency, us, >0) and avail (availability target
  // in percent, (0,100); default 99.0 when only p99_us is given).  "*" is
  // the default clause matching tenants with no clause of their own.
  // Returns nullptr (+ *err) on malformed specs.  Window widths are
  // captured from the trpc_slo_*_window_ms flags at parse time, so tests
  // compress them before Server::SetSlo.
  static std::shared_ptr<SloEngine> parse(const std::string& spec,
                                          std::string* err);

  // Dispatch feed (server.cc response closure).  Callers gate on
  // slo::enabled() — this re-checks, but the call itself must cost
  // nothing when the flag is off.
  void on_response(const std::string& tenant, int64_t latency_us,
                   bool error);

  // /slo builtin + trpc_slo_dump: {"enabled","burn_alert","tenants":[
  // {"tenant","p99_target_us","avail_target","fast":{...},"slow":{...},
  // "burn_fast","burn_slow","attainment","budget_remaining","breached",
  // "latency":{...}}]}.
  std::string dump_json() const;

  // Fleet publication blob (digest-wire 2, magic TRPCFL01): per-tenant
  // SLO state + a digest snapshot of the tenant's recorder.  Published by
  // the Announcer each renew round when trpc_fleet_publish is on.
  std::string encode_blob(int64_t wall_us) const;

  bool any_breached() const;
  // Per-tenant burn state for admission planes (net/infer.h): true while
  // the tenant's clause (or the "*" default) is burning past the alert
  // threshold on both windows.  Tenants with no clause never read as
  // breached.
  bool tenant_breached(const std::string& tenant) const;
  size_t tenant_count() const;

  struct Entry;  // opaque per-tenant state

 private:
  SloEngine() = default;
  std::vector<std::unique_ptr<Entry>> entries_;
  Entry* default_entry_ = nullptr;  // the "*" clause, if present

  Entry* find(const std::string& tenant) const;
};

}  // namespace trpc
