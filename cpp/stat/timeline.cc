#include "stat/timeline.h"

#include <sys/syscall.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <vector>

#include "base/flags.h"
#include "base/json.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "fiber/scheduler.h"
#include "stat/variable.h"

namespace trpc {
namespace timeline {

std::atomic<bool> g_enabled{false};

namespace {

// One recorded event.  Every field is an atomic so a concurrent dump is
// race-free under TSan; the per-slot seqlock below is what makes the
// VALUES coherent (torn slots are discarded, never surfaced).  64 bytes
// = one cache line per slot.
struct Slot {
  std::atomic<uint64_t> seq{0};  // absolute index + 1; 0 = being written
  std::atomic<int64_t> ts_us{0};
  std::atomic<uint64_t> a{0};
  std::atomic<uint64_t> b{0};
  std::atomic<uint64_t> trace_id{0};
  std::atomic<uint64_t> span_id{0};
  std::atomic<uint64_t> fid{0};
  std::atomic<uint32_t> type{0};
  uint32_t pad = 0;
};
static_assert(sizeof(Slot) == 64, "one cache line per slot");

struct Ring {
  explicit Ring(size_t nslots) : slots(nslots), mask(nslots - 1) {}
  std::vector<Slot> slots;  // power-of-two
  const uint64_t mask;
  // head = lifetime events written by the owner thread (single writer).
  std::atomic<uint64_t> head{0};
  // Dumps hide indices below floor (reset() support); writers ignore it.
  std::atomic<uint64_t> floor{0};
  uint64_t tid = 0;
  char name[16] = {};
};

std::mutex& registry_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}

// Leaked, append-only: a ring outlives its thread so late dumps stay
// safe, and readers can walk the vector snapshot without per-ring locks.
std::vector<Ring*>& rings() {
  static auto* v = new std::vector<Ring*>();
  return *v;
}

std::atomic<void (*)(uint64_t*, uint64_t*)> g_ctx_reader{nullptr};

Flag* ring_kb_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_timeline_ring_kb", 256,
        "per-thread flight-recorder ring size in KB (64 bytes/event; "
        "applies to rings created after the set — a live thread keeps "
        "its ring)");
    if (flag != nullptr) {
      // Range validator + introspectable bounds in one declaration.
      flag->set_int_range(64, 65536);
    }
    return flag;
  }();
  return f;
}

Flag* timeline_flag() {
  static Flag* f = [] {
    ring_kb_flag();  // companion knob registers alongside
    Flag* flag = Flag::define_bool(
        "trpc_timeline", false,
        "flight recorder: per-thread rings of fiber/messenger/socket/"
        "stripe/QoS timeline events, browsable via /timeline and merged "
        "into Perfetto by tools/trace_stitch.py --timeline (default off; "
        "flag-off cost is one relaxed load per hook)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        return v == "true" || v == "false" || v == "1" || v == "0" ||
               v == "on" || v == "off";
      });
      flag->on_update([](Flag* self) {
        g_enabled.store(self->bool_value(), std::memory_order_release);
      });
    }
    return flag;
  }();
  return f;
}

struct TimelineVars {
  std::unique_ptr<PassiveStatus<long>> events;
  std::unique_ptr<PassiveStatus<long>> ring_gauge;

  TimelineVars() {
    events = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(events_total()); });
    events->expose(
        "timeline_events_total",
        "flight-recorder events written across all per-thread rings "
        "(frozen at 0 while trpc_timeline has never been on)");
    ring_gauge = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(ring_count()); });
    ring_gauge->expose(
        "timeline_rings",
        "per-thread flight-recorder rings created so far");
  }
};

thread_local Ring* tls_ring = nullptr;

uint64_t pow2_floor(uint64_t n) {
  uint64_t p = 1;
  while (p * 2 <= n) {
    p *= 2;
  }
  return p;
}

Ring* ring_for_this_thread() {
  Ring* r = tls_ring;
  if (r != nullptr) {
    return r;
  }
  const int64_t kb = ring_kb_flag()->int64_value();
  const uint64_t nslots =
      pow2_floor(std::max<uint64_t>(256, kb * 1024 / sizeof(Slot)));
  r = new Ring(nslots);
  r->tid = static_cast<uint64_t>(syscall(SYS_gettid));
  Worker* w = tls_worker;
  if (w != nullptr) {
    snprintf(r->name, sizeof(r->name), "w%d.%d", w->tag(), w->index());
  } else {
    snprintf(r->name, sizeof(r->name), "thread");
  }
  {
    std::lock_guard<std::mutex> g(registry_mu());
    rings().push_back(r);
  }
  tls_ring = r;
  return r;
}

void write_event(uint32_t type, uint64_t a, uint64_t b, uint64_t trace_id,
                 uint64_t span_id) {
  Ring* r = ring_for_this_thread();
  // Relaxed single-writer head read: only this thread advances it.
  const uint64_t idx = r->head.load(std::memory_order_relaxed);
  Slot& s = r->slots[idx & r->mask];
  // Per-slot seqlock write: invalidate, fence, payload, publish.  The
  // release fence orders the invalidation before the payload stores so
  // a dump that read any new payload byte also sees seq == 0 at its
  // re-check (the standard seqlock store-store edge).
  // Relaxed: ordered by the release fence below, not by this store.
  s.seq.store(0, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
  // Relaxed payload: coherence comes from the seqlock protocol (readers
  // discard slots whose seq moved), not from per-field ordering.
  s.ts_us.store(monotonic_time_us(), std::memory_order_relaxed);
  s.a.store(a, std::memory_order_relaxed);
  s.b.store(b, std::memory_order_relaxed);
  s.trace_id.store(trace_id, std::memory_order_relaxed);
  s.span_id.store(span_id, std::memory_order_relaxed);
  s.fid.store(fiber_self(), std::memory_order_relaxed);
  s.type.store(type, std::memory_order_relaxed);
  s.seq.store(idx + 1, std::memory_order_release);
  r->head.store(idx + 1, std::memory_order_release);
}

struct EventCopy {
  int64_t ts_us;
  uint64_t a, b, trace_id, span_id, fid;
  uint32_t type;
};

// Snapshot of one ring's visible window, oldest first.  Slots the writer
// is overwriting (or has lapped) fail the seqlock re-check and drop out.
std::vector<EventCopy> snapshot(Ring* r, size_t limit) {
  std::vector<EventCopy> out;
  // Acquire: pairs with the writer's release publish so every slot at or
  // below head is at least attempted.
  const uint64_t h = r->head.load(std::memory_order_acquire);
  const uint64_t cap = r->mask + 1;
  uint64_t lo = h > cap ? h - cap : 0;
  // Acquire: a reset() racing this dump must hide a coherent prefix.
  // The floor is snapshotted AFTER head, so it can momentarily exceed
  // our h — that means "everything you saw is hidden", not underflow.
  const uint64_t floor = r->floor.load(std::memory_order_acquire);
  lo = std::max(lo, floor);
  if (lo >= h) {
    return out;
  }
  if (limit > 0 && h - lo > limit) {
    lo = h - limit;
  }
  out.reserve(h - lo);
  for (uint64_t idx = lo; idx < h; ++idx) {
    Slot& s = r->slots[idx & r->mask];
    // Acquire: pairs with the writer's release publish of this slot.
    if (s.seq.load(std::memory_order_acquire) != idx + 1) {
      continue;  // being rewritten / already lapped
    }
    EventCopy e;
    // Relaxed payload reads validated by the seqlock re-check below.
    e.ts_us = s.ts_us.load(std::memory_order_relaxed);
    e.a = s.a.load(std::memory_order_relaxed);
    e.b = s.b.load(std::memory_order_relaxed);
    e.trace_id = s.trace_id.load(std::memory_order_relaxed);
    e.span_id = s.span_id.load(std::memory_order_relaxed);
    e.fid = s.fid.load(std::memory_order_relaxed);
    e.type = s.type.load(std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_acquire);
    // Relaxed re-check: the fence above closes the torn-read window.
    if (s.seq.load(std::memory_order_relaxed) != idx + 1) {
      continue;  // torn: the writer lapped us mid-copy
    }
    out.push_back(e);
  }
  return out;
}

std::vector<Ring*> ring_snapshot() {
  std::lock_guard<std::mutex> g(registry_mu());
  return rings();
}

std::string hex_id(uint64_t id) {
  char buf[20];
  snprintf(buf, sizeof(buf), "%016llx",
           static_cast<unsigned long long>(id));
  return buf;
}

template <typename T>
void append_le(std::string* out, T v) {
  char buf[sizeof(T)];
  memcpy(buf, &v, sizeof(T));
  out->append(buf, sizeof(T));
}

// Eager registration: /flags can list+flip trpc_timeline and /vars shows
// the zeroed series before any traffic (same pattern as the stripe/QoS
// eager flag definitions).
[[maybe_unused]] const bool g_timeline_eager = [] {
  ensure_registered();
  return true;
}();

}  // namespace

void ensure_registered() {
  timeline_flag();
  // Deliberately leaked (the registry outlives statics), volatile so the
  // otherwise-unread pointer store survives optimization — without a
  // live root LSan reports the singleton as a direct leak.
  static TimelineVars* volatile vars = new TimelineVars();
  (void)vars;
}

void set_context_reader(void (*fn)(uint64_t*, uint64_t*)) {
  g_ctx_reader.store(fn, std::memory_order_release);
}

void record(uint32_t type, uint64_t a, uint64_t b) {
  if (!enabled()) {
    return;  // call sites gate too; this is belt-and-braces
  }
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  // Acquire: the reader fn must be fully published before invocation.
  auto fn = g_ctx_reader.load(std::memory_order_acquire);
  if (fn != nullptr) {
    fn(&trace_id, &span_id);
  }
  write_event(type, a, b, trace_id, span_id);
}

void record_ctx(uint32_t type, uint64_t a, uint64_t b, uint64_t trace_id,
                uint64_t span_id) {
  if (!enabled()) {
    return;
  }
  write_event(type, a, b, trace_id, span_id);
}

std::string dump_json(size_t per_thread_limit) {
  ensure_registered();
  Json root = Json::object();
  root.set("pid", Json::number(getpid()));
  // Mono/wall pair read back-to-back (same contract as rpcz_dump_json):
  // the stitcher maps this node's monotonic event times onto wall clock.
  root.set("now_mono_us",
           Json::number(static_cast<double>(monotonic_time_us())));
  root.set("now_wall_us",
           Json::number(static_cast<double>(realtime_us())));
  root.set("enabled", Json::boolean(enabled()));
  Json threads = Json::array();
  for (Ring* r : ring_snapshot()) {
    Json t = Json::object();
    t.set("tid", Json::number(static_cast<double>(r->tid)));
    t.set("name", Json::str(r->name));
    Json events = Json::array();
    for (const EventCopy& e : snapshot(r, per_thread_limit)) {
      Json j = Json::object();
      j.set("ts_us", Json::number(static_cast<double>(e.ts_us)));
      j.set("type", Json::number(e.type));
      j.set("name", Json::str(e.type < kEventTypeCount
                                  ? kEventNames[e.type]
                                  : "unknown"));
      // Hex strings, not numbers: a/b often carry versioned 64-bit
      // handles (fid, socket id) whose low bits a JSON double rounds
      // away past 2^53 — same convention as the trace/span ids.
      j.set("a", Json::str(hex_id(e.a)));
      j.set("b", Json::str(hex_id(e.b)));
      j.set("trace_id", Json::str(hex_id(e.trace_id)));
      j.set("span_id", Json::str(hex_id(e.span_id)));
      j.set("fid", Json::str(hex_id(e.fid)));
      events.push_back(std::move(j));
    }
    t.set("events", std::move(events));
    threads.push_back(std::move(t));
  }
  root.set("threads", std::move(threads));
  return root.dump();
}

std::string dump_binary(size_t per_thread_limit) {
  ensure_registered();
  std::string out;
  out.append("TRPCTL01", 8);
  append_le<int64_t>(&out, monotonic_time_us());
  append_le<int64_t>(&out, realtime_us());
  std::vector<Ring*> rs = ring_snapshot();
  append_le<uint32_t>(&out, static_cast<uint32_t>(rs.size()));
  for (Ring* r : rs) {
    const std::vector<EventCopy> evs = snapshot(r, per_thread_limit);
    append_le<uint64_t>(&out, r->tid);
    out.append(r->name, sizeof(r->name));
    append_le<uint32_t>(&out, static_cast<uint32_t>(evs.size()));
    for (const EventCopy& e : evs) {
      append_le<uint32_t>(&out, e.type);
      append_le<int64_t>(&out, e.ts_us);
      append_le<uint64_t>(&out, e.a);
      append_le<uint64_t>(&out, e.b);
      append_le<uint64_t>(&out, e.trace_id);
      append_le<uint64_t>(&out, e.span_id);
      append_le<uint64_t>(&out, e.fid);
    }
  }
  return out;
}

void reset() {
  for (Ring* r : ring_snapshot()) {
    // Acquire on head: the floor must cover every event published so
    // far, not a stale head that would leave old events visible.
    r->floor.store(r->head.load(std::memory_order_acquire),
                   std::memory_order_release);
  }
}

uint64_t events_total() {
  uint64_t n = 0;
  for (Ring* r : ring_snapshot()) {
    // Relaxed: a lifetime counter read for /vars — transient skew is
    // fine, no data hangs off the sum.
    n += r->head.load(std::memory_order_relaxed);
  }
  return n;
}

int ring_count() {
  std::lock_guard<std::mutex> g(registry_mu());
  return static_cast<int>(rings().size());
}

}  // namespace timeline
}  // namespace trpc
