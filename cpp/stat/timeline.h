// Always-on runtime flight recorder (ISSUE 9) — per-thread lock-free
// rings of fixed-size binary events tracing fiber scheduler transitions,
// messenger phases, socket write-path decisions, stripe chunk lifecycle
// and QoS lane drains, all joinable to rpcz spans via the trace/span ids
// stamped into every event (and the fiber id stamped into every span).
//
// Why a timeline tier on top of the sampling tier (vars, rpcz, pprof):
// a span says an RPC took 9ms; only a timeline says WHERE the 9ms went —
// runnable-but-not-scheduled, parked on a lane drainer, waiting on a
// stripe rail, or stuck behind a coalesced write.  The recorder is gated
// by the reloadable `trpc_timeline` flag (default off); with the flag
// off every hook is ONE relaxed atomic load + branch, the same contract
// as `trpc_analysis` (perf-smoke floors gate it).
//
// Ring model: one single-writer ring per OS thread (the owning thread is
// the only producer, so writes are wait-free — no CAS, no lock).  Each
// slot is a per-slot seqlock: the writer invalidates seq, stores the
// payload, then publishes seq = absolute-index+1 with release; a dump
// re-reads seq around the payload and discards torn slots.  Payload
// fields are relaxed atomics so concurrent dumps are race-free under
// TSan without taxing the writer (plain MOVs on x86).  Rings are sized
// by `trpc_timeline_ring_kb` at ring creation and overwrite oldest —
// a flight recorder keeps the recent window, not history.
//
// Readers: the /timeline builtin (JSON + binary), the trpc_timeline_*
// C API (brpc_tpu/rpc/observe.py timeline()), and tools/trace_stitch.py
// --timeline which merges these events with stitched rpcz spans into
// ONE Perfetto file.
#pragma once

#include <atomic>
#include <cstdint>
#include <string>

namespace trpc {
namespace timeline {

// Event-type table.  MUST stay in lockstep with TIMELINE_EVENTS in
// brpc_tpu/rpc/observe.py — tools/lint_trpc.py's timeline-event rule
// compares the `timeline-event N (name)` markers on kEventNames below
// against the Python decoder's and requires ids consecutive from 1 and
// identical both sides.  Ids are APPEND-ONLY: a recorded binary dump
// must stay decodable by a newer reader.
enum EventType : uint32_t {
  kNone = 0,
  // -- fiber scheduler transitions (a = target fid unless noted) --------
  kFiberCreate = 1,   // a=fid
  kFiberReady = 2,    // a=fid (first publish of a never-run fiber)
  kFiberRun = 3,      // a=fid b=worker index
  kFiberPark = 4,     // a=fid (suspends; Event wait / yield)
  kFiberWake = 5,     // a=fid (re-publish of a fiber that ran before)
  kFiberSteal = 6,    // a=fid b=victim worker index
  kFiberMigrate = 7,  // a=fid b=new worker index (ran elsewhere before)
  kFiberDone = 8,     // a=fid
  // -- messenger phases -------------------------------------------------
  kSweepStart = 9,    // a=socket id
  kSweepEnd = 10,     // a=socket id b=messages cut this sweep
  kInlineBegin = 11,  // a=socket id (inline-response window opens)
  kInlineEnd = 12,    // a=socket id
  kBulkWake = 13,     // a=batch size (one ParkingLot signal for a spawns)
  // -- socket write path ------------------------------------------------
  kWriteFlush = 14,     // a=socket id b=bytes flushed inline (wait-free)
  kWriterHandoff = 15,  // a=socket id (role handed to a KeepWrite fiber)
  kWriteCoalesce = 16,  // a=socket id b=queued Writes absorbed by a drain
  // -- stripe chunk lifecycle (a = stripe_id) ---------------------------
  kStripeCut = 17,   // b=total body bytes (sender starts cutting)
  kStripeSend = 18,  // b=(rail index << 48) | chunk offset; rail index
                     // kStripePrimaryRail = the call's primary socket
                     // (head frame, or a dead-rail fallback retry)
  kStripeLand = 19,  // b=chunk offset (receiver-side memcpy done)
  kStripeDone = 20,  // b=total (reassembly complete, dispatching)
  // -- QoS lane drains --------------------------------------------------
  kQosDrain = 21,  // a=(lane | shard cursor << 8) b=round quantum
  // -- KV-block registry / disaggregation (net/kvstore.h) ---------------
  kKvBlock = 22,  // a=block id, b=(op << 56) | payload len; ops:
                  // 1 publish, 2 serve, 3 evict, 4 stale-reject
  // -- collective transfer schedules (net/collective.h) ------------------
  kCollStep = 23,  // a=step index, b=(op << 56) | step bytes; ops:
                   // 1 all_gather, 2 reduce_scatter, 3 all_to_all,
                   // 4 reshard (CollOp values)
  // -- self-tuning controller (stat/tuner.h) -----------------------------
  kTunerDecision = 24,  // a=knob hash (tuner::knob_hash, FNV-1a of the
                        // flag name), b=(old & 0xffffffff) << 32 |
                        // (new & 0xffffffff) — values wider than 32
                        // bits truncate here; the /tuner journal keeps
                        // them exact
  kDeadline = 25,  // a=correlation id (0 where none applies),
                   // b=(op << 56) | detail; ops: kDeadlineShed* below.
                   // The deadline plane's shed / cancel-fan-out /
                   // suppression decisions (net/deadline.h)
  // -- traffic capture (stat/capture.h) ----------------------------------
  kCapture = 26,  // a=trace id, b=(op << 56) | request bytes; ops:
                  // 1 keep (record retained), 2 drop (reservoir full),
                  // 3 dump (b low bits = records written)
  // -- overlap-aware collectives (net/collective.h) ----------------------
  kCollReady = 27,  // a=schedule step, b=(chunk << 32) | bytes — a
                    // transfer fired by a readiness stamp (chunk =
                    // dep offset / trpc_coll_ready_granularity_bytes)
  // -- SLO engine (stat/slo.h) -------------------------------------------
  kSloBreach = 28,  // a=tenant hash (slo::tenant_hash, FNV-1a of the
                    // tenant name), b=(op << 56) | burn-rate in milli
                    // (fast window, clamped); ops: 1 breach, 2 clear
  // -- streamed-inference front door (net/infer.h) -----------------------
  kTokenStep = 29,  // a=request id, b=(op << 56) | token index; ops:
                    // kTokenStep* below (admit / prefill-done / token /
                    // eos / cancel / shed).  The continuous-batching
                    // scheduler's per-request lifecycle
  kEventTypeCount,
};

// kTokenStep b-field ops (high byte).  For kTokenStepAdmit the low bits
// carry the prefix-cache-matched token count instead of a token index;
// for kTokenStepShed they carry the shed reason (the error code).
constexpr uint64_t kTokenStepAdmit = 1;
constexpr uint64_t kTokenStepPrefillDone = 2;
constexpr uint64_t kTokenStepToken = 3;
constexpr uint64_t kTokenStepEos = 4;
constexpr uint64_t kTokenStepCancel = 5;
constexpr uint64_t kTokenStepShed = 6;

// kDeadline b-field ops (high byte).
constexpr uint64_t kDeadlineShedPreDispatch = 1;  // detail=stamped budget µs
constexpr uint64_t kDeadlineShedQueued = 2;       // expired in dispatch queue
constexpr uint64_t kDeadlineCancelFanout = 3;     // kCancel frame resolved
constexpr uint64_t kDeadlineHedgeSuppressed = 4;  // detail=remaining µs
constexpr uint64_t kDeadlineRetrySuppressed = 5;  // retry budget empty

// Names rendered in the JSON dump and Perfetto export; lint markers on
// each entry keep this table and the Python decoder's in lockstep.
constexpr const char* kEventNames[] = {
    "none",
    "fiber_create",    // timeline-event 1 (fiber_create)
    "fiber_ready",     // timeline-event 2 (fiber_ready)
    "fiber_run",       // timeline-event 3 (fiber_run)
    "fiber_park",      // timeline-event 4 (fiber_park)
    "fiber_wake",      // timeline-event 5 (fiber_wake)
    "fiber_steal",     // timeline-event 6 (fiber_steal)
    "fiber_migrate",   // timeline-event 7 (fiber_migrate)
    "fiber_done",      // timeline-event 8 (fiber_done)
    "sweep_start",     // timeline-event 9 (sweep_start)
    "sweep_end",       // timeline-event 10 (sweep_end)
    "inline_begin",    // timeline-event 11 (inline_begin)
    "inline_end",      // timeline-event 12 (inline_end)
    "bulk_wake",       // timeline-event 13 (bulk_wake)
    "write_flush",     // timeline-event 14 (write_flush)
    "writer_handoff",  // timeline-event 15 (writer_handoff)
    "write_coalesce",  // timeline-event 16 (write_coalesce)
    "stripe_cut",      // timeline-event 17 (stripe_cut)
    "stripe_send",     // timeline-event 18 (stripe_send)
    "stripe_land",     // timeline-event 19 (stripe_land)
    "stripe_done",     // timeline-event 20 (stripe_done)
    "qos_drain",       // timeline-event 21 (qos_drain)
    "kv_block",        // timeline-event 22 (kv_block)
    "coll_step",       // timeline-event 23 (coll_step)
    "tuner_decision",  // timeline-event 24 (tuner_decision)
    "deadline",        // timeline-event 25 (deadline)
    "capture",         // timeline-event 26 (capture)
    "coll_ready",      // timeline-event 27 (coll_ready)
    "slo_breach",      // timeline-event 28 (slo_breach)
    "token_step",      // timeline-event 29 (token_step)
};
static_assert(sizeof(kEventNames) / sizeof(kEventNames[0]) ==
                  kEventTypeCount,
              "kEventNames must cover every EventType");

// kStripeSend rail index meaning "the call's primary socket" — the head
// frame always rides the primary, and a chunk whose rail died retries
// there; labeling either as rail 0 would mis-attribute load to a real
// rail track.  Mirrored by the Python decoders.
constexpr uint64_t kStripePrimaryRail = 0xffff;

// kStripeSend rail values with this bit set are one-sided RMA rails
// (net/rma.h): the chunk was WRITTEN into the peer's registered region
// by rail (value & 0x7fff) — no ring/socket copy happened.  Distinct
// from kStripePrimaryRail (all-ones).  tools/trace_stitch.py renders
// them as their own "rma rail N" tracks so Perfetto shows the elided
// memcpys; brpc_tpu/rpc/observe.py mirrors the constant.
constexpr uint64_t kStripeRmaRailBit = 0x8000;

// Backing switch for the reloadable trpc_timeline flag (the flag's
// on_update hook writes it; hot-path gates inline to one relaxed load).
extern std::atomic<bool> g_enabled;
// Registers the flags + vars (idempotent); any surface that can flip the
// flag before first traffic calls it (builtin /flags does via the eager
// definition in timeline.cc).
void ensure_registered();

inline bool enabled() {
  return g_enabled.load(std::memory_order_relaxed);
}

// Records one event into the calling thread's ring.  trace/span context
// is read through the registered context reader (net/span.cc registers
// its ambient-trace accessor, covering fibers AND plain pthreads); the
// emitting fiber id is captured automatically.  Call sites MUST gate on
// enabled() themselves — record() re-checks, but the call itself should
// cost nothing when the flag is off.
void record(uint32_t type, uint64_t a, uint64_t b);
// Same, with an explicit trace/span context — the scheduler uses this to
// stamp the TARGET fiber's ambient trace onto ready/wake events emitted
// from the waker's thread.
void record_ctx(uint32_t type, uint64_t a, uint64_t b, uint64_t trace_id,
                uint64_t span_id);

// Installs the ambient-trace accessor record() consults (net/span.cc's
// get_ambient_trace).  A hook instead of a direct include keeps stat/
// from depending on net/.
void set_context_reader(void (*fn)(uint64_t* trace_id, uint64_t* span_id));

// Structured dump shared by /timeline?format=json and
// trpc_timeline_dump: {"pid","now_mono_us","now_wall_us","enabled",
// "threads":[{"tid","name","events":[{"ts_us","type","name","a","b",
// "trace_id","span_id","fid"}]}]}.  ALL 64-bit fields (a, b and the
// ids) render as 16-hex-digit strings — a/b often carry versioned
// handles whose low bits a JSON double rounds away past 2^53 (same
// convention as rpcz_dump_json).  Newest `per_thread_limit` events per
// thread, oldest first within a thread.
std::string dump_json(size_t per_thread_limit);
// Compact binary form (observe.py parses it with struct): header
// {char magic[8]="TRPCTL01", i64 now_mono_us, i64 now_wall_us,
// u32 nrings}; per ring {u64 tid, char name[16], u32 nevents}; events
// packed little-endian {u32 type, i64 ts_us, u64 a, u64 b, u64 trace_id,
// u64 span_id, u64 fid} (52 bytes each, no padding).
std::string dump_binary(size_t per_thread_limit);

// Test support: hides everything recorded so far (raises each ring's
// floor to its head — safe against concurrent writers; nothing is
// deallocated).  Lifetime counters keep counting.
void reset();

// Lifetime events recorded across all rings (the timeline_events_total
// var; provably frozen at 0 while the flag has never been on).
uint64_t events_total();
// Per-thread rings created so far (the timeline_rings var).
int ring_count();

}  // namespace timeline
}  // namespace trpc
