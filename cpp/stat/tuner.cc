#include "stat/tuner.h"

#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "base/flags.h"
#include "base/json.h"
#include "base/time.h"
#include "stat/timeline.h"
#include "stat/variable.h"

namespace trpc {
namespace tuner {

namespace {

// ---- flags ---------------------------------------------------------------

std::atomic<bool> g_enabled{false};
void start_loop_if_needed();  // defined with the loop below

Flag* interval_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_tuner_interval_ms", 100,
        "self-tuning controller sampling tick in ms ([10, 3600000]); "
        "rules evaluate every trpc_tuner_eval_ticks ticks");
    if (flag != nullptr) {
      flag->set_int_range(10, 3600000);
    }
    return flag;
  }();
  return f;
}

Flag* eval_ticks_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_tuner_eval_ticks", 3,
        "sampling ticks per tuner evaluation window ([1, 1000]); one "
        "window = one pending-change verdict and at most one new knob "
        "move process-wide");
    if (flag != nullptr) {
      flag->set_int_range(1, 1000);
    }
    return flag;
  }();
  return f;
}

Flag* hysteresis_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_tuner_hysteresis_pct", 5,
        "percentage band a metric must move past before the tuner "
        "calls a change better or worse ([0, 90]); inside the band a "
        "probe is neutral and simply kept");
    if (flag != nullptr) {
      flag->set_int_range(0, 90);
    }
    return flag;
  }();
  return f;
}

Flag* freeze_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_tuner_freeze_ticks", 20,
        "base evaluation windows a knob stays frozen after the "
        "revert-on-regression guard trips ([1, 100000]); doubles per "
        "consecutive trip up to 64x");
    if (flag != nullptr) {
      flag->set_int_range(1, 100000);
    }
    return flag;
  }();
  return f;
}

Flag* tuner_flag() {
  static Flag* f = [] {
    interval_flag();
    eval_ticks_flag();
    hysteresis_flag();
    freeze_flag();
    Flag* flag = Flag::define_bool(
        "trpc_tuner", false,
        "self-tuning controller: samples the var surfaces and drives "
        "per-knob feedback rules (hill-climb/AIMD with hysteresis, "
        "cooldown, revert-on-regression + freeze) through the validated "
        "flag-reload path; decisions journal to /tuner and emit "
        "tuner_decision timeline events (default off; while off no "
        "thread runs and nothing is sampled)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        return v == "true" || v == "false" || v == "1" || v == "0" ||
               v == "on" || v == "off";
      });
      flag->on_update([](Flag* self) {
        const bool on = self->bool_value();
        g_enabled.store(on, std::memory_order_release);
        if (on) {
          start_loop_if_needed();
        }
      });
    }
    return flag;
  }();
  return f;
}

// ---- vars the controller samples ----------------------------------------
// Rule targets/signals plus the status inputs /tuner reports.
// lint_trpc.py's tuner-rule check requires every entry to be an exposed
// var carrying Prometheus HELP (names ending in '_' match dynamically-
// suffixed families, e.g. qos_lane_depth_<n>).
constexpr const char* kTunerInputs[] = {
    "stripe_rx_bytes",               // tuner-input
    "stripe_tx_bytes",               // tuner-input
    "stripe_reassembled",            // tuner-input
    "messenger_cut_budget_yields",   // tuner-input
    "messenger_dispatch_messages",   // tuner-input
    "socket_inline_write_attempts",  // tuner-input
    "socket_inline_write_hits",      // tuner-input
    "qos_lane_depth_",               // tuner-input (one var per lane)
    "qos_lane_dispatch_",            // tuner-input (one var per lane)
    "rma_window_full",               // tuner-input
    "rma_tx_bytes",                  // tuner-input
    "coll_put_bytes",                // tuner-input
    "messenger_probe_stall_skips",   // tuner-input
};

// ---- built-in rule table -------------------------------------------------
// Every knob below must be a defined, validated, *reloadable* trpc_*
// flag — lint_trpc.py's tuner-rule check parses the tuner-knob markers
// against the flag definitions in cpp/.
std::vector<Rule> builtin_rules() {
  std::vector<Rule> v;
  {
    // Stripe chunk geometry: bigger chunks amortize per-frame cost,
    // smaller ones pipeline rails deeper — the optimum is the box's.
    Rule r;
    r.knob = "trpc_stripe_chunk_bytes";  // tuner-knob (trpc_stripe_chunk_bytes)
    r.mode = Mode::kHillClimb;
    r.target = "stripe_rx_bytes";
    r.min_activity = 8e6;  // act only while striping >= 8 MB/s
    r.step_mul = 2.0;
    v.push_back(r);
  }
  {
    Rule r;
    r.knob = "trpc_stripe_rails";  // tuner-knob (trpc_stripe_rails)
    r.mode = Mode::kHillClimb;
    r.target = "stripe_rx_bytes";
    r.min_activity = 8e6;
    r.step_add = 1;
    v.push_back(r);
  }
  {
    // Messenger cut budget, AIMD like the concurrency limiter: a backed-
    // up priority lane (HOL pressure) halves it; sustained cut-budget
    // yields while the lane is quiet double it back.
    Rule r;
    r.knob = "trpc_messenger_cut_budget";  // tuner-knob (trpc_messenger_cut_budget)
    r.mode = Mode::kAimd;
    r.pressure = "qos_lane_depth_0";
    r.pressure_is_level = true;
    r.pressure_high = 4.0;
    r.grow = "messenger_cut_budget_yields";
    r.grow_min = 20.0;  // yields/s before the budget is called binding
    // Growth is judged on dispatch throughput, not on the yields it
    // trivially erases: a bigger budget that doesn't move messages
    // faster is retracted (on this box a small budget often WINS —
    // yields interleave small RPCs better).
    r.objective = "messenger_dispatch_messages";
    r.relief_dir = -1;
    r.step_mul = 2.0;
    r.min = 64 << 10;
    r.max = 256ll << 20;
    r.skip_at_value = 0;  // 0 = never yield, an operator's deliberate
                          // choice the tuner must not override
    v.push_back(r);
  }
  {
    // RMA receive window: window-full fallbacks mean one-sided sends are
    // degrading to the copy path — double the window (new connections
    // pick it up; power-of-two preserved by exact doubling).
    Rule r;
    r.knob = "trpc_rma_window_bytes";  // tuner-knob (trpc_rma_window_bytes)
    r.mode = Mode::kAimd;
    r.pressure = "rma_window_full";
    r.pressure_is_level = false;  // fallbacks/s
    r.pressure_high = 0.5;
    r.relief_dir = 1;
    r.step_mul = 2.0;
    r.skip_at_value = 0;  // 0 = rma plane disabled: never re-enable
    v.push_back(r);
  }
  {
    Rule r;
    r.knob = "trpc_coll_chunk_bytes";  // tuner-knob (trpc_coll_chunk_bytes)
    r.mode = Mode::kHillClimb;
    r.target = "coll_put_bytes";
    r.min_activity = 8e6;
    r.step_mul = 2.0;
    v.push_back(r);
  }
  {
    Rule r;
    r.knob = "trpc_coll_inflight";  // tuner-knob (trpc_coll_inflight)
    r.mode = Mode::kHillClimb;
    r.target = "coll_put_bytes";
    r.min_activity = 8e6;
    r.step_add = 1;
    v.push_back(r);
  }
  {
    // QoS lane weights: while the highest-priority lane stays backed up,
    // double its DRR weight (CSV rewrite through the validated path).
    Rule r;
    r.knob = "trpc_qos_lane_weights";  // tuner-knob (trpc_qos_lane_weights)
    r.mode = Mode::kQosWeights;
    r.pressure = "qos_lane_depth_0";
    r.pressure_is_level = true;
    r.pressure_high = 2.0;
    v.push_back(r);
  }
  return v;
}

// ---- engine --------------------------------------------------------------

struct VarSeries {
  double last_raw = 0.0;
  bool have_raw = false;
  double ema = 0.0;  // rate/s for counters, level for gauges
  bool have_ema = false;
};

struct Decision {
  uint64_t seq;
  int64_t ts_mono_us;
  int64_t ts_wall_us;
  std::string knob;
  int64_t old_num;
  int64_t new_num;
  std::string old_str;  // string knobs (qos weights); empty for ints
  std::string new_str;
  std::string action;  // apply | revert | freeze
  std::string reason;
  double metric_before;
  double metric_after;
};

struct RuleState {
  Rule rule;
  Flag* flag = nullptr;
  int64_t lo = 0;
  int64_t hi = 0;
  int64_t dflt = 0;
  int dir = 0;
  int64_t prev_num = 0;
  std::string prev_str;
  double metric_at_change = 0.0;
  // Which series the pending change is judged on, and in which sense —
  // an AIMD growth move guards its growth signal (minimize), a pressure
  // move its pressure signal (minimize), a hill-climb its target
  // (maximize).
  std::string pending_metric;
  bool pending_maximize = false;
  bool pending = false;
  int cooldown = 0;  // evaluation windows to skip before acting again
  int freeze = 0;    // frozen evaluation windows left
  int backoff = 1;   // freeze multiplier (doubles per guard trip)
  int fails = 0;     // consecutive worsened probes (both directions)
  int neutral_streak = 0;  // consecutive no-effect probes (re-probe pacing)
};

struct Engine {
  std::mutex mu;  // ticks come from the loop thread OR tick_once_for_test
  bool builtins_installed = false;
  std::vector<RuleState> rules;
  std::vector<Rule> extra_rules;  // added before install; merged on tick
  // Rules whose knob flag wasn't registered yet (lazily-defined net/
  // flags, e.g. the collective knobs): re-tried each tick so a plane
  // that comes up AFTER the tuner still gets its rules.
  std::vector<Rule> unresolved_rules;
  size_t rr = 0;
  int64_t last_tick_us = 0;
  int ticks_in_window = 0;
  std::map<std::string, VarSeries> series;
  std::deque<Decision> journal;
  uint64_t seq = 0;
  // Lifetime counters (the tuner_* vars read these; relaxed — pure
  // monotonic telemetry, no data hangs off them).
  std::atomic<uint64_t> ticks{0};
  std::atomic<uint64_t> decisions{0};
  std::atomic<uint64_t> reverts{0};
  std::atomic<uint64_t> freezes{0};
  std::atomic<uint64_t> rejected{0};  // validated set refused (must stay 0)
  // Maintained by the tick so the /vars PassiveStatus can read it
  // WITHOUT taking mu — dump_exposed evaluates vars under the registry
  // lock, and a lambda taking mu there would invert the tick's
  // mu -> registry-lock order (ABBA).
  std::atomic<long> frozen_now{0};
};

Engine& engine() {
  static Engine* e = new Engine();  // leaked with the registries
  return *e;
}

struct TunerVars {
  std::unique_ptr<PassiveStatus<long>> ticks, decisions, reverts, freezes,
      frozen, rejected;
  TunerVars() {
    ticks = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(ticks_total()); });
    ticks->expose("tuner_ticks_total",
                  "self-tuning controller sampling ticks (frozen at 0 "
                  "while trpc_tuner has never been on)");
    decisions = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(decisions_total()); });
    decisions->expose("tuner_decisions_total",
                      "knob changes the tuner applied through the "
                      "validated flag-reload path");
    reverts = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(reverts_total()); });
    reverts->expose("tuner_reverts_total",
                    "tuner changes rolled back by the revert-on-"
                    "regression guard");
    freezes = std::make_unique<PassiveStatus<long>>(
        [] { return static_cast<long>(freezes_total()); });
    freezes->expose("tuner_freezes_total",
                    "knobs frozen for a backoff period after repeated "
                    "regressing probes");
    frozen = std::make_unique<PassiveStatus<long>>([] {
      // Relaxed: gauge maintained by the tick (see Engine::frozen_now —
      // taking the engine mutex here would deadlock against /vars).
      return engine().frozen_now.load(std::memory_order_relaxed);
    });
    frozen->expose("tuner_frozen_knobs",
                   "knobs currently held frozen by the regression guard");
    rejected = std::make_unique<PassiveStatus<long>>([] {
      // Relaxed: telemetry counter read.
      return static_cast<long>(
          engine().rejected.load(std::memory_order_relaxed));
    });
    rejected->expose("tuner_set_rejected",
                     "tuner actuations refused by a flag validator — "
                     "bounds clamping makes this provably 0");
  }
};

// ---- sampling ------------------------------------------------------------

bool read_var_number(const std::string& name, double* out) {
  std::string s;
  if (!Variable::read_exposed(name, &s)) {
    return false;
  }
  char* end = nullptr;
  const double v = strtod(s.c_str(), &end);
  if (end == s.c_str()) {
    return false;
  }
  *out = v;
  return true;
}

// Updates one var's series for this tick; counters become rates/s.
void sample_var(Engine& e, const std::string& name, bool is_level,
                double dt_s) {
  if (name.empty()) {
    return;
  }
  VarSeries& vs = e.series[name];
  double raw = 0.0;
  if (!read_var_number(name, &raw)) {
    vs.have_raw = false;
    vs.have_ema = false;
    return;
  }
  double sample = raw;
  if (!is_level) {
    if (!vs.have_raw || dt_s <= 0.0) {
      vs.last_raw = raw;
      vs.have_raw = true;
      return;  // first observation: no rate yet
    }
    sample = (raw - vs.last_raw) / dt_s;
    if (sample < 0.0) {
      sample = 0.0;  // counter reset (tests): treat as idle
    }
    vs.last_raw = raw;
  }
  vs.have_raw = true;
  // Responsive EMA: ~87% new weight across a 3-tick window.
  vs.ema = vs.have_ema ? 0.5 * vs.ema + 0.5 * sample : sample;
  vs.have_ema = true;
}

bool series_value(Engine& e, const std::string& name, double* out) {
  auto it = e.series.find(name);
  if (it == e.series.end() || !it->second.have_ema) {
    return false;
  }
  *out = it->second.ema;
  return true;
}

// ---- journal + actuation -------------------------------------------------

void journal_decision(Engine& e, const std::string& knob, int64_t old_num,
                      int64_t new_num, const std::string& old_str,
                      const std::string& new_str, const char* action,
                      std::string reason, double before, double after) {
  Decision d;
  d.seq = ++e.seq;
  d.ts_mono_us = monotonic_time_us();
  d.ts_wall_us = realtime_us();
  d.knob = knob;
  d.old_num = old_num;
  d.new_num = new_num;
  d.old_str = old_str;
  d.new_str = new_str;
  d.action = action;
  d.reason = std::move(reason);
  d.metric_before = before;
  d.metric_after = after;
  e.journal.push_back(std::move(d));
  while (e.journal.size() > 512) {
    e.journal.pop_front();
  }
  // Relaxed: pure telemetry counters.  Only APPLIED changes count —
  // reverts/freezes journal too (and emit timeline events) but have
  // their own counters; tuner_decisions_total must mean "the tuner
  // retuned something", not "the journal grew".
  if (strcmp(action, "apply") == 0) {
    e.decisions.fetch_add(1, std::memory_order_relaxed);
  }
  if (timeline::enabled()) {
    timeline::record(
        timeline::kTunerDecision, knob_hash(knob),
        ((static_cast<uint64_t>(old_num) & 0xffffffffull) << 32) |
            (static_cast<uint64_t>(new_num) & 0xffffffffull));
  }
}

// Validated set; clamping upstream makes rejection impossible — the
// tuner_set_rejected var proves it at test time.
bool apply_set(Engine& e, RuleState& s, const std::string& value) {
  if (Flag::set(s.rule.knob, value) != 0) {
    e.rejected.fetch_add(1, std::memory_order_relaxed);
    return false;
  }
  return true;
}

int64_t clamp_knob(const RuleState& s, int64_t v) {
  return std::min(s.hi, std::max(s.lo, v));
}

int64_t step_value(const RuleState& s, int64_t cur, int dir) {
  int64_t next;
  if (s.rule.step_add > 0) {
    next = cur + dir * s.rule.step_add;
  } else if (dir > 0) {
    next = static_cast<int64_t>(std::llround(cur * s.rule.step_mul));
  } else {
    next = static_cast<int64_t>(std::llround(cur / s.rule.step_mul));
  }
  return clamp_knob(s, next);
}

// ---- rule installation ---------------------------------------------------

bool install_rule(Engine& e, const Rule& r, bool quiet) {
  Flag* f = Flag::find(r.knob);
  if (f == nullptr || !f->reloadable()) {
    return false;
  }
  // Mode/type agreement: numeric modes actuate int64 flags only (a
  // hill-climb on a string flag would clobber it with a number the
  // validator might happen to accept); the qos-weights rule is the one
  // string actuator.
  if (r.mode == Mode::kQosWeights) {
    if (f->type() != Flag::Type::kString) {
      return false;
    }
  } else if (f->type() != Flag::Type::kInt64) {
    return false;
  }
  RuleState s;
  s.rule = r;
  s.flag = f;
  int64_t flo = 0;
  int64_t fhi = 0;
  const bool declared = f->bounds(&flo, &fhi);
  // Effective bounds: rule bounds intersected with the flag's declared
  // bounds; a numeric rule without its own bounds REQUIRES declared
  // ones (no bounds means no safe actuation range).  The qos-weights
  // rule rewrites a CSV string — its validator bounds each weight.
  if (r.mode != Mode::kQosWeights) {
    if (r.min == 0 && r.max == 0) {
      if (!declared) {
        return false;
      }
      s.lo = flo;
      s.hi = fhi;
    } else {
      s.lo = declared ? std::max(r.min, flo) : r.min;
      s.hi = declared ? std::min(r.max, fhi) : r.max;
    }
  }
  if (f->type() == Flag::Type::kInt64) {
    s.dflt = strtoll(f->default_value().c_str(), nullptr, 10);
  }
  e.rules.push_back(std::move(s));
  (void)quiet;
  return true;
}

void install_builtins(Engine& e) {
  if (!e.builtins_installed) {
    e.builtins_installed = true;
    for (const Rule& r : builtin_rules()) {
      // A TYPO'd knob here is a lint failure (tuner-rule), not a silent
      // skip; a knob whose defining plane hasn't initialized yet (the
      // lazily-registered collective flags) parks in unresolved_rules
      // and retries below.
      if (!install_rule(e, r, /*quiet=*/true)) {
        e.unresolved_rules.push_back(r);
      }
    }
    for (const Rule& r : e.extra_rules) {
      if (!install_rule(e, r, /*quiet=*/true)) {
        e.unresolved_rules.push_back(r);
      }
    }
    e.extra_rules.clear();
  }
  if (!e.unresolved_rules.empty()) {
    std::vector<Rule> still;
    for (const Rule& r : e.unresolved_rules) {
      if (!install_rule(e, r, /*quiet=*/true)) {
        still.push_back(r);
      }
    }
    e.unresolved_rules.swap(still);
  }
}

// ---- evaluation ----------------------------------------------------------

double hysteresis_frac() {
  return hysteresis_flag()->int64_value() / 100.0;
}

void freeze_rule(Engine& e, RuleState& s, const char* why, double before,
                 double after) {
  s.freeze = static_cast<int>(freeze_flag()->int64_value()) * s.backoff;
  s.backoff = std::min(s.backoff * 2, 64);
  s.fails = 0;
  e.freezes.fetch_add(1, std::memory_order_relaxed);
  const int64_t cur =
      s.flag->type() == Flag::Type::kInt64 ? s.flag->int64_value() : 0;
  journal_decision(e, s.rule.knob, cur, cur, "", "", "freeze",
                   std::string(why) + " (frozen " +
                       std::to_string(s.freeze) + " windows)",
                   before, after);
}

// Verdict on a pending change.  Returns true when the change survived.
bool evaluate_pending(Engine& e, RuleState& s) {
  double now = 0.0;
  if (!series_value(e, s.pending_metric, &now)) {
    // Signal vanished (lanes off, load gone): keep the change, no
    // verdict possible.
    s.pending = false;
    return true;
  }
  const double before = s.metric_at_change;
  const double hyst = hysteresis_frac();
  const bool maximize = s.pending_maximize;
  const bool worsened = maximize
                            ? now < before * (1.0 - hyst)
                            : now > before * (1.0 + hyst) + 1e-9;
  const bool improved = maximize
                            ? now > before * (1.0 + hyst)
                            : now < before * (1.0 - hyst) - 1e-9;
  s.pending = false;
  if (worsened) {
    // Revert-on-regression: roll the knob back through the validated
    // path, flip the probe direction, and freeze after two consecutive
    // failed probes (both directions worsened).
    const int64_t cur = s.flag->type() == Flag::Type::kInt64
                            ? s.flag->int64_value()
                            : 0;
    if (s.flag->type() == Flag::Type::kString) {
      const std::string cur_str = s.flag->string_value();
      apply_set(e, s, s.prev_str);
      journal_decision(e, s.rule.knob, 0, 0, cur_str, s.prev_str,
                       "revert", "metric worsened past hysteresis",
                       before, now);
    } else {
      apply_set(e, s, std::to_string(s.prev_num));
      journal_decision(e, s.rule.knob, cur, s.prev_num, "", "", "revert",
                       "metric worsened past hysteresis", before, now);
    }
    e.reverts.fetch_add(1, std::memory_order_relaxed);
    s.dir = -s.dir;
    s.cooldown = 1;
    if (++s.fails >= 2) {
      freeze_rule(e, s, "both probe directions regressed", before, now);
    }
    return false;
  }
  if (improved) {
    s.fails = 0;
    s.backoff = 1;
    s.neutral_streak = 0;
    return true;
  }
  // Neutral verdict.  A maximize-guarded probe (hill-climb, or an AIMD
  // growth move with a declared objective) that bought nothing
  // measurable is RETRACTED — keeping it would let a flat metric drift
  // the knob to a bound 5% at a time, below the hysteresis radar — and
  // re-probes back off exponentially so a settled knob stops churning.
  // AIMD relief moves keep instead: their effect can be legitimately
  // deferred (a bigger rma window only helps connections opened after
  // it), and the pressure signal re-triggering is the escalation path.
  if (s.rule.mode == Mode::kHillClimb ||
      (s.rule.mode == Mode::kAimd && s.pending_maximize)) {
    const int64_t cur = s.flag->int64_value();
    apply_set(e, s, std::to_string(s.prev_num));
    journal_decision(e, s.rule.knob, cur, s.prev_num, "", "", "revert",
                     "no measurable improvement: probe retracted",
                     before, now);
    e.reverts.fetch_add(1, std::memory_order_relaxed);
    s.dir = -s.dir;
    s.neutral_streak = std::min(s.neutral_streak + 1, 8);
    s.cooldown = 2 * s.neutral_streak;
    return false;
  }
  s.cooldown = 1;
  return true;
}

// Attempts a new action for rule `s`.  Returns true when a knob changed.
bool act(Engine& e, RuleState& s) {
  if (s.rule.mode == Mode::kQosWeights) {
    double depth = 0.0;
    if (!series_value(e, s.rule.pressure, &depth) ||
        depth <= s.rule.pressure_high) {
      return false;
    }
    const std::string cur = s.flag->string_value();
    // Double the highest-priority lane's weight, capped at the
    // validator's 4096 ceiling.
    const char* p = cur.c_str();
    char* end = nullptr;
    const long w0 = strtol(p, &end, 10);
    if (end == p || w0 >= 4096) {
      return false;
    }
    const long nw0 = std::min<long>(w0 * 2, 4096);
    std::string next = std::to_string(nw0) + std::string(end);
    s.prev_str = cur;
    s.metric_at_change = depth;
    s.pending_metric = s.rule.pressure;
    s.pending_maximize = false;  // a weight boost must DRAIN the lane
    if (!apply_set(e, s, next)) {
      return false;
    }
    s.pending = true;
    journal_decision(e, s.rule.knob, w0, nw0, cur, next, "apply",
                     "priority lane backed up: doubling lane-0 weight",
                     depth, 0.0);
    return true;
  }

  const int64_t cur = s.flag->int64_value();
  if (s.rule.skip_at_value >= 0 && cur == s.rule.skip_at_value) {
    return false;  // deliberately-disabled plane: never re-enable it
  }
  if (s.rule.mode == Mode::kAimd) {
    double pressure = 0.0;
    const bool have_pressure =
        series_value(e, s.rule.pressure, &pressure);
    if (have_pressure && pressure > s.rule.pressure_high) {
      const int64_t next = step_value(s, cur, s.rule.relief_dir);
      if (next == cur) {
        return false;
      }
      s.prev_num = cur;
      s.metric_at_change = pressure;
      s.pending_metric = s.rule.pressure;
      s.pending_maximize = false;  // relief must LOWER the pressure
      if (!apply_set(e, s, std::to_string(next))) {
        return false;
      }
      s.pending = true;
      journal_decision(e, s.rule.knob, cur, next, "", "", "apply",
                       "pressure " + s.rule.pressure + " above " +
                           std::to_string(s.rule.pressure_high),
                       pressure, 0.0);
      return true;
    }
    double grow = 0.0;
    if (!s.rule.grow.empty() && series_value(e, s.rule.grow, &grow) &&
        grow > s.rule.grow_min &&
        (!have_pressure || pressure <= s.rule.pressure_high)) {
      const int64_t next = step_value(s, cur, -s.rule.relief_dir);
      if (next == cur) {
        return false;
      }
      s.prev_num = cur;
      // Guard metric: the declared objective (maximize) when the rule
      // names one, else the growth signal itself (minimize).
      if (!s.rule.objective.empty()) {
        double obj = 0.0;
        if (!series_value(e, s.rule.objective, &obj)) {
          return false;  // objective not flowing: no evidence to act on
        }
        s.metric_at_change = obj;
        s.pending_metric = s.rule.objective;
        s.pending_maximize = true;
      } else {
        s.metric_at_change = grow;
        s.pending_metric = s.rule.grow;
        s.pending_maximize = false;
      }
      if (!apply_set(e, s, std::to_string(next))) {
        return false;
      }
      s.pending = true;
      journal_decision(e, s.rule.knob, cur, next, "", "", "apply",
                       "growth signal " + s.rule.grow + " above " +
                           std::to_string(s.rule.grow_min),
                       grow, 0.0);
      return true;
    }
    return false;
  }

  // Hill-climb.
  double metric = 0.0;
  if (!series_value(e, s.rule.target, &metric) ||
      metric < s.rule.min_activity) {
    return false;  // activity gate: idle traffic never random-walks knobs
  }
  if (s.dir == 0) {
    // First probe heads toward the compiled default (the hand-tuned
    // value) — recovery from a deliberately-wrong seed takes the short
    // way, and the metric verdict still vetoes a wrong guess.
    s.dir = cur < s.dflt ? 1 : (cur > s.dflt ? -1 : 1);
  }
  int64_t next = step_value(s, cur, s.dir);
  if (next == cur) {  // pinned at a bound: turn around
    s.dir = -s.dir;
    next = step_value(s, cur, s.dir);
    if (next == cur) {
      return false;  // lo == hi: nothing to tune
    }
  }
  s.prev_num = cur;
  s.metric_at_change = metric;
  s.pending_metric = s.rule.target;
  s.pending_maximize = true;
  if (!apply_set(e, s, std::to_string(next))) {
    return false;
  }
  s.pending = true;
  journal_decision(e, s.rule.knob, cur, next, "", "", "apply",
                   std::string("hill-climb probe ") +
                       (s.dir > 0 ? "up" : "down") + " on " +
                       s.rule.target,
                   metric, 0.0);
  return true;
}

void tick_locked(Engine& e) {
  install_builtins(e);
  const int64_t now = monotonic_time_us();
  const double dt_s =
      e.last_tick_us > 0 ? (now - e.last_tick_us) / 1e6 : 0.0;
  e.last_tick_us = now;
  e.ticks.fetch_add(1, std::memory_order_relaxed);

  // Sample every var any rule references — each name exactly ONCE per
  // tick (two rules sharing a counter would otherwise zero the second
  // rate computation).  A name claimed as a level anywhere samples as a
  // level.
  std::map<std::string, bool> wanted;  // name -> is_level
  for (const RuleState& s : e.rules) {
    if (!s.rule.target.empty()) {
      wanted[s.rule.target] |= s.rule.target_is_level;
    }
    if (!s.rule.pressure.empty()) {
      wanted[s.rule.pressure] |= s.rule.pressure_is_level;
    }
    if (!s.rule.grow.empty()) {
      wanted[s.rule.grow] |= false;
    }
    if (!s.rule.objective.empty()) {
      wanted[s.rule.objective] |= false;
    }
  }
  for (const auto& [name, is_level] : wanted) {
    sample_var(e, name, is_level, dt_s);
  }

  if (++e.ticks_in_window <
      static_cast<int>(eval_ticks_flag()->int64_value())) {
    return;
  }
  e.ticks_in_window = 0;

  // Evaluation window: verdicts on pending changes first, then at most
  // ONE new knob move process-wide (clean attribution).
  for (RuleState& s : e.rules) {
    if (s.freeze > 0) {
      --s.freeze;
      continue;
    }
    if (s.pending) {
      evaluate_pending(e, s);
    }
  }
  if (e.rules.empty()) {
    return;
  }
  for (size_t i = 0; i < e.rules.size(); ++i) {
    RuleState& s = e.rules[(e.rr + i) % e.rules.size()];
    if (s.freeze > 0 || s.pending) {
      continue;
    }
    if (s.cooldown > 0) {
      --s.cooldown;
      continue;
    }
    if (act(e, s)) {
      e.rr = (e.rr + i + 1) % e.rules.size();
      break;
    }
  }
  long frozen = 0;
  for (const RuleState& s : e.rules) {
    frozen += s.freeze > 0 ? 1 : 0;
  }
  // Relaxed: gauge published for the /vars PassiveStatus (which must
  // not take mu — see Engine::frozen_now).
  e.frozen_now.store(frozen, std::memory_order_relaxed);
}

// ---- control loop --------------------------------------------------------

std::atomic<bool> g_loop_started{false};

// Sliced-sleep control loop (same shape as the stat sampler thread: a
// detached pthread polling an atomic — no condvar, nothing for a
// sanitizer to model).  Sleeps the interval in <=50ms slices, so a
// disable stops ticking within one slice and an interval flip takes
// effect without a stale 1h sleep outliving it.  Ticks come AFTER a
// full interval, never immediately on enable — tests park the loop by
// pinning the interval high and drive tick_once_for_test instead.
void loop_body() {
  int64_t slept_ms = 0;
  for (;;) {
    if (!g_enabled.load(std::memory_order_acquire)) {
      slept_ms = 0;
      usleep(100 * 1000);  // idle poll: one relaxed load per 100ms
      continue;
    }
    const int64_t interval = interval_flag()->int64_value();
    if (slept_ms < interval) {
      const int64_t slice = std::min<int64_t>(50, interval - slept_ms);
      usleep(static_cast<useconds_t>(slice * 1000));
      slept_ms += slice;
      continue;
    }
    slept_ms = 0;
    Engine& e = engine();
    std::lock_guard<std::mutex> g(e.mu);
    if (g_enabled.load(std::memory_order_acquire)) {
      tick_locked(e);
    }
  }
}

void start_loop_if_needed() {
  // Acq_rel exchange: exactly one caller starts the (detached, leaked)
  // controller thread; later enables just let the running loop see
  // g_enabled flip.
  if (!g_loop_started.exchange(true, std::memory_order_acq_rel)) {
    std::thread(loop_body).detach();
  }
}

// Eager registration: /flags can list+flip trpc_tuner before traffic
// (same pattern as the timeline/stripe eager definitions).
[[maybe_unused]] const bool g_tuner_eager = [] {
  ensure_registered();
  return true;
}();

}  // namespace

void ensure_registered() {
  tuner_flag();
  // Deliberately leaked (registry outlives statics); volatile keeps the
  // otherwise-unread pointer store alive so LSan sees a root.
  static TunerVars* volatile vars = new TunerVars();
  (void)vars;
}

bool enabled() { return g_enabled.load(std::memory_order_acquire); }

int add_rule(const Rule& r) {
  Flag* f = Flag::find(r.knob);
  if (f == nullptr || !f->reloadable()) {
    return -1;
  }
  Engine& e = engine();
  std::lock_guard<std::mutex> g(e.mu);
  if (!e.builtins_installed) {
    e.extra_rules.push_back(r);
    return 0;
  }
  return install_rule(e, r, /*quiet=*/false) ? 0 : -1;
}

uint64_t knob_hash(const std::string& name) {
  uint64_t h = 1469598103934665603ull;  // FNV-1a 64
  for (const char c : name) {
    h ^= static_cast<unsigned char>(c);
    h *= 1099511628211ull;
  }
  return h;
}

std::string dump_json(size_t limit) {
  ensure_registered();
  Engine& e = engine();
  Json root = Json::object();
  root.set("enabled", Json::boolean(enabled()));
  root.set("interval_ms", Json::number(static_cast<double>(
                              interval_flag()->int64_value())));
  root.set("ticks_total",
           Json::number(static_cast<double>(ticks_total())));
  root.set("decisions_total",
           Json::number(static_cast<double>(decisions_total())));
  root.set("reverts_total",
           Json::number(static_cast<double>(reverts_total())));
  root.set("freezes_total",
           Json::number(static_cast<double>(freezes_total())));
  std::lock_guard<std::mutex> g(e.mu);
  install_builtins(e);  // idempotent: /tuner shows the table pre-tick
  Json rules = Json::array();
  for (const RuleState& s : e.rules) {
    Json j = Json::object();
    j.set("knob", Json::str(s.rule.knob));
    j.set("mode", Json::str(s.rule.mode == Mode::kHillClimb
                                ? "hill_climb"
                                : s.rule.mode == Mode::kAimd
                                      ? "aimd"
                                      : "qos_weights"));
    j.set("value", Json::str(s.flag->value_string()));
    j.set("min", Json::number(static_cast<double>(s.lo)));
    j.set("max", Json::number(static_cast<double>(s.hi)));
    j.set("pending", Json::boolean(s.pending));
    j.set("frozen_windows", Json::number(s.freeze));
    j.set("cooldown", Json::number(s.cooldown));
    j.set("dir", Json::number(s.dir));
    const std::string& sig = s.rule.mode == Mode::kHillClimb
                                 ? s.rule.target
                                 : s.rule.pressure;
    j.set("signal", Json::str(sig));
    auto it = e.series.find(sig);
    if (it != e.series.end() && it->second.have_ema) {
      j.set("metric", Json::number(it->second.ema));
    }
    rules.push_back(std::move(j));
  }
  root.set("rules", std::move(rules));
  // Live input snapshot (the observability surfaces the controller
  // samples — dynamic families skipped when unregistered).
  Json inputs = Json::object();
  for (const char* name : kTunerInputs) {
    std::string base(name);
    if (!base.empty() && base.back() == '_') {
      for (int i = 0; i < 8; ++i) {
        const std::string full = base + std::to_string(i);
        double v = 0.0;
        if (read_var_number(full, &v)) {
          inputs.set(full, Json::number(v));
        }
      }
      continue;
    }
    double v = 0.0;
    if (read_var_number(base, &v)) {
      inputs.set(base, Json::number(v));
    }
  }
  root.set("inputs", std::move(inputs));
  Json decisions = Json::array();
  const size_t n = e.journal.size();
  const size_t start = limit > 0 && n > limit ? n - limit : 0;
  for (size_t i = start; i < n; ++i) {
    const Decision& d = e.journal[i];
    Json j = Json::object();
    j.set("seq", Json::number(static_cast<double>(d.seq)));
    j.set("ts_mono_us",
          Json::number(static_cast<double>(d.ts_mono_us)));
    j.set("ts_wall_us",
          Json::number(static_cast<double>(d.ts_wall_us)));
    j.set("knob", Json::str(d.knob));
    j.set("old", Json::number(static_cast<double>(d.old_num)));
    j.set("new", Json::number(static_cast<double>(d.new_num)));
    if (!d.old_str.empty() || !d.new_str.empty()) {
      j.set("old_str", Json::str(d.old_str));
      j.set("new_str", Json::str(d.new_str));
    }
    j.set("action", Json::str(d.action));
    j.set("reason", Json::str(d.reason));
    j.set("metric_before", Json::number(d.metric_before));
    j.set("metric_after", Json::number(d.metric_after));
    decisions.push_back(std::move(j));
  }
  root.set("decisions", std::move(decisions));
  return root.dump();
}

uint64_t ticks_total() {
  // Relaxed: lifetime counter reads for /vars.
  return engine().ticks.load(std::memory_order_relaxed);
}
uint64_t decisions_total() {
  return engine().decisions.load(std::memory_order_relaxed);
}
uint64_t reverts_total() {
  return engine().reverts.load(std::memory_order_relaxed);
}
uint64_t freezes_total() {
  return engine().freezes.load(std::memory_order_relaxed);
}

int tick_once_for_test() {
  if (!enabled()) {
    return -1;
  }
  Engine& e = engine();
  std::lock_guard<std::mutex> g(e.mu);
  tick_locked(e);
  return 0;
}

void reset_for_test() {
  Engine& e = engine();
  std::lock_guard<std::mutex> g(e.mu);
  e.builtins_installed = false;
  e.rules.clear();
  e.extra_rules.clear();
  e.unresolved_rules.clear();
  e.rr = 0;
  e.last_tick_us = 0;
  e.ticks_in_window = 0;
  e.series.clear();
  e.journal.clear();
  e.seq = 0;
  e.ticks.store(0, std::memory_order_relaxed);
  e.decisions.store(0, std::memory_order_relaxed);
  e.reverts.store(0, std::memory_order_relaxed);
  e.freezes.store(0, std::memory_order_relaxed);
  e.rejected.store(0, std::memory_order_relaxed);
  e.frozen_now.store(0, std::memory_order_relaxed);
}

}  // namespace tuner
}  // namespace trpc
