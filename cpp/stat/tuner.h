// Self-tuning runtime (ROADMAP item 4): an in-process feedback
// controller that closes the observability loop back into the knobs.
//
// The runtime carries ~30 validated reloadable flags (stripe rails and
// chunk size, QoS lane weights, messenger cut budget, rma window,
// collective chunk/inflight ...) and, since the flight recorder, the
// vars to see exactly where time goes — but every number was hand-tuned
// per box, which no production fleet does.  This tier closes the loop:
// a control loop on its own background thread (never a dispatch fiber —
// tuning must not compete with the traffic it tunes, and it must run in
// fiber-less client processes too) samples the existing var surfaces on
// a `trpc_tuner_interval_ms` tick and drives per-knob feedback rules
// through the *validated* flag-reload path only:
//
//   - hill-climb rules (stripe chunk/rails, collective chunk/inflight)
//     probe a knob in its current direction and keep the move only when
//     the target metric (a counter rate, e.g. stripe_rx_bytes/s)
//     improves past a hysteresis band;
//   - AIMD rules (messenger cut budget, rma window) mirror the existing
//     concurrency limiter: a pressure signal (priority-lane depth,
//     window-full fallbacks) triggers a multiplicative corrective move,
//     a growth signal (cut-budget yields) an opposing step;
//   - the QoS-weights rule rewrites the lane-weight CSV (highest lane
//     doubled) while the priority lane stays backed up.
//
// Guardrails, all mandatory: per-knob hard bounds intersected with the
// flag's DECLARED bounds (base/flags.h set_int_range — clamping happens
// before the set, so out-of-range actuation is impossible by
// construction); a revert-on-regression guard (a change that worsens
// its own metric within one evaluation window is rolled back and the
// knob frozen for an exponentially-backed-off period); an activity gate
// (a rule whose target isn't flowing does nothing, so an idle or
// correctly-tuned box is never perturbed); and at most ONE knob change
// per evaluation window process-wide, so attribution stays clean.
//
// Every decision lands twice: a structured journal entry served by
// /tuner (and trpc_tuner_dump), and a `tuner_decision` timeline event
// (a = knob_hash(name), b = old<<32|new) so a tuning run is itself a
// Perfetto artifact via tools/trace_stitch.py --timeline.
//
// Flag-off contract (same as trpc_analysis / trpc_timeline): default
// off; while off, no thread runs, nothing is sampled, every tuner var
// is provably frozen at 0, and no flag is ever touched.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

namespace trpc {
namespace tuner {

enum class Mode : int {
  kHillClimb = 0,  // probe knob, keep moves that improve `target`
  kAimd = 1,       // pressure -> multiplicative relief; growth -> step back
  kQosWeights = 2, // CSV lane weights: double lane 0 under backlog
};

// One feedback rule.  `knob` must name a defined, *reloadable* trpc_*
// flag (add_rule rejects anything else); numeric actuation clamps into
// [min, max] intersected with the flag's declared bounds.
struct Rule {
  std::string knob;
  Mode mode = Mode::kHillClimb;

  // kHillClimb: maximize `target` — a counter whose per-second rate is
  // the metric, or the raw level when target_is_level (synthetic test
  // metrics).  The rule acts only while the metric >= min_activity.
  std::string target;
  bool target_is_level = false;
  double min_activity = 0.0;

  // kAimd / kQosWeights: `pressure` (level by default, rate when
  // pressure_is_level = false) above pressure_high triggers a
  // multiplicative move in relief_dir; `grow` (counter rate) above
  // grow_min while pressure is quiet steps the opposite way.
  std::string pressure;
  bool pressure_is_level = true;
  double pressure_high = 0.0;
  std::string grow;
  double grow_min = 0.0;
  int relief_dir = -1;
  // Optional guard for AIMD growth moves: when set, a growth move is
  // judged on THIS counter's rate (maximize) instead of on the grow
  // signal itself, and a move that buys nothing measurable is
  // retracted like a hill-climb probe.  Without it a growth move would
  // always "improve" its own trigger (doubling the cut budget always
  // lowers yields) while silently regressing the throughput the knob
  // exists to serve.
  std::string objective;

  // Step geometry and hard bounds (0/0 = flag-declared bounds only).
  double step_mul = 2.0;   // multiplicative step (> 1)
  int64_t step_add = 0;    // when > 0: additive step instead
  int64_t min = 0;
  int64_t max = 0;
  // Sentinel value meaning "this subsystem is deliberately disabled":
  // while the knob reads exactly this, the rule never actuates (the
  // tuner must not re-enable a plane behind the operator's back).
  // -1 = no sentinel.  The rma window rule sets 0.
  int64_t skip_at_value = -1;
};

// Registers the trpc_tuner* flags and tuner vars (idempotent).
void ensure_registered();
bool enabled();

// Installs an additional rule (tests, embedders).  Returns 0, or -1
// when the knob is not a defined reloadable flag.  Built-in rules are
// installed automatically on the first tick.
int add_rule(const Rule& r);

// FNV-1a 64 of the knob name — the `a` payload of tuner_decision
// timeline events.
uint64_t knob_hash(const std::string& name);

// The /tuner body: {"enabled", counters, "rules": [...], "inputs":
// {...}, "decisions": [newest `limit` entries, oldest first]}.  Served
// even while the flag is off (the journal may hold decisions from an
// earlier enabled window).
std::string dump_json(size_t limit);

// Lifetime counters (the tuner_* vars; provably frozen at 0 while
// trpc_tuner has never been on).
uint64_t ticks_total();
uint64_t decisions_total();
uint64_t reverts_total();
uint64_t freezes_total();

// -- test support ---------------------------------------------------------
// Runs one engine tick synchronously (same lock as the control loop).
// Returns 0, or -1 when the tuner is disabled.  Tests pin
// trpc_tuner_interval_ms high so the background loop stays parked and
// ticks are fully deterministic.
int tick_once_for_test();
// Drops dynamically-added rules, per-rule state, series history and the
// journal; lifetime counters reset too.  Call with the flag OFF.
void reset_for_test();

}  // namespace tuner
}  // namespace trpc
