#include "stat/variable.h"

#include <map>
#include <mutex>

namespace trpc {

namespace {
// Deliberately leaked: Variables with static storage (e.g. per-method
// recorders inside static Servers) deregister during static destruction,
// which can run after this TU's statics would have died.
std::mutex& vars_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
std::map<std::string, Variable*>& vars() {
  static auto* m = new std::map<std::string, Variable*>();
  return *m;
}
}  // namespace

Variable::~Variable() { hide(); }

int Variable::expose(const std::string& name) {
  std::lock_guard<std::mutex> g(vars_mu());
  if (!name_.empty()) {
    vars().erase(name_);
  }
  name_ = name;
  vars()[name] = this;
  return 0;
}

void Variable::hide() {
  std::lock_guard<std::mutex> g(vars_mu());
  if (!name_.empty()) {
    auto it = vars().find(name_);
    if (it != vars().end() && it->second == this) {
      vars().erase(it);
    }
    name_.clear();
  }
}

std::vector<std::pair<std::string, std::string>> Variable::dump_exposed() {
  std::lock_guard<std::mutex> g(vars_mu());
  std::vector<std::pair<std::string, std::string>> out;
  out.reserve(vars().size());
  for (auto& [name, var] : vars()) {
    out.emplace_back(name, var->value_str());
  }
  return out;
}

}  // namespace trpc
