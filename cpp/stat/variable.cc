#include "stat/variable.h"

#include <map>
#include <mutex>

namespace trpc {

namespace {
// Deliberately leaked: Variables with static storage (e.g. per-method
// recorders inside static Servers) deregister during static destruction,
// which can run after this TU's statics would have died.
std::mutex& vars_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
std::map<std::string, Variable*>& vars() {
  static auto* m = new std::map<std::string, Variable*>();
  return *m;
}
}  // namespace

std::string Variable::escape_help(const std::string& v) {
  std::string out;
  out.reserve(v.size());
  for (char c : v) {
    if (c == '\\') {
      out += "\\\\";
    } else if (c == '\n') {
      out += "\\n";
    } else {
      out.push_back(c);
    }
  }
  return out;
}

Variable::~Variable() { hide(); }

int Variable::expose(const std::string& name,
                     const std::string& description) {
  std::lock_guard<std::mutex> g(vars_mu());
  if (!name_.empty()) {
    vars().erase(name_);
  }
  name_ = name;
  description_ = description;
  vars()[name] = this;
  return 0;
}

void Variable::hide() {
  std::lock_guard<std::mutex> g(vars_mu());
  if (!name_.empty()) {
    auto it = vars().find(name_);
    if (it != vars().end() && it->second == this) {
      vars().erase(it);
    }
    name_.clear();
  }
}

// Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*
std::string Variable::sanitize_metric_name(const std::string& name) {
  std::string out = name;
  for (char& c : out) {
    const bool ok = (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
                    (c >= '0' && c <= '9') || c == '_' || c == ':';
    if (!ok) {
      c = '_';
    }
  }
  if (!out.empty() && out[0] >= '0' && out[0] <= '9') {
    out = "_" + out;
  }
  return out;
}

std::string Variable::ensure_total_suffix(std::string metric) {
  static const std::string kSuffix = "_total";
  if (metric.size() < kSuffix.size() ||
      metric.compare(metric.size() - kSuffix.size(), kSuffix.size(),
                     kSuffix) != 0) {
    metric += kSuffix;
  }
  return metric;
}

std::string Variable::prometheus_str(const std::string& name) const {
  const std::string v = value_str();
  // Emit only plainly numeric values.
  char* end = nullptr;
  strtod(v.c_str(), &end);
  if (end == v.c_str() || *end != '\0') {
    return "";
  }
  const char* type = prometheus_type();
  std::string metric = sanitize_metric_name(name);
  if (type == std::string("counter")) {
    // Monotonic series carry the conventional `_total` suffix so
    // Prometheus tooling (rate(), increase()) treats them correctly.
    metric = ensure_total_suffix(metric);
  }
  std::string out;
  if (!description_.empty()) {
    out += "# HELP " + metric + " " + escape_help(description_) + "\n";
  }
  out += "# TYPE " + metric + " " + type + "\n" + metric + " " + v + "\n";
  return out;
}

std::string Variable::dump_prometheus() {
  std::lock_guard<std::mutex> g(vars_mu());
  std::string out;
  for (auto& [name, var] : vars()) {
    out += var->prometheus_str(name);
  }
  return out;
}

std::vector<std::pair<std::string, std::string>> Variable::dump_exposed() {
  std::lock_guard<std::mutex> g(vars_mu());
  std::vector<std::pair<std::string, std::string>> out;
  out.reserve(vars().size());
  for (auto& [name, var] : vars()) {
    out.emplace_back(name, var->value_str());
  }
  return out;
}

bool Variable::read_exposed(const std::string& name, std::string* out) {
  std::lock_guard<std::mutex> g(vars_mu());
  auto it = vars().find(name);
  if (it == vars().end()) {
    return false;
  }
  if (out != nullptr) {
    *out = it->second->value_str();
  }
  return true;
}

bool Variable::with_exposed(const std::string& name,
                            const std::function<void(Variable*)>& fn) {
  std::lock_guard<std::mutex> g(vars_mu());
  auto it = vars().find(name);
  if (it == vars().end()) {
    return false;
  }
  fn(it->second);
  return true;
}

}  // namespace trpc
