#include "stat/variable.h"

#include <map>
#include <mutex>

namespace trpc {

namespace {
std::mutex g_vars_mu;
std::map<std::string, Variable*>& vars() {
  static std::map<std::string, Variable*> m;
  return m;
}
}  // namespace

Variable::~Variable() { hide(); }

int Variable::expose(const std::string& name) {
  std::lock_guard<std::mutex> g(g_vars_mu);
  if (!name_.empty()) {
    vars().erase(name_);
  }
  name_ = name;
  vars()[name] = this;
  return 0;
}

void Variable::hide() {
  std::lock_guard<std::mutex> g(g_vars_mu);
  if (!name_.empty()) {
    auto it = vars().find(name_);
    if (it != vars().end() && it->second == this) {
      vars().erase(it);
    }
    name_.clear();
  }
}

std::vector<std::pair<std::string, std::string>> Variable::dump_exposed() {
  std::lock_guard<std::mutex> g(g_vars_mu);
  std::vector<std::pair<std::string, std::string>> out;
  out.reserve(vars().size());
  for (auto& [name, var] : vars()) {
    out.emplace_back(name, var->value_str());
  }
  return out;
}

}  // namespace trpc
