// Variable — named metric registry (parity: bvar::Variable,
// /root/reference/src/bvar/variable.h:118 expose/dump_exposed, the substrate
// of the /vars builtin service).
#pragma once

#include <functional>
#include <string>
#include <vector>

namespace trpc {

class Variable {
 public:
  virtual ~Variable();
  virtual std::string value_str() const = 0;
  // Prometheus exposition lines for this variable (may be several series,
  // e.g. latency quantiles).  Default: one gauge when value_str is numeric.
  virtual std::string prometheus_str(const std::string& name) const;

  // Registers under `name` (replaces any previous owner of the name).
  int expose(const std::string& name);
  void hide();
  const std::string& name() const { return name_; }

  static std::vector<std::pair<std::string, std::string>> dump_exposed();
  // Rewrites a name into the Prometheus metric charset.
  static std::string sanitize_metric_name(const std::string& name);
  // Full Prometheus text-format dump (parity: builtin/
  // prometheus_metrics_service.*, served at /brpc_metrics).
  static std::string dump_prometheus();

 private:
  std::string name_;
};

// Pull-based variable: value computed by a callback at dump time (parity:
// bvar::PassiveStatus).
template <typename T>
class PassiveStatus : public Variable {
 public:
  explicit PassiveStatus(std::function<T()> fn) : fn_(std::move(fn)) {}
  ~PassiveStatus() override { hide(); }
  std::string value_str() const override {
    return std::to_string(fn_());
  }
  T get_value() const { return fn_(); }

 private:
  std::function<T()> fn_;
};

}  // namespace trpc
