// Variable — named metric registry (parity: bvar::Variable,
// /root/reference/src/bvar/variable.h:118 expose/dump_exposed, the substrate
// of the /vars builtin service and the trpc_vars_* C API).
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <string>
#include <vector>

namespace trpc {

class Variable {
 public:
  virtual ~Variable();
  virtual std::string value_str() const = 0;
  // Prometheus exposition lines for this variable (may be several series,
  // e.g. latency quantiles).  Default: one gauge (or counter, per
  // prometheus_type) when value_str is numeric, with a # HELP line when a
  // description was given at expose time.
  virtual std::string prometheus_str(const std::string& name) const;
  // Exposition type for the DEFAULT single-series renderer: "gauge" or
  // "counter".  Counters get the Prometheus `_total` suffix appended to
  // the metric name unless it is already there.
  virtual const char* prometheus_type() const { return "gauge"; }

  // Registers under `name` (replaces any previous owner of the name).
  // The description feeds the # HELP exposition line ("" = no HELP).
  int expose(const std::string& name, const std::string& description = "");
  void hide();
  const std::string& name() const { return name_; }
  const std::string& description() const { return description_; }

  static std::vector<std::pair<std::string, std::string>> dump_exposed();
  // Single-variable read under the registry lock; false when unknown.
  static bool read_exposed(const std::string& name, std::string* out);
  // Runs fn(var) under the registry lock (the var cannot be hidden or
  // destroyed while fn runs); false when the name is unknown.  fn must
  // not touch the registry (expose/hide) — that would self-deadlock.
  static bool with_exposed(const std::string& name,
                           const std::function<void(Variable*)>& fn);
  // Rewrites a name into the Prometheus metric charset.
  static std::string sanitize_metric_name(const std::string& name);
  // Appends `_total` to an (already sanitized) counter metric name when
  // missing — the Prometheus convention for monotonic series.
  static std::string ensure_total_suffix(std::string metric);
  // Escapes a description for a # HELP payload (newlines/backslashes —
  // a raw newline would start a bogus sample line).  Every renderer
  // emitting HELP must route descriptions through this; they can be
  // arbitrary user input via trpc_latency_create/trpc_gauge_create.
  static std::string escape_help(const std::string& description);
  // Full Prometheus text-format dump (parity: builtin/
  // prometheus_metrics_service.*, served at /brpc_metrics).
  static std::string dump_prometheus();

 private:
  std::string name_;
  std::string description_;
};

// Pull-based variable: value computed by a callback at dump time (parity:
// bvar::PassiveStatus).
template <typename T>
class PassiveStatus : public Variable {
 public:
  explicit PassiveStatus(std::function<T()> fn) : fn_(std::move(fn)) {}
  ~PassiveStatus() override { hide(); }
  std::string value_str() const override {
    return std::to_string(fn_());
  }
  T get_value() const { return fn_(); }

 private:
  std::function<T()> fn_;
};

// Push-based scalar gauge: a level someone SETS (pipeline depth, window
// size, inflight count) rather than a monotonic event count.  The C API
// hands these to Python (trpc_gauge_*) so client-side metrics live in the
// same registry as the native ones (parity: bvar::Status<int64_t>).
class IntGauge : public Variable {
 public:
  IntGauge() = default;
  explicit IntGauge(int64_t initial) : value_(initial) {}
  ~IntGauge() override { hide(); }

  void set(int64_t v) { value_.store(v, std::memory_order_relaxed); }
  int64_t add(int64_t delta) {
    return value_.fetch_add(delta, std::memory_order_relaxed) + delta;
  }
  int64_t get_value() const {
    return value_.load(std::memory_order_relaxed);
  }
  std::string value_str() const override {
    return std::to_string(get_value());
  }

 private:
  std::atomic<int64_t> value_{0};
};

}  // namespace trpc
