// Window / PerSecond — trailing-window views over reducers.
//
// Parity: bvar::Window / bvar::PerSecond (/root/reference/src/bvar/window.h):
// a Window<Adder> shows the delta accumulated over the last N seconds; a
// PerSecond divides it by the span.  Backed by the shared once-per-second
// Sampler thread.
#pragma once

#include <mutex>
#include <vector>

#include "stat/reducer.h"
#include "stat/sampler.h"
#include "stat/variable.h"

namespace trpc {

class WindowedAdder : public Variable, public Sampled {
 public:
  explicit WindowedAdder(Adder* base, int window_secs = 10)
      : base_(base),
        // Seed with the CURRENT total: an already-running counter's history
        // must not appear as trailing-window activity.
        samples_(static_cast<size_t>(std::max(window_secs, 1)) + 1,
                 base->get_value()) {
    Sampler::instance()->add(this);
  }
  ~WindowedAdder() override {
    hide();
    Sampler::instance()->remove(this);
  }

  // Sum accumulated during the trailing window.
  int64_t get_value() const {
    std::lock_guard<std::mutex> g(mu_);
    const size_t n = samples_.size();
    return samples_[(pos_ + n - 1) % n] - samples_[pos_ % n];
  }

  int64_t per_second() const {
    std::lock_guard<std::mutex> g(mu_);
    const size_t n = samples_.size();
    // Divide by the span actually sampled so young windows aren't diluted.
    const int64_t span = static_cast<int64_t>(
        std::min(pos_ > 0 ? pos_ : 1, n - 1));
    return (samples_[(pos_ + n - 1) % n] - samples_[pos_ % n]) / span;
  }

  std::string value_str() const override {
    return std::to_string(get_value());
  }

  void take_sample() override {
    std::lock_guard<std::mutex> g(mu_);
    samples_[pos_ % samples_.size()] = base_->get_value();
    ++pos_;
  }

 private:
  Adder* base_;
  mutable std::mutex mu_;
  std::vector<int64_t> samples_;  // ring of cumulative snapshots
  size_t pos_ = 0;
};

}  // namespace trpc
