// Tests for the runtime invariant checkers (fiber/analysis.h, ISSUE 7):
// a seeded deliberate lock-order inversion and a deliberate blocking
// call on a dispatch context must be CAUGHT with trpc_analysis on, and
// INVISIBLE with it off (the default).
#include "fiber/analysis.h"

#include <atomic>
#include <new>
#include <string>

#include "base/flags.h"
#include "fiber/event.h"
#include "fiber/fiber.h"
#include "fiber/sync.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

void set_analysis(bool on) {
  analysis::ensure_registered();
  EXPECT_EQ(Flag::set("trpc_analysis", on ? "true" : "false"), 0);
}

struct InversionArgs {
  FiberMutex* a;
  FiberMutex* b;
  CountdownEvent* done;
};

// Two fibers acquiring {a,b} in opposite orders — the textbook
// inversion.  Serialized (second order runs after the first completes)
// so the test records the ORDER VIOLATION without ever risking the
// actual deadlock.
void lock_ab(void* p) {
  auto* args = static_cast<InversionArgs*>(p);
  args->a->lock();
  args->b->lock();
  args->b->unlock();
  args->a->unlock();
  args->done->signal();
}

void lock_ba(void* p) {
  auto* args = static_cast<InversionArgs*>(p);
  args->b->lock();
  args->a->lock();
  args->a->unlock();
  args->b->unlock();
  args->done->signal();
}

uint64_t run_seeded_inversion() {
  FiberMutex a;
  FiberMutex b;
  {
    CountdownEvent done(1);
    InversionArgs args{&a, &b, &done};
    EXPECT_EQ(fiber_start(nullptr, lock_ab, &args, 0), 0);
    EXPECT_EQ(done.wait(), 0);
  }
  {
    CountdownEvent done(1);
    InversionArgs args{&a, &b, &done};
    EXPECT_EQ(fiber_start(nullptr, lock_ba, &args, 0), 0);
    EXPECT_EQ(done.wait(), 0);
  }
  return analysis::lock_cycles_found();
}

struct BlockArgs {
  CountdownEvent* done;
};

// A fiber that enters a dispatch scope (as the messenger inline window
// and QoS drainer role do) and then parks on an Event — the deliberate
// no-pinned-read-fiber violation.
void block_in_dispatch(void* p) {
  auto* args = static_cast<BlockArgs*>(p);
  {
    analysis::ScopedDispatch scope("test dispatch scope");
    fiber_sleep_us(10 * 1000);  // parks via Event::wait
  }
  args->done->signal();
}

uint64_t run_deliberate_block() {
  CountdownEvent done(1);
  BlockArgs args{&done};
  EXPECT_EQ(fiber_start(nullptr, block_in_dispatch, &args, 0), 0);
  EXPECT_EQ(done.wait(), 0);
  return analysis::blocking_violations();
}

}  // namespace

TEST_CASE(analysis_off_by_default_and_invisible) {
  fiber_init(0);
  analysis::reset_for_test();
  set_analysis(false);
  EXPECT(!analysis::enabled());
  const uint64_t cycles0 = run_seeded_inversion();
  const uint64_t blocks0 = run_deliberate_block();
  // Flag off: the same seeded misbehavior records NOTHING.
  EXPECT_EQ(cycles0, 0u);
  EXPECT_EQ(blocks0, 0u);
  EXPECT(analysis::report().find("OFF") != std::string::npos);
}

TEST_CASE(analysis_catches_seeded_lock_inversion) {
  fiber_init(0);
  analysis::reset_for_test();
  set_analysis(true);
  const uint64_t before = analysis::lock_cycles_found();
  const uint64_t after = run_seeded_inversion();
  set_analysis(false);
  EXPECT_EQ(before, 0u);
  EXPECT(after >= 1u);
  const std::string r = analysis::report();
  EXPECT(r.find("lock-order inversion") != std::string::npos);
}

TEST_CASE(analysis_catches_blocking_on_dispatch_fiber) {
  fiber_init(0);
  analysis::reset_for_test();
  set_analysis(true);
  const uint64_t after = run_deliberate_block();
  set_analysis(false);
  EXPECT(after >= 1u);
  const std::string r = analysis::report();
  EXPECT(r.find("blocking call (Event::wait)") != std::string::npos);
  EXPECT(r.find("test dispatch scope") != std::string::npos);
}

TEST_CASE(analysis_scope_exit_clears_context) {
  fiber_init(0);
  analysis::reset_for_test();
  set_analysis(true);
  const uint64_t before = analysis::blocking_violations();
  // Same park, but OUTSIDE any dispatch scope: clean.
  CountdownEvent done(1);
  BlockArgs args{&done};
  fiber_start(
      nullptr,
      [](void* p) {
        {
          analysis::ScopedDispatch scope("transient scope");
        }
        fiber_sleep_us(5 * 1000);  // scope already exited — no violation
        static_cast<BlockArgs*>(p)->done->signal();
      },
      &args, 0);
  EXPECT_EQ(done.wait(), 0);
  set_analysis(false);
  EXPECT_EQ(analysis::blocking_violations(), before);
}

namespace {

// Flag flipped OFF while a recorded lock is held: the unlock must still
// run release bookkeeping (per-acquisition latch), or `a` stays on the
// fiber's held stack and the later b-acquisition records a phantom a→b.
void toggle_while_held(void* p) {
  auto* args = static_cast<InversionArgs*>(p);
  args->a->lock();
  Flag::set("trpc_analysis", "false");
  args->a->unlock();
  Flag::set("trpc_analysis", "true");
  args->b->lock();
  args->b->unlock();
  args->done->signal();
}

}  // namespace

TEST_CASE(analysis_flag_toggle_while_held_leaves_no_stale_state) {
  fiber_init(0);
  analysis::reset_for_test();
  set_analysis(true);
  FiberMutex a;
  FiberMutex b;
  {
    CountdownEvent done(1);
    InversionArgs args{&a, &b, &done};
    EXPECT_EQ(fiber_start(nullptr, toggle_while_held, &args, 0), 0);
    EXPECT_EQ(done.wait(), 0);
  }
  {
    // Reverse order b→a: a cycle can exist ONLY via the stale a→b edge
    // a leaked held-stack entry would have recorded above.
    CountdownEvent done(1);
    InversionArgs args{&a, &b, &done};
    EXPECT_EQ(fiber_start(nullptr, lock_ba, &args, 0), 0);
    EXPECT_EQ(done.wait(), 0);
  }
  set_analysis(false);
  EXPECT_EQ(analysis::lock_cycles_found(), 0u);
}

TEST_CASE(analysis_lock_destruction_clears_graph_node) {
  fiber_init(0);
  analysis::reset_for_test();
  set_analysis(true);
  // Same ADDRESS, two distinct lock lifetimes, opposite orders against
  // G: without the destructor hook the recycled address would stitch a
  // phantom cycle between locks that never coexisted.
  FiberMutex g;
  alignas(FiberMutex) unsigned char storage[sizeof(FiberMutex)];
  {
    auto* l1 = new (storage) FiberMutex();
    CountdownEvent done(1);
    InversionArgs args{&g, l1, &done};
    EXPECT_EQ(fiber_start(nullptr, lock_ab, &args, 0), 0);  // g → l1
    EXPECT_EQ(done.wait(), 0);
    l1->~FiberMutex();
  }
  {
    auto* l2 = new (storage) FiberMutex();  // same address, new lock
    CountdownEvent done(1);
    InversionArgs args{&g, l2, &done};
    EXPECT_EQ(fiber_start(nullptr, lock_ba, &args, 0), 0);  // l2 → g
    EXPECT_EQ(done.wait(), 0);
    l2->~FiberMutex();
  }
  set_analysis(false);
  EXPECT_EQ(analysis::lock_cycles_found(), 0u);
}

TEST_CASE(analysis_recycled_addresses_report_fresh_inversion) {
  fiber_init(0);
  analysis::reset_for_test();
  set_analysis(true);
  // Dual of the destruction test: a REAL inversion between new locks
  // recycled onto previously-reported addresses must be reported again —
  // a stale reported-pair entry surviving destroy would swallow it.
  alignas(FiberMutex) unsigned char sa[sizeof(FiberMutex)];
  alignas(FiberMutex) unsigned char sb[sizeof(FiberMutex)];
  for (int life = 0; life < 2; ++life) {
    auto* a = new (sa) FiberMutex();
    auto* b = new (sb) FiberMutex();
    {
      CountdownEvent done(1);
      InversionArgs args{a, b, &done};
      EXPECT_EQ(fiber_start(nullptr, lock_ab, &args, 0), 0);
      EXPECT_EQ(done.wait(), 0);
    }
    {
      CountdownEvent done(1);
      InversionArgs args{a, b, &done};
      EXPECT_EQ(fiber_start(nullptr, lock_ba, &args, 0), 0);
      EXPECT_EQ(done.wait(), 0);
    }
    EXPECT_EQ(analysis::lock_cycles_found(), uint64_t(life + 1));
    b->~FiberMutex();
    a->~FiberMutex();
  }
  set_analysis(false);
}

TEST_CASE(analysis_ordered_locks_report_nothing) {
  fiber_init(0);
  analysis::reset_for_test();
  set_analysis(true);
  // Consistent a→b order across many fibers: a graph, but no cycle.
  FiberMutex a;
  FiberMutex b;
  constexpr int kFibers = 8;
  CountdownEvent done(kFibers);
  InversionArgs args{&a, &b, &done};
  for (int i = 0; i < kFibers; ++i) {
    EXPECT_EQ(fiber_start(nullptr, lock_ab, &args, 0), 0);
  }
  EXPECT_EQ(done.wait(), 0);
  set_analysis(false);
  EXPECT_EQ(analysis::lock_cycles_found(), 0u);
}

TEST_MAIN
