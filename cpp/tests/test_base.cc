// L1 base library unit tests (parity model: the reference's butil
// unittests, /root/reference/test/iobuf_unittest.cpp etc.)
#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <thread>

#include "base/doubly_buffered.h"
#include "base/endpoint.h"
#include "base/flat_map.h"
#include "base/iobuf.h"
#include "base/rand.h"
#include "base/recordio.h"
#include "base/sha256.h"
#include "base/snappy.h"
#include "base/resource_pool.h"
#include "base/time.h"
#include "base/json.h"
#include "tests/test_util.h"

using namespace trpc;

TEST_CASE(iobuf_append_copy) {
  IOBuf buf;
  buf.append("hello ");
  buf.append(std::string("world"));
  EXPECT_EQ(buf.size(), 11u);
  EXPECT(buf.to_string() == "hello world");

  char tmp[6] = {};
  EXPECT_EQ(buf.copy_to(tmp, 5, 6), 5u);
  EXPECT(memcmp(tmp, "world", 5) == 0);
}

TEST_CASE(iobuf_large_append_spans_blocks) {
  IOBuf buf;
  std::string big(100000, 'x');
  for (size_t i = 0; i < big.size(); ++i) {
    big[i] = static_cast<char>('a' + i % 26);
  }
  buf.append(big);
  EXPECT_EQ(buf.size(), big.size());
  EXPECT(buf.block_count() >= big.size() / HostArena::kDefaultBlockSize);
  EXPECT(buf.to_string() == big);
}

TEST_CASE(iobuf_zero_copy_share) {
  IOBuf a;
  a.append("0123456789");
  IOBuf b = a;  // shares blocks
  EXPECT_EQ(b.size(), 10u);
  a.clear();
  EXPECT(b.to_string() == "0123456789");  // b keeps blocks alive
}

TEST_CASE(iobuf_copy_then_append_does_not_corrupt) {
  IOBuf a;
  a.append("abc");
  IOBuf b = a;   // block now multi-referenced
  a.append("X");  // must NOT extend the shared block in place
  EXPECT(b.to_string() == "abc");
  EXPECT(a.to_string() == "abcX");
}

TEST_CASE(iobuf_cutn_pop) {
  IOBuf a;
  a.append("header|body-bytes");
  IOBuf head;
  EXPECT_EQ(a.cutn(&head, 7), 7u);
  EXPECT(head.to_string() == "header|");
  EXPECT(a.to_string() == "body-bytes");
  EXPECT_EQ(a.pop_front(5), 5u);
  EXPECT(a.to_string() == "bytes");
  EXPECT_EQ(a.pop_back(1), 1u);
  EXPECT(a.to_string() == "byte");
}

TEST_CASE(iobuf_user_data_deleter) {
  static std::atomic<int> deleted{0};
  static char payload[] = "device-buffer";
  {
    IOBuf a;
    a.append_user_data(
        payload, 13, [](void*, void*) { deleted.fetch_add(1); }, nullptr,
        0x1234);
    IOBuf b = a;
    a.clear();
    EXPECT_EQ(deleted.load(), 0);
    EXPECT(b.to_string() == "device-buffer");
    EXPECT_EQ(b.ref_at(0).block->user_meta, 0x1234u);
  }
  EXPECT_EQ(deleted.load(), 1);
}

TEST_CASE(iobuf_fd_roundtrip) {
  int fds[2];
  EXPECT_EQ(pipe(fds), 0);
  IOBuf w;
  std::string msg(20000, 'q');
  w.append(msg);
  size_t sent = 0;
  while (sent < msg.size()) {
    ssize_t rc = w.cut_into_fd(fds[1]);
    EXPECT(rc > 0);
    sent += rc;
  }
  IOBuf r;
  while (r.size() < msg.size()) {
    ssize_t rc = r.append_from_fd(fds[0], msg.size() - r.size());
    EXPECT(rc > 0);
  }
  EXPECT(r.to_string() == msg);
  close(fds[0]);
  close(fds[1]);
}

TEST_CASE(resource_pool_reuse) {
  struct Obj {
    uint32_t version = 0;
    int payload = 0;
  };
  auto* pool = ResourcePool<Obj>::instance();
  Obj* o1 = nullptr;
  const uint32_t id1 = pool->acquire(&o1);
  o1->version = 7;
  o1->payload = 42;
  pool->release(id1);
  Obj* o2 = nullptr;
  const uint32_t id2 = pool->acquire(&o2);
  EXPECT_EQ(id2, id1);       // recycled
  EXPECT_EQ(o2->version, 7u);  // state survives recycle (version armor)
  EXPECT(pool->at(id2) == o2);
}

TEST_CASE(flat_map_basics) {
  FlatMap<std::string, int> m;
  for (int i = 0; i < 100; ++i) {
    m["key" + std::to_string(i)] = i;
  }
  EXPECT_EQ(m.size(), 100u);
  for (int i = 0; i < 100; ++i) {
    int* v = m.seek("key" + std::to_string(i));
    EXPECT(v != nullptr && *v == i);
  }
  EXPECT(m.seek("missing") == nullptr);
  EXPECT(m.erase("key50"));
  EXPECT(!m.erase("key50"));
  EXPECT(m.seek("key50") == nullptr);
  EXPECT_EQ(m.size(), 99u);
  // All other keys still reachable after backward-shift deletion.
  for (int i = 0; i < 100; ++i) {
    if (i != 50) {
      EXPECT(m.seek("key" + std::to_string(i)) != nullptr);
    }
  }
}

TEST_CASE(doubly_buffered_read_write) {
  DoublyBufferedData<std::vector<int>> dbd;
  dbd.Modify([](std::vector<int>& v) {
    v = {1, 2, 3};
    return true;
  });
  {
    auto ptr = dbd.Read();
    EXPECT_EQ(ptr->size(), 3u);
  }
  std::atomic<bool> stop{false};
  std::thread reader([&] {
    while (!stop.load()) {
      auto ptr = dbd.Read();
      EXPECT(ptr->size() == 3u || ptr->size() == 4u);
    }
  });
  for (int i = 0; i < 100; ++i) {
    dbd.Modify([i](std::vector<int>& v) {
      v = (i % 2 == 0) ? std::vector<int>{1, 2, 3, 4}
                       : std::vector<int>{1, 2, 3};
      return true;
    });
  }
  stop.store(true);
  reader.join();
}

TEST_CASE(endpoint_parse_format) {
  EndPoint ep;
  EXPECT_EQ(str2endpoint("10.1.2.3:8080", &ep), 0);
  EXPECT_EQ(ep.port, 8080);
  EXPECT(endpoint2str(ep) == "10.1.2.3:8080");

  EXPECT_EQ(str2endpoint("10.1.2.3:8080/2", &ep), 0);
  EXPECT_EQ(ep.device_ordinal, 2);
  EXPECT(endpoint2str(ep) == "10.1.2.3:8080/2");

  EXPECT(str2endpoint("nonsense", &ep) != 0);
  EXPECT(str2endpoint("1.2.3.4:99999", &ep) != 0);
  EXPECT(str2endpoint("1.2.3.4:80oops", &ep) != 0);
  EXPECT(str2endpoint("1.2.3.4:80/3junk", &ep) != 0);
  EXPECT(hostname2endpoint("1.2.3.4:99999", &ep) != 0);
  EXPECT(hostname2endpoint("localhost:-5", &ep) != 0);
  EXPECT(hostname2endpoint("localhost:abc", &ep) != 0);

  EXPECT_EQ(hostname2endpoint("localhost:80", &ep), 0);
  EXPECT(endpoint2str(ep) == "127.0.0.1:80");
  EXPECT_EQ(hostname2endpoint("localhost:80/3", &ep), 0);
  EXPECT_EQ(ep.device_ordinal, 3);

  sockaddr_in sa = endpoint2sockaddr(ep);
  EndPoint back = sockaddr2endpoint(sa);
  EXPECT(back.ip == ep.ip && back.port == ep.port);
}

TEST_CASE(recordio_roundtrip) {
  #define RECPATH "/tmp/trpc_test_recordio.dat"
  unlink(RECPATH);
  {
    RecordWriter w(RECPATH);
    EXPECT(w.valid());
    for (int i = 0; i < 10; ++i) {
      IOBuf rec;
      rec.append("record-" + std::to_string(i) + std::string(i * 100, 'r'));
      EXPECT(w.write(rec));
    }
    w.flush();
  }
  RecordReader r(RECPATH);
  EXPECT(r.valid());
  int count = 0;
  IOBuf rec;
  while (r.read(&rec)) {
    const std::string s = rec.to_string();
    EXPECT(s.rfind("record-" + std::to_string(count), 0) == 0);
    EXPECT_EQ(s.size(), 8 + count * 100);
    rec.clear();
    ++count;
  }
  EXPECT_EQ(count, 10);
  unlink(RECPATH);
}

TEST_CASE(fast_rand_spread) {
  uint64_t seen_buckets = 0;
  for (int i = 0; i < 1000; ++i) {
    seen_buckets |= 1ull << (fast_rand_less_than(64));
  }
  EXPECT(__builtin_popcountll(seen_buckets) > 48);
}

TEST_CASE(time_monotonic) {
  const int64_t a = monotonic_time_ns();
  const int64_t b = monotonic_time_ns();
  EXPECT(b >= a);
  EXPECT(realtime_us() > 1600000000000000LL);  // sane wall clock
}

TEST_CASE(json_roundtrip_and_strictness) {
  Json j;
  EXPECT(Json::parse(
      "{\"a\": [1, 2.5, true, null, \"x\\n\\u0041\"], \"b\": {\"c\": -3}}",
      &j));
  EXPECT(j.find("a") != nullptr);
  EXPECT_EQ(j.find("a")->size(), 5u);
  EXPECT_EQ((*j.find("a"))[0].as_number(), 1.0);
  EXPECT((*j.find("a"))[2].as_bool());
  EXPECT((*j.find("a"))[3].is_null());
  EXPECT((*j.find("a"))[4].as_string() == "x\nA");
  EXPECT_EQ(j.find("b")->find("c")->as_number(), -3.0);
  // Dump → parse roundtrip is stable.
  Json j2;
  EXPECT(Json::parse(j.dump(), &j2));
  EXPECT(j2.dump() == j.dump());
  // Strictness: trailing garbage, unterminated, depth bomb.
  EXPECT(!Json::parse("{} x", &j));
  EXPECT(!Json::parse("\"abc", &j));
  EXPECT(!Json::parse("[1,]", &j));
  std::string bomb(100, '[');
  EXPECT(!Json::parse(bomb, &j));
  // Escaping in dump.
  Json s1 = Json::str("a\"b\\c\n");
  EXPECT(s1.dump() == "\"a\\\"b\\\\c\\n\"");
}

TEST_CASE(sha256_and_hmac_vectors) {
  auto hex = [](const uint8_t* d, size_t n) {
    std::string s;
    for (size_t i = 0; i < n; ++i) {
      char b[3];
      snprintf(b, 3, "%02x", d[i]);
      s += b;
    }
    return s;
  };
  uint8_t d[32];
  // FIPS 180-4 vectors.
  sha256("abc", 3, d);
  EXPECT(hex(d, 32) ==
         "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad");
  sha256("", 0, d);
  EXPECT(hex(d, 32) ==
         "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855");
  sha256("abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq", 56,
         d);
  EXPECT(hex(d, 32) ==
         "248d6a61d20638b8e5c026930c3e6039a33ce45964ff2167f6ecedd419db06c1");
  // One-million-'a' vector exercises the streaming/update path.
  {
    std::string m(1000000, 'a');
    sha256(m.data(), m.size(), d);
    EXPECT(hex(d, 32) ==
           "cdc76e5c9914fb9281a1c7e284d73e67f1809a48a497200e046d39ccc7112cd0");
  }
  // RFC 4231 HMAC-SHA256 cases 1-2.
  {
    std::string key(20, '\x0b');
    hmac_sha256(key.data(), key.size(), "Hi There", 8, d);
    EXPECT(hex(d, 32) ==
           "b0344c61d8db38535ca8afceaf0bf12b881dc200c9833da726e9376c2e32cff7");
  }
  hmac_sha256("Jefe", 4, "what do ya want for nothing?", 28, d);
  EXPECT(hex(d, 32) ==
         "5bdcc146bf60754e6a042426089575c75a003f089d2739839dec58b964ec3843");
  // RFC 4231 case 6: key longer than the block (hashed-key path).
  {
    std::string key(131, '\xaa');
    const char* msg = "Test Using Larger Than Block-Size Key - Hash Key First";
    hmac_sha256(key.data(), key.size(), msg, strlen(msg), d);
    EXPECT(hex(d, 32) ==
           "60e431591ee0b67f0d8a26aacbf5b77f8e0bc6213728c5140546040f0ee37f54");
  }
}

TEST_CASE(snappy_spec_vectors_decode) {
  // Hand-assembled frames straight from the format description.
  // Pure literal: varint(5) + tag(len-1=4)<<2 + "abcde".
  {
    std::string wire = "\x05\x10"
                       "abcde";
    std::string out;
    EXPECT(snappy_decompress(wire.data(), wire.size(), &out, 1 << 20));
    EXPECT(out == "abcde");
  }
  // Run-length via overlapping copy: varint(10), literal "x",
  // tag01 len=9 offset=1 → "x" * 10.
  {
    std::string wire("\x0a\x00x\x15\x01", 5);
    std::string out;
    EXPECT(snappy_decompress(wire.data(), wire.size(), &out, 1 << 20));
    EXPECT(out == std::string(10, 'x'));
  }
  // Copy with 16-bit offset (tag 2): "abcdabcd".
  {
    std::string wire = std::string("\x08\x0c"
                                   "abcd",
                                   6);
    wire += '\x0e';  // tag2 len=4
    wire += '\x04';  // offset lo
    wire += '\0';    // offset hi
    std::string out;
    EXPECT(snappy_decompress(wire.data(), wire.size(), &out, 1 << 20));
    EXPECT(out == "abcdabcd");
  }
  // Malformed: offset beyond produced output must fail, not read OOB.
  {
    std::string wire("\x08\x00x\x15\x09", 5);  // copy offset 9, produced 1
    std::string out;
    EXPECT(!snappy_decompress(wire.data(), wire.size(), &out, 1 << 20));
  }
  // Zip-bomb guard: declared size above the limit fails fast.
  {
    std::string wire = "\xff\xff\xff\x7f";  // varint ~256MB, no body
    std::string out;
    EXPECT(!snappy_decompress(wire.data(), wire.size(), &out, 1024));
  }
}

TEST_CASE(snappy_roundtrips) {
  auto rt = [](const std::string& plain) {
    std::string wire, back;
    snappy_compress(plain.data(), plain.size(), &wire);
    EXPECT(snappy_decompress(wire.data(), wire.size(), &back,
                             plain.size() + 1));
    EXPECT(back == plain);
    return wire.size();
  };
  rt("");
  rt("a");
  rt("hello");
  // Highly repetitive: must actually compress.
  std::string runs;
  for (int i = 0; i < 1000; ++i) {
    runs += "abcdefgh";
  }
  EXPECT(rt(runs) < runs.size() / 4);
  // Incompressible pseudo-random bytes: correctness over ratio, and the
  // multi-fragment path (>64KB) must reassemble exactly.
  std::string rand_big;
  uint64_t x = 0x9e3779b97f4a7c15ull;
  for (size_t i = 0; i < 200 * 1024; ++i) {
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    rand_big += static_cast<char>(x);
  }
  rt(rand_big);
  // Compressible data spanning fragments.
  std::string mix;
  for (int i = 0; i < 5000; ++i) {
    mix += "the quick brown fox jumps over the lazy dog ";
    mix += static_cast<char>(i);
  }
  rt(mix);
}

TEST_CASE(snappy_decode_rejects_mutations) {
  // Deterministic mutation fuzz over a valid frame: every single-byte
  // corruption must either fail cleanly or produce bounded output —
  // never crash or overread (ASan run covers the latter).
  std::string plain;
  for (int i = 0; i < 300; ++i) {
    plain += "payload-" + std::to_string(i % 37);
  }
  std::string wire;
  snappy_compress(plain.data(), plain.size(), &wire);
  for (size_t i = 0; i < wire.size(); ++i) {
    for (int delta : {1, 0x55, 0xff}) {
      std::string mut = wire;
      mut[i] = static_cast<char>(mut[i] + delta);
      std::string out;
      (void)snappy_decompress(mut.data(), mut.size(), &out,
                              plain.size() * 4);
      EXPECT(out.size() <= plain.size() * 4);
    }
  }
}

TEST_MAIN
