// Traffic-capture tests (stat/capture.h, ISSUE 16): flag-off
// invisibility (vars frozen at 0), deterministic sampling under a
// seeded stream, per-tenant stratified quotas with exact drop
// accounting, capture-file roundtrip including the tail-group metadata
// (tenant/priority/deadline budget/trace ids), bounded memory under
// 64MB bodies, and an end-to-end pass over a live server with QoS-
// tagged + deadline-stamped traffic.  Also runs under TSan via
// tests/test_cpp.py (record() contends with concurrent dumps by
// design).
#include "stat/capture.h"

#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <set>
#include <string>
#include <vector>

#include "base/flags.h"
#include "base/json.h"
#include "base/recordio.h"
#include "net/channel.h"
#include "net/server.h"
#include "stat/variable.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

Server* g_server = nullptr;
int g_port = 0;

void start_once() {
  if (g_server != nullptr) {
    return;
  }
  g_server = new Server();
  g_server->RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                           IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  EXPECT_EQ(g_server->Start(0), 0);
  g_port = g_server->port();
}

std::string addr() { return "127.0.0.1:" + std::to_string(g_port); }

void set_capture(bool on) {
  capture::ensure_registered();
  EXPECT_EQ(Flag::set("trpc_capture", on ? "true" : "false"), 0);
}

capture::Sample make_sample(uint64_t i, const std::string& tenant) {
  capture::Sample s;
  s.arrival_mono_us = static_cast<int64_t>(1000000 + i);
  s.arrival_wall_us = static_cast<int64_t>(1754000000000000ull + i);
  s.trace_id = i + 1;  // identity marker for determinism checks
  s.parent_span_id = i * 3;
  s.request_bytes = 1024 + i;
  s.response_bytes = 2048 + i;
  s.status = i % 7 == 0 ? 2005 : 0;
  s.queue_us = static_cast<uint32_t>(i % 50);
  s.handler_us = static_cast<uint32_t>(100 + i % 900);
  s.deadline_budget_us = static_cast<uint32_t>(i % 2 == 0 ? 250000 : 0);
  s.priority = static_cast<uint8_t>(i % 3);
  s.method = "Echo.Echo";
  s.tenant = tenant;
  return s;
}

std::set<uint64_t> kept_trace_ids() {
  Json root;
  EXPECT(Json::parse(capture::dump_json(1 << 17), &root));
  const Json* recs = root.find("records");
  EXPECT(recs != nullptr);
  std::set<uint64_t> out;
  for (size_t i = 0; i < recs->size(); ++i) {
    out.insert(strtoull((*recs)[i].find("trace_id")->as_string().c_str(),
                        nullptr, 16));
  }
  return out;
}

}  // namespace

TEST_CASE(capture_flag_off_invisible) {
  // MUST run first (registration order): proves the default-off
  // recorder retains nothing — vars frozen at 0 — while real traffic
  // flows.
  capture::ensure_registered();
  EXPECT(!capture::enabled());
  start_once();
  Channel ch;
  Channel::Options opts;
  opts.timeout_ms = 30000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  for (int i = 0; i < 32; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("ping");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  EXPECT_EQ(capture::seen_total(), 0u);
  EXPECT_EQ(capture::sampled_total(), 0u);
  EXPECT_EQ(capture::dropped_total(), 0u);
  EXPECT_EQ(capture::records_held(), 0u);
  std::string v;
  EXPECT(Variable::read_exposed("capture_seen_total", &v));
  EXPECT(v == "0");
  EXPECT(Variable::read_exposed("capture_dropped_total", &v));
  EXPECT(v == "0");
  // record() offered while off is a no-op, not a crash.
  capture::record(make_sample(0, "t"));
  EXPECT_EQ(capture::records_held(), 0u);
}

TEST_CASE(capture_record_serialize_roundtrip) {
  // The binary record layout must carry every tail-group-derived field
  // (tenant/priority from group 5, deadline budget from group 7, trace
  // ids) bit-exactly through serialize -> parse.
  capture::Sample in = make_sample(41, "tenant-α");
  in.method = "Model.Forward";
  IOBuf buf;
  capture::serialize_record(in, &buf);
  capture::Sample out;
  EXPECT(capture::parse_record(buf, &out));
  EXPECT_EQ(out.arrival_mono_us, in.arrival_mono_us);
  EXPECT_EQ(out.arrival_wall_us, in.arrival_wall_us);
  EXPECT_EQ(out.trace_id, in.trace_id);
  EXPECT_EQ(out.parent_span_id, in.parent_span_id);
  EXPECT_EQ(out.request_bytes, in.request_bytes);
  EXPECT_EQ(out.response_bytes, in.response_bytes);
  EXPECT_EQ(out.status, in.status);
  EXPECT_EQ(out.queue_us, in.queue_us);
  EXPECT_EQ(out.handler_us, in.handler_us);
  EXPECT_EQ(out.deadline_budget_us, in.deadline_budget_us);
  EXPECT_EQ(out.priority, in.priority);
  EXPECT(out.method == in.method);
  EXPECT(out.tenant == in.tenant);
  // Truncated payloads are rejected, not mis-parsed.
  IOBuf trunc;
  std::string flat = buf.to_string();
  trunc.append(flat.data(), flat.size() - 3);
  capture::Sample bad;
  EXPECT(!capture::parse_record(trunc, &bad));
}

TEST_CASE(capture_sampling_determinism) {
  // Same seed + same stream => the SAME kept set, twice.  The admission
  // hash and the reservoir eviction slots both key off the per-window
  // decision index, so a seeded stream is exactly reproducible.
  EXPECT_EQ(Flag::set("trpc_capture_max_records", "256"), 0);
  EXPECT_EQ(Flag::set("trpc_capture_sample_permille", "500"), 0);
  EXPECT_EQ(Flag::set("trpc_capture_seed", "42"), 0);
  set_capture(true);
  capture::reset();
  for (uint64_t i = 0; i < 2000; ++i) {
    capture::record(make_sample(i, "det"));
  }
  const std::set<uint64_t> first = kept_trace_ids();
  EXPECT(first.size() > 0);
  EXPECT(first.size() <= 256);
  capture::reset();
  for (uint64_t i = 0; i < 2000; ++i) {
    capture::record(make_sample(i, "det"));
  }
  const std::set<uint64_t> second = kept_trace_ids();
  EXPECT(first == second);
  // A different seed keeps a different set (sanity that the seed is
  // actually in the hash, not a constant).
  EXPECT_EQ(Flag::set("trpc_capture_seed", "43"), 0);
  capture::reset();
  for (uint64_t i = 0; i < 2000; ++i) {
    capture::record(make_sample(i, "det"));
  }
  EXPECT(kept_trace_ids() != first);
  set_capture(false);
  capture::reset();
  EXPECT_EQ(Flag::set("trpc_capture_sample_permille", "1000"), 0);
  EXPECT_EQ(Flag::set("trpc_capture_seed", "1"), 0);
}

TEST_CASE(capture_stratified_quota_and_drop_accounting) {
  // 3 tenants with a 100:10:1 traffic skew into a 256-slot reservoir:
  // stratification must hold every tenant near capacity/3 (the minority
  // tenant keeps EVERYTHING it sent), and the drop accounting must be
  // exact — kept == sampled - dropped, never silent thinning.
  EXPECT_EQ(Flag::set("trpc_capture_max_records", "256"), 0);
  set_capture(true);
  capture::reset();
  const uint64_t before_sampled = capture::sampled_total();
  const uint64_t before_dropped = capture::dropped_total();
  uint64_t id = 0;
  for (int round = 0; round < 3000; ++round) {
    capture::record(make_sample(id++, "heavy"));
    if (round % 10 == 0) {
      capture::record(make_sample(id++, "mid"));
    }
    if (round % 100 == 0) {
      capture::record(make_sample(id++, "rare"));
    }
  }
  Json root;
  EXPECT(Json::parse(capture::dump_json(0), &root));
  const Json* tenants = root.find("summary")->find("tenants");
  EXPECT(tenants != nullptr);
  const size_t heavy = static_cast<size_t>(
      tenants->find("heavy")->find("kept")->as_number());
  const size_t mid = static_cast<size_t>(
      tenants->find("mid")->find("kept")->as_number());
  const size_t rare = static_cast<size_t>(
      tenants->find("rare")->find("kept")->as_number());
  // Quota = 256/3 = 85.  heavy and mid both saturate it; rare sent only
  // 30 and keeps every one (stratification = minority tenants are never
  // crowded out by the heavy hitter).
  EXPECT(heavy <= 86);
  EXPECT(heavy >= 80);
  EXPECT(mid <= 86);
  EXPECT(mid >= 80);
  EXPECT_EQ(rare, 30u);
  const uint64_t sampled = capture::sampled_total() - before_sampled;
  const uint64_t dropped = capture::dropped_total() - before_dropped;
  EXPECT_EQ(capture::records_held(), heavy + mid + rare);
  // Exact coverage accounting: every sampled record is either held or
  // counted dropped.
  EXPECT_EQ(sampled - dropped, static_cast<uint64_t>(heavy + mid + rare));
  EXPECT(dropped > 0);
  set_capture(false);
  capture::reset();
}

TEST_CASE(capture_bounded_memory_under_64mb_bodies) {
  // A record of a 64MB request must cost ~100 bytes of reservoir
  // memory: sizes are kept as integers, strings clamp to 64 bytes.
  EXPECT_EQ(Flag::set("trpc_capture_max_records", "1024"), 0);
  set_capture(true);
  capture::reset();
  for (uint64_t i = 0; i < 1024; ++i) {
    capture::Sample s = make_sample(i, std::string(300, 't'));
    s.method = std::string(300, 'm');
    s.request_bytes = 64ull << 20;
    s.response_bytes = 64ull << 20;
    capture::record(std::move(s));
  }
  EXPECT_EQ(capture::records_held(), 1024u);
  // 1024 records of 64MB traffic: the reservoir must stay under 1MB.
  EXPECT(capture::approx_bytes() < (1u << 20));
  Json root;
  EXPECT(Json::parse(capture::dump_json(1), &root));
  const Json* recs = root.find("records");
  EXPECT_EQ(recs->size(), 1u);
  EXPECT_EQ((*recs)[0].find("tenant")->as_string().size(), 64u);
  EXPECT_EQ((*recs)[0].find("method")->as_string().size(), 64u);
  set_capture(false);
  capture::reset();
  EXPECT_EQ(Flag::set("trpc_capture_max_records", "65536"), 0);
}

TEST_CASE(capture_file_roundtrip_via_recordio) {
  // dump_file -> RecordReader + parse_record must reproduce the
  // reservoir exactly, with the JSON header carrying the window
  // counters and the per-tenant baseline.
  set_capture(true);
  capture::reset();
  for (uint64_t i = 0; i < 100; ++i) {
    capture::record(make_sample(i, i % 2 == 0 ? "a" : "b"));
  }
  char path[] = "/tmp/trpc_capture_test_XXXXXX";
  const int fd = mkstemp(path);
  EXPECT(fd >= 0);
  close(fd);
  EXPECT_EQ(capture::dump_file(path), 100);
  RecordReader reader(path);
  EXPECT(reader.valid());
  IOBuf head;
  EXPECT(reader.read(&head));
  const std::string hs = head.to_string();
  EXPECT(hs.size() > 8);
  EXPECT_EQ(hs.compare(0, 8, capture::kFileMagic, 8), 0);
  Json header;
  EXPECT(Json::parse(hs.substr(8), &header));
  EXPECT_EQ(header.find("counters")->find("window_sampled")->as_number(),
            100.0);
  EXPECT(header.find("summary")->find("tenants")->find("a") != nullptr);
  size_t n = 0;
  int64_t prev_arrival = 0;
  IOBuf rec;
  while (reader.read(&rec)) {
    capture::Sample s;
    EXPECT(capture::parse_record(rec, &s));
    EXPECT(s.arrival_mono_us >= prev_arrival);  // arrival order
    prev_arrival = s.arrival_mono_us;
    EXPECT(s.tenant == "a" || s.tenant == "b");
    EXPECT(s.method == "Echo.Echo");
    rec.clear();
    n++;
  }
  EXPECT_EQ(n, 100u);
  std::remove(path);
  set_capture(false);
  capture::reset();
}

TEST_CASE(capture_e2e_live_server_with_qos_and_deadline) {
  // Live traffic: QoS-tagged + deadline-stamped calls over a real
  // connection must land in the reservoir with tenant, priority,
  // budget, sizes and latency filled by the server-side hook.
  start_once();
  set_capture(true);
  capture::reset();
  Channel ch;
  Channel::Options opts;
  opts.timeout_ms = 30000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  for (int i = 0; i < 40; ++i) {
    Controller cntl;
    cntl.set_qos("fg", 1);
    cntl.set_timeout_ms(5000);  // stamps tail-group 7
    IOBuf req, resp;
    req.append(std::string(1024, 'x'));
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  EXPECT(capture::seen_total() >= 40);
  EXPECT(capture::records_held() >= 40);
  Json root;
  EXPECT(Json::parse(capture::dump_json(1 << 12), &root));
  const Json* tenants = root.find("summary")->find("tenants");
  const Json* fg = tenants->find("fg");
  EXPECT(fg != nullptr);
  EXPECT(fg->find("kept")->as_number() >= 40);
  EXPECT(fg->find("p99_us")->as_number() > 0);
  const Json* recs = root.find("records");
  bool saw_budget = false;
  for (size_t i = 0; i < recs->size(); ++i) {
    const Json& r = (*recs)[i];
    if (r.find("tenant")->as_string() != "fg") {
      continue;
    }
    EXPECT(r.find("method")->as_string() == "Echo.Echo");
    EXPECT_EQ(r.find("priority")->as_number(), 1.0);
    EXPECT_EQ(r.find("request_bytes")->as_number(), 1024.0);
    EXPECT_EQ(r.find("response_bytes")->as_number(), 1024.0);
    saw_budget |= r.find("deadline_budget_us")->as_number() > 0;
  }
  EXPECT(saw_budget);  // tail-group 7 budget made it into the records
  set_capture(false);
  capture::reset();
}

TEST_MAIN
