// Chaos soak for the retry / hedging / quarantine stack under the
// deterministic fault-injection subsystem (net/fault.h).
//
// What is being proven (ISSUE 1 acceptance):
//   (a) under drop/delay/corrupt/trunc/reset schedules every client call
//       either succeeds with EXACT payload or fails with a clean error —
//       no hangs, no accepted-but-corrupted responses (checksummed);
//   (b) quarantine isolates a faulty node and health-check probes restore
//       it once faults clear;
//   (c) a given seed replays the identical fault sequence.
#include <unistd.h>

#include <set>
#include <string>
#include <thread>
#include <vector>

#include "base/flags.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/cluster.h"
#include "net/controller.h"
#include "net/fault.h"
#include "net/server.h"
#include "net/transport.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

// Clears the global schedule on every exit path so one test's chaos can
// never leak into the next.
struct FaultGuard {
  ~FaultGuard() { FaultActor::global().set(""); }
};

struct Node {
  Server server;
  int port = 0;
};

Node g_nodes[3];
bool g_started = false;

void start_nodes() {
  if (g_started) {
    return;
  }
  g_started = true;
  for (int i = 0; i < 3; ++i) {
    g_nodes[i].server.RegisterMethod(
        "Echo.Echo", [](Controller*, const IOBuf& req, IOBuf* resp,
                        Closure done) {
          resp->append(req);
          done();
        });
    g_nodes[i].server.RegisterMethod(
        "Echo.WhoAmI",
        [i](Controller*, const IOBuf&, IOBuf* resp, Closure done) {
          resp->append("node-" + std::to_string(i));
          done();
        });
    EXPECT_EQ(g_nodes[i].server.Start(0), 0);
    g_nodes[i].port = g_nodes[i].server.port();
  }
}

std::string node_addr(int i) {
  return "127.0.0.1:" + std::to_string(g_nodes[i].port);
}

std::string list_url() {
  start_nodes();
  return "list://" + node_addr(0) + "," + node_addr(1) + "," + node_addr(2);
}

}  // namespace

// ---- schedule grammar ----------------------------------------------------

TEST_CASE(schedule_parse_roundtrip) {
  FaultSchedule s;
  EXPECT(FaultSchedule::parse(
      "seed=42;peer=127.0.0.1:8002;after=10;max=5;drop=0.25;"
      "delay=0.1:50;svr_error=0.5:1234", &s));
  EXPECT_EQ(s.seed, 42u);
  EXPECT(s.has_peer);
  EXPECT_EQ(s.peer.port, 8002);
  EXPECT_EQ(s.after, 10u);
  EXPECT_EQ(s.max_faults, 5u);
  EXPECT(s.drop == 0.25);
  EXPECT(s.delay == 0.1);
  EXPECT_EQ(s.delay_ms, 50);
  EXPECT(s.svr_error == 0.5);
  EXPECT_EQ(s.svr_error_code, 1234);
  // Canonical rendering re-parses to the same schedule.
  FaultSchedule s2;
  EXPECT(FaultSchedule::parse(s.to_string(), &s2));
  EXPECT_EQ(s2.seed, s.seed);
  EXPECT(s2.drop == s.drop);
  EXPECT_EQ(s2.delay_ms, s.delay_ms);
  // Whitespace + comma separators are accepted.
  EXPECT(FaultSchedule::parse("seed=1, drop=0.5", &s));
  // Rejections: unknown key, bad probability, missing/forbidden extras.
  EXPECT(!FaultSchedule::parse("dorp=0.5", &s));
  EXPECT(!FaultSchedule::parse("drop=1.5", &s));
  EXPECT(!FaultSchedule::parse("drop=nan", &s));
  EXPECT(!FaultSchedule::parse("drop=inf", &s));
  EXPECT(!FaultSchedule::parse("drop=0.5:10", &s));
  EXPECT(!FaultSchedule::parse("delay=0.5", &s));
  EXPECT(!FaultSchedule::parse("svr_error=0.5:0", &s));
  EXPECT(!FaultSchedule::parse("drop", &s));
  EXPECT(!FaultSchedule::parse("peer=notanaddr", &s));
}

TEST_CASE(decision_stream_is_seed_deterministic) {
  // (c) at the engine level: the (index → verdict) mapping is a pure
  // function of the schedule, independent of actor instance.
  const char* spec = "seed=7;drop=0.3;corrupt=0.2;reset=0.1";
  EndPoint ep;
  EXPECT_EQ(hostname2endpoint("127.0.0.1:9999", &ep), 0);
  FaultActor a, b;
  EXPECT_EQ(a.set(spec), 0);
  EXPECT_EQ(b.set(spec), 0);
  std::vector<FaultKind> seq_a, seq_b;
  for (int i = 0; i < 500; ++i) {
    seq_a.push_back(a.decide(FaultPoint::kTx, ep).kind);
    seq_b.push_back(b.decide(FaultPoint::kTx, ep).kind);
  }
  EXPECT(seq_a == seq_b);
  EXPECT(a.injected() > 0);           // the dice actually fired
  EXPECT(a.injected() < 500);         // ... and pass sometimes too
  EXPECT(a.log_text() == b.log_text());
  // reset_counters restarts the identical sequence.
  const std::string log1 = a.log_text();
  a.reset_counters();
  EXPECT_EQ(a.injected(), 0u);
  for (int i = 0; i < 500; ++i) {
    a.decide(FaultPoint::kTx, ep);
  }
  EXPECT(a.log_text() == log1);
  // A different seed gives a different stream.
  FaultActor c;
  EXPECT_EQ(c.set("seed=8;drop=0.3;corrupt=0.2;reset=0.1"), 0);
  std::vector<FaultKind> seq_c;
  for (int i = 0; i < 500; ++i) {
    seq_c.push_back(c.decide(FaultPoint::kTx, ep).kind);
  }
  EXPECT(seq_a != seq_c);
}

TEST_CASE(after_and_max_bound_the_faults) {
  EndPoint ep;
  EXPECT_EQ(hostname2endpoint("127.0.0.1:9999", &ep), 0);
  FaultActor a;
  EXPECT_EQ(a.set("seed=3;drop=1;after=10;max=4"), 0);
  int faulted = 0;
  for (int i = 0; i < 100; ++i) {
    const FaultDecision d = a.decide(FaultPoint::kTx, ep);
    if (d.kind != FaultKind::kNone) {
      EXPECT(d.index >= 10);  // warmup passed through
      ++faulted;
    }
  }
  EXPECT_EQ(faulted, 4);  // capped by max
  // The cap is a HARD bound under concurrency too (slot reservation, not
  // check-then-inject): hammer a fresh actor from 8 threads.
  FaultActor hammered;
  EXPECT_EQ(hammered.set("seed=3;drop=1;max=7"), 0);
  {
    std::vector<std::thread> threads;
    for (int t = 0; t < 8; ++t) {
      threads.emplace_back([&hammered, &ep] {
        for (int i = 0; i < 200; ++i) {
          hammered.decide(FaultPoint::kTx, ep);
        }
      });
    }
    for (auto& th : threads) {
      th.join();
    }
  }
  EXPECT_EQ(hammered.injected(), 7u);
  // Peer filter: a non-matching remote never draws (or counts).
  FaultActor b;
  EXPECT_EQ(b.set("seed=3;drop=1;peer=127.0.0.1:1"), 0);
  for (int i = 0; i < 50; ++i) {
    EXPECT(b.decide(FaultPoint::kTx, ep).kind == FaultKind::kNone);
  }
  EXPECT_EQ(b.decisions(), 0u);
  // Bad spec keeps the previous schedule.
  EXPECT_EQ(b.set("drop=oops"), -1);
  EXPECT(b.active());
}

TEST_CASE(mis_scoped_schedules_rejected_loudly) {
  // A parseable spec whose kinds can never fire on the target actor must
  // be rejected, not installed as a silent no-op (the same contract as
  // typo rejection).
  start_nodes();
  EXPECT_EQ(FaultActor::global().set("seed=1;svr_delay=1:50"), -1);
  EXPECT(!FaultActor::global().active());
  EXPECT(!FaultActor::global().parse_ok("svr_error=1:13"));
  EXPECT_EQ(g_nodes[0].server.SetFaults("seed=1;drop=0.5"), -1);
  EXPECT(!g_nodes[0].server.faults().active());
  EXPECT(!g_nodes[0].server.faults().parse_ok("reset=1"));
  // Correctly-scoped specs still land on either side.
  EXPECT_EQ(FaultActor::global().set("seed=1;drop=0.5;max=1"), 0);
  EXPECT_EQ(g_nodes[0].server.SetFaults("seed=1;svr_reject=0.5"), 0);
  EXPECT_EQ(FaultActor::global().set(""), 0);
  EXPECT_EQ(g_nodes[0].server.SetFaults(""), 0);
  // An unscoped actor (unit-test harness form) accepts both families.
  FaultActor any;
  EXPECT_EQ(any.set("drop=0.5;svr_reject=0.5"), 0);
}

TEST_CASE(fault_transport_wraps_and_forwards_identity) {
  Transport* tcp = tcp_transport();
  Transport* wrapped = fault_wrap(tcp);
  EXPECT(wrapped != tcp);
  EXPECT_EQ(fault_wrap(tcp), wrapped);        // cached
  EXPECT_EQ(fault_wrap(wrapped), wrapped);    // idempotent
  EXPECT_EQ(fault_unwrap(wrapped), tcp);
  EXPECT(std::string(wrapped->name()) == "tcp");
  EXPECT_EQ(wrapped->fd_based(), tcp->fd_based());
}

// ---- fault behaviors through the live stack ------------------------------

namespace {

// One checksummed echo call; returns 0 on success (payload verified
// EXACT) or the clean error code.  Any hang is caught by the timeout;
// any accepted-but-wrong payload fails the test immediately.
int checked_echo(Channel& ch, const std::string& payload,
                 int64_t timeout_ms = 400) {
  Controller cntl;
  cntl.set_timeout_ms(timeout_ms);
  cntl.set_enable_checksum(true);
  IOBuf req, resp;
  req.append(payload);
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  if (cntl.Failed()) {
    EXPECT(cntl.error_code() != 0);  // clean: a real code, not silence
    return cntl.error_code();
  }
  EXPECT_EQ(resp.size(), payload.size());
  EXPECT(resp.to_string() == payload);
  return 0;
}

}  // namespace

TEST_CASE(tx_reset_fails_cleanly) {
  start_nodes();
  FaultGuard guard;
  Channel ch;
  EXPECT_EQ(ch.Init(node_addr(0)), 0);
  EXPECT_EQ(checked_echo(ch, "warm"), 0);  // connection up
  EXPECT_EQ(FaultActor::global().set("seed=1;reset=1;peer=" + node_addr(0)),
            0);
  const int rc = checked_echo(ch, "doomed");
  EXPECT(rc != 0);
  EXPECT(FaultActor::global().injected() > 0);
  // Clearing the schedule heals the channel (fresh socket, clean call).
  EXPECT_EQ(FaultActor::global().set(""), 0);
  EXPECT_EQ(checked_echo(ch, "healed"), 0);
}

TEST_CASE(connect_refused_fails_cleanly) {
  start_nodes();
  FaultGuard guard;
  EXPECT_EQ(
      FaultActor::global().set("seed=1;refuse=1;peer=" + node_addr(0)), 0);
  Channel ch;
  EXPECT_EQ(ch.Init(node_addr(0)), 0);
  EXPECT(checked_echo(ch, "nope") != 0);
}

TEST_CASE(tx_drop_times_out_not_hangs) {
  start_nodes();
  FaultGuard guard;
  Channel ch;
  EXPECT_EQ(ch.Init(node_addr(1)), 0);
  EXPECT_EQ(checked_echo(ch, "warm"), 0);
  EXPECT_EQ(FaultActor::global().set("seed=1;drop=1;peer=" + node_addr(1)),
            0);
  const int64_t t0 = monotonic_time_us();
  const int rc = checked_echo(ch, "into-the-void", 250);
  const int64_t dt_ms = (monotonic_time_us() - t0) / 1000;
  EXPECT_EQ(rc, ETIMEDOUT);
  EXPECT(dt_ms >= 200 && dt_ms < 5000);  // timed out, did not hang
}

TEST_CASE(corruption_never_yields_wrong_payload) {
  // corrupt=1 scrambles EVERY moved chunk both ways; with checksums on,
  // every call must fail (or — impossible here — succeed exactly).
  start_nodes();
  FaultGuard guard;
  EXPECT_EQ(
      FaultActor::global().set("seed=5;corrupt=1;peer=" + node_addr(2)), 0);
  Channel ch;
  EXPECT_EQ(ch.Init(node_addr(2)), 0);
  int failures = 0;
  for (int i = 0; i < 5; ++i) {
    if (checked_echo(ch, "payload-" + std::to_string(i), 300) != 0) {
      ++failures;
    }
  }
  // checked_echo already fails the test on any accepted-but-wrong
  // payload; a flip can also land in an INERT meta byte (the trace /
  // deadline tail groups, ISSUE 15) and leave a call byte-exact — so
  // assert "almost always fails, never lies", not an exact count.
  EXPECT(failures >= 4);
  EXPECT(FaultActor::global().injected() > 0);
}

TEST_CASE(server_fault_points) {
  start_nodes();
  // Forced error code: a CLEAN well-formed error response.
  EXPECT_EQ(g_nodes[0].server.SetFaults("seed=1;svr_error=1:1234"), 0);
  {
    Channel ch;
    EXPECT_EQ(ch.Init(node_addr(0)), 0);
    Controller cntl;
    cntl.set_timeout_ms(500);
    IOBuf req, resp;
    req.append("x");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(cntl.Failed());
    EXPECT_EQ(cntl.error_code(), 1234);
  }
  // Reject-at-accept: fresh connections die; the client sees a clean
  // error, not a hang.
  EXPECT_EQ(g_nodes[0].server.SetFaults("seed=1;svr_reject=1"), 0);
  {
    Channel ch;
    EXPECT_EQ(ch.Init(node_addr(0)), 0);
    EXPECT(checked_echo(ch, "rejected", 300) != 0);
  }
  // Delayed dispatch: the call takes at least the injected delay.
  EXPECT_EQ(g_nodes[0].server.SetFaults("seed=1;svr_delay=1:120"), 0);
  {
    Channel ch;
    EXPECT_EQ(ch.Init(node_addr(0)), 0);
    const int64_t t0 = monotonic_time_us();
    EXPECT_EQ(checked_echo(ch, "slow", 1000), 0);
    EXPECT((monotonic_time_us() - t0) / 1000 >= 100);
  }
  EXPECT_EQ(g_nodes[0].server.SetFaults(""), 0);
  EXPECT(!g_nodes[0].server.faults().active());
  {
    Channel ch;
    EXPECT_EQ(ch.Init(node_addr(0)), 0);
    EXPECT_EQ(checked_echo(ch, "post-clear"), 0);
  }
}

TEST_CASE(hedging_beats_delayed_node) {
  // Satellite: backup_request_ms racing a second node while the primary
  // is stuck behind an injected server-side delay.  With ALL nodes
  // delayed except the backup candidates, whichever primary the LB picks
  // the hedge must win well before the 400ms injected delay.
  start_nodes();
  EXPECT_EQ(g_nodes[0].server.SetFaults("seed=1;svr_delay=1:400"), 0);
  ClusterChannel ch;
  ClusterChannel::Options opts;
  opts.timeout_ms = 2000;
  opts.backup_request_ms = 60;
  EXPECT_EQ(ch.Init(list_url(), "rr", &opts), 0);
  int fast = 0;
  for (int i = 0; i < 6; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("x");
    const int64_t t0 = monotonic_time_us();
    ch.CallMethod("Echo.WhoAmI", req, &resp, &cntl);
    const int64_t dt_ms = (monotonic_time_us() - t0) / 1000;
    EXPECT(!cntl.Failed());
    if (dt_ms < 350) {
      ++fast;
      EXPECT(resp.to_string() != "node-0");  // the delayed node lost
    }
  }
  // rr lands on node-0 in 2 of every 3 calls; hedges must have rescued
  // them (without hedging those calls take the full 400ms delay).
  EXPECT(fast >= 4);
  EXPECT_EQ(g_nodes[0].server.SetFaults(""), 0);
}

TEST_CASE(fault_transport_composes_with_shm_ring) {
  // Acceptance: the decorator wraps fd-less transports too.  Establish a
  // same-host ring channel, then fail its (wrapped) ring transport — the
  // call dies cleanly and the channel re-handshakes once faults clear.
  start_nodes();
  FaultGuard guard;
  Channel ch;
  Channel::Options copts;
  copts.use_shm = true;
  EXPECT_EQ(ch.Init(node_addr(0), &copts), 0);
  EXPECT_EQ(checked_echo(ch, "over-rings"), 0);
  EXPECT(ch.transport_name() == "shm_ring");  // identity forwards through
  EXPECT_EQ(FaultActor::global().set("seed=4;reset=1"), 0);
  EXPECT(checked_echo(ch, "doomed") != 0);
  EXPECT(FaultActor::global().injected() > 0);
  EXPECT_EQ(FaultActor::global().set(""), 0);
  EXPECT_EQ(checked_echo(ch, "healed"), 0);
  EXPECT(ch.transport_name() == "shm_ring");  // fresh rings, not tcp
}

// ---- the soak ------------------------------------------------------------

TEST_CASE(chaos_soak_escalating_schedules) {
  start_nodes();
  FaultGuard guard;
  ClusterChannel ch;
  ClusterChannel::Options opts;
  opts.timeout_ms = 250;
  opts.max_retry = 2;
  opts.quarantine_base_ms = 50;
  opts.quarantine_max_ms = 400;
  opts.health_check_method = "Echo.Echo";
  opts.refresh_interval_ms = 100;
  EXPECT_EQ(ch.Init(list_url(), "rr", &opts), 0);
  // Escalating phases, installed through the FLAG path (the same seam
  // /flags and /faults use).  Every call must complete (success or clean
  // error) and every success must carry the exact payload.
  const char* phases[] = {
      "seed=11;drop=0.15;delay=0.2:30",
      "seed=12;corrupt=0.2;trunc=0.1;partial=0.3",
      "seed=13;reset=0.2;refuse=0.2;drop=0.1",
  };
  for (const char* phase : phases) {
    EXPECT_EQ(Flag::set("fault_schedule", phase), 0);
    int ok = 0, clean_fail = 0;
    for (int i = 0; i < 25; ++i) {
      const std::string payload =
          "soak-" + std::to_string(i) + std::string(64, 'x');
      Controller cntl;
      cntl.set_enable_checksum(true);
      IOBuf req, resp;
      req.append(payload);
      const int64_t t0 = monotonic_time_us();
      ch.CallMethod("Echo.Echo", req, &resp, &cntl);
      const int64_t dt_ms = (monotonic_time_us() - t0) / 1000;
      EXPECT(dt_ms < 5000);  // bounded: never hangs
      if (cntl.Failed()) {
        EXPECT(cntl.error_code() != 0);
        ++clean_fail;
      } else {
        EXPECT(resp.to_string() == payload);  // exact, never corrupted
        ++ok;
      }
    }
    // Retry + multiple nodes must rescue a healthy majority of calls.
    EXPECT(ok > 0);
    (void)clean_fail;
  }
  EXPECT_EQ(Flag::set("fault_schedule", ""), 0);
  // Post-chaos: the cluster heals completely.
  for (int i = 0; i < 6; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("healed");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    EXPECT(resp.to_string() == "healed");
  }
}

TEST_CASE(quarantine_isolates_then_probes_revive) {
  start_nodes();
  FaultGuard guard;
  ClusterChannel ch;
  ClusterChannel::Options opts;
  opts.timeout_ms = 250;
  opts.max_retry = 2;
  // Quarantine windows far beyond the test horizon: ONLY health-check
  // probes can revive the node (expiry cannot), which is exactly the
  // behavior under test.
  opts.quarantine_base_ms = 60000;
  opts.quarantine_max_ms = 60000;
  opts.health_check_method = "Echo.WhoAmI";
  opts.health_check_timeout_ms = 150;
  opts.refresh_interval_ms = 100;
  EXPECT_EQ(ch.Init(list_url(), "rr", &opts), 0);
  // Fault ONLY node 1: every byte toward it dies with a reset.
  EXPECT_EQ(
      FaultActor::global().set("seed=2;reset=1;peer=" + node_addr(1)), 0);
  // Drive calls until the breaker isolates node 1.  Calls themselves
  // must keep succeeding (retry routes around the faulty node).
  int64_t deadline = monotonic_time_us() + 10 * 1000 * 1000;
  while (ch.healthy_count() != 2 && monotonic_time_us() < deadline) {
    Controller cntl;
    cntl.set_enable_checksum(true);
    IOBuf req, resp;
    req.append("x");
    ch.CallMethod("Echo.WhoAmI", req, &resp, &cntl);
    EXPECT(!cntl.Failed());  // retries rescue every call
  }
  EXPECT_EQ(ch.healthy_count(), 2u);
  // While quarantined, traffic spreads over the two healthy nodes only.
  for (int i = 0; i < 8; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("x");
    ch.CallMethod("Echo.WhoAmI", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    EXPECT(resp.to_string() != "node-1");
  }
  // Faults clear → the next probe tick revives node 1 (windows cannot
  // expire within the test, so a revival PROVES the probe path).
  EXPECT_EQ(FaultActor::global().set(""), 0);
  deadline = monotonic_time_us() + 10 * 1000 * 1000;
  while (ch.healthy_count() != 3 && monotonic_time_us() < deadline) {
    usleep(20 * 1000);
  }
  EXPECT_EQ(ch.healthy_count(), 3u);
  // ... and node 1 actually serves again.
  std::set<std::string> seen;
  for (int i = 0; i < 9; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("x");
    ch.CallMethod("Echo.WhoAmI", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    seen.insert(resp.to_string());
  }
  EXPECT(seen.count("node-1") == 1);
}

TEST_CASE(seed_replay_end_to_end) {
  // (c) through the live stack: one client, one node, sequential
  // checksummed calls — the injected-fault log replays byte-identical
  // for the same seed.
  start_nodes();
  FaultGuard guard;
  // drop-only: a dropped frame never perturbs the connection, so the
  // per-call decision sequence (connect, tx, rx-per-response) is exactly
  // reproducible; kinds that kill sockets reconnect at racy times.
  const std::string spec = "seed=21;drop=0.25;peer=" + node_addr(2);
  std::string logs[2];
  int outcomes[2][12];
  for (int run = 0; run < 2; ++run) {
    EXPECT_EQ(FaultActor::global().set(spec), 0);  // set resets counters
    Channel ch;
    EXPECT_EQ(ch.Init(node_addr(2)), 0);
    for (int i = 0; i < 12; ++i) {
      outcomes[run][i] = checked_echo(ch, "replay-" + std::to_string(i),
                                      200);
    }
    logs[run] = FaultActor::global().log_text();
  }
  EXPECT(!logs[0].empty());
  EXPECT(logs[0] == logs[1]);
  for (int i = 0; i < 12; ++i) {
    EXPECT_EQ(outcomes[0][i], outcomes[1][i]);
  }
}

TEST_MAIN
