// Cluster client tests: naming, LB spread, retry + circuit-breaker routing
// around dead nodes (the reference tests LB/health with N in-process
// servers, SURVEY.md §4).
#include <unistd.h>

#include <atomic>
#include <fstream>
#include <memory>
#include <set>
#include <string>
#include <vector>

#include "base/time.h"
#include "fiber/fiber.h"
#include "fiber/sync.h"
#include "net/cluster.h"
#include "net/lb_hint.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

struct Node {
  Server server;
  int port = 0;
};

Node g_nodes[3];
bool g_started = false;

void start_nodes() {
  if (g_started) {
    return;
  }
  g_started = true;
  for (int i = 0; i < 3; ++i) {
    g_nodes[i].server.RegisterMethod(
        "Echo.WhoAmI",
        [i](Controller*, const IOBuf&, IOBuf* resp, Closure done) {
          resp->append("node-" + std::to_string(i));
          done();
        });
    EXPECT_EQ(g_nodes[i].server.Start(0), 0);
    g_nodes[i].port = g_nodes[i].server.port();
  }
}

std::string list_url() {
  start_nodes();
  std::string url = "list://";
  for (int i = 0; i < 3; ++i) {
    url += "127.0.0.1:" + std::to_string(g_nodes[i].port);
    if (i < 2) {
      url += ",";
    }
  }
  return url;
}

std::string call_once(ClusterChannel& ch, uint64_t key = 0) {
  Controller cntl;
  IOBuf req, resp;
  req.append("x");
  ch.CallMethod("Echo.WhoAmI", req, &resp, &cntl, nullptr, key);
  return cntl.Failed() ? "FAILED:" + std::to_string(cntl.error_code())
                       : resp.to_string();
}

}  // namespace

TEST_CASE(round_robin_spreads) {
  ClusterChannel ch;
  EXPECT_EQ(ch.Init(list_url(), "rr"), 0);
  std::set<std::string> seen;
  for (int i = 0; i < 9; ++i) {
    seen.insert(call_once(ch));
  }
  EXPECT_EQ(seen.size(), 3u);  // all nodes hit
}

TEST_CASE(consistent_hash_stable) {
  ClusterChannel ch;
  EXPECT_EQ(ch.Init(list_url(), "c_hash"), 0);
  const std::string first = call_once(ch, 12345);
  EXPECT(first.rfind("node-", 0) == 0);
  for (int i = 0; i < 10; ++i) {
    EXPECT(call_once(ch, 12345) == first);  // same key → same node
  }
  std::set<std::string> spread;
  for (uint64_t k = 0; k < 40; ++k) {
    spread.insert(call_once(ch, k * 7919));
  }
  EXPECT(spread.size() >= 2);  // different keys spread
}

TEST_CASE(random_lb_works) {
  ClusterChannel ch;
  EXPECT_EQ(ch.Init(list_url(), "random"), 0);
  for (int i = 0; i < 10; ++i) {
    EXPECT(call_once(ch).rfind("node-", 0) == 0);
  }
}

TEST_CASE(retry_routes_around_dead_node) {
  start_nodes();
  // Cluster includes a dead port; rr will hit it, retry must recover.
  std::string url = list_url() + ",127.0.0.1:1";
  ClusterChannel::Options opts;
  opts.timeout_ms = 300;
  opts.max_retry = 2;
  ClusterChannel ch;
  EXPECT_EQ(ch.Init(url, "rr", &opts), 0);
  int ok = 0;
  for (int i = 0; i < 12; ++i) {
    if (call_once(ch).rfind("node-", 0) == 0) {
      ++ok;
    }
  }
  EXPECT_EQ(ok, 12);  // every call succeeded despite the dead node
  // Breaker quarantined the dead node.
  EXPECT(ch.healthy_count() <= 3u);
}

TEST_CASE(file_naming_service_and_refresh) {
  start_nodes();
  const std::string path = "/tmp/trpc_test_servers.txt";
  {
    std::ofstream out(path);
    out << "127.0.0.1:" << g_nodes[0].port << "\n";
  }
  ClusterChannel::Options opts;
  opts.refresh_interval_ms = 100;
  ClusterChannel ch;
  EXPECT_EQ(ch.Init("file://" + path, "rr", &opts), 0);
  EXPECT(call_once(ch) == "node-0");
  // Add the other two nodes; periodic refresh must pick them up.
  {
    std::ofstream out(path);
    for (int i = 0; i < 3; ++i) {
      out << "127.0.0.1:" << g_nodes[i].port << "\n";
    }
  }
  std::set<std::string> seen;
  const int64_t deadline = monotonic_time_us() + 3000000;
  while (seen.size() < 3 && monotonic_time_us() < deadline) {
    seen.insert(call_once(ch));
    usleep(20000);
  }
  EXPECT_EQ(seen.size(), 3u);
  unlink(path.c_str());
}

TEST_CASE(backup_request_hedging) {
  start_nodes();
  // Add a slow method on every node: node 0 is slow, others fast.
  static Server slow_nodes[2];
  static int slow_ports[2];
  for (int i = 0; i < 2; ++i) {
    slow_nodes[i].RegisterMethod(
        "Echo.MaybeSlow",
        [i](Controller*, const IOBuf&, IOBuf* resp, Closure done) {
          if (i == 0) {
            fiber_sleep_us(400000);  // slow primary
          }
          resp->append("slow-node-" + std::to_string(i));
          done();
        });
    EXPECT_EQ(slow_nodes[i].Start(0), 0);
    slow_ports[i] = slow_nodes[i].port();
  }
  ClusterChannel::Options opts;
  opts.timeout_ms = 2000;
  opts.backup_request_ms = 50;  // hedge after 50ms
  ClusterChannel ch;
  // rr alternates primaries; whichever is primary, the result must arrive
  // fast when the OTHER node can serve it.
  EXPECT_EQ(ch.Init("list://127.0.0.1:" + std::to_string(slow_ports[0]) +
                        ",127.0.0.1:" + std::to_string(slow_ports[1]),
                    "rr", &opts),
            0);
  int fast_wins = 0;
  for (int i = 0; i < 6; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("x");
    const int64_t t0 = monotonic_time_us();
    ch.CallMethod("Echo.MaybeSlow", req, &resp, &cntl);
    const int64_t dt = monotonic_time_us() - t0;
    EXPECT(!cntl.Failed());
    if (dt < 300000) {
      ++fast_wins;  // answered before the slow node could (hedge won)
      EXPECT(resp.to_string() == "slow-node-1");
    }
  }
  // Every call must beat the 400ms sleeper: either node 1 was primary, or
  // the backup fired at 50ms and won.
  EXPECT_EQ(fast_wins, 6);
}

TEST_CASE(health_check_revives_node) {
  start_nodes();
  ClusterChannel::Options opts;
  opts.timeout_ms = 300;
  opts.max_retry = 0;
  opts.refresh_interval_ms = 50;       // probe quickly
  opts.quarantine_base_ms = 60000;     // quarantine would last a minute...
  // Dead port + live node: the breaker quarantines the dead one.
  ClusterChannel ch2;
  EXPECT_EQ(ch2.Init("list://127.0.0.1:1,127.0.0.1:" +
                         std::to_string(g_nodes[0].port),
                     "rr", &opts),
            0);
  // Drive calls until the dead node lands in quarantine.
  for (int i = 0; i < 4; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("x");
    ch2.CallMethod("Echo.WhoAmI", req, &resp, &cntl);
  }
  // Probe ticks run every 50ms; the dead port can't answer, so after
  // several ticks it remains quarantined (revive only works on live nodes).
  usleep(300000);
  EXPECT_EQ(ch2.healthy_count(), 1u);  // live node healthy, dead one not

  // Now quarantine the LIVE node artificially by failing calls to a
  // stopped server, then restarting it: simulate with node churn instead —
  // probe revival is covered by: quarantine the live node via the breaker
  // on a method that times out.
  static Server slow;
  slow.RegisterMethod("Echo.WhoAmI", [](Controller*, const IOBuf&,
                                        IOBuf* resp, Closure done) {
    resp->append("slow-alive");
    done();
  });
  slow.RegisterMethod("Echo.Stall", [](Controller*, const IOBuf&, IOBuf*,
                                       Closure done) {
    fiber_sleep_us(600000);  // > timeout: breaker counts failures
    done();
  });
  EXPECT_EQ(slow.Start(0), 0);
  ClusterChannel ch3;
  EXPECT_EQ(ch3.Init("list://127.0.0.1:" + std::to_string(slow.port()), "rr",
                     &opts),
            0);
  for (int i = 0; i < 2; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("x");
    ch3.CallMethod("Echo.Stall", req, &resp, &cntl);  // times out → breaker
    EXPECT(cntl.Failed());
  }
  // (healthy_count may already be back to 1 if a probe tick raced in —
  // the durable assertion is revival well inside the 60s window below.)
  // Health probe (Echo.Health → ENOENT from this server = alive) must
  // revive it far sooner than the 60s window.
  const int64_t deadline = monotonic_time_us() + 3000000;
  while (ch3.healthy_count() == 0 && monotonic_time_us() < deadline) {
    usleep(20000);
  }
  EXPECT_EQ(ch3.healthy_count(), 1u);
  // And traffic flows again.
  Controller cntl;
  IOBuf req, resp;
  req.append("x");
  ch3.CallMethod("Echo.WhoAmI", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT(resp.to_string() == "slow-alive");
}

TEST_CASE(async_cluster_call) {
  ClusterChannel ch;
  EXPECT_EQ(ch.Init(list_url(), "rr"), 0);
  static CountdownEvent latch(1);
  auto* cntl = new Controller();
  auto* resp = new IOBuf();
  IOBuf req;
  req.append("x");
  ch.CallMethod("Echo.WhoAmI", req, resp, cntl, [cntl, resp] {
    EXPECT(!cntl->Failed());
    EXPECT(resp->to_string().rfind("node-", 0) == 0);
    latch.signal();
  });
  EXPECT_EQ(latch.wait(monotonic_time_us() + 5000000), 0);
  delete cntl;
  delete resp;
}

TEST_CASE(wrr_weight_distribution) {
  // Two servers, weights 3 and 1: wrr sends ~3x the traffic to the first.
  Server s1, s2;
  std::atomic<int> c1{0}, c2{0};
  s1.RegisterMethod("W.Hit", [&c1](Controller*, const IOBuf&, IOBuf* r,
                                   Closure done) {
    c1.fetch_add(1);
    r->append("1");
    done();
  });
  s2.RegisterMethod("W.Hit", [&c2](Controller*, const IOBuf&, IOBuf* r,
                                   Closure done) {
    c2.fetch_add(1);
    r->append("2");
    done();
  });
  EXPECT_EQ(s1.Start(0), 0);
  EXPECT_EQ(s2.Start(0), 0);
  ClusterChannel ch;
  const std::string url = "list://127.0.0.1:" + std::to_string(s1.port()) +
                          " 3,127.0.0.1:" + std::to_string(s2.port()) + " 1";
  EXPECT_EQ(ch.Init(url, "wrr"), 0);
  for (int i = 0; i < 80; ++i) {
    Controller cntl;
    // Generous: a timeout-driven retry under sanitizer slowdown would
    // double-count a hit and break the exact-count assertions below.
    cntl.set_timeout_ms(10000);
    IOBuf req, resp;
    req.append("x");
    ch.CallMethod("W.Hit", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  EXPECT_EQ(c1.load() + c2.load(), 80);
  EXPECT_EQ(c1.load(), 60);  // smooth wrr is exact over full cycles
  EXPECT_EQ(c2.load(), 20);
}

TEST_CASE(p2c_prefers_fast_server) {
  // One slow (20ms) and one fast server: p2c-EWMA shifts load to the
  // fast one once feedback accumulates.
  Server fast, slow;
  std::atomic<int> cf{0}, cs{0};
  fast.RegisterMethod("P.Hit", [&cf](Controller*, const IOBuf&, IOBuf* r,
                                     Closure done) {
    cf.fetch_add(1);
    r->append("f");
    done();
  });
  slow.RegisterMethod("P.Hit", [&cs](Controller*, const IOBuf&, IOBuf* r,
                                     Closure done) {
    cs.fetch_add(1);
    fiber_sleep_us(20000);
    r->append("s");
    done();
  });
  EXPECT_EQ(fast.Start(0), 0);
  EXPECT_EQ(slow.Start(0), 0);
  ClusterChannel ch;
  const std::string url = "list://127.0.0.1:" + std::to_string(fast.port()) +
                          ",127.0.0.1:" + std::to_string(slow.port());
  EXPECT_EQ(ch.Init(url, "p2c"), 0);
  for (int i = 0; i < 60; ++i) {
    Controller cntl;
    // Generous: under TSan's slowdown a tighter timeout can expire and
    // retry, double-counting a handler hit (the 61-vs-60 flake).
    cntl.set_timeout_ms(10000);
    IOBuf req, resp;
    req.append("x");
    ch.CallMethod("P.Hit", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  EXPECT_EQ(cf.load() + cs.load(), 60);
  EXPECT(cf.load() > cs.load() * 2);  // strongly skewed to the fast node
}

TEST_CASE(locality_aware_shifts_and_recovers) {
  // The locality-aware balancer must (1) move traffic away from a node
  // whose latency degrades, and (2) give it back after it recovers —
  // the deceleration/recovery loop of the reference's lalb
  // (policy/locality_aware_load_balancer.h:41).
  static Server a, b, c;
  static std::atomic<int> hits[3];
  static std::atomic<int64_t> delay_us[3];
  struct Reg {
    Reg() {
      Server* servers[3] = {&a, &b, &c};
      for (int i = 0; i < 3; ++i) {
        servers[i]->RegisterMethod(
            "L.Hit", [i](Controller*, const IOBuf&, IOBuf* r, Closure done) {
              hits[i].fetch_add(1);
              const int64_t d = delay_us[i].load();
              if (d > 0) {
                fiber_sleep_us(d);
              }
              r->append("ok");
              done();
            });
        EXPECT_EQ(servers[i]->Start(0), 0);
      }
    }
  };
  static Reg reg;
  ClusterChannel ch;
  const std::string url = "list://127.0.0.1:" + std::to_string(a.port()) +
                          ",127.0.0.1:" + std::to_string(b.port()) +
                          ",127.0.0.1:" + std::to_string(c.port());
  EXPECT_EQ(ch.Init(url, "la"), 0);
  auto run = [&](int n) {
    for (int i = 0; i < n; ++i) {
      Controller cntl;
      cntl.set_timeout_ms(10000);
      IOBuf req, resp;
      req.append("x");
      ch.CallMethod("L.Hit", req, &resp, &cntl);
      EXPECT(!cntl.Failed());
    }
  };
  auto reset = [] {
    for (auto& h : hits) {
      h.store(0);
    }
  };

  // Phase 1: all healthy — every node earns a real share.
  run(150);
  for (auto& h : hits) {
    EXPECT(h.load() > 15);
  }

  // Phase 2: node 1 degrades to 15ms — its share collapses.  (15ms, not
  // 5ms: under TSan's slowdown per-call overhead approaches small
  // injected delays and washes out the statistical skew.)
  delay_us[1].store(15000);
  run(100);  // let feedback observe the slowdown
  reset();
  run(200);
  EXPECT(hits[1].load() < 40);  // < 20% (fair share would be ~33%)
  EXPECT(hits[0].load() + hits[2].load() > 160);

  // Phase 3: node 1 recovers — probing re-earns its share.  Outside CPU
  // load makes real latencies noisy enough to slow the EWMA decay, so
  // give convergence several rounds rather than one fixed-length run
  // (a genuinely broken recovery path stays near zero through all of
  // them).
  delay_us[1].store(0);
  int share = 0;
  for (int round = 0; round < 6 && share <= 30; ++round) {
    run(400);  // decay the remembered EWMA through probe traffic
    reset();
    run(200);
    share = static_cast<int>(hits[1].load());
  }
  EXPECT(share > 30);  // back above 15%
}

TEST_CASE(hedge_spawn_failure_backup_still_wins) {
  // Regression: a failed hedge-attempt spawn must settle its slot with a
  // synthetic error (not hang wait_settled) and must not shadow the
  // OTHER attempt's real outcome.  Inject one spawn failure: the primary
  // slot settles synthetically, the backup runs and wins.
  ClusterChannel::Options opts;
  opts.backup_request_ms = 10;
  opts.timeout_ms = 2000;
  ClusterChannel ch;
  EXPECT_EQ(ch.Init(list_url(), "rr", &opts), 0);

  test_fail_hedge_spawns.store(1);
  const std::string r = call_once(ch);
  test_fail_hedge_spawns.store(0);
  EXPECT(r.rfind("node-", 0) == 0);  // the surviving attempt answered
}

TEST_CASE(hedge_spawn_failure_both_attempts) {
  // Both spawns failing must return promptly with the synthetic error —
  // the settle accounting (launched vs failures) must terminate the wait.
  ClusterChannel::Options opts;
  opts.backup_request_ms = 10;
  opts.timeout_ms = 2000;
  ClusterChannel ch;
  EXPECT_EQ(ch.Init(list_url(), "rr", &opts), 0);

  test_fail_hedge_spawns.store(2);
  const int64_t t0 = monotonic_time_us();
  const std::string r = call_once(ch);
  test_fail_hedge_spawns.store(0);
  EXPECT(r.rfind("FAILED:", 0) == 0);
  // Promptly = well under the 2s call timeout (the settle path, not a
  // timer, ended the call).
  EXPECT(monotonic_time_us() - t0 < 1500000);
}

TEST_CASE(destructor_races_inflight_probes) {
  // Regression for the destructor-vs-probe interaction: tear the channel
  // down while health-check probes against a blackholed node are still
  // in flight.  Probe fibers own their state via shared_ptrs; destruction
  // must neither hang nor touch freed memory (the ASan CI build enforces
  // the latter).
  int dead_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sin = {};
  sin.sin_family = AF_INET;
  sin.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  EXPECT_EQ(::bind(dead_fd, reinterpret_cast<sockaddr*>(&sin),
                   sizeof(sin)),
            0);
  socklen_t slen = sizeof(sin);
  ::getsockname(dead_fd, reinterpret_cast<sockaddr*>(&sin), &slen);
  const int dead_port = ntohs(sin.sin_port);
  ::close(dead_fd);  // connections now refuse fast

  for (int round = 0; round < 10; ++round) {
    ClusterChannel::Options opts;
    opts.timeout_ms = 200;
    opts.max_retry = 2;
    opts.refresh_interval_ms = 10;  // probe cycle fires quickly
    opts.health_check_method = "Echo.WhoAmI";
    opts.quarantine_base_ms = 50;
    ClusterChannel ch;
    const std::string url =
        list_url() + ",127.0.0.1:" + std::to_string(dead_port);
    EXPECT_EQ(ch.Init(url, "rr", &opts), 0);
    // Trip the breaker on the dead node (calls still succeed via retry).
    for (int i = 0; i < 12; ++i) {
      (void)call_once(ch);
    }
    // Let a refresher tick launch probes, then destroy mid-flight.
    usleep(15000 + (round % 3) * 10000);
    // ~ClusterChannel runs here.
  }
}

// Cache-aware routing (ISSUE 17): a prefix-hash hint steers c_hash_bl
// to the member holding the cached prefix — unless bounded load vetoes,
// in which case the ring walk takes over.
TEST_CASE(chash_bl_hint_routing_and_veto) {
  std::unique_ptr<LoadBalancer> lb(LoadBalancer::create("c_hash_bl"));
  EXPECT(lb != nullptr);
  std::vector<ServerNode> nodes(3);
  std::vector<size_t> healthy = {0, 1, 2};
  for (int i = 0; i < 3; ++i) {
    nodes[i].ep.ip = 0x0100007f;  // 127.0.0.1
    nodes[i].ep.port = 9000 + i;
  }
  uint64_t hit0, veto0, miss0;
  LbHintCounters& c = lb_hint_counters();
  hit0 = LbHintCounters::read(c.hit);
  veto0 = LbHintCounters::read(c.veto);
  miss0 = LbHintCounters::read(c.miss);
  // Idle cluster + valid hint: honored regardless of ring order.
  for (int i = 0; i < 3; ++i) {
    LbHintScope scope(nodes[i].ep);
    EXPECT_EQ(lb->select(healthy, nodes, 12345, 0),
              static_cast<size_t>(i));
  }
  EXPECT_EQ(LbHintCounters::read(c.hit), hit0 + 3);
  // Retries NEVER honor the hint (the hinted node was just tried).
  {
    LbHintScope scope(nodes[0].ep);
    (void)lb->select(healthy, nodes, 12345, 1);
    EXPECT_EQ(LbHintCounters::read(c.hit), hit0 + 3);
    EXPECT_EQ(LbHintCounters::read(c.veto), veto0);
  }
  // Hinted node over the bounded-load bound: VETO, and the ring walk
  // must pick one of the under-bound members instead.
  nodes[2].inflight->store(100, std::memory_order_relaxed);
  {
    LbHintScope scope(nodes[2].ep);
    const size_t picked = lb->select(healthy, nodes, 12345, 0);
    EXPECT(picked == 0 || picked == 1);
  }
  EXPECT_EQ(LbHintCounters::read(c.veto), veto0 + 1);
  nodes[2].inflight->store(0, std::memory_order_relaxed);
  // Hint naming a member OUTSIDE the view (it drained away): miss,
  // ring walk decides.
  EndPoint gone;
  gone.ip = 0x0100007f;
  gone.port = 9999;
  {
    LbHintScope scope(gone);
    (void)lb->select(healthy, nodes, 12345, 0);
  }
  EXPECT_EQ(LbHintCounters::read(c.miss), miss0 + 1);
  // The scope is RAII: once it unwinds, no residue steers later picks.
  EndPoint residue;
  EXPECT(!lb_hint_get(&residue));
  const size_t ring = lb->select(healthy, nodes, 12345, 0);
  EXPECT_EQ(lb->select(healthy, nodes, 12345, 0), ring);  // pure ring
  EXPECT_EQ(LbHintCounters::read(c.hit), hit0 + 3);
}

TEST_MAIN
