// Collective transfer-schedule tests (net/collective.h): plan
// correctness for all three ops at 3/4/8 members, pull/push execution
// over in-process member fleets (shm rings, one-sided landings),
// chunk-fault whole-step failure + recovery, window-full fallback,
// reshard plan minimality vs the naive full-exchange, naming-epoch
// whole-or-nothing, and cancel-mid-schedule quiescence — the group
// put-schedule tier ROADMAP item 3 names.
#include <string.h>
#include <unistd.h>

#include <string>
#include <thread>
#include <vector>

#include "base/flags.h"
#include "base/iobuf.h"
#include "base/time.h"
#include "net/channel.h"
#include "net/collective.h"
#include "net/controller.h"
#include "net/fault.h"
#include "net/hotpath_stats.h"
#include "net/naming.h"
#include "net/rma.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

struct FaultGuard {
  ~FaultGuard() { FaultActor::global().set(""); }
};

struct FlagGuard {
  std::string name, old_value;
  FlagGuard(const std::string& n, const std::string& v) : name(n) {
    old_value = Flag::find(n)->value_string();
    EXPECT_EQ(Flag::set(n, v), 0);
  }
  ~FlagGuard() { Flag::set(name, old_value); }
};

// One in-process member fleet: n servers with the collective handlers
// and n GroupChannels (rank r's channels to everyone else).
struct Fleet {
  std::vector<std::unique_ptr<Server>> servers;
  std::vector<std::string> members;
  std::vector<std::unique_ptr<GroupChannel>> groups;
  uint64_t seq = 0;

  explicit Fleet(uint32_t n, int64_t timeout_ms = 20000) {
    for (uint32_t i = 0; i < n; ++i) {
      auto s = std::make_unique<Server>();
      EXPECT_EQ(coll_attach(s.get()), 0);
      EXPECT_EQ(s->Start(0), 0);
      members.push_back("127.0.0.1:" + std::to_string(s->port()));
      servers.push_back(std::move(s));
    }
    for (uint32_t r = 0; r < n; ++r) {
      auto g = std::make_unique<GroupChannel>();
      GroupChannel::Options opts;
      opts.timeout_ms = timeout_ms;
      opts.use_shm = true;
      EXPECT_EQ(g->Init(members, r, &opts), 0);
      groups.push_back(std::move(g));
    }
  }

  ~Fleet() {
    groups.clear();
    for (auto& s : servers) {
      s->Stop();
    }
  }

  // Runs one collective on every member concurrently; returns per-rank
  // result codes.
  std::vector<int> run_all(
      const std::function<int(GroupChannel*, uint32_t, uint64_t)>& fn) {
    seq += 1;
    std::vector<int> rcs(groups.size(), -1);
    std::vector<std::thread> threads;
    for (uint32_t r = 0; r < groups.size(); ++r) {
      threads.emplace_back([&, r] { rcs[r] = fn(groups[r].get(), r, seq); });
    }
    for (auto& t : threads) {
      t.join();
    }
    return rcs;
  }
};

char pat(uint32_t rank, size_t i) {
  return static_cast<char>(((i + rank * 131) * 2654435761u) >> 17);
}

struct MemberBufs {
  char* send = nullptr;
  char* recv = nullptr;
  uint64_t send_rkey = 0, recv_rkey = 0;
  MemberBufs(size_t send_len, size_t recv_len) {
    send = static_cast<char*>(rma_alloc(send_len, &send_rkey));
    recv = static_cast<char*>(rma_alloc(recv_len, &recv_rkey));
    EXPECT(send != nullptr && recv != nullptr);
  }
  ~MemberBufs() {
    rma_free(send);
    rma_free(recv);
  }
};

void all_gather_case(uint32_t n, uint64_t shard) {
  Fleet fleet(n);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(shard, n * shard));
    for (size_t i = 0; i < shard; ++i) {
      bufs[r]->send[i] = pat(r, i);
    }
  }
  auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
    return g->run(plan_all_gather(n, shard), bufs[r]->send, shard,
                  bufs[r]->recv, n * shard, seq);
  });
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
    for (uint32_t src = 0; src < n; ++src) {
      for (size_t i = 0; i < shard; i += 37) {
        EXPECT_EQ(bufs[r]->recv[src * shard + i], pat(src, i));
      }
    }
  }
}

}  // namespace

// -- plans (pure, no fabric) -----------------------------------------------

TEST_CASE(plans_are_deterministic_and_cover) {
  for (uint32_t n : {2u, 3u, 4u, 8u}) {
    const uint64_t shard = 64 << 10;
    const TransferSchedule ag = plan_all_gather(n, shard);
    EXPECT_EQ(ag.steps.size(), n - 1);
    EXPECT_EQ(ag.bytes_moved(), static_cast<uint64_t>(n) * (n - 1) * shard);
    EXPECT_EQ(ag.bytes_reused(), static_cast<uint64_t>(n) * shard);
    const TransferSchedule rs = plan_reduce_scatter(n, shard);
    EXPECT_EQ(rs.steps.size(), n - 1);
    EXPECT_EQ(rs.final_copies.size(), n);
    const TransferSchedule aa = plan_all_to_all(n, shard);
    EXPECT_EQ(aa.steps.size(), n - 1);
    EXPECT_EQ(aa.bytes_moved(), static_cast<uint64_t>(n) * (n - 1) * shard);
    // Every member receives exactly (n-1) shards across each plan.
    for (uint32_t r = 0; r < n; ++r) {
      uint64_t recv = 0;
      for (const CollStep& s : ag.steps) {
        for (const CollTransfer& t : s.puts) {
          if (t.dst == r) {
            recv += t.len;
          }
        }
      }
      EXPECT_EQ(recv, (n - 1) * shard);
    }
  }
}

TEST_CASE(reshard_plan_minimal_vs_naive_full_exchange) {
  // Overlapping shardings: most bytes stay put, only the boundary strip
  // moves — the 2112.01075 decomposition must beat the all-gather
  // strawman by a wide margin.
  const uint64_t total = 4 << 20;
  const uint64_t quarter = total / 4;
  Sharding src;
  src.total = total;
  for (uint32_t r = 0; r < 4; ++r) {
    src.ranges.push_back({r, r * quarter, quarter});
  }
  Sharding dst;
  dst.total = total;
  const uint64_t shift = 64 << 10;  // each rank's range shifts by 64KB
  dst.ranges.push_back({0, 0, quarter + shift});
  dst.ranges.push_back({1, quarter + shift, quarter});
  dst.ranges.push_back({2, 2 * quarter + shift, quarter});
  dst.ranges.push_back({3, 3 * quarter + shift, quarter - shift});
  EXPECT(sharding_valid(src, 4));
  EXPECT(sharding_valid(dst, 4));
  const TransferSchedule plan = plan_reshard(src, dst, 4);
  const uint64_t naive = reshard_naive_bytes(src, 4);
  EXPECT_EQ(naive, 3 * total);
  // Only the shifted strips move: 3 boundaries x 64KB.
  EXPECT_EQ(plan.bytes_moved(), 3 * shift);
  EXPECT(plan.bytes_moved() < naive);
  EXPECT_EQ(plan.bytes_moved() + plan.bytes_reused(), total);
  // Identity reshard moves NOTHING.
  const TransferSchedule ident = plan_reshard(src, src, 4);
  EXPECT_EQ(ident.bytes_moved(), 0u);
  EXPECT_EQ(ident.bytes_reused(), total);
}

// -- execution over the fabric ---------------------------------------------

TEST_CASE(all_gather_3_4_8_members) {
  all_gather_case(3, 1 << 20);
  all_gather_case(4, 512 << 10);
  all_gather_case(8, 128 << 10);
}

TEST_CASE(reduce_scatter_u32_sums) {
  const uint32_t n = 4;
  const uint64_t shard = 256 << 10;  // u32-aligned
  Fleet fleet(n);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(n * shard, shard));
    auto* v = reinterpret_cast<uint32_t*>(bufs[r]->send);
    for (size_t i = 0; i < n * shard / 4; ++i) {
      v[i] = static_cast<uint32_t>(i + r * 1000003);
    }
  }
  auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
    return g->run(plan_reduce_scatter(n, shard), bufs[r]->send, n * shard,
                  bufs[r]->recv, shard, seq);
  });
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
    const auto* got = reinterpret_cast<const uint32_t*>(bufs[r]->recv);
    for (size_t i = 0; i < shard / 4; i += 97) {
      const size_t gi = r * (shard / 4) + i;
      uint32_t want = 0;
      for (uint32_t src = 0; src < n; ++src) {
        want += static_cast<uint32_t>(gi + src * 1000003);
      }
      EXPECT_EQ(got[i], want);
    }
  }
}

TEST_CASE(all_to_all_transposes_blocks) {
  const uint32_t n = 3;
  const uint64_t shard = 512 << 10;
  Fleet fleet(n);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(n * shard, n * shard));
    for (uint32_t d = 0; d < n; ++d) {
      memset(bufs[r]->send + d * shard, static_cast<int>(1 + r * 16 + d),
             shard);
    }
  }
  auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
    return g->run(plan_all_to_all(n, shard), bufs[r]->send, n * shard,
                  bufs[r]->recv, n * shard, seq);
  });
  for (uint32_t d = 0; d < n; ++d) {
    EXPECT_EQ(rcs[d], 0);
    for (uint32_t src = 0; src < n; ++src) {
      for (size_t i = 0; i < shard; i += 131) {
        EXPECT_EQ(bufs[d]->recv[src * shard + i],
                  static_cast<char>(1 + src * 16 + d));
      }
    }
  }
}

TEST_CASE(reshard_executes_minimal_schedule) {
  const uint32_t n = 3;
  const uint64_t total = 3 << 20;
  const uint64_t third = total / 3;
  Sharding src;
  src.total = total;
  for (uint32_t r = 0; r < n; ++r) {
    src.ranges.push_back({r, r * third, third});
  }
  // Target: rank 0 shrinks to half, ranks 1/2 shift left accordingly —
  // an overlapping pair, so the plan must move < naive.
  Sharding dst;
  dst.total = total;
  dst.ranges.push_back({0, 0, third / 2});
  dst.ranges.push_back({1, third / 2, third});
  dst.ranges.push_back({2, third / 2 + third, total - third - third / 2});
  const TransferSchedule plan = plan_reshard(src, dst, n);
  EXPECT(plan.bytes_moved() < reshard_naive_bytes(src, n));
  EXPECT(plan.bytes_moved() > 0);

  Fleet fleet(n);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(
        sharding_local_bytes(src, r), sharding_local_bytes(dst, r)));
  }
  // Fill each member's source shard from one global pattern.
  for (const ShardRange& sr : src.ranges) {
    uint64_t local = 0;
    for (const ShardRange& prev : src.ranges) {
      if (prev.rank == sr.rank && prev.off < sr.off) {
        local += prev.len;
      }
    }
    for (uint64_t i = 0; i < sr.len; ++i) {
      bufs[sr.rank]->send[local + i] = pat(7, sr.off + i);
    }
  }
  auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
    return g->reshard(src, dst, bufs[r]->send,
                      sharding_local_bytes(src, r), bufs[r]->recv,
                      sharding_local_bytes(dst, r), seq);
  });
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
  }
  // Verify the target layout against the global pattern.
  for (const ShardRange& dr : dst.ranges) {
    uint64_t local = 0;
    for (const ShardRange& prev : dst.ranges) {
      if (prev.rank == dr.rank && prev.off < dr.off) {
        local += prev.len;
      }
    }
    for (uint64_t i = 0; i < dr.len; i += 41) {
      EXPECT_EQ(bufs[dr.rank]->recv[local + i], pat(7, dr.off + i));
    }
  }
}

// -- fault semantics -------------------------------------------------------

TEST_CASE(chunk_fault_fails_step_whole_and_recovers) {
  const uint32_t n = 3;
  const uint64_t shard = 2 << 20;
  Fleet fleet(n, /*timeout_ms=*/4000);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(shard, n * shard));
    for (size_t i = 0; i < shard; ++i) {
      bufs[r]->send[i] = pat(r, i);
    }
  }
  auto ag = [&](GroupChannel* g, uint32_t r, uint64_t seq) {
    return g->run(plan_all_gather(n, shard), bufs[r]->send, shard,
                  bufs[r]->recv, n * shard, seq);
  };
  // Clean baseline.
  auto rcs = fleet.run_all(ag);
  for (int rc : rcs) {
    EXPECT_EQ(rc, 0);
  }
  {
    // Chunk drops: some member's transfer faults; its step fails
    // whole-or-nothing and the abort fans out — no member may report
    // success with torn bytes.
    FaultGuard guard;
    EXPECT_EQ(FaultActor::global().set("seed=23;drop=0.6;max=48"), 0);
    // Poison the recv patterns so a torn admit would be detectable.
    for (uint32_t r = 0; r < n; ++r) {
      memset(bufs[r]->recv, 0, n * shard);
    }
    rcs = fleet.run_all(ag);
    bool any_failed = false;
    for (uint32_t r = 0; r < n; ++r) {
      if (rcs[r] != 0) {
        any_failed = true;
      } else {
        // A member that DID report success must hold exact bytes.
        for (uint32_t src = 0; src < n; ++src) {
          for (size_t i = 0; i < shard; i += 53) {
            EXPECT_EQ(bufs[r]->recv[src * shard + i], pat(src, i));
          }
        }
      }
    }
    EXPECT(any_failed);
  }
  EXPECT_EQ(coll_sessions_live(), 0u);
  // Faults cleared: the SAME fleet recovers byte-exact (connections may
  // have fallen back to tcp — correctness is transport-independent).
  rcs = fleet.run_all(ag);
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
    for (uint32_t src = 0; src < n; ++src) {
      for (size_t i = 0; i < shard; i += 53) {
        EXPECT_EQ(bufs[r]->recv[src * shard + i], pat(src, i));
      }
    }
  }
}

TEST_CASE(window_full_falls_back_to_copy_path) {
  // A tiny receive window cannot hold two in-flight 8MB push chunks:
  // reduce-scatter's pushes must degrade to the striped copy path and
  // stay byte-correct (rma_window_full counts the fallbacks).
  FlagGuard window("trpc_rma_window_bytes", std::to_string(16 << 20));
  FlagGuard chunk("trpc_coll_chunk_bytes", std::to_string(8 << 20));
  const uint32_t n = 3;
  const uint64_t shard = 12 << 20;
  Fleet fleet(n);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(n * shard, shard));
    auto* v = reinterpret_cast<uint32_t*>(bufs[r]->send);
    for (size_t i = 0; i < n * shard / 4; ++i) {
      v[i] = static_cast<uint32_t>(i * 3 + r);
    }
  }
  auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
    return g->run(plan_reduce_scatter(n, shard), bufs[r]->send, n * shard,
                  bufs[r]->recv, shard, seq);
  });
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
    const auto* got = reinterpret_cast<const uint32_t*>(bufs[r]->recv);
    for (size_t i = 0; i < shard / 4; i += 1009) {
      const size_t gi = r * (shard / 4) + i;
      uint32_t want = 0;
      for (uint32_t src = 0; src < n; ++src) {
        want += static_cast<uint32_t>(gi * 3 + src);
      }
      EXPECT_EQ(got[i], want);
    }
  }
}

TEST_CASE(cancel_mid_schedule_quiesces) {
  // Rank 2 never enters the collective: the others' step parks at the
  // serve/arrival barrier and must fail within the run budget, abort
  // cleanly, and leave ZERO live sessions (no leaked receive state, no
  // handler still copying).
  FlagGuard rendezvous("trpc_coll_rendezvous_ms", "600");
  const uint32_t n = 3;
  const uint64_t shard = 1 << 20;
  Fleet fleet(n, /*timeout_ms=*/1500);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(shard, n * shard));
  }
  fleet.seq += 1;
  const uint64_t seq = fleet.seq;
  std::vector<int> rcs(2, -1);
  std::vector<std::thread> threads;
  for (uint32_t r = 0; r < 2; ++r) {
    threads.emplace_back([&, r] {
      rcs[r] = fleet.groups[r]->run(plan_all_gather(n, shard),
                                    bufs[r]->send, shard, bufs[r]->recv,
                                    n * shard, seq);
    });
  }
  for (auto& t : threads) {
    t.join();
  }
  EXPECT(rcs[0] != 0);
  EXPECT(rcs[1] != 0);
  // Quiesced: sessions unregistered, in-flight puts cancelled/drained.
  EXPECT_EQ(coll_sessions_live(), 0u);
  // The fleet is not poisoned: a full run afterwards succeeds.
  for (uint32_t r = 0; r < n; ++r) {
    for (size_t i = 0; i < shard; ++i) {
      bufs[r]->send[i] = pat(r, i);
    }
  }
  auto rcs2 = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t s) {
    return g->run(plan_all_gather(n, shard), bufs[r]->send, shard,
                  bufs[r]->recv, n * shard, s);
  });
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs2[r], 0);
  }
}

// -- naming-backed groups --------------------------------------------------

TEST_CASE(naming_group_epoch_change_fails_step) {
  naming_ensure_registered();
  Server registry;
  EXPECT_EQ(naming_attach(&registry), 0);
  EXPECT_EQ(registry.Start(0), 0);
  const std::string reg_addr =
      "127.0.0.1:" + std::to_string(registry.port());

  const uint32_t n = 3;
  std::vector<std::unique_ptr<Server>> servers;
  std::vector<std::string> addrs;
  Channel reg_ch;
  EXPECT_EQ(reg_ch.Init(reg_addr), 0);
  for (uint32_t i = 0; i < n; ++i) {
    auto s = std::make_unique<Server>();
    EXPECT_EQ(coll_attach(s.get()), 0);
    EXPECT_EQ(s->Start(0), 0);
    const std::string addr = "127.0.0.1:" + std::to_string(s->port());
    NamingMember m;
    m.addr = addr;
    m.zone = "z1";
    m.epoch = 1000 + i;
    EXPECT_EQ(naming_announce(&reg_ch, "collsvc", m, 60000), 0);
    addrs.push_back(addr);
    servers.push_back(std::move(s));
  }
  const std::string url = "naming://" + reg_addr + "/collsvc";
  std::vector<std::unique_ptr<GroupChannel>> groups(n);
  for (uint32_t i = 0; i < n; ++i) {
    groups[i] = std::make_unique<GroupChannel>();
    GroupChannel::Options opts;
    opts.timeout_ms = 10000;
    EXPECT_EQ(groups[i]->InitNaming(url, addrs[i], &opts), 0);
    EXPECT_EQ(groups[i]->nmembers(), n);
  }
  // Ranks are the sorted-address order — identical on every member.
  std::vector<std::string> sorted_addrs = addrs;
  std::sort(sorted_addrs.begin(), sorted_addrs.end());
  std::vector<GroupChannel*> by_rank(n);
  for (uint32_t i = 0; i < n; ++i) {
    by_rank[groups[i]->my_rank()] = groups[i].get();
    EXPECT(sorted_addrs[groups[i]->my_rank()] == addrs[i]);
  }
  const uint64_t shard = 256 << 10;
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(shard, n * shard));
    for (size_t i = 0; i < shard; ++i) {
      bufs[r]->send[i] = pat(r, i);
    }
  }
  auto run_all = [&](uint64_t seq) {
    std::vector<int> rcs(n, -1);
    std::vector<std::thread> threads;
    for (uint32_t r = 0; r < n; ++r) {
      threads.emplace_back([&, r] {
        rcs[r] = by_rank[r]->run(plan_all_gather(n, shard), bufs[r]->send,
                                 shard, bufs[r]->recv, n * shard, seq);
      });
    }
    for (auto& t : threads) {
      t.join();
    }
    return rcs;
  };
  auto rcs = run_all(1);
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
  }
  // Rolling restart analogue: a member re-announces under a NEWER epoch
  // (restarted process) — the view version moves, and every member's
  // next step fails kECollEpoch whole-or-nothing.
  NamingMember restarted;
  restarted.addr = addrs[0];
  restarted.zone = "z1";
  restarted.epoch = 99999;
  EXPECT_EQ(naming_announce(&reg_ch, "collsvc", restarted, 60000), 0);
  rcs = run_all(2);
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], kECollEpoch);
  }
  EXPECT_EQ(coll_sessions_live(), 0u);
  // Recompiling from the new view restores service.
  for (uint32_t i = 0; i < n; ++i) {
    groups[i] = std::make_unique<GroupChannel>();
    GroupChannel::Options opts;
    opts.timeout_ms = 10000;
    EXPECT_EQ(groups[i]->InitNaming(url, addrs[i], &opts), 0);
    by_rank[groups[i]->my_rank()] = groups[i].get();
  }
  rcs = run_all(3);
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
  }
  groups.clear();
  for (auto& s : servers) {
    s->Stop();
  }
  registry.Stop();
}

// -- readiness-triggered transfers (overlap-aware collectives) -------------

namespace {

// Per-rank ready maps over each member's sendbuf, destroyed on scope
// exit; stamp_all marks the full buffer, stamp_to a prefix.
struct ReadyMaps {
  std::vector<uint64_t> handles;
  ReadyMaps(const std::vector<std::unique_ptr<MemberBufs>>& bufs,
            uint64_t send_len, uint64_t granularity) {
    for (const auto& b : bufs) {
      const uint64_t h = rma_ready_create(b->send, send_len, granularity);
      EXPECT(h != 0);
      handles.push_back(h);
    }
  }
  ~ReadyMaps() {
    for (uint64_t h : handles) {
      rma_ready_destroy(h);
    }
  }
  void stamp_to(uint32_t r, uint64_t len) {
    if (len > 0) {
      EXPECT_EQ(rma_ready_stamp(handles[r], 0, len), 0);
    }
  }
};

}  // namespace

TEST_CASE(overlap_off_ready_map_byte_identical) {
  // Default trpc_coll_overlap=false: a run with a ready map attached
  // waits ONCE for the producer extent, then takes the unchanged
  // barrier path — bytes identical to a plain run, even when the
  // producer stamps late from another thread (serves never ship
  // unstamped bytes in either mode).
  const size_t maps0 = rma_ready_maps();
  const uint32_t n = 3;
  const uint64_t shard = 256 << 10;
  const uint64_t gran = 64 << 10;
  Fleet fleet(n);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(n * shard, shard));
  }
  auto fill = [&] {
    for (uint32_t r = 0; r < n; ++r) {
      auto* v = reinterpret_cast<uint32_t*>(bufs[r]->send);
      for (size_t i = 0; i < n * shard / 4; ++i) {
        v[i] = static_cast<uint32_t>(i * 7 + r * 1000003);
      }
    }
  };
  // Plain run → golden recv bytes (reduce_scatter MUTATES send, so the
  // ready-map run refills before reproducing it).
  fill();
  auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
    return g->run(plan_reduce_scatter(n, shard), bufs[r]->send, n * shard,
                  bufs[r]->recv, shard, seq);
  });
  std::vector<std::string> golden;
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
    golden.emplace_back(bufs[r]->recv, shard);
    memset(bufs[r]->recv, 0, shard);
  }
  fill();
  {
    ReadyMaps maps(bufs, n * shard, gran);
    // Producers stamp LATE, chunk by chunk, from their own threads —
    // the overlap-off executor must park until the extent is ready.
    std::vector<std::thread> producers;
    for (uint32_t r = 0; r < n; ++r) {
      producers.emplace_back([&, r] {
        for (uint64_t off = 0; off < n * shard; off += gran) {
          usleep(200);
          EXPECT_EQ(rma_ready_stamp(maps.handles[r], off, gran), 0);
        }
      });
    }
    rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
      return g->run(plan_reduce_scatter(n, shard), bufs[r]->send,
                    n * shard, bufs[r]->recv, shard, seq,
                    maps.handles[r]);
    });
    for (auto& t : producers) {
      t.join();
    }
    for (uint32_t r = 0; r < n; ++r) {
      EXPECT_EQ(rcs[r], 0);
      EXPECT_EQ(memcmp(bufs[r]->recv, golden[r].data(), shard), 0);
    }
  }
  EXPECT_EQ(coll_sessions_live(), 0u);
  EXPECT_EQ(rma_ready_maps(), maps0);
}

TEST_CASE(overlapped_run_byte_exact_vs_barrier) {
  // trpc_coll_overlap=true: transfers fire per-chunk as producers
  // stamp; the result must still be byte-exact against the barrier
  // run's golden bytes (whole-or-nothing step semantics preserved).
  FlagGuard overlap("trpc_coll_overlap", "true");
  const size_t maps0 = rma_ready_maps();
  const uint32_t n = 3;
  const uint64_t shard = 256 << 10;
  const uint64_t gran = 64 << 10;
  Fleet fleet(n);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(n * shard, shard));
  }
  auto fill = [&] {
    for (uint32_t r = 0; r < n; ++r) {
      auto* v = reinterpret_cast<uint32_t*>(bufs[r]->send);
      for (size_t i = 0; i < n * shard / 4; ++i) {
        v[i] = static_cast<uint32_t>(i * 13 + r * 999983);
      }
    }
  };
  fill();
  auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
    return g->run(plan_reduce_scatter(n, shard), bufs[r]->send, n * shard,
                  bufs[r]->recv, shard, seq);
  });
  std::vector<std::string> golden;
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
    golden.emplace_back(bufs[r]->recv, shard);
    memset(bufs[r]->recv, 0, shard);
  }
  fill();
  {
    ReadyMaps maps(bufs, n * shard, gran);
    std::vector<std::thread> producers;
    for (uint32_t r = 0; r < n; ++r) {
      producers.emplace_back([&, r] {
        for (uint64_t off = 0; off < n * shard; off += gran) {
          usleep(200);
          EXPECT_EQ(rma_ready_stamp(maps.handles[r], off, gran), 0);
        }
      });
    }
    rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
      return g->run(plan_reduce_scatter(n, shard), bufs[r]->send,
                    n * shard, bufs[r]->recv, shard, seq,
                    maps.handles[r]);
    });
    for (auto& t : producers) {
      t.join();
    }
    for (uint32_t r = 0; r < n; ++r) {
      EXPECT_EQ(rcs[r], 0);
      EXPECT_EQ(memcmp(bufs[r]->recv, golden[r].data(), shard), 0);
    }
  }
  EXPECT_EQ(coll_sessions_live(), 0u);
  EXPECT_EQ(rma_ready_maps(), maps0);
}

TEST_CASE(never_stamped_producer_trips_deadline_not_wedge) {
  // A producer that NEVER stamps must trip the run deadline — in both
  // modes — not wedge the fleet; sessions quiesce and the same fleet
  // serves a clean run afterwards.
  FlagGuard rendezvous("trpc_coll_rendezvous_ms", "600");
  const size_t maps0 = rma_ready_maps();
  const uint32_t n = 3;
  const uint64_t shard = 128 << 10;
  Fleet fleet(n, /*timeout_ms=*/1500);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(n * shard, shard));
    memset(bufs[r]->send, 1 + r, n * shard);
  }
  for (const char* mode : {"false", "true"}) {
    FlagGuard overlap("trpc_coll_overlap", mode);
    ReadyMaps maps(bufs, n * shard, 64 << 10);  // never stamped
    auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r,
                                 uint64_t seq) {
      return g->run(plan_reduce_scatter(n, shard), bufs[r]->send,
                    n * shard, bufs[r]->recv, shard, seq,
                    maps.handles[r]);
    });
    for (uint32_t r = 0; r < n; ++r) {
      EXPECT(rcs[r] != 0);
    }
    EXPECT_EQ(coll_sessions_live(), 0u);
  }
  EXPECT_EQ(rma_ready_maps(), maps0);
  // Not poisoned: a plain run on the SAME fleet succeeds byte-exact.
  for (uint32_t r = 0; r < n; ++r) {
    auto* v = reinterpret_cast<uint32_t*>(bufs[r]->send);
    for (size_t i = 0; i < n * shard / 4; ++i) {
      v[i] = static_cast<uint32_t>(i + r * 1000003);
    }
  }
  auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
    return g->run(plan_reduce_scatter(n, shard), bufs[r]->send, n * shard,
                  bufs[r]->recv, shard, seq);
  });
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
    const auto* got = reinterpret_cast<const uint32_t*>(bufs[r]->recv);
    for (size_t i = 0; i < shard / 4; i += 97) {
      const size_t gi = r * (shard / 4) + i;
      uint32_t want = 0;
      for (uint32_t src = 0; src < n; ++src) {
        want += static_cast<uint32_t>(gi + src * 1000003);
      }
      EXPECT_EQ(got[i], want);
    }
  }
}

TEST_CASE(chunk_fault_on_triggered_transfer_fails_whole) {
  // Chaos (chunk drops) against the readiness-TRIGGERED path: a step
  // whose transfer faults fails whole-or-nothing — a member reporting
  // success must hold exact bytes — and the fleet recovers once faults
  // clear.  Mirrors chunk_fault_fails_step_whole_and_recovers with the
  // overlap machinery live.
  FlagGuard overlap("trpc_coll_overlap", "true");
  const size_t maps0 = rma_ready_maps();
  const uint32_t n = 3;
  const uint64_t shard = 1 << 20;
  Fleet fleet(n, /*timeout_ms=*/4000);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(shard, n * shard));
    for (size_t i = 0; i < shard; ++i) {
      bufs[r]->send[i] = pat(r, i);
    }
  }
  {
    FaultGuard guard;
    EXPECT_EQ(FaultActor::global().set("seed=23;drop=0.6;max=48"), 0);
    ReadyMaps maps(bufs, shard, 64 << 10);
    std::vector<std::thread> producers;
    for (uint32_t r = 0; r < n; ++r) {
      producers.emplace_back([&, r] {
        for (uint64_t off = 0; off < shard; off += 64 << 10) {
          usleep(100);
          EXPECT_EQ(rma_ready_stamp(maps.handles[r], off, 64 << 10), 0);
        }
      });
    }
    auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r,
                                 uint64_t seq) {
      return g->run(plan_all_gather(n, shard), bufs[r]->send, shard,
                    bufs[r]->recv, n * shard, seq, maps.handles[r]);
    });
    for (auto& t : producers) {
      t.join();
    }
    bool any_failed = false;
    for (uint32_t r = 0; r < n; ++r) {
      if (rcs[r] != 0) {
        any_failed = true;
      } else {
        for (uint32_t src = 0; src < n; ++src) {
          for (size_t i = 0; i < shard; i += 53) {
            EXPECT_EQ(bufs[r]->recv[src * shard + i], pat(src, i));
          }
        }
      }
    }
    EXPECT(any_failed);
  }
  EXPECT_EQ(coll_sessions_live(), 0u);
  EXPECT_EQ(rma_ready_maps(), maps0);
  // Faults cleared: the SAME fleet recovers byte-exact.
  auto rcs = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t seq) {
    return g->run(plan_all_gather(n, shard), bufs[r]->send, shard,
                  bufs[r]->recv, n * shard, seq);
  });
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs[r], 0);
    for (uint32_t src = 0; src < n; ++src) {
      for (size_t i = 0; i < shard; i += 53) {
        EXPECT_EQ(bufs[r]->recv[src * shard + i], pat(src, i));
      }
    }
  }
}

TEST_CASE(cancel_mid_overlapped_dataflow_quiesces) {
  // Rank 2 never enters the overlapped dataflow and the producers only
  // stamp HALF their buffers: the others' steps must fail within the
  // run budget, abort cleanly, and leave zero sessions and no parked
  // readiness waiter (destroying the maps afterwards must not find
  // anyone still attached).
  FlagGuard overlap("trpc_coll_overlap", "true");
  FlagGuard rendezvous("trpc_coll_rendezvous_ms", "600");
  const size_t maps0 = rma_ready_maps();
  const uint32_t n = 3;
  const uint64_t shard = 512 << 10;
  Fleet fleet(n, /*timeout_ms=*/1500);
  std::vector<std::unique_ptr<MemberBufs>> bufs;
  for (uint32_t r = 0; r < n; ++r) {
    bufs.push_back(std::make_unique<MemberBufs>(n * shard, shard));
    memset(bufs[r]->send, 1 + r, n * shard);
  }
  {
    ReadyMaps maps(bufs, n * shard, 64 << 10);
    for (uint32_t r = 0; r < 2; ++r) {
      maps.stamp_to(r, n * shard / 2);  // half, never the rest
    }
    fleet.seq += 1;
    const uint64_t seq = fleet.seq;
    std::vector<int> rcs(2, -1);
    std::vector<std::thread> threads;
    for (uint32_t r = 0; r < 2; ++r) {
      threads.emplace_back([&, r] {
        rcs[r] = fleet.groups[r]->run(plan_reduce_scatter(n, shard),
                                      bufs[r]->send, n * shard,
                                      bufs[r]->recv, shard, seq,
                                      maps.handles[r]);
      });
    }
    for (auto& t : threads) {
      t.join();
    }
    EXPECT(rcs[0] != 0);
    EXPECT(rcs[1] != 0);
    EXPECT_EQ(coll_sessions_live(), 0u);
  }
  EXPECT_EQ(rma_ready_maps(), maps0);
  // The fleet is not poisoned: a full plain run afterwards succeeds.
  auto rcs2 = fleet.run_all([&](GroupChannel* g, uint32_t r, uint64_t s) {
    return g->run(plan_reduce_scatter(n, shard), bufs[r]->send, n * shard,
                  bufs[r]->recv, shard, s);
  });
  for (uint32_t r = 0; r < n; ++r) {
    EXPECT_EQ(rcs2[r], 0);
  }
}

TEST_MAIN
