// Combo channel tests: fan-out/merge, fail_limit, selective failover,
// partitioned calls (the reference drives these against N in-process
// servers, SURVEY.md §4).
#include <atomic>
#include <memory>
#include <string>

#include "base/time.h"
#include "net/combo.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

Server* g_nodes[3];
int g_ports[3];
bool g_started = false;

void start_nodes() {
  if (g_started) {
    return;
  }
  g_started = true;
  for (int i = 0; i < 3; ++i) {
    g_nodes[i] = new Server();
    g_nodes[i]->RegisterMethod(
        "C.Tag", [i](Controller*, const IOBuf& req, IOBuf* resp,
                     Closure done) {
          resp->append("[" + std::to_string(i) + ":" + req.to_string() + "]");
          done();
        });
    g_nodes[i]->RegisterMethod(
        "C.Sum", [](Controller*, const IOBuf& req, IOBuf* resp,
                    Closure done) {
          // Sums bytes of its partition.
          long total = 0;
          const std::string s = req.to_string();
          for (char c : s) {
            total += static_cast<unsigned char>(c);
          }
          resp->append(std::to_string(total) + ";");
          done();
        });
    EXPECT_EQ(g_nodes[i]->Start(0), 0);
    g_ports[i] = g_nodes[i]->port();
  }
}

std::shared_ptr<SubChannel> sub(int i) {
  auto ch = std::make_shared<Channel>();
  EXPECT_EQ(ch->Init("127.0.0.1:" + std::to_string(g_ports[i])), 0);
  return make_sub_channel(ch);
}

std::shared_ptr<SubChannel> dead_sub() {
  auto ch = std::make_shared<Channel>();
  Channel::Options o;
  o.timeout_ms = 200;
  EXPECT_EQ(ch->Init("127.0.0.1:1", &o), 0);
  return make_sub_channel(ch);
}

}  // namespace

TEST_CASE(parallel_broadcast_merge) {
  start_nodes();
  ParallelChannel pch;
  for (int i = 0; i < 3; ++i) {
    pch.add_sub_channel(sub(i));
  }
  Controller cntl;
  IOBuf req, resp;
  req.append("hi");
  pch.CallMethod("C.Tag", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  // Default merger concatenates (order = sub order since all succeed).
  EXPECT(resp.to_string() == "[0:hi][1:hi][2:hi]");
}

TEST_CASE(parallel_call_mapper) {
  start_nodes();
  ParallelChannel pch;
  for (int i = 0; i < 3; ++i) {
    pch.add_sub_channel(sub(i));
  }
  ParallelChannel::Options opts;
  opts.mapper = [](int i, const IOBuf&) {
    IOBuf b;
    b.append("sub" + std::to_string(i));
    return b;
  };
  Controller cntl;
  IOBuf req, resp;
  req.append("ignored");
  pch.CallMethod("C.Tag", req, &resp, &cntl, &opts);
  EXPECT(!cntl.Failed());
  EXPECT(resp.to_string() == "[0:sub0][1:sub1][2:sub2]");
}

TEST_CASE(parallel_fail_limit) {
  start_nodes();
  ParallelChannel pch;
  pch.add_sub_channel(sub(0));
  pch.add_sub_channel(dead_sub());
  pch.add_sub_channel(sub(2));

  // Default fail_limit 0: one dead sub fails the call.
  {
    Controller cntl;
    cntl.set_timeout_ms(500);
    IOBuf req, resp;
    req.append("x");
    pch.CallMethod("C.Tag", req, &resp, &cntl);
    EXPECT(cntl.Failed());
  }
  // fail_limit 1 tolerates it and merges the survivors.
  {
    ParallelChannel::Options opts;
    opts.fail_limit = 1;
    Controller cntl;
    cntl.set_timeout_ms(500);
    IOBuf req, resp;
    req.append("x");
    pch.CallMethod("C.Tag", req, &resp, &cntl, &opts);
    EXPECT(!cntl.Failed());
    EXPECT(resp.to_string() == "[0:x][2:x]");
  }
}

TEST_CASE(selective_failover) {
  start_nodes();
  SelectiveChannel sch;
  sch.add_sub_channel(dead_sub());
  sch.add_sub_channel(sub(1));
  int ok = 0;
  for (int i = 0; i < 6; ++i) {
    Controller cntl;
    cntl.set_timeout_ms(500);
    IOBuf req, resp;
    req.append("s");
    sch.CallMethod("C.Tag", req, &resp, &cntl, /*max_failover=*/1);
    if (!cntl.Failed()) {
      EXPECT(resp.to_string() == "[1:s]");
      ++ok;
    }
  }
  EXPECT_EQ(ok, 6);  // failover always reaches the live sub
}

TEST_CASE(partition_channel_shards) {
  start_nodes();
  PartitionChannel pch;
  for (int i = 0; i < 3; ++i) {
    pch.add_partition(sub(i));
  }
  Controller cntl;
  IOBuf req, resp;
  req.append("abcdef");  // 6 bytes → 2 per partition
  pch.CallMethod(
      "C.Sum", req, &resp, &cntl,
      [](const IOBuf& r, size_t n) {
        std::vector<IOBuf> parts(n);
        IOBuf copy = r;
        const size_t each = r.size() / n;
        for (size_t i = 0; i < n; ++i) {
          copy.cutn(&parts[i], i + 1 == n ? copy.size() : each);
        }
        return parts;
      });
  EXPECT(!cntl.Failed());
  // 'a'+'b'=195, 'c'+'d'=199, 'e'+'f'=203
  EXPECT(resp.to_string() == "195;199;203;");
}

TEST_MAIN
