// Combo channel tests: fan-out/merge, fail_limit, selective failover,
// partitioned calls (the reference drives these against N in-process
// servers, SURVEY.md §4).
#include <atomic>
#include <memory>
#include <string>

#include "base/time.h"
#include "fiber/fiber.h"
#include "net/combo.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

Server* g_nodes[3];
int g_ports[3];
bool g_started = false;

void start_nodes() {
  if (g_started) {
    return;
  }
  g_started = true;
  for (int i = 0; i < 3; ++i) {
    g_nodes[i] = new Server();
    g_nodes[i]->RegisterMethod(
        "C.Tag", [i](Controller*, const IOBuf& req, IOBuf* resp,
                     Closure done) {
          resp->append("[" + std::to_string(i) + ":" + req.to_string() + "]");
          done();
        });
    g_nodes[i]->RegisterMethod(
        "C.Sum", [](Controller*, const IOBuf& req, IOBuf* resp,
                    Closure done) {
          // Sums bytes of its partition.
          long total = 0;
          const std::string s = req.to_string();
          for (char c : s) {
            total += static_cast<unsigned char>(c);
          }
          resp->append(std::to_string(total) + ";");
          done();
        });
    EXPECT_EQ(g_nodes[i]->Start(0), 0);
    g_ports[i] = g_nodes[i]->port();
  }
}

std::shared_ptr<SubChannel> sub(int i) {
  auto ch = std::make_shared<Channel>();
  EXPECT_EQ(ch->Init("127.0.0.1:" + std::to_string(g_ports[i])), 0);
  return make_sub_channel(ch);
}

std::shared_ptr<SubChannel> dead_sub() {
  auto ch = std::make_shared<Channel>();
  Channel::Options o;
  o.timeout_ms = 200;
  EXPECT_EQ(ch->Init("127.0.0.1:1", &o), 0);
  return make_sub_channel(ch);
}

}  // namespace

TEST_CASE(parallel_broadcast_merge) {
  start_nodes();
  ParallelChannel pch;
  for (int i = 0; i < 3; ++i) {
    pch.add_sub_channel(sub(i));
  }
  Controller cntl;
  IOBuf req, resp;
  req.append("hi");
  pch.CallMethod("C.Tag", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  // Default merger concatenates (order = sub order since all succeed).
  EXPECT(resp.to_string() == "[0:hi][1:hi][2:hi]");
}

TEST_CASE(parallel_call_mapper) {
  start_nodes();
  ParallelChannel pch;
  for (int i = 0; i < 3; ++i) {
    pch.add_sub_channel(sub(i));
  }
  ParallelChannel::Options opts;
  opts.mapper = [](int i, const IOBuf&) {
    IOBuf b;
    b.append("sub" + std::to_string(i));
    return b;
  };
  Controller cntl;
  IOBuf req, resp;
  req.append("ignored");
  pch.CallMethod("C.Tag", req, &resp, &cntl, &opts);
  EXPECT(!cntl.Failed());
  EXPECT(resp.to_string() == "[0:sub0][1:sub1][2:sub2]");
}

TEST_CASE(parallel_fail_limit) {
  start_nodes();
  ParallelChannel pch;
  pch.add_sub_channel(sub(0));
  pch.add_sub_channel(dead_sub());
  pch.add_sub_channel(sub(2));

  // Default fail_limit 0: one dead sub fails the call.
  {
    Controller cntl;
    cntl.set_timeout_ms(500);
    IOBuf req, resp;
    req.append("x");
    pch.CallMethod("C.Tag", req, &resp, &cntl);
    EXPECT(cntl.Failed());
  }
  // fail_limit 1 tolerates it and merges the survivors.
  {
    ParallelChannel::Options opts;
    opts.fail_limit = 1;
    Controller cntl;
    cntl.set_timeout_ms(500);
    IOBuf req, resp;
    req.append("x");
    pch.CallMethod("C.Tag", req, &resp, &cntl, &opts);
    EXPECT(!cntl.Failed());
    EXPECT(resp.to_string() == "[0:x][2:x]");
  }
}

TEST_CASE(selective_failover) {
  start_nodes();
  SelectiveChannel sch;
  sch.add_sub_channel(dead_sub());
  sch.add_sub_channel(sub(1));
  int ok = 0;
  for (int i = 0; i < 6; ++i) {
    Controller cntl;
    cntl.set_timeout_ms(500);
    IOBuf req, resp;
    req.append("s");
    sch.CallMethod("C.Tag", req, &resp, &cntl, /*max_failover=*/1);
    if (!cntl.Failed()) {
      EXPECT(resp.to_string() == "[1:s]");
      ++ok;
    }
  }
  EXPECT_EQ(ok, 6);  // failover always reaches the live sub
}

TEST_CASE(partition_channel_shards) {
  start_nodes();
  PartitionChannel pch;
  for (int i = 0; i < 3; ++i) {
    pch.add_partition(sub(i));
  }
  Controller cntl;
  IOBuf req, resp;
  req.append("abcdef");  // 6 bytes → 2 per partition
  pch.CallMethod(
      "C.Sum", req, &resp, &cntl,
      [](const IOBuf& r, size_t n) {
        std::vector<IOBuf> parts(n);
        IOBuf copy = r;
        const size_t each = r.size() / n;
        for (size_t i = 0; i < n; ++i) {
          copy.cutn(&parts[i], i + 1 == n ? copy.size() : each);
        }
        return parts;
      });
  EXPECT(!cntl.Failed());
  // 'a'+'b'=195, 'c'+'d'=199, 'e'+'f'=203
  EXPECT(resp.to_string() == "195;199;203;");
}

TEST_CASE(dynamic_partition_capacity_and_feedback) {
  // Two coexisting partition schemes of one logical service (a 1-way and
  // a 2-way deployment, as during resharding): traffic divides by
  // capacity, then FOLLOWS OBSERVED QUALITY — slowing the bigger scheme
  // sheds its share, recovery re-earns it (partition_channel.h:136 +
  // closed-loop correction).
  static Server s1, s2a, s2b;
  static std::atomic<int> scheme_hits[2];
  static std::atomic<int64_t> big_delay_us{0};
  struct Reg {
    Reg() {
      s1.RegisterMethod("D.Part", [](Controller*, const IOBuf& req,
                                     IOBuf* r, Closure done) {
        scheme_hits[0].fetch_add(1);
        r->append(req);
        done();
      });
      for (Server* s : {&s2a, &s2b}) {
        s->RegisterMethod("D.Part", [](Controller*, const IOBuf& req,
                                       IOBuf* r, Closure done) {
          scheme_hits[1].fetch_add(1);
          const int64_t d = big_delay_us.load();
          if (d > 0) {
            fiber_sleep_us(d);
          }
          r->append(req);
          done();
        });
      }
      EXPECT_EQ(s1.Start(0), 0);
      EXPECT_EQ(s2a.Start(0), 0);
      EXPECT_EQ(s2b.Start(0), 0);
    }
  };
  static Reg reg;
  auto sub_for = [](int port) {
    auto ch = std::make_shared<Channel>();
    EXPECT_EQ(ch->Init("127.0.0.1:" + std::to_string(port)), 0);
    return make_sub_channel(ch);
  };
  DynamicPartitionChannel dyn;
  EXPECT_EQ(dyn.add_scheme({sub_for(s1.port())}), 0);
  EXPECT_EQ(dyn.add_scheme({sub_for(s2a.port()), sub_for(s2b.port())}), 1);

  auto split = [](const IOBuf& req, size_t n) {
    // Even byte split across partitions.
    std::vector<IOBuf> parts(n);
    IOBuf rest = req;
    const size_t per = req.size() / n;
    for (size_t i = 0; i + 1 < n; ++i) {
      rest.cutn(&parts[i], per);
    }
    parts[n - 1] = std::move(rest);
    return parts;
  };
  auto run = [&](int n) {
    for (int i = 0; i < n; ++i) {
      Controller cntl;
      cntl.set_timeout_ms(2000);
      IOBuf req, resp;
      req.append("0123456789abcdef");
      dyn.CallMethod("D.Part", req, &resp, &cntl, split);
      EXPECT(!cntl.Failed());
      EXPECT(resp.to_string() == "0123456789abcdef");
    }
  };
  auto reset = [] {
    scheme_hits[0].store(0);
    scheme_hits[1].store(0);
  };

  // Phase 1: capacity prior — the 2-way scheme carries ~2/3 of calls
  // (its per-call hits count double: each fanout touches both shards).
  run(150);
  const int calls0 = scheme_hits[0].load();
  const int calls1 = scheme_hits[1].load() / 2;  // 2 hits per fanout
  EXPECT_EQ(calls0 + calls1, 150);
  // Capacity weighting gives the 2-way scheme the larger PRIOR share;
  // quality feedback may pull it back toward parity where the wider
  // fanout itself costs latency (pronounced under sanitizers), so assert
  // a solid share rather than a strict majority.
  EXPECT(calls1 > 45);

  // Phase 2: the 2-way scheme degrades (5ms per shard) — share collapses.
  big_delay_us.store(20000);
  run(80);
  reset();
  run(150);
  EXPECT(scheme_hits[1].load() / 2 < 50);  // well under its fair share
  EXPECT(dyn.scheme_weight(1) < dyn.scheme_weight(0));

  // Phase 3: recovery — capacity share returns.  Noisy outside load slows
  // the EWMA decay; converge over rounds (a broken recovery path stays
  // pinned low through all of them).
  big_delay_us.store(0);
  int share = 0;
  for (int round = 0; round < 6 && share <= 50; ++round) {
    run(250);
    reset();
    run(150);
    share = scheme_hits[1].load() / 2;
  }
  EXPECT(share > 50);
}

TEST_MAIN
