// The butil/containers remainder: MruCache eviction/recency,
// CaseIgnoredFlatMap canonicalization, BoundedQueue ring wraparound,
// and the MPSC queue hammered by concurrent producers.
#include <atomic>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "base/containers.h"
#include "tests/test_util.h"

using namespace trpc;

TEST_CASE(mru_cache_evicts_least_recent) {
  MruCache<std::string, int> c(3);
  c.Put("a", 1);
  c.Put("b", 2);
  c.Put("c", 3);
  EXPECT_EQ(c.size(), 3u);
  // Touch "a" so it is most-recent; inserting "d" must evict "b".
  EXPECT(c.Get("a") != nullptr);
  c.Put("d", 4);
  EXPECT_EQ(c.size(), 3u);
  EXPECT(c.Get("b") == nullptr);
  EXPECT(c.Get("a") != nullptr && *c.Get("a") == 1);
  EXPECT(c.Get("c") != nullptr);
  EXPECT(c.Get("d") != nullptr);
  // Overwrite refreshes both value and recency.
  c.Put("c", 33);
  c.Put("e", 5);  // evicts "a" (oldest after c/d/a ordering... recency:
                  // Get(a),Get(c),Get(d),Put(c)→c,Put(e): oldest is a)
  EXPECT(c.Get("a") == nullptr);
  EXPECT_EQ(*c.Get("c"), 33);
  // Peek does not refresh recency.
  EXPECT(c.Peek("d") != nullptr);
  c.Put("f", 6);  // evicts d (Peek kept it cold)... order: c,e then d
  EXPECT(c.Get("d") == nullptr);
  EXPECT(c.Erase("f"));
  EXPECT(!c.Erase("f"));
  EXPECT_EQ(c.size(), 2u);
}

TEST_CASE(case_ignored_map_canonicalizes) {
  CaseIgnoredFlatMap<std::string> h;
  h["Content-Length"] = "42";
  h["X-Trace-ID"] = "abc";
  EXPECT_EQ(h.size(), 2u);
  EXPECT(h.seek("content-length") != nullptr);
  EXPECT(*h.seek("CONTENT-LENGTH") == "42");
  h["content-LENGTH"] = "7";  // same key, overwrite
  EXPECT_EQ(h.size(), 2u);
  EXPECT(*h.seek("Content-Length") == "7");
  std::set<std::string> keys;
  h.for_each([&](const std::string& k, const std::string&) {
    keys.insert(k);
  });
  EXPECT(keys.count("content-length") == 1);
  EXPECT(keys.count("x-trace-id") == 1);
  EXPECT(h.erase("X-TRACE-id"));
  EXPECT(h.seek("x-trace-id") == nullptr);
}

TEST_CASE(bounded_queue_ring) {
  BoundedQueue<int> q(4);
  EXPECT(q.empty());
  EXPECT_EQ(q.capacity(), 4u);
  for (int i = 0; i < 4; ++i) {
    EXPECT(q.push(i));
  }
  EXPECT(q.full());
  EXPECT(!q.push(99));
  int v = -1;
  EXPECT(q.pop(&v));
  EXPECT_EQ(v, 0);
  EXPECT(q.push(4));  // wraps
  // Drain in FIFO order across the wrap point.
  for (int want = 1; want <= 4; ++want) {
    EXPECT(q.pop(&v));
    EXPECT_EQ(v, want);
  }
  EXPECT(q.empty());
  EXPECT(!q.pop(&v));
  // Many laps exercise every ring slot repeatedly.
  for (int lap = 0; lap < 100; ++lap) {
    EXPECT(q.push(lap));
    EXPECT(q.push(lap + 1000));
    EXPECT(q.pop(&v));
    EXPECT_EQ(v, lap);
    EXPECT(q.pop(&v));
    EXPECT_EQ(v, lap + 1000);
  }
}

TEST_CASE(mpsc_queue_concurrent_producers) {
  MpscQueue<uint64_t> q;
  constexpr int kProducers = 4;
  constexpr uint64_t kPerProducer = 20000;
  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&q, p] {
      for (uint64_t i = 0; i < kPerProducer; ++i) {
        q.push((static_cast<uint64_t>(p) << 32) | i);
      }
    });
  }
  // Single consumer: per-producer sequences must arrive in order.
  uint64_t next_expected[kProducers] = {0, 0, 0, 0};
  uint64_t got = 0;
  while (got < kProducers * kPerProducer) {
    uint64_t v;
    if (!q.pop(&v)) {
      std::this_thread::yield();
      continue;
    }
    const int p = static_cast<int>(v >> 32);
    const uint64_t seq = v & 0xffffffffu;
    EXPECT_EQ(seq, next_expected[p]);
    next_expected[p] = seq + 1;
    ++got;
  }
  uint64_t leftover;
  EXPECT(!q.pop(&leftover));
  for (auto& t : producers) {
    t.join();
  }
}

TEST_MAIN
