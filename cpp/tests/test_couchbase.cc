// Couchbase vbucket routing over the memcache binary substrate: hash
// distribution, map-directed routing against nodes that ENFORCE
// ownership, NOT_MY_VBUCKET learning, and full-map installs.
#include <set>
#include <string>
#include <vector>

#include "net/couchbase.h"
#include "net/memcache.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

constexpr int kVb = 64;  // small power-of-two map for tests

struct CbNode {
  Server srv;
  MemcacheService* svc = nullptr;
  std::string addr;
};

// Two nodes enforcing even/odd vbucket ownership.
CbNode* cb_node(int i) {
  static CbNode n[2];
  return &n[i];
}

void start_nodes() {
  if (!cb_node(0)->addr.empty()) {
    return;
  }
  for (int i = 0; i < 2; ++i) {
    CbNode* n = cb_node(i);
    n->svc = new MemcacheService();
    n->svc->set_vbucket_filter(
        [i](uint16_t vb) { return (vb % 2) == static_cast<uint16_t>(i); });
    n->srv.set_memcache_service(n->svc);
    EXPECT_EQ(n->srv.Start(0), 0);
    n->addr = "127.0.0.1:" + std::to_string(n->srv.port());
  }
}

}  // namespace

TEST_CASE(vbucket_hash_spreads_and_is_stable) {
  // Deterministic and masked into range; a few hundred keys should
  // touch a healthy share of a 64-entry map.
  std::set<uint16_t> seen;
  for (int i = 0; i < 400; ++i) {
    const std::string key = "key-" + std::to_string(i);
    const uint16_t vb = couchbase_vbucket_of(key, kVb);
    EXPECT(vb < kVb);
    EXPECT_EQ(vb, couchbase_vbucket_of(key, kVb));
    seen.insert(vb);
  }
  EXPECT(seen.size() > kVb / 2);
}

TEST_CASE(couchbase_routes_by_vbucket_map) {
  start_nodes();
  CouchbaseClient cc;
  CouchbaseClient::Options opts;
  opts.n_vbuckets = kVb;
  EXPECT_EQ(cc.Init({cb_node(0)->addr, cb_node(1)->addr}, &opts), 0);
  // The default map (vb % 2 → node) happens to match the nodes'
  // even/odd enforcement exactly: no probes needed, everything lands.
  for (int i = 0; i < 32; ++i) {
    const std::string key = "k" + std::to_string(i);
    EXPECT(cc.Set(key, "v" + std::to_string(i)).ok());
  }
  for (int i = 0; i < 32; ++i) {
    McResult r = cc.Get("k" + std::to_string(i));
    EXPECT(r.ok());
    EXPECT(r.value == "v" + std::to_string(i));
  }
  // Items really split across the two stores.
  EXPECT(cb_node(0)->svc->item_count() > 0);
  EXPECT(cb_node(1)->svc->item_count() > 0);
  EXPECT_EQ(cb_node(0)->svc->item_count() + cb_node(1)->svc->item_count(),
            32u);
}

TEST_CASE(not_my_vbucket_probes_and_repairs_map) {
  start_nodes();
  CouchbaseClient cc;
  CouchbaseClient::Options opts;
  opts.n_vbuckets = kVb;
  EXPECT_EQ(cc.Init({cb_node(0)->addr, cb_node(1)->addr}, &opts), 0);
  // Install a fully WRONG map (everything → node 0): odd vbuckets
  // bounce with NOT_MY_VBUCKET and must be learned onto node 1.
  EXPECT_EQ(cc.set_vbucket_map(std::vector<int>(kVb, 0)), 0);
  std::string odd_key;
  for (int i = 0; i < 64 && odd_key.empty(); ++i) {
    const std::string key = "probe-" + std::to_string(i);
    if (couchbase_vbucket_of(key, kVb) % 2 == 1) {
      odd_key = key;
    }
  }
  EXPECT(!odd_key.empty());
  const int vb = couchbase_vbucket_of(odd_key, kVb);
  EXPECT_EQ(cc.vbucket_node(vb), 0);  // stale
  EXPECT(cc.Set(odd_key, "found-you").ok());
  EXPECT_EQ(cc.vbucket_node(vb), 1);  // repaired by the probe
  EXPECT(cc.Get(odd_key).value == "found-you");
  // Ops the map now gets right include incr with initial (data op
  // coverage beyond get/set through the vbucket path).
  McResult n = cc.Increment(odd_key + "-ctr", 5, 100);
  EXPECT(n.ok());
  EXPECT_EQ(n.numeric, 100u);
  EXPECT_EQ(cc.Increment(odd_key + "-ctr", 5, 100).numeric, 105u);
}

TEST_CASE(vbucket_map_install_validates) {
  start_nodes();
  CouchbaseClient cc;
  CouchbaseClient::Options opts;
  opts.n_vbuckets = kVb;
  EXPECT_EQ(cc.Init({cb_node(0)->addr, cb_node(1)->addr}, &opts), 0);
  EXPECT_EQ(cc.set_vbucket_map(std::vector<int>(kVb - 1, 0)), -1);  // size
  EXPECT_EQ(cc.set_vbucket_map(std::vector<int>(kVb, 7)), -1);  // range
  // Non-power-of-two maps are rejected at Init.
  CouchbaseClient bad;
  CouchbaseClient::Options bopts;
  bopts.n_vbuckets = 48;
  EXPECT_EQ(bad.Init({cb_node(0)->addr}, &bopts), -1);
}

TEST_MAIN
