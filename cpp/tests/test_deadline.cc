// Deadline & cancellation plane tests (ISSUE 15): wire tail-group 7
// roundtrip + unset-traffic byte identity, server-side shed before
// dispatch (in-flight, injected-dispatch-delay, and QoS-lane queueing),
// handler-visible remaining budget, budget shrinking across proxy hops,
// cascading cancel fan-out to downstream calls and mid-transfer
// one-sided puts (composed with chunk-drop faults), the typed
// kEDeadlineExpired stopping the cluster retry chain, the retry-budget
// token bucket bounding storm amplification, hedge suppression when the
// remaining budget cannot cover the observed p50, and registry hygiene.
#include <unistd.h>

#include <atomic>
#include <string>
#include <vector>

#include "base/flags.h"
#include "base/time.h"
#include "fiber/event.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/cluster.h"
#include "net/controller.h"
#include "net/deadline.h"
#include "net/fault.h"
#include "net/protocol.h"
#include "net/qos.h"
#include "net/rma.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

std::atomic<int> g_echo_execs{0};
std::atomic<int> g_med_execs{0};
std::atomic<int> g_fail_execs{0};
std::atomic<int64_t> g_seen_remaining{-1};

Server* g_server = nullptr;
int g_port = 0;

void register_common(Server* s) {
  s->RegisterMethod(
      "Echo.Echo", [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                      Closure done) {
        g_echo_execs.fetch_add(1, std::memory_order_acq_rel);
        g_seen_remaining.store(cntl->remaining_us(),
                               std::memory_order_release);
        resp->append(req);
        done();
      });
  s->RegisterMethod(
      "Echo.Med", [](Controller*, const IOBuf& req, IOBuf* resp,
                     Closure done) {
        g_med_execs.fetch_add(1, std::memory_order_acq_rel);
        fiber_sleep_us(30 * 1000);
        resp->append(req);
        done();
      });
  s->RegisterMethod(
      "Echo.Med2", [](Controller*, const IOBuf& req, IOBuf* resp,
                      Closure done) {
        fiber_sleep_us(60 * 1000);
        resp->append(req);
        done();
      });
  s->RegisterMethod(
      "Echo.Fail", [](Controller* cntl, const IOBuf&, IOBuf*,
                      Closure done) {
        g_fail_execs.fetch_add(1, std::memory_order_acq_rel);
        cntl->SetFailed(42, "deliberate failure");
        done();
      });
}

void start_server_once() {
  if (g_server != nullptr) {
    return;
  }
  g_server = new Server();
  register_common(g_server);
  EXPECT_EQ(g_server->Start(0), 0);
  g_port = g_server->port();
}

std::string addr() { return "127.0.0.1:" + std::to_string(g_port); }

struct DeadlineDelta {
  int64_t shed, stamped, client_expired, fanout, saved, retry_sup,
      hedge_sup;
  DeadlineDelta() { reset(); }
  void reset() {
    DeadlineVars& v = deadline_vars();
    shed = v.shed_total.get_value();
    stamped = v.stamped_total.get_value();
    client_expired = v.client_expired_total.get_value();
    fanout = v.cancel_fanout_total.get_value();
    saved = v.cancel_saved_bytes.get_value();
    retry_sup = v.retry_suppressed.get_value();
    hedge_sup = v.hedge_suppressed.get_value();
  }
  int64_t d_shed() const {
    return deadline_vars().shed_total.get_value() - shed;
  }
  int64_t d_stamped() const {
    return deadline_vars().stamped_total.get_value() - stamped;
  }
  int64_t d_client_expired() const {
    return deadline_vars().client_expired_total.get_value() -
           client_expired;
  }
  int64_t d_fanout() const {
    return deadline_vars().cancel_fanout_total.get_value() - fanout;
  }
  int64_t d_saved() const {
    return deadline_vars().cancel_saved_bytes.get_value() - saved;
  }
  int64_t d_retry_sup() const {
    return deadline_vars().retry_suppressed.get_value() - retry_sup;
  }
  int64_t d_hedge_sup() const {
    return deadline_vars().hedge_suppressed.get_value() - hedge_sup;
  }
};

void wait_until(const std::function<bool()>& pred, int64_t budget_ms) {
  const int64_t deadline = monotonic_time_us() + budget_ms * 1000;
  while (!pred() && monotonic_time_us() < deadline) {
    usleep(2000);
  }
}

std::string pattern(size_t n, int seed) {
  std::string s(n, 0);
  for (size_t i = 0; i < n; ++i) {
    s[i] = static_cast<char>((i * 131 + seed * 7) & 0xff);
  }
  return s;
}

}  // namespace

// ---- wire ----------------------------------------------------------------

TEST_CASE(wire_roundtrip_and_unset_byte_identity) {
  const Protocol& p = tstd_protocol();
  // Unset traffic: the frame must contain NO optional tail at all —
  // byte-for-byte the pre-deadline-plane layout (fixed fields + method
  // + empty error_text = 38 + 1 + 4 bytes of meta).
  {
    RpcMeta meta;
    meta.type = RpcMeta::kRequest;
    meta.correlation_id = 7;
    meta.method = "M";
    IOBuf frame, payload;
    payload.append("x");
    tstd_pack(&frame, meta, payload);
    char hdr[16];
    EXPECT_EQ(frame.copy_to(hdr, 16), 16u);
    uint32_t meta_len = 0;
    memcpy(&meta_len, hdr + 4, 4);
    EXPECT_EQ(meta_len, 43u);  // no tail groups emitted
    InputMessage msg;
    EXPECT(p.parse(&frame, &msg, nullptr) == ParseError::kOk);
    EXPECT_EQ(msg.meta.deadline_us, 0u);
    EXPECT_EQ(msg.arrival_us, 0);  // unstamped: no clock read either
  }
  // Deadline-only meta: groups 1..7 ride (121B tail), the budget
  // roundtrips exactly, and arrival is stamped at cut.
  {
    RpcMeta meta;
    meta.type = RpcMeta::kRequest;
    meta.correlation_id = 8;
    meta.method = "M";
    meta.deadline_us = 123456;
    IOBuf frame, payload;
    payload.append("x");
    tstd_pack(&frame, meta, payload);
    char hdr[16];
    EXPECT_EQ(frame.copy_to(hdr, 16), 16u);
    uint32_t meta_len = 0;
    memcpy(&meta_len, hdr + 4, 4);
    EXPECT_EQ(meta_len, 43u + 121u);
    const int64_t before = monotonic_time_us();
    InputMessage msg;
    EXPECT(p.parse(&frame, &msg, nullptr) == ParseError::kOk);
    EXPECT_EQ(msg.meta.deadline_us, 123456u);
    EXPECT(msg.arrival_us >= before);
  }
}

TEST_CASE(wire_flag_off_restores_byte_identity) {
  start_server_once();
  EXPECT_EQ(Flag::set("trpc_deadline_wire", "false"), 0);
  DeadlineDelta d;
  Channel ch;
  EXPECT_EQ(ch.Init(addr()), 0);
  Controller cntl;
  cntl.set_timeout_ms(5000);
  IOBuf req, resp;
  req.append("plain");
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT_EQ(d.d_stamped(), 0);  // vars provably frozen with the flag off
  // The handler saw NO deadline.
  EXPECT_EQ(g_seen_remaining.load(std::memory_order_acquire), INT64_MAX);
  EXPECT_EQ(Flag::set("trpc_deadline_wire", "true"), 0);
}

// ---- server enforcement --------------------------------------------------

TEST_CASE(handler_reads_propagated_remaining_budget) {
  start_server_once();
  DeadlineDelta d;
  Channel ch;
  EXPECT_EQ(ch.Init(addr()), 0);
  Controller cntl;
  cntl.set_timeout_ms(500);
  IOBuf req, resp;
  req.append("q");
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT_EQ(d.d_stamped(), 1);
  const int64_t seen = g_seen_remaining.load(std::memory_order_acquire);
  EXPECT(seen > 0);
  EXPECT(seen <= 500 * 1000);
}

TEST_CASE(expired_in_dispatch_delay_shed_never_executed) {
  start_server_once();
  EXPECT_EQ(g_server->SetFaults("seed=1;svr_delay=1:120"), 0);
  DeadlineDelta d;
  const int execs_before = g_echo_execs.load(std::memory_order_acquire);
  Channel ch;
  EXPECT_EQ(ch.Init(addr()), 0);
  Controller cntl;
  cntl.set_timeout_ms(40);  // budget dies inside the injected 120ms delay
  IOBuf req, resp;
  req.append("doomed");
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(cntl.Failed());  // locally: the 40ms timer
  // Server side: the request is SHED post-delay — never half-executed.
  wait_until([&] { return d.d_shed() >= 1; }, 3000);
  EXPECT(d.d_shed() >= 1);
  EXPECT_EQ(g_echo_execs.load(std::memory_order_acquire), execs_before);
  EXPECT_EQ(g_server->SetFaults(""), 0);
}

TEST_CASE(expired_in_qos_lane_shed_before_dispatch) {
  start_server_once();
  EXPECT_EQ(Flag::set("trpc_qos_lanes", "2"), 0);
  qos_test_pause(true);  // stage a backlog: requests queue, undrained
  DeadlineDelta d;
  const int execs_before = g_echo_execs.load(std::memory_order_acquire);
  Channel ch;
  EXPECT_EQ(ch.Init(addr()), 0);
  Controller cntl;
  cntl.set_timeout_ms(40);
  IOBuf req, resp;
  req.append("queued");
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(cntl.Failed());  // timed out while parked in the lane
  usleep(30 * 1000);      // arrival + 40ms is now well past
  qos_test_pause(false);
  // Kick a drain with a fresh (healthy) request.
  Controller kick;
  kick.set_timeout_ms(5000);
  IOBuf req2, resp2;
  req2.append("kick");
  ch.CallMethod("Echo.Echo", req2, &resp2, &kick);
  EXPECT(!kick.Failed());
  wait_until([&] { return d.d_shed() >= 1; }, 3000);
  // The queued-expired request was shed at dispatch (arrival stamped at
  // parse: lane wait counted against the budget), and only the healthy
  // kick executed.
  EXPECT(d.d_shed() >= 1);
  EXPECT_EQ(g_echo_execs.load(std::memory_order_acquire),
            execs_before + 1);
  EXPECT_EQ(Flag::set("trpc_qos_lanes", "0"), 0);
}

TEST_CASE(client_fail_fast_when_ambient_budget_exhausted) {
  start_server_once();
  DeadlineDelta d;
  set_ambient_deadline(monotonic_time_us() - 1);  // already past
  Channel ch;
  EXPECT_EQ(ch.Init(addr()), 0);
  Controller cntl;
  cntl.set_timeout_ms(5000);
  IOBuf req, resp;
  req.append("dead on arrival");
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  set_ambient_deadline(0);
  EXPECT(cntl.Failed());
  EXPECT_EQ(cntl.error_code(), kEDeadlineExpired);
  EXPECT_EQ(d.d_client_expired(), 1);
  EXPECT_EQ(d.d_stamped(), 0);  // never reached the wire
}

TEST_CASE(ambient_bound_expiry_surfaces_typed_error) {
  start_server_once();
  // The ambient budget (60ms) is strictly tighter than the call's own
  // 5s timeout: its expiry is budget exhaustion, surfaced as the TYPED
  // status so retry layers stop the chain.
  EXPECT_EQ(g_server->SetFaults("seed=1;svr_delay=1:250"), 0);
  set_ambient_deadline(monotonic_time_us() + 60 * 1000);
  Channel ch;
  EXPECT_EQ(ch.Init(addr()), 0);
  Controller cntl;
  cntl.set_timeout_ms(5000);
  IOBuf req, resp;
  req.append("x");
  const int64_t t0 = monotonic_time_us();
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  const int64_t dt_ms = (monotonic_time_us() - t0) / 1000;
  set_ambient_deadline(0);
  EXPECT(cntl.Failed());
  EXPECT_EQ(cntl.error_code(), kEDeadlineExpired);
  EXPECT(dt_ms < 250);  // died at the budget, not the hop timeout
  EXPECT_EQ(g_server->SetFaults(""), 0);
}

// ---- propagation across hops ---------------------------------------------

TEST_CASE(proxied_call_restamps_budget_minus_elapsed) {
  start_server_once();
  // Proxy server A: burns ~30ms, then calls the backend (g_server) with
  // a huge own timeout — the WIRE stamp must carry the caller's
  // remaining budget, not the proxy's fresh 10s.
  static std::string backend_addr;
  backend_addr = addr();
  Server proxy;
  proxy.RegisterMethod(
      "Proxy.Echo", [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                       Closure done) {
        fiber_sleep_us(30 * 1000);
        Channel down;
        if (down.Init(backend_addr) != 0) {
          cntl->SetFailed(EINVAL, "init");
          done();
          return;
        }
        Controller dc;
        dc.set_timeout_ms(10000);
        IOBuf dresp;
        down.CallMethod("Echo.Echo", req, &dresp, &dc);
        if (dc.Failed()) {
          cntl->SetFailed(dc.error_code(), dc.error_text());
        } else {
          resp->append(dresp);
        }
        done();
      });
  EXPECT_EQ(proxy.Start(0), 0);
  Channel ch;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(proxy.port())), 0);
  Controller cntl;
  cntl.set_timeout_ms(500);
  IOBuf req, resp;
  req.append("hop");
  ch.CallMethod("Proxy.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  const int64_t seen = g_seen_remaining.load(std::memory_order_acquire);
  // The backend saw the 500ms budget minus the proxy's ~30ms burn (and
  // NOT the proxy's own 10s): decremented-by-elapsed at every hop.
  EXPECT(seen > 0);
  EXPECT(seen < 480 * 1000);
  EXPECT(seen > 100 * 1000);
  proxy.Stop();
  proxy.Join();
}

// ---- cascading cancellation ----------------------------------------------

TEST_CASE(cancel_fans_out_to_downstream_call) {
  start_server_once();
  static std::string backend_addr;
  backend_addr = addr();
  static std::atomic<int> downstream_code{-1};
  static std::atomic<int> downstream_ok{0};
  static std::atomic<int64_t> downstream_ms{-1};
  downstream_code.store(-1, std::memory_order_release);
  downstream_ok.store(0, std::memory_order_release);
  downstream_ms.store(-1, std::memory_order_release);
  // Slow backend method for the downstream leg.
  Server proxy;
  proxy.RegisterMethod(
      "Proxy.Slow", [](Controller* cntl, const IOBuf& req, IOBuf* resp,
                       Closure done) {
        Channel down;
        if (down.Init(backend_addr) != 0) {
          cntl->SetFailed(EINVAL, "init");
          done();
          return;
        }
        Controller dc;
        dc.set_timeout_ms(10000);
        IOBuf dresp;
        IOBuf dreq;
        dreq.append("med");
        const int64_t t0 = monotonic_time_us();
        // Three sequential downstream calls ~90ms total: the cancel
        // lands mid-chain and must abort the in-flight one AND the
        // handler's loop (IsCanceled).
        for (int i = 0; i < 3 && !cntl->IsCanceled(); ++i) {
          dc.Reset();
          down.CallMethod("Echo.Med", dreq, &dresp, &dc);
          if (dc.Failed()) {
            break;
          }
          downstream_ok.fetch_add(1, std::memory_order_acq_rel);
        }
        downstream_code.store(dc.error_code(), std::memory_order_release);
        downstream_ms.store((monotonic_time_us() - t0) / 1000,
                            std::memory_order_release);
        if (dc.Failed()) {
          cntl->SetFailed(dc.error_code(), dc.error_text());
        } else {
          resp->append(dresp);
        }
        done();
      });
  EXPECT_EQ(proxy.Start(0), 0);
  DeadlineDelta d;
  Channel ch;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(proxy.port())), 0);
  Controller cntl;
  cntl.set_timeout_ms(10000);
  IOBuf req, resp;
  req.append("x");
  Event ev;
  ch.CallMethod("Proxy.Slow", req, &resp, &cntl, [&ev] {
    ev.value.fetch_add(1, std::memory_order_release);
    ev.wake_all();
  });
  usleep(40 * 1000);  // mid-chain (first ~30ms downstream in flight)
  cntl.StartCancel();
  wait_until(
      [&] {
        return downstream_ms.load(std::memory_order_acquire) >= 0;
      },
      3000);
  EXPECT(cntl.Failed());
  EXPECT_EQ(cntl.error_code(), ECANCELED);
  // The fan-out aborted the proxy's downstream CHAIN: either the
  // in-flight call died ECANCELED mid-flight, or (slower schedules —
  // TSan — where the cancel lands between calls) the IsCanceled guard
  // cut the loop.  Either way fewer than all 3 legs completed.
  const int code = downstream_code.load(std::memory_order_acquire);
  const int ok_legs = downstream_ok.load(std::memory_order_acquire);
  EXPECT(code == ECANCELED || ok_legs < 3);
  EXPECT(ok_legs < 3);
  EXPECT(d.d_fanout() >= 1);
  proxy.Stop();
  proxy.Join();
}

TEST_CASE(cancel_mid_rma_response_stops_transfer) {
  // A decode-side pull abandoned mid-transfer: the serving side's
  // one-sided put must stop within one chunk budget, not ship the rest.
  static Server* shm_srv = [] {
    auto* s = new Server();
    s->RegisterMethod(
        "Kv.SlowBig", [](Controller*, const IOBuf&, IOBuf* resp,
                         Closure done) {
          fiber_sleep_us(120 * 1000);  // cancel lands while we park
          resp->append(pattern(16 << 20, 5));
          done();
        });
    EXPECT_EQ(s->Start(0), 0);
    return s;
  }();
  Channel ch;
  Channel::Options opts;
  opts.use_shm = true;
  opts.timeout_ms = 60000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(shm_srv->port()), &opts),
            0);
  {
    Controller warm;
    IOBuf req, resp;
    req.append("w");
    ch.CallMethod("Kv.SlowBig", req, &resp, &warm);
    EXPECT(!warm.Failed());
  }
  const size_t cap = 32 << 20;
  uint64_t rkey = 0;
  void* land = rma_alloc(cap, &rkey);
  EXPECT(land != nullptr);
  DeadlineDelta d;
  {
    Controller cntl;
    cntl.set_timeout_ms(60000);
    cntl.call().land_buf = land;
    cntl.call().land_cap = cap;
    IOBuf req, resp;
    req.append("pull");
    Event ev;
    ch.CallMethod("Kv.SlowBig", req, &resp, &cntl, [&ev] {
      ev.value.fetch_add(1, std::memory_order_release);
      ev.wake_all();
    });
    usleep(40 * 1000);   // handler parked server-side
    cntl.StartCancel();  // kCancel frame → scope fires before the put
    wait_until([&] { return d.d_saved() > 0; }, 5000);
    EXPECT(cntl.Failed());
  }
  // At least all-but-one-chunk of the 16MB body was never written.
  EXPECT(d.d_saved() >= (16 << 20) - (4 << 20));
  EXPECT(d.d_fanout() >= 1);
  // The channel still works after the aborted transfer.
  {
    Controller cntl;
    IOBuf req, resp;
    req.append("after");
    ch.CallMethod("Kv.SlowBig", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    EXPECT_EQ(resp.size(), static_cast<size_t>(16 << 20));
  }
  rma_free(land);
}

TEST_CASE(cancel_fanout_composes_with_chunk_drop_faults) {
  // Chaos composition (satellite): cancels racing transfers WHILE the
  // seeded fault actor drops/garbles chunks — whatever the interleaving,
  // nothing crashes, no partial payload is ever admitted, and the
  // channel stays healthy once faults clear.
  start_server_once();
  Channel ch;
  EXPECT_EQ(ch.Init(addr()), 0);
  EXPECT_EQ(g_server->SetFaults("seed=5;svr_delay=0.5:60"), 0);
  EXPECT_EQ(FaultActor::global().set("seed=5;drop=0.15;trunc=0.1"), 0);
  const std::string big = pattern(6 << 20, 11);
  for (int i = 0; i < 8; ++i) {
    Controller cntl;
    cntl.set_timeout_ms(5000);
    IOBuf req, resp;
    req.append(big);
    Event ev;
    ch.CallMethod("Echo.Echo", req, &resp, &cntl, [&ev] {
      ev.value.fetch_add(1, std::memory_order_release);
      ev.wake_all();
    });
    usleep((i % 3) * 15 * 1000);
    cntl.StartCancel();
    const uint32_t snap = ev.value.load(std::memory_order_acquire);
    if (snap == 0) {
      ev.wait(0, monotonic_time_us() + 8 * 1000 * 1000);
    }
    // Whole-or-nothing: success echoes every byte, failure delivers none.
    if (!cntl.Failed()) {
      EXPECT_EQ(resp.size(), big.size());
    } else {
      EXPECT_EQ(resp.size(), 0u);
    }
  }
  FaultActor::global().set("");
  EXPECT_EQ(g_server->SetFaults(""), 0);
  // The last faulted frame may have left truncated residue in a parse
  // buffer, and the old channel's connection may be half-dead in any
  // direction — the recovery contract is that a FRESH connection to the
  // same server works once faults clear.  Short per-attempt timeouts:
  // a poisoned attempt costs one bounded timeout, not the budget.
  bool healed = false;
  for (int i = 0; i < 8 && !healed; ++i) {
    Channel fresh;
    EXPECT_EQ(fresh.Init(addr()), 0);
    Controller cntl;
    cntl.set_timeout_ms(2000);
    IOBuf req, resp;
    req.append("healed");
    fresh.CallMethod("Echo.Echo", req, &resp, &cntl);
    healed = !cntl.Failed() && resp.to_string() == "healed";
  }
  EXPECT(healed);
}

// ---- cluster governance --------------------------------------------------

namespace {

struct TwoNodes {
  Server a, b;
  std::string url;
};

TwoNodes* start_two_nodes() {
  auto* n = new TwoNodes();
  register_common(&n->a);
  register_common(&n->b);
  EXPECT_EQ(n->a.Start(0), 0);
  EXPECT_EQ(n->b.Start(0), 0);
  n->url = "list://127.0.0.1:" + std::to_string(n->a.port()) +
           ",127.0.0.1:" + std::to_string(n->b.port());
  return n;
}

}  // namespace

TEST_CASE(deadline_expired_stops_retry_chain) {
  TwoNodes* n = start_two_nodes();
  ClusterChannel ch;
  ClusterChannel::Options opts;
  opts.timeout_ms = 10000;
  opts.max_retry = 3;
  opts.health_check_method = "";
  EXPECT_EQ(ch.Init(n->url, "rr", &opts), 0);
  // Ambient budget (25ms) < the 30ms handler: the attempt dies with the
  // TYPED code and the chain stops — a dead budget must not burn
  // retries on every node.
  const int before = g_med_execs.load(std::memory_order_acquire);
  set_ambient_deadline(monotonic_time_us() + 25 * 1000);
  Controller cntl;
  IOBuf req, resp;
  req.append("x");
  ch.CallMethod("Echo.Med", req, &resp, &cntl);
  set_ambient_deadline(0);
  EXPECT(cntl.Failed());
  EXPECT_EQ(cntl.error_code(), kEDeadlineExpired);
  usleep(80 * 1000);  // let any (wrong) extra attempts land
  EXPECT_EQ(g_med_execs.load(std::memory_order_acquire), before + 1);
  delete n;
}

TEST_CASE(retry_budget_bounds_storm_amplification) {
  TwoNodes* n = start_two_nodes();
  const auto run_calls = [&](int count) {
    ClusterChannel ch;
    ClusterChannel::Options opts;
    opts.timeout_ms = 2000;
    opts.max_retry = 3;
    opts.health_check_method = "";
    EXPECT_EQ(ch.Init(n->url, "rr", &opts), 0);
    for (int i = 0; i < count; ++i) {
      Controller cntl;
      IOBuf req, resp;
      req.append("x");
      ch.CallMethod("Echo.Fail", req, &resp, &cntl);
      EXPECT(cntl.Failed());
    }
  };
  // Budget OFF: every failed call retries onto the other node — 2.0x
  // attempt amplification (bounded only by the node count here).
  EXPECT_EQ(Flag::set("trpc_cluster_retry_budget_pct", "0"), 0);
  int before = g_fail_execs.load(std::memory_order_acquire);
  run_calls(30);
  const int attempts_off =
      g_fail_execs.load(std::memory_order_acquire) - before;
  EXPECT_EQ(attempts_off, 60);
  // Budget ON (10%): amplification bounded ≤ 1.2x under 100% failure.
  EXPECT_EQ(Flag::set("trpc_cluster_retry_budget_pct", "10"), 0);
  DeadlineDelta d;
  before = g_fail_execs.load(std::memory_order_acquire);
  run_calls(30);
  const int attempts_on =
      g_fail_execs.load(std::memory_order_acquire) - before;
  EXPECT(attempts_on >= 30);
  EXPECT(attempts_on <= 36);  // ≤ 1.2x of 30 primaries
  EXPECT(d.d_retry_sup() >= 24);
  EXPECT_EQ(Flag::set("trpc_cluster_retry_budget_pct", "0"), 0);
  delete n;
}

TEST_CASE(hedge_suppressed_when_budget_cannot_cover_p50) {
  TwoNodes* n = start_two_nodes();
  ClusterChannel ch;
  ClusterChannel::Options opts;
  opts.timeout_ms = 2000;
  opts.max_retry = 0;
  opts.backup_request_ms = 10;
  opts.health_check_method = "";
  EXPECT_EQ(ch.Init(n->url, "rr", &opts), 0);
  // Warm the cluster's p50 estimate with ~60ms calls (the 10ms hedge
  // trigger fires on each, which is fine — the remaining 2s covers
  // them, so they launch and feed the estimate).
  for (int i = 0; i < 6; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("warm");
    ch.CallMethod("Echo.Med2", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  // Tight call on the FASTER (30ms) method: at hedge-arm time (~10ms
  // in) the remaining ~35ms budget cannot cover the observed ~60ms p50
  // — the hedge is suppressed; the primary still answers inside its
  // own budget.
  DeadlineDelta d;
  const int before = g_med_execs.load(std::memory_order_acquire);
  Controller cntl;
  cntl.set_timeout_ms(45);
  IOBuf req, resp;
  req.append("tight");
  ch.CallMethod("Echo.Med", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT(d.d_hedge_sup() >= 1);
  usleep(60 * 1000);
  EXPECT_EQ(g_med_execs.load(std::memory_order_acquire),
            before + 1);  // no second attempt ever launched
  delete n;
}

// ---- hygiene -------------------------------------------------------------

TEST_CASE(cancel_registry_drains_to_zero) {
  // Every dispatched request above unregistered its scope; slow
  // handlers (Echo.Slow-style parks) get a bounded grace.
  wait_until([] { return cancel_registered() == 0; }, 5000);
  EXPECT_EQ(cancel_registered(), 0u);
}

TEST_MAIN
