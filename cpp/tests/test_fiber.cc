// L2 fiber runtime unit tests (parity model: the reference's
// test/bthread_*_unittest.cpp matrix — start/join, butex, mutex, sleep,
// work stealing, fls).
#include <unistd.h>

#include <atomic>
#include <mutex>
#include <thread>
#include <vector>

#include "base/time.h"
#include "fiber/event.h"
#include "fiber/execution_queue.h"
#include <sched.h>
#include <sys/epoll.h>

#include "fiber/fiber.h"
#include "fiber/fid.h"
#include "fiber/sync.h"
#include "fiber/timer.h"
#include "tests/test_util.h"

using namespace trpc;

TEST_CASE(start_and_join) {
  fiber_init(4);
  static std::atomic<int> ran{0};
  fiber_t f;
  EXPECT_EQ(fiber_start(&f, [](void*) { ran.fetch_add(1); }, nullptr), 0);
  EXPECT_EQ(fiber_join(f), 0);
  EXPECT_EQ(ran.load(), 1);
  EXPECT(!fiber_exists(f));
  EXPECT_EQ(fiber_join(f), 0);  // joining a finished fiber is a no-op
}

TEST_CASE(many_fibers) {
  static std::atomic<int> count{0};
  count = 0;
  std::vector<fiber_t> ids(2000);
  for (auto& f : ids) {
    EXPECT_EQ(fiber_start(&f, [](void*) { count.fetch_add(1); }, nullptr), 0);
  }
  for (auto& f : ids) {
    fiber_join(f);
  }
  EXPECT_EQ(count.load(), 2000);
}

TEST_CASE(yield_interleaves) {
  static std::atomic<int> progress{0};
  fiber_t f;
  fiber_start(&f, [](void*) {
    for (int i = 0; i < 10; ++i) {
      progress.fetch_add(1);
      fiber_yield();
    }
  }, nullptr);
  fiber_join(f);
  EXPECT_EQ(progress.load(), 10);
}

TEST_CASE(nested_fibers) {
  static std::atomic<int> total{0};
  total = 0;
  fiber_t f;
  fiber_start(&f, [](void*) {
    fiber_t inner[10];
    for (auto& g : inner) {
      fiber_start(&g, [](void*) { total.fetch_add(1); }, nullptr);
    }
    for (auto& g : inner) {
      fiber_join(g);  // join from inside a fiber parks, not blocks
    }
    total.fetch_add(100);
  }, nullptr);
  fiber_join(f);
  EXPECT_EQ(total.load(), 110);
}

TEST_CASE(sleep_wakes_on_time) {
  static std::atomic<int64_t> slept_us{0};
  fiber_t f;
  fiber_start(&f, [](void*) {
    const int64_t t0 = monotonic_time_us();
    fiber_sleep_us(20000);
    slept_us.store(monotonic_time_us() - t0);
  }, nullptr);
  fiber_join(f);
  EXPECT(slept_us.load() >= 19000);
  EXPECT(slept_us.load() < 500000);
}

TEST_CASE(event_wake_from_pthread) {
  static Event ev;
  static std::atomic<int> woke{0};
  ev.value.store(0);
  woke = 0;
  fiber_t f;
  fiber_start(&f, [](void*) {
    while (ev.value.load() == 0) {
      ev.wait(0, -1);
    }
    woke.fetch_add(1);
  }, nullptr);
  usleep(20000);
  EXPECT_EQ(woke.load(), 0);  // parked, not finished
  ev.value.store(1);
  ev.wake_all();
  fiber_join(f);
  EXPECT_EQ(woke.load(), 1);
}

TEST_CASE(event_pthread_waiter) {
  static Event ev;
  ev.value.store(0);
  std::thread waker([&] {
    usleep(10000);
    ev.value.store(7);
    ev.wake_all();
  });
  while (ev.value.load() == 0) {
    const int rc = ev.wait(0, -1);  // pthread path (not on a fiber)
    (void)rc;
  }
  EXPECT_EQ(ev.value.load(), 7u);
  waker.join();
}

TEST_CASE(event_timeout) {
  static Event ev;
  ev.value.store(0);
  // pthread path
  const int64_t t0 = monotonic_time_us();
  const int rc = ev.wait(0, monotonic_time_us() + 30000);
  EXPECT_EQ(rc, ETIMEDOUT);
  EXPECT(monotonic_time_us() - t0 >= 29000);
  // fiber path
  static std::atomic<int> frc{-1};
  fiber_t f;
  fiber_start(&f, [](void*) {
    frc.store(ev.wait(0, monotonic_time_us() + 30000));
  }, nullptr);
  fiber_join(f);
  EXPECT_EQ(frc.load(), ETIMEDOUT);
}

TEST_CASE(fiber_mutex_contention) {
  static FiberMutex mu;
  static int counter = 0;
  counter = 0;
  std::vector<fiber_t> ids(64);
  for (auto& f : ids) {
    fiber_start(&f, [](void*) {
      for (int i = 0; i < 100; ++i) {
        LockGuard<FiberMutex> g(mu);
        counter += 1;  // data race iff mutex broken
      }
    }, nullptr);
  }
  for (auto& f : ids) {
    fiber_join(f);
  }
  EXPECT_EQ(counter, 6400);
}

TEST_CASE(countdown_event) {
  static CountdownEvent latch(5);
  for (int i = 0; i < 5; ++i) {
    fiber_t f;
    fiber_start(&f, [](void*) { latch.signal(); }, nullptr);
  }
  EXPECT_EQ(latch.wait(monotonic_time_us() + 1000000), 0);
}

TEST_CASE(timer_fires_and_cancels) {
  static std::atomic<int> fired{0};
  fired = 0;
  TimerThread::instance()->schedule(monotonic_time_us() + 10000,
                                    [](void*) { fired.fetch_add(1); },
                                    nullptr);
  const uint64_t id2 = TimerThread::instance()->schedule(
      monotonic_time_us() + 10000, [](void*) { fired.fetch_add(100); },
      nullptr);
  EXPECT(TimerThread::instance()->unschedule(id2));
  usleep(60000);
  EXPECT_EQ(fired.load(), 1);
  EXPECT(!TimerThread::instance()->unschedule(id2));  // already gone
}

TEST_CASE(fls_basic) {
  static fls_key_t key;
  static std::atomic<int> dtor_runs{0};
  EXPECT_EQ(fls_key_create(&key, [](void* v) {
    dtor_runs.fetch_add(static_cast<int>(reinterpret_cast<intptr_t>(v)));
  }), 0);
  fiber_t f;
  fiber_start(&f, [](void*) {
    EXPECT(fls_get(key) == nullptr);
    fls_set(key, reinterpret_cast<void*>(7));
    fiber_yield();  // survives suspension
    EXPECT(fls_get(key) == reinterpret_cast<void*>(7));
  }, nullptr);
  fiber_join(f);
  EXPECT_EQ(dtor_runs.load(), 7);  // destructor ran at fiber exit
  EXPECT_EQ(fls_key_delete(key), 0);
  EXPECT_EQ(fls_key_delete(key), -1);  // stale key rejected
}

TEST_CASE(execution_queue_serializes) {
  static ExecutionQueue<int> q;
  static std::vector<int> seen;
  static FiberMutex seen_mu;
  seen.clear();
  q.start(
      [](void*, int* items, size_t n) -> int {
        LockGuard<FiberMutex> g(seen_mu);
        for (size_t i = 0; i < n; ++i) {
          seen.push_back(items[i]);
        }
        return 0;
      },
      nullptr);
  std::vector<std::thread> producers;
  for (int t = 0; t < 4; ++t) {
    producers.emplace_back([t] {
      for (int i = 0; i < 100; ++i) {
        q.execute(t * 1000 + i);
      }
    });
  }
  for (auto& th : producers) {
    th.join();
  }
  for (int spin = 0; spin < 1000 && !q.idle(); ++spin) {
    usleep(1000);
  }
  EXPECT(q.idle());
  EXPECT_EQ(seen.size(), 400u);
  // Per-producer FIFO order must be preserved.
  int last[4] = {-1, -1, -1, -1};
  for (int v : seen) {
    const int t = v / 1000;
    EXPECT(v % 1000 > last[t]);
    last[t] = v % 1000;
  }
}

TEST_CASE(fid_lifecycle) {
  fid_t id;
  static std::atomic<int> errors{0};
  EXPECT_EQ(fid_create(&id, reinterpret_cast<void*>(0x42),
                       [](fid_t i, void*, int code) -> int {
                         errors.fetch_add(code);
                         return fid_unlock_and_destroy(i);
                       }),
            0);
  EXPECT(fid_exists(id));
  void* data = nullptr;
  EXPECT_EQ(fid_lock(id, &data), 0);
  EXPECT(data == reinterpret_cast<void*>(0x42));
  EXPECT_EQ(fid_unlock(id), 0);

  // join from a fiber while another errors the id.
  static fid_t shared_id;
  shared_id = id;
  fiber_t joiner;
  static std::atomic<bool> joined{false};
  joined = false;
  fiber_start(&joiner, [](void*) {
    fid_join(shared_id);
    joined.store(true);
  }, nullptr);
  usleep(20000);
  EXPECT(!joined.load());
  EXPECT_EQ(fid_error(id, 5), 0);  // on_error destroys
  fiber_join(joiner);
  EXPECT(joined.load());
  EXPECT_EQ(errors.load(), 5);
  EXPECT(!fid_exists(id));
  EXPECT_EQ(fid_lock(id, &data), EINVAL);  // stale id rejected
  EXPECT_EQ(fid_join(id), 0);              // joining dead id returns
}

TEST_CASE(cross_thread_start) {
  // Fibers startable from plain pthreads (remote queue path).
  static std::atomic<int> done{0};
  done = 0;
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([] {
      for (int i = 0; i < 50; ++i) {
        fiber_t f;
        EXPECT_EQ(fiber_start(&f, [](void*) { done.fetch_add(1); }, nullptr),
                  0);
        fiber_join(f);
      }
    });
  }
  for (auto& th : threads) {
    th.join();
  }
  EXPECT_EQ(done.load(), 200);
}

TEST_CASE(fiber_interrupt_wakes_parked_fiber) {
  static Event never;
  static std::atomic<int> rc_seen{-1};
  fiber_t f;
  fiber_start(&f, [](void*) {
    rc_seen.store(never.wait(0, -1));  // parks forever unless interrupted
  }, nullptr);
  fiber_sleep_us(20000);  // let it park
  EXPECT_EQ(fiber_interrupt(f), 0);
  EXPECT_EQ(fiber_join(f), 0);
  EXPECT_EQ(rc_seen.load(), EINTR);
  // Interrupting a dead fiber: ESRCH.
  EXPECT_EQ(fiber_interrupt(f), ESRCH);
  // Interrupt BEFORE the park: the pending flag makes the very next wait
  // return EINTR promptly (the publish-after-switch path re-checks it).
  static Event never2;
  static std::atomic<int> rc2{-1};
  static std::atomic<bool> go{false};
  fiber_t g;
  fiber_start(&g, [](void*) {
    while (!go.load(std::memory_order_acquire)) {
      sched_yield();  // runnable, NOT parked — parked_on stays null
    }
    rc2.store(never2.wait(0, -1));
  }, nullptr);
  fiber_sleep_us(10000);  // the fiber is spinning now
  EXPECT_EQ(fiber_interrupt(g), 0);  // flag set while runnable
  go.store(true, std::memory_order_release);
  EXPECT_EQ(fiber_join(g), 0);
  EXPECT_EQ(rc2.load(), EINTR);
}

TEST_CASE(fiber_fd_wait_readiness_and_timeout) {
  int fds[2];
  EXPECT_EQ(pipe(fds), 0);
  // Timeout first: nothing readable.
  static int pipe_rd = fds[0];
  static int pipe_wr = fds[1];
  static std::atomic<int> got{-2};
  fiber_t f;
  fiber_start(&f, [](void*) {
    got.store(fiber_fd_wait(pipe_rd, EPOLLIN,
                            monotonic_time_us() + 50 * 1000));
  }, nullptr);
  fiber_join(f);
  EXPECT_EQ(got.load(), -1);  // timed out
  // Readiness: a writer fiber makes the fd readable while we park.
  fiber_t r, w;
  static std::atomic<int> revents{0};
  fiber_start(&r, [](void*) {
    revents.store(fiber_fd_wait(pipe_rd, EPOLLIN,
                                monotonic_time_us() + 2000 * 1000));
  }, nullptr);
  fiber_start(&w, [](void*) {
    fiber_sleep_us(30000);
    EXPECT(write(pipe_wr, "x", 1) == 1);
  }, nullptr);
  fiber_join(r);
  fiber_join(w);
  EXPECT((revents.load() & EPOLLIN) != 0);
  close(fds[0]);
  close(fds[1]);
}

namespace {

// Three deliberately-named frames so the parked-stack unwind has a
// recognizable chain to find.  noinline keeps them distinct frames.
__attribute__((noinline)) void tracer_leaf(Event* ev) {
  ev->wait(0, -1);
  asm volatile("");  // keep the call below us a real frame, not a tail call
}

__attribute__((noinline)) void tracer_mid(Event* ev) {
  tracer_leaf(ev);
  asm volatile("");
}

Event* g_tracer_ev = nullptr;

void tracer_entry(void*) { tracer_mid(g_tracer_ev); }

}  // namespace

TEST_CASE(fiber_dump_unwinds_parked_stacks) {
  Event ev;
  g_tracer_ev = &ev;
  fiber_t f;
  EXPECT_EQ(fiber_start(&f, tracer_entry, nullptr, 0), 0);
  // Wait until the fiber is parked on the event.
  for (int spin = 0; spin < 1000; ++spin) {
    if (fiber_dump_all(200).find("parked") != std::string::npos) {
      break;
    }
    usleep(1000);
  }
  const std::string dump = fiber_dump_all(200, /*stacks=*/true);
  // The unwind walks leaf-ward frames of the parked fiber; the named
  // chain must appear (dladdr sees these — the test binary exports
  // dynamic symbols via -rdynamic... it may not, so accept the
  // module+offset fallback by requiring at least two stack frames).
  const size_t first = dump.find("    #0 ");
  EXPECT(first != std::string::npos);
  EXPECT(dump.find("    #1 ", first) != std::string::npos);
  ev.value.store(1);
  ev.wake_all();
  EXPECT_EQ(fiber_join(f), 0);
}

namespace {

struct TagProbe {
  std::atomic<int> seen_tag{-1};
  std::atomic<int> child_tag{-1};
};

void tag_child(void* p) {
  static_cast<TagProbe*>(p)->child_tag.store(fiber_current_tag());
}

void tag_probe_fiber(void* p) {
  auto* t = static_cast<TagProbe*>(p);
  t->seen_tag.store(fiber_current_tag());
  // Untagged spawn from a tagged worker INHERITS the tag.
  fiber_t c;
  fiber_start(&c, &tag_child, t, 0);
  fiber_join(c);
}

struct SpinCtx {
  std::atomic<bool>* stop;
};

void spin_fiber(void* p) {
  // Pthread-level busy spin: hogs the WORKER, not just the fiber — the
  // saturation a tag must contain.
  auto* c = static_cast<SpinCtx*>(p);
  while (!c->stop->load(std::memory_order_relaxed)) {
  }
}

void quick_flag_fiber(void* p) {
  static_cast<std::atomic<bool>*>(p)->store(true);
}

}  // namespace

TEST_CASE(worker_tags_pin_and_inherit) {
  fiber_init(0);
  EXPECT_EQ(fiber_start_tag_workers(1, 2), 0);
  EXPECT_EQ(fiber_worker_count_tag(1), 2);
  EXPECT_EQ(fiber_start_tag_workers(kMaxFiberTags, 2), EINVAL);
  TagProbe probe;
  fiber_t f;
  EXPECT_EQ(fiber_start(&f, &tag_probe_fiber, &probe, fiber_tag_flags(1)), 0);
  fiber_join(f);
  EXPECT_EQ(probe.seen_tag.load(), 1);
  EXPECT_EQ(probe.child_tag.load(), 1);  // inherited, not defaulted to 0
}

namespace bulkns {
std::atomic<int> bulk_count{0};
void bulk_count_fiber(void*) { bulk_count.fetch_add(1); }

std::mutex order_mu;
std::vector<long> order_seen;
void bulk_order_fiber(void* arg) {
  std::lock_guard<std::mutex> g(order_mu);
  order_seen.push_back(reinterpret_cast<long>(arg));
}
}  // namespace bulkns

TEST_CASE(bulk_start_runs_all) {
  fiber_init(0);
  bulkns::bulk_count = 0;
  constexpr size_t kN = 1000;
  std::vector<void*> args(kN, nullptr);
  // One publish per internal stride instead of kN signals; every fiber
  // must still run (none lost in a queue with no wakeup).
  EXPECT_EQ(fiber_start_batch(&bulkns::bulk_count_fiber, args.data(), kN),
            kN);
  const int64_t deadline = monotonic_time_us() + 10 * 1000 * 1000;
  while (bulkns::bulk_count.load() < static_cast<int>(kN) &&
         monotonic_time_us() < deadline) {
    usleep(1000);
  }
  EXPECT_EQ(bulkns::bulk_count.load(), static_cast<int>(kN));
  uint64_t batches = 0, fibers = 0, maxb = 0;
  fiber_bulk_wake_stats(&batches, &fibers, &maxb);
  EXPECT(batches >= 1);
  EXPECT(fibers >= kN);
  EXPECT(maxb >= 2);
}

TEST_CASE(bulk_start_preserves_enqueue_order) {
  fiber_init(0);
  // One worker in tag 3, batch published from a NON-worker thread → the
  // remote queue drains FIFO on a single thread: batched fibers run
  // exactly in args order.  (This is the documented FIFO recipe; a
  // worker-local publish pops its own queue LIFO, which is why batched
  // message dispatch only ever batches order-insensitive messages.)
  EXPECT_EQ(fiber_start_tag_workers(3, 1), 0);
  EXPECT_EQ(fiber_worker_count_tag(3), 1);
  bulkns::order_seen.clear();
  constexpr long kN = 200;
  std::vector<void*> args(kN);
  for (long i = 0; i < kN; ++i) {
    args[i] = reinterpret_cast<void*>(i);
  }
  EXPECT_EQ(fiber_start_batch(&bulkns::bulk_order_fiber, args.data(), kN,
                              fiber_tag_flags(3)),
            static_cast<size_t>(kN));
  const int64_t deadline = monotonic_time_us() + 10 * 1000 * 1000;
  while (monotonic_time_us() < deadline) {
    std::lock_guard<std::mutex> g(bulkns::order_mu);
    if (bulkns::order_seen.size() == static_cast<size_t>(kN)) {
      break;
    }
  }
  std::lock_guard<std::mutex> g(bulkns::order_mu);
  EXPECT_EQ(bulkns::order_seen.size(), static_cast<size_t>(kN));
  for (long i = 0; i < kN; ++i) {
    EXPECT_EQ(bulkns::order_seen[i], i);
  }
}

TEST_CASE(bulk_start_wakes_parked_workers) {
  fiber_init(0);
  // Let every worker park, then publish a batch with its single signal:
  // all fibers must still run promptly (the one-futex wake reaches
  // enough workers; nothing relies on per-spawn signals).
  usleep(100 * 1000);
  bulkns::bulk_count = 0;
  constexpr size_t kN = 64;
  std::vector<void*> args(kN, nullptr);
  const int64_t t0 = monotonic_time_us();
  EXPECT_EQ(fiber_start_batch(&bulkns::bulk_count_fiber, args.data(), kN),
            kN);
  const int64_t deadline = monotonic_time_us() + 5 * 1000 * 1000;
  while (bulkns::bulk_count.load() < static_cast<int>(kN) &&
         monotonic_time_us() < deadline) {
    usleep(1000);
  }
  EXPECT_EQ(bulkns::bulk_count.load(), static_cast<int>(kN));
  EXPECT(monotonic_time_us() - t0 < 5 * 1000 * 1000);
}

TEST_CASE(worker_tags_isolate_saturation) {
  fiber_init(0);
  // Saturate tag 2 (2 workers) with pthread-level spinners; a tag-0 fiber
  // must still run promptly — per-tag groups don't poach or share queues.
  EXPECT_EQ(fiber_start_tag_workers(2, 2), 0);
  std::atomic<bool> stop{false};
  SpinCtx ctx{&stop};
  fiber_t spinners[8];
  for (auto& s : spinners) {
    EXPECT_EQ(fiber_start(&s, &spin_fiber, &ctx, fiber_tag_flags(2)), 0);
  }
  usleep(50 * 1000);  // let the spinners occupy (and overcommit) tag 2
  std::atomic<bool> ran{false};
  fiber_t q;
  const int64_t t0 = monotonic_time_us();
  EXPECT_EQ(fiber_start(&q, &quick_flag_fiber, &ran, 0), 0);
  fiber_join(q);
  const int64_t dt = monotonic_time_us() - t0;
  EXPECT(ran.load());
  EXPECT(dt < 1000 * 1000);  // far below the spinners' lifetime
  stop.store(true);
  for (auto& s : spinners) {
    fiber_join(s);
  }
}

TEST_MAIN
