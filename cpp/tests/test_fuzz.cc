// Deterministic mutation fuzzing of the untrusted-byte parsers.
//
// Parity: the reference ships 18 libFuzzer targets (/root/reference/test/
// fuzzing/: fuzz_baidu_rpc, fuzz_http, fuzz_hpack, ...).  This image's
// GCC has no libFuzzer, so this is the same idea as a deterministic
// harness: seed corpus of valid messages, structure-aware mutations
// (bit flips, truncations, splices, length-field corruption) from a
// fixed-seed xorshift, run under the ASan build in CI.  Every input must
// parse without crashing and uphold the parser invariants; kCorrupted /
// kNotEnoughData are both fine answers.
#include <cstring>
#include <string>
#include <vector>

#include "base/iobuf.h"
#include "base/pbwire.h"
#include "net/http_message.h"
#include "net/redis.h"
#include "net/protocol.h"
#include "net/thrift.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

uint64_t g_rng = 0x9e3779b97f4a7c15ull;  // fixed seed: runs are repeatable

uint64_t rng() {
  g_rng ^= g_rng << 13;
  g_rng ^= g_rng >> 7;
  g_rng ^= g_rng << 17;
  return g_rng;
}

std::string mutate(const std::string& base) {
  std::string m = base;
  switch (rng() % 6) {
    case 0: {  // bit flip(s)
      for (int i = 0; i < 1 + static_cast<int>(rng() % 8); ++i) {
        if (!m.empty()) {
          m[rng() % m.size()] ^= static_cast<char>(1 << (rng() % 8));
        }
      }
      break;
    }
    case 1:  // truncate
      m.resize(rng() % (m.size() + 1));
      break;
    case 2: {  // splice two random halves
      const size_t cut = m.empty() ? 0 : rng() % m.size();
      m = m.substr(cut) + m.substr(0, cut);
      break;
    }
    case 3: {  // stomp a 4-byte window with a hostile length
      if (m.size() >= 4) {
        const uint32_t evil =
            (rng() % 2) ? 0xffffffffu : static_cast<uint32_t>(rng());
        memcpy(m.data() + rng() % (m.size() - 3), &evil, 4);
      }
      break;
    }
    case 4: {  // insert garbage
      const size_t at = m.empty() ? 0 : rng() % m.size();
      std::string junk;
      for (int i = 0; i < static_cast<int>(rng() % 32); ++i) {
        junk.push_back(static_cast<char>(rng()));
      }
      m.insert(at, junk);
      break;
    }
    case 5:  // pure noise
      m.clear();
      for (int i = 0; i < static_cast<int>(rng() % 256); ++i) {
        m.push_back(static_cast<char>(rng()));
      }
      break;
  }
  return m;
}

std::vector<std::string> tstd_corpus() {
  std::vector<std::string> out;
  for (int variant = 0; variant < 4; ++variant) {
    RpcMeta meta;
    meta.type = variant % 2 == 0 ? RpcMeta::kRequest : RpcMeta::kResponse;
    meta.correlation_id = 0x1234 + variant;
    meta.method = "Svc.Method";
    if (variant == 1) {
      meta.error_code = 42;
      meta.error_text = "deliberate";
    }
    if (variant == 2) {
      meta.trace_id = 0xabcdef;
      meta.span_id = 0x1111;
      meta.compress_type = 1;
      meta.has_checksum = true;
      meta.checksum = 0xdeadbeef;
    }
    if (variant == 3) {
      meta.type = RpcMeta::kStreamFrame;
      meta.stream_id = 7;
      meta.ack_bytes = 1 << 20;
    }
    IOBuf frame;
    IOBuf payload;
    payload.append(std::string(32 + variant * 100, 'x'));
    tstd_pack(&frame, meta, payload);
    out.push_back(frame.to_string());
  }
  return out;
}

std::vector<std::string> http_corpus() {
  return {
      "GET /vars HTTP/1.1\r\nHost: a\r\n\r\n",
      "POST /Echo.Echo HTTP/1.1\r\nHost: a\r\nContent-Length: 5\r\n\r\n"
      "hello",
      "POST /x HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
      "4\r\nWiki\r\n0\r\nX-T: v\r\n\r\n",
      "GET /flags/a?setvalue=%31+2&k HTTP/1.0\r\nConnection: "
      "keep-alive\r\n\r\n",
      "HEAD /health#frag HTTP/1.1\r\nA: b\r\nC: d\r\n\r\n",
  };
}

}  // namespace

TEST_CASE(fuzz_tstd_parser) {
  const auto corpus = tstd_corpus();
  for (int iter = 0; iter < 60000; ++iter) {
    const std::string input = mutate(corpus[rng() % corpus.size()]);
    IOBuf buf;
    buf.append(input);
    InputMessage msg;
    const size_t before = buf.size();
    const ParseError rc = tstd_protocol().parse(&buf, &msg, nullptr);
    // Invariants: never consume on NotEnoughData; never grow the buffer.
    if (rc == ParseError::kNotEnoughData) {
      EXPECT_EQ(buf.size(), before);
    }
    EXPECT(buf.size() <= before);
  }
}

TEST_CASE(fuzz_http_parser) {
  const auto corpus = http_corpus();
  for (int iter = 0; iter < 40000; ++iter) {
    const std::string input = mutate(corpus[rng() % corpus.size()]);
    IOBuf buf;
    buf.append(input);
    HttpRequest req;
    IOBuf body;
    const size_t before = buf.size();
    const ParseError rc = http_parse_request(&buf, &req, &body);
    if (rc == ParseError::kNotEnoughData) {
      EXPECT_EQ(buf.size(), before);
    }
    EXPECT(buf.size() <= before);
  }
}

TEST_CASE(fuzz_http_trickled_state) {
  // The resumable chunked path: feed each (mutated) input in random-sized
  // slices against one persistent state slot, as a socket would.
  const auto corpus = http_corpus();
  for (int iter = 0; iter < 4000; ++iter) {
    const std::string input = mutate(corpus[2]);  // chunked seed
    IOBuf buf;
    std::shared_ptr<void> state;
    size_t off = 0;
    while (off < input.size()) {
      const size_t n =
          std::min<size_t>(1 + rng() % 16, input.size() - off);
      buf.append(input.data() + off, n);
      off += n;
      HttpRequest req;
      IOBuf body;
      const ParseError rc = http_parse_request(&buf, &req, &body, &state);
      if (rc == ParseError::kOk || rc == ParseError::kCorrupted) {
        break;
      }
    }
  }
}

namespace {

std::vector<std::string> resp_corpus() {
  std::vector<std::string> seeds;
  // Command form (server side): arrays of bulk strings.
  std::string c1;
  resp_pack_command({"SET", "key", "value"}, &c1);
  std::string c2;
  resp_pack_command({"MSET", std::string(300, 'k'), std::string(1000, 'v'),
                     "k2", ""},
                    &c2);
  seeds.push_back(c1);
  seeds.push_back(c2);
  // Reply form (client side): every type + nesting.
  RedisReply r = RedisReply::Array({
      RedisReply::Status("OK"),
      RedisReply::Error("ERR x"),
      RedisReply::Integer(-9223372036854775807ll),
      RedisReply::Bulk(std::string(512, 'b')),
      RedisReply::Nil(),
      RedisReply::Array({RedisReply::Array({RedisReply::Integer(1)})}),
  });
  std::string rep;
  r.serialize(&rep);
  seeds.push_back(rep);
  return seeds;
}

}  // namespace

TEST_CASE(fuzz_resp_parsers) {
  const auto corpus = resp_corpus();
  for (int iter = 0; iter < 40000; ++iter) {
    const std::string input = mutate(corpus[rng() % corpus.size()]);
    // Command parser: must terminate with 1/0/-1 and never read past the
    // buffer (ASan build enforces); pos only advances on success.
    {
      std::vector<std::string> args;
      size_t pos = 0;
      const int rc = resp_parse_command(input, &pos, &args);
      EXPECT(rc >= -1 && rc <= 1);
      if (rc != 1) {
        EXPECT_EQ(pos, 0u);
      } else {
        EXPECT(pos <= input.size());
      }
    }
    // Reply parser: same contract, plus bounded recursion on hostile
    // nesting depth.
    {
      RedisReply reply;
      size_t pos = 0;
      const int rc = resp_parse_reply(input, &pos, &reply);
      EXPECT(rc >= -1 && rc <= 1);
      if (rc == 1) {
        EXPECT(pos <= input.size());
      }
    }
  }
  // Deep-nesting bomb: 64 levels of "*1\r\n" must be rejected, not
  // recursed into.
  std::string bomb;
  for (int i = 0; i < 64; ++i) {
    bomb += "*1\r\n";
  }
  bomb += ":1\r\n";
  RedisReply reply;
  size_t pos = 0;
  EXPECT_EQ(resp_parse_reply(bomb, &pos, &reply), -1);
}

TEST_CASE(fuzz_pbwire_parser) {
  // Corpus: the golden meta shapes the legacy pbrpc protocols exchange.
  std::vector<std::string> corpus;
  {
    PbMessage m;
    m.add_bytes(1, "EchoService");
    m.add_varint(2, 3);
    m.add_sint(3, -99);
    PbMessage inner;
    inner.add_bytes(1, std::string(200, 'n'));
    m.add_message(4, inner);
    m.add_fixed64(5, 0x1122334455667788ULL);
    m.add_fixed32(6, 0xabcdef01u);
    corpus.push_back(m.serialize());
  }
  for (int iter = 0; iter < 40000; ++iter) {
    const std::string input = mutate(corpus[rng() % corpus.size()]);
    PbMessage m;
    if (m.parse(input)) {
      // Parse success implies a semantic fixpoint: re-serializing and
      // re-parsing yields the same field list.  (Byte equality does NOT
      // hold — the parser accepts overlong varints, the serializer only
      // emits minimal ones.)
      const std::string round = m.serialize();
      PbMessage m2;
      EXPECT(m2.parse(round));
      EXPECT_EQ(m2.fields().size(), m.fields().size());
      for (size_t i = 0; i < m.fields().size(); ++i) {
        EXPECT_EQ(m2.fields()[i].num, m.fields()[i].num);
        EXPECT(m2.fields()[i].wire == m.fields()[i].wire);
        EXPECT_EQ(m2.fields()[i].varint, m.fields()[i].varint);
        EXPECT(m2.fields()[i].bytes == m.fields()[i].bytes);
      }
      EXPECT(m2.serialize() == round);  // minimal form IS a fixpoint
      // And the schemaless JSON walk terminates on anything parseable.
      (void)pb_to_json_schemaless(m);
    }
  }
}

TEST_CASE(fuzz_thrift_parser) {
  std::vector<std::string> corpus;
  {
    ThriftMessage m;
    m.mtype = TMessageType::kCall;
    m.method = "Echo";
    m.seq_id = 9;
    m.body = ThriftValue::Struct();
    m.body.add_field(1, ThriftValue::Str(std::string(64, 'p')));
    ThriftValue lst = ThriftValue::List(TType::kI32);
    lst.elems = {ThriftValue::I32(1), ThriftValue::I32(2)};
    m.body.add_field(2, lst);
    ThriftValue mp = ThriftValue::Map(TType::kString, TType::kI64);
    mp.kvs.emplace_back(ThriftValue::Str("k"), ThriftValue::I64(7));
    m.body.add_field(3, mp);
    std::string wire;
    thrift_pack_message(m, &wire);
    corpus.push_back(wire.substr(4));  // frame payload (length stripped)
  }
  for (int iter = 0; iter < 40000; ++iter) {
    const std::string input = mutate(corpus[rng() % corpus.size()]);
    ThriftMessage m;
    (void)thrift_parse_payload(input, &m);  // must terminate, never crash
  }
  // Nesting bomb: struct-in-struct 64 deep must be depth-rejected.
  std::string deep;
  deep.append("\x80\x01\x00\x01", 4);
  deep.append("\x00\x00\x00\x01x", 5);
  deep.append("\x00\x00\x00\x01", 4);
  for (int i = 0; i < 64; ++i) {
    deep.push_back(0x0c);            // field type STRUCT
    deep.append("\x00\x01", 2);      // fid 1
  }
  for (int i = 0; i < 65; ++i) {
    deep.push_back(0x00);            // matching STOPs
  }
  ThriftMessage m;
  EXPECT(!thrift_parse_payload(deep, &m));
}

TEST_MAIN
