// HPACK + HTTP/2 framing tests from hand-built byte sequences (the
// reference's protocol-unit style, e.g. test/brpc_http_parser_unittest).
// HPACK vectors are from RFC 7541 Appendix C.
#include <atomic>
#include <cstring>
#include <string>
#include <vector>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include "fiber/fiber.h"
#include "fiber/sync.h"
#include "net/auth.h"
#include "net/channel.h"
#include "net/hpack.h"
#include "net/progressive.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

class TokenAuth : public Authenticator {
 public:
  explicit TokenAuth(std::string tok) : tok_(std::move(tok)) {}
  int generate_credential(std::string* out) const override {
    *out = tok_;
    return 0;
  }
  int verify_credential(const std::string& cred,
                        const EndPoint&) const override {
    return cred == tok_ ? 0 : -1;
  }

 private:
  std::string tok_;
};

}  // namespace

namespace {

std::string unhex(const char* h) {
  std::string out;
  for (size_t i = 0; h[i] != '\0' && h[i + 1] != '\0'; i += 2) {
    auto val = [](char c) {
      return c <= '9' ? c - '0' : c - 'a' + 10;
    };
    out.push_back(static_cast<char>(val(h[i]) * 16 + val(h[i + 1])));
  }
  return out;
}

const uint8_t* u8(const std::string& s) {
  return reinterpret_cast<const uint8_t*>(s.data());
}

}  // namespace

TEST_CASE(hpack_integers_rfc_c1) {
  // C.1.1: 10 in a 5-bit prefix = 0x0a.
  std::string enc;
  hpack_encode_int(10, 5, 0, &enc);
  EXPECT_EQ(enc.size(), 1u);
  EXPECT_EQ(static_cast<uint8_t>(enc[0]), 0x0a);
  // C.1.2: 1337 in a 5-bit prefix = 1f 9a 0a.
  enc.clear();
  hpack_encode_int(1337, 5, 0, &enc);
  EXPECT(enc == unhex("1f9a0a"));
  // Roundtrip.
  const uint8_t* p = u8(enc);
  uint64_t v = 0;
  EXPECT(hpack_decode_int(&p, u8(enc) + enc.size(), 5, &v));
  EXPECT_EQ(v, 1337u);
}

TEST_CASE(hpack_huffman_rfc_vectors) {
  // C.4.1: "www.example.com" huffman-coded.
  std::string s = unhex("f1e3c2e5f23a6ba0ab90f4ff");
  std::string out;
  EXPECT(hpack_huffman_decode(u8(s), s.size(), &out));
  EXPECT(out == "www.example.com");
  // C.4.2: "no-cache".
  s = unhex("a8eb10649cbf");
  out.clear();
  EXPECT(hpack_huffman_decode(u8(s), s.size(), &out));
  EXPECT(out == "no-cache");
  // C.6.1: "Mon, 21 Oct 2013 20:13:21 GMT".
  s = unhex("d07abe941054d444a8200595040b8166e082a62d1bff");
  out.clear();
  EXPECT(hpack_huffman_decode(u8(s), s.size(), &out));
  EXPECT(out == "Mon, 21 Oct 2013 20:13:21 GMT");
  // Bad padding (zeros) must fail.
  s = unhex("f1e3c2e5f23a6ba0ab90f400");
  out.clear();
  EXPECT(!hpack_huffman_decode(u8(s), s.size(), &out));
}

TEST_CASE(hpack_decode_rfc_c3_request_sequence) {
  // C.3: three requests WITHOUT huffman on one connection (dynamic table
  // evolution across blocks).
  HpackDecoder dec;
  HeaderList h;
  std::string b1 = unhex(
      "828684410f7777772e6578616d706c652e636f6d");
  EXPECT(dec.decode(u8(b1), b1.size(), &h));
  EXPECT_EQ(h.size(), 4u);
  EXPECT(h[0].first == ":method" && h[0].second == "GET");
  EXPECT(h[1].first == ":scheme" && h[1].second == "http");
  EXPECT(h[2].first == ":path" && h[2].second == "/");
  EXPECT(h[3].first == ":authority" && h[3].second == "www.example.com");
  EXPECT_EQ(dec.dynamic_size(), 57u);

  h.clear();
  std::string b2 = unhex("828684be58086e6f2d6361636865");
  EXPECT(dec.decode(u8(b2), b2.size(), &h));
  EXPECT_EQ(h.size(), 5u);
  EXPECT(h[3].second == "www.example.com");  // from the dynamic table
  EXPECT(h[4].first == "cache-control" && h[4].second == "no-cache");

  h.clear();
  std::string b3 = unhex(
      "828785bf400a637573746f6d2d6b65790c637573746f6d2d76616c7565");
  EXPECT(dec.decode(u8(b3), b3.size(), &h));
  EXPECT_EQ(h.size(), 5u);
  EXPECT(h[1].second == "https");
  EXPECT(h[2].second == "/index.html");
  EXPECT(h[4].first == "custom-key" && h[4].second == "custom-value");
  EXPECT_EQ(dec.dynamic_size(), 164u);
}

TEST_CASE(hpack_decode_rfc_c4_huffman_sequence) {
  // C.4: the same requests WITH huffman coding.
  HpackDecoder dec;
  HeaderList h;
  std::string b1 = unhex("828684418cf1e3c2e5f23a6ba0ab90f4ff");
  EXPECT(dec.decode(u8(b1), b1.size(), &h));
  EXPECT_EQ(h.size(), 4u);
  EXPECT(h[3].second == "www.example.com");
  h.clear();
  std::string b2 = unhex("828684be5886a8eb10649cbf");
  EXPECT(dec.decode(u8(b2), b2.size(), &h));
  EXPECT(h[4].second == "no-cache");
  h.clear();
  std::string b3 = unhex(
      "828785bf408825a849e95ba97d7f8925a849e95bb8e8b4bf");
  EXPECT(dec.decode(u8(b3), b3.size(), &h));
  EXPECT(h[4].first == "custom-key" && h[4].second == "custom-value");
}

TEST_CASE(hpack_encoder_roundtrip) {
  HpackEncoder enc;
  HeaderList in = {
      {":method", "POST"},
      {":path", "/Svc.Method"},
      {":status", "200"},
      {"content-type", "application/grpc"},
      {"x-custom", "v1"},
  };
  std::string block;
  enc.encode(in, &block);
  HpackDecoder dec;
  HeaderList out;
  EXPECT(dec.decode(u8(block), block.size(), &out));
  EXPECT_EQ(out.size(), in.size());
  for (size_t i = 0; i < in.size(); ++i) {
    EXPECT(out[i] == in[i]);
  }
}

TEST_CASE(hpack_encoder_dynamic_table_shrinks_repeats) {
  // Incremental indexing: the second block carrying the same metadata
  // collapses to index bytes, and the encoder's table stays in sync with
  // the decoder's through eviction churn.
  HpackEncoder enc;
  HpackDecoder dec;
  HeaderList h = {
      {":method", "POST"},
      {":path", "/pkg.Svc/Method"},
      {":authority", "tpu-host-1234:8080"},
      {"x-trace-id", "abc123def456"},
      {"content-type", "application/grpc"},
  };
  std::string b1;
  enc.encode(h, &b1);
  std::string b2;
  enc.encode(h, &b2);
  HeaderList o1, o2;
  EXPECT(dec.decode(u8(b1), b1.size(), &o1));
  EXPECT(dec.decode(u8(b2), b2.size(), &o2));
  EXPECT(o1 == h);
  EXPECT(o2 == h);
  EXPECT(b2.size() * 2 < b1.size());  // repeats shrink to index bytes
  EXPECT(enc.dynamic_size() == 0 || enc.dynamic_size() <= 4096);

  // Flood with distinct entries: the table must bound and evict while
  // both sides stay aligned.
  for (int i = 0; i < 500; ++i) {
    HeaderList hh = {
        {"x-key-" + std::to_string(i), std::string(40, 'v')}};
    std::string b;
    enc.encode(hh, &b);
    HeaderList oo;
    EXPECT(dec.decode(u8(b), b.size(), &oo));
    EXPECT(oo == hh);
  }
  EXPECT(enc.dynamic_size() <= 4096);
  // The original block still roundtrips after the churn evicted it.
  std::string b3;
  enc.encode(h, &b3);
  HeaderList o3;
  EXPECT(dec.decode(u8(b3), b3.size(), &o3));
  EXPECT(o3 == h);
  // Oversized values are never indexed (they would evict everything).
  HeaderList big = {{"x-big", std::string(8000, 'B')}};
  std::string bb;
  enc.encode(big, &bb);
  HeaderList ob;
  EXPECT(dec.decode(u8(bb), bb.size(), &ob));
  EXPECT(ob == big);
  EXPECT(enc.dynamic_size() <= 4096);
}

TEST_CASE(hpack_malformed_rejected) {
  HpackDecoder dec;
  HeaderList h;
  // Index 0 is invalid.
  std::string bad = unhex("80");
  EXPECT(!dec.decode(u8(bad), bad.size(), &h));
  // Truncated varint.
  bad = unhex("1fff");
  EXPECT(!dec.decode(u8(bad), bad.size(), &h));
  // Reference beyond the tables.
  bad = unhex("ff80808001");
  EXPECT(!dec.decode(u8(bad), bad.size(), &h));
}

namespace {

Server* g_server = nullptr;
int g_port = 0;

void start_once() {
  if (g_server != nullptr) {
    return;
  }
  g_server = new Server();
  g_server->RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                           IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  EXPECT_EQ(g_server->Start(0), 0);
  g_port = g_server->port();
}

std::string fh(uint32_t len, uint8_t type, uint8_t flags, uint32_t sid) {
  std::string h;
  h.push_back(static_cast<char>(len >> 16));
  h.push_back(static_cast<char>(len >> 8));
  h.push_back(static_cast<char>(len));
  h.push_back(static_cast<char>(type));
  h.push_back(static_cast<char>(flags));
  h.push_back(static_cast<char>(sid >> 24));
  h.push_back(static_cast<char>(sid >> 16));
  h.push_back(static_cast<char>(sid >> 8));
  h.push_back(static_cast<char>(sid));
  return h;
}

struct H2TestClient {
  int fd = -1;
  std::string inbuf;

  bool connect_and_preface() {
    fd = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in sa = {};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    sa.sin_port = htons(static_cast<uint16_t>(g_port));
    if (connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0) {
      return false;
    }
    const std::string pre = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
    std::string wire = pre + fh(0, 0x4, 0, 0);  // empty SETTINGS
    return send_all(wire);
  }

  bool send_all(const std::string& w) {
    size_t off = 0;
    while (off < w.size()) {
      const ssize_t n = write(fd, w.data() + off, w.size() - off);
      if (n <= 0) {
        return false;
      }
      off += n;
    }
    return true;
  }

  // Reads one full frame (header + payload); appends nothing else.
  bool read_frame(uint8_t* type, uint8_t* flags, uint32_t* sid,
                  std::string* payload) {
    while (true) {
      if (inbuf.size() >= 9) {
        const uint32_t len =
            (static_cast<uint32_t>(static_cast<uint8_t>(inbuf[0])) << 16) |
            (static_cast<uint32_t>(static_cast<uint8_t>(inbuf[1])) << 8) |
            static_cast<uint8_t>(inbuf[2]);
        if (inbuf.size() >= 9ull + len) {
          *type = static_cast<uint8_t>(inbuf[3]);
          *flags = static_cast<uint8_t>(inbuf[4]);
          *sid =
              ((static_cast<uint32_t>(static_cast<uint8_t>(inbuf[5])) & 0x7f)
               << 24) |
              (static_cast<uint32_t>(static_cast<uint8_t>(inbuf[6])) << 16) |
              (static_cast<uint32_t>(static_cast<uint8_t>(inbuf[7])) << 8) |
              static_cast<uint8_t>(inbuf[8]);
          payload->assign(inbuf, 9, len);
          inbuf.erase(0, 9 + len);
          return true;
        }
      }
      char buf[8192];
      const ssize_t n = read(fd, buf, sizeof(buf));
      if (n <= 0) {
        return false;
      }
      inbuf.append(buf, n);
    }
  }

  ~H2TestClient() {
    if (fd >= 0) {
      close(fd);
    }
  }
};

}  // namespace

TEST_CASE(h2_end_to_end_echo) {
  start_once();
  H2TestClient cli;
  EXPECT(cli.connect_and_preface());
  // Request: POST /Echo.Echo with a body across two DATA frames.
  HpackEncoder enc;
  HeaderList req_headers = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", "/Echo.Echo"},
      {":authority", "test"},
  };
  std::string block;
  enc.encode(req_headers, &block);
  std::string wire =
      fh(static_cast<uint32_t>(block.size()), 0x1, 0x4, 1) + block;
  const std::string part1 = "hello-";
  const std::string part2 = "http2!";
  wire += fh(static_cast<uint32_t>(part1.size()), 0x0, 0, 1) + part1;
  wire += fh(static_cast<uint32_t>(part2.size()), 0x0, 0x1, 1) + part2;
  EXPECT(cli.send_all(wire));

  // Walk frames until stream 1's DATA arrives.
  HpackDecoder dec;
  bool got_headers = false;
  std::string resp_body;
  bool end_stream = false;
  while (!end_stream) {
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t sid = 0;
    std::string payload;
    EXPECT(cli.read_frame(&type, &flags, &sid, &payload));
    if (type == 0x1 && sid == 1) {  // HEADERS
      HeaderList h;
      EXPECT(dec.decode(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size(), &h));
      EXPECT(!h.empty() && h[0].first == ":status" && h[0].second == "200");
      got_headers = true;
      end_stream = (flags & 0x1) != 0;
    } else if (type == 0x0 && sid == 1) {  // DATA
      resp_body += payload;
      end_stream = (flags & 0x1) != 0;
    }
  }
  EXPECT(got_headers);
  EXPECT(resp_body == "hello-http2!");
}

TEST_CASE(h2_grpc_roundtrip) {
  start_once();
  H2TestClient cli;
  EXPECT(cli.connect_and_preface());
  HpackEncoder enc;
  HeaderList req_headers = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", "/Echo/Echo"},  // grpc path form
      {":authority", "test"},
      {"content-type", "application/grpc"},
      {"te", "trailers"},
  };
  std::string block;
  enc.encode(req_headers, &block);
  std::string msg = "grpc-payload";
  std::string framed;
  framed.push_back(0);
  framed.push_back(0);
  framed.push_back(0);
  framed.push_back(0);
  framed.push_back(static_cast<char>(msg.size()));
  framed += msg;
  std::string wire =
      fh(static_cast<uint32_t>(block.size()), 0x1, 0x4, 1) + block +
      fh(static_cast<uint32_t>(framed.size()), 0x0, 0x1, 1) + framed;
  EXPECT(cli.send_all(wire));

  HpackDecoder dec;
  std::string body;
  bool got_trailers = false;
  std::string grpc_status;
  while (!got_trailers) {
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t sid = 0;
    std::string payload;
    EXPECT(cli.read_frame(&type, &flags, &sid, &payload));
    if (type == 0x1 && sid == 1) {
      HeaderList h;
      EXPECT(dec.decode(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size(), &h));
      for (auto& [k, v] : h) {
        if (k == "grpc-status") {
          grpc_status = v;
          got_trailers = true;
        }
      }
    } else if (type == 0x0 && sid == 1) {
      body += payload;
    }
  }
  EXPECT(grpc_status == "0");
  // Response = grpc frame header + echoed message.
  EXPECT_EQ(body.size(), 5 + msg.size());
  EXPECT(body.substr(5) == msg);
}

TEST_CASE(h2_builtins_and_multiplex) {
  start_once();
  H2TestClient cli;
  EXPECT(cli.connect_and_preface());
  HpackEncoder enc;
  // Two GETs on interleaved streams 1 and 3.
  std::string wire;
  for (uint32_t sid : {1u, 3u}) {
    HeaderList h = {
        {":method", "GET"},
        {":scheme", "http"},
        {":path", sid == 1 ? "/health" : "/version"},
        {":authority", "test"},
    };
    std::string block;
    enc.encode(h, &block);
    wire += fh(static_cast<uint32_t>(block.size()), 0x1, 0x4 | 0x1, sid) +
            block;
  }
  EXPECT(cli.send_all(wire));
  HpackDecoder dec;
  std::string b1;
  std::string b3;
  int open_streams = 2;
  while (open_streams > 0) {
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t sid = 0;
    std::string payload;
    EXPECT(cli.read_frame(&type, &flags, &sid, &payload));
    if (type == 0x0) {
      (sid == 1 ? b1 : b3) += payload;
    }
    if ((type == 0x0 || type == 0x1) && (flags & 0x1) != 0) {
      --open_streams;
    }
    if (type == 0x1) {
      HeaderList h;
      EXPECT(dec.decode(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size(), &h));
    }
  }
  EXPECT(b1 == "OK\n");
  EXPECT(b3.find("tpu-rpc/") != std::string::npos);
}

TEST_CASE(h2_trickled_bytes) {
  // The wire arrives in tiny slices: the preface is consumed on an early
  // parse round BEFORE any complete request exists, so the socket is not
  // yet pinned — the h2 state tag must keep the connection claimed across
  // probing rounds.
  start_once();
  H2TestClient cli;
  cli.fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(cli.fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)),
            0);
  HpackEncoder enc;
  HeaderList req_headers = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", "/Echo.Echo"},
      {":authority", "t"},
  };
  std::string block;
  enc.encode(req_headers, &block);
  const std::string body = "trickle";
  std::string wire = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
  wire += fh(0, 0x4, 0, 0);
  wire += fh(static_cast<uint32_t>(block.size()), 0x1, 0x4, 1) + block;
  wire += fh(static_cast<uint32_t>(body.size()), 0x0, 0x1, 1) + body;
  for (size_t off = 0; off < wire.size(); off += 5) {
    const size_t n = std::min<size_t>(5, wire.size() - off);
    EXPECT(cli.send_all(wire.substr(off, n)));
    usleep(2000);  // force separate reads (and separate parse rounds)
  }
  std::string resp_body;
  bool end_stream = false;
  HpackDecoder dec;
  while (!end_stream) {
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t sid = 0;
    std::string payload;
    EXPECT(cli.read_frame(&type, &flags, &sid, &payload));
    if (type == 0x1 && sid == 1) {
      HeaderList h;
      EXPECT(dec.decode(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size(), &h));
      end_stream = (flags & 0x1) != 0;
    } else if (type == 0x0 && sid == 1) {
      resp_body += payload;
      end_stream = (flags & 0x1) != 0;
    }
  }
  EXPECT(resp_body == body);
}

TEST_CASE(h2_grpc_large_response_window_drain) {
  // A gRPC response bigger than the default 64KB window: DATA must stall
  // at the window, resume on our WINDOW_UPDATEs, and the grpc-status
  // trailers must arrive strictly AFTER the last DATA byte.
  static Server big;
  static std::string blob(200 * 1024, 'G');
  if (big.port() < 0) {
    big.RegisterMethod("Big.Get", [](Controller*, const IOBuf&, IOBuf* r,
                                     Closure done) {
      r->append(blob);
      done();
    });
    EXPECT_EQ(big.Start(0), 0);
  }
  H2TestClient cli;
  int save_port = g_port;
  g_port = big.port();
  EXPECT(cli.connect_and_preface());
  g_port = save_port;
  HpackEncoder enc;
  HeaderList h = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", "/Big/Get"},
      {":authority", "t"},
      {"content-type", "application/grpc"},
  };
  std::string block;
  enc.encode(h, &block);
  std::string framed;
  framed.push_back(0);
  framed.push_back(0);
  framed.push_back(0);
  framed.push_back(0);
  framed.push_back(0);
  std::string wire =
      fh(static_cast<uint32_t>(block.size()), 0x1, 0x4, 1) + block +
      fh(static_cast<uint32_t>(framed.size()), 0x0, 0x1, 1) + framed;
  EXPECT(cli.send_all(wire));

  HpackDecoder dec;
  std::string body;
  bool got_status = false;
  bool data_after_trailers = false;
  while (!got_status) {
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t sid = 0;
    std::string payload;
    EXPECT(cli.read_frame(&type, &flags, &sid, &payload));
    if (type == 0x0 && sid == 1) {
      if (got_status) {
        data_after_trailers = true;
      }
      body += payload;
      // Grant more window as a real client would.
      std::string wu;
      wu.push_back(0);
      wu.push_back(1);
      wu.push_back(0);
      wu.push_back(0);  // 65536 increment
      EXPECT(cli.send_all(fh(4, 0x8, 0, 0) + wu));
      EXPECT(cli.send_all(fh(4, 0x8, 0, 1) + wu));
    } else if (type == 0x1 && sid == 1) {
      HeaderList hh;
      EXPECT(dec.decode(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size(), &hh));
      for (auto& [k, v] : hh) {
        if (k == "grpc-status") {
          EXPECT(v == "0");
          got_status = true;
        }
      }
    }
  }
  EXPECT(!data_after_trailers);
  EXPECT_EQ(body.size(), 5 + blob.size());
  EXPECT(body.substr(5) == blob);
}

TEST_CASE(h2_trailers_after_data_carry_body) {
  // END_STREAM arriving on a trailing HEADERS frame (trailers after DATA,
  // legal HTTP/2) must not lose the accumulated body.
  start_once();
  H2TestClient cli;
  EXPECT(cli.connect_and_preface());
  HpackEncoder enc;
  HeaderList req_headers = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", "/Echo.Echo"},
      {":authority", "t"},
  };
  std::string block;
  enc.encode(req_headers, &block);
  const std::string body = "body-before-trailers";
  HeaderList trailers = {{"x-checksum", "fletcher"}};
  std::string tblock;
  enc.encode(trailers, &tblock);
  std::string wire =
      fh(static_cast<uint32_t>(block.size()), 0x1, 0x4, 1) + block +
      fh(static_cast<uint32_t>(body.size()), 0x0, 0, 1) + body +
      // trailing HEADERS: END_HEADERS | END_STREAM
      fh(static_cast<uint32_t>(tblock.size()), 0x1, 0x4 | 0x1, 1) + tblock;
  EXPECT(cli.send_all(wire));
  HpackDecoder dec;
  std::string resp_body;
  bool end_stream = false;
  while (!end_stream) {
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t sid = 0;
    std::string payload;
    EXPECT(cli.read_frame(&type, &flags, &sid, &payload));
    if (type == 0x1 && sid == 1) {
      HeaderList h;
      EXPECT(dec.decode(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size(), &h));
      end_stream = (flags & 0x1) != 0;
    } else if (type == 0x0 && sid == 1) {
      resp_body += payload;
      end_stream = (flags & 0x1) != 0;
    }
  }
  EXPECT(resp_body == body);
}

TEST_CASE(h2_window_update_overflow_kills_connection) {
  // A WINDOW_UPDATE pushing the connection send window past 2^31-1 is a
  // flow-control error (RFC 9113 §6.9.1) — the connection must die, not
  // wrap negative and stall.
  start_once();
  H2TestClient cli;
  EXPECT(cli.connect_and_preface());
  std::string inc;
  inc.push_back(0x7f);
  inc.push_back(static_cast<char>(0xff));
  inc.push_back(static_cast<char>(0xff));
  inc.push_back(static_cast<char>(0xff));  // +2147483647 on stream 0
  EXPECT(cli.send_all(fh(4, 0x8, 0, 0) + inc));
  // Connection must be closed by the server: reads drain then EOF.
  uint8_t type = 0;
  uint8_t flags = 0;
  uint32_t sid = 0;
  std::string payload;
  bool closed = false;
  for (int i = 0; i < 64 && !closed; ++i) {
    closed = !cli.read_frame(&type, &flags, &sid, &payload);
  }
  EXPECT(closed);
}

TEST_CASE(h2_stream_flood_refused_not_fatal) {
  // Opening more than the advertised MAX_CONCURRENT_STREAMS must refuse
  // the excess stream (RST_STREAM/REFUSED_STREAM) while the earlier
  // streams keep working — not tear down the whole connection.
  start_once();
  H2TestClient cli;
  EXPECT(cli.connect_and_preface());
  HpackEncoder enc;
  std::string wire;
  // 257 half-open request streams (headers sent, body pending).
  for (uint32_t i = 0; i < 257; ++i) {
    const uint32_t sid = 1 + 2 * i;
    HeaderList h = {
        {":method", "POST"},
        {":scheme", "http"},
        {":path", "/Echo.Echo"},
        {":authority", "t"},
    };
    std::string block;
    enc.encode(h, &block);
    wire += fh(static_cast<uint32_t>(block.size()), 0x1, 0x4, sid) + block;
  }
  EXPECT(cli.send_all(wire));
  // Expect RST_STREAM(REFUSED_STREAM) for the 257th (sid 513).
  bool refused = false;
  while (!refused) {
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t sid = 0;
    std::string payload;
    EXPECT(cli.read_frame(&type, &flags, &sid, &payload));
    if (type == 0x3 && sid == 513) {
      EXPECT_EQ(payload.size(), 4u);
      const uint32_t code =
          (static_cast<uint32_t>(static_cast<uint8_t>(payload[0])) << 24) |
          (static_cast<uint32_t>(static_cast<uint8_t>(payload[1])) << 16) |
          (static_cast<uint32_t>(static_cast<uint8_t>(payload[2])) << 8) |
          static_cast<uint8_t>(payload[3]);
      EXPECT_EQ(code, 0x7u);  // REFUSED_STREAM
      refused = true;
    }
  }
  // Stream 1 still completes end-to-end on the same connection.
  const std::string body = "still-alive";
  EXPECT(cli.send_all(fh(static_cast<uint32_t>(body.size()), 0x0, 0x1, 1) +
                      body));
  HpackDecoder dec;
  std::string resp_body;
  bool end_stream = false;
  while (!end_stream) {
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t sid = 0;
    std::string payload;
    EXPECT(cli.read_frame(&type, &flags, &sid, &payload));
    if (type == 0x1 && sid == 1) {
      HeaderList h;
      EXPECT(dec.decode(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size(), &h));
      end_stream = (flags & 0x1) != 0;
    } else if (type == 0x0 && sid == 1) {
      resp_body += payload;
      end_stream = (flags & 0x1) != 0;
    }
  }
  EXPECT(resp_body == body);
}

TEST_CASE(h2_client_end_to_end) {
  // Our own Channel speaking h2 against our own h2 server: a payload
  // larger than the 64KB default window exercises request-side flow
  // control (DATA stalls until the server's SETTINGS/WINDOW_UPDATEs) and
  // response-side window replenishment.
  start_once();
  Channel ch;
  Channel::Options opts;
  opts.protocol = "h2";
  opts.timeout_ms = 5000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port), &opts), 0);
  std::string blob(300 * 1024, 'h');
  for (int round = 0; round < 3; ++round) {  // stream ids 1, 3, 5
    Controller cntl;
    IOBuf req, resp;
    req.append(blob);
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    EXPECT(resp.to_string() == blob);
  }
  // Unknown method: plain h2 surfaces the HTTP status as an error.
  {
    Controller cntl;
    IOBuf req, resp;
    req.append("x");
    ch.CallMethod("No.Such", req, &resp, &cntl);
    EXPECT(cntl.Failed());
  }
}

TEST_CASE(h2_client_grpc_roundtrip) {
  start_once();
  Channel ch;
  Channel::Options opts;
  opts.protocol = "grpc";
  opts.timeout_ms = 5000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port), &opts), 0);
  Controller cntl;
  IOBuf req, resp;
  req.append("grpc-via-our-client");
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT(resp.to_string() == "grpc-via-our-client");
  // Unknown method → grpc-status 12 in trailers → client-side failure.
  Controller c2;
  IOBuf r2, p2;
  r2.append("x");
  ch.CallMethod("No.Such", r2, &p2, &c2);
  EXPECT(c2.Failed());
  EXPECT(c2.error_text().find("unimplemented") != std::string::npos);
}

TEST_CASE(h2_client_concurrent_multiplex) {
  // Many fibers multiplexing one h2 connection: responses must route to
  // the right calls via the stream-id map.
  start_once();
  static Channel ch;
  static std::atomic<int> failures{0};
  Channel::Options opts;
  opts.protocol = "h2";
  opts.timeout_ms = 5000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port), &opts), 0);
  constexpr int kCalls = 24;
  CountdownEvent all(kCalls);
  for (int i = 0; i < kCalls; ++i) {
    fiber_start(
        nullptr,
        [](void* arg) {
          auto* ev = static_cast<CountdownEvent*>(arg);
          static std::atomic<int> seq{0};
          const int me = seq.fetch_add(1);
          Controller cntl;
          IOBuf req, resp;
          const std::string body =
              "payload-" + std::to_string(me) + std::string(1024, 'x');
          req.append(body);
          ch.CallMethod("Echo.Echo", req, &resp, &cntl);
          if (cntl.Failed() || resp.to_string() != body) {
            failures.fetch_add(1);
          }
          ev->signal();
        },
        &all, 0);
  }
  all.wait(-1);
  EXPECT_EQ(failures.load(), 0);
}

TEST_CASE(h2_peer_header_table_size_zero) {
  // A client advertising SETTINGS_HEADER_TABLE_SIZE=0 disables dynamic
  // indexing: the server must open its next block with a §6.3 size
  // update and stop emitting dynamic indexes, or a table-less decoder
  // dies with COMPRESSION_ERROR (RFC 7541 §4.2).
  start_once();
  H2TestClient cli;
  cli.fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(cli.fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)),
            0);
  std::string settings;
  settings.append("\x00\x01", 2);  // HEADER_TABLE_SIZE
  settings.append(4, '\x00');      // = 0
  std::string wire = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
  wire += fh(static_cast<uint32_t>(settings.size()), 0x4, 0, 0) + settings;
  HpackEncoder enc;
  for (uint32_t sid : {1u, 3u}) {  // two rounds: repeats must NOT index
    HeaderList h = {
        {":method", "POST"},
        {":scheme", "http"},
        {":path", "/Echo.Echo"},
        {":authority", "t"},
    };
    std::string block;
    enc.encode(h, &block);
    wire += fh(static_cast<uint32_t>(block.size()), 0x1, 0x4, sid) + block;
    const std::string body = "tbl0";
    wire += fh(static_cast<uint32_t>(body.size()), 0x0, 0x1, sid) + body;
  }
  EXPECT(cli.send_all(wire));
  HpackDecoder dec(0);  // the table-less decoder we advertised
  int done = 0;
  while (done < 2) {
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t sid = 0;
    std::string payload;
    EXPECT(cli.read_frame(&type, &flags, &sid, &payload));
    if (type == 0x1) {
      HeaderList h;
      EXPECT(dec.decode(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size(), &h));
    }
    if ((type == 0x0 || type == 0x1) && (flags & 0x1) != 0) {
      ++done;
    }
  }
}

TEST_CASE(h2_client_progressive_reader) {
  // The h2 client hands DATA frames to a ProgressiveReader as they
  // arrive (progressive_reader.h parity): parts flow incrementally, the
  // response buffer stays empty, and on_done fires exactly once.
  static Server big;
  static std::string blob;
  if (big.port() < 0) {
    blob.assign(4 << 20, 'P');
    for (size_t i = 0; i < blob.size(); i += 4096) {
      blob[i] = static_cast<char>('a' + (i / 4096) % 26);
    }
    big.RegisterMethod("PR.Get", [](Controller*, const IOBuf&, IOBuf* r,
                                    Closure done) {
      r->append(blob);
      done();
    });
    EXPECT_EQ(big.Start(0), 0);
  }
  class Collector : public ProgressiveReader {
   public:
    bool on_part(const IOBuf& piece) override {
      parts += 1;
      max_part = std::max(max_part, piece.size());
      body += piece.to_string();
      return true;
    }
    void on_done(int ec, const std::string&) override {
      done_calls += 1;
      last_ec = ec;
    }
    int parts = 0;
    size_t max_part = 0;
    int done_calls = 0;
    int last_ec = -1;
    std::string body;
  };
  Collector col;
  Channel ch;
  Channel::Options opts;
  opts.protocol = "h2";
  opts.timeout_ms = 10000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(big.port()), &opts), 0);
  Controller cntl;
  cntl.ReadProgressively(&col);
  IOBuf req, resp;
  req.append("x");
  ch.CallMethod("PR.Get", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT_EQ(col.done_calls, 1);
  EXPECT_EQ(col.last_ec, 0);
  EXPECT(col.parts > 1);               // incremental, not one lump
  EXPECT(col.max_part <= 16 * 1024);   // bounded by the h2 frame size
  EXPECT(resp.empty());                // nothing accumulated
  EXPECT(col.body == blob);
}

TEST_CASE(h2_client_auth_header) {
  // h2 has no kAuth frame: the credential rides "authorization" and the
  // server marks the connection on first verify.
  static TokenAuth good("h2-sesame");
  static TokenAuth bad("h2-wrong");
  static Server auth_srv;
  auth_srv.RegisterMethod("A.Echo", [](Controller*, const IOBuf& req,
                                       IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  auth_srv.set_authenticator(&good);
  EXPECT_EQ(auth_srv.Start(0), 0);
  const std::string addr = "127.0.0.1:" + std::to_string(auth_srv.port());
  {
    Channel ch;
    Channel::Options opts;
    opts.protocol = "h2";
    opts.auth = &good;
    opts.timeout_ms = 3000;
    EXPECT_EQ(ch.Init(addr, &opts), 0);
    Controller cntl;
    IOBuf req, resp;
    req.append("authed-h2");
    ch.CallMethod("A.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    EXPECT(resp.to_string() == "authed-h2");
  }
  {
    Channel ch;
    Channel::Options opts;
    opts.protocol = "h2";
    opts.auth = &bad;
    opts.timeout_ms = 3000;
    EXPECT_EQ(ch.Init(addr, &opts), 0);
    Controller cntl;
    IOBuf req, resp;
    req.append("nope");
    ch.CallMethod("A.Echo", req, &resp, &cntl);
    EXPECT(cntl.Failed());
  }
}

TEST_MAIN
