// HTTP + builtin services tests: one port serves BOTH tstd RPC and HTTP
// (the multi-protocol feature, input_messenger.cpp:83 parity).
#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <string>

#include "base/time.h"
#include "net/channel.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

Server* g_server = nullptr;
int g_port = 0;

void start_once() {
  if (g_server != nullptr) {
    return;
  }
  g_server = new Server();
  g_server->RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                           IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  EXPECT_EQ(g_server->Start(0), 0);
  g_port = g_server->port();
}

// Plain-socket HTTP client (the test is the wire).
std::string http_get(const std::string& req_text) {
  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)), 0);
  EXPECT(write(fd, req_text.data(), req_text.size()) ==
         static_cast<ssize_t>(req_text.size()));
  std::string out;
  char buf[4096];
  // Read until headers+body complete (Content-Length framing).
  while (true) {
    const ssize_t n = read(fd, buf, sizeof(buf));
    if (n <= 0) {
      break;
    }
    out.append(buf, n);
    const size_t he = out.find("\r\n\r\n");
    if (he != std::string::npos) {
      const size_t cl = out.find("Content-Length: ");
      if (cl != std::string::npos) {
        const size_t len = strtoul(out.c_str() + cl + 16, nullptr, 10);
        if (out.size() >= he + 4 + len) {
          break;
        }
      }
    }
  }
  close(fd);
  return out;
}

}  // namespace

TEST_CASE(health_and_version) {
  start_once();
  std::string r = http_get("GET /health HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("200 OK") != std::string::npos);
  EXPECT(r.find("OK\n") != std::string::npos);
  r = http_get("GET /version HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("tpu-rpc/") != std::string::npos);
}

TEST_CASE(vars_and_status) {
  // Generate some RPC traffic first so method vars exist.
  Channel ch;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port)), 0);
  for (int i = 0; i < 5; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("ping");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  std::string r = http_get("GET /vars HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("rpc_server_Echo.Echo") != std::string::npos);
  r = http_get("GET /brpc_metrics HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("rpc_server_Echo_Echo_latency_us{quantile=\"0.5\"") != std::string::npos);
  EXPECT(r.find("_qps ") != std::string::npos);
  r = http_get("GET /status HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("requests_served") != std::string::npos);
  EXPECT(r.find("Echo.Echo") != std::string::npos);
  r = http_get("GET /connections HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("live_sockets") != std::string::npos);
}

TEST_CASE(rpc_over_http) {
  std::string body = "http-body-payload";
  std::string req = "POST /Echo.Echo HTTP/1.1\r\nHost: x\r\nContent-Length: " +
                    std::to_string(body.size()) + "\r\n\r\n" + body;
  const std::string r = http_get(req);
  EXPECT(r.find("200 OK") != std::string::npos);
  EXPECT(r.find(body) != std::string::npos);
}

TEST_CASE(http_404) {
  const std::string r = http_get("GET /nope HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("404") != std::string::npos);
}

TEST_CASE(keep_alive_multiple_requests) {
  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)), 0);
  for (int i = 0; i < 3; ++i) {
    const std::string req = "GET /health HTTP/1.1\r\nHost: x\r\n\r\n";
    EXPECT(write(fd, req.data(), req.size()) ==
           static_cast<ssize_t>(req.size()));
    char buf[1024];
    ssize_t n = read(fd, buf, sizeof(buf));
    EXPECT(n > 0);
    EXPECT(std::string(buf, n).find("200 OK") != std::string::npos);
  }
  close(fd);
}

TEST_CASE(mixed_protocols_one_port) {
  // tstd RPC and HTTP hitting the same port concurrently.
  Channel ch;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port)), 0);
  for (int i = 0; i < 10; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("mixed");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    const std::string r = http_get("GET /health HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT(r.find("200 OK") != std::string::npos);
  }
}

TEST_MAIN
