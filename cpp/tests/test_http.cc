// HTTP + builtin services tests: one port serves BOTH tstd RPC and HTTP
// (the multi-protocol feature, input_messenger.cpp:83 parity).
#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <string>
#include <thread>
#include <vector>

#include "base/time.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/http_client.h"
#include "net/http_protocol.h"
#include "net/progressive.h"
#include "net/server.h"
#include "stat/heap_profiler.h"
#include "tests/test_util.h"

using namespace trpc;

namespace trpc {
extern std::atomic<int64_t> g_socket_count;  // net/builtin.cc
}

namespace {

Server* g_server = nullptr;
int g_port = 0;

void start_once() {
  if (g_server != nullptr) {
    return;
  }
  g_server = new Server();
  g_server->RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                           IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  g_server->RegisterMethod("Gate.Slow", [](Controller*, const IOBuf& req,
                                           IOBuf* resp, Closure done) {
    usleep(50 * 1000);
    resp->append(req);
    done();
  });
  EXPECT_EQ(g_server->SetMethodMaxConcurrency("Gate.Slow", "2"), 0);
  EXPECT_EQ(g_server->MapRestful("/v1/echo/*", "Echo.Echo"), 0);
  EXPECT_EQ(g_server->MapRestful("/v1/ping", "Echo.Echo"), 0);
  EXPECT_EQ(g_server->Start(0), 0);
  g_port = g_server->port();
}

// Plain-socket HTTP client (the test is the wire).
std::string http_get(const std::string& req_text) {
  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)), 0);
  EXPECT(write(fd, req_text.data(), req_text.size()) ==
         static_cast<ssize_t>(req_text.size()));
  std::string out;
  char buf[4096];
  // Read until headers+body complete (Content-Length framing).
  while (true) {
    const ssize_t n = read(fd, buf, sizeof(buf));
    if (n <= 0) {
      break;
    }
    out.append(buf, n);
    const size_t he = out.find("\r\n\r\n");
    if (he != std::string::npos) {
      const size_t cl = out.find("Content-Length: ");
      if (cl != std::string::npos) {
        const size_t len = strtoul(out.c_str() + cl + 16, nullptr, 10);
        if (out.size() >= he + 4 + len) {
          break;
        }
      }
    }
  }
  close(fd);
  return out;
}

}  // namespace

TEST_CASE(health_and_version) {
  start_once();
  std::string r = http_get("GET /health HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("200 OK") != std::string::npos);
  EXPECT(r.find("OK\n") != std::string::npos);
  r = http_get("GET /version HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("tpu-rpc/") != std::string::npos);
}

TEST_CASE(vars_and_status) {
  // Generate some RPC traffic first so method vars exist.
  Channel ch;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port)), 0);
  for (int i = 0; i < 5; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("ping");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  std::string r = http_get("GET /vars HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("rpc_server_Echo.Echo") != std::string::npos);
  r = http_get("GET /brpc_metrics HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("rpc_server_Echo_Echo_latency_us{quantile=\"0.5\"") != std::string::npos);
  EXPECT(r.find("_qps ") != std::string::npos);
  r = http_get("GET /status HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("requests_served") != std::string::npos);
  EXPECT(r.find("Echo.Echo") != std::string::npos);
  r = http_get("GET /connections HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("live_sockets") != std::string::npos);
}

TEST_CASE(rpc_over_http) {
  std::string body = "http-body-payload";
  std::string req = "POST /Echo.Echo HTTP/1.1\r\nHost: x\r\nContent-Length: " +
                    std::to_string(body.size()) + "\r\n\r\n" + body;
  const std::string r = http_get(req);
  EXPECT(r.find("200 OK") != std::string::npos);
  EXPECT(r.find(body) != std::string::npos);
}

TEST_CASE(http_404) {
  const std::string r = http_get("GET /nope HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("404") != std::string::npos);
}

TEST_CASE(keep_alive_multiple_requests) {
  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)), 0);
  for (int i = 0; i < 3; ++i) {
    const std::string req = "GET /health HTTP/1.1\r\nHost: x\r\n\r\n";
    EXPECT(write(fd, req.data(), req.size()) ==
           static_cast<ssize_t>(req.size()));
    char buf[1024];
    ssize_t n = read(fd, buf, sizeof(buf));
    EXPECT(n > 0);
    EXPECT(std::string(buf, n).find("200 OK") != std::string::npos);
  }
  close(fd);
}

TEST_CASE(mixed_protocols_one_port) {
  // tstd RPC and HTTP hitting the same port concurrently.
  Channel ch;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port)), 0);
  for (int i = 0; i < 10; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("mixed");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    const std::string r = http_get("GET /health HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT(r.find("200 OK") != std::string::npos);
  }
}

TEST_CASE(chunked_request_body) {
  // Transfer-Encoding: chunked, decoded and delivered to the method.
  const std::string req =
      "POST /Echo.Echo HTTP/1.1\r\nHost: x\r\n"
      "Transfer-Encoding: chunked\r\n\r\n"
      "4\r\nWiki\r\n5\r\npedia\r\nE\r\n in\r\n\r\nchunks.\r\n"
      "0\r\n\r\n";
  const std::string r = http_get(req);
  EXPECT(r.find("200 OK") != std::string::npos);
  EXPECT(r.find("Wikipedia in\r\n\r\nchunks.") != std::string::npos);
}

TEST_CASE(smuggling_vectors_rejected) {
  // Duplicate Content-Length and chunked+Content-Length both desync
  // framing: the server must kill the connection, not guess.
  for (const char* req :
       {"POST /Echo.Echo HTTP/1.1\r\nHost: x\r\nContent-Length: 3\r\n"
        "Content-Length: 5\r\n\r\nabcde",
        "POST /Echo.Echo HTTP/1.1\r\nHost: x\r\nContent-Length: 5\r\n"
        "Transfer-Encoding: chunked\r\n\r\n5\r\nabcde\r\n0\r\n\r\n"}) {
    const std::string r = http_get(req);
    EXPECT(r.empty());  // connection killed without a response
  }
}

TEST_CASE(transfer_encoding_chunked_must_be_exact) {
  // "chunked, gzip" frames the body as gzip-of-chunks (desync behind
  // proxies honoring the full list); "gzip, chunked" would deliver
  // still-compressed bytes.  Only the exact value "chunked" is accepted.
  for (const char* te : {"chunked, gzip", "gzip, chunked", "chunkedx"}) {
    const std::string r = http_get(
        std::string("POST /Echo.Echo HTTP/1.1\r\nHost: x\r\n"
                    "Transfer-Encoding: ") +
        te + "\r\n\r\n5\r\nabcde\r\n0\r\n\r\n");
    EXPECT(r.empty());  // connection killed without a response
  }
  // "chunked" with surrounding whitespace stays accepted (OWS trim).
  const std::string ok = http_get(
      "POST /Echo.Echo HTTP/1.1\r\nHost: x\r\n"
      "Transfer-Encoding:  chunked \r\n\r\n5\r\nabcde\r\n0\r\n\r\n");
  EXPECT(ok.find("200") != std::string::npos);
}

TEST_CASE(pprof_endpoints) {
  start_once();
  // /pprof/profile: legacy binary CPU-profile format — header words
  // [0, 3, 0, period, 0] — that external pprof tooling parses.
  {
    const std::string r = http_get(
        "GET /pprof/profile?seconds=1 HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT(r.find("200 OK") != std::string::npos);
    const size_t he = r.find("\r\n\r\n");
    EXPECT(he != std::string::npos);
    const char* words = r.data() + he + 4;
    EXPECT(r.size() - he - 4 >= 8 * sizeof(uintptr_t));
    uintptr_t w[5];
    memcpy(w, words, sizeof(w));
    EXPECT_EQ(w[0], 0u);
    EXPECT_EQ(w[1], 3u);
    EXPECT_EQ(w[2], 0u);
    EXPECT_EQ(w[3], 10000u);  // 100hz → 10ms period
  }
  // /pprof/symbol: GET probe + POST address resolution.
  {
    std::string r = http_get("GET /pprof/symbol HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT(r.find("num_symbols: 1") != std::string::npos);
    char addr[32];
    snprintf(addr, sizeof(addr), "%p",
             reinterpret_cast<void*>(&builtin_http_dispatch));
    const std::string body = addr;
    r = http_get("POST /pprof/symbol HTTP/1.1\r\nHost: x\r\nContent-Length: " +
                 std::to_string(body.size()) + "\r\n\r\n" + body);
    EXPECT(r.find("builtin_http_dispatch") != std::string::npos);
  }
  // /pprof/cmdline mirrors /proc/self/cmdline.
  {
    const std::string r =
        http_get("GET /pprof/cmdline HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT(r.find("test_http") != std::string::npos);
  }
  // /pprof/heap: first call arms the sampler; after allocating enough to
  // cross sampling periods, the dump carries the gperftools text header
  // and stack lines.
  {
    std::string r = http_get("GET /pprof/heap HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT(r.find("heap sampling enabled") != std::string::npos);
    std::vector<std::string*> hold;
    for (int i = 0; i < 64; ++i) {
      hold.push_back(new std::string(256 * 1024, 'h'));  // cross periods
    }
    r = http_get("GET /pprof/heap HTTP/1.1\r\nHost: x\r\n\r\n");
    EXPECT(r.find("heap profile:") != std::string::npos);
    EXPECT(r.find("MAPPED_LIBRARIES:") != std::string::npos);
    EXPECT(r.find(" @ ") != std::string::npos);  // at least one stack row
    for (auto* s : hold) {
      delete s;
    }
    heap_profiler_stop();
  }
}

namespace {

std::atomic<bool> g_pa_wrote_last{false};

}  // namespace

TEST_CASE(progressive_attachment_streams_chunks) {
  // A handler that responds headers immediately and streams the body over
  // time (ProgressiveAttachment, progressive_attachment.h:32): the client
  // must see early chunks BEFORE the handler wrote the last one (no
  // full-body buffering), and the connection must survive for the next
  // request (keep-alive after the terminating chunk).
  static Server srv;
  srv.RegisterMethod("PA.Stream", [](Controller* cntl, const IOBuf&,
                                     IOBuf*, Closure done) {
    auto pa = cntl->CreateProgressiveAttachment();
    done();  // headers flush now; body follows from this fiber
    for (int i = 0; i < 8; ++i) {
      IOBuf piece;
      piece.append(std::string(256 * 1024, static_cast<char>('a' + i)));
      EXPECT_EQ(pa->Write(piece), 0);
      fiber_sleep_us(30 * 1000);  // pace: 8 chunks over ~240ms
    }
    g_pa_wrote_last.store(true);
    pa->close();
  });
  srv.RegisterMethod("PA.Ping", [](Controller*, const IOBuf&, IOBuf* r,
                                   Closure done) {
    r->append("pong");
    done();
  });
  EXPECT_EQ(srv.Start(0), 0);

  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(srv.port()));
  EXPECT_EQ(connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)), 0);
  const std::string rq = "GET /PA.Stream HTTP/1.1\r\nHost: x\r\n\r\n";
  EXPECT(write(fd, rq.data(), rq.size()) == static_cast<ssize_t>(rq.size()));

  std::string in;
  char buf[65536];
  bool checked_early = false;
  while (in.find("\r\n0\r\n\r\n") == std::string::npos) {
    const ssize_t n = read(fd, buf, sizeof(buf));
    EXPECT(n > 0);
    in.append(buf, n);
    if (!checked_early && in.size() > 4096) {
      // First bytes arrived: the handler must still be mid-stream.
      EXPECT(!g_pa_wrote_last.load());
      EXPECT(in.find("Transfer-Encoding: chunked") != std::string::npos);
      checked_early = true;
    }
  }
  EXPECT(checked_early);
  // De-chunk and verify the body.
  const size_t hdr_end = in.find("\r\n\r\n");
  EXPECT(hdr_end != std::string::npos);
  std::string body;
  size_t pos = hdr_end + 4;
  while (true) {
    const size_t nl = in.find("\r\n", pos);
    EXPECT(nl != std::string::npos);
    const size_t len = strtoul(in.substr(pos, nl - pos).c_str(), nullptr, 16);
    if (len == 0) {
      break;
    }
    body += in.substr(nl + 2, len);
    pos = nl + 2 + len + 2;
  }
  EXPECT_EQ(body.size(), 8u * 256 * 1024);
  for (int i = 0; i < 8; ++i) {
    EXPECT(body[i * 256 * 1024] == 'a' + i);
  }
  // Keep-alive: the connection serves the next request after the stream.
  const std::string rq2 =
      "POST /PA.Ping HTTP/1.1\r\nHost: x\r\nContent-Length: 0\r\n\r\n";
  EXPECT(write(fd, rq2.data(), rq2.size()) ==
         static_cast<ssize_t>(rq2.size()));
  const ssize_t n2 = read(fd, buf, sizeof(buf));
  EXPECT(n2 > 0);
  EXPECT(std::string(buf, n2).find("pong") != std::string::npos);
  close(fd);
}

TEST_CASE(uri_query_and_percent_decoding) {
  start_once();
  // Unknown flag name exercises the decoded single-target path.
  std::string r = http_get(
      "GET /flags/no%20such%20flag HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("404") != std::string::npos);
  EXPECT(r.find("no such flag: no such flag") != std::string::npos);
}

TEST_CASE(restful_mapping) {
  start_once();
  std::string body = "restful!";
  std::string req =
      "POST /v1/echo/anything HTTP/1.1\r\nHost: x\r\nContent-Length: " +
      std::to_string(body.size()) + "\r\n\r\n" + body;
  std::string r = http_get(req);
  EXPECT(r.find("200 OK") != std::string::npos);
  EXPECT(r.find(body) != std::string::npos);
  // Exact rule.
  req = "POST /v1/ping HTTP/1.1\r\nHost: x\r\nContent-Length: 2\r\n\r\nok";
  r = http_get(req);
  EXPECT(r.find("200 OK") != std::string::npos);
  // Prefix alone (no extra segment) does NOT match the wildcard rule.
  r = http_get("GET /v1/echo HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("404") != std::string::npos);
}

TEST_CASE(head_and_connection_close) {
  start_once();
  // HEAD: headers with the body's Content-Length but no body bytes.
  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)), 0);
  const std::string req =
      "HEAD /health HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n";
  EXPECT(write(fd, req.data(), req.size()) ==
         static_cast<ssize_t>(req.size()));
  std::string out;
  char buf[2048];
  ssize_t n;
  while ((n = read(fd, buf, sizeof(buf))) > 0) {
    out.append(buf, n);  // server must close (EOF ends this loop)
  }
  close(fd);
  EXPECT(out.find("200 OK") != std::string::npos);
  EXPECT(out.find("Content-Length: 3") != std::string::npos);
  EXPECT(out.find("Connection: close") != std::string::npos);
  EXPECT(out.find("OK\n") == std::string::npos);  // no body after HEAD
}

TEST_CASE(flags_list_get_set_live_limiter) {
  start_once();
  // Listed.
  std::string r = http_get("GET /flags HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("max_concurrency_Gate_Slow = 2") != std::string::npos);
  // Get one.
  r = http_get("GET /flags/max_concurrency_Gate_Slow HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("= 2") != std::string::npos);
  // Bad value rejected by the validator.
  r = http_get(
      "GET /flags/max_concurrency_Gate_Slow?setvalue=-3 HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("400") != std::string::npos);
  // Flip to 1 and verify the LIVE limiter tightened: two concurrent slow
  // calls must now collide (one 503).
  r = http_get(
      "GET /flags/max_concurrency_Gate_Slow?setvalue=1 HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("= 1") != std::string::npos);
  std::atomic<int> ok{0}, rejected{0};
  std::thread t1([&] {
    const std::string body = "a";
    const std::string rq =
        "POST /Gate.Slow HTTP/1.1\r\nHost: x\r\nContent-Length: 1\r\n\r\na";
    const std::string rr = http_get(rq);
    (rr.find("200 OK") != std::string::npos ? ok : rejected).fetch_add(1);
  });
  usleep(10 * 1000);  // first call is in the 50ms handler
  const std::string rr2 = http_get(
      "POST /Gate.Slow HTTP/1.1\r\nHost: x\r\nContent-Length: 1\r\n\r\nb");
  (rr2.find("200 OK") != std::string::npos ? ok : rejected).fetch_add(1);
  t1.join();
  EXPECT_EQ(ok.load(), 1);
  EXPECT_EQ(rejected.load(), 1);
  // Restore for other tests.
  http_get("GET /flags/max_concurrency_Gate_Slow?setvalue=2 HTTP/1.1\r\nHost: x\r\n\r\n");
}

TEST_CASE(chunked_trickled_bytes_resume) {
  start_once();
  // The chunked body arrives in many tiny segments: the resumable parser
  // state (Socket::parse_state) must assemble it across retries.
  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)), 0);
  std::string payload;
  std::string wire =
      "POST /Echo.Echo HTTP/1.1\r\nHost: x\r\n"
      "Transfer-Encoding: chunked\r\n\r\n";
  for (int i = 0; i < 64; ++i) {
    const std::string chunk = "chunk-" + std::to_string(i) + "-payload";
    payload += chunk;
    char size_hex[16];
    snprintf(size_hex, sizeof(size_hex), "%zx", chunk.size());
    wire += std::string(size_hex) + "\r\n" + chunk + "\r\n";
  }
  wire += "0\r\nX-Trailer: ignored\r\n\r\n";
  for (size_t off = 0; off < wire.size(); off += 7) {
    const size_t n = std::min<size_t>(7, wire.size() - off);
    EXPECT(write(fd, wire.data() + off, n) == static_cast<ssize_t>(n));
    if (off % 70 == 0) {
      usleep(1000);  // force separate reads server-side
    }
  }
  std::string out;
  char buf[8192];
  while (true) {
    const ssize_t n = read(fd, buf, sizeof(buf));
    if (n <= 0) {
      break;
    }
    out.append(buf, n);
    if (out.find(payload) != std::string::npos) {
      break;
    }
  }
  close(fd);
  EXPECT(out.find("200 OK") != std::string::npos);
  EXPECT(out.find(payload) != std::string::npos);
}

TEST_CASE(chunked_trailer_bomb_rejected) {
  start_once();
  // An endless trailer stream must kill the connection (bounded memory),
  // not buffer forever.
  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)), 0);
  std::string wire =
      "POST /Echo.Echo HTTP/1.1\r\nHost: x\r\n"
      "Transfer-Encoding: chunked\r\n\r\n2\r\nhi\r\n0\r\n";
  EXPECT(write(fd, wire.data(), wire.size()) ==
         static_cast<ssize_t>(wire.size()));
  // Pump >16KB of trailer lines, never the terminating CRLF.
  const std::string line = "X-Bomb: " + std::string(120, 'b') + "\r\n";
  bool killed = false;
  for (int i = 0; i < 400 && !killed; ++i) {
    if (write(fd, line.data(), line.size()) !=
        static_cast<ssize_t>(line.size())) {
      killed = true;  // server closed on us mid-write
    }
  }
  // Server must close the connection (read returns EOF), with no response.
  char buf[256];
  const ssize_t n = read(fd, buf, sizeof(buf));
  EXPECT(n <= 0);
  close(fd);
}

TEST_CASE(rpcz_linked_spans) {
  start_once();
  // Off by default.
  std::string r = http_get("GET /rpcz HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("rpcz is off") != std::string::npos);
  // Flip on, make a call, expect a linked client+server pair.
  http_get("GET /flags/rpcz_enabled?setvalue=true HTTP/1.1\r\nHost: x\r\n\r\n");
  Channel ch;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port)), 0);
  Controller cntl;
  IOBuf req, resp;
  req.append("traced");
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  // The server submits its span AFTER writing the response; poll briefly
  // so a preempted server fiber can finish.
  bool linked = false;
  std::string client_trace, client_span;
  for (int attempt = 0; attempt < 50 && !linked; ++attempt) {
    usleep(10 * 1000);
    r = http_get("GET /rpcz HTTP/1.1\r\nHost: x\r\n\r\n");
  // Find the client span's trace id and check a server span shares it
  // with parent == client span id.
  size_t pos = 0;
  client_trace.clear();
  while (true) {
    const size_t line_start = r.find('\n', pos);
    if (line_start == std::string::npos) {
      break;
    }
    pos = line_start + 1;
    const std::string line = r.substr(pos, r.find('\n', pos) - pos);
    if (line.size() > 57 && line.find("client") != std::string::npos &&
        line.find("Echo.Echo") != std::string::npos) {
      client_trace = line.substr(0, 16);
      client_span = line.substr(17, 16);
    }
  }
  if (client_trace.empty()) {
    continue;
  }
  pos = 0;
  while (true) {
    const size_t nl = r.find('\n', pos);
    if (nl == std::string::npos) {
      break;
    }
    const std::string line = r.substr(pos, nl - pos);
    pos = nl + 1;
    if (line.size() > 57 && line.substr(0, 16) == client_trace &&
        line.substr(34, 16) == client_span &&
        line.find("server") != std::string::npos) {
      linked = true;
    }
  }
  }
  EXPECT(linked);
  http_get("GET /flags/rpcz_enabled?setvalue=false HTTP/1.1\r\nHost: x\r\n\r\n");
}

TEST_CASE(http_response_parser_vectors) {
  // Content-Length body.
  {
    IOBuf src;
    src.append("HTTP/1.1 200 OK\r\nContent-Length: 5\r\n\r\nhello");
    HttpResponse resp;
    IOBuf body;
    EXPECT_EQ(static_cast<int>(http_parse_response(&src, &resp, &body)),
              static_cast<int>(ParseError::kOk));
    EXPECT_EQ(resp.status, 200);
    EXPECT(resp.reason == "OK");
    EXPECT(body.to_string() == "hello");
    EXPECT_EQ(src.size(), 0u);
  }
  // Chunked body arriving in fragments (resumable state).
  {
    const std::string full =
        "HTTP/1.1 200 OK\r\nTransfer-Encoding: chunked\r\n\r\n"
        "4\r\nwiki\r\n5\r\npedia\r\n0\r\n\r\n";
    IOBuf src;
    std::shared_ptr<void> st;
    HttpResponse resp;
    IOBuf body;
    for (size_t cut = 0; cut < full.size(); cut += 7) {
      src.append(full.substr(cut, 7));
      const ParseError rc = http_parse_response(&src, &resp, &body, &st);
      if (cut + 7 < full.size()) {
        EXPECT_EQ(static_cast<int>(rc),
                  static_cast<int>(ParseError::kNotEnoughData));
      } else {
        EXPECT_EQ(static_cast<int>(rc),
                  static_cast<int>(ParseError::kOk));
      }
    }
    EXPECT(body.to_string() == "wikipedia");
  }
  // 204 has no body even without Content-Length.
  {
    IOBuf src;
    src.append("HTTP/1.1 204 No Content\r\n\r\nNEXT");
    HttpResponse resp;
    IOBuf body;
    EXPECT_EQ(static_cast<int>(http_parse_response(&src, &resp, &body)),
              static_cast<int>(ParseError::kOk));
    EXPECT_EQ(resp.status, 204);
    EXPECT_EQ(body.size(), 0u);
    EXPECT(src.to_string() == "NEXT");  // next response's bytes survive
  }
  // HEAD responses keep their Content-Length but carry no body.
  {
    IOBuf src;
    src.append("HTTP/1.1 200 OK\r\nContent-Length: 999\r\n\r\n");
    HttpResponse resp;
    IOBuf body;
    EXPECT_EQ(static_cast<int>(http_parse_response(
                  &src, &resp, &body, nullptr, /*head_only=*/true)),
              static_cast<int>(ParseError::kOk));
    EXPECT_EQ(body.size(), 0u);
  }
  // Smuggling-class rejects: CL+TE together, garbage status line,
  // unframed body.
  for (const char* bad :
       {"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n"
        "Transfer-Encoding: chunked\r\n\r\nxx",
        "HTTP/9.9 20x OK\r\n\r\n",
        "HTTP/1.1 200 OK\r\n\r\nunframed-tail"}) {
    IOBuf src;
    src.append(bad);
    HttpResponse resp;
    IOBuf body;
    EXPECT_EQ(static_cast<int>(http_parse_response(&src, &resp, &body)),
              static_cast<int>(ParseError::kCorrupted));
  }
}

TEST_CASE(http_client_end_to_end) {
  start_once();
  HttpClient cli;
  EXPECT_EQ(cli.Init("http://127.0.0.1:" + std::to_string(g_port)), 0);
  HttpResult r = cli.Get("/health");
  // Keep-alive: after the first call's connection, further calls must
  // not create sockets (async teardown of EARLIER tests' sockets may
  // decrement the global count, so the check is one-sided).
  const int64_t after_first = g_socket_count.load();
  EXPECT(r.ok);
  EXPECT_EQ(r.status, 200);
  EXPECT(r.body == "OK\n");
  r = cli.Get("/status?format=json");
  EXPECT(r.ok && r.status == 200);
  EXPECT(r.header("Content-Type") != nullptr &&
         *r.header("Content-Type") == "application/json");
  EXPECT(r.body.find("requests_served") != std::string::npos);
  // RPC through the HTTP bridge.
  r = cli.Post("/Echo.Echo", "application/octet-stream", "via-HttpClient");
  EXPECT(r.ok && r.status == 200);
  EXPECT(r.body == "via-HttpClient");
  // 404 is a successful TRANSPORT result.
  r = cli.Get("/definitely-not-here");
  EXPECT(r.ok);
  EXPECT_EQ(r.status, 404);
  // HEAD: headers only.
  r = cli.Head("/health");
  EXPECT(r.ok && r.status == 200);
  EXPECT(r.body.empty());
  EXPECT(g_socket_count.load() <= after_first);
}

TEST_CASE(sockets_ids_vlog_dir_endpoints) {
  start_once();
  // /sockets lists this very connection (it is live while served).
  std::string r = http_get("GET /sockets HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("live sockets") != std::string::npos);
  EXPECT(r.find("127.0.0.1") != std::string::npos);
  EXPECT(r.find(" live") != std::string::npos);
  // /ids shows the correlation-id table (may be empty between calls).
  r = http_get("GET /ids HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("live correlation ids") != std::string::npos);
  // /vlog reads and flips the runtime log threshold, with validation.
  r = http_get("GET /vlog HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("min_log_level") != std::string::npos);
  r = http_get("GET /vlog?setlevel=3 HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("min_log_level 3 (error)") != std::string::npos);
  r = http_get("GET /vlog?setlevel=9 HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("400") != std::string::npos);
  r = http_get("GET /vlog?setlevel=1 HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("min_log_level 1 (info)") != std::string::npos);
  // /dir is opt-in (reference: -enable_dir_service defaults false).
  r = http_get("GET /dir/proc/self HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("403") != std::string::npos);
  r = http_get(
      "GET /flags/enable_dir_service?setvalue=true HTTP/1.1\r\n"
      "Host: x\r\n\r\n");
  EXPECT(r.find("200 OK") != std::string::npos);
  r = http_get("GET /dir/proc/self HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("cmdline") != std::string::npos);
  r = http_get("GET /dir/proc/self/cmdline HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("test_http") != std::string::npos);
  r = http_get("GET /dir/no/such/path HTTP/1.1\r\nHost: x\r\n\r\n");
  EXPECT(r.find("404") != std::string::npos);
  r = http_get(
      "GET /flags/enable_dir_service?setvalue=false HTTP/1.1\r\n"
      "Host: x\r\n\r\n");
  EXPECT(r.find("200 OK") != std::string::npos);
}

TEST_MAIN
