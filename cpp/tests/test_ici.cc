// ICI DMA-ring transport tests (rdma_endpoint parity): the credit-window
// machinery itself (posted blocks, window exhaustion parking the writer,
// deferred _sbuf release, end-to-end consumer backpressure), then the full
// RPC path over the rings, failure injection, and liveness reaping.
#include <errno.h>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <functional>
#include <string>
#include <vector>

#include "base/time.h"
#include "fiber/fiber.h"
#include "fiber/sync.h"
#include "net/channel.h"
#include "net/ici_transport.h"
#include "net/server.h"
#include "net/stream.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

Server* g_server = nullptr;
int g_port = 0;
std::atomic<size_t> g_stream_got{0};

void start_once() {
  if (g_server != nullptr) {
    return;
  }
  g_server = new Server();
  g_server->RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                           IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  g_server->RegisterMethod(
      "IciStream.Up",
      [](Controller* cntl, const IOBuf&, IOBuf* resp, Closure done) {
        StreamOptions sopts;
        sopts.on_message = [](StreamId, IOBuf&& chunk) {
          g_stream_got.fetch_add(chunk.size());
        };
        StreamId sid;
        if (StreamAccept(&sid, cntl, sopts) != 0) {
          cntl->SetFailed(EINVAL, "no stream");
        }
        resp->append("ok");
        done();
      });
  EXPECT_EQ(g_server->Start(0), 0);
  g_port = g_server->port();
}

// ---- raw ring pair (no RPC layer): exposes the machinery ----------------

// A raw receiving end: drains the transport into `acc` on readable edges.
struct RawSink {
  IOBuf acc;
  FiberMutex mu;
  std::atomic<size_t> total{0};
  std::atomic<bool> hold{false};  // when set, received refs are KEPT
  IOBuf held;
};

void raw_on_readable(SocketId id, void*) {
  SocketRef s(Socket::Address(id));
  if (!s) {
    return;
  }
  auto* sink = static_cast<RawSink*>(s->user_data);
  IOBuf got;
  while (true) {
    const ssize_t n = s->transport()->append_to_iobuf(s.get(), &got, 1 << 20);
    if (n <= 0) {
      break;
    }
  }
  if (!got.empty()) {
    LockGuard<FiberMutex> g(sink->mu);
    sink->total.fetch_add(got.size());
    if (sink->hold.load()) {
      sink->held.append(std::move(got));  // refs pin the recv blocks
    } else {
      sink->acc.append(std::move(got));
      sink->acc.clear();  // consume: deleters re-post blocks
    }
  }
}

struct RawPair {
  std::shared_ptr<IciConn> client, server;
  SocketId csock = 0, ssock = 0;
  RawSink csink, ssink;

  bool build() {
    std::string name;
    client = ici_conn_create(&name);
    if (client == nullptr) {
      return false;
    }
    server = ici_conn_open(name);
    if (server == nullptr) {
      return false;
    }
    // Order matters: the server side must exist (server_arena published)
    // before the client socket maps its DMA target.
    if (ici_socket_create(server, &raw_on_readable, nullptr, &ssock) != 0) {
      return false;
    }
    {
      SocketRef s(Socket::Address(ssock));
      s->user_data = &ssink;
    }
    if (ici_socket_create(client, &raw_on_readable, nullptr, &csock) != 0) {
      return false;
    }
    {
      SocketRef s(Socket::Address(csock));
      s->user_data = &csink;
    }
    return true;
  }

  ~RawPair() {
    SocketRef c(Socket::Address(csock));
    if (c) {
      c->SetFailed(ECANCELED);
    }
    SocketRef s(Socket::Address(ssock));
    if (s) {
      s->SetFailed(ECANCELED);
    }
  }
};

bool wait_until(const std::function<bool()>& pred, int64_t timeout_ms) {
  const int64_t deadline = monotonic_time_us() + timeout_ms * 1000;
  while (monotonic_time_us() < deadline) {
    if (pred()) {
      return true;
    }
    usleep(1000);
  }
  return pred();
}

}  // namespace

TEST_CASE(ici_window_exhaustion_and_deferred_release) {
  fiber_init(0);
  // Tiny window: 4 posted blocks of 4KB = 16KB in flight max.  A 1MB write
  // must cycle the window ~64 times; the writer parks on exhaustion and the
  // completion poller wakes it.
  ici_set_ring_geometry(4096, 4);
  auto* pair = new RawPair();
  EXPECT(pair->build());
  const size_t kPayload = 1 << 20;
  std::string big(kPayload, 'x');
  for (size_t i = 0; i < big.size(); i += 37) {
    big[i] = static_cast<char>('A' + (i / 37) % 26);
  }
  IOBuf out;
  out.append(big);
  {
    SocketRef c(Socket::Address(pair->csock));
    EXPECT_EQ(c->Write(std::move(out)), 0);
  }
  EXPECT(wait_until([&] { return pair->ssink.total.load() == kPayload; },
                    10000));
  // Content integrity across window cycles (held under sink lock).
  {
    LockGuard<FiberMutex> g(pair->ssink.mu);
    // acc was consumed block-by-block; re-read via totals only.
  }
  const IciConnStats cs = ici_conn_stats(*pair->client);
  EXPECT_EQ(cs.tx_bytes, kPayload);
  EXPECT(cs.tx_wrs >= kPayload / 4096);
  // The wait-free write queue hit the window (the machinery engaged).
  EXPECT(cs.window_exhausted > 0);
  // All completions arrived: no source refs still deferred.
  EXPECT(wait_until(
      [&] { return ici_conn_stats(*pair->client).sbuf_held == 0; }, 2000));
  ici_set_ring_geometry(64 * 1024, 16);
  delete pair;
}

TEST_CASE(ici_content_integrity_across_window_cycles) {
  fiber_init(0);
  ici_set_ring_geometry(4096, 4);
  auto* pair = new RawPair();
  EXPECT(pair->build());
  // Keep every received ref so we can byte-compare at the end — but that
  // pins recv blocks, so use a payload small enough to fit... no: holding
  // refs stalls the sender forever once the window is consumed.  Instead
  // accumulate a copy.
  pair->ssink.hold.store(true);
  const size_t kPayload = 12 * 1024;  // 3/4 of the 16KB window
  std::string msg(kPayload, 0);
  for (size_t i = 0; i < msg.size(); ++i) {
    msg[i] = static_cast<char>(i * 131 + 7);
  }
  IOBuf out;
  out.append(msg);
  {
    SocketRef c(Socket::Address(pair->csock));
    EXPECT_EQ(c->Write(std::move(out)), 0);
  }
  EXPECT(wait_until([&] { return pair->ssink.total.load() == kPayload; },
                    5000));
  {
    LockGuard<FiberMutex> g(pair->ssink.mu);
    EXPECT(pair->ssink.held.to_string() == msg);
  }
  ici_set_ring_geometry(64 * 1024, 16);
  delete pair;
}

TEST_CASE(ici_consumer_backpressure_reopens_on_release) {
  fiber_init(0);
  // Pool-exhaustion backpressure (block_pool-bound semantics): the
  // receiver READS everything promptly but KEEPS the IOBuf refs.  Re-posts
  // draw fresh blocks from the pool until its cap (8 blocks here); then
  // the sender's window must stay shut even though the reader is prompt.
  ici_set_ring_geometry(4096, 4, /*max_blocks=*/8);
  auto* pair = new RawPair();
  EXPECT(pair->build());
  pair->ssink.hold.store(true);
  const size_t kPool = 4096 * 8;
  std::string big(kPool * 2, 'b');
  IOBuf out;
  out.append(big);
  {
    SocketRef c(Socket::Address(pair->csock));
    EXPECT_EQ(c->Write(std::move(out)), 0);
  }
  // The receiver can take at most the pool while holding refs.
  EXPECT(wait_until([&] { return pair->ssink.total.load() >= kPool; },
                    5000));
  usleep(200 * 1000);  // give a stalled sender time to (wrongly) proceed
  EXPECT_EQ(pair->ssink.total.load(), kPool);
  const IciConnStats held = ici_conn_stats(*pair->server);
  EXPECT_EQ(held.rx_unposted, 8u);  // the whole pool sits with the app
  // Release the refs → blocks return → deferred posts clear → window
  // reopens → transfer finishes.
  {
    LockGuard<FiberMutex> g(pair->ssink.mu);
    pair->ssink.hold.store(false);
    pair->ssink.held.clear();
  }
  EXPECT(wait_until([&] { return pair->ssink.total.load() == big.size(); },
                    10000));
  ici_set_ring_geometry(64 * 1024, 16);
  delete pair;
}

TEST_CASE(ici_setfailed_mid_transfer_releases_everything) {
  fiber_init(0);
  ici_set_ring_geometry(4096, 4);
  // Earlier tests' failed sockets drain their arenas asynchronously (and
  // sanitizer slowdown stretches that window); settle before sampling
  // the baseline or the +2 check below misreads a late unregister.
  size_t slabs_before = ici_registered_slab_count();
  wait_until(
      [&] {
        usleep(50 * 1000);  // count must hold across a 50ms window
        const size_t now = ici_registered_slab_count();
        if (now == slabs_before) {
          return true;
        }
        slabs_before = now;
        return false;
      },
      3000);
  {
    auto* pair = new RawPair();
    EXPECT(pair->build());
    EXPECT_EQ(ici_registered_slab_count(), slabs_before + 2);
    // Receiver holds refs → sender wedges mid-transfer with a full sbuf
    // and a deep write queue.
    pair->ssink.hold.store(true);
    std::string big(1 << 20, 'k');
    IOBuf out;
    out.append(big);
    {
      SocketRef c(Socket::Address(pair->csock));
      EXPECT_EQ(c->Write(std::move(out)), 0);
    }
    EXPECT(wait_until([&] { return pair->ssink.total.load() >= 4096; },
                      5000));
    // Fail the sender mid-transfer from another thread of control.
    {
      SocketRef c(Socket::Address(pair->csock));
      c->SetFailed(ECONNRESET);
    }
    // The parked KeepWrite fiber must observe the failure and drop the
    // remaining queue; held refs on the receiver keep ITS slab alive.
    EXPECT(wait_until(
        [&] { return Socket::Address(pair->csock) == nullptr; }, 2000));
    delete pair;  // fails server socket too
  }
  // Sockets drain asynchronously (KeepWrite/read fibers hold refs); both
  // arenas must unregister once everything lets go.
  EXPECT(wait_until(
      [&] { return ici_registered_slab_count() == slabs_before; }, 5000));
  ici_set_ring_geometry(64 * 1024, 16);
}

TEST_CASE(ici_hostile_consumed_cursor_fails_socket_not_poller) {
  fiber_init(0);
  // ADVICE r4 (medium): a hostile peer storing a huge desc_consumed must
  // fail THAT socket (like every other ring-corruption check), not wedge
  // the completion poller draining toward 2^62.
  ici_set_ring_geometry(4096, 4);
  auto* pair = new RawPair();
  EXPECT(pair->build());
  std::string msg(4096, 'h');
  IOBuf out;
  out.append(msg);
  {
    SocketRef c(Socket::Address(pair->csock));
    EXPECT_EQ(c->Write(std::move(out)), 0);
  }
  EXPECT(wait_until([&] { return pair->ssink.total.load() == msg.size(); },
                    5000));
  ici_conn_corrupt_tx_consumed(*pair->client, uint64_t(1) << 62);
  // Poller detects corruption and fails the client socket.
  EXPECT(wait_until(
      [&] {
        SocketRef c(Socket::Address(pair->csock));
        return !c || c->Failed();
      },
      5000));
  // And the poller survived: a fresh pair still moves bytes.
  auto* pair2 = new RawPair();
  EXPECT(pair2->build());
  IOBuf out2;
  out2.append(std::string(1000, 'y'));
  {
    SocketRef c(Socket::Address(pair2->csock));
    EXPECT_EQ(c->Write(std::move(out2)), 0);
  }
  EXPECT(wait_until([&] { return pair2->ssink.total.load() == 1000; }, 5000));
  ici_set_ring_geometry(64 * 1024, 16);
  delete pair2;
  delete pair;
}

TEST_CASE(ici_staging_zero_copy_single_descriptor) {
  fiber_init(0);
  // A 1MB payload in a registered staging slab crosses the ring as ONE
  // sender-owned descriptor — no window cycling (4KB x 4 slots would need
  // ~256 cycles copy-mode), no ring DMA copy.
  ici_set_ring_geometry(4096, 4);
  // Earlier tests' failed sockets drain their arenas asynchronously;
  // settle before sampling the baseline or the final check misreads.
  size_t slabs_before = ici_registered_slab_count();
  wait_until(
      [&] {
        const size_t now = ici_registered_slab_count();
        if (now == slabs_before) {
          return true;
        }
        slabs_before = now;
        return false;
      },
      3000);
  uint32_t ord = 0;
  const size_t kLen = 1 << 20;
  char* stage = static_cast<char*>(ici_staging_alloc(kLen, &ord));
  EXPECT(stage != nullptr);
  EXPECT_EQ(ici_registered_slab_count(), slabs_before + 1);
  for (size_t i = 0; i < kLen; ++i) {
    stage[i] = static_cast<char>(i * 31 + 5);
  }
  auto* pair = new RawPair();
  EXPECT(pair->build());
  pair->ssink.hold.store(true);  // keep refs: verify content + deferral
  IOBuf out;
  out.append_user_data(stage, kLen, [](void*, void*) {}, nullptr, 0);
  {
    SocketRef c(Socket::Address(pair->csock));
    EXPECT_EQ(c->Write(std::move(out)), 0);
  }
  EXPECT(wait_until([&] { return pair->ssink.total.load() == kLen; }, 5000));
  const IciConnStats cs = ici_conn_stats(*pair->client);
  EXPECT_EQ(cs.tx_zero_copy_wrs, 1u);       // ONE descriptor for 1MB
  EXPECT_EQ(cs.tx_zero_copy_bytes, kLen);
  EXPECT_EQ(ici_conn_stats(*pair->server).rx_zero_copy_wrs, 1u);
  {
    LockGuard<FiberMutex> g(pair->ssink.mu);
    EXPECT(pair->ssink.held.to_string() ==
           std::string(stage, kLen));  // zero-copy content intact
  }
  // Deferred ack: while the receiver holds the wrapped range, the
  // descriptor must NOT complete (sender staging is still referenced).
  usleep(100 * 1000);
  EXPECT_EQ(ici_conn_stats(*pair->client).sbuf_held, 1u);
  // Free-while-referenced: the slab's name+registration go away now, but
  // the MAPPING must survive until the held refs drop (the consumer
  // keeps reading valid bytes — use-after-munmap regression).
  ici_staging_free(stage);
  // Unregistration is immediate (the pair's two rx arenas remain).
  EXPECT(wait_until(
      [&] { return ici_registered_slab_count() <= slabs_before + 2; },
      5000));
  {
    LockGuard<FiberMutex> g(pair->ssink.mu);
    EXPECT(pair->ssink.held.to_string() ==
           std::string(stage, kLen));  // still readable post-free
    pair->ssink.hold.store(false);
    pair->ssink.held.clear();  // drop refs → deleter acks → sbuf drains
  }
  EXPECT(wait_until(
      [&] { return ici_conn_stats(*pair->client).sbuf_held == 0; }, 2000));
  ici_set_ring_geometry(64 * 1024, 16);
  delete pair;
  EXPECT(wait_until(
      [&] { return ici_registered_slab_count() <= slabs_before; }, 5000));
}

TEST_CASE(ici_staging_rpc_echo_roundtrip_zero_copy) {
  // Full RPC over the rings with a staged payload: request AND (loopback)
  // response ride sender-owned descriptors; content verified end-to-end.
  start_once();
  uint64_t zc_wrs0 = 0, zc_bytes0 = 0;
  ici_zero_copy_counters(&zc_wrs0, &zc_bytes0);
  const size_t kLen = 2 << 20;
  uint32_t ord = 0;
  char* stage = static_cast<char*>(ici_staging_alloc(kLen, &ord));
  EXPECT(stage != nullptr);
  for (size_t i = 0; i < kLen; ++i) {
    stage[i] = static_cast<char>(i * 131 + 7);
  }
  Channel ch;
  Channel::Options opts;
  opts.use_ici = true;
  opts.timeout_ms = 10000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port), &opts), 0);
  Controller cntl;
  IOBuf req, resp;
  req.append_user_data(stage, kLen, [](void*, void*) {}, nullptr, 0);
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT(resp.to_string() == std::string(stage, kLen));
  uint64_t zc_wrs1 = 0, zc_bytes1 = 0;
  ici_zero_copy_counters(&zc_wrs1, &zc_bytes1);
  // At least the request payload went zero-copy (the tstd frame header
  // rides a normal block; the big ref is its own descriptor); loopback
  // echoes typically add the response too.
  EXPECT(zc_wrs1 > zc_wrs0);
  EXPECT(zc_bytes1 - zc_bytes0 >= kLen);
  ici_staging_free(stage);
}

TEST_CASE(ici_staging_repeated_large_echo_bench_geometry) {
  // Bench-shaped repro: 256KB x 32 rings, 64MB staged payload, repeated
  // sync echoes (the r5 bench wedged here at ~call 2).
  start_once();
  ici_set_ring_geometry(256 * 1024, 32, 1024);
  const size_t kLen = 64 << 20;
  uint32_t ord = 0;
  char* stage = static_cast<char*>(ici_staging_alloc(kLen, &ord));
  EXPECT(stage != nullptr);
  memset(stage, 0x5a, kLen);
  Channel ch;
  Channel::Options opts;
  opts.use_ici = true;
  opts.timeout_ms = 15000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port), &opts), 0);
  for (size_t len : {size_t{2} << 20, size_t{8} << 20, size_t{16} << 20,
                     size_t{32} << 20, size_t{64} << 20}) {
    for (int i = 0; i < 2; ++i) {
      Controller cntl;
      cntl.set_timeout_ms(8000);
      IOBuf req, resp;
      req.append_user_data(stage, len, [](void*, void*) {}, nullptr, 0);
      ch.CallMethod("Echo.Echo", req, &resp, &cntl);
      if (cntl.Failed()) {
        fprintf(stderr, "FAILED at len=%zu iter=%d: %s\n", len, i,
                cntl.error_text().c_str());
      }
      EXPECT(!cntl.Failed());
      EXPECT_EQ(resp.size(), len);
    }
  }
  ici_set_ring_geometry(64 * 1024, 16);
  ici_staging_free(stage);
}

// ---- full RPC path over the rings ---------------------------------------

TEST_CASE(ici_echo_roundtrip) {
  start_once();
  Channel ch;
  Channel::Options opts;
  opts.use_ici = true;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port), &opts), 0);
  for (int i = 0; i < 20; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("ici-" + std::to_string(i));
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    EXPECT(resp.to_string() == "ici-" + std::to_string(i));
  }
  EXPECT(ch.transport_name() == "ici_ring");
}

TEST_CASE(ici_payload_larger_than_window) {
  start_once();
  // 5MB payload through a 1MB window (16×64KB): many full window cycles in
  // both directions under the real RPC framing.
  Channel ch;
  Channel::Options opts;
  opts.use_ici = true;
  opts.timeout_ms = 15000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port), &opts), 0);
  std::string big(5 * 1024 * 1024, 'z');
  for (size_t i = 0; i < big.size(); i += 101) {
    big[i] = static_cast<char>('a' + i % 26);
  }
  Controller cntl;
  cntl.set_timeout_ms(15000);
  IOBuf req, resp;
  req.append(big);
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT_EQ(resp.size(), big.size());
  EXPECT(resp.to_string() == big);
}

TEST_CASE(ici_concurrent_calls) {
  start_once();
  static Channel ch;
  Channel::Options opts;
  opts.use_ici = true;
  opts.timeout_ms = 5000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port), &opts), 0);
  static std::atomic<int> ok{0};
  ok = 0;
  std::vector<fiber_t> ids(16);
  for (size_t i = 0; i < ids.size(); ++i) {
    fiber_start(&ids[i], [](void* arg) {
      const int base = static_cast<int>(reinterpret_cast<intptr_t>(arg));
      for (int k = 0; k < 20; ++k) {
        Controller cntl;
        cntl.set_timeout_ms(5000);
        IOBuf req, resp;
        req.append("p" + std::to_string(base * 100 + k) +
                   std::string(2000, 'q'));
        ch.CallMethod("Echo.Echo", req, &resp, &cntl);
        if (!cntl.Failed() && resp.size() == req.size()) {
          ok.fetch_add(1);
        }
      }
    }, reinterpret_cast<void*>(static_cast<intptr_t>(i)));
  }
  for (auto f : ids) {
    fiber_join(f);
  }
  EXPECT_EQ(ok.load(), 16 * 20);
}

TEST_CASE(ici_streaming_over_rings) {
  start_once();
  // Streaming RPC rides any transport; over ICI the stream's credit window
  // composes with the ring window.
  g_stream_got = 0;
  Channel ch;
  Channel::Options opts;
  opts.use_ici = true;
  opts.timeout_ms = 10000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(g_port), &opts), 0);
  StreamId sid = 0;
  Controller cntl;
  cntl.set_timeout_ms(10000);
  StreamOptions sopts;
  EXPECT_EQ(StreamCreate(&sid, &cntl, sopts), 0);
  IOBuf req, resp;
  req.append("start");
  ch.CallMethod("IciStream.Up", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  const std::string chunk(256 * 1024, 's');
  size_t sent = 0;
  for (int i = 0; i < 16; ++i) {
    IOBuf b;
    b.append(chunk);
    if (StreamWrite(sid, std::move(b)) == 0) {
      sent += chunk.size();
    }
  }
  EXPECT_EQ(sent, chunk.size() * 16);
  EXPECT(wait_until([&] { return g_stream_got.load() == sent; }, 10000));
  StreamClose(sid);
}

namespace {
// Auth over the rings: the bootstrap TCP channel must carry the
// credential (the server gates EVERY method, including __ici.Connect),
// and the fd-less ring socket must then authenticate itself too.
struct TokenAuth : public Authenticator {
  int generate_credential(std::string* s) const override {
    *s = "ici-secret";
    return 0;
  }
  int verify_credential(const std::string& s,
                        const EndPoint&) const override {
    return s == "ici-secret" ? 0 : -1;
  }
};
}  // namespace

TEST_CASE(ici_with_authenticated_server) {
  static TokenAuth auth;
  Server srv;
  srv.set_authenticator(&auth);
  srv.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                     IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  EXPECT_EQ(srv.Start(0), 0);
  Channel ch;
  Channel::Options opts;
  opts.use_ici = true;
  opts.auth = &auth;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(srv.port()), &opts), 0);
  Controller cntl;
  IOBuf req, resp;
  req.append("authed");
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT(resp.to_string() == "authed");
  // The call must have ridden the rings, not the TCP fallback.
  EXPECT(ch.transport_name() == "ici_ring");
  srv.Stop();
}

TEST_CASE(ici_bad_segment_rejected) {
  start_once();
  Channel tcp;
  EXPECT_EQ(tcp.Init("127.0.0.1:" + std::to_string(g_port)), 0);
  for (const char* bad :
       {"/etc/passwd", "not-a-path", "/trpc_ici_", "", "/trpc_arena_x"}) {
    Controller cntl;
    IOBuf req, resp;
    req.append(bad);
    tcp.CallMethod(kIciConnectMethod, req, &resp, &cntl);
    EXPECT(cntl.Failed());
    EXPECT_EQ(cntl.error_code(), EINVAL);
  }
  // A well-named segment with hostile contents (bad magic/geometry) must
  // be rejected too.
  const char* fake = "/trpc_ici_99999_feed";
  const int fd = shm_open(fake, O_CREAT | O_EXCL | O_RDWR, 0600);
  EXPECT(fd >= 0);
  EXPECT_EQ(ftruncate(fd, 1 << 20), 0);
  close(fd);
  Controller cntl;
  IOBuf req, resp;
  req.append(fake);
  tcp.CallMethod(kIciConnectMethod, req, &resp, &cntl);
  EXPECT(cntl.Failed());
  shm_unlink(fake);
}

TEST_CASE(ici_dead_peer_reaped_and_segment_unlinked) {
  start_once();
  std::string name;
  auto client = ici_conn_create(&name);
  EXPECT(client != nullptr);
  {
    Channel tcp;
    EXPECT_EQ(tcp.Init("127.0.0.1:" + std::to_string(g_port)), 0);
    Controller cntl;
    IOBuf req, resp;
    req.append(name);
    tcp.CallMethod(kIciConnectMethod, req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  pid_t child = fork();
  if (child == 0) {
    _exit(0);
  }
  int status = 0;
  waitpid(child, &status, 0);
  ici_conn_set_self_pid(*client, static_cast<int32_t>(child));
  bool unlinked = false;
  for (int i = 0; i < 80 && !unlinked; ++i) {
    usleep(100 * 1000);
    const int fd = shm_open(name.c_str(), O_RDWR, 0600);
    if (fd < 0 && errno == ENOENT) {
      unlinked = true;
    } else if (fd >= 0) {
      close(fd);
    }
  }
  EXPECT(unlinked);
}

TEST_CASE(ici_coalesce_desc_len_guard) {
  // Regression (ADVICE r5): the staging coalesce loop publishes the WR
  // length as uint32; growing a coalesced WR past UINT32_MAX would
  // silently truncate at the static_cast and corrupt >4GiB frames.  The
  // guard must stop EXACTLY at the boundary.
  const uint64_t max32 = 0xffffffffull;
  EXPECT(ici_desc_len_fits(0, max32));
  EXPECT(ici_desc_len_fits(max32 - 1, 1));
  EXPECT(ici_desc_len_fits(max32, 0));
  EXPECT(!ici_desc_len_fits(max32, 1));
  EXPECT(!ici_desc_len_fits(max32 - 1, 2));
  // The old loop bound (2^31 pre-append) admitted a 4GiB-1 ref on top of
  // a near-2^31 WR — exactly the silent-truncation shape.
  EXPECT(!ici_desc_len_fits((1ull << 31) - 1, max32));
  EXPECT(ici_desc_len_fits((1ull << 31) - 1, 1ull << 31));
}

TEST_CASE(ici_peer_stage_maps_read_only) {
  // Regression (ADVICE r5): a REMOTE peer's staging slab must map
  // PROT_READ — a receiver-side bug scribbling the sender's registered
  // payload memory would corrupt frames the sender believes are already
  // immutably in flight.  Map our own slab through the same path a
  // remote receiver uses and check the kernel's view of the mapping.
  constexpr size_t kLen = 64 * 1024;
  uint32_t ord = 0;
  char* stage = static_cast<char*>(ici_staging_alloc(kLen, &ord));
  EXPECT(stage != nullptr);
  memset(stage, 0x5a, kLen);
  const std::string name = ici_test_stage_shm_name(getpid(), ord);
  size_t mapped_len = 0;
  char* ro = ici_test_map_peer_stage(name, &mapped_len);
  EXPECT(ro != nullptr);
  EXPECT(mapped_len >= kLen);
  EXPECT(ro[0] == 0x5a && ro[kLen - 1] == 0x5a);  // readable, same bytes
  // /proc/self/maps must report the mapping read-only ("r--").
  char want[64];
  snprintf(want, sizeof(want), "%lx-", reinterpret_cast<unsigned long>(ro));
  FILE* maps = fopen("/proc/self/maps", "r");
  EXPECT(maps != nullptr);
  bool found = false, readonly = false;
  char line[512];
  while (fgets(line, sizeof(line), maps) != nullptr) {
    if (strncmp(line, want, strlen(want)) == 0) {
      found = true;
      const char* perms = strchr(line, ' ');
      readonly = perms != nullptr && strncmp(perms + 1, "r--", 3) == 0;
      break;
    }
  }
  fclose(maps);
  EXPECT(found);
  EXPECT(readonly);
  munmap(ro, mapped_len);
  ici_staging_free(stage);
}

TEST_MAIN
