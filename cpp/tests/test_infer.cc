// Streamed-inference front door tests (net/infer.h, ISSUE 20):
// end-to-end token streaming with EOS, continuous batching (requests
// join the running batch mid-flight and leave without idling a slot),
// prefix-cache prefill skipping recompute on a repeated prompt, deadline
// expiry cancelling a live stream, client close freeing the slot the
// same step, the chaos case (mid-stream disconnect under svr_delay
// aborts remote prefix fetches whole-or-nothing, credits
// deadline_cancel_saved_bytes, wedges nothing), per-tenant typed
// shedding under overload, flag-bound validation, and token_step
// timeline events.  Runs under TSan + ASan via tests/test_cpp.py.
#include "net/infer.h"

#include <unistd.h>

#include <atomic>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "base/flags.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/controller.h"
#include "net/deadline.h"
#include "net/kvstore.h"
#include "net/server.h"
#include "net/stream.h"
#include "stat/timeline.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

struct Serving {
  Server* srv = nullptr;
  InferScheduler* sched = nullptr;
  int port = 0;

  ~Serving() {
    if (sched != nullptr) {
      infer_stop(sched);
    }
    delete srv;
  }
};

void make_serving(Serving* s, const InferOptions& opts = InferOptions{}) {
  s->srv = new Server();
  s->sched = infer_attach(s->srv, opts);
  EXPECT(s->sched != nullptr);
  EXPECT_EQ(s->srv->Start(0), 0);
  s->port = s->srv->port();
}

std::string addr_of(const Serving& s) {
  return "127.0.0.1:" + std::to_string(s.port);
}

// Client side of one completion: offers the token stream, submits, and
// collects TokenRecords as the scheduler pushes them.
struct TokenClient {
  struct State {
    std::mutex mu;
    std::vector<TokenRecord> recs;
    std::atomic<int> nrecs{0};
    std::atomic<bool> closed{false};
  };
  std::shared_ptr<State> st = std::make_shared<State>();
  StreamId sid = 0;
  InferSubmitReply reply;
  int error_code = 0;
  bool ok = false;

  std::vector<TokenRecord> records() {
    std::lock_guard<std::mutex> g(st->mu);
    return st->recs;
  }
  bool wait_closed(int64_t timeout_ms = 5000) {
    const int64_t deadline = monotonic_time_us() + timeout_ms * 1000;
    while (!st->closed.load() && monotonic_time_us() < deadline) {
      usleep(5000);
    }
    return st->closed.load();
  }
  bool wait_records(int n, int64_t timeout_ms = 5000) {
    const int64_t deadline = monotonic_time_us() + timeout_ms * 1000;
    while (st->nrecs.load() < n && monotonic_time_us() < deadline) {
      usleep(5000);
    }
    return st->nrecs.load() >= n;
  }
};

TokenClient submit(Channel* ch, const std::vector<uint64_t>& prompt,
                   uint32_t max_new, int64_t timeout_ms = 30000,
                   const std::string& tenant = "", uint32_t flags = 0,
                   int64_t window_bytes = 0) {
  TokenClient c;
  auto st = c.st;
  Controller cntl;
  if (timeout_ms > 0) {
    cntl.set_timeout_ms(timeout_ms);
  }
  if (!tenant.empty()) {
    cntl.set_qos(tenant, 0);
  }
  StreamOptions opts;
  if (window_bytes > 0) {
    opts.window_bytes = window_bytes;
  }
  opts.on_message = [st](StreamId, IOBuf&& chunk) {
    TokenRecord rec;
    if (chunk.size() >= sizeof(rec)) {
      chunk.copy_to(&rec, sizeof(rec));
      std::lock_guard<std::mutex> g(st->mu);
      st->recs.push_back(rec);
    }
    st->nrecs.fetch_add(1);
  };
  opts.on_closed = [st](StreamId) { st->closed.store(true); };
  EXPECT_EQ(StreamCreate(&c.sid, &cntl, opts), 0);
  InferSubmitWire w;
  w.magic = kInferMagic;
  w.flags = flags;
  w.max_new_tokens = max_new;
  w.n_prompt_tokens = static_cast<uint32_t>(prompt.size());
  IOBuf req, resp;
  req.append(&w, sizeof(w));
  if (!prompt.empty()) {
    req.append(prompt.data(), prompt.size() * sizeof(uint64_t));
  }
  ch->CallMethod("Infer.Submit", req, &resp, &cntl);
  if (cntl.Failed()) {
    c.error_code = cntl.error_code();
    return c;
  }
  EXPECT_EQ(resp.size(), sizeof(InferSubmitReply));
  resp.copy_to(&c.reply, sizeof(c.reply));
  c.ok = true;
  return c;
}

std::vector<uint64_t> make_prompt(uint64_t seed, size_t n) {
  std::vector<uint64_t> p(n);
  for (size_t i = 0; i < n; ++i) {
    p[i] = seed * 100003 + i + 1;
  }
  return p;
}

void set_flag(const char* name, const std::string& value) {
  EXPECT_EQ(Flag::set(name, value), 0);
}

// Every test pins the flags it depends on (flags are process-global and
// earlier cases change them).
void reset_infer_flags() {
  infer_ensure_registered();
  kv_ensure_registered();  // trpc_kv_prefix_block_tokens lives there
  set_flag("trpc_infer_batch_max", "256");
  set_flag("trpc_infer_queue_max", "200000");
  set_flag("trpc_infer_step_us", "1000");
  set_flag("trpc_infer_prefill_us_per_token", "0");
  set_flag("trpc_infer_max_new_tokens", "256");
  set_flag("trpc_infer_bytes_per_token", "64");
  set_flag("trpc_kv_prefix_block_tokens", "8");
}

int64_t wait_live_zero(InferScheduler* sched, int64_t timeout_ms = 5000) {
  const int64_t deadline = monotonic_time_us() + timeout_ms * 1000;
  while (infer_streams_live(sched) > 0 && monotonic_time_us() < deadline) {
    usleep(5000);
  }
  return infer_streams_live(sched);
}

}  // namespace

TEST_CASE(infer_end_to_end_tokens_and_eos) {
  reset_infer_flags();
  Serving s;
  make_serving(&s);
  Channel ch;
  EXPECT_EQ(ch.Init(addr_of(s)), 0);

  TokenClient c = submit(&ch, make_prompt(1, 4), 8);
  EXPECT(c.ok);
  EXPECT(c.reply.request_id != 0);
  EXPECT_EQ(c.reply.cached_tokens, 0u);  // no prefix cache attached
  EXPECT(c.wait_records(8));
  EXPECT(c.wait_closed());
  auto recs = c.records();
  EXPECT_EQ(recs.size(), 8u);
  for (size_t i = 0; i < recs.size(); ++i) {
    EXPECT_EQ(recs[i].index, i);  // strictly ordered, no gaps
  }
  EXPECT_EQ(recs.back().flags, kTokenEos);
  // Same prompt generates the same tokens (deterministic decode sim).
  TokenClient c2 = submit(&ch, make_prompt(1, 4), 8);
  EXPECT(c2.ok);
  EXPECT(c2.wait_closed());
  auto recs2 = c2.records();
  EXPECT_EQ(recs2.size(), 8u);
  EXPECT_EQ(recs2[0].token, recs[0].token);
  EXPECT_EQ(wait_live_zero(s.sched), 0);
}

TEST_CASE(infer_continuous_batching_join_and_leave) {
  reset_infer_flags();
  set_flag("trpc_infer_batch_max", "2");
  set_flag("trpc_infer_step_us", "5000");
  Serving s;
  make_serving(&s);
  Channel ch;
  EXPECT_EQ(ch.Init(addr_of(s)), 0);

  // A occupies a slot for ~1s; B finishes in ~25ms and frees its slot;
  // C (queued behind the full batch) must JOIN the running batch the
  // step B leaves and finish while A is still streaming.
  TokenClient a = submit(&ch, make_prompt(2, 4), 200);
  TokenClient b = submit(&ch, make_prompt(3, 4), 5);
  TokenClient c = submit(&ch, make_prompt(4, 4), 5);
  EXPECT(a.ok);
  EXPECT(b.ok);
  EXPECT(c.ok);
  EXPECT(b.wait_closed());
  EXPECT(c.wait_closed());
  EXPECT_EQ(c.records().back().flags, kTokenEos);
  // A is mid-generation: its stream is open and far from done — C's
  // completion happened inside A's window, proving mid-flight join.
  EXPECT(!a.st->closed.load());
  EXPECT(a.st->nrecs.load() < 200);
  StreamClose(a.sid);  // client walks away; slot must free
  EXPECT(a.wait_closed());
  EXPECT_EQ(wait_live_zero(s.sched), 0);
}

TEST_CASE(infer_prefix_cache_skips_recompute) {
  reset_infer_flags();
  set_flag("trpc_infer_prefill_us_per_token", "200");
  static KvStore store;
  static KvRegistry registry;
  InferOptions opts;
  opts.store = &store;
  opts.registry = &registry;
  opts.node = "serve0";
  Serving s;
  make_serving(&s, opts);
  Channel ch;
  EXPECT_EQ(ch.Init(addr_of(s)), 0);

  // 32 tokens = 4 full blocks at block_tokens=8.
  const auto prompt = make_prompt(5, 32);
  const int64_t recomputed0 =
      infer_vars().prefill_bytes_recomputed.get_value();
  const int64_t cached_bytes0 =
      infer_vars().prefill_bytes_cached.get_value();

  // Cold: nothing cached, every byte recomputed, blocks published.
  TokenClient c1 = submit(&ch, prompt, 4);
  EXPECT(c1.ok);
  EXPECT_EQ(c1.reply.cached_tokens, 0u);
  EXPECT(c1.wait_closed());
  EXPECT_EQ(registry.prefix_count(), 4u);
  const int64_t recomputed1 =
      infer_vars().prefill_bytes_recomputed.get_value();
  EXPECT_EQ(recomputed1 - recomputed0, 32 * 64);

  // Warm: the whole prompt chain matches; prefill pulls bytes from the
  // store instead of recomputing ANY of them.
  TokenClient c2 = submit(&ch, prompt, 4);
  EXPECT(c2.ok);
  EXPECT_EQ(c2.reply.cached_tokens, 32u);
  EXPECT_EQ(c2.reply.block_tokens, 8u);
  EXPECT(c2.wait_closed());
  EXPECT_EQ(infer_vars().prefill_bytes_recomputed.get_value(), recomputed1);
  EXPECT_EQ(infer_vars().prefill_bytes_cached.get_value() - cached_bytes0,
            4 * 8 * 64);  // 4 blocks x block_tokens x bytes_per_token
  // Deterministic decode: the cached path emits the same tokens.
  EXPECT_EQ(c1.records()[0].token, c2.records()[0].token);
  EXPECT_EQ(wait_live_zero(s.sched), 0);
  store.clear();
  registry.clear();
}

TEST_CASE(infer_deadline_expiry_cancels_midstream) {
  reset_infer_flags();
  set_flag("trpc_infer_step_us", "20000");  // 20ms/token: 256 tokens ≈ 5s
  Serving s;
  make_serving(&s);
  Channel ch;
  EXPECT_EQ(ch.Init(addr_of(s)), 0);

  const int64_t cancelled0 = infer_vars().cancelled_total.get_value();
  // The submit call's 400ms budget becomes the request's end-to-end
  // deadline; generation needs ~5s, so the scheduler must reap it.
  TokenClient c = submit(&ch, make_prompt(6, 4), 256, /*timeout_ms=*/400);
  EXPECT(c.ok);
  EXPECT(c.wait_closed(10000));
  auto recs = c.records();
  EXPECT(!recs.empty());
  EXPECT(recs.size() < 256u);
  EXPECT_EQ(recs.back().flags, kTokenCancelled);
  EXPECT(infer_vars().cancelled_total.get_value() > cancelled0);
  EXPECT_EQ(wait_live_zero(s.sched), 0);
}

TEST_CASE(infer_client_close_frees_slot_for_waiter) {
  reset_infer_flags();
  set_flag("trpc_infer_batch_max", "1");
  set_flag("trpc_infer_step_us", "5000");
  Serving s;
  make_serving(&s);
  Channel ch;
  EXPECT_EQ(ch.Init(addr_of(s)), 0);

  TokenClient hog = submit(&ch, make_prompt(7, 4), 200);
  TokenClient waiter = submit(&ch, make_prompt(8, 4), 3);
  EXPECT(hog.ok);
  EXPECT(waiter.ok);
  EXPECT(hog.wait_records(1));
  EXPECT(!waiter.st->closed.load());
  // The only slot is held; closing the hog's stream client-side must
  // free it and admit the waiter the same step.
  StreamClose(hog.sid);
  EXPECT(waiter.wait_closed());
  EXPECT_EQ(waiter.records().back().flags, kTokenEos);
  EXPECT_EQ(wait_live_zero(s.sched), 0);
}

// The ISSUE 20 chaos case: a client disconnect mid-prefill, while the
// scheduler is pulling this request's matched prefix blocks from a
// DELAYED remote kv node, must abort the fetch sequence whole-or-nothing
// — unpulled bytes credited to deadline_cancel_saved_bytes, the aborted
// counter bumped, no stream or slot wedged, and the slot reusable.
TEST_CASE(infer_chaos_disconnect_aborts_prefix_fetch) {
  reset_infer_flags();

  // kv node: serves Kv.FetchPrefix out of the process store, with every
  // request delayed 100ms (fault plane svr_delay).
  Server* kvsrv = new Server();
  EXPECT_EQ(kv_attach_store(kvsrv), 0);
  EXPECT_EQ(kvsrv->Start(0), 0);
  EXPECT_EQ(kvsrv->SetFaults("svr_delay=1:100"), 0);
  const std::string kv_addr =
      "127.0.0.1:" + std::to_string(kvsrv->port());

  // Pre-populate: the prompt's 4 chain blocks live on the kv node.
  const auto prompt = make_prompt(9, 32);
  static KvRegistry registry;
  Key128 keys[8];
  const size_t nkeys = kv_prefix_chain(prompt.data(), prompt.size(), 8,
                                       keys, 8);
  EXPECT_EQ(nkeys, 4u);
  std::vector<uint8_t> block(8 * 64, 0xab);
  for (size_t d = 0; d < nkeys; ++d) {
    KvPrefixMeta meta;
    EXPECT_EQ(kv_store().publish_prefix(keys[d], static_cast<uint32_t>(d),
                                        block.data(), block.size(),
                                        prompt.data() + d * 8, 8, 60000,
                                        &meta),
              0);
    snprintf(meta.node, sizeof(meta.node), "kvnode");
    uint64_t gen = 0;
    EXPECT_EQ(registry.put_prefix(meta, 60000, &gen), 0);
  }

  // Serving node: matches against the registry, pulls over the wire from
  // the delayed kv node (no local store — every block is a remote RPC).
  InferOptions opts;
  opts.registry = &registry;
  opts.kv_fetch_addr = kv_addr;
  Serving s;
  make_serving(&s, opts);
  Channel ch;
  EXPECT_EQ(ch.Init(addr_of(s)), 0);

  const int64_t saved0 = deadline_vars().cancel_saved_bytes.get_value();
  const int64_t aborted0 = infer_vars().prefix_fetch_aborted.get_value();
  const int64_t cached0 = infer_vars().prefill_bytes_cached.get_value();

  TokenClient c = submit(&ch, prompt, 4);
  EXPECT(c.ok);
  EXPECT_EQ(c.reply.cached_tokens, 32u);
  // 4 blocks x 100ms delay each: disconnect ~150ms in, mid-chain.
  usleep(150 * 1000);
  StreamClose(c.sid);

  // The scheduler must reap the request and abort the in-flight pull.
  EXPECT_EQ(wait_live_zero(s.sched, 10000), 0);
  const int64_t deadline = monotonic_time_us() + 5 * 1000 * 1000;
  while (infer_vars().prefix_fetch_aborted.get_value() == aborted0 &&
         monotonic_time_us() < deadline) {
    usleep(5000);
  }
  EXPECT(infer_vars().prefix_fetch_aborted.get_value() > aborted0);
  EXPECT(deadline_vars().cancel_saved_bytes.get_value() > saved0);
  // Whole-or-nothing: whatever DID land is an integral number of
  // blocks, and at least one block was still unpulled when cancelled.
  const int64_t pulled =
      infer_vars().prefill_bytes_cached.get_value() - cached0;
  EXPECT_EQ(pulled % (8 * 64), 0);
  EXPECT(pulled < static_cast<int64_t>(nkeys) * 8 * 64);

  // Nothing wedged: the freed slot serves a fresh (uncached) request.
  TokenClient c2 = submit(&ch, make_prompt(10, 4), 3);
  EXPECT(c2.ok);
  EXPECT(c2.wait_closed());
  EXPECT_EQ(c2.records().back().flags, kTokenEos);
  EXPECT_EQ(wait_live_zero(s.sched), 0);

  registry.clear();
  kv_store().clear();
  delete kvsrv;
}

TEST_CASE(infer_overload_sheds_typed_per_tenant) {
  reset_infer_flags();
  set_flag("trpc_infer_batch_max", "2");
  set_flag("trpc_infer_queue_max", "6");  // cap = 8, pressure at live >= 4
  set_flag("trpc_infer_step_us", "5000");
  Serving s;
  make_serving(&s);
  Channel ch;
  EXPECT_EQ(ch.Init(addr_of(s)), 0);

  const int64_t shed0 = infer_vars().shed_total.get_value();
  std::vector<TokenClient> held;
  for (int i = 0; i < 4; ++i) {
    held.push_back(submit(&ch, make_prompt(20 + i, 4), 200, 30000, "hog"));
    EXPECT(held.back().ok);
  }
  held.push_back(submit(&ch, make_prompt(30, 4), 200, 30000, "victim"));
  EXPECT(held.back().ok);

  // Under pressure (live=5 of cap 8), "hog" holds 4 of a fair share of
  // 4 — its next submit sheds TYPED (kEOverloaded), not a timeout...
  TokenClient hog_extra =
      submit(&ch, make_prompt(31, 4), 200, 30000, "hog");
  EXPECT(!hog_extra.ok);
  EXPECT_EQ(hog_extra.error_code, kEOverloaded);
  EXPECT(infer_vars().shed_total.get_value() > shed0);
  // ...while the in-share tenant still admits at the same instant.
  TokenClient victim2 =
      submit(&ch, make_prompt(32, 4), 200, 30000, "victim");
  EXPECT(victim2.ok);
  held.push_back(victim2);

  for (auto& c : held) {
    StreamClose(c.sid);
  }
  EXPECT_EQ(wait_live_zero(s.sched, 10000), 0);
}

// A client advertising a stream window that cannot fit even ONE
// TokenRecord must be rejected at submit: admitting it unclamped would
// park the shared decode fiber on the first StreamWrite, stalling every
// tenant's requests (and the deadline reaper that runs in the same
// fiber).
TEST_CASE(infer_tiny_window_rejected_not_parked) {
  reset_infer_flags();
  Serving s;
  make_serving(&s);
  Channel ch;
  EXPECT_EQ(ch.Init(addr_of(s)), 0);

  TokenClient tiny = submit(&ch, make_prompt(50, 4), 8, 30000, "", 0,
                            /*window_bytes=*/8);
  EXPECT(!tiny.ok);
  EXPECT_EQ(tiny.error_code, EINVAL);
  // The admission slot reserved for it was released, not leaked.
  EXPECT_EQ(wait_live_zero(s.sched), 0);

  // The decode loop never parked: a sane request still completes.
  TokenClient c = submit(&ch, make_prompt(51, 4), 4);
  EXPECT(c.ok);
  EXPECT(c.wait_closed());
  EXPECT_EQ(c.records().back().flags, kTokenEos);
  EXPECT_EQ(wait_live_zero(s.sched), 0);
}

// Shutdown with a prefix fetch mid-RPC: infer_stop must cancel the
// request, WAIT for the detached fetch fiber to retire, and only then
// free the fetch channel and scheduler — the fiber holds a raw
// scheduler pointer, so ASan/TSan catch any early free here.
TEST_CASE(infer_stop_drains_inflight_prefix_fetch) {
  reset_infer_flags();
  Server* kvsrv = new Server();
  EXPECT_EQ(kv_attach_store(kvsrv), 0);
  EXPECT_EQ(kvsrv->Start(0), 0);
  EXPECT_EQ(kvsrv->SetFaults("svr_delay=1:100"), 0);
  const std::string kv_addr =
      "127.0.0.1:" + std::to_string(kvsrv->port());

  const auto prompt = make_prompt(52, 32);
  static KvRegistry registry;
  Key128 keys[8];
  const size_t nkeys = kv_prefix_chain(prompt.data(), prompt.size(), 8,
                                       keys, 8);
  EXPECT_EQ(nkeys, 4u);
  std::vector<uint8_t> block(8 * 64, 0xcd);
  for (size_t d = 0; d < nkeys; ++d) {
    KvPrefixMeta meta;
    EXPECT_EQ(kv_store().publish_prefix(keys[d], static_cast<uint32_t>(d),
                                        block.data(), block.size(),
                                        prompt.data() + d * 8, 8, 60000,
                                        &meta),
              0);
    snprintf(meta.node, sizeof(meta.node), "kvnode");
    uint64_t gen = 0;
    EXPECT_EQ(registry.put_prefix(meta, 60000, &gen), 0);
  }

  InferOptions opts;
  opts.registry = &registry;
  opts.kv_fetch_addr = kv_addr;
  auto* srv = new Server();
  InferScheduler* sched = infer_attach(srv, opts);
  EXPECT(sched != nullptr);
  EXPECT_EQ(srv->Start(0), 0);
  Channel ch;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(srv->port())), 0);

  TokenClient c = submit(&ch, prompt, 4);
  EXPECT(c.ok);
  EXPECT_EQ(c.reply.cached_tokens, 32u);
  // 4 blocks x 100ms delay each: stop ~120ms in, fetch mid-chain.
  usleep(120 * 1000);
  infer_stop(sched);
  delete srv;
  EXPECT(c.wait_closed());

  registry.clear();
  kv_store().clear();
  delete kvsrv;
}

TEST_CASE(infer_flag_bounds_validated) {
  infer_ensure_registered();
  EXPECT(Flag::set("trpc_infer_batch_max", "0") != 0);
  EXPECT(Flag::set("trpc_infer_batch_max", "70000") != 0);
  EXPECT_EQ(Flag::set("trpc_infer_batch_max", "16"), 0);
  EXPECT(Flag::set("trpc_infer_step_us", "-1") != 0);
  EXPECT(Flag::set("trpc_infer_queue_max", "2000000") != 0);
  EXPECT(Flag::set("trpc_infer_max_new_tokens", "0") != 0);
  EXPECT(Flag::set("trpc_infer_bytes_per_token", "0") != 0);
  EXPECT(Flag::set("trpc_infer_prefill_us_per_token", "1000001") != 0);
  reset_infer_flags();
}

TEST_CASE(infer_timeline_token_step_events) {
  reset_infer_flags();
  timeline::ensure_registered();
  EXPECT_EQ(Flag::set("trpc_timeline", "true"), 0);
  timeline::reset();
  Serving s;
  make_serving(&s);
  Channel ch;
  EXPECT_EQ(ch.Init(addr_of(s)), 0);

  TokenClient c = submit(&ch, make_prompt(40, 4), 4);
  EXPECT(c.ok);
  EXPECT(c.wait_closed());
  EXPECT_EQ(wait_live_zero(s.sched), 0);

  // admit + prefill_done + 4 tokens + eos = 7 token_step events.
  const std::string dump = timeline::dump_json(1 << 16);
  size_t count = 0;
  for (size_t pos = dump.find("\"token_step\""); pos != std::string::npos;
       pos = dump.find("\"token_step\"", pos + 1)) {
    ++count;
  }
  EXPECT(count >= 7);
  EXPECT_EQ(Flag::set("trpc_timeline", "false"), 0);
}

TEST_MAIN
