// Paged KV-block registry tests (net/kvstore.h): registry lifecycle and
// lease semantics, generation minting across evictions, double-register
// rejection, store eviction under byte-budget pressure, zero-copy
// serving out of registered pages, client lookup-cache invalidation on
// stale generations, the one-sided fetch ride over shm, and chunk-fault
// whole-or-nothing composition — the block-addressed transfer tier the
// prefill/decode disaggregation workload (tools/kv_disagg.py) runs on.
#include <unistd.h>

#include <cstring>
#include <string>

#include "base/flags.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/controller.h"
#include "net/fault.h"
#include "net/hotpath_stats.h"
#include "net/kvstore.h"
#include "net/rma.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

Server* g_server = nullptr;
int g_port = 0;

void start_once() {
  if (g_server != nullptr) {
    return;
  }
  g_server = new Server();
  kv_attach_store(g_server);
  kv_attach_registry(g_server);
  g_server->RegisterMethod("Token.Step", [](Controller*, const IOBuf& req,
                                            IOBuf* resp, Closure done) {
    resp->append(req);  // zero-copy ref share
    done();
  });
  EXPECT_EQ(g_server->Start(0), 0);
  g_port = g_server->port();
}

std::string addr() { return "127.0.0.1:" + std::to_string(g_port); }

// Patterned block content: a mis-offset or torn landing can never
// byte-match its own pattern.
void fill_pattern(char* p, size_t n, uint32_t salt) {
  for (size_t i = 0; i < n; ++i) {
    p[i] = static_cast<char>(((i + salt) * 2654435761u) >> 13);
  }
}

bool check_pattern(const IOBuf& buf, size_t n, uint32_t salt) {
  if (buf.size() != n) {
    return false;
  }
  std::string got = buf.to_string();
  for (size_t i = 0; i < n; ++i) {
    if (got[i] != static_cast<char>(((i + salt) * 2654435761u) >> 13)) {
      return false;
    }
  }
  return true;
}

struct FaultGuard {
  ~FaultGuard() { FaultActor::global().set(""); }
};

struct FlagGuard {
  std::string name, old_value;
  FlagGuard(const std::string& n, const std::string& v) : name(n) {
    old_value = Flag::find(n)->value_string();
    EXPECT_EQ(Flag::set(n, v), 0);
  }
  ~FlagGuard() { Flag::set(name, old_value); }
};

struct KvReset {
  KvReset() {
    kv_store().clear();
    kv_registry().clear();
  }
  ~KvReset() {
    kv_store().clear();
    kv_registry().clear();
  }
};

KvBlockMeta meta_for(uint64_t id, uint64_t gen, uint64_t len,
                     const char* node = "127.0.0.1:1") {
  KvBlockMeta m;
  m.block_id = id;
  m.generation = gen;
  m.rkey = 0x42;
  m.off = 0;
  m.len = len;
  snprintf(m.node, sizeof(m.node), "%s", node);
  return m;
}

}  // namespace

// -- registry ---------------------------------------------------------------

TEST_CASE(kv_registry_lifecycle_and_leases) {
  KvReset reset;
  KvRegistry& reg = kv_registry();
  uint64_t gen = 0;
  EXPECT_EQ(reg.do_register(meta_for(7, 1, 1024), 60000, &gen), 0);
  EXPECT_EQ(gen, 1u);
  KvBlockMeta out;
  int64_t left = 0;
  EXPECT_EQ(reg.lookup(7, &out, &left), 0);
  EXPECT_EQ(out.generation, 1u);
  EXPECT_EQ(out.len, 1024u);
  EXPECT(left > 0 && left <= 60000);
  EXPECT(std::string(out.node) == "127.0.0.1:1");
  // Unknown block: miss.
  EXPECT_EQ(reg.lookup(8, &out), kEKvMiss);
  // Eviction removes; a later lookup misses.
  uint64_t egen = 0;
  EXPECT_EQ(reg.evict(7, &egen), 0);
  EXPECT_EQ(egen, 1u);
  EXPECT_EQ(reg.lookup(7, &out), kEKvMiss);
  EXPECT_EQ(reg.evict(7, &egen), kEKvMiss);

  // Lease expiry: a 60ms lease lapses and the record prunes lazily.
  EXPECT_EQ(reg.do_register(meta_for(9, 2, 64), 60, &gen), 0);
  EXPECT_EQ(reg.lookup(9, &out), 0);
  usleep(90 * 1000);
  EXPECT_EQ(reg.lookup(9, &out), kEKvMiss);
  // A lapsed lease cannot be renewed, only re-registered.
  EXPECT_EQ(reg.renew(9, 60000), kEKvMiss);
  EXPECT_EQ(reg.do_register(meta_for(9, 3, 64), 60, &gen), 0);
  EXPECT_EQ(reg.renew(9, 60000), 0);
  usleep(90 * 1000);  // outlives the ORIGINAL 60ms lease
  EXPECT_EQ(reg.lookup(9, &out), 0);  // renew extended it
}

TEST_CASE(kv_registry_double_register_rejected) {
  KvReset reset;
  KvRegistry& reg = kv_registry();
  uint64_t gen = 0;
  EXPECT_EQ(reg.do_register(meta_for(5, 1, 128), 60000, &gen), 0);
  // Same generation while live: exclusive ownership holds.
  EXPECT_EQ(reg.do_register(meta_for(5, 1, 128), 60000, &gen), kEKvExists);
  // Older generation after the block moved on: zombie publisher.
  EXPECT_EQ(reg.do_register(meta_for(5, 3, 128), 60000, &gen), 0);
  EXPECT_EQ(reg.do_register(meta_for(5, 2, 128), 60000, &gen), kEKvStale);
  // The newer generation replaced the record in place.
  KvBlockMeta out;
  EXPECT_EQ(reg.lookup(5, &out), 0);
  EXPECT_EQ(out.generation, 3u);
  // Generation 0 is never minted: malformed registration.
  EXPECT_EQ(reg.do_register(meta_for(6, 0, 128), 60000, &gen), kEKvStale);
}

// -- store ------------------------------------------------------------------

TEST_CASE(kv_store_publish_fetch_zero_copy_generations) {
  KvReset reset;
  const size_t len = 1 << 20;
  uint64_t rkey = 0;
  char* region = static_cast<char*>(rma_alloc(4 << 20, &rkey));
  EXPECT(region != nullptr);
  fill_pattern(region, len, 3);
  KvBlockMeta m;
  EXPECT_EQ(kv_store().publish(21, region, len, 60000, &m), 0);
  EXPECT_EQ(m.generation, 1u);
  EXPECT_EQ(m.rkey, rkey);
  EXPECT_EQ(m.off, 0u);
  // Double-publish of a live block: rejected.
  EXPECT_EQ(kv_store().publish(21, region, len, 60000, &m), kEKvExists);
  // Non-registered memory is not publishable (zero-copy serving only).
  char stack_buf[64];
  EXPECT_EQ(kv_store().publish(22, stack_buf, sizeof(stack_buf), 0, &m), -1);

  IOBuf out;
  EXPECT_EQ(kv_store().fetch(21, 1, &out), 0);
  EXPECT(check_pattern(out, len, 3));
  // Zero-copy: the served payload is ONE block pointing into the region.
  EXPECT_EQ(out.block_count(), 1u);

  // Wrong generation: stale, nothing served.
  IOBuf out2;
  EXPECT_EQ(kv_store().fetch(21, 2, &out2), kEKvStale);
  EXPECT_EQ(out2.size(), 0u);
  // Withdraw tombstones the generation; fetch answers stale (the caller
  // held a record once), unknown ids answer miss.
  EXPECT_EQ(kv_store().withdraw(21), 0);
  EXPECT_EQ(kv_store().fetch(21, 1, &out2), kEKvStale);
  EXPECT_EQ(kv_store().fetch(999, 1, &out2), kEKvMiss);
  // Re-publish continues the generation sequence.
  EXPECT_EQ(kv_store().publish(21, region, len, 60000, &m), 0);
  EXPECT_EQ(m.generation, 2u);
  IOBuf out3;
  EXPECT_EQ(kv_store().fetch(21, 1, &out3), kEKvStale);  // old record
  EXPECT_EQ(kv_store().fetch(21, 2, &out3), 0);
  rma_free(region);
}

TEST_CASE(kv_store_lease_expiry_never_admits_stale) {
  KvReset reset;
  const size_t len = 64 << 10;
  uint64_t rkey = 0;
  char* region = static_cast<char*>(rma_alloc(len, &rkey));
  EXPECT(region != nullptr);
  fill_pattern(region, len, 5);
  KvBlockMeta m;
  EXPECT_EQ(kv_store().publish(31, region, len, 60, &m), 0);
  IOBuf ok;
  EXPECT_EQ(kv_store().fetch(31, m.generation, &ok), 0);
  usleep(90 * 1000);
  // Validity is decided AT SERVE TIME: the lapsed lease serves nothing,
  // even with the generation the caller legitimately held.
  IOBuf out;
  EXPECT_EQ(kv_store().fetch(31, m.generation, &out), kEKvStale);
  EXPECT_EQ(out.size(), 0u);
  EXPECT_EQ(kv_store().count(), 0u);  // folded to a tombstone
  rma_free(region);
}

TEST_CASE(kv_store_eviction_under_budget_pressure) {
  KvReset reset;
  const size_t len = 1 << 20;
  FlagGuard budget("trpc_kv_store_bytes", std::to_string(3 << 20));
  uint64_t rkey = 0;
  char* region = static_cast<char*>(rma_alloc(8 << 20, &rkey));
  EXPECT(region != nullptr);
  KvBlockMeta m;
  for (uint64_t id = 1; id <= 3; ++id) {
    EXPECT_EQ(kv_store().publish(id, region + (id - 1) * len, len, 60000,
                                 &m), 0);
  }
  EXPECT_EQ(kv_store().count(), 3u);
  EXPECT_EQ(kv_store().bytes_used(), static_cast<uint64_t>(3 << 20));
  // Touch block 1 (a fetch bumps LRU), then publish block 4: the budget
  // holds 3 — the LRU victim must be block 2, never the just-touched 1.
  IOBuf touch;
  EXPECT_EQ(kv_store().fetch(1, 1, &touch), 0);
  EXPECT_EQ(kv_store().publish(4, region + 3 * len, len, 60000, &m), 0);
  EXPECT_EQ(kv_store().count(), 3u);
  IOBuf out;
  EXPECT_EQ(kv_store().fetch(2, 1, &out), kEKvStale);  // evicted
  EXPECT_EQ(kv_store().fetch(1, 1, &out), 0);          // LRU-protected
  // A block bigger than the whole budget is rejected outright.
  EXPECT_EQ(kv_store().publish(9, region, 4 << 20, 60000, &m), -1);
  // A re-publish of the evicted block mints a NEWER generation.
  EXPECT_EQ(kv_store().publish(2, region + len, len, 60000, &m), 0);
  EXPECT_EQ(m.generation, 2u);
  rma_free(region);
}

// -- RPC surface + cache ----------------------------------------------------

TEST_CASE(kv_rpc_end_to_end_with_cache_invalidation) {
  KvReset reset;
  start_once();
  const size_t len = 1 << 20;
  uint64_t rkey = 0;
  char* region = static_cast<char*>(rma_alloc(4 << 20, &rkey));
  EXPECT(region != nullptr);
  fill_pattern(region, len, 11);
  KvBlockMeta m;
  EXPECT_EQ(kv_store().publish(41, region, len, 60000, &m), 0);
  snprintf(m.node, sizeof(m.node), "%s", addr().c_str());

  Channel reg_ch;
  Channel::Options opts;
  opts.timeout_ms = 20000;
  EXPECT_EQ(reg_ch.Init(addr(), &opts), 0);
  // Register over the wire.
  {
    KvWire w;
    memset(&w, 0, sizeof(w));
    w.block_id = m.block_id;
    w.generation = m.generation;
    w.rkey = m.rkey;
    w.off = m.off;
    w.len = m.len;
    w.lease_ms = 60000;
    memcpy(w.node, m.node, sizeof(w.node));
    IOBuf req, resp;
    req.append(&w, sizeof(w));
    Controller cntl;
    reg_ch.CallMethod(kKvRegisterMethod, req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    uint64_t gen = 0;
    EXPECT_EQ(resp.size(), sizeof(gen));
    resp.copy_to(&gen, sizeof(gen));
    EXPECT_EQ(gen, 1u);
  }

  KvCache cache(&reg_ch);
  KvBlockMeta got;
  EXPECT_EQ(cache.lookup(41, &got), 0);
  EXPECT_EQ(got.generation, 1u);
  EXPECT_EQ(cache.misses(), 1u);
  EXPECT_EQ(cache.lookup(41, &got), 0);  // cached
  EXPECT_EQ(cache.hits(), 1u);

  IOBuf bytes;
  EXPECT_EQ(cache.fetch(&reg_ch, 41, &bytes), 0);
  EXPECT(check_pattern(bytes, len, 11));

  // The publisher re-publishes (evict + publish = generation 2) and
  // re-registers; the decode side's CACHED generation-1 record must be
  // invalidated by the stale answer and the retry must land gen 2.
  EXPECT_EQ(kv_store().withdraw(41), 0);
  fill_pattern(region, len, 12);
  EXPECT_EQ(kv_store().publish(41, region, len, 60000, &m), 0);
  EXPECT_EQ(m.generation, 2u);
  {
    KvWire w;
    memset(&w, 0, sizeof(w));
    w.block_id = 41;
    w.generation = 2;
    w.rkey = m.rkey;
    w.off = m.off;
    w.len = m.len;
    w.lease_ms = 60000;
    snprintf(w.node, sizeof(w.node), "%s", addr().c_str());
    IOBuf req, resp;
    req.append(&w, sizeof(w));
    Controller cntl;
    reg_ch.CallMethod(kKvRegisterMethod, req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  IOBuf bytes2;
  const uint64_t misses_before = cache.misses();
  EXPECT_EQ(cache.fetch(&reg_ch, 41, &bytes2), 0);
  EXPECT(check_pattern(bytes2, len, 12));  // the NEW generation's bytes
  EXPECT_EQ(cache.misses(), misses_before + 1);  // stale → re-lookup
  rma_free(region);
}

TEST_CASE(kv_fetch_rides_one_sided_over_shm) {
  KvReset reset;
  start_once();
  const size_t len = 8 << 20;
  uint64_t rkey = 0;
  char* region = static_cast<char*>(rma_alloc(len, &rkey));
  EXPECT(region != nullptr);
  fill_pattern(region, len, 21);
  KvBlockMeta m;
  EXPECT_EQ(kv_store().publish(51, region, len, 60000, &m), 0);

  Channel ch;
  Channel::Options opts;
  opts.timeout_ms = 60000;
  opts.use_shm = true;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  {
    Controller warm;  // establish the ring
    IOBuf req, resp;
    req.append("warm");
    ch.CallMethod("Token.Step", req, &resp, &warm);
    EXPECT(!warm.Failed());
  }
  HotPathVars& v = hotpath_vars();
  const int64_t rx0 = v.rma_rx_msgs.get_value();
  KvWire w;
  memset(&w, 0, sizeof(w));
  w.block_id = 51;
  w.generation = m.generation;
  IOBuf req, resp;
  req.append(&w, sizeof(w));
  Controller cntl;
  cntl.set_timeout_ms(60000);
  ch.CallMethod(kKvFetchMethod, req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT(check_pattern(resp, len, 21));
  // The MB-scale response rode the one-sided window put, not the frame
  // plane: block-addressed transfer over the RMA fabric, verified.
  EXPECT(v.rma_rx_msgs.get_value() > rx0);
  rma_free(region);
}

TEST_CASE(kv_chunk_fault_whole_or_nothing_and_recovery) {
  KvReset reset;
  start_once();
  const size_t len = 8 << 20;
  uint64_t rkey = 0;
  char* region = static_cast<char*>(rma_alloc(len, &rkey));
  EXPECT(region != nullptr);
  fill_pattern(region, len, 31);
  KvBlockMeta m;
  EXPECT_EQ(kv_store().publish(61, region, len, 600000, &m), 0);

  Channel ch;
  Channel::Options opts;
  opts.timeout_ms = 60000;
  opts.use_shm = true;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  {
    Controller warm;
    IOBuf req, resp;
    req.append("warm");
    ch.CallMethod("Token.Step", req, &resp, &warm);
    EXPECT(!warm.Failed());
  }
  KvWire w;
  memset(&w, 0, sizeof(w));
  w.block_id = 61;
  w.generation = m.generation;
  {
    FaultGuard guard;
    EXPECT_EQ(FaultActor::global().set("seed=11;drop=0.7"), 0);
    IOBuf req, resp;
    req.append(&w, sizeof(w));
    Controller cntl;
    cntl.set_timeout_ms(1500);
    ch.CallMethod(kKvFetchMethod, req, &resp, &cntl);
    // Dropped chunks leave completion bits clear: the block fetch fails
    // WHOLE — no partial bytes are ever dispatched as a response.
    EXPECT(cntl.Failed());
    EXPECT_EQ(resp.size(), 0u);
  }
  // Faults cleared: the SAME cached record still works (transport
  // failures never invalidate the block's generation), byte-exact.
  IOBuf req2, resp2;
  req2.append(&w, sizeof(w));
  Controller ok;
  ok.set_timeout_ms(60000);
  ch.CallMethod(kKvFetchMethod, req2, &resp2, &ok);
  EXPECT(!ok.Failed());
  EXPECT(check_pattern(resp2, len, 31));
  rma_free(region);
}

// -- content-addressed prefix cache (ISSUE 17) ------------------------------

namespace {

KvPrefixMeta prefix_meta_for(const Key128& key, const Key128& hash,
                             uint64_t gen, uint64_t len,
                             const char* node, uint32_t depth = 0) {
  KvPrefixMeta m;
  m.key = key;
  m.hash = hash;
  m.generation = gen;
  m.len = len;
  m.depth = depth;
  snprintf(m.node, sizeof(m.node), "%s", node);
  return m;
}

Key128 k128(uint64_t hi, uint64_t lo) {
  Key128 k;
  k.hi = hi;
  k.lo = lo;
  return k;
}

}  // namespace

TEST_CASE(kv_prefix_registry_dedup_replica_sets) {
  KvReset reset;
  KvRegistry& reg = kv_registry();
  const Key128 key = k128(0x11, 0x22);
  const Key128 hash = k128(0xAA, 0xBB);
  uint64_t gen = 0;
  // Two publishers of the SAME (key, hash): one record, two replicas.
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, hash, 1, 4096, "127.0.0.1:1"),
                60000, &gen), 0);
  const uint64_t dedup0 =
      KvPrefixCounters::read(kv_prefix_counters().dedup);
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, hash, 1, 4096, "127.0.0.1:2"),
                60000, &gen), 0);
  EXPECT_EQ(reg.prefix_count(), 1u);
  EXPECT_EQ(reg.prefix_replicas(), 2u);
  EXPECT_EQ(KvPrefixCounters::read(kv_prefix_counters().dedup),
            dedup0 + 1);
  // Same node, same generation: idempotent renew (every cache hit
  // re-offers), answered kEKvExists — no third replica.
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, hash, 1, 4096, "127.0.0.1:1"),
                60000, &gen), kEKvExists);
  EXPECT_EQ(reg.prefix_replicas(), 2u);
  // Same node, newer generation: replaces in place.
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, hash, 3, 4096, "127.0.0.1:1"),
                60000, &gen), 0);
  EXPECT_EQ(gen, 3u);
  EXPECT_EQ(reg.prefix_replicas(), 2u);
  // Zombie publisher re-offering an older generation: fenced.
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, hash, 2, 4096, "127.0.0.1:1"),
                60000, &gen), kEKvStale);
  // Same chain key, DIFFERENT content hash: divergence, never aliased.
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, k128(0xAA, 0xCC), 1, 4096,
                                "127.0.0.1:3"),
                60000, &gen), kEKvStale);
  // Generation 0 is never minted: malformed.
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, hash, 0, 4096, "127.0.0.1:4"),
                60000, &gen), kEKvStale);
}

TEST_CASE(kv_prefix_replica_lease_expiry_and_zombie_fence) {
  KvReset reset;
  KvRegistry& reg = kv_registry();
  const Key128 key = k128(0x31, 0x32);
  const Key128 hash = k128(0xDD, 0xEE);
  uint64_t gen = 0;
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, hash, 5, 1024, "127.0.0.1:1"),
                60, &gen), 0);
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, hash, 2, 1024, "127.0.0.1:2"),
                60000, &gen), 0);
  EXPECT_EQ(reg.prefix_replicas(), 2u);
  usleep(90 * 1000);  // node 1's lease lapses; node 2's holds
  std::vector<KvPrefixMeta> out;
  EXPECT_EQ(reg.match(&key, 1, &out), 1u);
  EXPECT_EQ(out.size(), 1u);  // the expired replica pruned in match
  EXPECT(std::string(out[0].node) == "127.0.0.1:2");
  // The per-node fence SURVIVES pruning: node 1 re-offering its old
  // generation is still a zombie; a fresh generation re-admits.
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, hash, 4, 1024, "127.0.0.1:1"),
                60000, &gen), kEKvStale);
  EXPECT_EQ(reg.put_prefix(
                prefix_meta_for(key, hash, 6, 1024, "127.0.0.1:1"),
                60000, &gen), 0);
  EXPECT_EQ(reg.prefix_replicas(), 2u);
}

TEST_CASE(kv_prefix_trie_longest_match_walk) {
  KvReset reset;
  // Chain keys: deterministic, prefix-stable, block-size-sensitive.
  uint64_t tokens[512];
  for (size_t i = 0; i < 512; ++i) {
    tokens[i] = 1000 + i;
  }
  Key128 chain[4], chain2[4], shorter[2];
  EXPECT_EQ(kv_prefix_chain(tokens, 512, 128, chain, 4), 4u);
  EXPECT_EQ(kv_prefix_chain(tokens, 512, 128, chain2, 4), 4u);
  EXPECT_EQ(kv_prefix_chain(tokens, 300, 128, shorter, 2), 2u);
  for (int i = 0; i < 4; ++i) {
    EXPECT(chain[i] == chain2[i]);
  }
  EXPECT(chain[0] == shorter[0] && chain[1] == shorter[1]);
  Key128 other_bs[2];
  EXPECT_EQ(kv_prefix_chain(tokens, 512, 256, other_bs, 2), 2u);
  EXPECT(other_bs[0] != chain[0]);  // block size folds into the keys
  // A diverging token in block 1 changes keys 1..3 but not key 0.
  uint64_t diverged[512];
  memcpy(diverged, tokens, sizeof(tokens));
  diverged[200] ^= 1;
  Key128 chain_d[4];
  EXPECT_EQ(kv_prefix_chain(diverged, 512, 128, chain_d, 4), 4u);
  EXPECT(chain_d[0] == chain[0]);
  EXPECT(chain_d[1] != chain[1] && chain_d[3] != chain[3]);

  // Registry walk: 3 of 4 blocks cached -> longest prefix is 3; a hole
  // at depth 1 stops the walk at 1 regardless of deeper blocks.
  KvRegistry& reg = kv_registry();
  const Key128 hash = k128(0x77, 0x88);
  uint64_t gen = 0;
  for (uint32_t d = 0; d < 3; ++d) {
    EXPECT_EQ(reg.put_prefix(
                  prefix_meta_for(chain[d], k128(0x77, 0x88 + d), 1,
                                  4096, "127.0.0.1:1", d),
                  60000, &gen), 0);
  }
  (void)hash;
  std::vector<KvPrefixMeta> out;
  std::vector<int64_t> leases;
  EXPECT_EQ(reg.match(chain, 4, &out, &leases), 3u);
  EXPECT_EQ(out.size(), 3u);
  EXPECT_EQ(leases.size(), 3u);
  for (size_t i = 0; i < out.size(); ++i) {
    EXPECT_EQ(out[i].depth, static_cast<uint32_t>(i));
    EXPECT(leases[i] > 0);
  }
  EXPECT_EQ(reg.evict_prefix(chain[1], "127.0.0.1:1"), 0);
  EXPECT_EQ(reg.match(chain, 4, nullptr), 1u);  // the walk stops at the hole
}

TEST_CASE(kv_prefix_two_tier_promotion_on_hit) {
  KvReset reset;
  FlagGuard hot("trpc_kv_prefix_hot_bytes", std::to_string(1 << 20));
  const size_t len = 768 << 10;
  std::string a(len, '\0'), b(len, '\0');
  fill_pattern(a.data(), len, 41);
  fill_pattern(b.data(), len, 42);
  uint64_t toks_a[4] = {1, 2, 3, 4}, toks_b[4] = {5, 6, 7, 8};
  KvPrefixMeta ma, mb;
  EXPECT_EQ(kv_store().publish_prefix(k128(1, 1), 0, a.data(), len,
                                      toks_a, 4, 60000, &ma), 0);
  EXPECT_EQ(ma.generation, 1u);
  EXPECT(ma.rkey != 0);  // hot: registered pages
  EXPECT_EQ(kv_store().prefix_hot_bytes(), len);
  // Identical re-publish: the cache-hit path — kEKvExists, record
  // echoed, NO new bytes admitted.
  KvPrefixMeta dup;
  EXPECT_EQ(kv_store().publish_prefix(k128(1, 1), 0, a.data(), len,
                                      toks_a, 4, 60000, &dup), kEKvExists);
  EXPECT(dup.hash == ma.hash);
  EXPECT_EQ(kv_store().prefix_count(), 1u);
  // Block B exceeds the remaining hot budget: A (LRU) demotes, B lands
  // hot.  Nothing drops.
  const uint64_t demote0 =
      KvPrefixCounters::read(kv_prefix_counters().demote);
  EXPECT_EQ(kv_store().publish_prefix(k128(1, 2), 1, b.data(), len,
                                      toks_b, 4, 60000, &mb), 0);
  EXPECT_EQ(kv_store().prefix_count(), 2u);
  EXPECT_EQ(kv_store().prefix_hot_bytes(), len);
  EXPECT_EQ(kv_store().prefix_cold_bytes(), len);
  EXPECT_EQ(KvPrefixCounters::read(kv_prefix_counters().demote),
            demote0 + 1);
  // Fetching demoted A is a COLD hit that promotes it back (B demotes
  // in turn) — the bytes are identical either way.
  const uint64_t promote0 =
      KvPrefixCounters::read(kv_prefix_counters().promote);
  IOBuf out_a;
  EXPECT_EQ(kv_store().fetch_prefix(ma.hash, ma.generation, &out_a), 0);
  EXPECT(check_pattern(out_a, len, 41));
  EXPECT_EQ(KvPrefixCounters::read(kv_prefix_counters().promote),
            promote0 + 1);
  EXPECT_EQ(kv_store().prefix_hot_bytes(), len);   // A hot again
  EXPECT_EQ(kv_store().prefix_cold_bytes(), len);  // B demoted
  // A second fetch of A is a hot zero-copy hit.
  const uint64_t hot0 =
      KvPrefixCounters::read(kv_prefix_counters().hot_hits);
  IOBuf out_a2;
  EXPECT_EQ(kv_store().fetch_prefix(ma.hash, ma.generation, &out_a2), 0);
  EXPECT(check_pattern(out_a2, len, 41));
  EXPECT_EQ(out_a2.block_count(), 1u);  // served from registered pages
  EXPECT_EQ(KvPrefixCounters::read(kv_prefix_counters().hot_hits),
            hot0 + 1);
  // Wrong generation: stale.  Unknown hash: miss.
  IOBuf bad;
  EXPECT_EQ(kv_store().fetch_prefix(ma.hash, 99, &bad), kEKvStale);
  EXPECT_EQ(kv_store().fetch_prefix(k128(9, 9), 0, &bad), kEKvMiss);
}

TEST_CASE(kv_prefix_demote_under_budget_drops_cold_last) {
  KvReset reset;
  FlagGuard total("trpc_kv_store_bytes", std::to_string(3 << 20));
  FlagGuard hot("trpc_kv_prefix_hot_bytes", std::to_string(1 << 20));
  const size_t len = 1 << 20;
  std::string buf(len, '\0');
  KvPrefixMeta m[4];
  for (uint64_t i = 0; i < 4; ++i) {
    fill_pattern(buf.data(), len, 50 + i);
    uint64_t toks[2] = {i, i + 1};
    EXPECT_EQ(kv_store().publish_prefix(k128(2, i), 0, buf.data(), len,
                                        toks, 2, 60000, &m[i]), 0);
  }
  // Budget holds 3 x 1MB: block 0 (the LRU COLD block) dropped with a
  // tombstone; 1..3 survive — the newest hot, the others demoted.
  EXPECT_EQ(kv_store().prefix_count(), 3u);
  EXPECT_EQ(kv_store().prefix_hot_bytes(), len);
  EXPECT_EQ(kv_store().prefix_cold_bytes(), 2 * len);
  IOBuf out;
  EXPECT_EQ(kv_store().fetch_prefix(m[0].hash, m[0].generation, &out),
            kEKvStale);  // dropped block: tombstoned, never silent
  EXPECT_EQ(kv_store().fetch_prefix(m[1].hash, m[1].generation, &out), 0);
  EXPECT(check_pattern(out, len, 51));
  // A re-publish of the dropped block mints a NEWER generation.
  fill_pattern(buf.data(), len, 50);
  uint64_t toks0[2] = {0, 1};
  KvPrefixMeta again;
  EXPECT_EQ(kv_store().publish_prefix(k128(2, 0), 0, buf.data(), len,
                                      toks0, 2, 60000, &again), 0);
  EXPECT_EQ(again.generation, m[0].generation + 1);
  // Drain tombstones EVERY prefix block (successor re-homing relies on
  // the stale answer, never on silence).
  EXPECT(kv_store().withdraw_all() >= 3u);
  EXPECT_EQ(kv_store().prefix_count(), 0u);
  EXPECT_EQ(kv_store().fetch_prefix(again.hash, again.generation, &out),
            kEKvStale);
}

TEST_MAIN
