// Legacy protocol family: nshead raw service + client, esp msg_id
// correlation, and the four pbrpc personalities (hulu/sofa by magic,
// nova/public over nshead) all dispatching into the shared method
// registry.  Every loopback goes over real sockets through protocol
// probing on the shared port.
#include <atomic>
#include <thread>

#include "net/legacy_pbrpc.h"
#include "net/nshead.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

TEST_CASE(nshead_loopback_raw) {
  NsheadService svc([](const NsheadHead& head, const IOBuf& body,
                       NsheadHead* resp_head, IOBuf* resp_body) {
    // Echo body; reflect log_id into reserved to prove head plumbing.
    resp_head->reserved = head.log_id + 1;
    resp_body->append(body);
    resp_body->append("!");
  });
  Server server;
  server.set_nshead_service(&svc);
  EXPECT_EQ(server.Start(0), 0);

  NsheadClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(server.port())), 0);
  NsheadHead head;
  head.log_id = 41;
  IOBuf body;
  body.append("payload");
  NsheadHead rsp_head;
  IOBuf rsp_body;
  EXPECT_EQ(cli.call(head, body, &rsp_head, &rsp_body), 0);
  EXPECT_EQ(rsp_head.reserved, 42u);
  EXPECT(rsp_body.to_string() == "payload!");
  EXPECT_EQ(rsp_head.magic_num, kNsheadMagic);

  server.Stop();
  server.Join();
}

TEST_CASE(esp_loopback_msg_id_correlation) {
  EspService svc;
  svc.AddMessageHandler(7, [](const EspHead& head, const IOBuf& body,
                              IOBuf* resp) {
    resp->append("msg7:");
    resp->append(body);
  });
  Server server;
  server.set_esp_service(&svc);
  EXPECT_EQ(server.Start(0), 0);

  EspClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(server.port())), 0);

  // Concurrent calls: msg_id correlation must route each reply home
  // even when handlers run in parallel fibers.
  std::vector<std::thread> ts;
  std::atomic<int> ok{0};
  for (int i = 0; i < 6; ++i) {
    ts.emplace_back([&cli, &ok, i] {
      IOBuf b;
      b.append("x" + std::to_string(i));
      IOBuf r;
      if (cli.call(7, b, &r) == 0 &&
          r.to_string() == "msg7:x" + std::to_string(i)) {
        ok.fetch_add(1);
      }
    });
  }
  for (auto& t : ts) {
    t.join();
  }
  EXPECT_EQ(ok.load(), 6);

  // Unknown msg -> empty reply body but the call still completes.
  IOBuf b, r;
  b.append("?");
  EXPECT_EQ(cli.call(99, b, &r), 0);
  EXPECT(r.empty());

  server.Stop();
  server.Join();
}

namespace {

void register_echo(Server* server) {
  // One handler, many protocols: name-addressed and index-addressed keys.
  Server::Handler echo = [](Controller* cntl, const IOBuf& req,
                            IOBuf* rsp, Closure done) {
    rsp->append(req);
    done();
  };
  server->RegisterMethod("EchoService.Echo", echo);
  server->RegisterMethod("EchoService.#3", echo);
  server->RegisterMethod("Nova.#5", echo);
  Server::Handler boom = [](Controller* cntl, const IOBuf&, IOBuf*,
                            Closure done) {
    cntl->SetFailed(42, "deliberate failure");
    done();
  };
  server->RegisterMethod("EchoService.Boom", boom);
}

}  // namespace

TEST_CASE(hulu_loopback_name_and_index) {
  Server server;
  register_echo(&server);
  EXPECT_EQ(server.Start(0), 0);

  LegacyRpcClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(server.port()),
                     LegacyProto::kHulu),
            0);
  IOBuf req;
  req.append("hulu-payload");
  // Name-addressed (method_name field 14 present).
  LegacyRpcClient::Result r = cli.call("EchoService", "Echo", 0, req);
  EXPECT(r.ok);
  EXPECT(r.response.to_string() == "hulu-payload");
  // Index-addressed (no name -> "EchoService.#3").
  r = cli.call("EchoService", "", 3, req);
  EXPECT(r.ok);
  EXPECT(r.response.to_string() == "hulu-payload");
  // Handler failure surfaces code+text through the response meta.
  r = cli.call("EchoService", "Boom", 0, req);
  EXPECT(!r.ok);
  EXPECT_EQ(r.error_code, 42);
  EXPECT(r.error_text.find("deliberate") != std::string::npos);
  // Unknown method.
  r = cli.call("EchoService", "Nope", 0, req);
  EXPECT(!r.ok);
  EXPECT_EQ(r.error_code, ENOENT);

  server.Stop();
  server.Join();
}

TEST_CASE(sofa_loopback) {
  Server server;
  register_echo(&server);
  EXPECT_EQ(server.Start(0), 0);

  LegacyRpcClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(server.port()),
                     LegacyProto::kSofa),
            0);
  IOBuf req;
  req.append(std::string(100000, 's'));  // exercise the u64 body sizes
  LegacyRpcClient::Result r = cli.call("EchoService", "Echo", 0, req);
  EXPECT(r.ok);
  EXPECT_EQ(r.response.size(), 100000u);
  r = cli.call("EchoService", "Boom", 0, req);
  EXPECT(!r.ok);
  EXPECT_EQ(r.error_code, 42);

  server.Stop();
  server.Join();
}

TEST_CASE(nova_loopback_index_dispatch) {
  Server server;
  register_echo(&server);
  server.enable_nova_pbrpc();
  EXPECT_EQ(server.Start(0), 0);

  LegacyRpcClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(server.port()),
                     LegacyProto::kNova),
            0);
  IOBuf req;
  req.append("nova-pb-bytes");
  LegacyRpcClient::Result r = cli.call("", "", 5, req);
  EXPECT(r.ok);
  EXPECT(r.response.to_string() == "nova-pb-bytes");

  server.Stop();
  server.Join();
}

TEST_CASE(public_pbrpc_loopback) {
  Server server;
  register_echo(&server);
  server.enable_public_pbrpc();
  EXPECT_EQ(server.Start(0), 0);

  LegacyRpcClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(server.port()),
                     LegacyProto::kPublic),
            0);
  IOBuf req;
  req.append("public-payload");
  LegacyRpcClient::Result r = cli.call("EchoService", "", 3, req);
  EXPECT(r.ok);
  EXPECT(r.response.to_string() == "public-payload");
  // Error path: head.code + body.error ride back.
  r = cli.call("EchoService", "", 999, req);
  EXPECT(!r.ok);
  EXPECT_EQ(r.error_code, ENOENT);

  server.Stop();
  server.Join();
}

TEST_CASE(legacy_protocols_share_port_with_tstd) {
  // The SAME server answers hulu and sofa on one port — probing routes
  // each connection by its magic.
  Server server;
  register_echo(&server);
  EXPECT_EQ(server.Start(0), 0);
  const std::string addr = "127.0.0.1:" + std::to_string(server.port());

  LegacyRpcClient hulu, sofa;
  EXPECT_EQ(hulu.Init(addr, LegacyProto::kHulu), 0);
  EXPECT_EQ(sofa.Init(addr, LegacyProto::kSofa), 0);
  IOBuf req;
  req.append("mix");
  EXPECT(hulu.call("EchoService", "Echo", 0, req).ok);
  EXPECT(sofa.call("EchoService", "Echo", 0, req).ok);

  server.Stop();
  server.Join();
}

TEST_MAIN
