// mcpack_v2 codec tests: golden wire bytes (hand-assembled per the head
// layouts in /root/reference/src/mcpack2pb/parser.cpp:30-80), full-type
// round-trips, deleted-item skipping, malformed rejection, and the
// classic pairing: mcpack bodies over nshead framing.
#include <cstring>
#include <string>

#include "base/mcpack.h"
#include "net/channel.h"
#include "net/nshead.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

TEST_CASE(mcpack_golden_bytes_int32) {
  // Unnamed INT32(7): fixed head {0x14, 0x00} + 4 LE value bytes.
  McpackValue v = McpackValue::I32(7);
  const std::string wire = v.serialize();
  const char expect[] = {0x14, 0x00, 0x07, 0x00, 0x00, 0x00};
  EXPECT_EQ(wire.size(), sizeof(expect));
  EXPECT(memcmp(wire.data(), expect, sizeof(expect)) == 0);
}

TEST_CASE(mcpack_golden_bytes_named_string_in_object) {
  // Object{"k": "hi"}: long head object, items_head count=1, then a
  // SHORT-head string (0x50|0x80) named "k\0" valued "hi\0".
  McpackValue obj = McpackValue::Object();
  obj.add_field("k", McpackValue::Str("hi"));
  const std::string wire = obj.serialize();
  const char expect[] = {
      0x10, 0x00, 0x0c, 0x00, 0x00, 0x00,        // long head, value=12
      0x01, 0x00, 0x00, 0x00,                    // item_count = 1
      static_cast<char>(0xD0), 0x02, 0x03,       // short string head
      'k',  0x00, 'h',  'i',  0x00,              // name + value
  };
  EXPECT_EQ(wire.size(), sizeof(expect));
  EXPECT(memcmp(wire.data(), expect, sizeof(expect)) == 0);
  McpackValue back;
  EXPECT(McpackValue::parse(wire.data(), wire.size(), &back));
  EXPECT(back.type == McpackType::kObject);
  const McpackValue* k = back.field("k");
  EXPECT(k != nullptr && k->str == "hi");
}

TEST_CASE(mcpack_all_types_roundtrip) {
  McpackValue obj = McpackValue::Object();
  obj.add_field("i8", [] {
    McpackValue v;
    v.type = McpackType::kInt8;
    v.i64 = -5;
    return v;
  }());
  obj.add_field("i32", McpackValue::I32(-123456));
  obj.add_field("i64", McpackValue::I64(-(int64_t{1} << 40)));
  obj.add_field("u64", McpackValue::U64(uint64_t{1} << 63));
  obj.add_field("b", McpackValue::Bool(true));
  obj.add_field("d", McpackValue::Double(3.25));
  obj.add_field("s", McpackValue::Str("hello mcpack"));
  obj.add_field("bin", McpackValue::Binary(std::string("\x00\x01\x02", 3)));
  obj.add_field("nil", McpackValue::Null());
  McpackValue arr = McpackValue::Array();
  arr.add_item(McpackValue::Str("a"));
  arr.add_item(McpackValue::I32(2));
  obj.add_field("arr", std::move(arr));
  McpackValue iso = McpackValue::IsoArray(McpackType::kInt32);
  for (int i = 0; i < 5; ++i) {
    iso.add_item(McpackValue::I32(i * 100));
  }
  obj.add_field("iso", std::move(iso));
  // Big string forces the LONG head (> 255).
  obj.add_field("big", McpackValue::Str(std::string(1000, 'x')));

  const std::string wire = obj.serialize();
  McpackValue back;
  size_t consumed = 0;
  EXPECT(McpackValue::parse(wire.data(), wire.size(), &back, &consumed));
  EXPECT_EQ(consumed, wire.size());
  EXPECT_EQ(back.fields.size(), obj.fields.size());
  EXPECT_EQ(back.field("i8")->i64, -5);
  EXPECT_EQ(back.field("i32")->i64, -123456);
  EXPECT_EQ(back.field("i64")->i64, -(int64_t{1} << 40));
  EXPECT_EQ(back.field("u64")->u64, uint64_t{1} << 63);
  EXPECT_EQ(back.field("b")->i64, 1);
  EXPECT(back.field("d")->f64 == 3.25);
  EXPECT(back.field("s")->str == "hello mcpack");
  EXPECT_EQ(back.field("bin")->str.size(), 3u);
  EXPECT(back.field("nil")->type == McpackType::kNull);
  EXPECT_EQ(back.field("arr")->items.size(), 2u);
  EXPECT(back.field("arr")->items[0].str == "a");
  EXPECT_EQ(back.field("arr")->items[1].i64, 2);
  EXPECT_EQ(back.field("iso")->items.size(), 5u);
  EXPECT_EQ(back.field("iso")->items[4].i64, 400);
  EXPECT_EQ(back.field("big")->str.size(), 1000u);
  // Round-trip is byte-stable.
  EXPECT(back.serialize() == wire);
}

TEST_CASE(mcpack_deleted_items_and_name_limit) {
  // Deleted tombstones ((type & 0x70) == 0) are counted on the wire but
  // absent from the tree.  Object{<deleted>, "k":I32(3)} with count=2:
  const char wire[] = {
      0x10, 0x00, 0x0f, 0x00, 0x00, 0x00,  // object long head, value=15
      0x02, 0x00, 0x00, 0x00,              // item_count = 2
      0x01, 0x00, 0x00,                    // DELETED fixed item (1B value)
      0x14, 0x02, 'k',  0x00,              // named INT32...
      0x03, 0x00, 0x00, 0x00,              // = 3
  };
  McpackValue v;
  EXPECT(McpackValue::parse(wire, sizeof(wire), &v));
  EXPECT_EQ(v.fields.size(), 1u);  // tombstone not surfaced
  EXPECT(v.field("k") != nullptr && v.field("k")->i64 == 3);
  // Field names beyond the wire's 1-byte name_size must be REJECTED, not
  // silently truncated into a corrupt image.
  McpackValue bad = McpackValue::Object();
  bad.add_field(std::string(300, 'n'), McpackValue::I32(1));
  EXPECT(bad.serialize().empty());
}

TEST_CASE(mcpack_rejects_malformed) {
  McpackValue out;
  // Truncated heads/values.
  const std::string ok = [] {
    McpackValue obj = McpackValue::Object();
    obj.add_field("x", McpackValue::I32(1));
    return obj.serialize();
  }();
  for (size_t cut = 1; cut < ok.size(); ++cut) {
    McpackValue v;
    // Either it fails, or (long-head inner sizes still fitting) it must
    // never read past the truncation — parse on the prefix:
    McpackValue::parse(ok.data(), cut, &v);
  }
  // Bad string (missing trailing NUL).
  const char bad_str[] = {static_cast<char>(0xD0), 0x00, 0x02, 'h', 'i'};
  EXPECT(!McpackValue::parse(bad_str, sizeof(bad_str), &out));
  // Name whose last byte is not NUL (ADVICE r5): the reference treats
  // names as C-strings INCLUDING the NUL, so this is malformed — it must
  // be REJECTED, not silently parsed with its last real byte eaten
  // (golden layout: 0xD0, name_size, value_size, name..., value...).
  const char bad_name[] = {static_cast<char>(0xD0), 0x02, 0x03,
                           'k',  'X',  'h', 'i', 0x00};
  EXPECT(!McpackValue::parse(bad_name, sizeof(bad_name), &out));
  // Control: the same item with a proper NUL-terminated name parses.
  const char good_name[] = {static_cast<char>(0xD0), 0x02, 0x03,
                            'k',  0x00, 'h', 'i', 0x00};
  EXPECT(McpackValue::parse(good_name, sizeof(good_name), &out));
  // Iso array with non-fixed element type.
  const char bad_iso[] = {0x30, 0x00, 0x02, 0x00, 0x00, 0x00, 0x50, 0x00};
  EXPECT(!McpackValue::parse(bad_iso, sizeof(bad_iso), &out));
  // Container count larger than its bytes.
  const char bad_count[] = {0x10, 0x00, 0x04, 0x00, 0x00, 0x00,
                            static_cast<char>(0xFF), 0x00, 0x00, 0x00};
  EXPECT(!McpackValue::parse(bad_count, sizeof(bad_count), &out));
}

TEST_CASE(mcpack_over_nshead_service) {
  // The deployment pairing the format exists for: mcpack request/response
  // bodies inside nshead frames (reference: nshead_mcpack_protocol).
  NsheadService svc([](const NsheadHead&, const IOBuf& body,
                       NsheadHead*, IOBuf* resp_body) {
    const std::string bytes = body.to_string();
    McpackValue in;
    if (!McpackValue::parse(bytes.data(), bytes.size(), &in)) {
      resp_body->append("parse error");
      return;
    }
    McpackValue out = McpackValue::Object();
    const McpackValue* a = in.field("a");
    const McpackValue* b = in.field("b");
    out.add_field("sum", McpackValue::I64((a != nullptr ? a->i64 : 0) +
                                          (b != nullptr ? b->i64 : 0)));
    out.add_field("echo",
                  McpackValue::Str(in.field("msg") != nullptr
                                       ? in.field("msg")->str
                                       : ""));
    resp_body->append(out.serialize());
  });
  Server srv;
  srv.set_nshead_service(&svc);
  EXPECT_EQ(srv.Start(0), 0);

  NsheadClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(srv.port())), 0);
  McpackValue req = McpackValue::Object();
  req.add_field("a", McpackValue::I64(40));
  req.add_field("b", McpackValue::I64(2));
  req.add_field("msg", McpackValue::Str("mcpack over nshead"));
  IOBuf req_body, resp_body;
  req_body.append(req.serialize());
  NsheadHead head, resp_head;
  EXPECT_EQ(cli.call(head, req_body, &resp_head, &resp_body), 0);
  const std::string resp_bytes = resp_body.to_string();
  McpackValue resp;
  EXPECT(McpackValue::parse(resp_bytes.data(), resp_bytes.size(), &resp));
  EXPECT_EQ(resp.field("sum")->i64, 42);
  EXPECT(resp.field("echo")->str == "mcpack over nshead");
  srv.Stop();
  srv.Join();
}

TEST_MAIN
