// Memcache binary protocol: frame codec units, service semantics (CAS,
// add/replace, incr/decr wrap+floor, expiry), client loopback incl.
// pipelined batch, and malformed-frame rejection.
#include "net/memcache.h"

#include <thread>

#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

TEST_CASE(mc_frame_roundtrip) {
  McCommand cmd;
  cmd.op = McOp::kSet;
  cmd.key = "k1";
  cmd.value = std::string("v\0v", 3);
  cmd.flags = 0xdeadbeef;
  cmd.exptime = 3600;
  cmd.cas = 0x1122334455667788ULL;
  std::string wire;
  mc_pack_request(cmd, /*opaque=*/42, &wire);
  // 24B header + 8B extras + 2B key + 3B value.
  EXPECT_EQ(wire.size(), 24u + 8 + 2 + 3);
  EXPECT_EQ(static_cast<uint8_t>(wire[0]), 0x80);

  McFrame f;
  size_t pos = 0;
  EXPECT_EQ(mc_parse_frame(wire, &pos, &f), 1);
  EXPECT_EQ(pos, wire.size());
  EXPECT(f.op == McOp::kSet);
  EXPECT(f.key == "k1");
  EXPECT(f.value == std::string("v\0v", 3));
  EXPECT_EQ(f.opaque, 42u);
  EXPECT_EQ(f.cas, 0x1122334455667788ULL);
  EXPECT_EQ(f.extras.size(), 8u);

  // Truncation -> partial; bad magic -> malformed; inconsistent
  // lengths -> malformed.
  pos = 0;
  std::string cut = wire.substr(0, 30);
  EXPECT_EQ(mc_parse_frame(cut, &pos, &f), 0);
  std::string bad = wire;
  bad[0] = 0x7f;
  pos = 0;
  EXPECT_EQ(mc_parse_frame(bad, &pos, &f), -1);
  std::string inc = wire;
  inc[2] = 0x7f;  // key_len 0x7f02 > total_body
  pos = 0;
  EXPECT_EQ(mc_parse_frame(inc, &pos, &f), -1);
}

TEST_CASE(mc_service_semantics) {
  MemcacheService svc;
  McCommand set;
  set.op = McOp::kSet;
  set.key = "n";
  set.value = "10";
  McResult r = svc.Execute(set);
  EXPECT(r.ok());
  const uint64_t cas1 = r.cas;
  EXPECT(cas1 != 0);

  // CAS mismatch rejected, match accepted.
  set.cas = cas1 + 999;
  EXPECT(svc.Execute(set).status == McStatus::kExists);
  set.cas = cas1;
  EXPECT(svc.Execute(set).ok());

  // Add fails on present key; replace fails on absent.
  McCommand add;
  add.op = McOp::kAdd;
  add.key = "n";
  add.value = "x";
  EXPECT(svc.Execute(add).status == McStatus::kNotStored);
  McCommand rep;
  rep.op = McOp::kReplace;
  rep.key = "absent";
  rep.value = "x";
  EXPECT(svc.Execute(rep).status == McStatus::kNotStored);

  // Incr on numeric value; decr floors at zero.
  McCommand incr;
  incr.op = McOp::kIncrement;
  incr.key = "n";
  incr.delta = 5;
  r = svc.Execute(incr);
  EXPECT(r.ok());
  EXPECT_EQ(r.numeric, 15u);
  McCommand decr;
  decr.op = McOp::kDecrement;
  decr.key = "n";
  decr.delta = 100;
  r = svc.Execute(decr);
  EXPECT(r.ok());
  EXPECT_EQ(r.numeric, 0u);

  // Incr on non-numeric -> delta error.
  McCommand sets;
  sets.op = McOp::kSet;
  sets.key = "s";
  sets.value = "abc";
  svc.Execute(sets);
  incr.key = "s";
  EXPECT(svc.Execute(incr).status == McStatus::kDeltaBadValue);

  // Incr miss with initial creates; with 0xffffffff exptime doesn't.
  McCommand miss;
  miss.op = McOp::kIncrement;
  miss.key = "fresh";
  miss.delta = 3;
  miss.initial = 7;
  r = svc.Execute(miss);
  EXPECT(r.ok());
  EXPECT_EQ(r.numeric, 7u);
  miss.key = "fresh2";
  miss.exptime = 0xffffffffu;
  EXPECT(svc.Execute(miss).status == McStatus::kNotFound);

  // Append/prepend require presence.
  McCommand app;
  app.op = McOp::kAppend;
  app.key = "s";
  app.value = "!";
  EXPECT(svc.Execute(app).ok());
  McCommand get;
  get.op = McOp::kGet;
  get.key = "s";
  EXPECT(svc.Execute(get).value == "abc!");
}

TEST_CASE(mc_loopback_client_server) {
  MemcacheService svc;
  Server server;
  server.set_memcache_service(&svc);
  EXPECT_EQ(server.Start(0), 0);

  MemcacheClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(server.port())), 0);

  EXPECT(cli.Version().value.find("trpc") != std::string::npos);
  McResult set = cli.Set("greeting", "hello", /*flags=*/7);
  EXPECT(set.ok());
  McResult get = cli.Get("greeting");
  EXPECT(get.ok());
  EXPECT(get.value == "hello");
  EXPECT_EQ(get.flags, 7u);
  EXPECT_EQ(get.cas, set.cas);

  // CAS round trip through the wire.
  EXPECT(cli.Set("greeting", "v2", 0, 0, get.cas).ok());
  EXPECT(cli.Set("greeting", "v3", 0, 0, get.cas).status ==
         McStatus::kExists);

  EXPECT(cli.Get("missing").status == McStatus::kNotFound);
  EXPECT(cli.Delete("greeting").ok());
  EXPECT(cli.Get("greeting").status == McStatus::kNotFound);

  // Numeric round trip (big-endian u64 response value).
  EXPECT(cli.Set("ctr", "41").ok());
  McResult inc = cli.Increment("ctr", 1);
  EXPECT(inc.ok());
  EXPECT_EQ(inc.numeric, 42u);

  server.Stop();
  server.Join();
}

TEST_CASE(mc_pipelined_batch) {
  MemcacheService svc;
  Server server;
  server.set_memcache_service(&svc);
  EXPECT_EQ(server.Start(0), 0);

  MemcacheClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(server.port())), 0);

  std::vector<McCommand> cmds;
  for (int i = 0; i < 32; ++i) {
    McCommand c;
    c.op = McOp::kSet;
    c.key = "k" + std::to_string(i);
    c.value = std::string(1000, static_cast<char>('a' + i % 26));
    cmds.push_back(c);
  }
  std::vector<McResult> rs = cli.batch(cmds);
  EXPECT_EQ(rs.size(), 32u);
  for (const McResult& r : rs) {
    EXPECT(r.ok());
  }
  EXPECT_EQ(svc.item_count(), 32u);

  cmds.clear();
  for (int i = 0; i < 32; ++i) {
    McCommand c;
    c.op = McOp::kGet;
    c.key = "k" + std::to_string(i);
    cmds.push_back(c);
  }
  rs = cli.batch(cmds);
  for (int i = 0; i < 32; ++i) {
    EXPECT(rs[i].ok());
    EXPECT_EQ(rs[i].value.size(), 1000u);
    EXPECT(rs[i].value[0] == static_cast<char>('a' + i % 26));
  }

  server.Stop();
  server.Join();
}

TEST_MAIN
