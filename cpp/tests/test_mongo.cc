// Mongo OP_MSG + BSON: codec roundtrip for every supported type,
// malformed-input rejection, loopback command dispatch (custom handler,
// builtin handshake commands, unknown-command error), and correlation
// under concurrent callers.
#include "net/mongo.h"

#include <atomic>
#include <thread>

#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

TEST_CASE(bson_roundtrip_all_types) {
  BsonDoc inner;
  inner.emplace_back("s", BsonValue::Str("nested"));
  BsonDoc doc;
  doc.emplace_back("d", BsonValue::Double(2.5));
  doc.emplace_back("str", BsonValue::Str("hello"));
  doc.emplace_back("doc", BsonValue::Document(inner));
  doc.emplace_back("arr", BsonValue::Array({BsonValue::Int32(1),
                                            BsonValue::Str("two")}));
  doc.emplace_back("bin",
                   BsonValue::Binary(std::string("\x00\x01\xfe", 3), 4));
  doc.emplace_back("oid", BsonValue::ObjectId("0123456789ab"));
  doc.emplace_back("t", BsonValue::Bool(true));
  doc.emplace_back("when", BsonValue::DateTime(1700000000000LL));
  doc.emplace_back("nil", BsonValue::Null());
  doc.emplace_back("i32", BsonValue::Int32(-42));
  doc.emplace_back("i64", BsonValue::Int64(1LL << 60));

  std::string wire;
  bson_write_doc(doc, &wire);
  BsonDoc back;
  size_t pos = 0;
  EXPECT_EQ(bson_read_doc(wire, &pos, &back), 1);
  EXPECT_EQ(pos, wire.size());
  EXPECT(back == doc);
  // Array element order/keys preserved.
  const BsonValue* arr = bson_find(back, "arr");
  EXPECT(arr != nullptr && arr->doc->size() == 2);
  EXPECT((*arr->doc)[0].first == "0");
  EXPECT((*arr->doc)[1].second.str == "two");
}

TEST_CASE(bson_rejects_malformed) {
  BsonDoc d;
  size_t pos = 0;
  // Truncated length.
  EXPECT_EQ(bson_read_doc(std::string("\x05\x00", 2), &pos, &d), 0);
  // Length smaller than minimum.
  pos = 0;
  EXPECT_EQ(bson_read_doc(std::string("\x04\x00\x00\x00", 4), &pos, &d),
            -1);
  // Missing terminator.
  pos = 0;
  std::string bad("\x06\x00\x00\x00\x10\x01", 6);
  EXPECT_EQ(bson_read_doc(bad, &pos, &d), -1);
  // String whose declared length escapes the document.
  pos = 0;
  std::string esc;
  esc.append("\x10\x00\x00\x00", 4);     // doc claims 16 bytes
  esc.push_back(0x02);                   // string element
  esc.append("k\0", 2);
  esc.append("\xff\xff\xff\x7f", 4);     // len 2^31-1
  esc.append("xx\0", 3);
  esc.push_back('\0');
  esc.append(64, 'P');  // surplus buffer: the 2^31 length is a true
                        // escape attempt, not ambiguous truncation
  EXPECT_EQ(bson_read_doc(esc, &pos, &d), -1);
  // Nesting bomb: 64 nested docs must be depth-rejected.
  BsonDoc deep;
  deep.emplace_back("x", BsonValue::Int32(1));
  for (int i = 0; i < 64; ++i) {
    BsonDoc outer;
    outer.emplace_back("d", BsonValue::Document(std::move(deep)));
    deep = std::move(outer);
  }
  std::string wire;
  bson_write_doc(deep, &wire);
  pos = 0;
  EXPECT_EQ(bson_read_doc(wire, &pos, &d), -1);
}

TEST_CASE(mongo_loopback_commands) {
  MongoService svc;
  svc.AddCommandHandler("insert", [](const BsonDoc& req) {
    const BsonValue* docs = bson_find(req, "documents");
    BsonDoc reply = MongoService::ok_reply();
    reply.emplace_back(
        "n", BsonValue::Int32(docs != nullptr && docs->doc != nullptr
                                  ? static_cast<int32_t>(docs->doc->size())
                                  : 0));
    return reply;
  });
  Server server;
  server.set_mongo_service(&svc);
  EXPECT_EQ(server.Start(0), 0);

  MongoClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(server.port())), 0);

  // Builtin handshake commands (stock drivers call these first).
  BsonDoc hello;
  hello.emplace_back("hello", BsonValue::Int32(1));
  MongoClient::Result r = cli.run_command(hello);
  EXPECT(r.ok);
  EXPECT(bson_find(r.reply, "isWritablePrimary") != nullptr);
  EXPECT(bson_find(r.reply, "ok")->d == 1.0);

  BsonDoc ping;
  ping.emplace_back("ping", BsonValue::Int32(1));
  EXPECT(cli.run_command(ping).ok);

  // Custom handler sees the request document.
  BsonDoc ins;
  ins.emplace_back("insert", BsonValue::Str("coll"));
  BsonDoc row;
  row.emplace_back("x", BsonValue::Int32(7));
  ins.emplace_back("documents",
                   BsonValue::Array({BsonValue::Document(row),
                                     BsonValue::Document(row)}));
  r = cli.run_command(ins);
  EXPECT(r.ok);
  EXPECT_EQ(bson_find(r.reply, "n")->i, 2);

  // Unknown command -> CommandNotFound shape.
  BsonDoc nope;
  nope.emplace_back("frobnicate", BsonValue::Int32(1));
  r = cli.run_command(nope);
  EXPECT(r.ok);
  EXPECT(bson_find(r.reply, "ok")->d == 0.0);
  EXPECT_EQ(bson_find(r.reply, "code")->i, 59);

  server.Stop();
  server.Join();
}

TEST_CASE(mongo_concurrent_correlation) {
  MongoService svc;
  svc.AddCommandHandler("echoval", [](const BsonDoc& req) {
    BsonDoc reply = MongoService::ok_reply();
    const BsonValue* v = bson_find(req, "v");
    reply.emplace_back("v", v != nullptr ? *v : BsonValue::Null());
    return reply;
  });
  Server server;
  server.set_mongo_service(&svc);
  EXPECT_EQ(server.Start(0), 0);

  MongoClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(server.port())), 0);

  std::vector<std::thread> ts;
  std::atomic<int> ok{0};
  for (int i = 0; i < 8; ++i) {
    ts.emplace_back([&cli, &ok, i] {
      BsonDoc cmd;
      cmd.emplace_back("echoval", BsonValue::Int32(1));
      cmd.emplace_back("v", BsonValue::Int64(1000 + i));
      MongoClient::Result r = cli.run_command(cmd);
      if (r.ok && bson_find(r.reply, "v")->i == 1000 + i) {
        ok.fetch_add(1);
      }
    });
  }
  for (auto& t : ts) {
    t.join();
  }
  EXPECT_EQ(ok.load(), 8);

  server.Stop();
  server.Join();
}

TEST_MAIN
