// MySQL client: SHA-1 vectors, the native-password scramble, and a full
// conversation against an in-process fake mysql server (greeting, auth
// verification, OK/ERR/resultset responses, ping, USE, reconnect after
// server-side drop) — the reference's own tests fake the server the
// same way (no external mysqld).
#include "net/mysql.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstring>
#include <thread>

#include "base/sha1.h"
#include "tests/test_util.h"

using namespace trpc;

TEST_CASE(sha1_known_vectors) {
  // RFC 3174 / FIPS 180 test vectors.
  auto hex = [](const std::string& d) {
    static const char* k = "0123456789abcdef";
    std::string out;
    for (unsigned char c : d) {
      out.push_back(k[c >> 4]);
      out.push_back(k[c & 15]);
    }
    return out;
  };
  EXPECT(hex(sha1(std::string("abc"))) ==
         "a9993e364706816aba3e25717850c26c9cd0d89d");
  EXPECT(hex(sha1(std::string(""))) ==
         "da39a3ee5e6b4b0d3255bfef95601890afd80709");
  EXPECT(hex(sha1(std::string(
             "abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq"))) ==
         "84983e441c3bd26ebaae4aa1f95129e5e54670f1");
  // One block-boundary case (55/56/64 bytes straddle padding paths).
  EXPECT(hex(sha1(std::string(64, 'a'))) ==
         "0098ba824b5c16427bd7a1122a5a442a25ec644d");
}

namespace {

// ---- a minimal blocking fake mysql server --------------------------------

constexpr char kNonce[] = "0123456789abcdefghij";  // 20 bytes
constexpr char kPassword[] = "sekrit";

void put3len(std::string* out, size_t n, uint8_t seq) {
  out->push_back(static_cast<char>(n));
  out->push_back(static_cast<char>(n >> 8));
  out->push_back(static_cast<char>(n >> 16));
  out->push_back(static_cast<char>(seq));
}

void send_pkt(int fd, const std::string& payload, uint8_t seq) {
  std::string wire;
  put3len(&wire, payload.size(), seq);
  wire.append(payload);
  (void)!::send(fd, wire.data(), wire.size(), MSG_NOSIGNAL);
}

bool recv_pkt(int fd, std::string* payload, uint8_t* seq) {
  uint8_t head[4];
  size_t got = 0;
  while (got < 4) {
    ssize_t rc = ::read(fd, head + got, 4 - got);
    if (rc <= 0) {
      return false;
    }
    got += rc;
  }
  const size_t len = head[0] | (head[1] << 8) | (head[2] << 16);
  *seq = head[3];
  payload->resize(len);
  got = 0;
  while (got < len) {
    ssize_t rc = ::read(fd, payload->data() + got, len - got);
    if (rc <= 0) {
      return false;
    }
    got += rc;
  }
  return true;
}

std::string lenenc_str(const std::string& s) {
  std::string out;
  out.push_back(static_cast<char>(s.size()));  // all test strings < 0xfb
  out.append(s);
  return out;
}

std::string column_def(const std::string& name) {
  std::string p;
  p += lenenc_str("def");
  p += lenenc_str("db");
  p += lenenc_str("t");
  p += lenenc_str("t");
  p += lenenc_str(name);
  p += lenenc_str(name);
  p.push_back(0x0c);
  p.append("\x21\x00", 2);              // charset
  p.append("\xff\x00\x00\x00", 4);      // length
  p.push_back(0xfd);                    // VAR_STRING
  p.append("\x00\x00", 2);              // flags
  p.push_back(0);                       // decimals
  p.append("\x00\x00", 2);              // filler
  return p;
}

std::string eof_pkt() {
  return std::string("\xfe\x00\x00\x00\x00", 5);
}

std::string ok_pkt(uint64_t affected, uint64_t insert_id) {
  std::string p;
  p.push_back(0x00);
  p.push_back(static_cast<char>(affected));   // < 0xfb in tests
  p.push_back(static_cast<char>(insert_id));
  p.append("\x02\x00\x00\x00", 4);            // status, warnings
  return p;
}

std::string err_pkt(uint16_t code, const std::string& msg) {
  std::string p;
  p.push_back(static_cast<char>(0xff));
  p.push_back(static_cast<char>(code));
  p.push_back(static_cast<char>(code >> 8));
  p.append("#42000");
  p.append(msg);
  return p;
}

// Serves one client connection; returns when the client disconnects
// (or immediately after auth when `drop` — unused by default — is set).
void serve_conn(int fd, std::atomic<int>* authed, bool drop) {
  // Greeting: v10, version, thread id, nonce split 8 + 12 + NUL.
  std::string g;
  g.push_back(10);
  g.append("5.7.0-fake");
  g.push_back('\0');
  g.append("\x01\x00\x00\x00", 4);           // thread id
  g.append(kNonce, 8);
  g.push_back('\0');
  g.append("\xff\xff", 2);                   // caps lower (all)
  g.push_back(33);                           // charset
  g.append("\x02\x00", 2);                   // status
  g.append("\x0f\x00", 2);                   // caps upper (plugin auth)
  g.push_back(21);                           // auth data len (8+12+NUL)
  g.append(10, '\0');                        // reserved
  g.append(kNonce + 8, 12);
  g.push_back('\0');
  g.append("mysql_native_password");
  g.push_back('\0');
  send_pkt(fd, g, 0);

  std::string pkt;
  uint8_t seq = 0;
  if (!recv_pkt(fd, &pkt, &seq)) {
    return;
  }
  // HandshakeResponse41: caps(4) maxpkt(4) charset(1) filler(23) user\0
  // authlen auth [db\0] plugin\0.
  size_t pos = 32;
  const size_t unul = pkt.find('\0', pos);
  if (unul == std::string::npos) {
    return;
  }
  const std::string user = pkt.substr(pos, unul - pos);
  pos = unul + 1;
  const size_t alen = static_cast<uint8_t>(pkt[pos]);
  const std::string proof = pkt.substr(pos + 1, alen);
  const std::string want =
      MysqlClient::native_scramble(kPassword, std::string(kNonce, 20));
  if (user != "tester" || proof != want) {
    send_pkt(fd, err_pkt(1045, "Access denied"), seq + 1);
    return;
  }
  authed->fetch_add(1);
  send_pkt(fd, ok_pkt(0, 0), seq + 1);
  if (drop) {
    return;  // simulate a server-side kill right after auth
  }

  int stmt_params = 0;
  bool stmt_select = false;
  while (recv_pkt(fd, &pkt, &seq)) {
    if (pkt.empty()) {
      return;
    }
    const uint8_t com = static_cast<uint8_t>(pkt[0]);
    const std::string arg = pkt.substr(1);
    if (com == 0x01) {  // QUIT
      return;
    }
    if (com == 0x0e || com == 0x02) {  // PING / INIT_DB
      send_pkt(fd, ok_pkt(0, 0), 1);
      continue;
    }
    if (com == 0x16) {  // STMT_PREPARE
      stmt_params = static_cast<int>(
          std::count(arg.begin(), arg.end(), '?'));
      stmt_select = arg.rfind("SELECT", 0) == 0;
      const int ncols = stmt_select ? 2 : 0;
      std::string ok;
      ok.push_back(0x00);
      ok.append("\x07\x00\x00\x00", 4);  // stmt id 7
      ok.push_back(static_cast<char>(ncols));
      ok.push_back(0);
      ok.push_back(static_cast<char>(stmt_params));
      ok.push_back(0);
      ok.append("\x00\x00\x00", 3);  // filler + warnings
      uint8_t s2 = 1;
      send_pkt(fd, ok, s2++);
      for (int i = 0; i < stmt_params; ++i) {
        send_pkt(fd, column_def("?"), s2++);
      }
      if (stmt_params > 0) {
        send_pkt(fd, eof_pkt(), s2++);
      }
      for (int i = 0; i < ncols; ++i) {
        send_pkt(fd, column_def("p" + std::to_string(i)), s2++);
      }
      if (ncols > 0) {
        send_pkt(fd, eof_pkt(), s2++);
      }
      continue;
    }
    if (com == 0x19) {  // STMT_CLOSE: no response
      continue;
    }
    if (com == 0x17) {  // STMT_EXECUTE
      // [stmt_id u32][flags][iter u32] + bitmap + new-bound + types + vals.
      size_t ep = 4 + 1 + 4;
      std::vector<std::string> vals;
      std::vector<bool> nulls;
      if (stmt_params > 0 && arg.size() > ep) {
        const size_t bml = (stmt_params + 7) / 8;
        const uint8_t* bm =
            reinterpret_cast<const uint8_t*>(arg.data()) + ep;
        ep += bml + 1 + 2 * stmt_params;  // bitmap, bound flag, types
        for (int i = 0; i < stmt_params; ++i) {
          const bool is_null = bm[i / 8] & (1 << (i % 8));
          nulls.push_back(is_null);
          if (is_null) {
            vals.emplace_back();
            continue;
          }
          const uint8_t len = static_cast<uint8_t>(arg[ep]);  // short vals
          vals.push_back(arg.substr(ep + 1, len));
          ep += 1 + len;
        }
      }
      if (!stmt_select) {
        send_pkt(fd, ok_pkt(1, 9), 1);
        continue;
      }
      uint8_t s2 = 1;
      std::string hdr(1, 2);
      send_pkt(fd, hdr, s2++);
      send_pkt(fd, column_def("p0"), s2++);
      send_pkt(fd, column_def("p1"), s2++);
      send_pkt(fd, eof_pkt(), s2++);
      // ONE binary row echoing the two params (null bitmap offset 2).
      std::string row;
      row.push_back(0x00);
      uint8_t bm0 = 0;
      for (int i = 0; i < 2 && i < static_cast<int>(nulls.size()); ++i) {
        if (nulls[i]) {
          bm0 |= static_cast<uint8_t>(1 << (i + 2));
        }
      }
      row.push_back(static_cast<char>(bm0));
      for (int i = 0; i < 2 && i < static_cast<int>(vals.size()); ++i) {
        if (nulls[i]) {
          continue;
        }
        row.push_back(static_cast<char>(vals[i].size()));
        row.append(vals[i]);
      }
      send_pkt(fd, row, s2++);
      send_pkt(fd, eof_pkt(), s2++);
      continue;
    }
    if (com != 0x03) {
      send_pkt(fd, err_pkt(1047, "unknown command"), 1);
      continue;
    }
    if (arg.rfind("DIE", 0) == 0) {
      return;  // close without replying (dead-connection simulation)
    }
    if (arg.rfind("SELECT", 0) == 0) {
      uint8_t s = 1;
      std::string hdr(1, 2);  // 2 columns
      send_pkt(fd, hdr, s++);
      send_pkt(fd, column_def("id"), s++);
      send_pkt(fd, column_def("name"), s++);
      send_pkt(fd, eof_pkt(), s++);
      std::string row1 = lenenc_str("1") + lenenc_str("alice");
      send_pkt(fd, row1, s++);
      std::string row2 = lenenc_str("2");
      row2.push_back(static_cast<char>(0xfb));  // NULL cell
      send_pkt(fd, row2, s++);
      send_pkt(fd, eof_pkt(), s++);
    } else if (arg.rfind("INSERT", 0) == 0) {
      send_pkt(fd, ok_pkt(3, 42), 1);
    } else {
      send_pkt(fd, err_pkt(1064, "You have an error in your SQL"), 1);
    }
  }
}

struct FakeMysqld {
  int listen_fd = -1;
  int port = 0;
  std::thread th;
  std::atomic<int> authed{0};
  std::atomic<int> active_fd{-1};
  std::atomic<bool> stop{false};

  void start() {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in sin = {};
    sin.sin_family = AF_INET;
    sin.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    EXPECT_EQ(::bind(listen_fd, reinterpret_cast<sockaddr*>(&sin),
                     sizeof(sin)),
              0);
    socklen_t slen = sizeof(sin);
    ::getsockname(listen_fd, reinterpret_cast<sockaddr*>(&sin), &slen);
    port = ntohs(sin.sin_port);
    ::listen(listen_fd, 8);
    th = std::thread([this] {
      while (!stop.load()) {
        int fd = ::accept(listen_fd, nullptr, nullptr);
        if (fd < 0) {
          return;
        }
        active_fd.store(fd);
        serve_conn(fd, &authed, /*drop=*/false);
        active_fd.store(-1);
        ::close(fd);
      }
    });
  }
  void shutdown() {
    stop.store(true);
    ::shutdown(listen_fd, SHUT_RDWR);
    ::close(listen_fd);
    // Unblock serve_conn if the client still holds its connection open
    // (the serving thread would otherwise sit in read() forever).
    const int afd = active_fd.load();
    if (afd >= 0) {
      ::shutdown(afd, SHUT_RDWR);
    }
    th.join();
  }
};

}  // namespace

TEST_CASE(mysql_scramble_shape) {
  const std::string s =
      MysqlClient::native_scramble("pw", std::string(20, 'n'));
  EXPECT_EQ(s.size(), 20u);
  // Empty password sends an empty proof per the protocol.
  EXPECT(MysqlClient::native_scramble("", std::string(20, 'n')).empty());
}

TEST_CASE(mysql_full_conversation) {
  FakeMysqld srv;
  srv.start();

  MysqlClient cli;
  MysqlClient::Options opts;
  opts.user = "tester";
  opts.password = kPassword;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(srv.port), &opts), 0);

  // SELECT resultset with a NULL cell.
  MysqlClient::Result r = cli.Query("SELECT id, name FROM t");
  EXPECT(r.ok);
  EXPECT_EQ(r.columns.size(), 2u);
  EXPECT(r.columns[0] == "id");
  EXPECT(r.columns[1] == "name");
  EXPECT_EQ(r.rows.size(), 2u);
  EXPECT(r.rows[0][0].has_value() && *r.rows[0][0] == "1");
  EXPECT(*r.rows[0][1] == "alice");
  EXPECT(!r.rows[1][1].has_value());  // NULL

  // OK packet fields.
  r = cli.Query("INSERT INTO t VALUES (1)");
  EXPECT(r.ok);
  EXPECT_EQ(r.affected_rows, 3u);
  EXPECT_EQ(r.last_insert_id, 42u);

  // ERR packet.
  r = cli.Query("BROKEN SQL");
  EXPECT(!r.ok);
  EXPECT_EQ(r.error_code, 1064);
  EXPECT(r.error_text.find("SQL") != std::string::npos);

  // Ping + USE.
  EXPECT_EQ(cli.Ping(), 0);
  EXPECT_EQ(cli.SelectDb("other"), 0);
  EXPECT_EQ(srv.authed.load(), 1);  // all on ONE bound connection

  srv.shutdown();
}

TEST_CASE(mysql_auth_rejected) {
  FakeMysqld srv;
  srv.start();

  MysqlClient cli;
  MysqlClient::Options opts;
  opts.user = "tester";
  opts.password = "wrong";
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(srv.port), &opts), 0);
  MysqlClient::Result r = cli.Query("SELECT 1");
  EXPECT(!r.ok);
  EXPECT_EQ(r.error_code, 2003);  // surfaces as connect failure

  srv.shutdown();
}

TEST_CASE(mysql_prepared_statements) {
  FakeMysqld srv;
  srv.start();
  {
    MysqlClient cli;
    MysqlClient::Options opts;
    opts.user = "tester";
    opts.password = kPassword;
    EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(srv.port), &opts), 0);

    MysqlClient::Stmt sel;
    EXPECT_EQ(cli.Prepare("SELECT ? , ?", &sel), 0);
    EXPECT_EQ(sel.id, 7u);
    EXPECT_EQ(sel.n_params, 2);
    EXPECT_EQ(sel.n_cols, 2);

    // Binary roundtrip with one NULL param.
    MysqlClient::Result r =
        cli.ExecuteStmt(sel, {std::string("alpha"), std::nullopt});
    EXPECT(r.ok);
    EXPECT_EQ(r.rows.size(), 1u);
    EXPECT(r.rows[0][0].has_value() && *r.rows[0][0] == "alpha");
    EXPECT(!r.rows[0][1].has_value());

    // Param-count mismatch is a client-side error.
    EXPECT_EQ(cli.ExecuteStmt(sel, {std::string("x")}).error_code, 2031);

    // Non-SELECT statement answers with an OK packet.
    MysqlClient::Stmt ins;
    EXPECT_EQ(cli.Prepare("INSERT INTO t VALUES (?)", &ins), 0);
    EXPECT_EQ(ins.n_cols, 0);
    r = cli.ExecuteStmt(ins, {std::string("v")});
    EXPECT(r.ok);
    EXPECT_EQ(r.affected_rows, 1u);
    EXPECT_EQ(r.last_insert_id, 9u);

    cli.CloseStmt(sel);
    EXPECT_EQ(cli.Ping(), 0);  // connection healthy after CLOSE
  }
  srv.shutdown();
}

TEST_CASE(mysql_reconnects_after_drop) {
  FakeMysqld srv;
  srv.start();

  MysqlClient cli;
  MysqlClient::Options opts;
  opts.user = "tester";
  opts.password = kPassword;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(srv.port), &opts), 0);
  EXPECT_EQ(cli.Ping(), 0);
  EXPECT_EQ(srv.authed.load(), 1);

  // "DIE" makes the server close without replying; the command layer
  // retries ONCE on a fresh connection (which also dies), then reports
  // the connection as lost.
  MysqlClient::Result r = cli.Query("DIE");
  EXPECT(!r.ok);
  EXPECT_EQ(r.error_code, 2013);
  EXPECT_EQ(srv.authed.load(), 2);  // the one retry re-authed

  // The next command transparently lands on a fresh connection.
  EXPECT_EQ(cli.Ping(), 0);
  EXPECT_EQ(srv.authed.load(), 3);

  srv.shutdown();
}

TEST_MAIN
