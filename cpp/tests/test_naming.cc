// Cluster control-plane tests (ISSUE 12): naming registry lease/epoch
// semantics, push-based Watch, the naming:// cluster channel, bounded-
// load c_hash and zone_la policies, deterministic subsetting, graceful
// drain (kEDraining = failover WITHOUT quarantine), the membership-
// churn x fault-schedule chaos soak, and the SO_REUSEPORT listener
// handoff hot restart.
#include <unistd.h>

#include <atomic>
#include <memory>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "base/flags.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "fiber/sync.h"
#include "net/cluster.h"
#include "net/concurrency_limiter.h"
#include "net/naming.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

struct FlagGuard {
  std::string name, old_value;
  FlagGuard(const std::string& n, const std::string& v) : name(n) {
    naming_ensure_registered();
    cluster_ensure_registered();
    old_value = Flag::find(n)->value_string();
    EXPECT_EQ(Flag::set(n, v), 0);
  }
  ~FlagGuard() { Flag::set(name, old_value); }
};

struct NamingReset {
  NamingReset() { naming_registry().clear(); }
  ~NamingReset() { naming_registry().clear(); }
};

NamingMember member(const std::string& addr, uint64_t epoch,
                    const std::string& zone = "", int weight = 1) {
  NamingMember m;
  m.addr = addr;
  m.zone = zone;
  m.weight = weight;
  m.epoch = epoch;
  return m;
}

std::string call_echo(ClusterChannel& ch, uint64_t key = 0) {
  Controller cntl;
  IOBuf req, resp;
  req.append("x");
  ch.CallMethod("Echo.WhoAmI", req, &resp, &cntl, nullptr, key);
  return cntl.Failed() ? "FAILED:" + std::to_string(cntl.error_code())
                       : resp.to_string();
}

// A disposable echo node that identifies itself (drain tests stop nodes,
// so unlike test_cluster.cc these are NOT process-lifetime singletons).
struct EchoNode {
  Server server;
  int port = 0;
  int Start(const std::string& tag) {
    server.RegisterMethod(
        "Echo.WhoAmI",
        [tag](Controller*, const IOBuf&, IOBuf* resp, Closure done) {
          resp->append(tag);
          done();
        });
    const int rc = server.Start(0);
    port = server.port();
    return rc;
  }
  std::string addr() const {
    return "127.0.0.1:" + std::to_string(port);
  }
};

}  // namespace

// ---- registry semantics ---------------------------------------------------

TEST_CASE(registry_lease_and_epoch_rules) {
  NamingReset reset;
  NamingRegistry& reg = naming_registry();
  EXPECT_EQ(reg.announce("svc", member("127.0.0.1:1000", 100, "z1", 2), 0),
            0);
  EXPECT_EQ(reg.announce("svc", member("127.0.0.1:2000", 100), 0), 0);
  std::vector<NamingMember> view;
  uint64_t version = 0;
  EXPECT_EQ(reg.resolve("svc", &view, &version), 0);
  EXPECT_EQ(view.size(), 2u);
  EXPECT(view[0].zone == "z1");
  EXPECT_EQ(view[0].weight, 2);
  EXPECT(view[0].lease_left_ms > 0);

  // Zombie fence: an OLDER epoch must not touch the record.
  EXPECT_EQ(reg.announce("svc", member("127.0.0.1:1000", 99), 0),
            kENamingStaleEpoch);
  EXPECT_EQ(reg.withdraw("svc", "127.0.0.1:1000", 99), kENamingStaleEpoch);
  // Takeover: a NEWER epoch replaces (hot-restart successor).
  EXPECT_EQ(reg.announce("svc", member("127.0.0.1:1000", 101, "z2"), 0), 0);
  EXPECT_EQ(reg.resolve("svc", &view, nullptr), 0);
  EXPECT(view[0].zone == "z2");
  // Renewal (same epoch, same fields) must NOT bump the version.
  uint64_t v_before = 0;
  EXPECT_EQ(reg.resolve("svc", &view, &v_before), 0);
  EXPECT_EQ(reg.announce("svc", member("127.0.0.1:1000", 101, "z2"), 0), 0);
  uint64_t v_after = 0;
  EXPECT_EQ(reg.resolve("svc", &view, &v_after), 0);
  EXPECT_EQ(v_before, v_after);
  // Withdraw at the live epoch; idempotent second withdraw.
  EXPECT_EQ(reg.withdraw("svc", "127.0.0.1:1000", 101), 0);
  EXPECT_EQ(reg.withdraw("svc", "127.0.0.1:1000", 101), 0);
  EXPECT_EQ(reg.member_count("svc"), 1u);
  // Zombie-renewal fence: the withdraw tombstoned epoch 101 — a late
  // renewal racing its own withdraw must NOT resurrect the member; a
  // successor's newer epoch passes (and clears the tombstone).
  EXPECT_EQ(reg.announce("svc", member("127.0.0.1:1000", 101), 0),
            kENamingStaleEpoch);
  EXPECT_EQ(reg.member_count("svc"), 1u);
  EXPECT_EQ(reg.announce("svc", member("127.0.0.1:1000", 102), 0), 0);
  EXPECT_EQ(reg.member_count("svc"), 2u);
  EXPECT_EQ(reg.resolve("nope", &view, nullptr), kENamingMiss);
}

TEST_CASE(registry_lease_expiry_prunes) {
  NamingReset reset;
  NamingRegistry& reg = naming_registry();
  EXPECT_EQ(reg.announce("svc", member("127.0.0.1:1000", 1), 250), 0);
  EXPECT_EQ(reg.member_count("svc"), 1u);
  usleep(300 * 1000);
  EXPECT_EQ(reg.member_count("svc"), 0u);  // expired = gone
  // Expiry counted as a change: version moved.
  std::vector<NamingMember> view;
  uint64_t version = 0;
  EXPECT_EQ(reg.resolve("svc", &view, &version), 0);
  EXPECT_EQ(view.size(), 0u);
  EXPECT(version >= 3);  // announce + expiry both bumped
}

TEST_CASE(watch_parks_and_wakes_on_change) {
  NamingReset reset;
  fiber_init(0);
  NamingRegistry& reg = naming_registry();
  std::vector<NamingMember> view;
  uint64_t version = 0;
  EXPECT_EQ(reg.announce("svc", member("127.0.0.1:1000", 1), 0), 0);
  EXPECT_EQ(reg.resolve("svc", &view, &version), 0);

  // Unchanged version: the watch must PARK (not answer instantly).
  const int64_t t0 = monotonic_time_us();
  uint64_t v2 = version;
  EXPECT_EQ(reg.watch("svc", version, 120, &view, &v2), 0);
  EXPECT(monotonic_time_us() - t0 >= 100 * 1000);
  EXPECT_EQ(v2, version);

  // A concurrent announce wakes the parked watcher immediately.
  std::thread bumper([&reg] {
    usleep(50 * 1000);
    reg.announce("svc", member("127.0.0.1:2000", 1), 0);
  });
  const int64_t t1 = monotonic_time_us();
  EXPECT_EQ(reg.watch("svc", version, 5000, &view, &v2), 0);
  const int64_t waited_us = monotonic_time_us() - t1;
  bumper.join();
  EXPECT(v2 > version);
  EXPECT_EQ(view.size(), 2u);
  EXPECT(waited_us < 3000 * 1000);  // push, not the 5s budget
}

// ---- naming:// cluster channel (push-based membership) --------------------

TEST_CASE(cluster_channel_follows_naming_pushes) {
  NamingReset reset;
  Server registry;
  EXPECT_EQ(naming_attach(&registry), 0);
  EXPECT_EQ(registry.Start(0), 0);
  const std::string reg_addr =
      "127.0.0.1:" + std::to_string(registry.port());

  auto n1 = std::make_unique<EchoNode>();
  auto n2 = std::make_unique<EchoNode>();
  EXPECT_EQ(n1->Start("node-1"), 0);
  EXPECT_EQ(n2->Start("node-2"), 0);
  EXPECT_EQ(server_announce(&n1->server, reg_addr, "echo", "z1", 1), 0);

  ClusterChannel::Options opts;
  opts.timeout_ms = 2000;
  opts.refresh_interval_ms = 60000;  // poll OFF: only pushes apply
  ClusterChannel ch;
  EXPECT_EQ(ch.Init("naming://" + reg_addr + "/echo", "rr", &opts), 0);
  EXPECT(call_echo(ch) == "node-1");

  // Announce node-2: the watch fiber must fold it in WITHOUT a refresh
  // tick (refresh interval is 60s).
  EXPECT_EQ(server_announce(&n2->server, reg_addr, "echo", "z2", 1), 0);
  std::set<std::string> seen;
  const int64_t deadline = monotonic_time_us() + 5 * 1000 * 1000;
  while (seen.size() < 2 && monotonic_time_us() < deadline) {
    seen.insert(call_echo(ch));
    usleep(10 * 1000);
  }
  EXPECT_EQ(seen.size(), 2u);
  EXPECT(seen.count("node-1") == 1 && seen.count("node-2") == 1);

  // Drain node-1: its withdrawal pushes, and every subsequent call lands
  // on node-2 with ZERO failures (kEDraining = silent failover).
  EXPECT_EQ(n1->server.Drain(3000), 0);
  int failures = 0;
  bool only_n2 = false;
  const int64_t d2 = monotonic_time_us() + 5 * 1000 * 1000;
  while (monotonic_time_us() < d2) {
    std::string got = call_echo(ch);
    if (got.rfind("FAILED", 0) == 0) {
      ++failures;
    }
    if (got == "node-2") {
      only_n2 = true;
      break;
    }
    usleep(5 * 1000);
  }
  EXPECT_EQ(failures, 0);
  EXPECT(only_n2);
}

// ---- balancing policies ---------------------------------------------------

TEST_CASE(chash_bounded_load_diffuses_hotspots) {
  std::unique_ptr<LoadBalancer> lb(LoadBalancer::create("c_hash_bl"));
  EXPECT(lb != nullptr);
  std::vector<ServerNode> nodes(3);
  std::vector<size_t> healthy = {0, 1, 2};
  for (int i = 0; i < 3; ++i) {
    EndPoint ep;
    hostname2endpoint(("127.0.0.1:" + std::to_string(7000 + i)).c_str(),
                      &ep);
    nodes[i].ep = ep;
  }
  // Idle cluster: affinity — one key always lands on the same node.
  const size_t home = lb->select(healthy, nodes, 42, 0);
  for (int i = 0; i < 16; ++i) {
    EXPECT_EQ(lb->select(healthy, nodes, 42, 0), home);
  }
  // Overload the home node far past factor x mean: the SAME key must
  // diffuse to a different node while the hotspot persists.
  nodes[home].inflight->store(1000, std::memory_order_relaxed);
  const size_t spill = lb->select(healthy, nodes, 42, 0);
  EXPECT(spill != home);
  // Relief: affinity returns.
  nodes[home].inflight->store(0, std::memory_order_relaxed);
  EXPECT_EQ(lb->select(healthy, nodes, 42, 0), home);
}

TEST_CASE(zone_la_prefers_local_zone) {
  FlagGuard zone("trpc_cluster_zone", "z1");
  std::unique_ptr<LoadBalancer> lb(LoadBalancer::create("zone_la"));
  EXPECT(lb != nullptr);
  std::vector<ServerNode> nodes(2);
  std::vector<size_t> healthy = {0, 1};
  for (int i = 0; i < 2; ++i) {
    EndPoint ep;
    hostname2endpoint(("127.0.0.1:" + std::to_string(7100 + i)).c_str(),
                      &ep);
    nodes[i].ep = ep;
    // Identical latency/load: zone is the only differentiator.
    nodes[i].ewma_latency_us->store(1000, std::memory_order_relaxed);
  }
  nodes[0].zone = "z1";
  nodes[1].zone = "z2";
  int local = 0;
  const int kRounds = 2000;
  for (int i = 0; i < kRounds; ++i) {
    if (lb->select(healthy, nodes, 0, 0) == 0) {
      ++local;
    }
  }
  // Expected share: 4/(4+1) = 80%; allow generous slack for dice.
  EXPECT(local > kRounds * 65 / 100);
  EXPECT(local < kRounds);  // the remote zone still gets SOME traffic
}

TEST_CASE(subsetting_is_deterministic_and_stable) {
  // Static 4-node list, subset of 2: the same seed must pick the same
  // pair across refreshes (connection stability), different seeds must
  // (for this seed choice) pick a different pair (client spread).
  std::vector<std::unique_ptr<EchoNode>> nodes;
  std::string url = "list://";
  for (int i = 0; i < 4; ++i) {
    nodes.push_back(std::make_unique<EchoNode>());
    EXPECT_EQ(nodes.back()->Start("node-" + std::to_string(i)), 0);
    url += nodes.back()->addr() + (i < 3 ? "," : "");
  }
  const auto subset_of = [&url](uint64_t seed) {
    ClusterChannel::Options opts;
    opts.timeout_ms = 2000;
    opts.subset_size = 2;
    opts.subset_seed = seed;
    ClusterChannel ch;
    EXPECT_EQ(ch.Init(url, "rr", &opts), 0);
    EXPECT_EQ(ch.refresh(), 0);  // second resolve: must not churn
    std::set<std::string> seen;
    for (int i = 0; i < 32; ++i) {
      seen.insert(call_echo(ch));
    }
    return seen;
  };
  const std::set<std::string> a1 = subset_of(7);
  const std::set<std::string> a2 = subset_of(7);
  EXPECT_EQ(a1.size(), 2u);
  EXPECT(a1 == a2);  // deterministic across channels AND refreshes
  bool spread = false;
  for (uint64_t seed = 8; seed < 16 && !spread; ++seed) {
    spread = subset_of(seed) != a1;
  }
  EXPECT(spread);  // some other seed lands elsewhere
}

// ---- drain semantics ------------------------------------------------------

TEST_CASE(drain_fails_over_without_quarantine) {
  // Static list (no naming): the drained node STAYS in the view, so
  // every call exercises the kEDraining failover path — and the breaker
  // must stay closed for it (healthy_count holds at 3).
  std::vector<std::unique_ptr<EchoNode>> nodes;
  std::string url = "list://";
  for (int i = 0; i < 3; ++i) {
    nodes.push_back(std::make_unique<EchoNode>());
    EXPECT_EQ(nodes.back()->Start("node-" + std::to_string(i)), 0);
    url += nodes.back()->addr() + (i < 2 ? "," : "");
  }
  ClusterChannel::Options opts;
  opts.timeout_ms = 2000;
  opts.max_retry = 2;
  ClusterChannel ch;
  EXPECT_EQ(ch.Init(url, "rr", &opts), 0);
  EXPECT_EQ(ch.healthy_count(), 3u);
  // Warm a live connection to every member: the kEDraining contract is
  // about in-flight fleets (a drained node ANSWERS on established
  // connections; only after teardown do fresh connects get refused).
  for (int i = 0; i < 9; ++i) {
    EXPECT(call_echo(ch).rfind("FAILED", 0) != 0);
  }
  EXPECT_EQ(nodes[0]->server.Drain(3000), 0);
  int failures = 0;
  for (int i = 0; i < 60; ++i) {
    const std::string got = call_echo(ch);
    if (got.rfind("FAILED", 0) == 0) {
      ++failures;
    } else {
      EXPECT(got != "node-0");  // drained node serves nothing new
    }
  }
  EXPECT_EQ(failures, 0);
  // THE drain guarantee: zero quarantine entries for the drained node.
  EXPECT_EQ(ch.healthy_count(), 3u);
}

TEST_CASE(drain_waits_in_flight_requests) {
  Server srv;
  Event release;
  std::atomic<int> completions{0};
  srv.RegisterMethod("Slow.Wait", [&release, &completions](
                                      Controller*, const IOBuf&,
                                      IOBuf* resp, Closure done) {
    release.wait(0, monotonic_time_us() + 2 * 1000 * 1000);
    resp->append("done");
    completions.fetch_add(1, std::memory_order_release);
    done();
  });
  EXPECT_EQ(srv.Start(0), 0);
  Channel ch;
  Channel::Options copts;
  copts.timeout_ms = 5000;
  EXPECT_EQ(ch.Init("127.0.0.1:" + std::to_string(srv.port()), &copts), 0);
  CountdownEvent started(1);
  std::thread caller([&ch, &started] {
    Controller cntl;
    IOBuf req, resp;
    started.signal();
    ch.CallMethod("Slow.Wait", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  });
  started.wait();
  // Let the request reach the handler, then drain: Drain must NOT
  // return success until the parked handler completed.
  while (srv.in_flight.load(std::memory_order_acquire) == 0) {
    usleep(1000);
  }
  std::thread releaser([&release] {
    usleep(100 * 1000);
    release.value.store(1, std::memory_order_release);
    release.wake_all();
  });
  EXPECT_EQ(srv.Drain(3000), 0);
  EXPECT_EQ(completions.load(std::memory_order_acquire), 1);
  caller.join();
  releaser.join();
}

TEST_CASE(quarantine_backoff_jitter_decorrelates) {
  // Two clients watching the same dead node must not compute identical
  // quarantine windows round after round (the lockstep-reprobe bug).
  // Windows come from the FaultActor splitmix64 side stream, so under a
  // default actor they are deterministic per process but DIFFER across
  // consecutive draws.
  std::vector<std::unique_ptr<EchoNode>> nodes;
  nodes.push_back(std::make_unique<EchoNode>());
  EXPECT_EQ(nodes.back()->Start("alive"), 0);
  // One dead endpoint forces breaker feeding on every call round.
  Server dead;
  dead.RegisterMethod("Echo.WhoAmI",
                      [](Controller*, const IOBuf&, IOBuf* resp,
                         Closure done) {
                        resp->append("dead");
                        done();
                      });
  EXPECT_EQ(dead.Start(0), 0);
  const std::string dead_addr = "127.0.0.1:" + std::to_string(dead.port());
  dead.Stop();
  ClusterChannel::Options opts;
  opts.timeout_ms = 300;
  opts.max_retry = 2;
  opts.quarantine_base_ms = 50;
  opts.quarantine_max_ms = 10000;
  opts.health_check_method = "";  // no probes: windows expire naturally
  ClusterChannel ch;
  EXPECT_EQ(ch.Init("list://" + nodes[0]->addr() + "," + dead_addr, "rr",
                    &opts),
            0);
  // Collect distinct quarantine windows by tripping the breaker
  // repeatedly; the jitter makes consecutive windows differ.
  std::set<int64_t> windows;
  for (int round = 0; round < 6; ++round) {
    for (int i = 0; i < 6; ++i) {
      (void)call_echo(ch);
    }
    // healthy_count dips to 1 while the dead node is quarantined.
    if (ch.healthy_count() == 1) {
      windows.insert(round);
    }
    usleep(20 * 1000);
  }
  EXPECT(windows.size() >= 1);  // the breaker did open
  // The decisive assertion: consecutive draws from the jitter stream
  // differ (a constant stream would reintroduce lockstep).
  const uint64_t a = FaultActor::global().jitter_draw();
  const uint64_t b = FaultActor::global().jitter_draw();
  const uint64_t c = FaultActor::global().jitter_draw();
  EXPECT(a != b || b != c);
}

// ---- chaos: membership churn x fault schedule (satellite) -----------------

TEST_CASE(chaos_drain_under_faults_zero_client_errors) {
  NamingReset reset;
  Server registry;
  EXPECT_EQ(naming_attach(&registry), 0);
  EXPECT_EQ(registry.Start(0), 0);
  const std::string reg_addr =
      "127.0.0.1:" + std::to_string(registry.port());
  std::vector<std::unique_ptr<EchoNode>> nodes;
  for (int i = 0; i < 3; ++i) {
    nodes.push_back(std::make_unique<EchoNode>());
    EXPECT_EQ(nodes.back()->Start("node-" + std::to_string(i)), 0);
    EXPECT_EQ(
        server_announce(&nodes.back()->server, reg_addr, "echo", "", 1), 0);
  }
  // Seeded faults on node-1 WHILE node-0 drains: delayed dispatch +
  // injected errors.  The cluster client's retry/failover must absorb
  // every one — zero client-visible errors — and the drained node must
  // end with no quarantine entry.
  EXPECT_EQ(nodes[1]->server.SetFaults(
                "seed=7;svr_delay=0.2:30;svr_error=0.1:5000"),
            0);
  ClusterChannel::Options opts;
  opts.timeout_ms = 3000;
  opts.max_retry = 2;
  opts.refresh_interval_ms = 100;
  ClusterChannel ch;
  EXPECT_EQ(ch.Init("naming://" + reg_addr + "/echo", "rr", &opts), 0);
  std::atomic<int> failures{0};
  std::atomic<int> calls{0};
  std::atomic<bool> stop{false};
  std::thread load([&ch, &failures, &calls, &stop] {
    while (!stop.load(std::memory_order_acquire)) {
      if (call_echo(ch).rfind("FAILED", 0) == 0) {
        failures.fetch_add(1, std::memory_order_relaxed);
      }
      calls.fetch_add(1, std::memory_order_relaxed);
    }
  });
  usleep(200 * 1000);                       // steady load under faults
  EXPECT_EQ(nodes[0]->server.Drain(5000), 0);  // churn: node-0 leaves
  usleep(400 * 1000);                       // load continues post-drain
  stop.store(true, std::memory_order_release);
  load.join();
  EXPECT(calls.load() > 20);
  EXPECT_EQ(failures.load(), 0);
  // The drained node left the view via withdrawal (never via
  // quarantine), and the survivors keep serving.
  EXPECT_EQ(naming_registry().member_count("echo"), 2u);
  EXPECT(ch.healthy_count() >= 1);
  nodes[1]->server.SetFaults("");
}

// ---- hot restart: SO_REUSEPORT listener handoff ---------------------------

TEST_CASE(hot_restart_handoff_keeps_port_and_traffic) {
  std::vector<std::unique_ptr<EchoNode>> nodes;
  for (int i = 0; i < 2; ++i) {
    nodes.push_back(std::make_unique<EchoNode>());
    EXPECT_EQ(nodes.back()->Start("gen1-" + std::to_string(i)), 0);
  }
  const int port = nodes[0]->port;
  ClusterChannel::Options opts;
  opts.timeout_ms = 2000;
  opts.max_retry = 2;
  ClusterChannel ch;
  EXPECT_EQ(ch.Init("list://" + nodes[0]->addr() + "," + nodes[1]->addr(),
                    "rr", &opts),
            0);
  std::atomic<int> failures{0};
  std::atomic<bool> stop{false};
  std::thread load([&ch, &failures, &stop] {
    while (!stop.load(std::memory_order_acquire)) {
      if (call_echo(ch).rfind("FAILED", 0) == 0) {
        failures.fetch_add(1, std::memory_order_relaxed);
      }
    }
  });
  // Successor (same process stands in for the fresh pid; the orchestrator
  // covers the cross-process run) adopts WHILE the predecessor drains.
  const std::string ho = "/tmp/trpc_test_handoff_" +
                         std::to_string(getpid()) + ".sock";
  Server successor;
  successor.RegisterMethod("Echo.WhoAmI",
                           [](Controller*, const IOBuf&, IOBuf* resp,
                              Closure done) {
                             resp->append("gen2-0");
                             done();
                           });
  std::thread adopt([&successor, &ho] {
    EXPECT_EQ(successor.StartFromHandoff(ho, 8000), 0);
  });
  EXPECT_EQ(nodes[0]->server.Drain(5000, ho), 0);
  adopt.join();
  EXPECT_EQ(successor.port(), port);  // same port, adopted listeners
  // The successor answers on the ORIGINAL endpoint (new conns land in
  // the shared accept queue it now owns).
  Channel fresh;
  Channel::Options copts;
  copts.timeout_ms = 2000;
  EXPECT_EQ(fresh.Init("127.0.0.1:" + std::to_string(port), &copts), 0);
  Controller cntl;
  IOBuf req, resp;
  req.append("x");
  fresh.CallMethod("Echo.WhoAmI", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT(resp.to_string() == "gen2-0");
  // The restart window produced ZERO client-visible errors.
  usleep(100 * 1000);
  stop.store(true, std::memory_order_release);
  load.join();
  EXPECT_EQ(failures.load(), 0);
}

TEST_MAIN
