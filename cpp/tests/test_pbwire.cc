// pbwire codec tests: primitives, message roundtrip, byte-for-byte
// interop against a golden buffer produced by protoc+python-protobuf,
// and the JSON transcoding seam.
#include "base/pbwire.h"

#include <cstring>

#include "tests/test_util.h"

using namespace trpc;

static std::string unhex(const char* h) {
  std::string out;
  for (size_t i = 0; h[i] && h[i + 1]; i += 2) {
    auto nib = [](char c) {
      return c <= '9' ? c - '0' : (c | 0x20) - 'a' + 10;
    };
    out.push_back(static_cast<char>((nib(h[i]) << 4) | nib(h[i + 1])));
  }
  return out;
}

// protoc golden: see the message literal in the comment below.
//   service_name="EchoService" f1, method_index=3 f2(int32),
//   scid=-12345 f3(sint64), correlation_id=-7 f4(int64), flag=true f5,
//   d=2.5 f6(double), fl=-1.5 f7(float), f64=0xdeadbeefcafe f8,
//   f32=0x12345678 f9, raw=00 01 fe f10, inner{s="hi",i=-2} f11,
//   reps=[1,300,70000] f12(repeated uint32), big=2^63+5 f13.
static const char* kGoldenHex =
    "0a0b4563686f53657276696365100318f1c00120f9ffffffffffffffff01280131"
    "00000000000004403d0000c0bf41fecaefbeadde00004d785634125203000"
    "1fe5a0f0a02686910feffffffffffffffff01600160ac0260f0a2046885808080"
    "808080808001";

TEST_CASE(pbwire_varint_primitives) {
  std::string buf;
  pb_put_varint(&buf, 0);
  pb_put_varint(&buf, 127);
  pb_put_varint(&buf, 128);
  pb_put_varint(&buf, 0xffffffffffffffffULL);
  size_t pos = 0;
  uint64_t v;
  EXPECT(pb_get_varint(buf, &pos, &v) && v == 0);
  EXPECT(pb_get_varint(buf, &pos, &v) && v == 127);
  EXPECT(pb_get_varint(buf, &pos, &v) && v == 128);
  EXPECT(pb_get_varint(buf, &pos, &v) && v == 0xffffffffffffffffULL);
  EXPECT_EQ(pos, buf.size());
  // Truncated varint fails.
  std::string trunc("\x80", 1);
  pos = 0;
  EXPECT(!pb_get_varint(trunc, &pos, &v));
  // Zigzag.
  EXPECT_EQ(pb_zigzag(0), 0u);
  EXPECT_EQ(pb_zigzag(-1), 1u);
  EXPECT_EQ(pb_zigzag(1), 2u);
  EXPECT_EQ(pb_unzigzag(pb_zigzag(-12345)), -12345);
  EXPECT_EQ(pb_unzigzag(pb_zigzag(INT64_MIN)), INT64_MIN);
}

static PbMessage build_golden() {
  PbMessage m;
  m.add_bytes(1, "EchoService");
  m.add_varint(2, 3);
  m.add_sint(3, -12345);
  m.add_varint(4, static_cast<uint64_t>(int64_t{-7}));
  m.add_bool(5, true);
  m.add_double(6, 2.5);
  m.add_float(7, -1.5f);
  m.add_fixed64(8, 0xdeadbeefcafeULL);
  m.add_fixed32(9, 0x12345678u);
  m.add_bytes(10, std::string_view("\x00\x01\xfe", 3));
  PbMessage inner;
  inner.add_bytes(1, "hi");
  inner.add_varint(2, static_cast<uint64_t>(int64_t{-2}));
  m.add_message(11, inner);
  m.add_varint(12, 1);
  m.add_varint(12, 300);
  m.add_varint(12, 70000);
  m.add_varint(13, (1ULL << 63) + 5);
  return m;
}

TEST_CASE(pbwire_matches_protoc_golden_bytes) {
  EXPECT(build_golden().serialize() == unhex(kGoldenHex));
}

TEST_CASE(pbwire_parses_protoc_golden) {
  PbMessage m;
  EXPECT(m.parse(unhex(kGoldenHex)));
  EXPECT(m.get_bytes(1) == "EchoService");
  EXPECT_EQ(m.get_varint(2), 3u);
  EXPECT_EQ(m.get_sint(3), -12345);
  EXPECT_EQ(static_cast<int64_t>(m.get_varint(4)), -7);
  EXPECT(m.get_bool(5));
  EXPECT_EQ(m.get_double(6), 2.5);
  EXPECT_EQ(m.get_fixed(8), 0xdeadbeefcafeULL);
  EXPECT_EQ(m.get_fixed(9), 0x12345678u);
  EXPECT(m.get_bytes(10) == std::string_view("\x00\x01\xfe", 3));
  PbMessage inner;
  EXPECT(m.get_message(11, &inner));
  EXPECT(inner.get_bytes(1) == "hi");
  EXPECT_EQ(static_cast<int64_t>(inner.get_varint(2)), -2);
  auto reps = m.all(12);
  EXPECT_EQ(reps.size(), 3u);
  EXPECT_EQ(reps[1]->varint, 300u);
  EXPECT_EQ(m.get_varint(13), (1ULL << 63) + 5);
  // Roundtrip is byte-identical (field order preserved).
  EXPECT(m.serialize() == unhex(kGoldenHex));
}

TEST_CASE(pbwire_rejects_malformed) {
  PbMessage m;
  EXPECT(!m.parse(std::string_view("\x08", 1)));     // tag, no value
  EXPECT(!m.parse(std::string_view("\x0a\x05""ab", 4)));  // short bytes
  EXPECT(!m.parse(std::string_view("\x0b", 1)));     // group wire type 3
  EXPECT(!m.parse(std::string_view("\x00\x00", 2))); // field number 0
  // 11-byte varint rejected.
  std::string over("\x08", 1);
  for (int i = 0; i < 10; ++i) over.push_back('\x80');
  over.push_back('\x01');
  EXPECT(!m.parse(over));
  // Length overflow (len > remaining, with a huge len that would wrap
  // naive pos+len arithmetic).
  std::string wrap("\x0a\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01x", 12);
  EXPECT(!m.parse(wrap));
}

static const PbSchema& golden_schema() {
  static PbSchema inner{{
      {1, "s", PbSchema::kString},
      {2, "i", PbSchema::kInt64},
  }};
  static PbSchema s{{
      {1, "service_name", PbSchema::kString},
      {2, "method_index", PbSchema::kInt64},
      {3, "scid", PbSchema::kSint64},
      {4, "correlation_id", PbSchema::kInt64},
      {5, "flag", PbSchema::kBool},
      {6, "d", PbSchema::kDouble},
      {10, "raw", PbSchema::kBytesHex},
      {11, "inner", PbSchema::kMessage, &inner},
      {12, "reps", PbSchema::kUint64, nullptr, /*repeated=*/true},
  }};
  return s;
}

TEST_CASE(pbwire_json_transcode_schemad) {
  PbMessage m;
  EXPECT(m.parse(unhex(kGoldenHex)));
  Json j = pb_to_json(m, golden_schema());
  EXPECT(j.find("service_name") &&
         j.find("service_name")->as_string() == "EchoService");
  EXPECT_EQ(static_cast<int64_t>(j.find("scid")->as_number()), -12345);
  EXPECT_EQ(static_cast<int64_t>(j.find("correlation_id")->as_number()),
            -7);
  EXPECT(j.find("flag")->as_bool());
  EXPECT(j.find("raw")->as_string() == "0001fe");
  EXPECT(j.find("inner")->find("s")->as_string() == "hi");
  EXPECT_EQ(j.find("reps")->size(), 3u);
  // Unknown fields (7/8/9/13 not in schema) surface under their numbers.
  EXPECT(j.find("8") != nullptr);

  // JSON -> pb -> JSON fixpoint over the schema'd subset.
  PbMessage back;
  EXPECT(json_to_pb(j, golden_schema(), &back));
  Json j2 = pb_to_json(back, golden_schema());
  EXPECT(j2.find("service_name")->as_string() == "EchoService");
  EXPECT_EQ(static_cast<int64_t>(j2.find("scid")->as_number()), -12345);
  EXPECT_EQ(j2.find("reps")->size(), 3u);
  EXPECT(j2.find("inner")->find("s")->as_string() == "hi");
  // Type mismatch is rejected, not coerced.
  Json bad = Json::object();
  bad.set("flag", Json::number(1));
  PbMessage sink;
  EXPECT(!json_to_pb(bad, golden_schema(), &sink));
}

TEST_CASE(pbwire_json_schemaless_walk) {
  PbMessage m;
  EXPECT(m.parse(unhex(kGoldenHex)));
  Json j = pb_to_json_schemaless(m);
  EXPECT(j.find("1") && j.find("1")->as_string() == "EchoService");
  // Nested message recursed under "11".
  EXPECT(j.find("11") && j.find("11")->find("1") &&
         j.find("11")->find("1")->as_string() == "hi");
  // Repeated field 12 collapsed to an array.
  EXPECT(j.find("12")->type() == Json::Type::kArray);
  EXPECT_EQ(j.find("12")->size(), 3u);
}


TEST_CASE(runtime_proto_parse_and_transcode) {
  // tools/rpc_press_impl parity: .proto loaded at runtime, JSON encoded
  // through the resulting schema, decoded back.
  const std::string proto = R"(
    // press request
    syntax = "proto3";
    package example.press;
    option cc_enable_arenas = true;

    message Inner {
      string note = 1;
      repeated int32 vals = 2;
    }

    message PressRequest {
      string name = 1;            // who
      int64 count = 2;
      sint32 delta = 3;
      bool flag = 4;
      double ratio = 5;
      bytes blob = 6;
      Inner inner = 7;
      repeated string tags = 8;
    }
  )";
  std::map<std::string, PbSchema> schemas;
  std::string err;
  EXPECT(parse_proto_file(proto, &schemas, &err));
  EXPECT_EQ(schemas.size(), 2u);
  const PbSchema& req = schemas.at("PressRequest");
  EXPECT_EQ(req.fields.size(), 8u);
  EXPECT(req.by_name("inner") != nullptr);
  EXPECT(req.by_name("inner")->nested == &schemas.at("Inner"));
  EXPECT(req.by_name("tags")->repeated);

  Json j;
  EXPECT(Json::parse(
      "{\"name\":\"press\",\"count\":42,\"delta\":-7,\"flag\":true,"
      "\"ratio\":2.5,\"blob\":\"00ff\","
      "\"inner\":{\"note\":\"n\",\"vals\":[1,2,3]},"
      "\"tags\":[\"a\",\"b\"]}",
      &j));
  PbMessage m;
  EXPECT(json_to_pb(j, req, &m));
  const std::string wire = m.serialize();
  PbMessage back;
  EXPECT(back.parse(wire));
  EXPECT(back.get_bytes(1) == "press");
  EXPECT_EQ(back.get_varint(2), 42u);
  EXPECT_EQ(back.get_sint(3), -7);
  EXPECT(back.get_bool(4));
  EXPECT(back.get_double(5) == 2.5);
  PbMessage inner;
  EXPECT(back.get_message(7, &inner));
  EXPECT(inner.get_bytes(1) == "n");
  EXPECT_EQ(inner.all(2).size(), 3u);
  EXPECT_EQ(back.all(8).size(), 2u);
  // And the reverse transcode sees the same values by NAME.
  const Json round = pb_to_json(back, req);
  EXPECT(round.find("name") != nullptr);

  // Unknown message type is an error, not a silent skip.
  std::map<std::string, PbSchema> bad;
  EXPECT(!parse_proto_file("message A { NoSuch x = 1; }", &bad, &err));
}

TEST_MAIN
