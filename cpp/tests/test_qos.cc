// QoS subsystem tests (ISSUE 6): weighted-fair lane ordering under
// contention, per-tenant weighted fairness inside one lane,
// starvation-freedom of the lowest lane, admission-control shed with the
// distinct kEOverloaded status, tenant isolation, REUSEPORT
// multi-dispatcher accept distribution, default-off byte-identity, and
// the high-priority small-RPC p99 guarantee under low-priority bulk.
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <mutex>
#include <string>
#include <vector>

#include "base/flags.h"
#include "base/time.h"
#include "fiber/event.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/cluster.h"
#include "net/concurrency_limiter.h"
#include "net/dispatcher.h"
#include "net/protocol.h"
#include "net/qos.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

// Latch the dispatcher count at 2 BEFORE any socket exists in this
// process (the flag is read once, at the first fd registration).
const int g_force_two_dispatchers = [] {
  Flag* f = Flag::define_int64("trpc_event_dispatchers", 1, "");
  return f != nullptr ? f->set_from_string("2") : -1;
}();

// ---- direct lane-machinery fixtures ------------------------------------

std::mutex g_tap_mu;
std::vector<std::pair<int, std::string>> g_taps;

void tap_record(int lane, const std::string& tenant) {
  std::lock_guard<std::mutex> g(g_tap_mu);
  g_taps.emplace_back(lane, tenant);
}

std::atomic<int> g_processed{0};

void discard_process(void* arg) {
  delete static_cast<InputMessage*>(arg);
  g_processed.fetch_add(1, std::memory_order_acq_rel);
}

InputMessage* make_msg(const std::string& tenant, uint8_t prio) {
  auto* m = new InputMessage();
  m->meta.type = RpcMeta::kRequest;
  m->meta.qos_tenant = tenant;
  m->meta.qos_priority = prio;
  return m;
}

void reset_tap() {
  std::lock_guard<std::mutex> g(g_tap_mu);
  g_taps.clear();
}

void drain_and_wait(int expect) {
  qos_test_pause(false);
  qos_test_drive(&discard_process);
  const int64_t deadline = monotonic_time_us() + 10 * 1000 * 1000;
  while (g_processed.load(std::memory_order_acquire) < expect &&
         monotonic_time_us() < deadline) {
    usleep(1000);
  }
  EXPECT_EQ(g_processed.load(), expect);
}

struct QosGuard {
  ~QosGuard() {
    qos_test_pause(false);
    qos_test_tap(nullptr);
    Flag::set("trpc_qos_lanes", "0");
    Flag::set("trpc_qos_lane_weights", "8,4,2,1");
  }
};

}  // namespace

TEST_CASE(qos_weighted_fair_lane_ordering_under_contention) {
  QosGuard guard;
  EXPECT_EQ(g_force_two_dispatchers, 0);
  reset_tap();
  g_processed = 0;
  qos_test_tap(&tap_record);
  qos_test_pause(true);
  // Stage a contended backlog: 160 top-lane + 160 bottom-lane messages
  // (weights 8 vs 1), then release and observe POP order.
  for (int i = 0; i < 160; ++i) {
    qos_enqueue(0, "hi", make_msg("hi", 0), &discard_process);
    qos_enqueue(3, "lo", make_msg("lo", 3), &discard_process);
  }
  EXPECT_EQ(qos_lane_depth(0), 160);
  EXPECT_EQ(qos_lane_depth(3), 160);
  drain_and_wait(320);
  std::lock_guard<std::mutex> g(g_tap_mu);
  EXPECT_EQ(g_taps.size(), 320u);
  // DRR with weights 8:1 (quantum unit 4): each round serves 32 lane-0
  // pops against 4 lane-3 pops, so the first 90 pops are >= ~8:1 lane 0.
  int lane0_early = 0;
  for (size_t i = 0; i < 90; ++i) {
    lane0_early += g_taps[i].first == 0 ? 1 : 0;
  }
  EXPECT(lane0_early >= 72);
  qos_test_tap(nullptr);
}

TEST_CASE(qos_tenant_weighted_fair_within_one_lane) {
  QosGuard guard;
  // Two tenants in the SAME lane, hashed to different shards (pick the
  // second name so the shards differ — same formula as qos.cc's
  // shard_for), weights 8 vs 1: pops should favor the heavy tenant ~8:1.
  const std::string heavy = "heavy";
  std::string light = "light";
  const size_t hshard = std::hash<std::string>{}(heavy) % kQosLaneShards;
  for (int i = 0; std::hash<std::string>{}(light) % kQosLaneShards == hshard;
       ++i) {
    light = "light" + std::to_string(i);
  }
  qos_set_tenant_weight(heavy, 8);
  qos_set_tenant_weight(light, 1);
  reset_tap();
  g_processed = 0;
  qos_test_tap(&tap_record);
  qos_test_pause(true);
  for (int i = 0; i < 80; ++i) {
    qos_enqueue(1, heavy, make_msg(heavy, 1), &discard_process);
    qos_enqueue(1, light, make_msg(light, 1), &discard_process);
  }
  drain_and_wait(160);
  std::lock_guard<std::mutex> g(g_tap_mu);
  int heavy_early = 0;
  for (size_t i = 0; i < 45 && i < g_taps.size(); ++i) {
    heavy_early += g_taps[i].second == heavy ? 1 : 0;
  }
  // Shard DRR pops 8 heavy per cursor visit vs 1 light: first 45 pops
  // carry ~40 heavy.  Bound left loose for the interleaved empty shards.
  EXPECT(heavy_early >= 32);
  qos_test_tap(nullptr);
}

TEST_CASE(qos_lowest_lane_never_starves) {
  QosGuard guard;
  reset_tap();
  g_processed = 0;
  qos_test_tap(&tap_record);
  qos_test_pause(true);
  for (int i = 0; i < 2000; ++i) {
    qos_enqueue(0, "flood", make_msg("flood", 0), &discard_process);
  }
  for (int i = 0; i < 20; ++i) {
    qos_enqueue(3, "meek", make_msg("meek", 3), &discard_process);
  }
  drain_and_wait(2020);
  std::lock_guard<std::mutex> g(g_tap_mu);
  // DRR guarantees the bottom lane 4 pops per ~36-pop round even under a
  // 100:1 flood: the 20 meek messages all dispatch within the first ~200
  // pops, nowhere near the flood's tail.
  size_t last_meek = 0;
  size_t meek_seen = 0;
  for (size_t i = 0; i < g_taps.size(); ++i) {
    if (g_taps[i].first == 3) {
      last_meek = i;
      ++meek_seen;
    }
  }
  EXPECT_EQ(meek_seen, 20u);
  EXPECT(last_meek < 400);
  qos_test_tap(nullptr);
}

namespace {

Server* g_qos_server = nullptr;
int g_qos_port = 0;
Event g_release;          // parked handlers wait on this
std::atomic<int> g_holding{0};

void start_qos_server_once() {
  if (g_qos_server != nullptr) {
    return;
  }
  g_qos_server = new Server();
  g_qos_server->RegisterMethod(
      "Echo.Echo", [](Controller*, const IOBuf& req, IOBuf* resp,
                      Closure done) {
        resp->append(req);
        done();
      });
  g_qos_server->RegisterMethod(
      "Hold.Until", [](Controller* cntl, const IOBuf&, IOBuf* resp,
                       Closure done) {
        // Surfaces the tag, then parks until the test releases.
        resp->append(cntl->qos_tenant());
        g_holding.fetch_add(1, std::memory_order_acq_rel);
        const uint32_t snap =
            g_release.value.load(std::memory_order_acquire);
        g_release.wait(snap, monotonic_time_us() + 10 * 1000 * 1000);
        g_holding.fetch_sub(1, std::memory_order_acq_rel);
        done();
      });
  EXPECT_EQ(g_qos_server->SetQos(
                "cap:weight=4,limit=2;roomy:weight=1,limit=64;*:limit=500"),
            0);
  // Malformed specs must be rejected loudly, keeping the old governor.
  EXPECT_EQ(g_qos_server->SetQos("nonsense"), -1);
  EXPECT_EQ(g_qos_server->SetQos("t:limit=banana"), -1);
  EXPECT_EQ(g_qos_server->Start(0), 0);
  g_qos_port = g_qos_server->port();
}

std::string qos_addr() {
  return "127.0.0.1:" + std::to_string(g_qos_port);
}

struct CallOut {
  Channel* ch;
  int code = -1;
  std::string resp;
};

void call_hold_fiber(void* p) {
  auto* out = static_cast<CallOut*>(p);
  Controller cntl;
  cntl.set_timeout_ms(8000);
  IOBuf req, resp;
  out->ch->CallMethod("Hold.Until", req, &resp, &cntl);
  out->code = cntl.error_code();
  out->resp = resp.to_string();
}

}  // namespace

TEST_CASE(qos_shed_under_overload_answers_overloaded_status) {
  start_qos_server_once();
  Channel ch;
  Channel::Options opts;
  opts.timeout_ms = 8000;
  opts.qos_tenant = "cap";
  EXPECT_EQ(ch.Init(qos_addr(), &opts), 0);
  // Fill tenant "cap"'s limit=2 with parked calls...
  CallOut held[2] = {{&ch}, {&ch}};
  fiber_t fids[2];
  for (int i = 0; i < 2; ++i) {
    EXPECT_EQ(fiber_start(&fids[i], &call_hold_fiber, &held[i], 0), 0);
  }
  const int64_t deadline = monotonic_time_us() + 5 * 1000 * 1000;
  while (g_holding.load(std::memory_order_acquire) < 2 &&
         monotonic_time_us() < deadline) {
    usleep(1000);
  }
  EXPECT_EQ(g_holding.load(), 2);
  // ...then the third is shed with kEOverloaded, immediately (no park).
  Controller cntl;
  cntl.set_timeout_ms(2000);
  cntl.set_qos("cap", 0);
  IOBuf req, resp;
  const int64_t t0 = monotonic_time_us();
  ch.CallMethod("Hold.Until", req, &resp, &cntl);
  EXPECT_EQ(cntl.error_code(), kEOverloaded);
  EXPECT(monotonic_time_us() - t0 < 1000 * 1000);
  // Tenant isolation: "roomy" (its own limiter) admits while "cap" is
  // saturated.
  Controller ok;
  ok.set_timeout_ms(5000);
  ok.set_qos("roomy", 0);
  IOBuf req2, resp2;
  ch.CallMethod("Echo.Echo", req2, &resp2, &ok);
  EXPECT(!ok.Failed());
  // Release the parked holders; their responses carry the tenant tag the
  // server-side controller observed (roundtrip proof).
  g_release.value.fetch_add(1, std::memory_order_release);
  g_release.wake_all();
  for (fiber_t f : fids) {
    fiber_join(f);
  }
  for (const CallOut& h : held) {
    EXPECT_EQ(h.code, 0);
    EXPECT(h.resp == "cap");
  }
}

TEST_CASE(qos_overloaded_routes_cluster_failover) {
  start_qos_server_once();
  // Second, unconstrained server: after the capped node sheds, the
  // cluster client must land the call here without surfacing an error.
  Server other;
  other.RegisterMethod("Hold.Until",
                       [](Controller*, const IOBuf&, IOBuf* resp,
                          Closure done) {
                         resp->append("other");
                         done();
                       });
  EXPECT_EQ(other.Start(0), 0);
  // Saturate "cap" on the governed server again.
  Channel ch;
  Channel::Options copts;
  copts.timeout_ms = 8000;
  copts.qos_tenant = "cap";
  EXPECT_EQ(ch.Init(qos_addr(), &copts), 0);
  CallOut held[2] = {{&ch}, {&ch}};
  fiber_t fids[2];
  for (int i = 0; i < 2; ++i) {
    EXPECT_EQ(fiber_start(&fids[i], &call_hold_fiber, &held[i], 0), 0);
  }
  const int64_t deadline = monotonic_time_us() + 5 * 1000 * 1000;
  while (g_holding.load(std::memory_order_acquire) < 2 &&
         monotonic_time_us() < deadline) {
    usleep(1000);
  }
  ClusterChannel cc;
  ClusterChannel::Options opts;
  opts.timeout_ms = 5000;
  opts.max_retry = 2;
  EXPECT_EQ(cc.Init("list://" + qos_addr() + ",127.0.0.1:" +
                        std::to_string(other.port()),
                    "rr", &opts),
            0);
  // Every call succeeds: a shed on the governed node fails over to the
  // healthy one within the same call (tried-set exclusion), and the shed
  // node's breaker backs subsequent traffic off it.
  for (int i = 0; i < 8; ++i) {
    Controller cntl;
    cntl.set_qos("cap", 0);
    IOBuf req, resp;
    cc.CallMethod("Hold.Until", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  g_release.value.fetch_add(1, std::memory_order_release);
  g_release.wake_all();
  for (fiber_t f : fids) {
    fiber_join(f);
  }
  other.Stop();
}

TEST_CASE(qos_reuseport_shards_spread_accepts_across_dispatchers) {
  EXPECT_EQ(EventDispatcher::count(), 2);  // latched by our initializer
  Server srv;
  srv.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                     IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  EXPECT_EQ(srv.set_reuseport_shards(4), 0);
  EXPECT_EQ(srv.Start(0), 0);
  EXPECT_EQ(srv.set_reuseport_shards(2), -1);  // running: refused
  const std::string addr = "127.0.0.1:" + std::to_string(srv.port());
  // 200 short-lived connections from 200 distinct source ports: the
  // kernel's REUSEPORT hash spreads them across all four shards.
  Channel ch;
  Channel::Options opts;
  opts.timeout_ms = 5000;
  opts.connection_type = "short";
  EXPECT_EQ(ch.Init(addr, &opts), 0);
  for (int i = 0; i < 200; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("ping");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  const std::vector<uint64_t> counts = srv.accept_counts();
  EXPECT_EQ(counts.size(), 4u);
  uint64_t total = 0;
  for (uint64_t c : counts) {
    // P(any shard empty after 200 4-tuple-hashed accepts) ~ 4*(3/4)^200.
    EXPECT(c > 0);
    total += c;
  }
  EXPECT_EQ(total, 200u);
  srv.Stop();
}

TEST_CASE(qos_absent_tag_stays_off_the_wire_and_vars_frozen) {
  // Wire layer: an untagged meta must encode byte-identically to the
  // pre-QoS format (shorter frame, no tail groups) and a tagged one must
  // roundtrip through the parser.
  RpcMeta plain;
  plain.type = RpcMeta::kRequest;
  plain.correlation_id = 7;
  plain.method = "Echo.Echo";
  IOBuf plain_frame;
  tstd_pack(&plain_frame, plain, IOBuf());
  RpcMeta tagged = plain;
  tagged.qos_priority = 2;
  tagged.qos_tenant = "alice";
  IOBuf tagged_frame;
  tstd_pack(&tagged_frame, tagged, IOBuf());
  // trace(24) + comp(6) + streams(4) + stripe(24) + qos(3 + 5 tenant)
  EXPECT_EQ(tagged_frame.size(), plain_frame.size() + 24 + 6 + 4 + 24 + 8);
  InputMessage out;
  ParseError rc = tstd_protocol().parse(&tagged_frame, &out, nullptr);
  EXPECT(rc == ParseError::kOk);
  EXPECT_EQ(out.meta.qos_priority, 2);
  EXPECT(out.meta.qos_tenant == "alice");
  InputMessage out2;
  rc = tstd_protocol().parse(&plain_frame, &out2, nullptr);
  EXPECT(rc == ParseError::kOk);
  EXPECT_EQ(out2.meta.qos_priority, 0);
  EXPECT(out2.meta.qos_tenant.empty());

  // Dispatch layer: with lanes at the default 0, traffic never touches
  // the lane machinery (the small-RPC hot path is unchanged).
  start_qos_server_once();
  Channel ch;
  Channel::Options opts;
  opts.timeout_ms = 5000;
  EXPECT_EQ(ch.Init(qos_addr(), &opts), 0);
  const int64_t before = qos_vars().enqueued.get_value();
  for (int i = 0; i < 100; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("ping");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  EXPECT_EQ(qos_vars().enqueued.get_value(), before);
  for (int i = 0; i < kQosMaxLanes; ++i) {
    EXPECT_EQ(qos_lane_depth(i), 0);
  }
}

// Name deliberately avoids the "qos" substring: the TSan gate runs the
// binary with that filter, and this case is timing-bound (it stays
// native, like the stripe suite's p99 guard).
TEST_CASE(high_priority_small_p99_held_under_low_prio_bulk) {
  QosGuard guard;
  start_qos_server_once();
  EXPECT_EQ(Flag::set("trpc_qos_lanes", "4"), 0);
  // Low-priority bulk: 16MB echoes streaming on a pooled channel tagged
  // to the bottom lane.
  static Channel big_ch;
  Channel::Options big_opts;
  big_opts.connection_type = "pooled";
  big_opts.timeout_ms = 60000;
  big_opts.qos_tenant = "bulk";
  big_opts.qos_priority = 3;
  EXPECT_EQ(big_ch.Init(qos_addr(), &big_opts), 0);
  static Channel small_ch;
  Channel::Options small_opts;
  small_opts.timeout_ms = 10000;
  small_opts.qos_tenant = "interactive";
  small_opts.qos_priority = 0;
  EXPECT_EQ(small_ch.Init(qos_addr(), &small_opts), 0);
  {
    Controller warm;
    IOBuf req, resp;
    req.append("warm");
    small_ch.CallMethod("Echo.Echo", req, &resp, &warm);
    EXPECT(!warm.Failed());
  }
  static std::atomic<bool> big_done{false};
  static std::atomic<int> big_failures{0};
  big_done = false;
  big_failures = 0;
  fiber_t big_fiber;
  EXPECT_EQ(fiber_start(&big_fiber,
                        [](void*) {
                          const std::string big(16 << 20, 'b');
                          for (int i = 0; i < 4; ++i) {
                            Controller cntl;
                            IOBuf req, resp;
                            req.append(big);
                            big_ch.CallMethod("Echo.Echo", req, &resp,
                                              &cntl);
                            if (cntl.Failed() ||
                                resp.size() != big.size()) {
                              big_failures.fetch_add(1);
                            }
                          }
                          big_done.store(true);
                        },
                        nullptr),
            0);
  std::vector<int64_t> lat;
  while (!big_done.load(std::memory_order_acquire)) {
    Controller cntl;
    cntl.set_timeout_ms(10000);
    IOBuf req, resp;
    req.append("ping");
    const int64_t t0 = monotonic_time_us();
    small_ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    lat.push_back(monotonic_time_us() - t0);
    EXPECT(!cntl.Failed());
  }
  fiber_join(big_fiber);
  EXPECT_EQ(big_failures.load(), 0);
  EXPECT(lat.size() > 20);
  std::sort(lat.begin(), lat.end());
  const int64_t p99 = lat[lat.size() * 99 / 100];
  // Generous CI bound (mirrors the stripe HOL guard): the lane layer must
  // not ADD head-of-line blocking on top of the cut-budget guarantee.
  EXPECT(p99 < 200 * 1000);
}

TEST_MAIN
