// Redis (RESP) protocol: codec vectors, redis-speaking server via
// RedisService, client with FIFO pipelining, auth, and wire-level
// interop from hand-built bytes (the reference's redis_protocol_unittest
// style).
#include <atomic>
#include <map>
#include <string>
#include <vector>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include "net/auth.h"
#include "net/redis.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

TEST_CASE(resp_codec_roundtrip) {
  // Every reply type serializes and parses back identically.
  RedisReply in = RedisReply::Array({
      RedisReply::Status("OK"),
      RedisReply::Error("ERR boom"),
      RedisReply::Integer(-42),
      RedisReply::Integer(INT64_MIN),  // magnitude 2^63 must roundtrip
      RedisReply::Bulk("hello\r\nworld"),  // embedded CRLF must survive
      RedisReply::Nil(),
      RedisReply::Array({RedisReply::Integer(1), RedisReply::Bulk("")}),
  });
  std::string wire;
  in.serialize(&wire);
  RedisReply out;
  size_t pos = 0;
  EXPECT_EQ(resp_parse_reply(wire, &pos, &out), 1);
  EXPECT_EQ(pos, wire.size());
  EXPECT_EQ(out.type, RedisReply::kArray);
  EXPECT_EQ(out.elements.size(), 7u);
  EXPECT(out.elements[0].type == RedisReply::kStatus &&
         out.elements[0].str == "OK");
  EXPECT(out.elements[1].is_error() && out.elements[1].str == "ERR boom");
  EXPECT_EQ(out.elements[2].integer, -42);
  EXPECT_EQ(out.elements[3].integer, INT64_MIN);
  EXPECT(out.elements[4].str == "hello\r\nworld");
  EXPECT_EQ(out.elements[5].type, RedisReply::kNil);
  EXPECT_EQ(out.elements[6].elements.size(), 2u);
}

TEST_CASE(resp_codec_partial_and_malformed) {
  // Partial input reports 0 (need more), never consumes.
  std::string full = "$5\r\nhello\r\n";
  for (size_t cut = 1; cut < full.size(); ++cut) {
    RedisReply r;
    size_t pos = 0;
    EXPECT_EQ(resp_parse_reply(full.substr(0, cut), &pos, &r), 0);
    EXPECT_EQ(pos, 0u);
  }
  // Malformed markers and framing report -1.
  for (const char* bad :
       {"?3\r\nabc\r\n", "$5\r\nhelloXX", "$abc\r\n", ":12x\r\n",
        "*2\r\n:1\r\n?\r\n"}) {
    RedisReply r;
    size_t pos = 0;
    EXPECT_EQ(resp_parse_reply(bad, &pos, &r), -1);
  }
  // Command parsing requires arrays of bulk strings.
  std::vector<std::string> args;
  size_t pos = 0;
  EXPECT_EQ(resp_parse_command("PING\r\n", &pos, &args), -1);  // inline
  pos = 0;
  EXPECT_EQ(resp_parse_command("*1\r\n:5\r\n", &pos, &args), -1);
  pos = 0;
  std::string cmd;
  resp_pack_command({"SET", "k", "v"}, &cmd);
  EXPECT_EQ(resp_parse_command(cmd, &pos, &args), 1);
  EXPECT(args.size() == 3 && args[0] == "SET" && args[2] == "v");
}

namespace {

// A tiny keyspace: the user-built redis-speaking server of redis.h:194.
std::map<std::string, std::string>* store() {
  static auto* s = new std::map<std::string, std::string>();
  return s;
}

RedisService* make_service() {
  auto* rs = new RedisService();
  rs->AddCommandHandler("set", [](const std::vector<std::string>& a) {
    if (a.size() != 3) {
      return RedisReply::Error("ERR wrong number of arguments");
    }
    (*store())[a[1]] = a[2];
    return RedisReply::Status("OK");
  });
  rs->AddCommandHandler("get", [](const std::vector<std::string>& a) {
    if (a.size() != 2) {
      return RedisReply::Error("ERR wrong number of arguments");
    }
    auto it = store()->find(a[1]);
    return it == store()->end() ? RedisReply::Nil()
                                : RedisReply::Bulk(it->second);
  });
  rs->AddCommandHandler("del", [](const std::vector<std::string>& a) {
    int64_t n = 0;
    for (size_t i = 1; i < a.size(); ++i) {
      n += store()->erase(a[i]);
    }
    return RedisReply::Integer(n);
  });
  rs->AddCommandHandler("incr", [](const std::vector<std::string>& a) {
    std::string& v = (*store())[a[1]];
    const int64_t n = v.empty() ? 1 : atoll(v.c_str()) + 1;
    v = std::to_string(n);
    return RedisReply::Integer(n);
  });
  return rs;
}

Server* g_srv = nullptr;
int g_port = 0;

void start_once() {
  if (g_srv != nullptr) {
    return;
  }
  g_srv = new Server();
  g_srv->set_redis_service(make_service());
  g_srv->RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                        IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  EXPECT_EQ(g_srv->Start(0), 0);
  g_port = g_srv->port();
}

}  // namespace

TEST_CASE(redis_client_get_set_roundtrip) {
  start_once();
  RedisClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(g_port)), 0);
  RedisReply r = cli.execute({"SET", "alpha", "one"});
  EXPECT(r.type == RedisReply::kStatus && r.str == "OK");
  r = cli.execute({"GET", "alpha"});
  EXPECT(r.type == RedisReply::kString && r.str == "one");
  r = cli.execute({"GET", "missing-key"});
  EXPECT_EQ(r.type, RedisReply::kNil);
  r = cli.execute({"DEL", "alpha"});
  EXPECT(r.type == RedisReply::kInteger && r.integer == 1);
  // Case-insensitive dispatch + builtin fallbacks.
  r = cli.execute({"set", "beta", "two"});
  EXPECT(r.str == "OK");
  r = cli.execute({"PING"});
  EXPECT(r.str == "PONG");
  r = cli.execute({"ECHO", "echoed"});
  EXPECT(r.str == "echoed");
  r = cli.execute({"NOSUCHCMD"});
  EXPECT(r.is_error());
}

TEST_CASE(redis_pipeline_order_and_throughput) {
  start_once();
  RedisClient cli;
  EXPECT_EQ(cli.Init("127.0.0.1:" + std::to_string(g_port)), 0);
  // One write carries 200 commands; replies come back in exact order.
  std::vector<std::vector<std::string>> cmds;
  for (int i = 0; i < 100; ++i) {
    cmds.push_back({"SET", "k" + std::to_string(i), "v" + std::to_string(i)});
    cmds.push_back({"GET", "k" + std::to_string(i)});
  }
  std::vector<RedisReply> replies = cli.pipeline(cmds);
  EXPECT_EQ(replies.size(), 200u);
  for (int i = 0; i < 100; ++i) {
    EXPECT(replies[2 * i].str == "OK");
    EXPECT(replies[2 * i + 1].str == "v" + std::to_string(i));
  }
  // INCR through the pipeline is sequential per connection.
  cli.execute({"DEL", "ctr"});
  cmds.assign(50, {"INCR", "ctr"});
  replies = cli.pipeline(cmds);
  EXPECT_EQ(replies.back().integer, 50);
}

TEST_CASE(redis_raw_wire_interop) {
  // A hand-rolled client (stand-in for redis-cli) speaking raw RESP.
  start_once();
  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)), 0);
  const std::string wire =
      "*3\r\n$3\r\nSET\r\n$4\r\nwire\r\n$3\r\nraw\r\n"
      "*2\r\n$3\r\nGET\r\n$4\r\nwire\r\n";
  EXPECT(write(fd, wire.data(), wire.size()) ==
         static_cast<ssize_t>(wire.size()));
  std::string in;
  char buf[512];
  while (in.find("raw") == std::string::npos) {
    const ssize_t n = read(fd, buf, sizeof(buf));
    if (n <= 0) {
      break;
    }
    in.append(buf, n);
  }
  EXPECT(in == "+OK\r\n$3\r\nraw\r\n");
  close(fd);
}

TEST_CASE(redis_mixed_protocols_one_port) {
  // The same port serves redis AND HTTP (protocol probing by first bytes).
  start_once();
  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(g_port));
  EXPECT_EQ(connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)), 0);
  const std::string rq = "GET /health HTTP/1.1\r\nHost: x\r\n\r\n";
  EXPECT(write(fd, rq.data(), rq.size()) == static_cast<ssize_t>(rq.size()));
  char buf[512];
  const ssize_t n = read(fd, buf, sizeof(buf));
  EXPECT(n > 0);
  EXPECT(std::string(buf, n).find("200 OK") != std::string::npos);
  close(fd);
}

namespace {
class TokenAuth : public Authenticator {
 public:
  explicit TokenAuth(std::string tok) : tok_(std::move(tok)) {}
  int generate_credential(std::string* out) const override {
    *out = tok_;
    return 0;
  }
  int verify_credential(const std::string& cred,
                        const EndPoint&) const override {
    return cred == tok_ ? 0 : -1;
  }

 private:
  std::string tok_;
};
}  // namespace

TEST_CASE(redis_auth_command_gates_connection) {
  static TokenAuth tok("hunter2");
  static Server srv;
  srv.set_redis_service(make_service());
  srv.set_authenticator(&tok);
  EXPECT_EQ(srv.Start(0), 0);
  const std::string addr = "127.0.0.1:" + std::to_string(srv.port());
  {
    // No AUTH: commands are refused, PING stays open.
    RedisClient cli;
    EXPECT_EQ(cli.Init(addr), 0);
    RedisReply r = cli.execute({"GET", "x"});
    EXPECT(r.is_error() && r.str.find("NOAUTH") != std::string::npos);
    EXPECT(cli.execute({"PING"}).str == "PONG");
  }
  {
    // Wrong password: still gated.
    RedisClient cli;
    RedisClient::Options opts;
    opts.password = "wrong";
    EXPECT_EQ(cli.Init(addr, &opts), 0);
    EXPECT(cli.execute({"GET", "x"}).is_error());
  }
  {
    // Correct password (AUTH pipelined on the fresh connection).
    RedisClient cli;
    RedisClient::Options opts;
    opts.password = "hunter2";
    EXPECT_EQ(cli.Init(addr, &opts), 0);
    EXPECT(cli.execute({"SET", "authed", "yes"}).str == "OK");
    EXPECT(cli.execute({"GET", "authed"}).str == "yes");
  }
}

TEST_MAIN
