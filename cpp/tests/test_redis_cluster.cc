// Redis Cluster client: spec CRC16/slot vectors, routing across a
// simulated two-node cluster, MOVED (permanent) and ASK (one-shot)
// redirects, and the redirect budget.  The "cluster" is two in-process
// RedisService servers whose handlers enforce slot ownership the way
// redis-server does (reference analogue: redis_cluster.cpp's unittest
// drives a mock node answering MOVED/ASK).
#include <map>
#include <string>
#include <vector>

#include "net/redis.h"
#include "net/redis_cluster.h"
#include "net/server.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

struct Node {
  Server srv;
  std::map<std::string, std::string> store;
  int slot_beg = 0, slot_end = 0;  // inclusive ownership range
  std::string addr;
  int moved_served = 0;  // how many MOVED errors this node issued
};

Node* node_a() {
  static Node n;
  return &n;
}
Node* node_b() {
  static Node n;
  return &n;
}
// Consumed by node B's get when a key was announced via ASKING.
bool g_asking = false;
// When non-empty, node A answers ASK→B for exactly this key (simulating
// a slot mid-migration: A still owns it, the key already moved).
std::string g_ask_key;

RedisReply slots_reply() {
  auto range = [](int beg, int end, const std::string& addr) {
    const size_t colon = addr.rfind(':');
    return RedisReply::Array({
        RedisReply::Integer(beg),
        RedisReply::Integer(end),
        RedisReply::Array({
            RedisReply::Bulk(addr.substr(0, colon)),
            RedisReply::Integer(atoi(addr.c_str() + colon + 1)),
        }),
    });
  };
  return RedisReply::Array({
      range(node_a()->slot_beg, node_a()->slot_end, node_a()->addr),
      range(node_b()->slot_beg, node_b()->slot_end, node_b()->addr),
  });
}

void start_node(Node* n, int beg, int end) {
  n->slot_beg = beg;
  n->slot_end = end;
  auto* rs = new RedisService();
  rs->AddCommandHandler(
      "cluster", [](const std::vector<std::string>& a) {
        if (a.size() >= 2 && (a[1] == "SLOTS" || a[1] == "slots")) {
          return slots_reply();
        }
        return RedisReply::Error("ERR unsupported subcommand");
      });
  rs->AddCommandHandler("asking", [](const std::vector<std::string>&) {
    g_asking = true;
    return RedisReply::Status("OK");
  });
  auto owned = [n](const std::string& key) {
    const int s = redis_key_slot(key);
    return s >= n->slot_beg && s <= n->slot_end;
  };
  auto moved = [n](const std::string& key) {
    Node* other = (n == node_a()) ? node_b() : node_a();
    ++n->moved_served;
    return RedisReply::Error("MOVED " +
                             std::to_string(redis_key_slot(key)) + " " +
                             other->addr);
  };
  rs->AddCommandHandler(
      "set", [n, owned, moved](const std::vector<std::string>& a) {
        if (a.size() != 3) {
          return RedisReply::Error("ERR wrong number of arguments");
        }
        if (!owned(a[1])) {
          return moved(a[1]);
        }
        n->store[a[1]] = a[2];
        return RedisReply::Status("OK");
      });
  rs->AddCommandHandler(
      "get", [n, owned, moved](const std::vector<std::string>& a) {
        if (a.size() != 2) {
          return RedisReply::Error("ERR wrong number of arguments");
        }
        if (n == node_a() && !g_ask_key.empty() && a[1] == g_ask_key) {
          return RedisReply::Error(
              "ASK " + std::to_string(redis_key_slot(a[1])) + " " +
              node_b()->addr);
        }
        // An ASKING announcement lets a key through even when the slot
        // map says it moved on (migration import, redis semantics).
        if (!owned(a[1]) && !g_asking) {
          return moved(a[1]);
        }
        g_asking = false;
        auto it = n->store.find(a[1]);
        return it == n->store.end() ? RedisReply::Nil()
                                    : RedisReply::Bulk(it->second);
      });
  n->srv.set_redis_service(rs);
  EXPECT_EQ(n->srv.Start(0), 0);
  n->addr = "127.0.0.1:" + std::to_string(n->srv.port());
}

void start_cluster() {
  if (!node_a()->addr.empty()) {
    return;
  }
  start_node(node_a(), 0, 8191);
  start_node(node_b(), 8192, 16383);
}

}  // namespace

TEST_CASE(crc16_and_slot_vectors) {
  // XMODEM check value from the CRC catalogue; slots from the cluster
  // spec ("foo"→12182, "bar"→5061, hash tags collapse to the tag).
  EXPECT_EQ(redis_crc16("123456789", 9), 0x31C3);
  EXPECT_EQ(redis_key_slot("foo"), 12182);
  EXPECT_EQ(redis_key_slot("bar"), 5061);
  EXPECT_EQ(redis_key_slot("{user1000}.following"),
            redis_key_slot("{user1000}.followers"));
  EXPECT_EQ(redis_key_slot("{user1000}.following"),
            redis_key_slot("user1000"));
  // Empty tag "{}" is NOT a tag: the whole key hashes.
  EXPECT_EQ(redis_key_slot("foo{}{bar}"),
            redis_crc16("foo{}{bar}", 10) % 16384);
  // Only the FIRST '{' opens a candidate tag.
  EXPECT_EQ(redis_key_slot("foo{{bar}}"), redis_crc16("{bar", 4) % 16384);
}

TEST_CASE(cluster_routes_by_slot) {
  start_cluster();
  RedisClusterClient cc;
  EXPECT_EQ(cc.Init({node_a()->addr}), 0);
  // "foo"→12182 lives on B, "bar"→5061 on A; both through one client.
  EXPECT(cc.execute({"SET", "foo", "on-b"}).str == "OK");
  EXPECT(cc.execute({"SET", "bar", "on-a"}).str == "OK");
  EXPECT(node_b()->store["foo"] == "on-b");
  EXPECT(node_a()->store["bar"] == "on-a");
  EXPECT(cc.execute({"GET", "foo"}).str == "on-b");
  // The map was learned from CLUSTER SLOTS, not from redirects.
  EXPECT(cc.slot_owner(12182) == node_b()->addr);
  EXPECT(cc.slot_owner(5061) == node_a()->addr);
  EXPECT_EQ(node_a()->moved_served + node_b()->moved_served, 0);
}

TEST_CASE(moved_updates_map_once) {
  start_cluster();
  node_a()->moved_served = 0;
  node_b()->moved_served = 0;
  RedisClusterClient cc;
  EXPECT_EQ(cc.Init({node_a()->addr}), 0);
  EXPECT(cc.execute({"SET", "foo", "v1"}).str == "OK");  // learns map
  // Migrate "foo"'s slot to A behind the client's back.
  node_a()->slot_beg = 0;
  node_a()->slot_end = 16383;
  node_b()->slot_beg = 1;
  node_b()->slot_end = 0;  // owns nothing now
  node_a()->store["foo"] = "v2";
  // Stale map points at B; B answers MOVED→A; client retries at A and
  // repairs the single slot entry.
  EXPECT(cc.execute({"GET", "foo"}).str == "v2");
  EXPECT_EQ(node_b()->moved_served, 1);
  EXPECT(cc.slot_owner(12182) == node_a()->addr);
  // Second hit goes straight to A: no further MOVED.
  EXPECT(cc.execute({"GET", "foo"}).str == "v2");
  EXPECT_EQ(node_b()->moved_served, 1);
  // Restore the split for later cases.
  node_a()->slot_beg = 0;
  node_a()->slot_end = 8191;
  node_b()->slot_beg = 8192;
  node_b()->slot_end = 16383;
  node_a()->store.erase("foo");
}

TEST_CASE(ask_is_one_shot) {
  start_cluster();
  RedisClusterClient cc;
  EXPECT_EQ(cc.Init({node_a()->addr}), 0);
  EXPECT(cc.execute({"SET", "bar", "migrating"}).str == "OK");  // on A
  // A announces "bar" is mid-migration via ASK; B holds the value in
  // its import buffer and serves it only behind ASKING ("bar"'s slot
  // 5061 is outside B's range, so a bare GET at B would bounce).
  g_ask_key = "bar";
  node_b()->store["bar"] = "imported";
  RedisReply r = cc.execute({"GET", "bar"});
  EXPECT(r.str == "imported");
  // One-shot: the slot map still points at A...
  EXPECT(cc.slot_owner(5061) == node_a()->addr);
  // ...and once migration "finishes" traffic flows to A again.
  g_ask_key.clear();
  EXPECT(cc.execute({"GET", "bar"}).str == "migrating");
  node_b()->store.erase("bar");
}

TEST_CASE(redirect_budget_surfaces_loop) {
  // Two nodes that each insist the other owns everything: the client
  // must give up after max_redirects and surface the MOVED error.
  start_cluster();
  node_a()->moved_served = 0;
  node_b()->moved_served = 0;
  const int a_beg = node_a()->slot_beg, a_end = node_a()->slot_end;
  const int b_beg = node_b()->slot_beg, b_end = node_b()->slot_end;
  node_a()->slot_beg = 1;
  node_a()->slot_end = 0;
  node_b()->slot_beg = 1;
  node_b()->slot_end = 0;
  RedisClusterClient cc;
  RedisClusterClient::Options opts;
  opts.max_redirects = 3;
  EXPECT_EQ(cc.Init({node_a()->addr}, &opts), 0);
  RedisReply r = cc.execute({"GET", "foo"});
  EXPECT(r.is_error());
  EXPECT_EQ(r.str.compare(0, 5, "MOVED"), 0);
  EXPECT_EQ(node_a()->moved_served + node_b()->moved_served, 4);  // 1+3
  node_a()->slot_beg = a_beg;
  node_a()->slot_end = a_end;
  node_b()->slot_beg = b_beg;
  node_b()->slot_end = b_end;
}

TEST_MAIN
