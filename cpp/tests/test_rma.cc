// One-sided RMA plane tests (net/rma.h): region registration lifecycle,
// use-after-unregister rejection, shm multi-rail 64MB integrity, ici
// parallel-rail integrity, direct-to-caller-region response landing,
// cancel-mid-put buffer quiescence, sub-threshold bypass byte-identity,
// window-full fallback to the striped copy path, and chunk-level fault
// injection (drop / trunc / corrupt) asserting whole-or-nothing failure —
// a registered buffer is never observable as complete with partial bytes.
#include <unistd.h>

#include <cstring>
#include <string>

#include "base/flags.h"
#include "base/proc.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/fault.h"
#include "net/hotpath_stats.h"
#include "net/protocol.h"
#include "net/rma.h"
#include "net/server.h"
#include "net/stripe.h"
#include "tests/test_util.h"

using namespace trpc;

namespace {

Server* g_server = nullptr;
int g_port = 0;

void start_once() {
  if (g_server != nullptr) {
    return;
  }
  g_server = new Server();
  g_server->RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                           IOBuf* resp, Closure done) {
    resp->append(req);  // zero-copy ref share
    done();
  });
  EXPECT_EQ(g_server->Start(0), 0);
  g_port = g_server->port();
}

std::string addr() { return "127.0.0.1:" + std::to_string(g_port); }

// Patterned payload: a mis-offset one-sided write changes bytes, unlike
// a constant fill.
std::string pattern(size_t n, uint32_t salt = 0) {
  std::string s(n, 0);
  for (size_t i = 0; i < n; ++i) {
    s[i] = static_cast<char>(((i + salt) * 2654435761u) >> 13);
  }
  return s;
}

struct FaultGuard {
  ~FaultGuard() { FaultActor::global().set(""); }
};

struct FlagGuard {
  std::string name, old_value;
  FlagGuard(const std::string& n, const std::string& v) : name(n) {
    old_value = Flag::find(n)->value_string();
    EXPECT_EQ(Flag::set(n, v), 0);
  }
  ~FlagGuard() { Flag::set(name, old_value); }
};

struct RmaDelta {
  int64_t tx_msgs, rx_msgs, tx_bytes, rejected, window_full;
  RmaDelta() { reset(); }
  void reset() {
    HotPathVars& v = hotpath_vars();
    tx_msgs = v.rma_tx_msgs.get_value();
    rx_msgs = v.rma_rx_msgs.get_value();
    tx_bytes = v.rma_tx_bytes.get_value();
    rejected = v.rma_rejected.get_value();
    window_full = v.rma_window_full.get_value();
  }
  int64_t d_tx_msgs() const {
    return hotpath_vars().rma_tx_msgs.get_value() - tx_msgs;
  }
  int64_t d_rx_msgs() const {
    return hotpath_vars().rma_rx_msgs.get_value() - rx_msgs;
  }
  int64_t d_tx_bytes() const {
    return hotpath_vars().rma_tx_bytes.get_value() - tx_bytes;
  }
  int64_t d_rejected() const {
    return hotpath_vars().rma_rejected.get_value() - rejected;
  }
  int64_t d_window_full() const {
    return hotpath_vars().rma_window_full.get_value() - window_full;
  }
};

}  // namespace

TEST_CASE(rma_registration_lifecycle) {
  const size_t n0 = rma_region_count();
  uint64_t rkey = 0;
  void* buf = rma_alloc(1 << 20, &rkey);
  EXPECT(buf != nullptr);
  EXPECT(rkey != 0);
  EXPECT_EQ(rma_region_count(), n0 + 1);
  // The data area is usable memory.
  memset(buf, 0x5a, 1 << 20);
  uint64_t found_rkey = 0, off = 0;
  EXPECT(rma_exportable(buf, 1 << 20, &found_rkey, &off));
  EXPECT_EQ(found_rkey, rkey);
  EXPECT_EQ(off, 0u);
  // Interior ranges resolve with their offset.
  EXPECT(rma_exportable(static_cast<char*>(buf) + 4096, 1024, &found_rkey,
                        &off));
  EXPECT_EQ(off, 4096u);
  rma_free(buf);
  EXPECT_EQ(rma_region_count(), n0);
  EXPECT(!rma_exportable(buf, 1, &found_rkey, &off));

  // Local pins: registered, never exportable, unregister exactly once.
  char local[256];
  const uint64_t pin = rma_reg(local, sizeof(local));
  EXPECT(pin != 0);
  EXPECT(!rma_exportable(local, sizeof(local), &found_rkey, &off));
  EXPECT_EQ(rma_unreg(pin), 0);
  EXPECT_EQ(rma_unreg(pin), -1);
}

TEST_CASE(rma_shm_multi_rail_64mb_integrity) {
  start_once();
  FlagGuard rails("trpc_shm_rails", "8");
  Channel ch;
  Channel::Options opts;
  opts.use_shm = true;
  opts.timeout_ms = 60000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  const std::string big = pattern(64 << 20);
  RmaDelta d;
  Controller cntl;
  cntl.set_enable_checksum(true);  // per-chunk CRCs in the transfer hdr
  IOBuf req, resp;
  req.append(big);
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT_EQ(resp.size(), big.size());
  EXPECT(resp.equals(big.data(), big.size()));
  // Request + response both rode the one-sided path, not frames.
  EXPECT(d.d_tx_msgs() >= 2);
  EXPECT(d.d_rx_msgs() >= 2);
  EXPECT(d.d_tx_bytes() >= 2ll * (64 << 20));
  EXPECT_EQ(d.d_rejected(), 0);
  EXPECT_EQ(stripe_pending_reassemblies(), 0u);
}

TEST_CASE(rma_ici_parallel_rail_integrity) {
  start_once();
  FlagGuard rails("trpc_ici_rails", "4");
  Channel ch;
  Channel::Options opts;
  opts.use_ici = true;
  opts.timeout_ms = 60000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  // Ordinary (non-staging) payload: descriptors would copy it through
  // the ring DMA serially; the rma path writes it with parallel rails.
  const std::string big = pattern(24 << 20, 7);
  RmaDelta d;
  for (int i = 0; i < 2; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append(big);
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    EXPECT_EQ(resp.size(), big.size());
    EXPECT(resp.equals(big.data(), big.size()));
  }
  EXPECT(d.d_tx_msgs() >= 4);  // 2 calls x (request + response)
  EXPECT_EQ(d.d_rejected(), 0);
}

TEST_CASE(rma_direct_response_lands_in_caller_region) {
  start_once();
  Channel ch;
  Channel::Options opts;
  opts.use_shm = true;
  opts.timeout_ms = 60000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  const size_t cap = 8 << 20;
  uint64_t rkey = 0;
  void* land = rma_alloc(cap, &rkey);
  EXPECT(land != nullptr);
  const std::string big = pattern(6 << 20, 3);
  RmaDelta d;
  Controller cntl;
  cntl.call().land_buf = land;  // the batch plane's registration path
  cntl.call().land_cap = cap;
  IOBuf req, resp;
  req.append(big);
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT_EQ(resp.size(), big.size());
  EXPECT(resp.equals(big.data(), big.size()));
  // The response payload IS the caller's registered buffer (in-place
  // view, zero receiver-side copies), and its bytes match.
  EXPECT(resp.block_count() >= 1);
  EXPECT(resp.ref_at(0).block->data + resp.ref_at(0).offset ==
         static_cast<char*>(land));
  EXPECT_EQ(memcmp(land, big.data(), big.size()), 0);
  EXPECT(d.d_tx_msgs() >= 2);
  resp.clear();  // drop the view before the region goes away
  rma_free(land);
}

TEST_CASE(rma_use_after_unregister_rejected) {
  start_once();
  // A control frame naming a landing that is no longer bound (the
  // caller unregistered / the region was freed) must drop whole.
  const size_t cap = 4 << 20;
  uint64_t rkey = 0;
  void* land = rma_alloc(cap, &rkey);
  EXPECT(land != nullptr);
  const uint64_t cid = 0x5eed5eed12345678ull;
  stripe_register_landing(cid, land, cap);
  stripe_unregister_landing(cid);  // caller cancelled: bind must be gone
  RmaDelta d;
  InputMessage msg;
  msg.meta.type = RpcMeta::kResponse;
  msg.meta.correlation_id = cid;
  msg.meta.rma_rkey = rkey;
  msg.meta.rma_off = kRmaDirectOff;
  msg.meta.rma_len = 1 << 20;
  msg.meta.rma_chunk = 1 << 20;
  EXPECT(!rma_resolve(&msg, nullptr));
  EXPECT_EQ(d.d_rejected(), 1);
  // Freed region + still-bound cid is equally rejected (use after free).
  stripe_register_landing(cid, land, cap);
  rma_free(land);
  InputMessage msg2;
  msg2.meta.type = RpcMeta::kResponse;
  msg2.meta.correlation_id = cid;
  msg2.meta.rma_rkey = rkey;
  msg2.meta.rma_off = kRmaDirectOff;
  msg2.meta.rma_len = 1 << 20;
  msg2.meta.rma_chunk = 1 << 20;
  EXPECT(!rma_resolve(&msg2, nullptr));
  EXPECT_EQ(d.d_rejected(), 2);
  stripe_unregister_landing(cid);
  // A window-path control frame with no socket/session context is
  // rejected too (never resolves arbitrary local regions).
  InputMessage msg3;
  msg3.meta.type = RpcMeta::kRequest;
  msg3.meta.correlation_id = 1;
  msg3.meta.rma_rkey = rkey;
  msg3.meta.rma_off = 0;
  msg3.meta.rma_len = 4096;
  msg3.meta.rma_chunk = 4096;
  EXPECT(!rma_resolve(&msg3, nullptr));
  EXPECT_EQ(d.d_rejected(), 3);
}

TEST_CASE(rma_cancel_mid_put_buffer_quiescent) {
  start_once();
  Channel ch;
  Channel::Options opts;
  opts.use_shm = true;
  opts.timeout_ms = 60000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  {
    // Warm the ring + window so the failing call below is established.
    Controller cntl;
    IOBuf req, resp;
    req.append("warm");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  const size_t cap = 8 << 20;
  uint64_t rkey = 0;
  void* land = rma_alloc(cap, &rkey);
  EXPECT(land != nullptr);
  memset(land, 0x77, cap);
  // Server answers late; the call times out first — the client-side
  // completion unregisters the landing BEFORE the response's one-sided
  // put could be resolved against it.  Deadline stamping OFF for this
  // scenario: with the deadline plane (ISSUE 15) a stamped budget makes
  // the server SHED the delayed request instead of producing the late
  // response — this test models the peer that never learned of the
  // abandonment (old client / wire stamping disabled), where the
  // landing-unbind defense is the only line left.
  FlagGuard wire("trpc_deadline_wire", "false");
  EXPECT_EQ(g_server->SetFaults("svr_delay=1:800"), 0);
  RmaDelta d;
  {
    Controller cntl;
    cntl.set_timeout_ms(150);
    cntl.call().land_buf = land;
    cntl.call().land_cap = cap;
    IOBuf req, resp;
    req.append(pattern(4 << 20, 9));
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(cntl.Failed());  // timed out; landing unregistered on return
  }
  g_server->SetFaults("");
  // The late response's control frame must be REJECTED (unbound cid),
  // not land in a buffer the caller already considers recycled.
  const int64_t deadline = monotonic_time_us() + 5 * 1000 * 1000;
  while (d.d_rejected() == 0 && monotonic_time_us() < deadline) {
    fiber_sleep_us(20 * 1000);
  }
  EXPECT(d.d_rejected() >= 1);
  rma_free(land);
  // The channel still works after the rejected transfer.
  Controller cntl;
  IOBuf req, resp;
  req.append("after");
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
}

TEST_CASE(rma_sub_threshold_bypass_byte_identity) {
  start_once();
  Channel ch;
  Channel::Options opts;
  opts.use_shm = true;
  opts.timeout_ms = 15000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  RmaDelta d;
  for (int i = 0; i < 32; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append(pattern(1024, i));
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    EXPECT_EQ(resp.size(), 1024u);
  }
  // Sub-threshold traffic leaves the entire rma plane untouched — the
  // proof small RPCs pay nothing for it.
  EXPECT_EQ(d.d_tx_msgs(), 0);
  EXPECT_EQ(d.d_rx_msgs(), 0);
  EXPECT_EQ(d.d_tx_bytes(), 0);
  EXPECT_EQ(d.d_rejected(), 0);
  EXPECT_EQ(d.d_window_full(), 0);
}

TEST_CASE(rma_window_full_falls_back_to_copy_path) {
  start_once();
  // A 16MB window (64 slots of 256KB) cannot hold a 20MB transfer: the
  // send must fall back to the striped copy path and stay correct.
  FlagGuard window("trpc_rma_window_bytes", "16777216");
  Channel ch;
  Channel::Options opts;
  opts.use_shm = true;
  opts.timeout_ms = 60000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  const std::string big = pattern(20 << 20, 11);
  RmaDelta d;
  const int64_t stripe0 = hotpath_vars().stripe_tx_chunks.get_value();
  Controller cntl;
  cntl.set_enable_checksum(true);
  IOBuf req, resp;
  req.append(big);
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  EXPECT(!cntl.Failed());
  EXPECT_EQ(resp.size(), big.size());
  EXPECT(resp.equals(big.data(), big.size()));
  EXPECT_EQ(d.d_tx_msgs(), 0);  // nothing fit the one-sided window
  EXPECT(hotpath_vars().stripe_tx_chunks.get_value() - stripe0 > 0);
}

TEST_CASE(rma_chunk_drop_fails_call_whole) {
  start_once();
  Channel ch;
  Channel::Options opts;
  opts.use_shm = true;
  opts.timeout_ms = 60000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  {
    Controller cntl;  // establish the ring before arming faults
    IOBuf req, resp;
    req.append("warm");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  FaultGuard guard;
  EXPECT_EQ(FaultActor::global().set("seed=11;drop=0.7"), 0);
  Controller cntl;
  cntl.set_timeout_ms(1200);
  IOBuf req, resp;
  req.append(pattern(8 << 20, 13));
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  // Dropped chunks leave completion bits clear (or the control frame
  // vanished): the CALL fails whole, never a partial payload.
  EXPECT(cntl.Failed());
  EXPECT_EQ(resp.size(), 0u);
  FaultActor::global().set("");
  // Clean again afterwards (reconnects if the fault killed the ring).
  Controller ok;
  ok.set_timeout_ms(20000);
  IOBuf req2, resp2;
  const std::string big = pattern(4 << 20, 17);
  req2.append(big);
  ch.CallMethod("Echo.Echo", req2, &resp2, &ok);
  EXPECT(!ok.Failed());
  EXPECT(resp2.equals(big.data(), big.size()));
}

TEST_CASE(rma_chunk_corrupt_rejected_by_chunk_crc) {
  start_once();
  Channel ch;
  Channel::Options opts;
  opts.use_shm = true;
  opts.timeout_ms = 60000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  {
    Controller cntl;
    IOBuf req, resp;
    req.append("warm");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  FaultGuard guard;
  EXPECT_EQ(FaultActor::global().set("seed=3;corrupt=0.8"), 0);
  RmaDelta d;
  Controller cntl;
  cntl.set_timeout_ms(1500);
  cntl.set_enable_checksum(true);  // arms the per-chunk CRCs
  IOBuf req, resp;
  req.append(pattern(8 << 20, 19));
  ch.CallMethod("Echo.Echo", req, &resp, &cntl);
  // A flipped byte in a landed chunk fails CRC verification at resolve:
  // the transfer is dropped whole and the call times out.
  EXPECT(cntl.Failed());
  EXPECT_EQ(resp.size(), 0u);
  EXPECT(d.d_rejected() >= 1);
}

TEST_CASE(rma_span_scavenger_reclaims_leaked_never_live) {
  // The documented span-leak-on-dropped-control degradation: a sender
  // allocates a window span, writes (or drops) its chunks, and the
  // CONTROL frame vanishes in transit — the slots stayed allocated
  // until connection teardown.  The scavenger must reclaim exactly
  // those spans, and never a live admitted one.
  start_once();
  Channel ch;
  Channel::Options opts;
  opts.use_shm = true;
  opts.timeout_ms = 60000;
  EXPECT_EQ(ch.Init(addr(), &opts), 0);
  {
    Controller cntl;  // establish the ring before arming faults
    IOBuf req, resp;
    req.append("warm");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
  }
  FlagGuard age("trpc_rma_span_scavenge_ms", "150");
  // Earlier suite tests (chunk-drop/corrupt, cancel/deadline races)
  // legitimately leak never-admitted spans — exactly the class this
  // scavenger exists for.  Purge that residue first so the live-span
  // exemption below is judged on this test's own span only.  Two passes
  // a full age apart: the scavenger is mark-then-sweep (first_seen
  // stamping), so one pass only STARTS aging a slot it never saw.
  rma_scavenge();
  usleep(200 * 1000);
  rma_scavenge();
  // A LIVE span first: hold the zero-copy response (it wraps a span in
  // OUR window) past the scavenge age — admitted spans are exempt.
  {
    Controller cntl;
    cntl.set_timeout_ms(20000);
    IOBuf req, resp;
    const std::string body = pattern(8 << 20, 23);
    req.append(body);
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(!cntl.Failed());
    EXPECT(resp.equals(body.data(), body.size()));
    EXPECT(rma_spans_in_use() >= 1);
    usleep(300 * 1000);  // older than the scavenge age, but admitted
    EXPECT_EQ(rma_scavenge(), 0u);
    EXPECT(rma_spans_in_use() >= 1);  // still held by `resp`
  }
  // The response ref dropped: its span frees via the deleter, not the
  // scavenger.  (The request-side span frees when the echo's shared
  // payload refs drop — poll briefly for the async release.)
  for (int i = 0; i < 100 && rma_spans_in_use() != 0; ++i) {
    usleep(10 * 1000);
  }
  EXPECT_EQ(rma_spans_in_use(), 0u);

  // Now the leak: drop EVERYTHING (chunk writes and the control frame
  // itself) — the span allocated in the peer window is never resolved
  // and never freed.
  const int64_t scavenged_before = [] {
    // rma_span_scavenged is registry-read (no struct access needed).
    std::string out;
    return Variable::read_exposed("rma_span_scavenged", &out)
               ? strtoll(out.c_str(), nullptr, 10)
               : 0;
  }();
  {
    FaultGuard guard;
    EXPECT_EQ(FaultActor::global().set("seed=31;drop=1.0;max=64"), 0);
    Controller cntl;
    cntl.set_timeout_ms(800);
    IOBuf req, resp;
    req.append(pattern(8 << 20, 29));
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    EXPECT(cntl.Failed());  // control frame dropped: the call dies whole
    EXPECT_EQ(resp.size(), 0u);
  }
  EXPECT(rma_spans_in_use() >= 1);  // the leaked span
  usleep(250 * 1000);  // first pass stamps first-seen...
  rma_scavenge();
  usleep(250 * 1000);  // ...second pass ages it past 150ms and reclaims
  rma_scavenge();
  EXPECT_EQ(rma_spans_in_use(), 0u);
  std::string out;
  EXPECT(Variable::read_exposed("rma_span_scavenged", &out));
  EXPECT(strtoll(out.c_str(), nullptr, 10) > scavenged_before);
  // The window is healthy again: a clean large echo reuses the slots.
  Controller ok;
  ok.set_timeout_ms(20000);
  IOBuf req2, resp2;
  const std::string big = pattern(4 << 20, 31);
  req2.append(big);
  ch.CallMethod("Echo.Echo", req2, &resp2, &ok);
  EXPECT(!ok.Failed());
  EXPECT(resp2.equals(big.data(), big.size()));
}

TEST_CASE(rma_kernel_capability_probe) {
  // The satellite gate: the probe answers deterministically, and on this
  // repo's dev boxes (kernel 4.4.0) io_uring is known-absent — but the
  // test only pins the CONTRACT (0/1, stable, unknown = -1).
  const int a = kernel_supports("io_uring");
  EXPECT(a == 0 || a == 1);
  EXPECT_EQ(kernel_supports("io_uring"), a);  // memoized, stable
  EXPECT_EQ(kernel_supports("no_such_feature"), -1);
  EXPECT_EQ(kernel_supports(nullptr), -1);
}

TEST_MAIN
